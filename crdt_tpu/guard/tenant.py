"""Per-tenant admission budgets + fairness for multi-doc serving.

The round-10 ladders bound ONE replica's memory/disk/device exposure;
a multi-tenant batch server (:class:`crdt_tpu.models.multidoc.
MultiDocServer`) adds the cross-tenant failure mode: one flooding doc
filling the shared admission queue until every other tenant's deltas
wait behind it. Same discipline, tenant-scoped:

- **budget** — each tenant's PENDING (admitted, not yet converged)
  updates are bounded by bytes and count. Overflow sheds the
  tenant's OWN oldest pending updates (keep-the-newest, the
  round-10 inbox rule: a single over-budget repair blob still
  lands whole). A flooding tenant therefore degrades alone — its
  backlog is trimmed — while every other tenant's queue, and the
  bytes they converge to, are untouched (tests/test_multidoc.py
  chaos leg).
- **fairness** — dispatch admission orders dirty docs by how long
  ago they were last served (then doc id for determinism), so a
  tenant that fills every tick's row budget cannot starve the rest:
  the docs left out of this tick are FIRST in line for the next.
- **resident budget** (round 15) — the delta-tick path keeps per-doc
  RESIDENT state (device matrices + host caches) across ticks; that
  memory is bounded by :class:`ResidentBudget`
  (``CRDT_TPU_MT_RESIDENT_BYTES``). Overflow evicts the
  least-recently-served docs' resident state back to cold replay
  (``tenant.resident_evictions``) — eviction costs the evicted doc a
  cold replay on its next touch, never bytes.

Counters (README "Observability" registry): ``tenant.shed`` /
``tenant.shed_bytes`` on every trimmed update, the
``tenant.pending_bytes`` gauge for the queue's live total,
``tenant.resident_evictions`` + the ``tenant.resident_bytes`` /
``tenant.resident_docs`` gauges for the resident-state ledger.
Round 18: a trim with a known ``tenant=`` additionally emits the
labeled ``tenant.shed{tenant=}`` counter and a ``tenant.shed``
flight-recorder event (``doc``/``count``/``bytes`` fields), so a
shed shows up attributed in the SLO ledger's route mix, the
``/events?doc=`` filter, and an ``obsq`` query — not just as an
anonymous aggregate.
"""

from __future__ import annotations

from typing import Callable, Deque, Dict, Iterable, List, Optional, Tuple

from crdt_tpu.obs.recorder import get_recorder
from crdt_tpu.obs.tracer import get_tracer


class TenantBudget:
    """Byte + count budget over one tenant's pending update queue."""

    def __init__(self, max_bytes: int = 1 << 22,
                 max_updates: int = 4096):
        self.max_bytes = int(max_bytes)
        self.max_updates = int(max_updates)
        # round 22: per-tenant runtime overrides (the control plane's
        # budget_squeeze actuator) — tenant -> (max_bytes, max_updates)
        self._overrides: Dict[object, Tuple[int, int]] = {}

    def limits(self, tenant=None) -> Tuple[int, int]:
        """The effective ``(max_bytes, max_updates)`` for a tenant:
        its override when the control plane has squeezed it, the
        static budget otherwise."""
        if tenant is not None:
            ov = self._overrides.get(tenant)
            if ov is not None:
                return ov
        return (self.max_bytes, self.max_updates)

    def set_override(self, tenant, max_bytes: int,
                     max_updates: int) -> None:
        self._overrides[tenant] = (
            max(1, int(max_bytes)), max(1, int(max_updates))
        )

    def clear_override(self, tenant) -> None:
        self._overrides.pop(tenant, None)

    def overrides(self) -> Dict[object, Tuple[int, int]]:
        return dict(self._overrides)

    def trim(self, queue: Deque[bytes],
             tenant=None) -> List[bytes]:
        """Shed OLDEST pending updates until ``queue`` fits the
        budget; the newest update is always kept (keep-the-newest).
        Returns the shed blobs (callers count them). ``tenant``,
        when given, attributes the shed: the labeled
        ``tenant.shed{tenant=}`` counter and a ``tenant.shed``
        flight-recorder event carry it into the SLO route mix and
        the ``/events`` filters — and selects any control-plane
        override of the static budget (:meth:`limits`)."""
        max_bytes, max_updates = self.limits(tenant)
        shed: List[bytes] = []
        size = sum(len(b) for b in queue)
        while len(queue) > 1 and (
            size > max_bytes or len(queue) > max_updates
        ):
            old = queue.popleft()
            size -= len(old)
            shed.append(old)
        if shed and tenant is not None:
            nbytes = sum(len(b) for b in shed)
            tracer = get_tracer()
            if tracer.enabled:
                tracer.count("tenant.shed", len(shed),
                             labels={"tenant": tenant})
            rec = get_recorder()
            if rec.enabled:
                rec.record("tenant.shed", doc=str(tenant),
                           count=len(shed), bytes=nbytes)
        return shed


def fair_order(doc_ids: Iterable,
               last_served: Dict) -> List:
    """Dirty docs in service order: least-recently-served first,
    then doc id (deterministic). ``last_served`` maps doc id -> the
    tick index it last converged in (absent = never served, which
    sorts first)."""
    return sorted(doc_ids, key=lambda d: (last_served.get(d, -1), d))


class ResidentBudget:
    """Byte ledger over per-doc resident state (round 15).

    Tracks one server's total resident bytes (each doc's device
    matrix + host column store, :meth:`crdt_tpu.models.incremental.
    IncrementalReplay.resident_bytes`) and answers the two questions
    the tick loop asks:

    - :meth:`fits` — may a doc of this (estimated) size be promoted
      to resident, after evicting least-recently-served residents to
      make room? Eviction happens eagerly inside the call via the
      caller's ``evict`` callback, so the ledger NEVER exceeds the
      budget: an over-budget promotion is refused before the engine
      is built, not rolled back after.
    - :meth:`set_doc` / :meth:`drop_doc` — commit a doc's measured
      bytes (post-promotion, post-round growth) or clear them on
      eviction/fallback.

    ``max_bytes=None`` disables the bound (unbudgeted server).
    ``peak`` tracks the ledger's high-water mark, noted only at
    STABLE points (:meth:`note_peak` — post-enforcement commit, tick
    end), so the published bound is the committed resident state,
    never a mid-enforcement transient."""

    def __init__(self, max_bytes: Optional[int] = None):
        self.max_bytes = None if max_bytes is None else int(max_bytes)
        self._bytes: Dict[object, int] = {}
        self.total = 0
        self.peak = 0

    def doc_bytes(self, doc_id) -> int:
        return self._bytes.get(doc_id, 0)

    def has_doc(self, doc_id) -> bool:
        return doc_id in self._bytes

    def docs(self) -> int:
        return len(self._bytes)

    def note_peak(self) -> int:
        self.peak = max(self.peak, self.total)
        return self.peak

    def set_doc(self, doc_id, nbytes: int) -> None:
        self.total += int(nbytes) - self._bytes.get(doc_id, 0)
        self._bytes[doc_id] = int(nbytes)

    def drop_doc(self, doc_id) -> int:
        """Clear a doc's ledger entry; returns the bytes released."""
        freed = self._bytes.pop(doc_id, 0)
        self.total -= freed
        return freed

    def fits(self, need: int, *,
             lru: Iterable,
             evict: Callable[[object], None]) -> bool:
        """Can ``need`` more resident bytes be admitted? Evicts docs
        from ``lru`` (least-recently-served first; ids without a
        ledger entry are skipped) through the caller's ``evict``
        callback — which must end up calling :meth:`drop_doc` — until
        the admission fits or no evictable doc remains. The caller
        counts evictions (its callback owns the observable side)."""
        if self.max_bytes is None:
            return True
        if need > self.max_bytes:
            return False  # one doc larger than the whole budget
        for doc_id in lru:
            if self.total + need <= self.max_bytes:
                break
            if doc_id not in self._bytes:
                continue
            evict(doc_id)
        return self.total + need <= self.max_bytes


def pack_batches(rows_of: List[Tuple[object, int]],
                 max_rows: int) -> List[List[object]]:
    """Greedy bin-pack of (doc, row_count) pairs — in the given
    fairness order — into dispatch batches of at most ``max_rows``
    rows. A doc larger than ``max_rows`` gets a batch of its own
    (it cannot be split: segments never cross docs, and a doc's
    converge is whole-history)."""
    batches: List[List[object]] = []
    cur: List[object] = []
    cur_rows = 0
    for doc_id, n in rows_of:
        if cur and cur_rows + n > max_rows:
            batches.append(cur)
            cur, cur_rows = [], 0
        cur.append(doc_id)
        cur_rows += n
    if cur:
        batches.append(cur)
    return batches
