"""Seeded storage + device fault adversaries (net/faults.py style).

The network fabric's lesson (PR 2): recovery behavior is only trusted
when the adversary is a reusable, SEEDED object whose schedule replays
identically run to run. This module extends that discipline to the two
failure domains the network schedule cannot reach:

- **disk** — :class:`DiskFaultSchedule` + :class:`FaultyKv` wrap a
  :class:`crdt_tpu.storage.kv.KvLog` and inject ``ENOSPC`` / ``EIO``
  write failures (seeded probabilities or an explicit write-index
  set), TORN batches (the first half of a multi-op batch lands, then
  the write dies — simulating a store without the native log's atomic
  batch), and CRASH POINTS (a :class:`SimulatedCrash` at the j-th op
  of the i-th batch, after which the wrapper is dead — the crash-point
  matrix over ``LogPersistence.compact``/``store_updates`` reopens the
  real file underneath and proves no acked update is lost).
  :class:`FaultyFs` extends the same schedule to the snapshot
  writer's file primitives (write/fsync/rename/unlink), so the
  round-21 snapshot ALICE matrix kills the writer at every op.
- **device** — :class:`DeviceFaultPlan` installs itself as the
  :func:`crdt_tpu.ops.device.set_device_fault_hook` hook and fails the
  first N guarded dispatch attempts with ``RuntimeError`` (optionally
  stage-filtered), driving the retry → split → host ladder in
  :mod:`crdt_tpu.guard.device` without a real dying accelerator.
- **network** — :class:`WithholdDeps`, the dependency-withholding
  adversary: a :class:`crdt_tpu.net.faults.FaultSchedule` that drops
  the first W messages of chosen flows, so later updates arrive first,
  stash as pending, and (under a pending cap) force evictions that
  only the SV re-probe path can repair.
"""

from __future__ import annotations

import errno
import time
from typing import Dict, Iterable, List, Optional, Set, Tuple

from crdt_tpu.net.faults import FaultSchedule, _hash01
from crdt_tpu.obs.recorder import get_recorder


class SimulatedCrash(BaseException):
    """A process kill at a storage op. BaseException on purpose: no
    retry/degrade policy may swallow it (a real crash isn't caught),
    only the test harness driving the crash-point matrix does."""


class DiskFaultSchedule:
    """Per-write fault plan for :class:`FaultyKv`.

    Two addressing modes, composable:

    - seeded probabilities ``enospc`` / ``eio`` / ``torn`` per write
      index (crc32-hashed like the network schedule — replayable),
      with ``heal_after`` capping the total number of injected faults
      (the recovery leg needs the disk to come back);
    - explicit ``fail_writes`` — a set of write indices that raise
      ``eio_errno``-style ``OSError`` deterministically (pinning exact
      retry/degrade counter values in tests).

    ``crash_at=(batch_index, op_index)`` arms ONE simulated process
    kill: the ``batch_index``-th ``write()`` applies its first
    ``op_index`` ops individually, then raises
    :class:`SimulatedCrash` and the wrapper goes dead.
    """

    def __init__(
        self,
        seed: int = 0,
        *,
        enospc: float = 0.0,
        eio: float = 0.0,
        torn: float = 0.0,
        heal_after: Optional[int] = None,
        fail_writes: Iterable[int] = (),
        fail_errno: int = errno.EIO,
        crash_at: Optional[Tuple[int, int]] = None,
    ):
        self.seed = seed
        self.enospc = enospc
        self.eio = eio
        self.torn = torn
        self.heal_after = heal_after
        self.fail_writes: Set[int] = set(fail_writes)
        self.fail_errno = fail_errno
        self.crash_at = crash_at
        self.fired = 0

    def decide(self, n: int) -> Optional[str]:
        """Fault kind for the n-th write(): "enospc" | "eio" | "torn"
        | "crash" | None."""
        if self.crash_at is not None and n == self.crash_at[0]:
            return "crash"
        if n in self.fail_writes:
            self.fired += 1
            return "eio" if self.fail_errno == errno.EIO else "enospc"
        if self.heal_after is not None and self.fired >= self.heal_after:
            return None
        for kind, p in (("enospc", self.enospc), ("eio", self.eio),
                        ("torn", self.torn)):
            if p and _hash01(self.seed, kind, n) < p:
                self.fired += 1
                return kind
        return None


class FaultyKv:
    """KvLog wrapper applying a :class:`DiskFaultSchedule` to writes.

    Same surface as :class:`crdt_tpu.storage.kv.KvLog`; install via
    ``LogPersistence(path, kv_wrapper=lambda kv: FaultyKv(kv, sched))``
    (the seam survives close/open cycles). Only ``write`` (the batch
    verb every LogPersistence mutation uses) consults the schedule;
    reads pass through untouched. ``batches`` records each batch's op
    count so a clean run can enumerate the crash-point matrix."""

    def __init__(self, inner, schedule: DiskFaultSchedule):
        self._inner = inner
        self.schedule = schedule
        self.writes = 0
        self.batches: List[int] = []
        self.dead = False
        self.stats: Dict[str, int] = {
            "enospc": 0, "eio": 0, "torn": 0, "crashed": 0,
        }

    def write(self, batch) -> None:
        if self.dead:
            raise SimulatedCrash("store is dead (post-crash)")
        n = self.writes
        self.writes += 1
        ops = list(batch.ops())
        self.batches.append(len(ops))
        kind = self.schedule.decide(n)
        rec = get_recorder()
        if kind and rec.enabled:
            rec.record("fault.disk", kind=kind, write=n, ops=len(ops))
        if kind == "crash":
            self._apply_ops(ops[: self.schedule.crash_at[1]])
            self.stats["crashed"] += 1
            self.dead = True
            raise SimulatedCrash(
                f"crash at write {n} op {self.schedule.crash_at[1]}"
            )
        if kind == "enospc":
            self.stats["enospc"] += 1
            raise OSError(errno.ENOSPC, "injected: no space left")
        if kind == "eio":
            self.stats["eio"] += 1
            raise OSError(errno.EIO, "injected: I/O error")
        if kind == "torn":
            # the first half lands, then the write dies — the torn
            # multi-op batch a store WITHOUT atomic batches produces
            self._apply_ops(ops[: len(ops) // 2])
            self.stats["torn"] += 1
            raise OSError(errno.EIO, "injected: torn batch")
        self._inner.write(batch)

    def _apply_ops(self, ops) -> None:
        for op, key, val in ops:
            if op == "put":
                self._inner.put(key, val)
            else:
                self._inner.delete(key)

    def close(self) -> None:
        self._inner.close()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultyFs:
    """Snapshot-seam fs adversary (round 21): wraps the
    :class:`crdt_tpu.storage.snapshot.Fs` primitives and applies a
    :class:`DiskFaultSchedule` to every MUTATING op — ``write``,
    ``fsync``, ``rename``, ``fsync_dir``, ``unlink`` — addressed by a
    single per-op index (the n-th mutating op overall), so
    ``crash_at=(i, 0)`` kills the writer immediately BEFORE its i-th
    op and ``torn`` lands half the bytes of a ``write`` before dying.
    Together the two modes cover every prefix of the snapshot
    writer's op sequence — the ALICE matrix ``tests/test_snapshot.py``
    enumerates from a clean run's recorded ``ops`` list. Reads pass
    through untouched (recovery must see whatever the crash left)."""

    def __init__(self, inner, schedule: DiskFaultSchedule):
        self._inner = inner
        self.schedule = schedule
        self.n = 0
        self.ops: List[Tuple[str, str]] = []
        self.dead = False
        self.stats: Dict[str, int] = {
            "enospc": 0, "eio": 0, "torn": 0, "crashed": 0,
        }

    def _gate(self, verb: str, path: str, data: Optional[bytes] = None):
        if self.dead:
            raise SimulatedCrash("fs is dead (post-crash)")
        n = self.n
        self.n += 1
        self.ops.append((verb, path))
        kind = self.schedule.decide(n)
        rec = get_recorder()
        if kind and rec.enabled:
            rec.record("fault.fs", kind=kind, op=n, verb=verb)
        if kind == "crash":
            # crash BEFORE the op applies (torn covers the mid-write
            # states); the fs is dead from here on
            self.stats["crashed"] += 1
            self.dead = True
            raise SimulatedCrash(f"crash at fs op {n} ({verb})")
        if kind == "enospc":
            self.stats["enospc"] += 1
            raise OSError(errno.ENOSPC, "injected: no space left")
        if kind == "eio":
            self.stats["eio"] += 1
            raise OSError(errno.EIO, "injected: I/O error")
        if kind == "torn":
            self.stats["torn"] += 1
            if verb == "write" and data:
                self._inner.write(path, data[: len(data) // 2])
            raise OSError(errno.EIO, "injected: torn write")
        return None

    def write(self, path: str, data: bytes) -> None:
        self._gate("write", path, data)
        self._inner.write(path, data)

    def fsync(self, path: str) -> None:
        self._gate("fsync", path)
        self._inner.fsync(path)

    def rename(self, src: str, dst: str) -> None:
        self._gate("rename", src)
        self._inner.rename(src, dst)

    def fsync_dir(self, path: str) -> None:
        self._gate("fsync_dir", path)
        self._inner.fsync_dir(path)

    def unlink(self, path: str) -> None:
        self._gate("unlink", path)
        self._inner.unlink(path)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class DeviceFaultPlan:
    """Scripted device-fault injector for the guarded-dispatch hook.

    Fails the first ``fail_attempts`` guarded attempts whose stage
    matches ``stages`` (``None`` = every stage) with ``RuntimeError``,
    then heals. Use as a context manager (or ``install()`` /
    ``uninstall()``) — the hook slot in :mod:`crdt_tpu.ops.device` is
    process-global."""

    def __init__(self, fail_attempts: int = 2,
                 stages: Optional[Iterable[str]] = None):
        self.fail_attempts = fail_attempts
        self.stages = set(stages) if stages is not None else None
        self.fired = 0
        self._old = None

    def __call__(self, stage: str, attempt: int) -> None:
        if self.stages is not None and stage not in self.stages:
            return
        if self.fired < self.fail_attempts:
            self.fired += 1
            raise RuntimeError(
                f"injected device fault #{self.fired} at {stage!r} "
                f"(attempt {attempt})"
            )

    def install(self) -> "DeviceFaultPlan":
        from crdt_tpu.ops.device import set_device_fault_hook

        self._old = set_device_fault_hook(self)
        return self

    def uninstall(self) -> None:
        from crdt_tpu.ops.device import set_device_fault_hook

        set_device_fault_hook(self._old)
        self._old = None

    def __enter__(self) -> "DeviceFaultPlan":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


class WithholdDeps(FaultSchedule):
    """Dependency-withholding adversary: DROP the first ``withhold``
    messages of each flow in ``flows`` (``(src_port, dst_port)``
    pairs), then behave like the base schedule. The receiver sees
    later updates before their dependencies, stashes them pending, and
    — under a pending cap — evicts; only the SV re-probe path (the
    withheld sender answers a ready probe with the full diff) repairs
    the gap, which is exactly the recovery the chaos tests pin."""

    def __init__(self, seed: int = 0, *,
                 flows: Iterable[Tuple[int, int]] = (),
                 withhold: int = 2, **kw):
        super().__init__(seed, **kw)
        self.flows = set(flows)
        self.withhold = withhold
        self.withheld = 0

    def decide(self, src: int, dst: int, n: int) -> dict:
        if (src, dst) in self.flows and n < self.withhold:
            self.withheld += 1
            return {"drop": True, "dup": False, "delay": 0,
                    "corrupt": False, "withheld": True}
        return super().decide(src, dst, n)


def retry_with_backoff(fn, *, retries: int, backoff_s: float,
                       counter: Optional[str] = None):
    """Run ``fn`` with up to ``retries`` retries on ``OSError``,
    sleeping ``backoff_s * 2**attempt`` between attempts. The last
    failure re-raises. Shared by the storage failure policy (and any
    future retryable seam); ``counter`` names the tracer counter
    bumped once per retry."""
    from crdt_tpu.obs.tracer import get_tracer

    for attempt in range(retries + 1):
        try:
            return fn()
        except OSError:
            if attempt == retries:
                raise
            if counter:
                get_tracer().count(counter)
            time.sleep(backoff_s * (2 ** attempt))


class MigrationCrashPlan:
    """Process-kill-at-step-k for the fleet migration ladder
    (round 24): :meth:`check` is called at every migration step
    boundary (``fleet/migration.py``); the k-th occurrence of a
    scheduled step raises :class:`SimulatedCrash` — the chaos
    harness catches it and kills that node, exactly like the disk
    matrix's ``crash_at``. Deterministic by construction: occurrence
    counts, no clocks, no randomness."""

    def __init__(self, kill_at: Optional[dict] = None):
        # step name -> 1-based occurrence at which to die
        self.kill_at = dict(kill_at or {})
        self.seen: dict = {}
        self.fired: list = []

    def check(self, step: str) -> None:
        n = self.seen.get(step, 0) + 1
        self.seen[step] = n
        k = self.kill_at.get(step)
        if k is not None and n == k:
            self.fired.append(step)
            raise SimulatedCrash(
                "migration step %s occurrence %d" % (step, n))
