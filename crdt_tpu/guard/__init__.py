"""Resource governance + failure policy: degrade, don't die.

Every other layer of the stack has a loss story (PR 2's retry/relay/
anti-entropy ladder for the network, PR 3's recorder/sentinel for
observing failure) — this package is the same discipline for MEMORY,
DISK, and DEVICE. Four bounded-degradation ladders, each observable
through the tracer and each exercisable by a seeded fault schedule:

- **ingest**  — ``Replica._inbox`` byte/count budget; overflow sheds
  the OLDEST buffered updates and re-arms the anti-entropy/re-probe
  path to re-fetch them (``guard.inbox_shed`` counters).
- **engine**  — ``Engine.pending`` / ``IncrementalReplay._pending``
  record cap; overflow evicts the records FURTHEST from integrable
  (largest clocks — their blocker is deepest), records the missing
  ``(client, clock)`` ranges, and the replica re-probes the blocking
  peer with bounded backoff until the evicted state is re-fetched
  (``engine.pending_evictions``, ``guard.resync_probes``).
- **storage** — ``LogPersistence`` retries failed KV batches with
  backoff, then degrades to a bounded in-memory overflow buffer
  (``persist.degraded`` gauge) and writes it back + ``sync()`` on the
  first successful write (``persist.recovered_updates``).
- **device**  — converge dispatches run through a
  retry → split-in-half → host-route ladder
  (:func:`crdt_tpu.guard.device.dispatch_guarded`), so a TPU OOM or
  transient XLA error yields a slower correct answer instead of an
  exception mid-merge (``device.retries``, ``device.fallback``).
- **tenant**  — the round-14 multi-doc server's admission ladder
  (:mod:`crdt_tpu.guard.tenant`): per-tenant pending-queue budgets
  shed a flooding tenant's OWN oldest updates (keep-the-newest)
  while neighbors stay untouched, plus the fairness ordering and
  dispatch bin-packing (``tenant.shed``, ``tenant.shed_bytes``).

The adversaries live in :mod:`crdt_tpu.guard.faults` (seeded
ENOSPC/EIO/torn-batch disk schedules, crash points, scripted device
faults, a dependency-withholding network schedule) in the
:mod:`crdt_tpu.net.faults` style: deterministic, replayable, pinned by
tier-1 chaos tests (tests/test_guard.py). See README "Overload &
failure policy" for the knob table and counter registry.
"""

from crdt_tpu.guard.device import dispatch_guarded
from crdt_tpu.guard.limits import evict_deepest
from crdt_tpu.guard.tenant import TenantBudget, fair_order, pack_batches
from crdt_tpu.guard.faults import (
    DeviceFaultPlan,
    DiskFaultSchedule,
    FaultyKv,
    SimulatedCrash,
    WithholdDeps,
)

__all__ = [
    "DeviceFaultPlan",
    "DiskFaultSchedule",
    "FaultyKv",
    "SimulatedCrash",
    "TenantBudget",
    "WithholdDeps",
    "dispatch_guarded",
    "evict_deepest",
    "fair_order",
    "pack_batches",
]
