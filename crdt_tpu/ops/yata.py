"""YATA sequence-ordering kernel.

Order semantics (must match ``Engine``'s faithful integrate scan):

- The document order of a sequence is the depth-first traversal of the
  *origin tree*: every item hangs under its left origin (or the
  sequence's virtual root), and a node is emitted before its subtree.
  Subtrees always ride with their root: the Yjs conflict scan never
  separates an item from its origin-descendants (case 2 of the scan
  either adopts or skips whole subtrees).
- What the scan does decide is the ORDER OF SIBLINGS within one origin
  group. For groups where no member's right origin is another member
  ("no attachments" — true for every append-only workload), the order
  is ascending client id with DESCENDING clock within one client: a
  later same-client same-origin sibling hits the scan's break rule and
  is placed BEFORE its predecessor, and an induction over the scan
  shows attachment-free placement otherwise always lands a new sibling
  directly after the last smaller-client sibling, whatever the right
  origins are. The device key (client, ~clock) is therefore EXACT for
  every attachment-free group, duplicates included — the same
  lexicographic rule the map winner kernel uses (ops/lww.py). In the
  general (attachment) case the order follows the full scan rule: a
  new sibling lands after the last smaller-client sibling positioned
  before its *stop point* (its right origin, or the first
  larger-client sibling with the same right origin); larger-client
  siblings with different right origins are scanned through
  transparently.

The split of labor is therefore:

  host   sibling ranks ONLY for groups containing right-origin
         attachments (concurrent inserts anchored inside the same
         sibling set — an exact group-local replay of the scan,
         O(g^2) worst case on that group's g siblings only; g is the
         number of concurrent same-position inserts, bounded by the
         writers racing one position, not by doc size);
  device everything else, vectorized: group detection,
         (client, ~clock) sibling ranks, and the full tree-DFS
         ranking — one lexsort for sibling adjacency, pointer
         doubling to climb last-child chains, successor pointers, and
         Wyllie list ranking. O(n log n) work in O(log n) gather
         rounds, independent of tree depth (the reference's scalar
         integrate is O(n) sequential per chain, crdt.js:294).

Round 12 (the sort diet) narrowed where this full-width kernel runs:
the staged cold replay now precomputes the sibling adjacency and
first-child tables on the host (``ops.packed._stage``, shipped as
staged sections) and ranks them sortlessly with the Pallas
document-order scatter (``ops.pallas_kernels.stream_scatter``), so
``order_sequences``/``tree_order_ranks`` remain the engine-mode
merge path and the differential oracle the staged kernels are tested
against — same semantics, two routes.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Tuple

import jax

from crdt_tpu.compat import enable_x64
import jax.numpy as jnp

from crdt_tpu.ops.device import (
    NULLI,
    dfs_ranks,
    lexsort,
    pack_id,
    run_edge_lookup,
    scatter_perm,
    searchsorted_ids,
)


@partial(jax.jit, static_argnames=("num_segments",))
def tree_order_ranks(
    seg,  # [N] int32 dense sequence id (-1 = not a sequence item)
    parent_idx,  # [N] int32 origin-tree parent (item index), NULLI = root
    key1,  # [N] int64 primary sibling key (scan rank or client)
    key2,  # [N] int64 secondary sibling key (0 or NEGATED clock)
    valid,  # [N] bool
    num_segments: int,
):
    """DFS position of every item within its sequence (tombstones
    included). Returns (rank[N] int32, seq_len[num_segments] int32)."""
    n = seg.shape[0]
    m = n + num_segments
    is_seq = valid & (seg >= 0)

    parent = jnp.where(
        is_seq & (parent_idx >= 0), parent_idx, n + jnp.maximum(seg, 0)
    )
    parent = jnp.where(is_seq, parent, m)  # invalid rows -> overflow bucket

    # sibling adjacency: sort by (parent, key1, key2). Rows routed to
    # the overflow slot m are exactly the non-sequence rows, so every
    # run with parent < m is a clean sibling group.
    order = lexsort([parent, key1, key2])
    p_s = parent[order]
    same_group = jnp.concatenate([p_s[1:] == p_s[:-1], jnp.zeros(1, bool)])
    nxt_sorted = jnp.where(same_group, jnp.roll(order, -1), NULLI).astype(jnp.int32)
    next_sib = scatter_perm(order, nxt_sorted)  # scatter-free inverse

    # dense first-child table via one searchsorted over the run starts
    first_pos, _ = run_edge_lookup(p_s, m, side="left")
    first_child = jnp.where(
        first_pos >= 0, order[jnp.clip(first_pos, 0, n - 1)], NULLI
    ).astype(jnp.int32)

    # DFS successor assembly + Wyllie ranking (shared helper; fixpoint
    # early exit keeps rounds at the real document depth)
    dist_to_end = dfs_ranks(parent, next_sib, first_child, is_seq,
                            num_segments)

    root_dist = dist_to_end[n + jnp.maximum(seg, 0)]
    rank = jnp.where(is_seq, root_dist - dist_to_end[:n] - 1, NULLI).astype(
        jnp.int32
    )
    return rank, dist_to_end[n:]


@partial(jax.jit, static_argnames=("num_segments",))
def converge_sequences(
    client,  # [N] int32
    clock,  # [N] int64
    parent_is_root,  # [N] bool
    parent_a,  # [N] int64  root name id | parent item client
    parent_b,  # [N] int64  -1           | parent item clock
    key_id,  # [N] int32  -1 for sequence rows (map rows are skipped)
    origin_client,  # [N] int32
    origin_clock,  # [N] int64
    valid,  # [N] bool
    num_segments: int,
):
    """Union-level sequence ordering, entirely on device: dedup by
    packed id, dense per-parent segments, origin resolution by binary
    search, then the DFS rank kernel. The union-side counterpart of
    :func:`crdt_tpu.ops.merge.converge_maps` — together they are the
    full device ``applyUpdate`` of a gossip round (crdt.js:294).

    Returns ``(order, seg, rank, seq_len)``; all but ``order`` live in
    id-sorted space and ``order[i]`` maps sorted position i back to the
    caller's row. Sibling order within an origin group is the
    (client asc, clock DESC) key — exact for every attachment-free
    group, same-client duplicates included (see module docstring);
    only right-origin attachment groups need the host scan, which is
    :func:`order_sequences` / ``core.device_apply``'s job.
    """
    n = client.shape[0]
    ikey = jnp.where(valid, pack_id(client, clock), jnp.int64(2**62))
    order = jnp.argsort(ikey, stable=True)
    ikey = ikey[order]
    client = client[order]
    clock = clock[order]
    parent_is_root = parent_is_root[order]
    parent_a = parent_a[order]
    parent_b = parent_b[order]
    key_id = key_id[order]
    origin_client = origin_client[order]
    origin_clock = origin_clock[order]
    valid = valid[order]
    dup = jnp.concatenate([jnp.zeros(1, bool), ikey[1:] == ikey[:-1]])
    uniq_valid = valid & ~dup
    is_seq = uniq_valid & (key_id < 0)

    # dense per-parent segments (same composite-change scheme as
    # converge_maps, restricted to sequence rows)
    segkey = [
        (~is_seq).astype(jnp.int32),
        parent_is_root.astype(jnp.int32),
        jnp.where(is_seq, parent_a, jnp.int64(-2)),
        jnp.where(is_seq, parent_b, jnp.int64(-2)),
    ]
    sorder = lexsort(segkey)
    changed = jnp.zeros(n, bool)
    for k in segkey:
        ks = k[sorder]
        changed = changed | jnp.concatenate([jnp.ones(1, bool), ks[1:] != ks[:-1]])
    seg_sorted = jnp.cumsum(changed.astype(jnp.int32)) - 1
    seg = scatter_perm(sorder, seg_sorted)
    seg = jnp.where(is_seq, seg, NULLI)

    # origin rows; cross-segment / absent origins hang off the segment
    # root (the GC'd-origin convention shared with map_winners)
    okey = pack_id(origin_client, origin_clock)
    origin_idx = searchsorted_ids(ikey, okey)
    oseg = jnp.where(
        origin_idx >= 0, seg[jnp.clip(origin_idx, 0, n - 1)], NULLI
    )
    parent_idx = jnp.where(
        (origin_idx >= 0) & (oseg == seg), origin_idx, NULLI
    )

    rank, seq_len = tree_order_ranks(
        seg,
        parent_idx,
        client.astype(jnp.int64),
        -clock.astype(jnp.int64),  # clock-DESC within a client
        is_seq,
        num_segments=num_segments,
    )
    return order, seg, rank, seq_len


# ---------------------------------------------------------------------------
# host side: orphan drops + sibling ranks for attachment groups
# ---------------------------------------------------------------------------


def drop_orphan_subtrees(rows, seg, parent_idx) -> list:
    """Keep only rows whose origin-ancestor path reaches a chain root
    (parent < 0) without crossing a segment boundary. Orphans (items
    whose origin is a GC filler or a foreign row) get ``seg = -1`` —
    the engine splices them after a chain-less row, so its head walk
    never emits them — and the drop cascades to their subtrees.
    Vectorized reachability: numpy pointer doubling over the parent
    function, O(rows log depth) array work instead of a python BFS.

    ``rows`` is an iterable of row indices; ``seg``/``parent_idx`` are
    indexable by row. Mutates ``seg`` in place; returns the kept rows
    in input order.
    """
    import numpy as np

    rows = np.asarray(list(rows), dtype=np.int64)
    n = len(rows)
    if n == 0:
        return []
    seg_np = np.asarray(seg)
    par_np = np.asarray(parent_idx)
    # local index of each row's parent (rows outside the set, or
    # out-of-range parent references, -> -1)
    m = int(seg_np.shape[0])
    pos = np.full(m, -1, np.int64)
    pos[rows] = np.arange(n)
    p = par_np[rows]
    in_range = (p >= 0) & (p < m)
    pc = np.clip(p, 0, m - 1)
    p_local = np.where(in_range, pos[pc], -1)
    same_seg = in_range & (p_local >= 0) & (seg_np[pc] == seg_np[rows])
    ok = p < 0  # chain roots are reachable; dead ends (cross-seg /
    # foreign parents) self-loop with ok=False and stay False
    idx = np.arange(n)
    ptr = np.where(same_seg, p_local, idx)
    for _ in range(max(1, (max(n, 2) - 1).bit_length() + 1)):
        ok = ok | ok[ptr]
        ptr = ptr[ptr]
    for i in rows[~ok]:
        seg[int(i)] = -1
    return rows[ok].tolist()


def _simulate_group(sibs: List[dict], member_ids: set) -> List[Tuple[int, int]]:
    """Exact group-local replay of the Yjs conflict scan.

    ``sibs``: [{id, client, clock, right}] of one origin group. Returns
    member ids in final order. Items are integrated in causal rounds
    (an item whose right origin is an unplaced member waits); within a
    round, processing order is (client, clock) — convergence makes any
    causal order equivalent.
    """
    remaining = sorted(sibs, key=lambda s: (s["client"], s["clock"]))
    placed: List[dict] = []
    placed_ids: set = set()
    while remaining:
        progress = False
        still = []
        for s in remaining:
            anchor = s["right"] if s["right"] in member_ids else None
            if anchor is not None and anchor not in placed_ids:
                still.append(s)
                continue
            left = -1
            for i, t in enumerate(placed):
                if anchor is not None and t["id"] == anchor:
                    break
                if t["client"] < s["client"]:
                    left = i
                elif t["client"] > s["client"] and t["right"] == s["right"]:
                    break
            placed.insert(left + 1, s)
            placed_ids.add(s["id"])
            progress = True
        if not progress:
            # malformed input (anchor cycle): append rest deterministically
            for s in still:
                placed.append(s)
                placed_ids.add(s["id"])
            still = []
        remaining = still
    return [s["id"] for s in placed]


def order_hard_segment(seg_records, ref_exists=None) -> List[Tuple[int, int]]:
    """Exact chain order for one sequence via a throwaway scalar
    integrate — the fallback for segments whose right origins the
    sibling-rank model cannot express (rights pointing INTO a member's
    subtree, dangling rights, cross-parent rights: shapes honest Yjs
    peers never produce, but hostile updates can).

    The slice is made integrable WITHOUT changing its chain outcome:
    per-client clocks renumber to a contiguous run (the real document
    may interleave other collections' clocks, which must not pend the
    slice), and references to ids outside the slice are rewritten —
    ones that EXIST elsewhere (``ref_exists``; default: treat as
    existing) get a synthetic donor item in a foreign chain (dep
    satisfied, never encountered by this chain's scan, equality
    classes of right origins preserved), while truly dangling ones map
    to absent ids so the member pends, exactly like the engine."""
    from crdt_tpu.core.engine import Engine
    from crdt_tpu.core.records import ItemRecord

    # dedup by id: redelivered blobs reach some callers unmerged, and a
    # duplicate would double-count in the clock renumbering (leaving a
    # gap that pends the whole client)
    uniq: Dict[Tuple[int, int], object] = {}
    for r in seg_records:
        uniq.setdefault(r.id, r)
    seg_records = list(uniq.values())

    by_client: Dict[int, List[Tuple[int, int]]] = {}
    for r in sorted(seg_records, key=lambda x: (x.client, x.clock)):
        by_client.setdefault(r.client, []).append(r.id)
    remap = {
        rid: (rid[0], i)
        for ids_ in by_client.values()
        for i, rid in enumerate(ids_)
    }
    SENT = 1 << 45  # outside any real client-id namespace
    ext: Dict[Tuple[int, int], Tuple[int, int]] = {}
    donors: List[ItemRecord] = []

    def map_ref(ref):
        if ref is None:
            return None
        if ref in remap:
            return remap[ref]
        if ref not in ext:
            sid = (SENT + len(ext), 0)
            ext[ref] = sid
            if ref_exists is None or ref_exists(ref):
                donors.append(ItemRecord(
                    client=sid[0], clock=0, parent_root="__other__",
                    content=None,
                ))
            # else: absent id — the referencing member pends
        return ext[ref]

    rewritten = [
        ItemRecord(
            client=r.client, clock=remap[r.id][1], parent_root="__hard__",
            origin=map_ref(r.origin), right=map_ref(r.right), kind=r.kind,
            type_ref=r.type_ref,
        )
        for r in seg_records
    ]
    eng = Engine(10**9)
    eng.apply_records(donors + rewritten)
    inv = {v: k for k, v in remap.items()}
    return [
        inv[i]
        for i in eng.seq_order_table().get(("root", "__hard__"), [])
        if i in inv
    ]


def right_walk_is_hard(
    right, member_ids, lookup, seg_of, gseg, id_of, origin_of, max_steps
) -> bool:
    """Shared hard-shape walk for one out-of-group right origin: True
    when it is dangling in the caller's universe, in another segment,
    or a DESCENDANT of a group member (the integrate scan would stop
    inside that member's subtree, splitting it — inexpressible by
    sibling ranks). ``max_steps`` must bound the UNIVERSE size, not
    the group size: subtree depth is unrelated to sibling count."""
    cur = lookup(right)
    if cur is None:
        return True  # dangling right: the engine pends the member
    if seg_of(cur) != gseg:
        return True  # cross-parent right: malformed
    steps = 0
    while cur is not None and steps <= max_steps:
        steps += 1
        if id_of(cur) in member_ids:
            return True  # right sits inside a member's subtree
        cur = origin_of(cur)
    return False


def _group_is_hard(rows, member_ids, row_of, records, seg, gseg) -> bool:
    for i in rows:
        right = records[i].right
        if right is None or right in member_ids:
            continue  # no right, or a plain in-group anchor
        if right_walk_is_hard(
            right,
            member_ids,
            row_of.get,
            lambda cur: seg[cur],
            gseg,
            lambda cur: records[cur].id,
            lambda cur: (
                row_of.get(records[cur].origin)
                if records[cur].origin is not None
                else None
            ),
            len(records),
        ):
            return True
    return False


def order_sequences(records):
    """Order a record union's sequences through the device kernel.

    Returns {parent: [(client, clock), ...]} in final document order,
    tombstones included. Parent is ("root", name) or ("item", c, k).
    """
    import numpy as np

    from crdt_tpu.core.store import K_GC
    from crdt_tpu.ops.merge import _pad_to, resolve_parents

    records = resolve_parents(records)
    uniq = {}
    for r in records:
        uniq.setdefault(r.id, r)
    records = list(uniq.values())
    n = len(records)
    if n == 0:
        return {}
    row_of = {r.id: i for i, r in enumerate(records)}

    seq_specs: Dict[Tuple, int] = {}
    seg = np.full(n, -1, np.int32)
    parent_idx = np.full(n, -1, np.int32)
    key1 = np.zeros(n, np.int64)
    key2 = np.zeros(n, np.int64)
    seq_rows: List[int] = []
    for i, r in enumerate(records):
        if r.kind == K_GC or r.key is not None:
            continue
        if r.parent_root is not None:
            spec: Tuple = ("root", r.parent_root)
        elif r.parent_item is not None:
            spec = ("item",) + tuple(r.parent_item)
        else:
            continue  # unresolvable parent (origin outside batch)
        seg[i] = seq_specs.setdefault(spec, len(seq_specs))
        if r.origin is not None and r.origin in row_of:
            parent_idx[i] = row_of[r.origin]
        key1[i] = r.client
        key2[i] = -r.clock  # clock-DESC within a client (break rule)
        seq_rows.append(i)

    seg_all = seg.copy()  # pre-drop assignment (hard fallback needs it)
    seq_rows = drop_orphan_subtrees(seq_rows, seg, parent_idx)

    # group members by origin-tree parent; detect attachment groups
    # and HARD segments (rights the sibling-rank model cannot express
    # — those sequences fall back to an exact scalar integrate)
    groups: Dict[Tuple[int, int], List[int]] = {}
    for i in seq_rows:
        groups.setdefault((seg[i], parent_idx[i]), []).append(i)
    hard_segs: set = set()
    for (gseg, gparent), rows in groups.items():
        if gseg in hard_segs:
            continue
        member_ids = {records[i].id for i in rows}
        if _group_is_hard(rows, member_ids, row_of, records, seg, gseg):
            hard_segs.add(gseg)
            continue
        has_attachment = any(
            records[i].right in member_ids for i in rows if records[i].right
        )
        if not has_attachment:
            # (client, ~clock) keys are exact here — including
            # same-client duplicates, which the break rule places
            # clock-descending (see module docstring)
            continue
        sibs = [
            {
                "id": records[i].id,
                "client": records[i].client,
                "clock": records[i].clock,
                "right": records[i].right,
            }
            for i in rows
        ]
        ordered = _simulate_group(sibs, member_ids)
        for rank_pos, sid in enumerate(ordered):
            key1[row_of[sid]] = rank_pos
            key2[row_of[sid]] = 0

    # power-of-two buckets for BOTH static dims so jit compiles once
    # per bucket, not once per (record count, sequence count) pair
    num_segments = 1 << max(3, (max(1, len(seq_specs)) - 1).bit_length())
    pad = 1 << max(9, (n - 1).bit_length())

    # this is HOST machinery (the right-bearing wholesale pass a
    # resident replica runs below the crossover): the ranking kernel
    # executes on the LOCAL CPU backend — on a tunnelled platform the
    # default backend would charge ~3 fixed latencies per call, more
    # than many whole host rounds (measured: it compressed the
    # resident swarm's margin 1.9x -> 1.1x before this pin)
    from crdt_tpu.ops.device import on_local_cpu

    with on_local_cpu(
        cache_key=("order_sequences", pad, num_segments)
    ), enable_x64(True):
        rank, _ = tree_order_ranks(
            jnp.asarray(_pad_to(seg, pad, -1)),
            jnp.asarray(_pad_to(parent_idx, pad, -1)),
            jnp.asarray(_pad_to(key1, pad, 0)),
            jnp.asarray(_pad_to(key2, pad, 0)),
            jnp.asarray(np.arange(pad) < n),
            num_segments=num_segments,
        )
        rank = np.asarray(rank[:n])
    by_spec: Dict[int, List[Tuple[int, Tuple[int, int]]]] = {}
    for i in seq_rows:
        if int(seg[i]) in hard_segs:
            continue  # ordered by the scalar fallback below
        by_spec.setdefault(int(seg[i]), []).append((int(rank[i]), records[i].id))
    inv = {v: k for k, v in seq_specs.items()}
    out = {spec: [] for spec in seq_specs}
    for sid, pairs in by_spec.items():
        pairs.sort()
        out[inv[sid]] = [pid for _, pid in pairs]
    for sid in hard_segs:
        out[inv[sid]] = order_hard_segment(
            [records[i] for i in range(n) if seg_all[i] == sid],
            ref_exists=lambda ref: ref in row_of,
        )
    return out
