"""HBM-resident columnar union — converge without restaging the doc.

The north star names this explicitly: incoming peer updates are
buffered into columnar tensors, applied as one vectorized applyUpdate,
and the ``crdt.c`` cache is rebuilt from HBM — NOT re-uploaded from the
host every dispatch. :class:`ResidentColumns` is that buffer:

- the op columns live in device memory across rounds (capacity grows
  by power-of-two buckets, one recompile per bucket);
- ``append`` ships ONLY the new delta over PCIe/ICI (padded to a
  delta bucket) and splices it in-place with ``dynamic_update_slice``;
- ``converge`` dispatches the LWW map kernel and the YATA sequence
  kernel over the resident buffers and returns DEVICE arrays — nothing
  crosses back to the host until the caller materializes.

Client ids are interned to DENSE, ORDER-PRESERVING values on append:
the kernels pack (client << 40 | clock) into int64, which random
31-bit replica ids would alias (same rationale as the remap in
``core.device_apply``), and YATA/LWW sibling rules compare client ids,
so the mapping must be monotone in the raw id. Dense id = rank among
all raw ids seen; a new id arriving BETWEEN existing ones shifts later
ranks, triggering a one-off on-device relabel of the client columns
(O(capacity), at most once per distinct client — and never when the
client set is pre-registered via the ``clients=`` argument, which the
fleet path can always do).

Product-path counterpart: ``core.device_apply.rebuild_chains`` keeps
per-parent incremental state on the host engine; this class is the
firehose path (ReplicaFleet fan-in, trace replay, the benchmark).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax

from crdt_tpu.compat import enable_x64
import jax.numpy as jnp
import numpy as np

from crdt_tpu.ops import deleteset as ds_ops
from crdt_tpu.ops.device import bucket_pow2 as _bucket  # shared policy

# (name, dtype) in kernel argument order
COLUMNS = (
    ("client", np.int32),
    ("clock", np.int64),
    ("parent_is_root", np.bool_),
    ("parent_a", np.int64),
    ("parent_b", np.int64),
    ("key_id", np.int32),
    ("origin_client", np.int32),
    ("origin_clock", np.int64),
    ("valid", np.bool_),
)

_FILL = {
    "client": 0,
    "clock": 0,
    "parent_is_root": False,
    "parent_a": -2,
    "parent_b": -2,
    "key_id": -1,
    "origin_client": -1,
    "origin_clock": -1,
    "valid": False,
}


@partial(jax.jit, static_argnames=("num_segments", "ds_mode"))
def _converge_all(bufs, d_client, d_start, d_end, num_segments,
                  ds_mode=None):
    """Map + sequence convergence as ONE XLA program: both kernels
    share the packed-id sort and dedup, which XLA CSEs when they are
    traced together — one dispatch instead of two (each dispatch costs
    ~0.35s in the tunnelled platform's degraded state). ``ds_mode``
    is the host-computed delete-mask kernel static (crdtlint CL702 —
    never read CRDT_TPU_PALLAS in here)."""
    from crdt_tpu.ops.merge import converge_maps
    from crdt_tpu.ops.yata import converge_sequences

    maps_out = converge_maps(
        *bufs, d_client, d_start, d_end, num_segments=num_segments,
        ds_mode=ds_mode,
    )
    seq_out = converge_sequences(*bufs, num_segments=num_segments)
    return maps_out, seq_out


@partial(jax.jit, donate_argnums=(0,))
def _splice(bufs, delta, n):
    """In-place (donated) append of a padded delta at offset n."""
    return tuple(
        jax.lax.dynamic_update_slice(b, d, (n,)) for b, d in zip(bufs, delta)
    )


@partial(jax.jit, donate_argnums=(0,),
         static_argnames=("num_segments", "ds_mode"))
def _splice_and_converge(bufs, delta, n, d_client, d_start, d_end,
                         num_segments, ds_mode=None):
    """Append + full convergence as ONE program: the splice, the LWW
    map kernel, and the YATA sequence kernel trace together, so a
    single-delta replay pays one dispatch instead of two (each costs
    ~0.35s in the tunnelled platform's degraded state). ``ds_mode``
    threads through to the delete-mask kernel (host-computed static,
    crdtlint CL702)."""
    bufs = tuple(
        jax.lax.dynamic_update_slice(b, d, (n,)) for b, d in zip(bufs, delta)
    )
    maps_out, seq_out = _converge_all(
        bufs, d_client, d_start, d_end, num_segments=num_segments,
        ds_mode=ds_mode,
    )
    return bufs, maps_out, seq_out


@partial(jax.jit, donate_argnums=(0,))
def _relabel(bufs, perm):
    """Rewrite the client columns through an old-dense -> new-dense
    permutation (invalid rows hold 0, which perm covers; -1 origins
    stay -1)."""
    bufs = list(bufs)
    bufs[0] = perm[bufs[0]].astype(bufs[0].dtype)
    oc = bufs[6]
    bufs[6] = jnp.where(
        oc >= 0, perm[jnp.clip(oc, 0, perm.shape[0] - 1)], oc
    ).astype(oc.dtype)
    return tuple(bufs)


class ResidentColumns:
    """Growable device-resident op columns + in-place convergence."""

    def __init__(
        self,
        capacity: int = 1 << 15,
        clients: Optional[Sequence[int]] = None,
    ):
        cap = _bucket(capacity)
        self.n = 0
        self._seen: List[int] = []  # sorted raw client ids
        self._dense: Dict[int, int] = {}  # raw -> rank among seen
        if clients is not None and len(clients) > 0:
            self._intern(np.asarray(sorted(set(int(c) for c in clients))))
        with enable_x64(True):
            self._bufs: Tuple[jnp.ndarray, ...] = tuple(
                jnp.full(cap, _FILL[name], dtype=dt) for name, dt in COLUMNS
            )

    @property
    def capacity(self) -> int:
        return int(self._bufs[0].shape[0])

    def device_bytes(self) -> int:
        """Device-memory footprint of the resident buffers — the
        firehose-path counterpart of :meth:`crdt_tpu.models.
        incremental.IncrementalReplay.resident_bytes` (which is what
        the multi-doc server's ``CRDT_TPU_MT_RESIDENT_BYTES`` budget
        actually sums): a capacity planner sizing a fleet of
        ResidentColumns stores reads it per store. Computed from
        dtype itemsizes, so it tracks the column schema
        automatically."""
        cap = self.capacity
        return sum(
            cap * np.dtype(dt).itemsize for _, dt in COLUMNS
        )

    # -- client interning ---------------------------------------------
    def _intern(self, raw_ids: np.ndarray) -> Optional[np.ndarray]:
        """Register raw ids. Returns an old-dense->new-dense permutation
        when existing ranks shifted (caller must relabel the resident
        columns), else None."""
        new = sorted(set(int(c) for c in raw_ids) - self._dense.keys())
        if not new:
            return None
        shifted = bool(self._seen) and new[0] < self._seen[-1]
        old = dict(self._dense) if shifted else None
        self._seen = sorted(self._seen + new)
        self._seen_arr = np.asarray(self._seen)
        self._dense = {raw: i for i, raw in enumerate(self._seen)}
        if old and self.n:
            perm = np.zeros(len(old), np.int32)
            for raw, od in old.items():
                perm[od] = self._dense[raw]
            return perm
        return None

    def _map_clients(self, arr: np.ndarray, mask: np.ndarray) -> np.ndarray:
        """Raw -> dense for the masked entries; others untouched."""
        out = arr.astype(np.int32).copy()
        if mask.any():
            vals = arr[mask]
            out[mask] = np.searchsorted(self._seen_arr, vals).astype(np.int32)
        return out

    # -- append / converge --------------------------------------------
    def _prepare_delta(self, cols: Dict[str, np.ndarray], k: int):
        """Shared append preamble: client interning (+ on-device
        relabel when ranks shifted), capacity growth, and the padded
        delta arrays. Caller splices inside the same x64 scope."""
        valid = np.asarray(cols["valid"][:k], bool)
        raw_cl = np.asarray(cols["client"][:k])
        raw_ocl = np.asarray(cols["origin_client"][:k])
        perm = self._intern(
            np.concatenate([raw_cl[valid], raw_ocl[raw_ocl >= 0]])
        )
        if perm is not None:
            self._bufs = _relabel(self._bufs, jnp.asarray(perm))
        if self.n + k > self.capacity:
            self._grow(self.n + k)
        kpad = min(_bucket(k, floor=6), self.capacity)
        if self.n + kpad > self.capacity:
            self._grow(self.n + kpad)
        from crdt_tpu.ops.device import xfer_put

        delta = []
        for name, dt in COLUMNS:
            arr = np.full(kpad, _FILL[name], dtype=dt)
            if name == "client":
                arr[:k] = np.where(
                    valid, self._map_clients(raw_cl, valid), 0
                )
            elif name == "origin_client":
                arr[:k] = self._map_clients(raw_ocl, raw_ocl >= 0)
            else:
                arr[:k] = cols[name][:k]
            # the xfer seam accounts every appended delta column:
            # resident rounds must show DELTA-sized h2d growth, never
            # the full matrix (pinned by tests/test_transfer_diet.py)
            delta.append(xfer_put(arr, label="resident.delta"))
        return tuple(delta)

    def append(self, cols: Dict[str, np.ndarray]) -> None:
        """Splice a host-side delta into the resident union. Only the
        delta (padded to its power-of-two bucket) crosses to the
        device; resident rows never re-upload."""
        k = len(cols["client"])
        if k == 0:
            return
        with enable_x64(True):
            delta = self._prepare_delta(cols, k)
            self._bufs = _splice(self._bufs, delta, jnp.int32(self.n))
        self.n += k

    def append_converge(
        self,
        cols: Dict[str, np.ndarray],
        num_segments: Optional[int] = None,
        d_client=None,
        d_start=None,
        d_end=None,
    ):
        """Fused append + convergence: the splice and both kernels run
        as ONE dispatch — the single-delta replay path. Equivalent to
        ``append(cols)`` then ``converge(...)``."""
        k = len(cols["client"])
        if k == 0:
            return self.converge(
                num_segments=num_segments, d_client=d_client,
                d_start=d_start, d_end=d_end,
            )
        with enable_x64(True):
            delta = self._prepare_delta(cols, k)
            # default segments AFTER _prepare_delta: it may grow the
            # capacity, and a pre-growth default would alias segment
            # ids (diverging from append() + converge())
            segs = num_segments or self.capacity
            if d_client is None:
                d_client = jnp.full(16, -1, jnp.int32)
                d_start = jnp.full(16, -1, jnp.int64)
                d_end = jnp.full(16, -1, jnp.int64)
            self._bufs, maps_out, seq_out = _splice_and_converge(
                self._bufs, delta, jnp.int32(self.n),
                d_client, d_start, d_end, num_segments=segs,
                ds_mode=ds_ops.mask_mode(),  # host static (CL702)
            )
        self.n += k
        return maps_out, seq_out

    def _grow(self, need: int) -> None:
        new_cap = _bucket(need)
        grown = []
        for (name, dt), b in zip(COLUMNS, self._bufs):
            nb = jnp.full(new_cap, _FILL[name], dtype=dt)
            grown.append(jax.lax.dynamic_update_slice(nb, b, (0,)))
        self._bufs = tuple(grown)

    def dense_client(self, raw: int) -> Optional[int]:
        """Dense id currently assigned to a raw client id."""
        return self._dense.get(int(raw))

    def converge(
        self,
        num_segments: Optional[int] = None,
        d_client=None,
        d_start=None,
        d_end=None,
    ):
        """One full device applyUpdate over the resident union: map
        winners (converge_maps) + sequence order (converge_sequences)
        in a single fused dispatch. Returns the two kernels' raw
        outputs as DEVICE arrays.

        Delete ranges, when given, must use DENSE client ids
        (:meth:`dense_client`).
        """
        segs = num_segments or self.capacity
        with enable_x64(True):
            if d_client is None:
                d_client = jnp.full(16, -1, jnp.int32)
                d_start = jnp.full(16, -1, jnp.int64)
                d_end = jnp.full(16, -1, jnp.int64)
            return _converge_all(
                self._bufs, d_client, d_start, d_end,
                num_segments=segs,
                ds_mode=ds_ops.mask_mode(),  # host static (CL702)
            )
