"""HBM-resident columnar union — converge without restaging the doc.

The north star names this explicitly: incoming peer updates are
buffered into columnar tensors, applied as one vectorized applyUpdate,
and the ``crdt.c`` cache is rebuilt from HBM — NOT re-uploaded from the
host every dispatch. :class:`ResidentColumns` is that buffer:

- the op columns live in device memory across rounds (capacity grows
  by power-of-two buckets, one recompile per bucket);
- ``append`` ships ONLY the new delta over PCIe/ICI (padded to a
  delta bucket) and splices it in-place with ``dynamic_update_slice``;
- ``converge`` dispatches the LWW map kernel and the YATA sequence
  kernel over the resident buffers and returns DEVICE arrays — nothing
  crosses back to the host until the caller materializes.

Client ids are interned to DENSE, ORDER-PRESERVING values on append:
the kernels pack (client << 40 | clock) into int64, which random
31-bit replica ids would alias (same rationale as the remap in
``core.device_apply``), and YATA/LWW sibling rules compare client ids,
so the mapping must be monotone in the raw id. Dense id = rank among
all raw ids seen; a new id arriving BETWEEN existing ones shifts later
ranks, triggering a one-off on-device relabel of the client columns
(O(capacity), at most once per distinct client — and never when the
client set is pre-registered via the ``clients=`` argument, which the
fleet path can always do).

Product-path counterpart: ``core.device_apply.rebuild_chains`` keeps
per-parent incremental state on the host engine; this class is the
firehose path (ReplicaFleet fan-in, trace replay, the benchmark).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax

from crdt_tpu.compat import enable_x64
import jax.numpy as jnp
import numpy as np

from crdt_tpu.obs.tracer import get_tracer
from crdt_tpu.ops import deleteset as ds_ops
from crdt_tpu.ops.device import bucket_pow2 as _bucket  # shared policy

# (name, dtype) in kernel argument order
COLUMNS = (
    ("client", np.int32),
    ("clock", np.int64),
    ("parent_is_root", np.bool_),
    ("parent_a", np.int64),
    ("parent_b", np.int64),
    ("key_id", np.int32),
    ("origin_client", np.int32),
    ("origin_clock", np.int64),
    ("valid", np.bool_),
)

_FILL = {
    "client": 0,
    "clock": 0,
    "parent_is_root": False,
    "parent_a": -2,
    "parent_b": -2,
    "key_id": -1,
    "origin_client": -1,
    "origin_clock": -1,
    "valid": False,
}


@partial(jax.jit, static_argnames=("num_segments", "ds_mode"))
def _converge_all(bufs, d_client, d_start, d_end, num_segments,
                  ds_mode=None):
    """Map + sequence convergence as ONE XLA program: both kernels
    share the packed-id sort and dedup, which XLA CSEs when they are
    traced together — one dispatch instead of two (each dispatch costs
    ~0.35s in the tunnelled platform's degraded state). ``ds_mode``
    is the host-computed delete-mask kernel static (crdtlint CL702 —
    never read CRDT_TPU_PALLAS in here)."""
    from crdt_tpu.ops.merge import converge_maps
    from crdt_tpu.ops.yata import converge_sequences

    maps_out = converge_maps(
        *bufs, d_client, d_start, d_end, num_segments=num_segments,
        ds_mode=ds_mode,
    )
    seq_out = converge_sequences(*bufs, num_segments=num_segments)
    return maps_out, seq_out


@partial(jax.jit, donate_argnums=(0,))
def _splice(bufs, delta, n):
    """In-place (donated) append of a padded delta at offset n."""
    return tuple(
        jax.lax.dynamic_update_slice(b, d, (n,)) for b, d in zip(bufs, delta)
    )


@partial(jax.jit, donate_argnums=(0,),
         static_argnames=("num_segments", "ds_mode"))
def _splice_and_converge(bufs, delta, n, d_client, d_start, d_end,
                         num_segments, ds_mode=None):
    """Append + full convergence as ONE program: the splice, the LWW
    map kernel, and the YATA sequence kernel trace together, so a
    single-delta replay pays one dispatch instead of two (each costs
    ~0.35s in the tunnelled platform's degraded state). ``ds_mode``
    threads through to the delete-mask kernel (host-computed static,
    crdtlint CL702)."""
    bufs = tuple(
        jax.lax.dynamic_update_slice(b, d, (n,)) for b, d in zip(bufs, delta)
    )
    maps_out, seq_out = _converge_all(
        bufs, d_client, d_start, d_end, num_segments=num_segments,
        ds_mode=ds_mode,
    )
    return bufs, maps_out, seq_out


@partial(jax.jit, donate_argnums=(0,))
def _relabel(bufs, perm):
    """Rewrite the client columns through an old-dense -> new-dense
    permutation (invalid rows hold 0, which perm covers; -1 origins
    stay -1)."""
    bufs = list(bufs)
    bufs[0] = perm[bufs[0]].astype(bufs[0].dtype)
    oc = bufs[6]
    bufs[6] = jnp.where(
        oc >= 0, perm[jnp.clip(oc, 0, perm.shape[0] - 1)], oc
    ).astype(oc.dtype)
    return tuple(bufs)


class ResidentColumns:
    """Growable device-resident op columns + in-place convergence."""

    def __init__(
        self,
        capacity: int = 1 << 15,
        clients: Optional[Sequence[int]] = None,
    ):
        cap = _bucket(capacity)
        self.n = 0
        self._seen: List[int] = []  # sorted raw client ids
        self._dense: Dict[int, int] = {}  # raw -> rank among seen
        if clients is not None and len(clients) > 0:
            self._intern(np.asarray(sorted(set(int(c) for c in clients))))
        with enable_x64(True):
            self._bufs: Tuple[jnp.ndarray, ...] = tuple(
                jnp.full(cap, _FILL[name], dtype=dt) for name, dt in COLUMNS
            )

    @property
    def capacity(self) -> int:
        return int(self._bufs[0].shape[0])

    def device_bytes(self) -> int:
        """Device-memory footprint of the resident buffers — the
        firehose-path counterpart of :meth:`crdt_tpu.models.
        incremental.IncrementalReplay.resident_bytes` (which is what
        the multi-doc server's ``CRDT_TPU_MT_RESIDENT_BYTES`` budget
        actually sums): a capacity planner sizing a fleet of
        ResidentColumns stores reads it per store. Computed from
        dtype itemsizes, so it tracks the column schema
        automatically."""
        cap = self.capacity
        return sum(
            cap * np.dtype(dt).itemsize for _, dt in COLUMNS
        )

    # -- client interning ---------------------------------------------
    def _intern(self, raw_ids: np.ndarray) -> Optional[np.ndarray]:
        """Register raw ids. Returns an old-dense->new-dense permutation
        when existing ranks shifted (caller must relabel the resident
        columns), else None."""
        new = sorted(set(int(c) for c in raw_ids) - self._dense.keys())
        if not new:
            return None
        shifted = bool(self._seen) and new[0] < self._seen[-1]
        old = dict(self._dense) if shifted else None
        self._seen = sorted(self._seen + new)
        self._seen_arr = np.asarray(self._seen)
        self._dense = {raw: i for i, raw in enumerate(self._seen)}
        if old and self.n:
            perm = np.zeros(len(old), np.int32)
            for raw, od in old.items():
                perm[od] = self._dense[raw]
            return perm
        return None

    def _map_clients(self, arr: np.ndarray, mask: np.ndarray) -> np.ndarray:
        """Raw -> dense for the masked entries; others untouched."""
        out = arr.astype(np.int32).copy()
        if mask.any():
            vals = arr[mask]
            out[mask] = np.searchsorted(self._seen_arr, vals).astype(np.int32)
        return out

    # -- append / converge --------------------------------------------
    def _prepare_delta(self, cols: Dict[str, np.ndarray], k: int):
        """Shared append preamble: client interning (+ on-device
        relabel when ranks shifted), capacity growth, and the padded
        delta arrays. Caller splices inside the same x64 scope."""
        valid = np.asarray(cols["valid"][:k], bool)
        raw_cl = np.asarray(cols["client"][:k])
        raw_ocl = np.asarray(cols["origin_client"][:k])
        perm = self._intern(
            np.concatenate([raw_cl[valid], raw_ocl[raw_ocl >= 0]])
        )
        if perm is not None:
            self._bufs = _relabel(self._bufs, jnp.asarray(perm))
        if self.n + k > self.capacity:
            self._grow(self.n + k)
        kpad = min(_bucket(k, floor=6), self.capacity)
        if self.n + kpad > self.capacity:
            self._grow(self.n + kpad)
        from crdt_tpu.ops.device import xfer_put

        delta = []
        for name, dt in COLUMNS:
            arr = np.full(kpad, _FILL[name], dtype=dt)
            if name == "client":
                arr[:k] = np.where(
                    valid, self._map_clients(raw_cl, valid), 0
                )
            elif name == "origin_client":
                arr[:k] = self._map_clients(raw_ocl, raw_ocl >= 0)
            else:
                arr[:k] = cols[name][:k]
            # the xfer seam accounts every appended delta column:
            # resident rounds must show DELTA-sized h2d growth, never
            # the full matrix (pinned by tests/test_transfer_diet.py)
            delta.append(xfer_put(arr, label="resident.delta"))
        return tuple(delta)

    def append(self, cols: Dict[str, np.ndarray]) -> None:
        """Splice a host-side delta into the resident union. Only the
        delta (padded to its power-of-two bucket) crosses to the
        device; resident rows never re-upload."""
        k = len(cols["client"])
        if k == 0:
            return
        with enable_x64(True):
            delta = self._prepare_delta(cols, k)
            self._bufs = _splice(self._bufs, delta, jnp.int32(self.n))
        self.n += k

    def append_converge(
        self,
        cols: Dict[str, np.ndarray],
        num_segments: Optional[int] = None,
        d_client=None,
        d_start=None,
        d_end=None,
    ):
        """Fused append + convergence: the splice and both kernels run
        as ONE dispatch — the single-delta replay path. Equivalent to
        ``append(cols)`` then ``converge(...)``."""
        k = len(cols["client"])
        if k == 0:
            return self.converge(
                num_segments=num_segments, d_client=d_client,
                d_start=d_start, d_end=d_end,
            )
        with enable_x64(True):
            delta = self._prepare_delta(cols, k)
            # default segments AFTER _prepare_delta: it may grow the
            # capacity, and a pre-growth default would alias segment
            # ids (diverging from append() + converge())
            segs = num_segments or self.capacity
            if d_client is None:
                d_client = jnp.full(16, -1, jnp.int32)
                d_start = jnp.full(16, -1, jnp.int64)
                d_end = jnp.full(16, -1, jnp.int64)
            self._bufs, maps_out, seq_out = _splice_and_converge(
                self._bufs, delta, jnp.int32(self.n),
                d_client, d_start, d_end, num_segments=segs,
                ds_mode=ds_ops.mask_mode(),  # host static (CL702)
            )
        self.n += k
        return maps_out, seq_out

    def _grow(self, need: int) -> None:
        new_cap = _bucket(need)
        grown = []
        for (name, dt), b in zip(COLUMNS, self._bufs):
            nb = jnp.full(new_cap, _FILL[name], dtype=dt)
            grown.append(jax.lax.dynamic_update_slice(nb, b, (0,)))
        self._bufs = tuple(grown)

    def dense_client(self, raw: int) -> Optional[int]:
        """Dense id currently assigned to a raw client id."""
        return self._dense.get(int(raw))

    def converge(
        self,
        num_segments: Optional[int] = None,
        d_client=None,
        d_start=None,
        d_end=None,
    ):
        """One full device applyUpdate over the resident union: map
        winners (converge_maps) + sequence order (converge_sequences)
        in a single fused dispatch. Returns the two kernels' raw
        outputs as DEVICE arrays.

        Delete ranges, when given, must use DENSE client ids
        (:meth:`dense_client`).
        """
        segs = num_segments or self.capacity
        with enable_x64(True):
            if d_client is None:
                d_client = jnp.full(16, -1, jnp.int32)
                d_start = jnp.full(16, -1, jnp.int64)
                d_end = jnp.full(16, -1, jnp.int64)
            return _converge_all(
                self._bufs, d_client, d_start, d_end,
                num_segments=segs,
                ds_mode=ds_ops.mask_mode(),  # host static (CL702)
            )


# ---- the POOLED resident matrix (round 20) --------------------------

from crdt_tpu.ops import packed as pk  # noqa: E402  (pool device ops)


def _octave8(n: int, floor: int) -> int:
    """Factor-8 size bucket (the incremental dispatch's static-shape
    policy — see ``models.incremental._octave``): a handful of XLA
    variants over the pool's lifetime instead of one per doubling."""
    b = floor
    while b < n:
        b *= 8
    return b


_LANES = 8            # pooled matrix lanes (7 delta columns + slot)
_EXT_FLOOR = 1 << 10  # smallest extent, in rows (pow2 buckets above)
_CLIENT_BOUND = 1 << 22   # composite client must fit pack_id's width
_PREF_BOUND = 1 << 40     # composite pref must stay under segkey bit 62


class _Extent:
    """One doc's reserved column range in the pooled matrix. The
    invariant the splice relies on: device position of a doc's host
    row ``r`` is ``off + r`` (admission appends rows in order, and a
    relocation moves the WHOLE extent)."""

    __slots__ = ("off", "cap", "n", "slot", "move_from")

    def __init__(self, off: int, cap: int, slot: int):
        self.off = off
        self.cap = cap
        self.n = 0          # rows spliced so far (== engine.n_dev)
        self.slot = slot
        # (old off, old cap) awaiting the flush's device move — the
        # copy width must be the OLD bucket: the new cap can overrun
        # the old region into a neighbour's extent
        self.move_from = None


class ResidentPool:
    """ONE device allocation for every warm doc's resident matrix
    (round 20): per-doc extents co-locate the docs' columns, and all
    above-crossover deltas of a `MultiDocServer` tick batch into ONE
    scatter-splice + converge dispatch
    (:func:`crdt_tpu.ops.packed._pool_splice_select_converge`)
    instead of one per doc. Engines attach via the ``pool=``
    constructor argument of :class:`crdt_tpu.models.incremental.
    IncrementalReplay`; their device rounds then DEFER here
    (:meth:`defer`) and the server's tick flushes once
    (:meth:`flush`).

    Geometry: extents are pow2-bucketed row ranges allocated at the
    tail; a doc outgrowing its extent relocates by an on-device copy
    (never a host restage), eviction frees the extent, and holes are
    squeezed by a bounded compaction (one device gather) when they
    exceed the live rows — or on demand, when an allocation would
    otherwise burst ``max_bytes``. ``max_bytes`` bounds the pooled
    ALLOCATION (``CRDT_TPU_MT_POOL_BYTES`` at the server); a doc that
    cannot fit even after compaction is refused and falls back to a
    private resident matrix — correctness never depends on pooling.

    Counters/gauges (README "Observability" registry):
    ``tenant.pool_dispatches`` per pooled flush,
    ``tenant.pool_compactions`` per hole squeeze, and the
    ``tenant.pool_bytes`` / ``tenant.pool_docs`` gauges for the live
    allocation and extent count."""

    def __init__(self, max_bytes: Optional[int] = None,
                 capacity: int = 1 << 15):
        self.max_bytes = None if max_bytes is None else int(max_bytes)
        self._cap0 = _bucket(capacity)
        self._mat = None                       # lazy [8, cap] int64
        self._ext: Dict[object, _Extent] = {}
        self._free_slots: List[int] = []
        self._next_slot = 0
        self._pending: Dict[object, set] = {}
        # released extents' (off, cap) whose columns are still LIVE
        # on device: killed lazily at the next dispatch (or dropped
        # wholesale by a compaction's gather). Until then a reused
        # slot could alias the stale rows onto another doc's
        # composite ids — the kill runs BEFORE any splice.
        self._dead: List[Tuple[int, int]] = []
        self.dispatches = 0
        self.compactions = 0
        self.peak_bytes = 0

    # -- accounting ---------------------------------------------------
    def device_bytes(self) -> int:
        """Live pooled allocation — the ``tenant.pool_bytes`` gauge.
        Unit contract (pinned by tests/test_pooled.py): lanes x
        capacity x int64 itemsize, the same dtype-derived accounting
        as :meth:`ResidentColumns.device_bytes`."""
        if self._mat is None:
            return 0
        return int(self._mat.shape[0]) * int(self._mat.shape[1]) * 8

    def doc_device_bytes(self, eng) -> int:
        """One doc's reserved share — what the engine's
        ``resident_bytes`` (and through it the MT resident ledger)
        accounts for a pooled doc."""
        ext = self._ext.get(eng)
        return 0 if ext is None else ext.cap * _LANES * 8

    def doc_count(self) -> int:
        return len(self._ext)

    def has_pending(self, eng=None) -> bool:
        return bool(self._pending) if eng is None \
            else eng in self._pending

    def take_pending(self, eng) -> set:
        """Pop an engine's deferred segments (the unpooling fallback
        host-routes them itself)."""
        return set(self._pending.pop(eng, ()))

    def _note_peak(self) -> None:
        self.peak_bytes = max(self.peak_bytes, self.device_bytes())

    def _tail(self) -> int:
        return max((e.off + e.cap for e in self._ext.values()),
                   default=0)

    def _live_rows(self) -> int:
        return sum(e.cap for e in self._ext.values())

    # -- membership ---------------------------------------------------
    def register(self, eng) -> None:
        """Attach an engine (host bookkeeping only — no extent, no
        device touch: a doc that never crosses to the device route
        costs the pool nothing)."""
        if eng in self._ext:
            return
        slot = (self._free_slots.pop()
                if self._free_slots else self._next_slot)
        if slot == self._next_slot:
            self._next_slot += 1
        self._ext[eng] = _Extent(0, 0, slot)

    def release(self, eng) -> None:
        """Detach an engine (eviction / fallback): free its extent
        and slot; squeeze holes when they outgrow the live rows. The
        last doc leaving drops the whole allocation."""
        ext = self._ext.pop(eng, None)
        self._pending.pop(eng, None)
        if ext is None:
            return
        self._free_slots.append(ext.slot)
        if self._mat is not None and ext.n:
            # the doc's columns are still live on device (at the old
            # location when a relocation is still pending)
            self._dead.append(
                ext.move_from if ext.move_from is not None
                else (ext.off, ext.cap)
            )
        if not self._ext:
            self._mat = None
            self._free_slots.clear()
            self._next_slot = 0
            self._dead.clear()
        elif self._mat is not None and \
                self._tail() > 2 * self._live_rows():
            self.compact()
        tracer = get_tracer()
        if tracer.enabled:
            tracer.gauge("tenant.pool_bytes", self.device_bytes())
            tracer.gauge("tenant.pool_docs", self.doc_count())

    def _reset_all(self) -> None:
        """Device-failure ladder exhausted mid-flush: a post-donation
        failure may have invalidated the pooled matrix, so drop it —
        every attached engine restages its WHOLE host column set on
        the next flush (n_dev=0), the same full-rebuild contract the
        private matrix uses."""
        self._mat = None
        self._dead.clear()
        for eng, ext in self._ext.items():
            ext.n = 0
            ext.move_from = None
            eng.n_dev = 0

    # -- geometry -----------------------------------------------------
    def _fits_budget(self, cap_rows: int) -> bool:
        return self.max_bytes is None or \
            cap_rows * _LANES * 8 <= self.max_bytes

    def defer(self, eng, segs) -> bool:
        """Queue one engine device round for the batched flush:
        reserve (or pow2-grow) the doc's extent — host bookkeeping
        now, device moves at the flush — and merge its touched
        segments. Returns False when the pool cannot hold the doc
        within ``max_bytes`` even after compaction: the caller falls
        back to a private resident matrix."""
        ext = self._ext.get(eng)
        if ext is None:
            self.register(eng)
            ext = self._ext[eng]
        need = _bucket(max(eng.cols.n, _EXT_FLOOR))
        if ext.cap < need:
            tail = self._tail()
            if not self._fits_budget(_bucket(tail + need)) and \
                    self._mat is not None and \
                    self._tail() > self._live_rows():
                self.compact()
                tail = self._tail()
            if not self._fits_budget(_bucket(tail + need)):
                return False
            if ext.cap and ext.n:
                # relocation: the device copy runs inside the
                # flush's guarded dispatch; splice positions already
                # use the new offset
                if ext.move_from is None:
                    ext.move_from = (ext.off, ext.cap)
            ext.off = tail
            ext.cap = need
        self._pending.setdefault(eng, set()).update(segs)
        return True

    def relabel(self, eng, perm: np.ndarray) -> None:
        """Per-doc client relabel after a mid-table insertion —
        :meth:`IncrementalReplay._intern_clients`'s pooled branch.
        Only the doc's spliced extent columns rewrite."""
        ext = self._ext.get(eng)
        if ext is None or not ext.n or self._mat is None:
            return
        with enable_x64(True):
            self._mat = pk._pool_relabel_range(
                self._mat, jnp.asarray(perm),
                jnp.int32(ext.off), jnp.int32(ext.n),
            )

    def _ensure_mat(self, need_cols: int):
        with enable_x64(True):
            if self._mat is None:
                cap = _bucket(max(need_cols, self._cap0))
                if not self._fits_budget(cap):
                    # a budget tighter than the default first bucket:
                    # allocate only what the extents need
                    cap = _bucket(max(need_cols, 1))
                m = jnp.zeros((_LANES, cap), jnp.int64)
                m = m.at[3:6, :].set(-1)
                self._mat = m.at[7, :].set(-1)
            elif need_cols > self._mat.shape[1]:
                self._mat = pk._pool_grow(
                    self._mat, new_cap=_bucket(need_cols)
                )
        return self._mat

    def compact(self) -> None:
        """Squeeze eviction holes: repack live extents tight (in off
        order) with ONE device gather, shrinking the allocation to
        the covering pow2 bucket. Extents relocate wholesale, so the
        ``off + host_row`` position invariant is untouched. Bounded:
        O(pool) work, triggered only by releases and budget-pressed
        allocations — never on the steady path."""
        if self._mat is None or not self._ext:
            return
        exts = sorted(self._ext.values(), key=lambda e: e.off)
        tail = 0
        plan = []
        for e in exts:
            plan.append((e, tail))
            tail += e.cap
        # the default first bucket is only a FLOOR when it fits the
        # budget — a compaction must never re-grow a budget-clamped
        # pool past ``max_bytes`` (the ``tenant.pool_bytes`` peak is
        # pinned <= budget mid-compaction by tests/test_pooled.py)
        floor_cap = self._cap0 if self._fits_budget(self._cap0) else 1
        new_cap = _bucket(max(tail, floor_cap))
        src = np.zeros(new_cap, np.int32)
        keep = np.zeros(new_cap, bool)
        for e, new_off in plan:
            # a pending relocation's live rows are still at the OLD
            # location — gather from there (the compaction subsumes
            # the move); the new cap's surplus columns init dead
            s_off, s_cap = (e.move_from if e.move_from is not None
                            else (e.off, e.cap))
            w = min(s_cap, e.cap)
            src[new_off : new_off + w] = np.arange(
                s_off, s_off + w, dtype=np.int32
            )
            keep[new_off : new_off + w] = True
        from crdt_tpu.guard.device import dispatch_guarded

        def _gather():
            with enable_x64(True):
                return pk._pool_compact(
                    self._mat, jnp.asarray(src), jnp.asarray(keep)
                )

        res = dispatch_guarded("pool.compact", _gather,
                               host=lambda: None)
        if res is None:
            self._reset_all()
            return
        self._mat = res
        self._dead.clear()  # the gather dropped every hole
        for e, new_off in plan:
            e.off = new_off
            e.move_from = None
        self.compactions += 1
        self._note_peak()
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count("tenant.pool_compactions")
            tracer.gauge("tenant.pool_bytes", self.device_bytes())

    # -- the one pooled dispatch --------------------------------------
    def flush(self) -> int:
        """Converge EVERY deferred device round in one dispatch:
        execute pending extent moves, scatter-splice the combined
        delta block at the docs' extents, select the touched
        COMPOSITE segments, converge, and unpack winners/orders back
        to each engine. Returns the number of converge dispatches
        issued (0 when nothing pends, 1 on the batched path).

        Composite id bases are recomputed per flush from the live
        engines' id tables (traced operands — growth never
        recompiles); when the combined tables would overflow the
        kernel's packed widths the round routes host-side instead
        (exact, conservative). Device failure follows the guarded
        ladder: retry, then host-route the round and drop the pooled
        matrix for a full rebuild on the next flush."""
        if not self._pending:
            return 0
        pending = {e: sorted(s) for e, s in self._pending.items()}
        self._pending = {}

        # composite bases over ALL attached docs (live rows compose
        # too): disjoint, cumulative, slot-indexed. A doc's client
        # span carries HEADROOM for its pending tail — the staging
        # below interns the tail's clients AFTER these bases are
        # fixed, and the composite ranges only need to be disjoint
        # and order-preserving, not tight (each tail row introduces
        # at most one new client; origin clients always own a row).
        by_slot = sorted(self._ext.items(), key=lambda kv: kv[1].slot)
        spad = _octave8(self._next_slot, floor=16)
        cbase = np.zeros(spad, np.int64)
        pbase = np.zeros(spad, np.int64)
        tot_c = tot_p = 0
        for eng, ext in by_slot:
            cbase[ext.slot] = tot_c
            pbase[ext.slot] = tot_p
            tail = eng.cols.n - eng.n_dev if eng in pending else 0
            tot_c += len(eng._clients) + tail + 1
            tot_p += len(eng._pref_spec) + 1
        if tot_c >= _CLIENT_BOUND or tot_p >= _PREF_BOUND:
            # packed-width overflow (thousands of docs x clients):
            # host-route this round — exact, never wrong
            self._host_fallback(pending)
            return 0

        from crdt_tpu.guard.device import dispatch_guarded
        from crdt_tpu.ops.device import xfer_fetch, xfer_put

        n_sel = sum(
            len(eng._seg_rows[sk])
            for eng, segs in pending.items() for sk in segs
        )
        n_touch = sum(len(segs) for segs in pending.values())
        k_tot = sum(eng.cols.n - eng.n_dev for eng in pending)
        tpad = _octave8(n_touch, floor=1 << 10)
        kpad = _octave8(max(k_tot, 1), floor=1 << 6)

        def _dispatch():
            # EVERY device interaction of the flush — interning
            # relabels, extent moves, growth, the splice — runs
            # inside the guarded attempt (same idempotence contract
            # as the private round: intern commits only after its
            # relabel, moves clear only after their copy, staging
            # rebuilds per attempt)
            mat = self._ensure_mat(self._tail())
            with enable_x64(True):
                # released extents' stale columns die FIRST — a
                # reused slot (or an extent re-allocated over the
                # hole) must never see them alive. Idempotent per
                # guarded attempt.
                for d_off, d_cap in self._dead:
                    mat = pk._pool_kill(
                        mat, jnp.int32(d_off), width=d_cap
                    )
                for eng, _segs in pending.items():
                    ext = self._ext[eng]
                    if ext.move_from is not None and ext.n:
                        s_off, s_cap = ext.move_from
                        mat = pk._pool_move(
                            mat, jnp.int32(s_off),
                            jnp.int32(ext.off), width=s_cap,
                        )
                    ext.move_from = None
                self._mat = mat
                parts = []
                touched = []
                for eng, segs in pending.items():
                    ext = self._ext[eng]
                    rows = np.arange(eng.n_dev, eng.cols.n)
                    oc_tail = eng.cols.col("oc")[rows]
                    eng._intern_clients(np.concatenate([
                        eng.cols.col("client")[rows],
                        oc_tail[oc_tail >= 0],
                    ]))
                    parts.append((
                        eng._dense_of(eng.cols.col("client")[rows]),
                        eng.cols.col("clock")[rows],
                        eng.cols.col("pref")[rows],
                        eng.cols.col("kid")[rows],
                        np.where(
                            oc_tail >= 0,
                            eng._dense_of(np.clip(
                                oc_tail,
                                eng._clients[0] if eng._clients else 0,
                                None,
                            )),
                            -1,
                        ),
                        eng.cols.col("ock")[rows],
                        np.full(len(rows), ext.slot, np.int64),
                        (ext.off + rows).astype(np.int64),
                    ))
                    pb = int(pbase[ext.slot])
                    touched.extend(
                        sk + (pb << pk._KID_BITS) for sk in segs
                    )
                cat = [np.concatenate(c) for c in zip(*parts)]
                delta, ppos = pk.stage_pooled_delta(
                    *cat[:7], cat[7], kpad,
                    int(self._mat.shape[1]),
                )
                tarr = np.full(tpad, np.iinfo(np.int64).max, np.int64)
                tarr[: len(touched)] = np.sort(
                    np.asarray(touched, np.int64)
                )
                sel_bucket = min(
                    _octave8(n_sel, floor=1 << 13),
                    int(self._mat.shape[1]),
                )
                mat2, packed_out = pk._pool_splice_select_converge(
                    self._mat,
                    xfer_put(delta, label="incremental.delta"),
                    xfer_put(ppos, label="incremental.delta"),
                    xfer_put(tarr, label="incremental.delta"),
                    xfer_put(cbase, label="incremental.delta"),
                    xfer_put(pbase, label="incremental.delta"),
                    num_segments=tpad, sel_bucket=sel_bucket,
                    seq_bucket=sel_bucket,
                    mode=pk.kernel_mode_for(sel_bucket),
                    # None = sel_bucket bound: device-side segment
                    # numbering root-attaches in-flight-origin rows,
                    # so host `_seg_rows` counts can undercount the
                    # device populations (see the private-round note
                    # in models/incremental.py)
                    rank_rounds=None, map_rounds=None,
                )
                return mat2, xfer_fetch(
                    packed_out, label="incremental.out"
                ), sel_bucket

        res = dispatch_guarded("pool.converge", _dispatch,
                               host=lambda: None)
        if res is None:
            self._reset_all()
            self._host_fallback(pending)
            return 0
        self._mat, h, sel_bucket = res
        self._dead.clear()
        self._unpack(pending, h, tpad, sel_bucket)
        for eng in pending:
            ext = self._ext[eng]
            eng.n_dev = eng.cols.n
            ext.n = eng.n_dev
        self.dispatches += 1
        pk.count_device_dispatch()
        self._note_peak()
        tracer = get_tracer()
        if tracer.enabled:
            tracer.count("tenant.pool_dispatches")
            tracer.gauge("tenant.pool_bytes", self.device_bytes())
            tracer.gauge("tenant.pool_docs", self.doc_count())
        return 1

    def _unpack(self, pending, h, tpad: int, sel_bucket: int) -> None:
        """Split the fetch and route winners / orders back per doc:
        pool position -> (engine, host row) through the pending
        extents (position = off + host row, the extent invariant)."""
        exts = sorted(
            ((self._ext[eng].off, self._ext[eng].cap, eng)
             for eng in pending),
            key=lambda t: t[0],
        )
        offs = np.asarray([o for o, _, _ in exts], np.int64)
        engs = [e for _, _, e in exts]

        def locate(pos: int):
            i = int(np.searchsorted(offs, pos, side="right")) - 1
            return engs[i], pos - int(offs[i])

        s, b = tpad, sel_bucket
        win_local = h[:s]
        stream_seg = h[s : s + b]
        stream_row = h[s + b : s + 2 * b]
        sel_rows = h[s + 2 * b : s + 3 * b]
        for w in win_local[win_local >= 0]:
            eng, row = locate(int(sel_rows[w]))
            eng._win[eng._row_segkey(row)] = row
        m = stream_row >= 0
        rows_s, segs_s = stream_row[m], stream_seg[m]
        if len(rows_s):
            pool_rows = sel_rows[rows_s]
            cuts = np.r_[
                0, np.flatnonzero(segs_s[1:] != segs_s[:-1]) + 1,
                len(segs_s),
            ]
            for a, bnd in zip(cuts[:-1], cuts[1:]):
                eng, first = locate(int(pool_rows[a]))
                off = int(pool_rows[a]) - first
                chunk = (pool_rows[a:bnd] - off).tolist()
                eng._set_order(eng._row_segkey(chunk[0]), chunk)

    def _host_fallback(self, pending) -> None:
        """Exact host route for a flush that cannot (bounds) or could
        not (dead device) dispatch: each pending segment re-derives
        against the host columns; the unspliced tails simply wait for
        the next healthy flush — latency, never state."""
        for eng, segs in pending.items():
            for sk in segs:
                eng._host_order_segment(sk)
