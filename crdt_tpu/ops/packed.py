"""Packed one-dispatch trace-replay convergence.

The firehose replay (BASELINE config #5; ``crdt_tpu.models.replay``)
is a COLD start: decode a trace, converge once, materialize. On a
tunnelled single-chip platform every host<->device interaction pays a
fixed round-trip (measured ~25ms) and bulk transfer runs ~60MB/s, so
the general :class:`~crdt_tpu.ops.resident.ResidentColumns` path —
9 buffer allocations + 9 column uploads + dispatch — spends most of
its wall clock on transport, not merging. This module collapses the
whole cold replay to exactly three device interactions:

  1. ONE host->device transfer: all op columns packed into a single
     int32 (or int64 when clocks are wide) matrix;
  2. ONE dispatch: unpack -> shared id-sort/dedup/origin resolution ->
     map winners (:func:`crdt_tpu.ops.lww.map_winners`) + sequence DFS
     ranks over a compact sequence-rows-only prefix (the shared
     :func:`crdt_tpu.ops.device.dfs_ranks` machinery the general YATA
     kernel also uses) — plus document-order assembly, all fused;
  3. ONE device->host transfer: a single packed int32 result (winner
     rows + per-sequence document-order streams).

Segment ids for maps and sequences come from ONE argsort of a single
composite key (is_map | parent_ref | key_id) — parent specs are
interned to dense ids on the host, which already walks the columns
once to build them. Clients are interned to dense ORDER-PRESERVING
ranks (the sibling rules compare client ids, so the map must be
monotone — same rationale as ``ResidentColumns``).

Reference hot loop being replaced: crdt.js:294 (``Y.applyUpdate`` per
update); here the whole union is one applyUpdate, as the north star
prescribes.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict, NamedTuple, Optional

import jax

from crdt_tpu.compat import enable_x64
import jax.numpy as jnp
import numpy as np

from crdt_tpu.ops.device import (
    NULLI,
    bucket_grid,
    dense_ranks_sorted,
    dfs_ranks,
    lexsort,
    pack_id,
    pointer_double,
    record_staged_widths,
    run_edge_lookup,
    scatter_perm,
    searchsorted_ids,
    wide_staging_forced,
    xfer_fetch,
    xfer_put,
)
from crdt_tpu.ops.lww import map_winners
from crdt_tpu.obs.profiling import device_annotation
from crdt_tpu.obs.tracer import get_tracer

# host-side packing limits for the composite segment key:
# (is_map:1 | pref:25 bits | kid:21 bits) must fit non-negative int64
_PREF_BITS = 25
_KID_BITS = 21


from crdt_tpu.ops.device import _CLOCK_BITS  # pack_id's clock width

_SEQ_FLAG = 1 << 30          # bit in the seg column marking sequence rows

# floor of _stage_rights' per-SEGMENT origin-chain walk budget (the
# real budget is linear in the segment's row count): exhaustion marks
# the segment hard (exact scalar fallback) instead of letting hostile
# updates buy O(n^2) staging time, while benign long chains — whose
# total walk work stays linear-ish in segment size — keep the staged
# device path
_RIGHT_WALK_CAP = 1024

# row count above which eager per-row device shipping (stage(put=...))
# beats one matrix put: below it the extra per-put fixed latencies
# outweigh any staging/transfer overlap. One constant so the bench
# and the product replay always measure the same pipeline shape.
EAGER_PUT_MIN_ROWS = 1 << 19

# chain-split width (round 13, widened to SUBTREE granularity in
# round 23 — the post-sort-diet ROUNDS lever): a sequence segment
# larger than this many rows is re-cut at staging into bounded-size
# synthetic segments, each a contiguous suffix of the segment's DFS
# stream (any node whose remaining subtree ends the stream is a cut
# candidate, so branching trees split too, not just pure append
# chains). Deep LWW map key chains re-cut the same way. Each piece's
# doubling then runs ceil(log2(width)) rounds instead of
# ceil(log2(deepest path)), and the pieces are synthetic segments the
# multi-chip sharder can spread across chips. The seams are
# host-stitched: pieces are numbered in exact document order, so
# concatenating the per-piece streams IS the unsplit stream —
# byte-identical, tests/test_shard.py + tests/test_subtree_split.py.
# CRDT_TPU_CHAIN_SPLIT overrides (0 disables).
_CHAIN_SPLIT_ENV = "CRDT_TPU_CHAIN_SPLIT"
CHAIN_SPLIT_DEFAULT = 1 << 13

# cached (raw env string, parsed width): staging consults the width
# once per union and re-parsing the environment each call was pure
# overhead. Keying on the RAW string keeps the override semantics
# exact for tests that monkeypatch the variable between calls, and
# the value is only ever read on the host — never inside a traced
# body (the r16 CRDT_TPU_PALLAS host-static discipline).
_split_width_cache: tuple = (None, CHAIN_SPLIT_DEFAULT)


def chain_split_width() -> int:
    """The staging chain-split width (0 = disabled)."""
    global _split_width_cache
    raw = os.environ.get(_CHAIN_SPLIT_ENV, "")
    if raw != _split_width_cache[0]:
        if raw == "":
            w = CHAIN_SPLIT_DEFAULT
        else:
            try:
                w = max(0, int(raw))
            except ValueError:
                w = CHAIN_SPLIT_DEFAULT
        _split_width_cache = (raw, w)
    return _split_width_cache[1]


# ---------------------------------------------------------------------------
# narrow-section staging: the transfer diet (round 9), re-cut for the
# round-12 sort diet's precomputed-layout upload
#
# The staged upload is pure LAYOUT data — dense ranks, run flags,
# block-local tree tables — whose values are tiny compared to their
# int32 slots for every real workload. Round 12 moves the sibling
# grouping the device used to re-derive with global argsorts INTO
# staging (host radix passes any columnar store pays at ingest), so
# what ships is no longer raw columns but the layout's OUTPUT, cut
# into named SECTIONS of one flat array:
#
#   seq_seg      [B]   dense segment id per compact seq row (-1 pad)
#   seg_off      [S]   doc-order exclusive offset per segment (the
#                      scatter targets: out[off[seg] + rank] = row)
#   seq_parent   [B]   compact origin-tree parent, -1 root
#   seq_next     [B]   next sibling in (parent, client, clock desc)
#                      order, -1 at group end
#   seq_first    [B+S] first child per node (items + virtual roots)
#   map_key      [M]   map rows grouped by chain parent: dense client
#                      rank << 1 | run-start flag (-1 pad)
#   map_chain_end[M]   grouped END position of each node's child run,
#                      -1 leaf
#   map_root_end [S]   grouped END position of each segment's
#                      root-children run, -1 no map rows
#
# Each section gets a frame-of-reference/delta encoding into int16
# when its values fit ('i16' identity / 'd16' delta-from-position),
# with a fused widening prelude inside the one-dispatch converge
# program reconstructing the exact int32 values — kernel semantics
# and outputs stay byte-identical (tests/test_transfer_diet.py,
# tests/test_sort_diet.py). A section whose values do not fit ships
# as TWO exact int16 hi/lo stretches ('hilo': any int32 splits
# exactly), so one overflowing section never collapses the whole
# upload back to int32. CRDT_TPU_WIDE_STAGING=1 forces plain int32
# everywhere ('i32', README "Transfer diet").
# ---------------------------------------------------------------------------

_I16_MIN = -(1 << 15)
_I16_MAX = (1 << 15) - 1

# fixed section order of the flat staged array; the eager path ships
# the same sections as three group uploads (see _SECTION_GROUPS)
SECTION_NAMES = (
    "seq_seg", "seg_off", "seq_parent", "seq_next", "seq_first",
    "map_key", "map_chain_end", "map_root_end",
)

# section name -> preferred narrow encoder; 'hilo' is the shared
# exact fallback when the preferred one refuses
_SECTION_NARROW = {
    "seq_seg": "i16", "seg_off": "i16", "seq_parent": "d16",
    "seq_next": "d16", "seq_first": "d16",
    "map_key": "i16", "map_chain_end": "d16", "map_root_end": "i16",
}

# eager (stage(put=...)) upload groups, as index ranges over
# SECTION_NAMES: group 0 and 2 are complete before the right-origin
# pass and ship immediately; group 1 (the sibling tables) depends on
# the simulated group ranks and ships last
_SECTION_GROUPS = ((0, 3), (3, 5), (5, 8))


def _narrow_ident(vals: np.ndarray):
    """int16 identity encoding (values in [-1, 32767]), or None."""
    if len(vals) and (int(vals.max()) > _I16_MAX
                      or int(vals.min()) < -1):
        return None
    return vals.astype(np.int16)


def _narrow_delta_ref(vals: np.ndarray):
    """int16 (index - reference) encoding of a position-reference
    section (-1 = no reference -> 0), or None when a delta overflows
    int16 or collides with the no-reference sentinel (a
    self-referential slot — hostile input — forces the hi/lo layout,
    never a wrong decode)."""
    idx = np.arange(len(vals), dtype=np.int64)
    live = vals >= 0
    d = np.where(live, idx - vals, 0)
    if live.any():
        bad = live & ((d == 0) | (d < _I16_MIN) | (d > _I16_MAX))
        if bad.any():
            return None
    return d.astype(np.int16)


def _split_hi_lo(row: np.ndarray):
    """Any int32 section as TWO exact int16 stretches: hi =
    arithmetic >> 16, lo = low 16 bits biased into int16 range.
    Always feasible — the escape for a section whose values overflow
    one narrow stretch."""
    v = row.astype(np.int32)
    hi = (v >> 16).astype(np.int16)
    lo = ((v & 0xFFFF) - 0x8000).astype(np.int16)
    return hi, lo


def _join_hi_lo(hi, lo):
    """Device inverse of :func:`_split_hi_lo`."""
    return (
        (hi.astype(jnp.int32) << 16)
        | ((lo.astype(jnp.int32) + 0x8000) & 0xFFFF)
    )


def _widen_delta_ref(v):
    v = v.astype(jnp.int32)
    idx = jnp.arange(v.shape[0], dtype=jnp.int32)
    return jnp.where(v == 0, NULLI, idx - v)


def _encode_sections(named, wide: bool, force=None):
    """[(name, int-array)] -> (flat staged array, enc tuple, widths).
    Narrow: each section becomes one int16 stretch via its preferred
    encoder, or two exact hi/lo stretches when the encoder refuses.
    Wide: one int32 stretch per section.

    ``force`` (a per-section kind tuple aligned with ``named``) pins
    each section's encoding — the multi-chip sharder uses it so every
    shard of one sharded plan shares ONE static encoding tuple (the
    shard_map program is compiled once for all shards). Forcing
    'hilo' on a section that would have narrowed is exact, never
    wrong — it only costs the narrow win on that section."""
    if wide:
        flat = np.concatenate([a.astype(np.int32) for _, a in named])
        return flat, tuple("i32" for _ in named), {
            name: 32 for name, _ in named
        }
    parts, encs, widths = [], [], {}
    for i, (name, arr) in enumerate(named):
        kind = force[i] if force is not None else _SECTION_NARROW[name]
        enc = None
        if kind != "hilo":
            enc = (_narrow_ident(arr) if kind == "i16"
                   else _narrow_delta_ref(arr))
            if enc is None and force is not None:
                raise ValueError(
                    f"forced narrow encoding {kind!r} refused for "
                    f"section {name!r}"
                )
        if enc is not None:
            parts.append(enc)
            encs.append(kind)
            widths[name] = 16
        else:
            hi, lo = _split_hi_lo(arr)
            parts.extend((hi, lo))
            encs.append("hilo")
            widths[name] = 32
    return np.concatenate(parts), tuple(encs), widths


def _decode_sections(flat, sizes, encs):
    """Device inverse of :func:`_encode_sections` — the fused widening
    prelude (a handful of elementwise ops traced into the same program
    as the convergence, so reconstruction never costs a dispatch).
    ``sizes``/``encs`` are static per plan."""
    out = []
    off = 0
    for size, enc in zip(sizes, encs):
        if enc == "hilo":
            out.append(_join_hi_lo(flat[off:off + size],
                                   flat[off + size:off + 2 * size]))
            off += 2 * size
        elif enc == "d16":
            out.append(_widen_delta_ref(flat[off:off + size]))
            off += size
        else:  # 'i16' / 'i32': identity widen
            out.append(flat[off:off + size].astype(jnp.int32))
            off += size
    return out


class PackedPlan(NamedTuple):
    """Host-side staging result: one flat staged array + static
    metadata + host-retained translation tables.

    Staging does the layout work a tuned columnar store would do
    anyway — id radix sort, dedup, origin resolution, dense segment
    numbering, and (round 12, the sort diet) the chain-parent
    grouping of the map block plus the sibling/first-child tables of
    the sequence forest — and ships its OUTPUT: the device dispatch
    starts at the combinatorial core (segmented argmax scan, pointer
    doubling, document-order scatter) with ZERO device-width sorts.
    Raw columns (client ranks, segment flags, origin rows) no longer
    ship at all; the device translates everything through block-local
    indices, and the host maps the two small result vectors back
    through ``map_back``/``seq_back`` after the fetch.
    """

    mat: Optional[np.ndarray]  # flat 1-D staged array: the SECTION_NAMES
                              # sections concatenated, int16
                              # narrow-encoded per section (``encs``)
                              # or int32 wide. None when sections were
                              # shipped eagerly via ``stage(put=...)``
                              # — see ``dev``
    n: int                    # real rows (rest is padding)
    num_segments: int         # size bucket over distinct segments
    seq_bucket: int           # size bucket over sequence-row count
    map_bucket: int           # size bucket over map-row count (the
                              # map chain runs at THIS width, not
                              # padded n — round-12 satellite)
    order: np.ndarray         # id-sort permutation: staged row i =
                              # caller row order[i]
    clients: np.ndarray       # sorted raw client ids (dense rank = index)
    rank_rounds: int          # doubling rounds bound (seq DFS)
    map_rounds: int           # doubling rounds bound (map chains)
    hard_rows: tuple = ()     # caller-space rows marking segments the
                              # scalar fallback must re-order (gather)
    dev: tuple = ()           # device refs (one per _SECTION_GROUPS
                              # entry) when sections were shipped
                              # eagerly during staging
    staged_widths: tuple = () # ((section, bits), ...) chosen per
                              # section — recorded into the xfer
                              # registry at the plan's actual UPLOAD
                              # (matrix path), so plans that never
                              # cross the link (host route,
                              # repeat-dispatch probes) leave no
                              # phantom width/savings entries
    encs: tuple = ()          # per-section encoding kinds
                              # ('i16'/'d16'/'hilo'/'i32'), aligned
                              # with SECTION_NAMES — static dispatch
                              # arg driving the widening prelude
    map_back: Optional[np.ndarray] = None
                              # [M] grouped map position -> caller row
                              # (-1 pad): winner translation, on host
    seq_back: Optional[np.ndarray] = None
                              # [B] compact seq index -> caller row
                              # (-1 pad): stream translation, on host
    seg_counts: Optional[np.ndarray] = None
                              # [S] sequence-row count per segment
                              # (host-known; rebuilds stream_seg
                              # without fetching a segment column).
                              # With chain-split active, a split
                              # segment's pieces accumulate onto its
                              # first synthetic id, so the assembler
                              # sees the UNSPLIT boundaries
    seam_rows: tuple = ()     # caller-space rows opening a chain-split
                              # piece (depth > 0): the host-stitched
                              # seams; counted as converge.chain_seams
                              # at staging and shard.seam_rows per
                              # sharded dispatch
    win_src: Optional[np.ndarray] = None
                              # [S] winner-stitch for split MAP
                              # segments: slot i of the fetched win
                              # vector reads win[win_src[i]] (-1
                              # suppresses the slot). A split map
                              # segment's first synthetic slot points
                              # at the piece holding the true winner;
                              # its other slots are suppressed so the
                              # per-original-segment winner set stays
                              # exactly the unsplit one. None =
                              # identity (no map split)


def _even_up(x: int) -> int:
    """Round a doubling-rounds bound up to even: halves the static
    variants the jit cache sees at a cost of at most one extra round."""
    return x + (x & 1)


def _stage_rights(cols, order, ikey_s, uniq, seg, origin_row, oc_s,
                  seq_rows, uniq_valid, kid_s, client_s, client_raw_s,
                  clock_raw_s):
    """Exact right-origin (attachment) ordering, computed at staging
    in column space — the device kernel needs NO change: a simulated
    group's conflict-scan ranks are written over its members' entries
    in the client column, and since ranks are unique within a group
    the kernel's (client, position) tie-break never fires.

    Semantics match ops.yata.order_sequences exactly. A segment is
    HARD — routed to the scalar fallback at gather via the returned
    representative rows — when any member's declared origin is
    unresolved (orphan subtrees take the fallback's dropping rules),
    or any member's right is dangling/unknown, cross-segment, or
    inside another member's subtree (right_walk_is_hard). Groups with
    in-group anchors replay the Yjs conflict scan (_simulate_group);
    attachment-free groups keep the plain (client, clock-desc) key.

    Returns (client column, caller-space hard rows, max rank written,
    hard segment ids). The hard segment ids let the subtree split
    skip exactly the segments whose staged order is inexact — every
    other right-bearing segment has its conflict-scan ranks baked
    into the client column by the time the split runs, so the
    sibling comparator (and any DFS-suffix cut of it) stays exact.
    """
    from crdt_tpu.ops.yata import _simulate_group

    n = len(client_s)
    rr = np.asarray(cols["right_client"], np.int64)[order]
    rk = np.asarray(cols["right_clock"], np.int64)[order]
    rows_r = np.flatnonzero(uniq_valid & (kid_s < 0) & (rr >= 0))
    if not len(rows_r):
        return client_s, [], 0, []

    # resolve right-target rows through the dense id table (leftmost
    # match is the kept duplicate representative, like origins)
    posu = np.clip(
        np.searchsorted(uniq, np.clip(rr, uniq[0], None)), 0, len(uniq) - 1
    )
    known_c = (
        (rr >= 0) & (uniq[posu] == rr)
        & (rk >= 0) & (rk < (1 << _CLOCK_BITS))
    )
    rkey = np.where(known_c, (posu << _CLOCK_BITS) | rk, np.int64(-1))
    pos = np.clip(np.searchsorted(ikey_s, rkey), 0, n - 1)
    right_row = np.where((rkey >= 0) & (ikey_s[pos] == rkey), pos, -1)

    # segment -> member rows (one stable sort over the seq rows)
    seg_of_seq = seg[seq_rows]
    so = np.argsort(seg_of_seq, kind="stable")
    ss, sr = seg_of_seq[so], seq_rows[so]
    seg_cuts = np.r_[0, np.flatnonzero(ss[1:] != ss[:-1]) + 1, len(ss)]
    seg_slices = {
        int(ss[a]): sr[a:b] for a, b in zip(seg_cuts[:-1], seg_cuts[1:])
    }

    hard_reps: list = []
    hard_segs: list = []
    max_rank = 0
    # accumulated conflict-scan ranks, written with ONE bulk
    # searchsorted at the end (a per-sid binary search dominated text
    # staging time — profiled round 4)
    rank_sids: list = []
    rank_vals: list = []
    for S in np.unique(seg[rows_r]).tolist():
        members = seg_slices.get(int(S))
        if members is None:
            continue
        # orphan member (declared origin that resolved nowhere):
        # vectorized — member loops in python made staging the text
        # replay's dominant cost
        if bool(np.any((oc_s[members] >= 0) & (origin_row[members] < 0))):
            hard_reps.append(int(order[int(members[0])]))
            hard_segs.append(int(S))
            continue
        # groups within the segment, keyed by in-union origin row:
        # one stable sort + run split instead of a python setdefault
        # walk over every member
        og = origin_row[members]
        gorder = np.argsort(og, kind="stable")
        og_s, mem_s = og[gorder], members[gorder]
        gcuts = np.r_[
            0, np.flatnonzero(og_s[1:] != og_s[:-1]) + 1, len(og_s)
        ]
        hard = False
        # shared walk budget for ALL of this segment's out-of-group
        # right walks: linear in segment size (hostile staging cost
        # stays O(n) total — advisor finding, round 3), generous for
        # benign shapes; exhaustion marks the segment hard, which the
        # exact scalar fallback absorbs
        walk_budget = max(_RIGHT_WALK_CAP, 8 * len(members))
        seg_rank_sids: list = []
        seg_rank_vals: list = []
        seg_max_rank = 0
        for a, b in zip(gcuts[:-1], gcuts[1:]):
            grows = mem_s[a:b]
            # only right-bearing members need the per-row checks
            gr = grows[rr[grows] >= 0]
            if not len(gr):
                continue
            grow_set = set(grows.tolist())
            has_anchor = False
            # one fused python pass (groups are tiny — typically the
            # few writers racing one position — so per-group numpy
            # reductions cost more than they save)
            for rt in right_row[gr].tolist():
                if rt < 0 or seg[rt] != S:
                    hard = True  # dangling/unknown or cross-parent
                    break
                if rt in grow_set:
                    has_anchor = True  # in-group anchor: simulated
                    continue
                # out-of-group right: hard if its origin chain passes
                # through a GROUP member (the scan would stop inside
                # that member's subtree). Walks draw on the segment's
                # shared linear budget (see above)
                cur = rt
                while cur >= 0:
                    if cur in grow_set:
                        hard = True
                        break
                    walk_budget -= 1
                    if walk_budget < 0:
                        hard = True  # budget spent: exact fallback
                        break
                    cur = int(origin_row[cur])
                if hard:
                    break
            if hard:
                break
            if not has_anchor:
                continue  # attachment-free: plain keys are exact
            glist = grows.tolist()
            sibs = [
                {
                    "id": int(ikey_s[r]),
                    "client": int(client_raw_s[r]),
                    "clock": int(clock_raw_s[r]),
                    "right": int(rkey[r]) if rr[r] >= 0 else None,
                }
                for r in glist
            ]
            ordered = _simulate_group(
                sibs, {int(ikey_s[r]) for r in glist}
            )
            seg_rank_sids.extend(ordered)
            seg_rank_vals.extend(range(len(ordered)))
            seg_max_rank = max(seg_max_rank, len(ordered) - 1)
        if hard:
            hard_reps.append(int(order[int(members[0])]))
            hard_segs.append(int(S))
            continue
        rank_sids.extend(seg_rank_sids)
        rank_vals.extend(seg_rank_vals)
        max_rank = max(max_rank, seg_max_rank)
    if rank_sids:
        rows = np.searchsorted(ikey_s, np.asarray(rank_sids, np.int64))
        client_s[rows] = np.asarray(rank_vals, np.int64)
    return client_s, hard_reps, max_rank, hard_segs


def dfs_suffix_boundaries(par_l, cl_l, posd_l, width: int,
                          max_pieces: int):
    """Greedy DFS-suffix cut of ONE segment's compact forest (round
    23, the subtree generalization of the round-13 chain cut).

    ``par_l`` are segment-local parent indices (-1 roots), ``cl_l`` /
    ``posd_l`` the sibling comparator keys — client ascending then
    ``posd_l`` ascending, EXACTLY the staged sibling-table keys, so
    the preorder computed here is the stream the device will emit.

    The cut walks the stream from its END: the last remaining node's
    every ancestor owns a remaining subtree that is a contiguous
    stream SUFFIX, so the topmost ancestor still inside the width
    window opens a piece, extended left over whole preceding
    same-parent sibling subtrees while they fit. Cutting a suffix
    keeps the invariant for the next round, so concatenating pieces
    in cut order (piece 0 = the final prefix) reproduces the stream
    bit-for-bit. ``max_pieces`` bounds hostile shapes that shed
    one-row suffixes: when reached, the remaining prefix stays one
    (large) piece — a best-effort rounds bound, never an error.

    Returns ``(pos, starts)``: the preorder position per local node
    and the ascending piece start positions (``starts[0] == 0``).
    Pure host numpy — log2-depth doubling passes plus one python
    step per piece (each bounded by that piece's size).
    """
    m = len(par_l)
    levels = max(1, (max(m, 2) - 1).bit_length() + 1)
    # sibling tables, exactly as staging's g1 builds them
    pslot = np.where(par_l >= 0, par_l, m)
    sord = np.lexsort((posd_l, cl_l, pslot))
    ps = pslot[sord]
    same = ps[1:] == ps[:-1]
    nxt = np.full(m, -1, np.int64)
    nxt[sord[:-1][same]] = sord[1:][same]
    fc = np.full(m + 1, -1, np.int64)
    starts_r = np.r_[0, np.flatnonzero(~same) + 1]
    fc[ps[starts_r]] = sord[starts_r]
    # g(v): nearest ancestor-or-self with a next sibling (absorbing
    # path doubling: nodes that have one are fixed points)
    g = np.where(nxt >= 0, np.arange(m, dtype=np.int64), par_l)
    for _ in range(levels):
        g = np.where(g >= 0, g[np.clip(g, 0, m - 1)], np.int64(-1))
    # preorder successor chain -> position = m-1 - distance-to-end
    succ = np.where(
        fc[:m] >= 0, fc[:m],
        np.where(g >= 0, nxt[np.clip(g, 0, m - 1)], np.int64(-1)),
    )
    t = np.where(succ >= 0, succ, np.arange(m, dtype=np.int64))
    dist = (succ >= 0).astype(np.int64)
    for _ in range(levels):
        dist = dist + dist[t]
        t = t[t]
    pos = (m - 1) - dist
    by_pos = np.empty(m, np.int64)
    by_pos[pos] = np.arange(m)
    # sibling runs in sorted order (positions ascend within a run —
    # sibling order IS subtree-start order), for the left-extension
    # binary search
    spos = np.empty(m, np.int64)
    spos[sord] = np.arange(m)
    run_of = np.cumsum(np.r_[True, ~same]) - 1
    pos_sorted = pos[sord]
    bounds = [m]
    e = m
    while e > width and len(bounds) <= max_pieces:
        lim = e - width
        A = int(by_pos[e - 1])
        while par_l[A] >= 0 and pos[par_l[A]] >= lim:
            A = int(par_l[A])
        i = int(spos[A])
        lo = int(starts_r[run_of[i]])
        j = lo + int(np.searchsorted(pos_sorted[lo:i + 1], lim))
        b = int(pos_sorted[j])
        bounds.append(b)
        e = b
    bounds.append(0)
    return pos, np.unique(np.asarray(bounds[::-1][:-1], np.int64))


def _subtree_split(seg, seq_rows, c_parent, client_s, width,
                   hard_seg_ids, map_rows, origin_row, rr_s):
    """Re-cut oversized sequence segments at SUBTREE granularity and
    deep LWW map key chains at depth granularity into bounded-size
    synthetic segments (round 23, generalizing the round-13 chain
    split — see the CHAIN_SPLIT_DEFAULT block).

    A sequence segment qualifies when it is larger than ``width``
    rows, is not HARD (the scalar fallback must see the original
    segment), and has no origin cycles. Branching nodes and benign
    right-origin rows no longer disqualify: this runs AFTER
    :func:`_stage_rights`, so the conflict-scan ranks are already
    baked into ``client_s`` and the sibling comparator — hence the
    DFS stream and any suffix cut of it — is exact. Pure chain
    bundles keep the fully vectorized round-13 bin/depth cut;
    branching trees take :func:`dfs_suffix_boundaries`. Either way
    the pieces are numbered in exact document order, so the host
    stitch remains the synthetic numbering itself.

    A map segment qualifies when it is larger than ``width`` rows,
    is a pure chain bundle (argmax-descend only factors over pieces
    of single-child chains), carries no right origins (the host
    right-fix at assembly walks the original chain), and has no
    cycles. Its chains bin/depth-cut like sequence chains; the piece
    holding the true winner (the deepest node of the max-root chain)
    is recorded in the returned ``win_src`` stitch so the assembled
    winner set is exactly the unsplit one.

    Returns ``(seg2, c_parent2, seam_compact_rows, synth_orig,
    win_src, n_seq_cuts, n_map_cuts)`` or None when nothing splits.
    ``win_src`` is None when no map segment split.
    """
    n = len(seg)
    n_seq = len(seq_rows)
    n_map = len(map_rows)
    if width <= 0 or n == 0:
        return None
    n_segs = int(seg.max()) + 1
    sub_full = np.zeros(n, np.int64)
    seam_mask = np.zeros(n_seq, bool)
    n_seq_cuts = 0
    n_map_cuts = 0
    win_map: dict = {}
    did = False

    if n_seq:
        seg_q = seg[seq_rows]
        sizes = np.bincount(seg_q, minlength=n_segs)
        excl = np.zeros(n_segs, bool)
        if hard_seg_ids:
            excl[np.asarray(hard_seg_ids, np.int64)] = True
        # host pointer doubling over the compact parents: chain head +
        # depth per row (vectorized; log2(n_seq) gathers)
        idx = np.arange(n_seq, dtype=np.int64)
        f = np.where(c_parent >= 0, c_parent, idx)
        d = (c_parent >= 0).astype(np.int64)
        for _ in range(max(1, (max(n_seq, 2) - 1).bit_length() + 1)):
            d = d + d[f]
            f = f[f]
        # hostile cyclic origins never reach a root; exclude their
        # segments (the unsplit path already has defined semantics
        # there)
        incyc = c_parent[f] >= 0
        if incyc.any():
            excl[np.unique(seg_q[incyc])] = True
        cand = (sizes > width) & ~excl
        if cand.any():
            clen = np.bincount(f, minlength=n_seq)
            cc = np.bincount(c_parent[c_parent >= 0], minlength=n_seq)
            branchy = np.zeros(n_segs, bool)
            if (cc > 1).any():
                branchy[np.unique(seg_q[cc > 1])] = True
            cl_q = client_s[seq_rows]
            posd = int(seq_rows.max()) - seq_rows
            for s in np.flatnonzero(cand).tolist():
                rows_s = np.flatnonzero(seg_q == s)
                if branchy[s]:
                    cp = c_parent[rows_s]
                    par_l = np.where(
                        cp >= 0,
                        np.searchsorted(rows_s, np.clip(cp, 0, None)),
                        np.int64(-1),
                    )
                    pos, cuts = dfs_suffix_boundaries(
                        par_l, cl_q[rows_s], posd[rows_s], width,
                        max_pieces=max(2, 4 * len(rows_s) // width),
                    )
                    if len(cuts) < 2:
                        continue
                    sub_s = np.searchsorted(
                        cuts, pos, side="right"
                    ) - 1
                    seam = (par_l >= 0) & (
                        sub_s[np.clip(par_l, 0, len(rows_s) - 1)]
                        != sub_s
                    )
                else:
                    sub_s, seam = _chain_bundle_cut(
                        rows_s, c_parent, f, d, clen, cl_q, posd,
                        width,
                    )
                sub_full[seq_rows[rows_s]] = sub_s
                seam_mask[rows_s[seam]] = True
                n_seq_cuts += int(sub_s.max())
                did = did or bool(sub_s.max())

    if n_map:
        seg_m = seg[map_rows]
        msizes = np.bincount(seg_m, minlength=n_segs)
        mbig = msizes > width
        if mbig.any():
            o = origin_row[map_rows]
            o_c = np.clip(o, 0, n - 1)
            same_m = (o >= 0) & (seg[o_c] == seg_m)
            m_par = np.where(
                same_m, np.searchsorted(map_rows, o_c), np.int64(-1)
            )
            mexcl = np.zeros(n_segs, bool)
            if rr_s is not None:
                rb = rr_s[map_rows] >= 0
                if rb.any():
                    mexcl[np.unique(seg_m[rb])] = True
            ccm = np.bincount(m_par[m_par >= 0], minlength=n_map)
            if (ccm > 1).any():
                mexcl[np.unique(seg_m[ccm > 1])] = True
            idx_m = np.arange(n_map, dtype=np.int64)
            fm = np.where(m_par >= 0, m_par, idx_m)
            dm = (m_par >= 0).astype(np.int64)
            for _ in range(
                max(1, (max(n_map, 2) - 1).bit_length() + 1)
            ):
                dm = dm + dm[fm]
                fm = fm[fm]
            incyc_m = m_par[fm] >= 0
            if incyc_m.any():
                mexcl[np.unique(seg_m[incyc_m])] = True
            mcand = mbig & ~mexcl
            if mcand.any():
                clen_m = np.bincount(fm, minlength=n_map)
                # head order by compact row index: map pieces never
                # emit a stream, so any deterministic order works —
                # index order keeps the win stitch trivial
                zid = np.zeros(n_map, np.int64)
                for s in np.flatnonzero(mcand).tolist():
                    rows_s = np.flatnonzero(seg_m == s)
                    sub_s, _seam = _chain_bundle_cut(
                        rows_s, m_par, fm, dm, clen_m, zid,
                        idx_m, width,
                    )
                    if not sub_s.max():
                        continue
                    sub_full[map_rows[rows_s]] = sub_s
                    n_map_cuts += int(sub_s.max())
                    did = True
                    # winner stitch: the device's winner root is the
                    # root run's prefix-argmax read at its end — the
                    # (max client, min clock) root (see _map_block);
                    # its chain's deepest node lives in that chain's
                    # LAST piece. The same argmax inside the winner's
                    # piece re-elects it (any subset containing the
                    # global argmax keeps it), so pointing the stitch
                    # at that piece reads the true unsplit winner
                    roots = rows_s[m_par[rows_s] < 0]
                    rcl = client_s[map_rows[roots]]
                    best = int(roots[rcl == rcl.max()].min())
                    lo = np.searchsorted(rows_s, best)
                    base = int(sub_s[lo])
                    depth_last = (int(clen_m[best]) - 1) // width \
                        if clen_m[best] > width else 0
                    win_map[s] = base + depth_last

    if not did:
        return None
    maxsub = int(sub_full.max()) + 1
    live = seg >= 0
    key = seg * maxsub + sub_full
    uniq_k, inv = np.unique(key[live], return_inverse=True)
    seg2 = np.full(n, -1, np.int64)
    seg2[live] = inv
    synth_orig = uniq_k // maxsub
    c_parent2 = np.array(c_parent, copy=True)
    c_parent2[seam_mask] = -1
    win_src = None
    if win_map:
        win_src = np.arange(len(uniq_k), dtype=np.int64)
        for s, wsub in win_map.items():
            a = int(np.searchsorted(synth_orig, s))
            b = int(np.searchsorted(synth_orig, s + 1))
            wid = int(np.searchsorted(uniq_k, s * maxsub + wsub))
            win_src[a:b] = -1
            win_src[a] = wid
    return (seg2, c_parent2, np.flatnonzero(seam_mask), synth_orig,
            win_src, n_seq_cuts, n_map_cuts)


def _chain_bundle_cut(rows_s, c_parent, f, d, clen, cl_q, posd,
                      width: int):
    """The round-13 vectorized cut of ONE pure-chain-bundle segment
    (every member has at most one child): short chains pack greedily
    into <=``width`` synthetic pieces in head sibling order (client
    asc, clock desc — the staged sibling key); a chain longer than
    ``width`` takes consecutive EXCLUSIVE pieces, one per
    depth-``width`` slab. Pieces are numbered in exact document
    order. Returns ``(sub_s, seam_mask_local)`` aligned with
    ``rows_s``."""
    heads = rows_s[c_parent[rows_s] < 0]
    horder = np.lexsort((posd[heads], cl_q[heads]))
    heads_o = heads[horder]
    # first synthetic id of each head's bin/piece run, aligned
    # with heads_o — all scratch here is SEGMENT-local (a full
    # compact-width table per candidate would turn staging
    # quadratic on many-list documents)
    head_base = np.zeros(len(heads_o), np.int64)
    cur = 0
    fill = 0
    started = False
    for i, h in enumerate(heads_o.tolist()):
        length = int(clen[h])
        if length > width:
            if started:
                cur += 1
                fill = 0
                started = False
            head_base[i] = cur
            cur += -(-length // width)
        else:
            if started and fill + length > width:
                cur += 1
                fill = 0
            head_base[i] = cur
            fill += length
            started = True
    # row -> its head's position in heads_o, by binary search
    hsort = np.argsort(heads_o, kind="stable")
    hs = heads_o[hsort]
    r_root = f[rows_s]
    hpos = hsort[np.searchsorted(hs, r_root)]
    r_long = clen[r_root] > width
    sub_s = head_base[hpos] + np.where(
        r_long, d[rows_s] // width, 0
    )
    seam = r_long & (d[rows_s] % width == 0) & (d[rows_s] > 0)
    return sub_s, seam


def stage(cols: Dict[str, np.ndarray],
          put=None, wide: Optional[bool] = None,
          _sections: Optional[list] = None) -> Optional[PackedPlan]:
    """Pack kernel columns into the single-transfer matrix (the
    tracer's ``pack`` span — one per staged union/shard).

    See :func:`_stage` for the layout contract (``_sections`` is the
    multi-chip sharder's layout-only seam)."""
    with get_tracer().span("pack"):
        return _stage(cols, put, wide, _sections=_sections)


def _doc_column(cols, valid) -> Optional[np.ndarray]:
    """The active multi-doc column, or None (absent / single doc).
    Docs must be dense non-negative ints; only admitted rows decide
    whether more than one doc is present."""
    if "doc" not in cols:
        return None
    doc = np.asarray(cols["doc"], np.int64)
    dv = doc[valid]
    if not len(dv) or int(dv.max()) == int(dv.min()):
        return None
    # garbage in invalid / padding rows must not overflow the
    # composite arithmetic (the admitted-rows-only rule every other
    # staging bound follows)
    return np.clip(doc, 0, int(dv.max()))


def _compose_doc_ids(cols, doc, client, oc, valid, live_origin):
    """Fold the doc column into the client-id space (round 14, the
    tenant-packing tentpole): every client-bearing column remaps to
    ``doc * stride + rank`` where rank is the row's client's position
    in ONE shared raw-client table. The map is order-preserving
    WITHIN each doc (rank is monotone in the raw id) and DISJOINT
    across docs (stride > max rank), so everything downstream — the
    id sort, duplicate drop, origin resolution, right-origin
    attachment walks — stays doc-local with no further doc handling:
    two docs' rows can never share an id key, so a row can never
    dedup against, resolve an origin in, or anchor a right to another
    doc. Sibling rules compare clients only through a monotone map
    (the ResidentColumns rationale), so per-doc outputs are
    byte-identical to each doc staged alone (tests/test_multidoc.py).

    Returns ``(cols, client, oc)`` with ``cols`` shallow-copied when
    the right-origin column needed remapping, or None when the
    composite space would overflow the packable id range (callers
    fall back, exactly like the other staging bounds)."""
    rc_raw = (np.asarray(cols["right_client"], np.int64)
              if "right_client" in cols else None)
    pools = [client[valid], oc[live_origin]]
    live_r = None
    if rc_raw is not None:
        live_r = valid & (rc_raw >= 0)
        if live_r.any():
            pools.append(rc_raw[live_r])
    uniq_all = np.unique(np.concatenate(pools))
    stride = np.int64(len(uniq_all) + 1)
    if int(doc[valid].max()) >= (1 << 61) // int(stride):
        return None
    base = doc * stride

    def comp(x, live):
        r = np.searchsorted(uniq_all, np.clip(x, uniq_all[0], None))
        return np.where(live, base + r, x)

    client = comp(client, valid)
    oc = comp(oc, oc >= 0)
    if rc_raw is not None and live_r.any():
        cols = dict(cols)
        cols["right_client"] = comp(rc_raw, rc_raw >= 0)
    return cols, client, oc


def _stage(cols: Dict[str, np.ndarray],
           put=None, wide: Optional[bool] = None,
           _sections: Optional[list] = None) -> Optional[PackedPlan]:
    """Pack kernel columns into the single-transfer matrix.

    ``_sections`` (internal; the multi-chip sharder's seam): when a
    list is passed, the layout work runs in full but the encode step
    is SKIPPED — the named section arrays are appended to the list in
    ``SECTION_NAMES`` order and the returned plan has
    ``mat=None/encs=()``. The sharder pads every shard's sections to
    common bucket sizes and encodes them with one shared encoding
    tuple (:func:`_encode_sections` ``force=``), so one shard_map
    program serves all shards.

    Returns None when the batch exceeds the packed path's bounds
    (callers fall back to the general kernels): >=2^25 distinct
    parents, >=2^21 distinct map keys, clocks >= 2^40 (the shared
    ``pack_id`` bound), or >=2^30 segments. (The round-11 63-bit
    sibling-key precheck is gone: the sort diet builds the sibling
    order on the host with ``np.lexsort`` over separate keys, so no
    packed device key exists to overflow.)

    ``put`` (e.g. :func:`crdt_tpu.ops.device.xfer_put`) switches
    staging to EAGER row shipping: each packed row starts its (async)
    host->device transfer the moment its layout pass finishes, so the
    upload overlaps the remaining staging work instead of serializing
    after it — on the tunnelled platform that hides most of one of the
    two costs. The compact sequence block also ships at its own bucket
    width (B, not kpad), cutting the transfer by up to a third. The
    plan then has ``mat=None`` and device refs in ``dev``.

    ``wide`` (None = the CRDT_TPU_WIDE_STAGING env default) disables
    the narrow-section encodings: every section ships at its int32
    width. The default NARROW path halves the staged bytes whenever
    every section's range fits (see the module's transfer-diet
    block); a section that does not fit falls back automatically to
    two exact int16 hi/lo stretches — on BOTH the matrix and eager
    paths — and the chosen widths are recorded per upload
    (:func:`crdt_tpu.ops.device.record_staged_widths`).
    """
    if wide is None:
        wide = wide_staging_forced()
    client = np.asarray(cols["client"], np.int64)
    clock = np.asarray(cols["clock"], np.int64)
    pir = np.asarray(cols["parent_is_root"], bool)
    pa = np.asarray(cols["parent_a"], np.int64)
    pb = np.asarray(cols["parent_b"], np.int64)
    kid = np.asarray(cols["key_id"], np.int64)
    oc = np.asarray(cols["origin_client"], np.int64)
    ock = np.asarray(cols["origin_clock"], np.int64)
    valid = np.asarray(cols["valid"], bool)
    n = len(client)
    if n == 0 or not valid.any():
        return None
    # bound checks consider only admitted rows: garbage in invalid /
    # padding rows must not force a spurious fallback (advisor
    # finding, round 2)
    if int(clock[valid].max()) >= (1 << _CLOCK_BITS):
        return None
    live_origin = valid & (oc >= 0)
    if live_origin.any() and int(ock[live_origin].max()) >= (1 << _CLOCK_BITS):
        return None

    # multi-doc staging (round 14): doc-id becomes a first-class
    # segment column — client ids fold into doc-composite ids (one
    # doc's ids can never collide with another's) and the parent-ref
    # interning below takes doc as its MAJOR key, so segments are
    # doc-pure and numbered doc-major. One dispatch then converges a
    # whole tenant batch with per-doc outputs byte-identical to each
    # doc converged alone.
    doc = _doc_column(cols, valid)
    if doc is not None:
        composed = _compose_doc_ids(cols, doc, client, oc, valid,
                                    live_origin)
        if composed is None:
            return None
        cols, client, oc = composed

    # dense order-preserving client ranks (origins share the table;
    # only admitted rows contribute — garbage in invalid rows must not
    # widen client_bits toward a spurious key-width fallback)
    uniq = np.unique(np.concatenate([client[valid], oc[live_origin]]))
    client_d = np.searchsorted(uniq, np.clip(client, uniq[0], None))
    client_d = np.where(valid, client_d, 0)
    oc_d = np.where(oc >= 0, np.searchsorted(uniq, np.clip(oc, uniq[0], None)), -1)

    # dense parent refs: exact two-key unique via lexsort runs. With
    # docs active the doc column is the MAJOR sort key, so parent
    # refs (and through segkey_of, segments) never merge across docs
    # and number doc-major — within one doc the order is exactly the
    # single-doc (pir, pa, pb) order, so a doc's slice of the packed
    # stream is its own oracle stream
    if doc is not None:
        porder = np.lexsort((pb, pa, pir, doc))
        doc_s = doc[porder]
        doc_run = np.r_[False, doc_s[1:] != doc_s[:-1]]
    else:
        porder = np.lexsort((pb, pa, pir))
        doc_run = False
    pir_s, pa_s, pb_s = pir[porder], pa[porder], pb[porder]
    new_run = np.r_[
        True,
        (pir_s[1:] != pir_s[:-1])
        | (pa_s[1:] != pa_s[:-1])
        | (pb_s[1:] != pb_s[:-1]),
    ] | doc_run
    ref_sorted = np.cumsum(new_run) - 1
    pref = np.empty(n, np.int64)
    pref[porder] = ref_sorted

    kid_max = int(kid[valid].max())
    if (int(pref[valid].max()) >= (1 << _PREF_BITS)
            or kid_max >= (1 << _KID_BITS)):
        return None

    # id sort + dedup (dense client ranks are monotone in the raw ids,
    # so the dense-packed id sorts identically to the raw-packed one)
    ikey = np.where(
        valid, (client_d << _CLOCK_BITS) | clock, np.int64(2**62)
    )
    order = np.argsort(ikey, kind="stable").astype(np.int32)
    ikey_s = ikey[order]
    kid_s = kid[order]
    pref_s = pref[order]
    oc_s = oc_d[order]
    ock_s = ock[order]
    valid_s = valid[order]
    client_s = client_d[order]
    dup = np.r_[False, ikey_s[1:] == ikey_s[:-1]]
    uniq_valid = valid_s & ~dup

    # dense segments over live rows; map segkeys carry bit 62, so
    # np.unique numbers every sequence segment below every map segment
    sk = segkey_of(pref_s, kid_s)
    uniq_sk, seg_inv, seg_counts = np.unique(
        sk[uniq_valid], return_inverse=True, return_counts=True
    )
    n_segs = len(uniq_sk)
    if n_segs >= _SEQ_FLAG:
        return None
    seg = np.full(n, -1, np.int64)
    seg[uniq_valid] = seg_inv
    map_seg = uniq_sk >= (1 << 62)
    # per-segment populations bound the device doubling rounds: a DFS
    # path cannot exceed its segment's row count + 1 (virtual root),
    # a map key chain cannot be deeper than its segment's row count
    max_map = int(seg_counts[map_seg].max()) if map_seg.any() else 1
    max_seq = int(seg_counts[~map_seg].max()) if (~map_seg).any() else 1

    # origin rows by binary search over the sorted ids (leftmost match
    # is the kept representative of any duplicate run)
    okey = np.where(
        oc_s >= 0, (oc_s << _CLOCK_BITS) | ock_s, np.int64(-1)
    )
    pos = np.searchsorted(ikey_s, okey)
    posc = np.clip(pos, 0, n - 1)
    origin_row = np.where(
        (okey >= 0) & (ikey_s[posc] == okey), posc, -1
    )
    is_map_row = uniq_valid & (kid_s >= 0)

    # compact sequence block: seq rows ascending (= id rank ascending),
    # same-segment origins resolved to compact positions
    seq_rows = np.flatnonzero(uniq_valid & (kid_s < 0))
    n_seq = len(seq_rows)
    if n_seq:
        o_rows = origin_row[seq_rows]
        o_seg = seg[np.clip(o_rows, 0, n - 1)]
        same_seg = (o_rows >= 0) & (o_seg == seg[seq_rows])
        cpos = np.searchsorted(seq_rows, np.clip(o_rows, 0, None))
        cposc = np.clip(cpos, 0, n_seq - 1)
        c_parent = np.where(
            same_seg & (seq_rows[cposc] == o_rows), cposc, -1
        )
    else:
        c_parent = np.empty(0, np.int64)

    # right-origin attachment ordering (mid-inserts/prepends): groups
    # with in-group anchors get their exact conflict-scan ranks
    # written INTO the client column (ranks are unique per group, so
    # the id tie-break never fires and the sibling tables need no
    # change); inexpressible shapes mark their segments hard for the
    # scalar fallback at gather. Since round 23 this runs BEFORE the
    # subtree split: with the ranks baked into client_s the sibling
    # comparator — hence the DFS stream any suffix cut preserves — is
    # exact, so benign right-bearing segments become split candidates
    # and only HARD segments stay pinned
    hard_rep_rows: list = []
    hard_seg_ids: list = []
    if "right_client" in cols:
        client_s, hard_rep_rows, _, hard_seg_ids = _stage_rights(
            cols, order, ikey_s, uniq, seg, origin_row, oc_s, seq_rows,
            uniq_valid, kid_s, client_s.copy(), client[order],
            clock[order],
        )

    # subtree split (rounds 13 + 23): re-cut oversized sequence
    # segments at DFS-suffix subtree granularity — branching trees
    # included — and deep LWW map key chains at depth granularity
    # into bounded-size synthetic segments, dropping BOTH device
    # doubling bounds from ceil(log2(deepest structure)) to
    # ceil(log2(split width)) — and giving the multi-chip sharder
    # independent pieces to spread across chips
    map_rows = np.flatnonzero(is_map_row)
    n_map = len(map_rows)
    synth_orig = None
    seam_compact = np.empty(0, np.int64)
    win_src = None
    n_seq_cuts = n_map_cuts = 0
    w_split = chain_split_width()
    if w_split and (n_seq or n_map):
        rr_all = (np.asarray(cols["right_client"], np.int64)[order]
                  if "right_client" in cols
                  else np.full(n, -1, np.int64))
        split = _subtree_split(
            seg, seq_rows, c_parent, client_s, w_split,
            hard_seg_ids, map_rows, origin_row, rr_all,
        )
        if split is not None and len(split[3]) < _SEQ_FLAG:
            (seg, c_parent, seam_compact, synth_orig, win_src,
             n_seq_cuts, n_map_cuts) = split
            n_segs = len(synth_orig)
            if n_seq:
                bc2 = np.bincount(seg[seq_rows], minlength=1)
                max_seq = int(bc2.max())
            if n_map:
                bcm = np.bincount(seg[map_rows], minlength=1)
                max_map = int(bcm.max())

    # size buckets early: eager shipping needs the padded widths now,
    # and the int32-index guard must run BEFORE the first put — an
    # infeasible plan must not queue dead transfers through the
    # tunnel only to fall back and re-ship via the general path.
    # (The round-11 63-bit sibling-key prechecks are GONE: the sort
    # diet builds the sibling order on the host with np.lexsort over
    # separate keys, so no packed device key exists to overflow.)
    kpad = bucket_grid(n, floor=6)
    Sb = bucket_grid(max(n_segs, 1), floor=6)
    n_seq_early = int(np.count_nonzero(uniq_valid & (kid_s < 0)))
    n_map_early = int(np.count_nonzero(uniq_valid & (kid_s >= 0)))
    B = min(kpad, bucket_grid(max(n_seq_early, 1), floor=6))
    M = min(kpad, bucket_grid(max(n_map_early, 1), floor=6))
    if max(kpad, B, M) + Sb >= (1 << 31) - 1:
        return None

    # group 0 sections (complete now): segment ids + doc-order
    # offsets + compact parents. The offsets are the scatter targets:
    # document order is out[off[seg] + dfs_rank] = row, so the device
    # never sorts by (seg, rank) again
    seq_seg = np.full(B, -1, np.int64)
    seq_seg[:n_seq] = seg[seq_rows]
    counts = np.zeros(Sb, np.int64)
    if n_seq:
        bc = np.bincount(seg[seq_rows], minlength=1)
        counts[: len(bc)] = bc
    seg_off = np.concatenate(([0], np.cumsum(counts)[:-1]))
    seq_parent = np.full(B, -1, np.int64)
    seq_parent[:n_seq] = c_parent
    g0 = [("seq_seg", seq_seg), ("seg_off", seg_off),
          ("seq_parent", seq_parent)]
    d0 = d1 = d2 = None
    enc0 = enc1 = enc2 = ()
    w_all: dict = {}
    shipped = 0
    if put is not None:
        f0, enc0, w0 = _encode_sections(g0, wide)
        w_all.update(w0)
        shipped += f0.nbytes
        d0 = put(f0)

    # group 2 sections: the map block, grouped by chain parent. One
    # stable host radix pass puts every node's children in one
    # contiguous run ordered (client asc, clock asc), so the device's
    # segmented argmax scan reads each run's last child at its END —
    # the sort + run-edge chain of lww.map_winners collapses to one
    # VMEM pass at map-bucket width M, not padded n. Runs on the
    # POST-split segment column: a split map chain's pieces parent
    # within their own synthetic segment only, so the same-segment
    # test below cuts each piece's chain at its seam for free
    map_key = np.full(M, -1, np.int64)
    chain_end = np.full(M, -1, np.int64)
    root_end = np.full(Sb, -1, np.int64)
    if n_map:
        o = origin_row[map_rows]
        o_c = np.clip(o, 0, n - 1)
        # same-segment origin => chain parent; anything else (missing,
        # cross-segment, a sequence row) roots the chain — the GC'd
        # -origin convention shared with lww.map_winners
        same = (o >= 0) & (seg[o_c] == seg[map_rows])
        cm_par = np.where(same, np.searchsorted(map_rows, o_c), -1)
        pslot = np.where(cm_par >= 0, cm_par, M + seg[map_rows])
        gorder = np.argsort(pslot, kind="stable")
        ps = pslot[gorder]
        newrun = np.r_[True, ps[1:] != ps[:-1]]
        ends = np.r_[np.flatnonzero(ps[1:] != ps[:-1]), n_map - 1]
        run_key = ps[ends]
        inv_g = np.empty(n_map, np.int64)
        inv_g[gorder] = np.arange(n_map)
        item_run = run_key < M
        # chain_end is indexed by the PARENT's grouped position — the
        # node space the device's last-child doubling runs in
        chain_end[inv_g[run_key[item_run]]] = ends[item_run]
        root_end[run_key[~item_run] - M] = ends[~item_run]
        # dense client rank with the run-start flag folded into bit 0
        # (one section instead of two; clients past 2^14 ranks spill
        # the section to hi/lo, never a wrong decode)
        map_key[:n_map] = (client_s[map_rows[gorder]] << 1) | newrun
    else:
        gorder = np.empty(0, np.int64)
    g2 = [("map_key", map_key), ("map_chain_end", chain_end),
          ("map_root_end", root_end)]
    if put is not None:
        f2, enc2, w2 = _encode_sections(g2, wide)
        w_all.update(w2)
        shipped += f2.nbytes
        d2 = put(f2)

    # group 1 sections (after the rank overwrites): the sequence
    # forest's sibling tables. ONE host lexsort by (parent, client,
    # clock desc) — cost scales with the compact block, and the
    # next-sibling / first-child tables fall out of the same pass, so
    # the device's B-width sibling argsort + run-edge searchsorted
    # disappear from the dispatch entirely
    nxt = np.full(B, -1, np.int64)
    fc = np.full(B + Sb, -1, np.int64)
    if n_seq:
        cl_q = client_s[seq_rows]
        posd = (n - 1) - seq_rows  # clock desc within (parent, client)
        pslot2 = np.where(c_parent >= 0, c_parent, B + seg[seq_rows])
        sord2 = np.lexsort((posd, cl_q, pslot2))
        ps2 = pslot2[sord2]
        same2 = ps2[1:] == ps2[:-1]
        nxt[sord2[:-1][same2]] = sord2[1:][same2]
        starts = np.r_[0, np.flatnonzero(~same2) + 1]
        fc[ps2[starts]] = sord2[starts]
    g1 = [("seq_next", nxt), ("seq_first", fc)]

    if put is not None:
        f1, enc1, w1 = _encode_sections(g1, wide)
        w_all.update(w1)
        shipped += f1.nbytes
        d1 = put(f1)
        mat = None
        dev = (d0, d1, d2)
        encs = enc0 + enc1 + enc2
        # eager puts ARE the upload: record here, at the seam's
        # moment. The diet baseline stays the PRE-diet (round-8)
        # staging of the same union — raw int32 columns + compact
        # block — so both the round-9 narrowing and the round-12
        # section re-cut count as transfer savings
        record_staged_widths(w_all, shipped, (3 * kpad + 2 * B) * 4)
    else:
        named = g0 + g1 + g2
        if _sections is not None:
            # layout-only: the sharder pads + encodes across shards
            _sections.extend(named)
            mat, encs, w_all = None, (), {}
        else:
            mat, encs, w_all = _encode_sections(named, wide)
        dev = ()
        # NOT recorded here: a matrix plan may never cross the link
        # (converge_host, make_repeat_dispatch) — the width/savings
        # record fires at the plan's actual upload instead

    # assembly counts: the host rebuilds the stream's per-segment
    # boundaries from these. With chain-split active the counts of a
    # split segment's pieces accumulate onto its FIRST synthetic id —
    # pieces are consecutive in both numbering and stream order, so
    # the merged run is exactly the unsplit segment's run and the
    # assembler never sees a seam
    counts_asm = counts
    if synth_orig is not None:
        counts_asm = np.zeros(Sb, np.int64)
        _, first_idx, inv_o = np.unique(
            synth_orig, return_index=True, return_inverse=True
        )
        np.add.at(counts_asm, first_idx[inv_o], counts[:n_segs])

    rank_rounds_v = _even_up((max_seq + 2).bit_length() + 1)
    map_rounds_v = _even_up((max_map + 2).bit_length() + 1)
    tracer = get_tracer()
    if tracer.enabled:
        # the doubling-rounds bounds this plan's dispatch will run —
        # the subtree-split lever's regression evidence (lower =
        # fewer random-gather rounds on the device), plus the cut
        # counts that explain WHY a bound moved
        tracer.gauge("converge.wyllie_rounds", rank_rounds_v)
        tracer.gauge("converge.map_rounds", map_rounds_v)
        tracer.gauge("converge.subtree_cuts", n_seq_cuts)
        tracer.gauge("converge.map_chain_cuts", n_map_cuts)
        if len(seam_compact):
            tracer.count("converge.chain_seams", len(seam_compact))
        if doc is not None:
            # the tenant-packing evidence: how many independent docs
            # this ONE staged plan carries (every dispatch of it
            # amortizes the fixed floor across that many tenants)
            tracer.count("converge.docs_packed",
                         len(np.unique(doc[valid])))

    # map-winner stitch, padded to the segment bucket with identity
    # (pad slots read their own — always -1 — winner)
    win_src_pad = None
    if win_src is not None:
        win_src_pad = np.arange(Sb, dtype=np.int64)
        win_src_pad[:len(win_src)] = win_src

    map_back = np.full(M, NULLI, np.int32)
    if n_map:
        map_back[:n_map] = order[map_rows[gorder]]
    seq_back = np.full(B, NULLI, np.int32)
    seq_back[:n_seq] = order[seq_rows]
    return PackedPlan(
        mat=mat,
        dev=dev,
        n=n,
        num_segments=Sb,
        seq_bucket=B,
        map_bucket=M,
        order=order,
        clients=uniq,
        rank_rounds=rank_rounds_v,
        map_rounds=map_rounds_v,
        hard_rows=tuple(hard_rep_rows),
        staged_widths=tuple(sorted(w_all.items())),
        encs=encs,
        map_back=map_back,
        seq_back=seq_back,
        seg_counts=counts_asm,
        seam_rows=tuple(
            np.asarray(order)[seq_rows[seam_compact]].tolist()
        ) if len(seam_compact) else (),
        win_src=win_src_pad,
    )


def _section_sizes(num_segments: int, seq_bucket: int,
                   map_bucket: int) -> tuple:
    """Static per-section lengths, aligned with SECTION_NAMES."""
    B, S, M = seq_bucket, num_segments, map_bucket
    return (B, S, B, B, B + S, M, M, S)


def _map_block(mkey, cend, rend, *, map_rounds: int, mode: str):
    """Map side of the fused converge: segmented Lamport argmax over
    chain-parent runs + winner-chain doubling, all at map-bucket
    width. Each node's children sit in one contiguous run (staging
    grouped them), ordered (client asc, clock asc); the scan's
    run-prefix argmax read at a run's END is the run's (max client,
    min clock) member — the last child of the Yjs sibling order. The
    chain walk (deep key chains) stays pointer doubling.

    ONE definition shared by :func:`_converge_packed_body` and the
    bench ablation rig (``bench.kernel_ablation_leg``), so the gated
    ``kernel_ablation.map_winners_ms`` numbers always time the
    algorithm production runs."""
    M = mkey.shape[0]
    mflag = jnp.where(mkey >= 0, mkey & 1, 1).astype(jnp.int32)
    mcl = jnp.where(mkey >= 0, mkey >> 1, NULLI).astype(jnp.int32)
    from crdt_tpu.ops.pallas_kernels import seg_argmax_scan

    arg = seg_argmax_scan(mcl, mflag, mode=mode)
    iota_m = jnp.arange(M, dtype=jnp.int32)
    last = jnp.where(
        cend >= 0, arg[jnp.clip(cend, 0, M - 1)], iota_m
    ).astype(jnp.int32)
    tail = pointer_double(last, max_iters=map_rounds)
    start = jnp.where(rend >= 0, arg[jnp.clip(rend, 0, M - 1)], NULLI)
    return jnp.where(
        start >= 0, tail[jnp.clip(start, 0, M - 1)], NULLI
    ).astype(jnp.int32)


def _converge_packed_body(sseg, soff, cp, nxt, fc, mkey, cend, rend, *,
                          num_segments: int, seq_bucket: int,
                          map_bucket: int, rank_rounds: int,
                          map_rounds: int, mode: str):
    """The fused convergence over PRECOMPUTED layout sections (see the
    module's section table): the round-12 sort diet. The dispatch
    contains ZERO sorts and zero searchsorteds — its work is the two
    Pallas kernels (segmented Lamport argmax, document-order scatter),
    the pointer-doubling loops, and a handful of block-width gathers.
    Returns one packed int32 array:

      [ win_pos[S] | stream_perm[B] ]

    - win_pos: grouped map-block position of each segment's winner
      (-1 for non-map / empty segments; the host maps back through
      ``plan.map_back``);
    - stream_perm: compact sequence index at each document-order
      position, grouped by segment id ascending (-1 padding at the
      tail; the host maps back through ``plan.seq_back``).

    ``mode`` is the static kernel-dispatch decision
    (:func:`crdt_tpu.ops.pallas_kernels.converge_kernel_mode`).
    """
    from crdt_tpu.ops.pallas_kernels import stream_scatter

    B, S, M = seq_bucket, num_segments, map_bucket

    win_pos = _map_block(mkey, cend, rend, map_rounds=map_rounds,
                         mode=mode)

    # ---- sequence side: DFS ranks over the PRE-BUILT sibling tables
    # (no sibling sort, no run-edge searchsorted), then document
    # order as a permutation scatter out[off[seg] + rank] = row
    c_ok = sseg >= 0
    mB = B + S
    parent = jnp.where(c_ok & (cp >= 0), cp, B + jnp.maximum(sseg, 0))
    parent = jnp.where(c_ok, parent, mB).astype(jnp.int32)
    dist = dfs_ranks(
        parent, nxt.astype(jnp.int32), fc.astype(jnp.int32), c_ok, S,
        rank_rounds=rank_rounds,
    )
    root_dist = dist[B + jnp.maximum(sseg, 0)]
    c_rank = jnp.where(c_ok, root_dist - dist[:B] - 1, NULLI)
    pos = jnp.where(
        c_ok & (c_rank >= 0),
        soff[jnp.clip(sseg, 0, S - 1)] + c_rank,
        NULLI,
    )
    perm = stream_scatter(pos.astype(jnp.int32), B, mode=mode)
    return jnp.concatenate([win_pos, perm])


_STATIC_ARGS = ("num_segments", "seq_bucket", "map_bucket",
                "rank_rounds", "map_rounds", "encs", "mode")


def _body_from_flat(mat, num_segments, seq_bucket, map_bucket,
                    rank_rounds, map_rounds, encs, mode):
    secs = _decode_sections(
        mat, _section_sizes(num_segments, seq_bucket, map_bucket), encs
    )
    return _converge_packed_body(
        *secs, num_segments=num_segments, seq_bucket=seq_bucket,
        map_bucket=map_bucket, rank_rounds=rank_rounds,
        map_rounds=map_rounds, mode=mode,
    )


@partial(jax.jit, donate_argnums=(0,), static_argnames=_STATIC_ARGS)
def _converge_packed(mat, num_segments: int, seq_bucket: int,
                     map_bucket: int, rank_rounds: int,
                     map_rounds: int, encs=(), mode="jnp"):
    """Single-array entry over :func:`_converge_packed_body`
    (matrix-staged plans): widening prelude + fused body. The staged
    array is DONATED: its device buffer is consumed by the dispatch
    (the allocator reuses it for outputs / the next shard's upload
    instead of holding both live), so a plan must be converged at
    most once — repeated-dispatch probes use
    :func:`make_repeat_dispatch`."""
    return _body_from_flat(mat, num_segments, seq_bucket, map_bucket,
                           rank_rounds, map_rounds, encs, mode)


@partial(jax.jit, donate_argnums=(0, 1, 2),
         static_argnames=_STATIC_ARGS)
def _converge_rows(d0, d1, d2, num_segments: int, seq_bucket: int,
                   map_bucket: int, rank_rounds: int, map_rounds: int,
                   encs=(), mode="jnp"):
    """Separate-group entry for eagerly shipped plans (``stage(put=)``):
    same fused body, the three section groups already resident on
    device and DONATED to the dispatch (see :func:`_converge_packed`).
    ``encs`` carries the full per-section encoding tuple; each group
    decodes its own slice of it."""
    sizes = _section_sizes(num_segments, seq_bucket, map_bucket)
    secs = []
    for dref, (a, b) in zip((d0, d1, d2), _SECTION_GROUPS):
        secs.extend(_decode_sections(dref, sizes[a:b], encs[a:b]))
    return _converge_packed_body(
        *secs, num_segments=num_segments, seq_bucket=seq_bucket,
        map_bucket=map_bucket, rank_rounds=rank_rounds,
        map_rounds=map_rounds, mode=mode,
    )


@partial(jax.jit, static_argnames=_STATIC_ARGS)
def _converge_packed_nodonate(mat, num_segments: int, seq_bucket: int,
                              map_bucket: int, rank_rounds: int,
                              map_rounds: int, encs=(), mode="jnp"):
    """Undonated twin of :func:`_converge_packed` for the consumers
    that cannot honor (or benefit from) donation: the local-CPU host
    route (CPU has no donation — the donating entry would warn per
    compiled shape in library consumers' stderr) and the repeated
    bench-sweep probe."""
    return _body_from_flat(mat, num_segments, seq_bucket, map_bucket,
                           rank_rounds, map_rounds, encs, mode)


def make_repeat_dispatch(plan: PackedPlan):
    """(device_matrix, fn) for REPEATED undonated dispatches of a
    matrix-staged plan — the bench kernel sweep's probe. The
    production entries donate their staged buffers to the program
    (one plan, one dispatch), which makes re-dispatching the same
    device array through them invalid on donation-capable backends."""
    if plan.mat is None:
        raise ValueError("repeat dispatch needs a matrix-staged plan")
    args = _plan_args(plan)

    def fn(m):
        # the mode decision (and its converge.pallas{mode} count) is
        # made PER DISPATCH, honoring the counter's one-count-per-
        # dispatch contract for the repeat probe too — a closure
        # built but never invoked records nothing
        mode = kernel_mode_for(plan.map_bucket, plan.seq_bucket)
        with enable_x64(True):  # the ranking loop packs int64 words
            return _converge_packed_nodonate(
                m, **args, encs=plan.encs, mode=mode
            )

    return jnp.asarray(plan.mat), fn




def _rank_compact(parent, c_client, pos_desc, c_seg, c_ok, row_of, *,
                  num_segments: int, rank_rounds: Optional[int],
                  client_bits: int, qbits: int, doc_off=None,
                  mode: str = "jnp"):
    """Sibling sort + tree tables + climb + Wyllie ranking + document
    order over the COMPACT sequence space (B rows + S virtual roots).
    ``row_of[i]`` is the caller-space row of compact row i, used only
    to label the output stream. Engine of the general/incremental
    :func:`_converge_core` (the cold staged dispatch now precomputes
    the sibling tables at staging and runs the sortless
    :func:`_converge_packed_body` instead).

    Sibling order is (parent, client asc, clock DESC); ``pos_desc``
    must be descending in clock within one (parent, client) group —
    all callers derive it from id-sorted row positions.

    ``doc_off`` [S] is each segment's first compact position (the
    caller reads it off its already-sorted segment keys): document
    order becomes the scatter out[doc_off[seg] + rank] = row — the
    round-12 sort diet's replacement for the B-width argsort over
    (seg, rank) keys. ``mode`` picks the scatter kernel
    (:func:`crdt_tpu.ops.pallas_kernels.converge_kernel_mode`).
    """
    from crdt_tpu.ops.pallas_kernels import stream_scatter

    B = parent.shape[0]
    mB = B + num_segments
    pbits = int(mB).bit_length()
    if pbits + client_bits + qbits <= 63:
        sibkey = (
            (parent.astype(jnp.int64) << (client_bits + qbits))
            | (c_client.astype(jnp.int64) << qbits)
            | pos_desc.astype(jnp.int64)
        )
        sord2 = jnp.argsort(sibkey, stable=True)
    else:
        sord2 = lexsort([
            parent.astype(jnp.int64),
            (c_client.astype(jnp.int64) << qbits)
            | pos_desc.astype(jnp.int64),
        ])
    p_s = parent[sord2]
    same_group = jnp.concatenate([p_s[1:] == p_s[:-1], jnp.zeros(1, bool)])
    nxt_sorted = jnp.where(
        same_group, jnp.roll(sord2, -1), NULLI
    ).astype(jnp.int32)
    next_sib = scatter_perm(sord2, nxt_sorted)
    first_pos, _ = run_edge_lookup(p_s, mB, side="left")
    first_child = jnp.where(
        first_pos >= 0, sord2[jnp.clip(first_pos, 0, B - 1)], NULLI
    ).astype(jnp.int32)

    dist_to_end = dfs_ranks(parent, next_sib, first_child, c_ok,
                            num_segments, rank_rounds=rank_rounds)
    root_dist = dist_to_end[B + jnp.maximum(c_seg, 0)]
    c_rank = jnp.where(c_ok, root_dist - dist_to_end[:B] - 1, NULLI)

    ranked = c_ok & (c_rank >= 0)
    pos = jnp.where(
        ranked,
        doc_off[jnp.clip(c_seg, 0, num_segments - 1)].astype(jnp.int32)
        + c_rank.astype(jnp.int32),
        NULLI,
    )
    perm = stream_scatter(pos.astype(jnp.int32), B, mode=mode)
    okp = perm >= 0
    permc = jnp.clip(perm, 0, B - 1)
    stream_seg = jnp.where(okp, c_seg[permc], NULLI).astype(jnp.int32)
    stream_row = jnp.where(okp, row_of[permc], NULLI).astype(jnp.int32)
    return stream_seg, stream_row


def _converge_core(client, clock, pref, kid, oc, ock, valid, *,
                   num_segments: int, seq_bucket: int,
                   rank_rounds: Optional[int] = None,
                   map_rounds: Optional[int] = None,
                   mode: str = "jnp"):
    """Traced body of the GENERAL packed convergence: does its own id
    sort, dedup, origin resolution, and segment numbering on device.
    The cold replay no longer routes here (its staging precomputes the
    layout — see :func:`_converge_packed`); this remains the engine of
    the incremental touched-segment path
    (``crdt_tpu.models.incremental``), where rows live resident in HBM
    and host precomputation is not available. Row indices in the
    output refer to the CALLER's row space."""
    n = client.shape[0]

    # shared id-sort + dedup + origin resolution (one for both kernels)
    ikey = jnp.where(valid, pack_id(client, clock), jnp.int64(2**62))
    order = jnp.argsort(ikey, stable=True)
    ikey = ikey[order]
    client = client[order]
    clock = clock[order]
    pref = pref[order]
    kid = kid[order]
    oc = oc[order]
    ock = ock[order]
    valid = valid[order]
    dup = jnp.concatenate([jnp.zeros(1, bool), ikey[1:] == ikey[:-1]])
    uniq_valid = valid & ~dup
    okey = pack_id(oc, ock)
    origin_idx = searchsorted_ids(ikey, okey)

    is_map = uniq_valid & (kid >= 0)
    is_seq = uniq_valid & (kid < 0)

    # one composite segment key covers maps AND sequences (dup rows of
    # a map item are ~uniq_valid, so the unmasked kid flag is moot for
    # them — the invalid-row sentinel overrides either way)
    segkey = jnp.where(
        uniq_valid,
        segkey_of(pref, kid.astype(jnp.int64)),
        jnp.int64(2**63 - 1),
    )
    sorder = jnp.argsort(segkey, stable=True)
    seg_sorted = dense_ranks_sorted(segkey[sorder])
    seg = scatter_perm(sorder, seg_sorted)
    seg_map = jnp.where(is_map, seg, NULLI)
    seg_seq = jnp.where(is_seq, seg, NULLI)

    winners = map_winners(
        seg_map, client, clock, origin_idx, is_map, num_segments,
        rows_id_ranked=True, chain_rounds=map_rounds, client_bits=23,
    )
    win_rows = jnp.where(
        winners >= 0, order[jnp.clip(winners, 0, n - 1)], NULLI
    ).astype(jnp.int32)

    # ---- sequence ranking in COMPACT space ---------------------------
    # Sequence segkeys sort below map segkeys (bit 62) and invalid rows
    # (max), so sorder's prefix holds exactly the sequence rows and the
    # static seq bucket B >= n_seq covers them. All sibling/climb/rank
    # machinery runs at size B (+S roots) instead of the full padded n.
    B = seq_bucket
    mB = B + num_segments
    sub = sorder[:B]
    c_ok = is_seq[sub]
    c_seg = jnp.where(c_ok, seg[sub], NULLI)
    # full-space row -> sorder position (compact index for seq rows)
    inv_sorder = jnp.argsort(sorder, stable=True).astype(jnp.int32)
    o = origin_idx[sub]
    o_ok = c_ok & (o >= 0)
    o_seg = jnp.where(o_ok, seg[jnp.clip(o, 0, n - 1)], NULLI)
    same_seg = o_ok & (o_seg == c_seg)
    c_parent = jnp.where(
        same_seg, inv_sorder[jnp.clip(o, 0, n - 1)], NULLI
    ).astype(jnp.int32)

    parent = jnp.where(
        c_ok & (c_parent >= 0), c_parent, B + jnp.maximum(c_seg, 0)
    )
    parent = jnp.where(c_ok, parent, mB).astype(jnp.int32)

    # sibling order by (parent, client asc, clock DESC). Within one
    # client, clock order == id-sorted position order, so the global
    # row index (already an id-rank here) stands in for the clock —
    # making the whole key fit one int64 when the static widths allow.
    c_client = client[sub]
    pos_desc = (n - 1) - sub  # descending position == descending clock
    # document-order offsets off the ALREADY segment-sorted keys: one
    # S-vs-n searchsorted instead of re-deriving them with the B-width
    # (seg, rank) argsort the scatter now replaces. Compact space is
    # the sorted prefix, so a segment's first sorted position IS its
    # exclusive document-order offset.
    doc_off, _ = run_edge_lookup(seg_sorted, num_segments, side="left")
    stream_seg, stream_row = _rank_compact(
        parent, c_client, pos_desc, c_seg, c_ok, order[sub],
        num_segments=num_segments, rank_rounds=rank_rounds,
        client_bits=23, qbits=int(max(n - 1, 1)).bit_length(),
        doc_off=doc_off, mode=mode,
    )
    return jnp.concatenate([win_rows, stream_seg, stream_row])


def segkey_int(pref: int, kid: int) -> int:
    """Scalar-Python :func:`segkey_of` for per-op hot paths (the
    resident doc's local ops): no numpy temporaries, same key."""
    if kid >= 0:
        return ((pref << _KID_BITS) | kid) | (1 << 62)
    return pref << _KID_BITS


def segkey_of(pref, kid):
    """The composite segment key, shared by staging, the fused kernel,
    and the incremental host bookkeeping. Works on numpy or jnp
    (dtype-explicit: the map-flag bit 62 must not fall into a narrow
    weak-typed promotion)."""
    is_map = (kid >= 0).astype(np.int64)
    base = (pref << _KID_BITS) | (is_map * kid)
    return base | (is_map << np.int64(62))


def stage_resident_delta(client, clock, pref, kid, oc, ock,
                         dev_segs, kpad: int) -> np.ndarray:
    """Stage one incremental round's DELTA against a resident base:
    the ``[8, kpad]`` int64 block :func:`_splice_select_converge`
    consumes. Rows 0-6 are the packed delta columns (dense clients,
    clocks, parent refs; ``valid`` = resolvable parent), row 7 the
    touched-segment keys (ascending segkeys, int64-max padded). This
    is the delta-tick staging seam — a warm round ships THIS block
    only; the doc's history never restages (it is already resident in
    the donated matrix the splice updates in place)."""
    k = len(client)
    delta = np.zeros((8, kpad), np.int64)
    delta[3:6, :] = -1
    delta[7, :] = np.iinfo(np.int64).max
    delta[7, : len(dev_segs)] = dev_segs
    pref = np.asarray(pref, np.int64)
    delta[0, :k] = client
    delta[1, :k] = clock
    delta[2, :k] = np.maximum(pref, 0)
    delta[3, :k] = kid
    delta[4, :k] = oc
    delta[5, :k] = ock
    delta[6, :k] = pref >= 0
    return delta


@partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=("num_segments", "sel_bucket", "seq_bucket",
                     "mode", "rank_rounds", "map_rounds"),
)
def _splice_select_converge(mat, delta8, n_off,
                            num_segments: int, sel_bucket: int,
                            seq_bucket: int, mode: str = "jnp",
                            rank_rounds: Optional[int] = None,
                            map_rounds: Optional[int] = None):
    """Incremental warm dispatch — exactly THREE host<->device
    interactions per round: ONE upload (``delta8``: the packed delta
    columns with the touched-segment keys riding as row 7 — ascending
    segkeys, int64-max padding), ONE dispatch, and ONE fetch of a
    single packed array (the caller splits it). Splices the delta into
    the resident matrix (donated), selects the rows of the touched
    segments, and re-converges only that compact subset. Returns

      (resident_mat, [ out[S + 2B] | sel_rows[sel_bucket] ] int32)

    where out's row indices are LOCAL to sel_rows; callers map back
    with sel_rows (resident row ids, -1 padding)."""
    touched_sorted = delta8[7]
    mat = jax.lax.dynamic_update_slice(
        mat, delta8[:7].astype(mat.dtype),
        (jnp.int32(0), n_off.astype(jnp.int32)),
    )
    client = mat[0].astype(jnp.int32)
    clock = mat[1].astype(jnp.int64)
    pref = mat[2].astype(jnp.int64)
    kid = mat[3].astype(jnp.int32)
    oc = mat[4].astype(jnp.int32)
    ock = mat[5].astype(jnp.int64)
    valid = mat[6] != 0

    segkey = segkey_of(pref, kid.astype(jnp.int64))
    pos = jnp.searchsorted(touched_sorted, segkey, method="sort")
    pos_c = jnp.clip(pos, 0, touched_sorted.shape[0] - 1)
    sel = valid & (touched_sorted[pos_c] == segkey)
    skey = jnp.where(sel, segkey, jnp.int64(2**63 - 1))
    order2 = jnp.argsort(skey, stable=True)
    sel_rows = order2[:sel_bucket].astype(jnp.int32)
    sub_valid = sel[sel_rows]
    out = _converge_core(
        client[sel_rows], clock[sel_rows], pref[sel_rows], kid[sel_rows],
        oc[sel_rows], ock[sel_rows], sub_valid,
        num_segments=num_segments, seq_bucket=seq_bucket, mode=mode,
        rank_rounds=rank_rounds, map_rounds=map_rounds,
    )
    packed_out = jnp.concatenate([
        out, jnp.where(sub_valid, sel_rows, NULLI).astype(jnp.int32)
    ])
    return mat, packed_out


@partial(jax.jit, donate_argnums=(0,), static_argnames=("new_cap",))
def _grow_mat(mat, new_cap: int):
    """Capacity growth for the resident matrix, on device."""
    big = jnp.zeros((7, new_cap), mat.dtype)
    big = big.at[3:6, :].set(-1)  # key_id / origin columns: null
    return jax.lax.dynamic_update_slice(big, mat, (0, 0))


@partial(jax.jit, donate_argnums=(0,))
def _relabel_mat(mat, perm):
    """Rewrite dense client ids through an old->new permutation after
    a mid-table client insertion (order-preserving interning)."""
    cl = mat[0]
    oc = mat[4]
    mat = mat.at[0, :].set(perm[jnp.clip(cl, 0, perm.shape[0] - 1)]
                           .astype(mat.dtype))
    new_oc = jnp.where(
        oc >= 0, perm[jnp.clip(oc, 0, perm.shape[0] - 1)], oc
    )
    return mat.at[4, :].set(new_oc.astype(mat.dtype))


# ---- the POOLED resident matrix (round 20) --------------------------
#
# N warm docs co-located in ONE device allocation: rows carry their
# doc's POOL SLOT as lane 7 and store doc-LOCAL dense client / parent
# ids, and every dispatch composes DOC-COMPOSITE ids on the fly from
# per-slot base offsets (the `_compose_doc_ids` discipline: disjoint
# composite ranges keep dedup, origin resolution, and segment
# numbering doc-local with ZERO changes to `_converge_core`). Storing
# local ids — and composing per dispatch from traced base operands —
# means a doc joining or growing its id table never relabels any
# OTHER doc's rows, and base growth never recompiles.

# running count of warm device-route converge dispatches (one per
# `_splice_select_converge` round, one per pooled flush): the bench's
# `multitenant.steady.device_dispatches_per_tick` reads the delta
# around a tick. A plain module int — single-process bench plumbing,
# same pattern as the tracer's process-local counters.
device_dispatch_count = 0


def count_device_dispatch(n: int = 1) -> None:
    global device_dispatch_count
    device_dispatch_count += n


def stage_pooled_delta(client, clock, pref, kid, oc, ock, slot,
                       pos, kpad: int, pool_cap: int):
    """Stage one POOLED round's delta: the ``[8, kpad]`` int64 block
    plus the ``[kpad]`` int32 scatter positions
    :func:`_pool_splice_select_converge` consumes. Rows 0-6 follow
    :func:`stage_resident_delta` (doc-LOCAL dense ids), row 7 is the
    doc's pool slot. Padding positions land at ``pool_cap`` and are
    dropped by the scatter — the touched-segment keys travel as their
    own operand (no kpad >= tpad coupling)."""
    k = len(client)
    delta = np.zeros((8, kpad), np.int64)
    delta[3:6, :] = -1
    delta[7, :] = -1
    pref = np.asarray(pref, np.int64)
    delta[0, :k] = client
    delta[1, :k] = clock
    delta[2, :k] = np.maximum(pref, 0)
    delta[3, :k] = kid
    delta[4, :k] = oc
    delta[5, :k] = ock
    delta[6, :k] = pref >= 0
    delta[7, :k] = slot
    ppos = np.full(kpad, pool_cap, np.int32)
    ppos[:k] = pos
    return delta, ppos


def _pool_splice_body(mat, delta8, pos, touched_sorted, cbase, pbase,
                      num_segments: int, sel_bucket: int,
                      seq_bucket: int, mode: str,
                      rank_rounds: Optional[int] = None,
                      map_rounds: Optional[int] = None):
    """Shared traced body of the pooled splice+select+converge (see
    :func:`_pool_splice_select_converge` for the contract)."""
    mat = mat.at[:, pos].set(delta8.astype(mat.dtype), mode="drop")
    live = mat[6] != 0
    slot = jnp.clip(mat[7], 0, cbase.shape[0] - 1)
    cb = jnp.where(live, cbase[slot], 0)
    pb = jnp.where(live, pbase[slot], 0)
    client = (mat[0] + cb).astype(jnp.int32)
    clock = mat[1].astype(jnp.int64)
    pref = (mat[2] + pb).astype(jnp.int64)
    kid = mat[3].astype(jnp.int32)
    oc0 = mat[4]
    oc = jnp.where(oc0 >= 0, oc0 + cb, oc0).astype(jnp.int32)
    ock = mat[5].astype(jnp.int64)

    segkey = segkey_of(pref, kid.astype(jnp.int64))
    tpos = jnp.searchsorted(touched_sorted, segkey, method="sort")
    tpos_c = jnp.clip(tpos, 0, touched_sorted.shape[0] - 1)
    sel = live & (touched_sorted[tpos_c] == segkey)
    skey = jnp.where(sel, segkey, jnp.int64(2**63 - 1))
    order2 = jnp.argsort(skey, stable=True)
    sel_rows = order2[:sel_bucket].astype(jnp.int32)
    sub_valid = sel[sel_rows]
    out = _converge_core(
        client[sel_rows], clock[sel_rows], pref[sel_rows], kid[sel_rows],
        oc[sel_rows], ock[sel_rows], sub_valid,
        num_segments=num_segments, seq_bucket=seq_bucket, mode=mode,
        rank_rounds=rank_rounds, map_rounds=map_rounds,
    )
    packed_out = jnp.concatenate([
        out, jnp.where(sub_valid, sel_rows, NULLI).astype(jnp.int32)
    ])
    return mat, packed_out


@partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=("num_segments", "sel_bucket", "seq_bucket",
                     "mode", "rank_rounds", "map_rounds"),
)
def _pool_splice_select_converge(mat, delta8, pos, touched_sorted,
                                 cbase, pbase,
                                 num_segments: int, sel_bucket: int,
                                 seq_bucket: int, mode: str = "jnp",
                                 rank_rounds: Optional[int] = None,
                                 map_rounds: Optional[int] = None):
    """One warm dispatch for EVERY pooled doc's delta: scatter-splice
    the combined delta block into the pooled matrix (donated) at the
    docs' extent positions, compose doc-composite client / origin /
    parent ids from the per-slot bases, select the rows of the
    touched COMPOSITE segments, and re-converge that compact subset —
    the exact :func:`_splice_select_converge` contract lifted from
    one doc to the whole warm set. Returns the same
    ``(mat, [ out[S + 2B] | sel_rows[sel_bucket] ])`` shape; sel_rows
    are POOL positions (callers map back through their extents).

    ``touched_sorted`` must hold ascending composite segkeys
    (``sk_local + (pbase[slot] << _KID_BITS)``, int64-max padded) and
    ``cbase``/``pbase`` the per-slot id base offsets — disjoint
    ranges per doc, so every cross-doc comparison inside
    `_converge_core` is decided by the doc part of the key."""
    return _pool_splice_body(
        mat, delta8, pos, touched_sorted, cbase, pbase,
        num_segments, sel_bucket, seq_bucket, mode,
        rank_rounds, map_rounds,
    )


@partial(
    jax.jit,
    static_argnames=("num_segments", "sel_bucket", "seq_bucket",
                     "mode", "rank_rounds", "map_rounds"),
)
def _pool_splice_select_converge_nodonate(
        mat, delta8, pos, touched_sorted, cbase, pbase,
        num_segments: int, sel_bucket: int,
        seq_bucket: int, mode: str = "jnp",
        rank_rounds: Optional[int] = None,
        map_rounds: Optional[int] = None):
    """Undonated twin of :func:`_pool_splice_select_converge` for
    repeat-dispatch consumers (bench probes re-driving one staged
    pool, CPU hosts where donation only warns) — same contract, the
    input matrix stays valid after the call."""
    return _pool_splice_body(
        mat, delta8, pos, touched_sorted, cbase, pbase,
        num_segments, sel_bucket, seq_bucket, mode,
        rank_rounds, map_rounds,
    )


@partial(jax.jit, donate_argnums=(0,), static_argnames=("new_cap",))
def _pool_grow(mat, new_cap: int):
    """Capacity growth for the POOLED matrix (8 lanes: lane 7 holds
    pool slots, null = -1)."""
    big = jnp.zeros((8, new_cap), mat.dtype)
    big = big.at[3:6, :].set(-1)
    big = big.at[7, :].set(-1)
    return jax.lax.dynamic_update_slice(big, mat, (0, 0))


@partial(jax.jit, donate_argnums=(0,), static_argnames=("width",))
def _pool_kill(mat, off, width: int):
    """Kill a released extent's columns (valid + slot lanes) so an
    evicted doc's stale rows can never be selected — and a reused
    slot can never alias them onto another doc's composite ids. Runs
    lazily at the next flush (idempotent: killing twice is a no-op),
    or is subsumed by a compaction's gather dropping the range."""
    dead = jnp.zeros((1, width), mat.dtype)
    mat = jax.lax.dynamic_update_slice(
        mat, dead, (jnp.int32(6), off.astype(jnp.int32))
    )
    return jax.lax.dynamic_update_slice(
        mat, dead - 1, (jnp.int32(7), off.astype(jnp.int32))
    )


@partial(jax.jit, donate_argnums=(0,), static_argnames=("width",))
def _pool_move(mat, src_off, dst_off, width: int):
    """Relocate one doc's extent (pow2 outgrowth): copy the ``width``
    columns at ``src_off`` to ``dst_off``, then kill the old extent
    (valid + slot lanes) so stale copies can never be selected. The
    allocator guarantees the ranges never overlap (the destination is
    fresh tail space)."""
    blk = jax.lax.dynamic_slice(
        mat, (jnp.int32(0), src_off.astype(jnp.int32)), (8, width)
    )
    mat = jax.lax.dynamic_update_slice(
        mat, blk, (jnp.int32(0), dst_off.astype(jnp.int32))
    )
    dead = jnp.zeros((1, width), mat.dtype)
    mat = jax.lax.dynamic_update_slice(
        mat, dead, (jnp.int32(6), src_off.astype(jnp.int32))
    )
    return jax.lax.dynamic_update_slice(
        mat, dead - 1, (jnp.int32(7), src_off.astype(jnp.int32))
    )


@partial(jax.jit, donate_argnums=(0,))
def _pool_relabel_range(mat, perm, off, n):
    """Per-DOC client relabel after a mid-table insertion: rewrite
    dense ids through ``perm`` over the doc's extent columns
    ``[off, off+n)`` only — other docs' rows (their id spaces are
    doc-local) are untouched."""
    idx = jnp.arange(mat.shape[1])
    m = (idx >= off) & (idx < off + n)
    cl = mat[0]
    oc = mat[4]
    pc = perm[jnp.clip(cl, 0, perm.shape[0] - 1)].astype(mat.dtype)
    mat = mat.at[0, :].set(jnp.where(m, pc, cl))
    po = jnp.where(
        oc >= 0, perm[jnp.clip(oc, 0, perm.shape[0] - 1)], oc
    ).astype(mat.dtype)
    return mat.at[4, :].set(jnp.where(m, po, oc))


@partial(jax.jit, donate_argnums=(0,))
def _pool_compact(mat, src, keep):
    """Bounded pool compaction (eviction holes): one device gather
    through the host-computed ``src`` index array (new position ->
    old position); positions outside any live extent reset to the
    null pattern."""
    out = mat[:, src]
    fill = jnp.array([0, 0, 0, -1, -1, -1, 0, -1], mat.dtype)
    return jnp.where(keep[None, :], out, fill[:, None])


class PackedResult(NamedTuple):
    win_rows: np.ndarray     # [S] original row of each map winner (-1 none)
    stream_seg: np.ndarray   # [B] doc-order segment ids (-1 padding)
    stream_row: np.ndarray   # [B] doc-order original rows (-1 padding)
    hard_rows: tuple = ()    # rows marking segments needing the scalar
                             # fallback (right shapes the sibling-rank
                             # model cannot express)


def kernel_mode_for(*widths: int) -> str:
    """The static kernel-dispatch decision for a converge call
    (:func:`crdt_tpu.ops.pallas_kernels.converge_kernel_mode`), with
    the mode evidence counted at the same moment: one
    ``converge.pallas{mode}`` count per dispatch, plus a
    ``converge.pallas_fallback`` count when the Pallas kernels were
    requested but a block past the VMEM width guard forced the jnp
    oracle path. ONE helper for every dispatch site (packed plans,
    the incremental splice) so the evidence is uniform."""
    from crdt_tpu.ops.pallas_kernels import (
        converge_kernel_mode,
        use_pallas,
    )

    mode = converge_kernel_mode(*widths)
    tracer = get_tracer()
    if tracer.enabled:
        tracer.count("converge.pallas", labels={"mode": mode})
        if mode == "jnp" and use_pallas():
            tracer.count("converge.pallas_fallback")
    return mode


def _plan_args(plan: PackedPlan) -> dict:
    return dict(
        num_segments=plan.num_segments,
        seq_bucket=plan.seq_bucket,
        map_bucket=plan.map_bucket,
        rank_rounds=plan.rank_rounds,
        map_rounds=plan.map_rounds,
    )


def _put_mat(plan: PackedPlan):
    """A matrix plan's ONE upload through the xfer seam, with the
    per-section width/savings record made at the same moment — never
    at stage time, where a plan destined for the zero-link host route
    or a repeat-dispatch probe would leave phantom entries. The diet
    baseline is the PRE-diet (round-8) staging of the same union
    (five int32 columns at padded n), so the round-9 narrowing and
    the round-12 section re-cut both count as transfer savings."""
    record_staged_widths(
        dict(plan.staged_widths), plan.mat.nbytes,
        5 * bucket_grid(plan.n, floor=6) * 4,
    )
    return xfer_put(plan.mat, label="converge.mat")


def _assemble_result(plan: PackedPlan, h: np.ndarray) -> PackedResult:
    """The one fetch -> caller-space result (shared by the device and
    local-CPU executions of the identical kernel). The device returns
    block-local positions; the host maps them through the staged
    translation tables (``map_back``/``seq_back``) and rebuilds the
    per-segment stream boundaries from the host-known counts — no
    segment column ever crosses the link."""
    s = plan.num_segments
    b = plan.seq_bucket
    win = h[:s]
    if plan.win_src is not None:
        # map-chain split stitch (round 23): a split map segment's
        # true winner lives in the piece holding its max-root chain's
        # bottom; the first piece reads it from there and the other
        # pieces mute (their locally-converged winners are interior
        # chain nodes of the unsplit segment)
        src = plan.win_src
        win = np.where(src >= 0, win[np.clip(src, 0, s - 1)], -1)
    perm = h[s:s + b]
    counts = plan.seg_counts
    k = int(counts.sum())
    stream_seg = np.full(b, NULLI, np.int32)
    stream_seg[:k] = np.repeat(np.arange(s, dtype=np.int32), counts)
    mb = plan.map_back
    sb = plan.seq_back
    return PackedResult(
        win_rows=np.where(
            win >= 0, mb[np.clip(win, 0, len(mb) - 1)], NULLI
        ),
        stream_seg=stream_seg,
        stream_row=np.where(
            perm >= 0, sb[np.clip(perm, 0, len(sb) - 1)], NULLI
        ),
        hard_rows=plan.hard_rows,
    )


def converge_async(plan: PackedPlan):
    """ENQUEUE the fused convergence and return immediately — no
    blocking fetch. The returned handle is the streaming executor's
    overlap seam: while the dispatch is in flight the host stages,
    uploads, and dispatches the NEXT chunk (and materializes the
    previous one); :func:`converge_fetch` blocks only when the
    consumer actually needs the winners. ``jnp.asarray``/``device_put``
    and jitted calls are all asynchronous, so the only synchronization
    point in the whole (stage -> upload -> dispatch) chain is the
    fetch."""
    args = _plan_args(plan)
    mode = kernel_mode_for(plan.map_bucket, plan.seq_bucket)
    # span = enqueue cost (the dispatch is async); the XProf
    # annotation brackets the jitted call so device timelines
    # attribute the fused kernel to the converge phase. The staged
    # buffers are DONATED to the program (matrix upload through the
    # xfer seam, eager sections at stage time): one plan, one
    # dispatch.
    with get_tracer().span("converge.dispatch"), \
            device_annotation("crdt.converge.dispatch"), \
            enable_x64(True):
        if plan.dev:
            out = _converge_rows(*plan.dev, **args,
                                 encs=plan.encs, mode=mode)
        else:
            out = _converge_packed(
                _put_mat(plan), **args,
                encs=plan.encs, mode=mode,
            )
    return plan, out


def converge_fetch(handle) -> PackedResult:
    """Block on an in-flight :func:`converge_async` dispatch and
    assemble its one packed fetch into caller row space (the tracer's
    ``converge.fetch`` span: wait + transfer + assembly). The D2H
    transfer itself goes through :func:`crdt_tpu.ops.device.
    xfer_fetch` AFTER an explicit wait-for-execution, so the
    ``xfer.d2h`` histogram records pure transfer time (previously the
    wait was folded in and the fetch cost was unattributable)."""
    plan, out = handle
    with get_tracer().span("converge.fetch"), \
            device_annotation("crdt.converge.fetch"):
        jax.block_until_ready(out)  # execution wait, not transfer
        return _assemble_result(
            plan, xfer_fetch(out, label="converge.out")
        )


def converge(plan: PackedPlan,
             phases: Optional[dict] = None) -> PackedResult:
    """Stage -> single dispatch -> single fetch. Device outputs are in
    id-sorted row space; the plan's sort permutation maps them back to
    the caller's rows (one numpy gather, off the device clock). Plans
    staged with ``put=`` skip the transfer here — their rows are
    already (asynchronously) on device.

    ``phases``, when given, receives the span's sub-costs
    (``upload_wait``/``dispatch``/``fetch`` seconds) so published
    numbers itemize against the floor derivation (ROOFLINE.md) instead
    of reporting one opaque "converge"."""
    import time as _t

    if phases is None:
        # production shape: enqueue + one blocking fetch (the same
        # two-step seam the streaming executor drives directly)
        return converge_fetch(converge_async(plan))

    args = _plan_args(plan)
    mode = kernel_mode_for(plan.map_bucket, plan.seq_bucket)

    def mark(name, t0):
        phases[name] = round(_t.perf_counter() - t0, 4)

    # from here on phases is non-None: this is the INSTRUMENTED shape
    # only — its sync barriers exist to itemize upload/dispatch/fetch
    # against the floor derivation (ROOFLINE.md), and would serialize
    # the production path, which took the async early return above
    with enable_x64(True):
        if plan.dev:
            t0 = _t.perf_counter()
            jax.block_until_ready(plan.dev)  # eager uploads land
            mark("upload_wait", t0)
            t0 = _t.perf_counter()
            out = _converge_rows(*plan.dev, **args,          # 1 dispatch
                                 encs=plan.encs, mode=mode)
            jax.block_until_ready(out)
            mark("dispatch", t0)
        else:
            t0 = _t.perf_counter()
            dev_mat = _put_mat(plan)
            jax.block_until_ready(dev_mat)                   # 1 transfer
            mark("upload_wait", t0)
            t0 = _t.perf_counter()
            out = _converge_packed(dev_mat, **args,          # 1 dispatch
                                   encs=plan.encs, mode=mode)
            jax.block_until_ready(out)
            mark("dispatch", t0)
        # the fetch is attributed to its OWN phase (and the xfer.d2h
        # histogram), never folded into dispatch: the dispatch mark
        # above waits for EXECUTION, this times the D2H transfer +
        # nothing else, so converge_detail.fetch matches xfer.d2h_bytes
        t0 = _t.perf_counter()
        h = xfer_fetch(out, label="converge.out")            # 1 fetch
        mark("fetch", t0)
        phases["d2h_bytes"] = int(h.nbytes)
        if plan.mat is not None:
            phases["h2d_bytes"] = int(plan.mat.nbytes)
    # mirror the async seam's tracer spans so instrumented runs (the
    # bench's per-phase detail path) still feed the same histograms
    tracer = get_tracer()
    if tracer.enabled:
        tracer.observe("converge.dispatch", phases["dispatch"])
        tracer.observe("converge.fetch", phases["fetch"])
    return _assemble_result(plan, h)


def converge_host(plan: PackedPlan) -> PackedResult:
    """The IDENTICAL fused convergence executed on the process's
    local CPU backend: zero tunnel interactions, byte-identical
    outputs (differential-tested). This is the engine under the
    host side of the product crossover — on a tunnelled platform a
    sub-threshold union pays ~3 fixed interaction latencies to reach
    the accelerator, while the same XLA program on the local backend
    ran a 20k-row text union in ~30ms.

    Requires a matrix-staged plan (``stage(put=None)``); eagerly
    shipped plans already live on the accelerator — converge them
    there. Compilation-cache handling (suppression of XLA:CPU AOT
    artifacts from TPU processes) lives in
    :func:`crdt_tpu.ops.device.on_local_cpu`."""
    if plan.dev:
        raise ValueError(
            "converge_host needs a matrix-staged plan (stage(put=None))"
        )
    from crdt_tpu.ops.device import on_local_cpu

    args = _plan_args(plan)
    mode = kernel_mode_for(plan.map_bucket, plan.seq_bucket)
    key = ("converge_host", plan.mat.shape, plan.encs, mode,
           tuple(sorted(args.items())))
    with get_tracer().span("converge.dispatch"), \
            on_local_cpu(cache_key=key), enable_x64(True):
        # NO xfer seam here: the whole point of this path is zero
        # bytes on the tunnel link (local CPU backend) — and the
        # UNDONATED entry, since CPU can never honor donation and the
        # donating twin would warn into library consumers' stderr
        h = np.asarray(
            _converge_packed_nodonate(jnp.asarray(plan.mat), **args,
                                      encs=plan.encs, mode=mode)
        )
    with get_tracer().span("converge.fetch"):
        return _assemble_result(plan, h)
