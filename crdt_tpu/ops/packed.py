"""Packed one-dispatch trace-replay convergence.

The firehose replay (BASELINE config #5; ``crdt_tpu.models.replay``)
is a COLD start: decode a trace, converge once, materialize. On a
tunnelled single-chip platform every host<->device interaction pays a
fixed round-trip (measured ~25ms) and bulk transfer runs ~60MB/s, so
the general :class:`~crdt_tpu.ops.resident.ResidentColumns` path —
9 buffer allocations + 9 column uploads + dispatch — spends most of
its wall clock on transport, not merging. This module collapses the
whole cold replay to exactly three device interactions:

  1. ONE host->device transfer: all op columns packed into a single
     int32 (or int64 when clocks are wide) matrix;
  2. ONE dispatch: unpack -> shared id-sort/dedup/origin resolution ->
     map winners (:func:`crdt_tpu.ops.lww.map_winners`) + sequence DFS
     ranks over a compact sequence-rows-only prefix (the shared
     :func:`crdt_tpu.ops.device.dfs_ranks` machinery the general YATA
     kernel also uses) — plus document-order assembly, all fused;
  3. ONE device->host transfer: a single packed int32 result (winner
     rows + per-sequence document-order streams).

Segment ids for maps and sequences come from ONE argsort of a single
composite key (is_map | parent_ref | key_id) — parent specs are
interned to dense ids on the host, which already walks the columns
once to build them. Clients are interned to dense ORDER-PRESERVING
ranks (the sibling rules compare client ids, so the map must be
monotone — same rationale as ``ResidentColumns``).

Reference hot loop being replaced: crdt.js:294 (``Y.applyUpdate`` per
update); here the whole union is one applyUpdate, as the north star
prescribes.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from crdt_tpu.ops.device import (
    NULLI,
    bucket_pow2,
    dense_ranks_sorted,
    dfs_ranks,
    lexsort,
    pack_id,
    run_edge_lookup,
    scatter_perm,
    searchsorted_ids,
)

# host-side packing limits for the composite segment key:
# (is_map:1 | pref:25 bits | kid:21 bits) must fit non-negative int64
_PREF_BITS = 25
_KID_BITS = 21


class PackedPlan(NamedTuple):
    """Host-side staging result: one matrix + static metadata."""

    mat: np.ndarray           # [7, kpad] i32 (narrow) or i64 (wide)
    n: int                    # real rows (rest is padding)
    num_segments: int         # pow2 bucket over distinct segments
    seq_bucket: int           # pow2 bucket over sequence-row count
    clients: np.ndarray       # sorted raw client ids (dense rank = index)


def stage(cols: Dict[str, np.ndarray]) -> Optional[PackedPlan]:
    """Pack kernel columns into the single-transfer matrix.

    Returns None when the batch exceeds the packed path's key bounds
    (callers fall back to the general kernels): >=2^25 distinct
    parents or >=2^21 distinct map keys.
    """
    client = np.asarray(cols["client"], np.int64)
    clock = np.asarray(cols["clock"], np.int64)
    pir = np.asarray(cols["parent_is_root"], bool)
    pa = np.asarray(cols["parent_a"], np.int64)
    pb = np.asarray(cols["parent_b"], np.int64)
    kid = np.asarray(cols["key_id"], np.int64)
    oc = np.asarray(cols["origin_client"], np.int64)
    ock = np.asarray(cols["origin_clock"], np.int64)
    valid = np.asarray(cols["valid"], bool)
    n = len(client)
    if n == 0 or not valid.any():
        return None

    # dense order-preserving client ranks (origins share the table)
    uniq = np.unique(np.concatenate([client[valid], oc[oc >= 0]]))
    client_d = np.searchsorted(uniq, np.clip(client, uniq[0], None))
    client_d = np.where(valid, client_d, 0)
    oc_d = np.where(oc >= 0, np.searchsorted(uniq, np.clip(oc, uniq[0], None)), -1)

    # dense parent refs: exact two-key unique via lexsort runs
    porder = np.lexsort((pb, pa, pir))
    pir_s, pa_s, pb_s = pir[porder], pa[porder], pb[porder]
    new_run = np.r_[
        True,
        (pir_s[1:] != pir_s[:-1])
        | (pa_s[1:] != pa_s[:-1])
        | (pb_s[1:] != pb_s[:-1]),
    ]
    ref_sorted = np.cumsum(new_run) - 1
    pref = np.empty(n, np.int64)
    pref[porder] = ref_sorted
    n_parents = int(ref_sorted[-1]) + 1

    kid_max = int(kid.max())
    if n_parents >= (1 << _PREF_BITS) or kid_max >= (1 << _KID_BITS):
        return None

    # distinct segments: map rows by (pref, kid), seq rows by pref
    n_segs = len(np.unique(segkey_of(pref, kid)[valid]))
    n_seq = int((valid & (kid < 0)).sum())

    narrow = clock.max() < (1 << 31) and ock.max() < (1 << 31)
    dt = np.int32 if narrow else np.int64
    kpad = bucket_pow2(n, floor=6)
    mat = np.zeros((7, kpad), dt)
    mat[0, :n] = client_d
    mat[1, :n] = clock
    mat[2, :n] = pref
    mat[3, :n] = kid
    mat[4, :n] = oc_d
    mat[5, :n] = ock
    mat[6, :n] = valid
    mat[3, n:] = -1  # padding rows: invalid, non-map, null origins
    mat[4, n:] = -1
    mat[5, n:] = -1
    return PackedPlan(
        mat=mat,
        n=n,
        num_segments=bucket_pow2(n_segs),
        seq_bucket=min(kpad, bucket_pow2(max(n_seq, 1), floor=6)),
        clients=uniq,
    )


@partial(jax.jit, static_argnames=("num_segments", "seq_bucket"))
def _converge_packed(mat, num_segments: int, seq_bucket: int):
    """The single fused dispatch. Returns one packed int32 array:

      [ win_rows[S] | stream_seg[B] | stream_row[B] ]

    - win_rows: original row index of each map segment's winner (-1
      for non-map / empty segments);
    - stream_seg/stream_row: sequence rows in document order, grouped
      by segment id (B = seq_bucket; -1 padding at the tail).
    """
    client = mat[0].astype(jnp.int32)
    clock = mat[1].astype(jnp.int64)
    pref = mat[2].astype(jnp.int64)
    kid = mat[3].astype(jnp.int32)
    oc = mat[4].astype(jnp.int32)
    ock = mat[5].astype(jnp.int64)
    valid = mat[6] != 0
    return _converge_core(
        client, clock, pref, kid, oc, ock, valid,
        num_segments=num_segments, seq_bucket=seq_bucket,
    )


def _converge_core(client, clock, pref, kid, oc, ock, valid, *,
                   num_segments: int, seq_bucket: int):
    """Traced body shared by the cold single-dispatch replay and the
    incremental touched-segment path (``crdt_tpu.models.incremental``).
    Row indices in the output refer to the CALLER's row space."""
    from crdt_tpu.ops.lww import map_winners

    n = client.shape[0]

    # shared id-sort + dedup + origin resolution (one for both kernels)
    ikey = jnp.where(valid, pack_id(client, clock), jnp.int64(2**62))
    order = jnp.argsort(ikey, stable=True)
    ikey = ikey[order]
    client = client[order]
    clock = clock[order]
    pref = pref[order]
    kid = kid[order]
    oc = oc[order]
    ock = ock[order]
    valid = valid[order]
    dup = jnp.concatenate([jnp.zeros(1, bool), ikey[1:] == ikey[:-1]])
    uniq_valid = valid & ~dup
    okey = pack_id(oc, ock)
    origin_idx = searchsorted_ids(ikey, okey)

    is_map = uniq_valid & (kid >= 0)
    is_seq = uniq_valid & (kid < 0)

    # one composite segment key covers maps AND sequences (dup rows of
    # a map item are ~uniq_valid, so the unmasked kid flag is moot for
    # them — the invalid-row sentinel overrides either way)
    segkey = jnp.where(
        uniq_valid,
        segkey_of(pref, kid.astype(jnp.int64)),
        jnp.int64(2**63 - 1),
    )
    sorder = jnp.argsort(segkey, stable=True)
    seg_sorted = dense_ranks_sorted(segkey[sorder])
    seg = scatter_perm(sorder, seg_sorted)
    seg_map = jnp.where(is_map, seg, NULLI)
    seg_seq = jnp.where(is_seq, seg, NULLI)

    winners = map_winners(
        seg_map, client, clock, origin_idx, is_map, num_segments
    )
    win_rows = jnp.where(
        winners >= 0, order[jnp.clip(winners, 0, n - 1)], NULLI
    ).astype(jnp.int32)

    # ---- sequence ranking in COMPACT space ---------------------------
    # Sequence segkeys sort below map segkeys (bit 62) and invalid rows
    # (max), so sorder's prefix holds exactly the sequence rows and the
    # static seq bucket B >= n_seq covers them. All sibling/climb/rank
    # machinery runs at size B (+S roots) instead of the full padded n.
    B = seq_bucket
    mB = B + num_segments
    sub = sorder[:B]
    c_ok = is_seq[sub]
    c_seg = jnp.where(c_ok, seg[sub], NULLI)
    # full-space row -> sorder position (compact index for seq rows)
    inv_sorder = jnp.argsort(sorder, stable=True).astype(jnp.int32)
    o = origin_idx[sub]
    o_ok = c_ok & (o >= 0)
    o_seg = jnp.where(o_ok, seg[jnp.clip(o, 0, n - 1)], NULLI)
    same_seg = o_ok & (o_seg == c_seg)
    c_parent = jnp.where(
        same_seg, inv_sorder[jnp.clip(o, 0, n - 1)], NULLI
    ).astype(jnp.int32)

    parent = jnp.where(
        c_ok & (c_parent >= 0), c_parent, B + jnp.maximum(c_seg, 0)
    )
    parent = jnp.where(c_ok, parent, mB).astype(jnp.int32)

    # sibling order by (parent, client asc, clock DESC). Within one
    # client, clock order == id-sorted position order, so the global
    # row index (already an id-rank here) stands in for the clock —
    # making the whole key fit one int64 when the static widths allow.
    c_client = client[sub]
    pos_desc = (n - 1) - sub  # descending position == descending clock
    pbits = int(mB).bit_length()
    qbits = int(max(n - 1, 1)).bit_length()
    if pbits + 22 + qbits <= 63:
        sibkey = (
            (parent.astype(jnp.int64) << (22 + qbits))
            | (c_client.astype(jnp.int64) << qbits)
            | pos_desc.astype(jnp.int64)
        )
        sord2 = jnp.argsort(sibkey, stable=True)
    else:
        sord2 = lexsort([
            parent.astype(jnp.int64),
            (c_client.astype(jnp.int64) << qbits)
            | pos_desc.astype(jnp.int64),
        ])
    p_s = parent[sord2]
    same_group = jnp.concatenate([p_s[1:] == p_s[:-1], jnp.zeros(1, bool)])
    nxt_sorted = jnp.where(
        same_group, jnp.roll(sord2, -1), NULLI
    ).astype(jnp.int32)
    next_sib = scatter_perm(sord2, nxt_sorted)
    first_pos, _ = run_edge_lookup(p_s, mB, side="left")
    first_child = jnp.where(
        first_pos >= 0, sord2[jnp.clip(first_pos, 0, B - 1)], NULLI
    ).astype(jnp.int32)

    # climb + DFS-successor + Wyllie ranking via the shared helper, at
    # compact size (B items + S virtual roots instead of n + S)
    dist_to_end = dfs_ranks(parent, next_sib, first_child, c_ok,
                            num_segments)
    root_dist = dist_to_end[B + jnp.maximum(c_seg, 0)]
    c_rank = jnp.where(c_ok, root_dist - dist_to_end[:B] - 1, NULLI)

    # document-order stream: compact rows sorted by (segment, rank)
    skey2 = jnp.where(
        c_ok & (c_rank >= 0),
        (c_seg.astype(jnp.int64) << qbits) | c_rank.astype(jnp.int64),
        jnp.int64(2**62),
    )
    dorder = jnp.argsort(skey2, stable=True)
    d_ok = (c_ok & (c_rank >= 0))[dorder]
    stream_seg = jnp.where(d_ok, c_seg[dorder], NULLI).astype(jnp.int32)
    stream_row = jnp.where(
        d_ok, order[sub[dorder]], NULLI
    ).astype(jnp.int32)

    return jnp.concatenate([win_rows, stream_seg, stream_row])


def segkey_of(pref, kid):
    """The composite segment key, shared by staging, the fused kernel,
    and the incremental host bookkeeping. Works on numpy or jnp
    (dtype-explicit: the map-flag bit 62 must not fall into a narrow
    weak-typed promotion)."""
    is_map = (kid >= 0).astype(np.int64)
    base = (pref << _KID_BITS) | (is_map * kid)
    return base | (is_map << np.int64(62))


@partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=("num_segments", "sel_bucket", "seq_bucket"),
)
def _splice_select_converge(mat, delta, n_off, touched_sorted,
                            num_segments: int, sel_bucket: int,
                            seq_bucket: int):
    """Incremental warm dispatch: splice a packed delta into the
    resident matrix (donated), select the rows of the TOUCHED segments
    (touched_sorted: ascending segkeys, padded with int64 max), and
    re-converge only that compact subset. Returns

      (resident_mat, out[S + 2B] int32, sel_rows[sel_bucket] int32)

    where out's row indices are LOCAL to sel_rows; callers map back
    with sel_rows (resident row ids, -1 padding)."""
    mat = jax.lax.dynamic_update_slice(
        mat, delta.astype(mat.dtype), (jnp.int32(0), n_off.astype(jnp.int32))
    )
    client = mat[0].astype(jnp.int32)
    clock = mat[1].astype(jnp.int64)
    pref = mat[2].astype(jnp.int64)
    kid = mat[3].astype(jnp.int32)
    oc = mat[4].astype(jnp.int32)
    ock = mat[5].astype(jnp.int64)
    valid = mat[6] != 0

    segkey = segkey_of(pref, kid.astype(jnp.int64))
    pos = jnp.searchsorted(touched_sorted, segkey, method="sort")
    pos_c = jnp.clip(pos, 0, touched_sorted.shape[0] - 1)
    sel = valid & (touched_sorted[pos_c] == segkey)
    skey = jnp.where(sel, segkey, jnp.int64(2**63 - 1))
    order2 = jnp.argsort(skey, stable=True)
    sel_rows = order2[:sel_bucket].astype(jnp.int32)
    sub_valid = sel[sel_rows]
    out = _converge_core(
        client[sel_rows], clock[sel_rows], pref[sel_rows], kid[sel_rows],
        oc[sel_rows], ock[sel_rows], sub_valid,
        num_segments=num_segments, seq_bucket=seq_bucket,
    )
    return mat, out, jnp.where(sub_valid, sel_rows, NULLI)


@partial(jax.jit, donate_argnums=(0,))
def _splice_mat(mat, delta, n_off):
    """Delta splice without convergence (delete-only / host-only
    rounds still need the rows resident for later dispatches)."""
    return jax.lax.dynamic_update_slice(
        mat, delta.astype(mat.dtype), (jnp.int32(0), n_off.astype(jnp.int32))
    )


@partial(jax.jit, donate_argnums=(0,), static_argnames=("new_cap",))
def _grow_mat(mat, new_cap: int):
    """Capacity growth for the resident matrix, on device."""
    big = jnp.zeros((7, new_cap), mat.dtype)
    big = big.at[3:6, :].set(-1)  # key_id / origin columns: null
    return jax.lax.dynamic_update_slice(big, mat, (0, 0))


@partial(jax.jit, donate_argnums=(0,))
def _relabel_mat(mat, perm):
    """Rewrite dense client ids through an old->new permutation after
    a mid-table client insertion (order-preserving interning)."""
    cl = mat[0]
    oc = mat[4]
    mat = mat.at[0, :].set(perm[jnp.clip(cl, 0, perm.shape[0] - 1)]
                           .astype(mat.dtype))
    new_oc = jnp.where(
        oc >= 0, perm[jnp.clip(oc, 0, perm.shape[0] - 1)], oc
    )
    return mat.at[4, :].set(new_oc.astype(mat.dtype))


class PackedResult(NamedTuple):
    win_rows: np.ndarray     # [S] original row of each map winner (-1 none)
    stream_seg: np.ndarray   # [B] doc-order segment ids (-1 padding)
    stream_row: np.ndarray   # [B] doc-order original rows (-1 padding)


def converge(plan: PackedPlan) -> PackedResult:
    """Stage -> single dispatch -> single fetch."""
    with jax.enable_x64(True):
        dev_mat = jnp.asarray(plan.mat)                      # 1 transfer
        out = _converge_packed(
            dev_mat,
            num_segments=plan.num_segments,
            seq_bucket=plan.seq_bucket,
        )                                                    # 1 dispatch
        h = np.asarray(out)                                  # 1 fetch
    s = plan.num_segments
    b = plan.seq_bucket
    return PackedResult(
        win_rows=h[:s],
        stream_seg=h[s:s + b],
        stream_row=h[s + b:s + 2 * b],
    )
