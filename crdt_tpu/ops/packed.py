"""Packed one-dispatch trace-replay convergence.

The firehose replay (BASELINE config #5; ``crdt_tpu.models.replay``)
is a COLD start: decode a trace, converge once, materialize. On a
tunnelled single-chip platform every host<->device interaction pays a
fixed round-trip (measured ~25ms) and bulk transfer runs ~60MB/s, so
the general :class:`~crdt_tpu.ops.resident.ResidentColumns` path —
9 buffer allocations + 9 column uploads + dispatch — spends most of
its wall clock on transport, not merging. This module collapses the
whole cold replay to exactly three device interactions:

  1. ONE host->device transfer: all op columns packed into a single
     int32 (or int64 when clocks are wide) matrix;
  2. ONE dispatch: unpack -> shared id-sort/dedup/origin resolution ->
     map winners (:func:`crdt_tpu.ops.lww.map_winners`) + sequence DFS
     ranks over a compact sequence-rows-only prefix (the shared
     :func:`crdt_tpu.ops.device.dfs_ranks` machinery the general YATA
     kernel also uses) — plus document-order assembly, all fused;
  3. ONE device->host transfer: a single packed int32 result (winner
     rows + per-sequence document-order streams).

Segment ids for maps and sequences come from ONE argsort of a single
composite key (is_map | parent_ref | key_id) — parent specs are
interned to dense ids on the host, which already walks the columns
once to build them. Clients are interned to dense ORDER-PRESERVING
ranks (the sibling rules compare client ids, so the map must be
monotone — same rationale as ``ResidentColumns``).

Reference hot loop being replaced: crdt.js:294 (``Y.applyUpdate`` per
update); here the whole union is one applyUpdate, as the north star
prescribes.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, NamedTuple, Optional

import jax

from crdt_tpu.compat import enable_x64
import jax.numpy as jnp
import numpy as np

from crdt_tpu.ops.device import (
    NULLI,
    bucket_grid,
    dense_ranks_sorted,
    dfs_ranks,
    lexsort,
    pack_id,
    record_staged_widths,
    run_edge_lookup,
    scatter_perm,
    searchsorted_ids,
    wide_staging_forced,
    xfer_fetch,
    xfer_put,
)
from crdt_tpu.ops.lww import map_winners
from crdt_tpu.obs.profiling import device_annotation
from crdt_tpu.obs.tracer import get_tracer

# host-side packing limits for the composite segment key:
# (is_map:1 | pref:25 bits | kid:21 bits) must fit non-negative int64
_PREF_BITS = 25
_KID_BITS = 21


from crdt_tpu.ops.device import _CLOCK_BITS  # pack_id's clock width

_SEQ_FLAG = 1 << 30          # bit in the seg column marking sequence rows

# floor of _stage_rights' per-SEGMENT origin-chain walk budget (the
# real budget is linear in the segment's row count): exhaustion marks
# the segment hard (exact scalar fallback) instead of letting hostile
# updates buy O(n^2) staging time, while benign long chains — whose
# total walk work stays linear-ish in segment size — keep the staged
# device path
_RIGHT_WALK_CAP = 1024

# row count above which eager per-row device shipping (stage(put=...))
# beats one matrix put: below it the extra per-put fixed latencies
# outweigh any staging/transfer overlap. One constant so the bench
# and the product replay always measure the same pipeline shape.
EAGER_PUT_MIN_ROWS = 1 << 19


# ---------------------------------------------------------------------------
# narrow-column staging: the transfer diet (round 9)
#
# The staged upload is pure LAYOUT data — dense ranks, segment numbers,
# row references — whose values are tiny compared to their int32 slots
# for every real workload (the headline 100k-op trace tops out at ~12k
# segments and 1k clients). Each row gets a frame-of-reference/delta
# encoding into int16, HALVING bytes-on-link, with a fused widening
# prelude inside the one-dispatch converge program that reconstructs
# the exact int32 values — kernel semantics and outputs stay
# byte-identical (differential-tested in tests/test_transfer_diet.py).
# A row whose values do not fit falls back automatically: the matrix
# path keeps the int16 dtype and ships that column as two exact hi/lo
# rows (see below), the eager path ships that array wide int32.
# CRDT_TPU_WIDE_STAGING=1 forces wide everywhere (README "Transfer
# diet").
#
# Encodings (host encoder and device decoder kept adjacent; each pair
# must be an exact inverse):
#   client     : identity (values are dense ranks / group ranks >= 0)
#   seg        : map seg -> seg; seq seg -> -(seg+2); dead -> -1
#                (the _SEQ_FLAG bit folded into the sign)
#   origin     : -1 -> 0; else (row_index - origin_row), biased to the
#                chain-local distance (same-client chains sit adjacent
#                in id-sorted order)
#   seq rows   : strictly-ascending prefix delta-coded (w0 = s0 + 1,
#                wj = sj - s(j-1), all >= 1); padding -> 0
#   seq parent : -1 -> 0; else (compact_index - parent_index)
#
# A matrix column whose range does NOT fit one int16 row ships as TWO
# int16 hi/lo rows instead (any int32 splits exactly), so one
# overflowing column — e.g. the segment row past 32k segments on the
# scale run's stream shards — costs 6/10 of the wide bytes instead of
# collapsing the whole upload back to int32.
# ---------------------------------------------------------------------------

_I16_MIN = -(1 << 15)
_I16_MAX = (1 << 15) - 1


def _narrow_client(r0: np.ndarray):
    """int16 client-rank row, or None when a rank overflows."""
    if len(r0) and int(r0.max()) > _I16_MAX:
        return None
    return r0.astype(np.int16)


def _narrow_seg(r1: np.ndarray, n_segs: int):
    """int16 segment row with the seq flag folded into the sign, or
    None when the segment count overflows the narrow space."""
    if n_segs > _I16_MAX:
        return None
    seq = (r1 >= 0) & ((r1 & _SEQ_FLAG) != 0)
    seg = r1 & (_SEQ_FLAG - 1)
    out = np.where(r1 < 0, -1, np.where(seq, -(seg + 2), seg))
    return out.astype(np.int16)


def _narrow_delta_ref(vals: np.ndarray):
    """int16 (index - reference) encoding of a row-reference column
    (-1 = no reference -> 0), or None when a delta overflows int16 or
    collides with the no-reference sentinel (a self-referential row —
    hostile input — forces the wide layout, never a wrong decode)."""
    idx = np.arange(len(vals), dtype=np.int64)
    live = vals >= 0
    d = np.where(live, idx - vals, 0)
    if live.any():
        bad = live & ((d == 0) | (d < _I16_MIN) | (d > _I16_MAX))
        if bad.any():
            return None
    return d.astype(np.int16)


def _narrow_ascending(rows: np.ndarray):
    """int16 delta code of a strictly-ascending valid PREFIX (-1
    padding tail), or None when a gap overflows int16."""
    w = np.zeros(len(rows), np.int64)
    m = rows >= 0
    if m.any():
        pref = rows[m]
        w[: len(pref)] = np.diff(pref, prepend=-1)
    if len(w) and int(w.max()) > _I16_MAX:
        return None
    return w.astype(np.int16)


def _split_hi_lo(row: np.ndarray):
    """Any int32 row as TWO exact int16 rows: hi = arithmetic >> 16,
    lo = low 16 bits biased into int16 range. Always feasible — the
    matrix path's escape for a column whose values overflow one
    narrow row."""
    v = row.astype(np.int32)
    hi = (v >> 16).astype(np.int16)
    lo = ((v & 0xFFFF) - 0x8000).astype(np.int16)
    return hi, lo


def _join_hi_lo(hi, lo):
    """Device inverse of :func:`_split_hi_lo`."""
    return (
        (hi.astype(jnp.int32) << 16)
        | ((lo.astype(jnp.int32) + 0x8000) & 0xFFFF)
    )


def _widen_client(v):
    return v.astype(jnp.int32)


def _widen_seg(v):
    v = v.astype(jnp.int32)
    return jnp.where(
        v >= 0, v, jnp.where(v == NULLI, NULLI, (-v - 2) | _SEQ_FLAG)
    )


def _widen_delta_ref(v):
    v = v.astype(jnp.int32)
    idx = jnp.arange(v.shape[0], dtype=jnp.int32)
    return jnp.where(v == 0, NULLI, idx - v)


def _widen_ascending(v):
    v = v.astype(jnp.int32)
    c = jnp.cumsum(v)
    return jnp.where(v > 0, c - 1, NULLI)


class PackedPlan(NamedTuple):
    """Host-side staging result: one matrix + static metadata.

    Staging does the layout work a tuned columnar store would do
    anyway — id radix sort, dedup, origin resolution, dense segment
    numbering — and ships its OUTPUT: the device dispatch starts at
    the combinatorial core (sibling sort, tree tables, pointer-doubled
    ranking) instead of re-deriving layout with device-width sorts.
    Measured on v5e (tools/profile_kernel.py), the id sort + origin
    searchsorted + segment sort cost ~14ms of the fused dispatch at
    100k rows; as numpy radix passes at staging they cost ~6ms of host
    time and drop the matrix from 7 to 5 rows (one int32 transfer).
    """

    mat: Optional[np.ndarray]  # [5, kpad], rows in id-sorted order:
                              #   0: dense client rank
                              #   1: dense segment id | _SEQ_FLAG (-1 dead)
                              #   2: origin row (map rows; -1 root)
                              #   3: compact block - seq row ids (-1 pad)
                              #   4: compact block - compact parent (-1 root)
                              # int32 wide, or int16 narrow-encoded
                              # (``narrow`` below; the fused widening
                              # prelude reconstructs the wide values on
                              # device). None when rows were shipped
                              # eagerly via ``stage(put=...)`` — ``dev``
    n: int                    # real rows (rest is padding)
    num_segments: int         # size bucket over distinct segments
    seq_bucket: int           # size bucket over sequence-row count
    order: np.ndarray         # id-sort permutation: mat row i = caller
                              # row order[i] (maps device output back)
    clients: np.ndarray       # sorted raw client ids (dense rank = index)
    client_bits: int          # dense client rank width (static)
    rank_rounds: int          # doubling rounds bound (seq DFS)
    map_rounds: int           # doubling rounds bound (map chains)
    hard_rows: tuple = ()     # caller-space rows marking segments the
                              # scalar fallback must re-order (gather)
    dev: tuple = ()           # device refs (r0, r1, r2, r34) when rows
                              # were shipped eagerly during staging:
                              # r0/r1/r2 are [kpad], r34 is [2, B] (the
                              # compact sequence block never needs the
                              # full row width on the wire)
    staged_widths: tuple = () # ((col, bits), ...) chosen per column —
                              # recorded into the xfer registry at the
                              # plan's actual UPLOAD (matrix path), so
                              # plans that never cross the link (host
                              # route, repeat-dispatch probes) leave
                              # no phantom width/savings entries
    narrow: bool = False      # matrix path: mat is the int16 layout
    narrow_cols: tuple = ()   # matrix path row map (one bool per
                              # column): True = one delta-encoded row,
                              # False = two exact hi/lo rows — static
                              # dispatch arg
    dev_narrow: tuple = (False, False, False, False)
                              # eager path: per-array narrow flags for
                              # (r0, r1, r2, r34) — static dispatch args


def _even_up(x: int) -> int:
    """Round a doubling-rounds bound up to even: halves the static
    variants the jit cache sees at a cost of at most one extra round."""
    return x + (x & 1)


def _stage_rights(cols, order, ikey_s, uniq, seg, origin_row, oc_s,
                  seq_rows, uniq_valid, kid_s, client_s, client_raw_s,
                  clock_raw_s):
    """Exact right-origin (attachment) ordering, computed at staging
    in column space — the device kernel needs NO change: a simulated
    group's conflict-scan ranks are written over its members' entries
    in the client column, and since ranks are unique within a group
    the kernel's (client, position) tie-break never fires.

    Semantics match ops.yata.order_sequences exactly. A segment is
    HARD — routed to the scalar fallback at gather via the returned
    representative rows — when any member's declared origin is
    unresolved (orphan subtrees take the fallback's dropping rules),
    or any member's right is dangling/unknown, cross-segment, or
    inside another member's subtree (right_walk_is_hard). Groups with
    in-group anchors replay the Yjs conflict scan (_simulate_group);
    attachment-free groups keep the plain (client, clock-desc) key.

    Returns (client column, caller-space hard rows, max rank written).
    """
    from crdt_tpu.ops.yata import _simulate_group

    n = len(client_s)
    rr = np.asarray(cols["right_client"], np.int64)[order]
    rk = np.asarray(cols["right_clock"], np.int64)[order]
    rows_r = np.flatnonzero(uniq_valid & (kid_s < 0) & (rr >= 0))
    if not len(rows_r):
        return client_s, [], 0

    # resolve right-target rows through the dense id table (leftmost
    # match is the kept duplicate representative, like origins)
    posu = np.clip(
        np.searchsorted(uniq, np.clip(rr, uniq[0], None)), 0, len(uniq) - 1
    )
    known_c = (
        (rr >= 0) & (uniq[posu] == rr)
        & (rk >= 0) & (rk < (1 << _CLOCK_BITS))
    )
    rkey = np.where(known_c, (posu << _CLOCK_BITS) | rk, np.int64(-1))
    pos = np.clip(np.searchsorted(ikey_s, rkey), 0, n - 1)
    right_row = np.where((rkey >= 0) & (ikey_s[pos] == rkey), pos, -1)

    # segment -> member rows (one stable sort over the seq rows)
    seg_of_seq = seg[seq_rows]
    so = np.argsort(seg_of_seq, kind="stable")
    ss, sr = seg_of_seq[so], seq_rows[so]
    seg_cuts = np.r_[0, np.flatnonzero(ss[1:] != ss[:-1]) + 1, len(ss)]
    seg_slices = {
        int(ss[a]): sr[a:b] for a, b in zip(seg_cuts[:-1], seg_cuts[1:])
    }

    hard_reps: list = []
    max_rank = 0
    # accumulated conflict-scan ranks, written with ONE bulk
    # searchsorted at the end (a per-sid binary search dominated text
    # staging time — profiled round 4)
    rank_sids: list = []
    rank_vals: list = []
    for S in np.unique(seg[rows_r]).tolist():
        members = seg_slices.get(int(S))
        if members is None:
            continue
        # orphan member (declared origin that resolved nowhere):
        # vectorized — member loops in python made staging the text
        # replay's dominant cost
        if bool(np.any((oc_s[members] >= 0) & (origin_row[members] < 0))):
            hard_reps.append(int(order[int(members[0])]))
            continue
        # groups within the segment, keyed by in-union origin row:
        # one stable sort + run split instead of a python setdefault
        # walk over every member
        og = origin_row[members]
        gorder = np.argsort(og, kind="stable")
        og_s, mem_s = og[gorder], members[gorder]
        gcuts = np.r_[
            0, np.flatnonzero(og_s[1:] != og_s[:-1]) + 1, len(og_s)
        ]
        hard = False
        # shared walk budget for ALL of this segment's out-of-group
        # right walks: linear in segment size (hostile staging cost
        # stays O(n) total — advisor finding, round 3), generous for
        # benign shapes; exhaustion marks the segment hard, which the
        # exact scalar fallback absorbs
        walk_budget = max(_RIGHT_WALK_CAP, 8 * len(members))
        seg_rank_sids: list = []
        seg_rank_vals: list = []
        seg_max_rank = 0
        for a, b in zip(gcuts[:-1], gcuts[1:]):
            grows = mem_s[a:b]
            # only right-bearing members need the per-row checks
            gr = grows[rr[grows] >= 0]
            if not len(gr):
                continue
            grow_set = set(grows.tolist())
            has_anchor = False
            # one fused python pass (groups are tiny — typically the
            # few writers racing one position — so per-group numpy
            # reductions cost more than they save)
            for rt in right_row[gr].tolist():
                if rt < 0 or seg[rt] != S:
                    hard = True  # dangling/unknown or cross-parent
                    break
                if rt in grow_set:
                    has_anchor = True  # in-group anchor: simulated
                    continue
                # out-of-group right: hard if its origin chain passes
                # through a GROUP member (the scan would stop inside
                # that member's subtree). Walks draw on the segment's
                # shared linear budget (see above)
                cur = rt
                while cur >= 0:
                    if cur in grow_set:
                        hard = True
                        break
                    walk_budget -= 1
                    if walk_budget < 0:
                        hard = True  # budget spent: exact fallback
                        break
                    cur = int(origin_row[cur])
                if hard:
                    break
            if hard:
                break
            if not has_anchor:
                continue  # attachment-free: plain keys are exact
            glist = grows.tolist()
            sibs = [
                {
                    "id": int(ikey_s[r]),
                    "client": int(client_raw_s[r]),
                    "clock": int(clock_raw_s[r]),
                    "right": int(rkey[r]) if rr[r] >= 0 else None,
                }
                for r in glist
            ]
            ordered = _simulate_group(
                sibs, {int(ikey_s[r]) for r in glist}
            )
            seg_rank_sids.extend(ordered)
            seg_rank_vals.extend(range(len(ordered)))
            seg_max_rank = max(seg_max_rank, len(ordered) - 1)
        if hard:
            hard_reps.append(int(order[int(members[0])]))
            continue
        rank_sids.extend(seg_rank_sids)
        rank_vals.extend(seg_rank_vals)
        max_rank = max(max_rank, seg_max_rank)
    if rank_sids:
        rows = np.searchsorted(ikey_s, np.asarray(rank_sids, np.int64))
        client_s[rows] = np.asarray(rank_vals, np.int64)
    return client_s, hard_reps, max_rank


def stage(cols: Dict[str, np.ndarray],
          put=None, wide: Optional[bool] = None) -> Optional[PackedPlan]:
    """Pack kernel columns into the single-transfer matrix (the
    tracer's ``pack`` span — one per staged union/shard).

    See :func:`_stage` for the layout contract."""
    with get_tracer().span("pack"):
        return _stage(cols, put, wide)


def _stage(cols: Dict[str, np.ndarray],
           put=None, wide: Optional[bool] = None) -> Optional[PackedPlan]:
    """Pack kernel columns into the single-transfer matrix.

    Returns None when the batch exceeds the packed path's bounds
    (callers fall back to the general kernels): >=2^25 distinct
    parents, >=2^21 distinct map keys, clocks >= 2^40 (the shared
    ``pack_id`` bound), >=2^30 segments, or composite sibling keys
    that do not fit an int64 at this row count.

    ``put`` (e.g. :func:`crdt_tpu.ops.device.xfer_put`) switches
    staging to EAGER row shipping: each packed row starts its (async)
    host->device transfer the moment its layout pass finishes, so the
    upload overlaps the remaining staging work instead of serializing
    after it — on the tunnelled platform that hides most of one of the
    two costs. The compact sequence block also ships at its own bucket
    width (B, not kpad), cutting the transfer by up to a third. The
    plan then has ``mat=None`` and device refs in ``dev``.

    ``wide`` (None = the CRDT_TPU_WIDE_STAGING env default) disables
    the narrow-column encodings: every row ships at its int32 width.
    The default NARROW path halves the staged bytes whenever every
    column's range fits (see the module's transfer-diet block); a
    column that does not fit falls back automatically (hi/lo int16
    row pair on the matrix path, wide int32 array on the eager path)
    and the chosen widths are recorded per upload
    (:func:`crdt_tpu.ops.device.record_staged_widths`).
    """
    if wide is None:
        wide = wide_staging_forced()
    client = np.asarray(cols["client"], np.int64)
    clock = np.asarray(cols["clock"], np.int64)
    pir = np.asarray(cols["parent_is_root"], bool)
    pa = np.asarray(cols["parent_a"], np.int64)
    pb = np.asarray(cols["parent_b"], np.int64)
    kid = np.asarray(cols["key_id"], np.int64)
    oc = np.asarray(cols["origin_client"], np.int64)
    ock = np.asarray(cols["origin_clock"], np.int64)
    valid = np.asarray(cols["valid"], bool)
    n = len(client)
    if n == 0 or not valid.any():
        return None
    # bound checks consider only admitted rows: garbage in invalid /
    # padding rows must not force a spurious fallback (advisor
    # finding, round 2)
    if int(clock[valid].max()) >= (1 << _CLOCK_BITS):
        return None
    live_origin = valid & (oc >= 0)
    if live_origin.any() and int(ock[live_origin].max()) >= (1 << _CLOCK_BITS):
        return None

    # dense order-preserving client ranks (origins share the table;
    # only admitted rows contribute — garbage in invalid rows must not
    # widen client_bits toward a spurious key-width fallback)
    uniq = np.unique(np.concatenate([client[valid], oc[live_origin]]))
    client_d = np.searchsorted(uniq, np.clip(client, uniq[0], None))
    client_d = np.where(valid, client_d, 0)
    oc_d = np.where(oc >= 0, np.searchsorted(uniq, np.clip(oc, uniq[0], None)), -1)

    # dense parent refs: exact two-key unique via lexsort runs
    porder = np.lexsort((pb, pa, pir))
    pir_s, pa_s, pb_s = pir[porder], pa[porder], pb[porder]
    new_run = np.r_[
        True,
        (pir_s[1:] != pir_s[:-1])
        | (pa_s[1:] != pa_s[:-1])
        | (pb_s[1:] != pb_s[:-1]),
    ]
    ref_sorted = np.cumsum(new_run) - 1
    pref = np.empty(n, np.int64)
    pref[porder] = ref_sorted

    kid_max = int(kid[valid].max())
    if (int(pref[valid].max()) >= (1 << _PREF_BITS)
            or kid_max >= (1 << _KID_BITS)):
        return None

    # id sort + dedup (dense client ranks are monotone in the raw ids,
    # so the dense-packed id sorts identically to the raw-packed one)
    ikey = np.where(
        valid, (client_d << _CLOCK_BITS) | clock, np.int64(2**62)
    )
    order = np.argsort(ikey, kind="stable").astype(np.int32)
    ikey_s = ikey[order]
    kid_s = kid[order]
    pref_s = pref[order]
    oc_s = oc_d[order]
    ock_s = ock[order]
    valid_s = valid[order]
    client_s = client_d[order]
    dup = np.r_[False, ikey_s[1:] == ikey_s[:-1]]
    uniq_valid = valid_s & ~dup

    # dense segments over live rows; map segkeys carry bit 62, so
    # np.unique numbers every sequence segment below every map segment
    sk = segkey_of(pref_s, kid_s)
    uniq_sk, seg_inv, seg_counts = np.unique(
        sk[uniq_valid], return_inverse=True, return_counts=True
    )
    n_segs = len(uniq_sk)
    if n_segs >= _SEQ_FLAG:
        return None
    seg = np.full(n, -1, np.int64)
    seg[uniq_valid] = seg_inv
    map_seg = uniq_sk >= (1 << 62)
    # per-segment populations bound the device doubling rounds: a DFS
    # path cannot exceed its segment's row count + 1 (virtual root),
    # a map key chain cannot be deeper than its segment's row count
    max_map = int(seg_counts[map_seg].max()) if map_seg.any() else 1
    max_seq = int(seg_counts[~map_seg].max()) if (~map_seg).any() else 1

    # size buckets early: eager shipping needs the padded widths now,
    # and the width feasibility checks must run BEFORE the first put —
    # an infeasible plan must not queue dead transfers through the
    # tunnel only to fall back and re-ship via the general path
    kpad = bucket_grid(n, floor=6)
    Sb = bucket_grid(max(n_segs, 1), floor=6)
    n_seq_early = int(np.count_nonzero(uniq_valid & (kid_s < 0)))
    B = min(kpad, bucket_grid(max(n_seq_early, 1), floor=6))
    if max(kpad, B) + Sb >= (1 << 31) - 1:
        return None
    # rank-0 lower-bound width precheck (the exact check re-runs after
    # _stage_rights can only RAISE cbits via simulated group ranks)
    pbits = int(max(kpad, B) + Sb + 1).bit_length()
    qbits = (kpad - 1).bit_length()
    if pbits + _even_up(max(8, len(uniq).bit_length())) + qbits > 63:
        return None
    # eagerness gate: a group's simulated rank is bounded by its
    # segment's row count, so if even the pessimistic cbits (max_seq
    # as the rank bound) fit, _stage_rights cannot push the exact
    # check past 63 and the stages may ship before it runs. A batch
    # near the width limit defers its puts until the exact check —
    # otherwise three dead tunnel transfers would queue before the
    # fallback (advisor finding, round 4).
    eager = put is not None and (
        pbits
        + _even_up(max(
            8, len(uniq).bit_length(), (max_seq + 1).bit_length()
        ))
        + qbits
    ) <= 63
    r1 = np.full(kpad, -1, np.int32)
    r1[:n] = np.where(
        seg >= 0, seg | np.where(kid_s < 0, _SEQ_FLAG, 0), -1
    )
    s1 = d1 = None
    if put is not None:  # matrix staging encodes from mat rows instead
        s1 = None if wide else _narrow_seg(r1, n_segs)
        if eager:
            d1 = put(s1 if s1 is not None else r1)

    # origin rows by binary search over the sorted ids (leftmost match
    # is the kept representative of any duplicate run)
    okey = np.where(
        oc_s >= 0, (oc_s << _CLOCK_BITS) | ock_s, np.int64(-1)
    )
    pos = np.searchsorted(ikey_s, okey)
    posc = np.clip(pos, 0, n - 1)
    origin_row = np.where(
        (okey >= 0) & (ikey_s[posc] == okey), posc, -1
    )
    is_map_row = uniq_valid & (kid_s >= 0)
    origin_map = np.where(is_map_row, origin_row, -1)
    if put is not None:
        r2 = np.full(kpad, -1, np.int32)
        r2[:n] = origin_map
        s2 = None if wide else _narrow_delta_ref(r2)
        if eager:
            d2 = put(s2 if s2 is not None else r2)

    # compact sequence block: seq rows ascending (= id rank ascending),
    # same-segment origins resolved to compact positions
    seq_rows = np.flatnonzero(uniq_valid & (kid_s < 0))
    n_seq = len(seq_rows)
    if n_seq:
        o_rows = origin_row[seq_rows]
        o_seg = seg[np.clip(o_rows, 0, n - 1)]
        same_seg = (o_rows >= 0) & (o_seg == seg[seq_rows])
        cpos = np.searchsorted(seq_rows, np.clip(o_rows, 0, None))
        cposc = np.clip(cpos, 0, n_seq - 1)
        c_parent = np.where(
            same_seg & (seq_rows[cposc] == o_rows), cposc, -1
        )
    else:
        c_parent = np.empty(0, np.int64)
    if put is not None:
        r34 = np.full((2, B), -1, np.int32)
        r34[0, :n_seq] = seq_rows
        r34[1, :n_seq] = c_parent
        s34 = None
        w3 = w4 = None
        if not wide:
            w3 = _narrow_ascending(r34[0])
            w4 = _narrow_delta_ref(r34[1])
            if w3 is not None and w4 is not None:
                s34 = np.stack([w3, w4])
        if eager:
            d34 = put(s34 if s34 is not None else r34)

    # right-origin attachment ordering (mid-inserts/prepends): groups
    # with in-group anchors get their exact conflict-scan ranks
    # written INTO the client column (ranks are unique per group, so
    # the id tie-break never fires and the device kernel needs no
    # change); inexpressible shapes mark their segments hard for the
    # scalar fallback at gather
    hard_rep_rows: list = []
    max_rank = 0
    if "right_client" in cols:
        client_s, hard_rep_rows, max_rank = _stage_rights(
            cols, order, ikey_s, uniq, seg, origin_row, oc_s, seq_rows,
            uniq_valid, kid_s, client_s.copy(), client[order],
            clock[order],
        )

    # static key widths (the client field must also hold the largest
    # simulated group rank)
    cbits = _even_up(max(
        8, len(uniq).bit_length(), (max_rank + 1).bit_length()
    ))
    # (the 2^31 width guard already ran before the first eager put;
    # only the rank-dependent cbits can have grown since)
    if pbits + cbits + qbits > 63:
        return None

    narrow = False
    narrow_cols = ()
    dev_narrow = (False, False, False, False)
    if put is not None:
        if not eager:  # width-deferred stages ship now, post-check
            d1 = put(s1 if s1 is not None else r1)
            d2 = put(s2 if s2 is not None else r2)
            d34 = put(s34 if s34 is not None else r34)
        r0 = np.zeros(kpad, np.int32)
        r0[:n] = client_s
        s0 = None if wide else _narrow_client(r0)
        d0 = put(s0 if s0 is not None else r0)
        mat = None
        dev = (d0, d1, d2, d34)
        dev_narrow = (
            s0 is not None, s1 is not None, s2 is not None,
            s34 is not None,
        )
        widths = {
            "client": 16 if s0 is not None else 32,
            "seg": 16 if s1 is not None else 32,
            "origin": 16 if s2 is not None else 32,
            # the r34 block ships as ONE array: when either half's
            # encoding refuses, BOTH rows go wide — record what
            # actually crossed the wire, not what could have
            "seq_rows": 16 if s34 is not None else 32,
            "seq_parent": 16 if s34 is not None else 32,
        }
        staged_widths = tuple(sorted(widths.items()))
        # eager puts ARE the upload: record here, at the seam's moment
        record_staged_widths(
            widths,
            sum(
                (s if s is not None else r).nbytes
                for s, r in ((s0, r0), (s1, r1), (s2, r2), (s34, r34))
            ),
            (3 * kpad + 2 * B) * 4,
        )
    else:
        mat = np.full((5, kpad), -1, np.int32)
        mat[0, :] = 0
        mat[0, :n] = client_s
        mat[1, :] = r1
        mat[2, :n] = origin_map
        mat[3, :n_seq] = seq_rows
        mat[4, :n_seq] = c_parent
        dev = ()
        if not wide:
            # ONE upload means one dtype: the matrix always ships
            # int16, with each column taking one delta-encoded row
            # when its range fits, or two exact hi/lo rows when it
            # does not (a >32k-segment shard costs 6/10 of wide, not
            # a collapse back to int32)
            encs = (
                _narrow_client(mat[0]),
                _narrow_seg(mat[1], n_segs),
                _narrow_delta_ref(mat[2]),
                _narrow_ascending(mat[3]),
                _narrow_delta_ref(mat[4]),
            )
            widths = {
                c: (16 if e is not None else 32)
                for c, e in zip(
                    ("client", "seg", "origin", "seq_rows",
                     "seq_parent"), encs
                )
            }
            rows16 = []
            for e, wide_row in zip(encs, mat):
                if e is not None:
                    rows16.append(e)
                else:
                    rows16.extend(_split_hi_lo(wide_row))
            mat = np.stack(rows16)
            narrow = True
            narrow_cols = tuple(e is not None for e in encs)
        else:
            widths = {
                c: 32 for c in ("client", "seg", "origin", "seq_rows",
                                "seq_parent")
            }
        # NOT recorded here: a matrix plan may never cross the link
        # (converge_host, make_repeat_dispatch) — the width/savings
        # record fires at the plan's actual upload instead
        staged_widths = tuple(sorted(widths.items()))
    return PackedPlan(
        mat=mat,
        dev=dev,
        n=n,
        num_segments=Sb,
        seq_bucket=B,
        order=order,
        clients=uniq,
        client_bits=cbits,
        rank_rounds=_even_up((max_seq + 2).bit_length() + 1),
        map_rounds=_even_up((max_map + 2).bit_length() + 1),
        hard_rows=tuple(hard_rep_rows),
        narrow=narrow,
        narrow_cols=narrow_cols,
        dev_narrow=dev_narrow,
        staged_widths=staged_widths,
    )


def _converge_packed_body(client, segf, origin_map, sub, cp,
                          num_segments: int, seq_bucket: int,
                          rank_rounds: int, map_rounds: int,
                          client_bits: int):
    """The fused convergence over STAGED rows (id-sorted, deduped,
    origin-resolved, segment-numbered — see :class:`PackedPlan`).
    Returns one packed int32 array:

      [ win_rows[S] | seg_counts[S] | stream_row[B] ]

    - win_rows: id-sorted row index of each map segment's winner (-1
      for non-map / empty segments; the host maps back through
      ``plan.order``);
    - seg_counts: ranked-row count per segment — the host rebuilds the
      per-segment stream boundaries from these instead of fetching a
      B-wide segment column (one third less result transfer);
    - stream_row: sequence rows in document order, grouped by segment
      id ascending (B = seq_bucket; -1 padding at the tail).
    """
    n = client.shape[0]
    live = segf >= 0
    seg = jnp.where(live, segf & (_SEQ_FLAG - 1), NULLI)
    is_map = live & ((segf & _SEQ_FLAG) == 0)
    seg_map = jnp.where(is_map, seg, NULLI)

    winners = map_winners(
        seg_map, client, None, origin_map, is_map, num_segments,
        rows_id_ranked=True, chain_rounds=map_rounds,
        client_bits=client_bits,
    )
    win_rows = winners.astype(jnp.int32)

    B = seq_bucket
    c_ok = sub >= 0
    subc = jnp.clip(sub, 0, n - 1)
    c_seg = jnp.where(c_ok, seg[subc], NULLI)
    parent = jnp.where(c_ok & (cp >= 0), cp, B + jnp.maximum(c_seg, 0))
    parent = jnp.where(c_ok, parent, B + num_segments).astype(jnp.int32)
    c_client = client[subc]
    pos_desc = jnp.where(c_ok, (n - 1) - sub, 0)
    stream_seg, stream_row = _rank_compact(
        parent, c_client, pos_desc, c_seg, c_ok, sub,
        num_segments=num_segments, rank_rounds=rank_rounds,
        client_bits=client_bits,
        qbits=int(max(n - 1, 1)).bit_length(),
    )
    # stream_seg is ascending over its valid prefix (doc order groups
    # by segment) with -1 padding at the tail: counts come from one
    # searchsorted over the monotone remap
    ss = jnp.where(stream_seg >= 0, stream_seg, num_segments)
    bounds = jnp.searchsorted(
        ss, jnp.arange(num_segments + 1, dtype=ss.dtype), method="sort"
    )
    seg_counts = (bounds[1:] - bounds[:-1]).astype(jnp.int32)
    return jnp.concatenate([win_rows, seg_counts, stream_row])


_WIDEN_FNS = (_widen_client, _widen_seg, _widen_delta_ref,
              _widen_ascending, _widen_delta_ref)


def _mat_operands(mat, seq_bucket: int, narrow):
    """The five kernel operands from a staged matrix — the fused
    WIDENING PRELUDE when the matrix shipped in the int16 layout (a
    handful of elementwise ops + one cumsum, traced into the same
    program as the convergence, so the reconstruction never costs an
    extra dispatch).

    ``narrow`` is False for the wide int32 matrix, or the plan's
    ``narrow_cols`` row map: each True column occupies one
    delta-encoded row (decoded by its paired widener), each False
    column two exact hi/lo rows."""
    if narrow is False or narrow == ():
        return (
            mat[0], mat[1], mat[2], mat[3, :seq_bucket],
            mat[4, :seq_bucket],
        )
    ops = []
    r = 0
    for i, (is_narrow, fn) in enumerate(zip(narrow, _WIDEN_FNS)):
        sl = slice(None) if i < 3 else slice(0, seq_bucket)
        if is_narrow:
            ops.append(fn(mat[r][sl]))
            r += 1
        else:
            ops.append(_join_hi_lo(mat[r][sl], mat[r + 1][sl]))
            r += 2
    return tuple(ops)


@partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=("num_segments", "seq_bucket", "rank_rounds",
                     "map_rounds", "client_bits", "narrow"),
)
def _converge_packed(mat, num_segments: int, seq_bucket: int,
                     rank_rounds: int, map_rounds: int,
                     client_bits: int, narrow=False):
    """Single-matrix entry over :func:`_converge_packed_body`
    (matrix-staged plans). The staged matrix is DONATED: its device
    buffer is consumed by the dispatch (the allocator reuses it for
    outputs / the next shard's upload instead of holding both live),
    so a plan must be converged at most once — repeated-dispatch
    probes use :func:`make_repeat_dispatch`."""
    return _converge_packed_body(
        *_mat_operands(mat, seq_bucket, narrow),
        num_segments=num_segments, seq_bucket=seq_bucket,
        rank_rounds=rank_rounds, map_rounds=map_rounds,
        client_bits=client_bits,
    )


@partial(
    jax.jit,
    donate_argnums=(0, 1, 2, 3),
    static_argnames=("num_segments", "seq_bucket", "rank_rounds",
                     "map_rounds", "client_bits", "narrow"),
)
def _converge_rows(r0, r1, r2, r34, num_segments: int, seq_bucket: int,
                   rank_rounds: int, map_rounds: int, client_bits: int,
                   narrow=(False, False, False, False)):
    """Separate-row entry for eagerly shipped plans (``stage(put=)``):
    same fused body, rows already resident on device and DONATED to
    the dispatch (see :func:`_converge_packed`). ``narrow`` carries
    the per-array encoding flags the stager chose."""
    n0, n1, n2, n34 = narrow
    return _converge_packed_body(
        _widen_client(r0) if n0 else r0,
        _widen_seg(r1) if n1 else r1,
        _widen_delta_ref(r2) if n2 else r2,
        _widen_ascending(r34[0]) if n34 else r34[0],
        _widen_delta_ref(r34[1]) if n34 else r34[1],
        num_segments=num_segments, seq_bucket=seq_bucket,
        rank_rounds=rank_rounds, map_rounds=map_rounds,
        client_bits=client_bits,
    )


@partial(
    jax.jit,
    static_argnames=("num_segments", "seq_bucket", "rank_rounds",
                     "map_rounds", "client_bits", "narrow"),
)
def _converge_packed_nodonate(mat, num_segments: int, seq_bucket: int,
                              rank_rounds: int, map_rounds: int,
                              client_bits: int, narrow=False):
    """Undonated twin of :func:`_converge_packed` for the consumers
    that cannot honor (or benefit from) donation: the local-CPU host
    route (CPU has no donation — the donating entry would warn per
    compiled shape in library consumers' stderr) and the repeated
    bench-sweep probe."""
    return _converge_packed_body(
        *_mat_operands(mat, seq_bucket, narrow),
        num_segments=num_segments, seq_bucket=seq_bucket,
        rank_rounds=rank_rounds, map_rounds=map_rounds,
        client_bits=client_bits,
    )


def make_repeat_dispatch(plan: PackedPlan):
    """(device_matrix, fn) for REPEATED undonated dispatches of a
    matrix-staged plan — the bench kernel sweep's probe. The
    production entries donate their staged buffers to the program
    (one plan, one dispatch), which makes re-dispatching the same
    device array through them invalid on donation-capable backends."""
    if plan.mat is None:
        raise ValueError("repeat dispatch needs a matrix-staged plan")
    args = _plan_args(plan)
    narrow = _mat_narrow_arg(plan)

    def fn(m):
        with enable_x64(True):  # the id packing needs real int64
            return _converge_packed_nodonate(m, **args, narrow=narrow)

    return jnp.asarray(plan.mat), fn




def _rank_compact(parent, c_client, pos_desc, c_seg, c_ok, row_of, *,
                  num_segments: int, rank_rounds: Optional[int],
                  client_bits: int, qbits: int):
    """Sibling sort + tree tables + climb + Wyllie ranking + document
    order over the COMPACT sequence space (B rows + S virtual roots).
    ``row_of[i]`` is the caller-space row of compact row i, used only
    to label the output stream. Shared by the cold staged dispatch and
    the general/incremental :func:`_converge_core`.

    Sibling order is (parent, client asc, clock DESC); ``pos_desc``
    must be descending in clock within one (parent, client) group —
    all callers derive it from id-sorted row positions.
    """
    B = parent.shape[0]
    mB = B + num_segments
    pbits = int(mB).bit_length()
    if pbits + client_bits + qbits <= 63:
        sibkey = (
            (parent.astype(jnp.int64) << (client_bits + qbits))
            | (c_client.astype(jnp.int64) << qbits)
            | pos_desc.astype(jnp.int64)
        )
        sord2 = jnp.argsort(sibkey, stable=True)
    else:
        sord2 = lexsort([
            parent.astype(jnp.int64),
            (c_client.astype(jnp.int64) << qbits)
            | pos_desc.astype(jnp.int64),
        ])
    p_s = parent[sord2]
    same_group = jnp.concatenate([p_s[1:] == p_s[:-1], jnp.zeros(1, bool)])
    nxt_sorted = jnp.where(
        same_group, jnp.roll(sord2, -1), NULLI
    ).astype(jnp.int32)
    next_sib = scatter_perm(sord2, nxt_sorted)
    first_pos, _ = run_edge_lookup(p_s, mB, side="left")
    first_child = jnp.where(
        first_pos >= 0, sord2[jnp.clip(first_pos, 0, B - 1)], NULLI
    ).astype(jnp.int32)

    dist_to_end = dfs_ranks(parent, next_sib, first_child, c_ok,
                            num_segments, rank_rounds=rank_rounds)
    root_dist = dist_to_end[B + jnp.maximum(c_seg, 0)]
    c_rank = jnp.where(c_ok, root_dist - dist_to_end[:B] - 1, NULLI)

    skey2 = jnp.where(
        c_ok & (c_rank >= 0),
        (c_seg.astype(jnp.int64) << qbits) | c_rank.astype(jnp.int64),
        jnp.int64(2**62),
    )
    dorder = jnp.argsort(skey2, stable=True)
    d_ok = (c_ok & (c_rank >= 0))[dorder]
    stream_seg = jnp.where(d_ok, c_seg[dorder], NULLI).astype(jnp.int32)
    stream_row = jnp.where(
        d_ok, row_of[dorder], NULLI
    ).astype(jnp.int32)
    return stream_seg, stream_row


def _converge_core(client, clock, pref, kid, oc, ock, valid, *,
                   num_segments: int, seq_bucket: int,
                   rank_rounds: Optional[int] = None,
                   map_rounds: Optional[int] = None):
    """Traced body of the GENERAL packed convergence: does its own id
    sort, dedup, origin resolution, and segment numbering on device.
    The cold replay no longer routes here (its staging precomputes the
    layout — see :func:`_converge_packed`); this remains the engine of
    the incremental touched-segment path
    (``crdt_tpu.models.incremental``), where rows live resident in HBM
    and host precomputation is not available. Row indices in the
    output refer to the CALLER's row space."""
    n = client.shape[0]

    # shared id-sort + dedup + origin resolution (one for both kernels)
    ikey = jnp.where(valid, pack_id(client, clock), jnp.int64(2**62))
    order = jnp.argsort(ikey, stable=True)
    ikey = ikey[order]
    client = client[order]
    clock = clock[order]
    pref = pref[order]
    kid = kid[order]
    oc = oc[order]
    ock = ock[order]
    valid = valid[order]
    dup = jnp.concatenate([jnp.zeros(1, bool), ikey[1:] == ikey[:-1]])
    uniq_valid = valid & ~dup
    okey = pack_id(oc, ock)
    origin_idx = searchsorted_ids(ikey, okey)

    is_map = uniq_valid & (kid >= 0)
    is_seq = uniq_valid & (kid < 0)

    # one composite segment key covers maps AND sequences (dup rows of
    # a map item are ~uniq_valid, so the unmasked kid flag is moot for
    # them — the invalid-row sentinel overrides either way)
    segkey = jnp.where(
        uniq_valid,
        segkey_of(pref, kid.astype(jnp.int64)),
        jnp.int64(2**63 - 1),
    )
    sorder = jnp.argsort(segkey, stable=True)
    seg_sorted = dense_ranks_sorted(segkey[sorder])
    seg = scatter_perm(sorder, seg_sorted)
    seg_map = jnp.where(is_map, seg, NULLI)
    seg_seq = jnp.where(is_seq, seg, NULLI)

    winners = map_winners(
        seg_map, client, clock, origin_idx, is_map, num_segments,
        rows_id_ranked=True, chain_rounds=map_rounds, client_bits=23,
    )
    win_rows = jnp.where(
        winners >= 0, order[jnp.clip(winners, 0, n - 1)], NULLI
    ).astype(jnp.int32)

    # ---- sequence ranking in COMPACT space ---------------------------
    # Sequence segkeys sort below map segkeys (bit 62) and invalid rows
    # (max), so sorder's prefix holds exactly the sequence rows and the
    # static seq bucket B >= n_seq covers them. All sibling/climb/rank
    # machinery runs at size B (+S roots) instead of the full padded n.
    B = seq_bucket
    mB = B + num_segments
    sub = sorder[:B]
    c_ok = is_seq[sub]
    c_seg = jnp.where(c_ok, seg[sub], NULLI)
    # full-space row -> sorder position (compact index for seq rows)
    inv_sorder = jnp.argsort(sorder, stable=True).astype(jnp.int32)
    o = origin_idx[sub]
    o_ok = c_ok & (o >= 0)
    o_seg = jnp.where(o_ok, seg[jnp.clip(o, 0, n - 1)], NULLI)
    same_seg = o_ok & (o_seg == c_seg)
    c_parent = jnp.where(
        same_seg, inv_sorder[jnp.clip(o, 0, n - 1)], NULLI
    ).astype(jnp.int32)

    parent = jnp.where(
        c_ok & (c_parent >= 0), c_parent, B + jnp.maximum(c_seg, 0)
    )
    parent = jnp.where(c_ok, parent, mB).astype(jnp.int32)

    # sibling order by (parent, client asc, clock DESC). Within one
    # client, clock order == id-sorted position order, so the global
    # row index (already an id-rank here) stands in for the clock —
    # making the whole key fit one int64 when the static widths allow.
    c_client = client[sub]
    pos_desc = (n - 1) - sub  # descending position == descending clock
    stream_seg, stream_row = _rank_compact(
        parent, c_client, pos_desc, c_seg, c_ok, order[sub],
        num_segments=num_segments, rank_rounds=rank_rounds,
        client_bits=23, qbits=int(max(n - 1, 1)).bit_length(),
    )
    return jnp.concatenate([win_rows, stream_seg, stream_row])


def segkey_int(pref: int, kid: int) -> int:
    """Scalar-Python :func:`segkey_of` for per-op hot paths (the
    resident doc's local ops): no numpy temporaries, same key."""
    if kid >= 0:
        return ((pref << _KID_BITS) | kid) | (1 << 62)
    return pref << _KID_BITS


def segkey_of(pref, kid):
    """The composite segment key, shared by staging, the fused kernel,
    and the incremental host bookkeeping. Works on numpy or jnp
    (dtype-explicit: the map-flag bit 62 must not fall into a narrow
    weak-typed promotion)."""
    is_map = (kid >= 0).astype(np.int64)
    base = (pref << _KID_BITS) | (is_map * kid)
    return base | (is_map << np.int64(62))


@partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=("num_segments", "sel_bucket", "seq_bucket"),
)
def _splice_select_converge(mat, delta8, n_off,
                            num_segments: int, sel_bucket: int,
                            seq_bucket: int):
    """Incremental warm dispatch — exactly THREE host<->device
    interactions per round: ONE upload (``delta8``: the packed delta
    columns with the touched-segment keys riding as row 7 — ascending
    segkeys, int64-max padding), ONE dispatch, and ONE fetch of a
    single packed array (the caller splits it). Splices the delta into
    the resident matrix (donated), selects the rows of the touched
    segments, and re-converges only that compact subset. Returns

      (resident_mat, [ out[S + 2B] | sel_rows[sel_bucket] ] int32)

    where out's row indices are LOCAL to sel_rows; callers map back
    with sel_rows (resident row ids, -1 padding)."""
    touched_sorted = delta8[7]
    mat = jax.lax.dynamic_update_slice(
        mat, delta8[:7].astype(mat.dtype),
        (jnp.int32(0), n_off.astype(jnp.int32)),
    )
    client = mat[0].astype(jnp.int32)
    clock = mat[1].astype(jnp.int64)
    pref = mat[2].astype(jnp.int64)
    kid = mat[3].astype(jnp.int32)
    oc = mat[4].astype(jnp.int32)
    ock = mat[5].astype(jnp.int64)
    valid = mat[6] != 0

    segkey = segkey_of(pref, kid.astype(jnp.int64))
    pos = jnp.searchsorted(touched_sorted, segkey, method="sort")
    pos_c = jnp.clip(pos, 0, touched_sorted.shape[0] - 1)
    sel = valid & (touched_sorted[pos_c] == segkey)
    skey = jnp.where(sel, segkey, jnp.int64(2**63 - 1))
    order2 = jnp.argsort(skey, stable=True)
    sel_rows = order2[:sel_bucket].astype(jnp.int32)
    sub_valid = sel[sel_rows]
    out = _converge_core(
        client[sel_rows], clock[sel_rows], pref[sel_rows], kid[sel_rows],
        oc[sel_rows], ock[sel_rows], sub_valid,
        num_segments=num_segments, seq_bucket=seq_bucket,
    )
    packed_out = jnp.concatenate([
        out, jnp.where(sub_valid, sel_rows, NULLI).astype(jnp.int32)
    ])
    return mat, packed_out


@partial(jax.jit, donate_argnums=(0,), static_argnames=("new_cap",))
def _grow_mat(mat, new_cap: int):
    """Capacity growth for the resident matrix, on device."""
    big = jnp.zeros((7, new_cap), mat.dtype)
    big = big.at[3:6, :].set(-1)  # key_id / origin columns: null
    return jax.lax.dynamic_update_slice(big, mat, (0, 0))


@partial(jax.jit, donate_argnums=(0,))
def _relabel_mat(mat, perm):
    """Rewrite dense client ids through an old->new permutation after
    a mid-table client insertion (order-preserving interning)."""
    cl = mat[0]
    oc = mat[4]
    mat = mat.at[0, :].set(perm[jnp.clip(cl, 0, perm.shape[0] - 1)]
                           .astype(mat.dtype))
    new_oc = jnp.where(
        oc >= 0, perm[jnp.clip(oc, 0, perm.shape[0] - 1)], oc
    )
    return mat.at[4, :].set(new_oc.astype(mat.dtype))


class PackedResult(NamedTuple):
    win_rows: np.ndarray     # [S] original row of each map winner (-1 none)
    stream_seg: np.ndarray   # [B] doc-order segment ids (-1 padding)
    stream_row: np.ndarray   # [B] doc-order original rows (-1 padding)
    hard_rows: tuple = ()    # rows marking segments needing the scalar
                             # fallback (right shapes the sibling-rank
                             # model cannot express)


def _mat_narrow_arg(plan: PackedPlan):
    """The static ``narrow`` dispatch arg for a matrix-staged plan:
    False for the wide layout, the row map for the int16 layout."""
    return plan.narrow_cols if plan.narrow else False


def _plan_args(plan: PackedPlan) -> dict:
    return dict(
        num_segments=plan.num_segments,
        seq_bucket=plan.seq_bucket,
        rank_rounds=plan.rank_rounds,
        map_rounds=plan.map_rounds,
        client_bits=plan.client_bits,
    )


def _put_mat(plan: PackedPlan):
    """A matrix plan's ONE upload through the xfer seam, with the
    per-column width/savings record made at the same moment — never
    at stage time, where a plan destined for the zero-link host route
    or a repeat-dispatch probe would leave phantom entries."""
    record_staged_widths(
        dict(plan.staged_widths), plan.mat.nbytes,
        5 * plan.mat.shape[1] * 4,
    )
    return xfer_put(plan.mat, label="converge.mat")


def _assemble_result(plan: PackedPlan, h: np.ndarray) -> PackedResult:
    """The one fetch -> caller-space result (shared by the device and
    local-CPU executions of the identical kernel)."""
    s = plan.num_segments
    b = plan.seq_bucket
    order = plan.order
    win = h[:s]
    counts = h[s:2 * s]
    srow = h[2 * s:2 * s + b]
    k = int(counts.sum())
    stream_seg = np.full(b, NULLI, np.int32)
    stream_seg[:k] = np.repeat(
        np.arange(s, dtype=np.int32), counts
    )
    last = max(len(order) - 1, 0)
    return PackedResult(
        win_rows=np.where(win >= 0, order[np.clip(win, 0, last)], NULLI),
        stream_seg=stream_seg,
        stream_row=np.where(srow >= 0, order[np.clip(srow, 0, last)], NULLI),
        hard_rows=plan.hard_rows,
    )


def converge_async(plan: PackedPlan):
    """ENQUEUE the fused convergence and return immediately — no
    blocking fetch. The returned handle is the streaming executor's
    overlap seam: while the dispatch is in flight the host stages,
    uploads, and dispatches the NEXT chunk (and materializes the
    previous one); :func:`converge_fetch` blocks only when the
    consumer actually needs the winners. ``jnp.asarray``/``device_put``
    and jitted calls are all asynchronous, so the only synchronization
    point in the whole (stage -> upload -> dispatch) chain is the
    fetch."""
    args = _plan_args(plan)
    # span = enqueue cost (the dispatch is async); the XProf
    # annotation brackets the jitted call so device timelines
    # attribute the fused kernel to the converge phase. The staged
    # buffers are DONATED to the program (matrix upload through the
    # xfer seam, eager rows at stage time): one plan, one dispatch.
    with get_tracer().span("converge.dispatch"), \
            device_annotation("crdt.converge.dispatch"), \
            enable_x64(True):
        if plan.dev:
            out = _converge_rows(*plan.dev, **args,
                                 narrow=plan.dev_narrow)
        else:
            out = _converge_packed(
                _put_mat(plan), **args,
                narrow=_mat_narrow_arg(plan),
            )
    return plan, out


def converge_fetch(handle) -> PackedResult:
    """Block on an in-flight :func:`converge_async` dispatch and
    assemble its one packed fetch into caller row space (the tracer's
    ``converge.fetch`` span: wait + transfer + assembly). The D2H
    transfer itself goes through :func:`crdt_tpu.ops.device.
    xfer_fetch` AFTER an explicit wait-for-execution, so the
    ``xfer.d2h`` histogram records pure transfer time (previously the
    wait was folded in and the fetch cost was unattributable)."""
    plan, out = handle
    with get_tracer().span("converge.fetch"), \
            device_annotation("crdt.converge.fetch"):
        jax.block_until_ready(out)  # execution wait, not transfer
        return _assemble_result(
            plan, xfer_fetch(out, label="converge.out")
        )


def converge(plan: PackedPlan,
             phases: Optional[dict] = None) -> PackedResult:
    """Stage -> single dispatch -> single fetch. Device outputs are in
    id-sorted row space; the plan's sort permutation maps them back to
    the caller's rows (one numpy gather, off the device clock). Plans
    staged with ``put=`` skip the transfer here — their rows are
    already (asynchronously) on device.

    ``phases``, when given, receives the span's sub-costs
    (``upload_wait``/``dispatch``/``fetch`` seconds) so published
    numbers itemize against the floor derivation (ROOFLINE.md) instead
    of reporting one opaque "converge"."""
    import time as _t

    if phases is None:
        # production shape: enqueue + one blocking fetch (the same
        # two-step seam the streaming executor drives directly)
        return converge_fetch(converge_async(plan))

    args = _plan_args(plan)

    def mark(name, t0):
        phases[name] = round(_t.perf_counter() - t0, 4)

    # from here on phases is non-None: this is the INSTRUMENTED shape
    # only — its sync barriers exist to itemize upload/dispatch/fetch
    # against the floor derivation (ROOFLINE.md), and would serialize
    # the production path, which took the async early return above
    with enable_x64(True):
        if plan.dev:
            t0 = _t.perf_counter()
            jax.block_until_ready(plan.dev)  # eager uploads land
            mark("upload_wait", t0)
            t0 = _t.perf_counter()
            out = _converge_rows(*plan.dev, **args,          # 1 dispatch
                                 narrow=plan.dev_narrow)
            jax.block_until_ready(out)
            mark("dispatch", t0)
        else:
            t0 = _t.perf_counter()
            dev_mat = _put_mat(plan)
            jax.block_until_ready(dev_mat)                   # 1 transfer
            mark("upload_wait", t0)
            t0 = _t.perf_counter()
            out = _converge_packed(dev_mat, **args,          # 1 dispatch
                                   narrow=_mat_narrow_arg(plan))
            jax.block_until_ready(out)
            mark("dispatch", t0)
        # the fetch is attributed to its OWN phase (and the xfer.d2h
        # histogram), never folded into dispatch: the dispatch mark
        # above waits for EXECUTION, this times the D2H transfer +
        # nothing else, so converge_detail.fetch matches xfer.d2h_bytes
        t0 = _t.perf_counter()
        h = xfer_fetch(out, label="converge.out")            # 1 fetch
        mark("fetch", t0)
        phases["d2h_bytes"] = int(h.nbytes)
        if plan.mat is not None:
            phases["h2d_bytes"] = int(plan.mat.nbytes)
    # mirror the async seam's tracer spans so instrumented runs (the
    # bench's per-phase detail path) still feed the same histograms
    tracer = get_tracer()
    if tracer.enabled:
        tracer.observe("converge.dispatch", phases["dispatch"])
        tracer.observe("converge.fetch", phases["fetch"])
    return _assemble_result(plan, h)


def converge_host(plan: PackedPlan) -> PackedResult:
    """The IDENTICAL fused convergence executed on the process's
    local CPU backend: zero tunnel interactions, byte-identical
    outputs (differential-tested). This is the engine under the
    host side of the product crossover — on a tunnelled platform a
    sub-threshold union pays ~3 fixed interaction latencies to reach
    the accelerator, while the same XLA program on the local backend
    ran a 20k-row text union in ~30ms.

    Requires a matrix-staged plan (``stage(put=None)``); eagerly
    shipped plans already live on the accelerator — converge them
    there. Compilation-cache handling (suppression of XLA:CPU AOT
    artifacts from TPU processes) lives in
    :func:`crdt_tpu.ops.device.on_local_cpu`."""
    if plan.dev:
        raise ValueError(
            "converge_host needs a matrix-staged plan (stage(put=None))"
        )
    from crdt_tpu.ops.device import on_local_cpu

    args = _plan_args(plan)
    key = ("converge_host", plan.mat.shape, _mat_narrow_arg(plan),
           tuple(sorted(args.items())))
    with get_tracer().span("converge.dispatch"), \
            on_local_cpu(cache_key=key), enable_x64(True):
        # NO xfer seam here: the whole point of this path is zero
        # bytes on the tunnel link (local CPU backend) — and the
        # UNDONATED entry, since CPU can never honor donation and the
        # donating twin would warn into library consumers' stderr
        h = np.asarray(
            _converge_packed_nodonate(jnp.asarray(plan.mat), **args,
                                      narrow=_mat_narrow_arg(plan))
        )
    with get_tracer().span("converge.fetch"):
        return _assemble_result(plan, h)
