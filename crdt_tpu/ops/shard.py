"""Multi-chip sharded converge — the staged packed pipeline spread
over a device mesh (round 13, ROADMAP item 1).

The single-chip cold path (:mod:`crdt_tpu.ops.packed`) stages the
whole union into one flat section array and converges it in one
dispatch on one device. This module cuts the SAME staged layout at
segment granularity across the mesh:

1. **Partition** — the union's rows group by full segment identity
   (parent ref + key), and whole segments greedy-balance across K
   shards by row count. YATA origins and LWW key chains never cross
   segments, so every shard's converge is independent — Wyllie
   doubling never crosses a chip. A DOMINATING sequence segment is
   pre-cut at DFS-suffix subtree granularity (round 23, the same
   :func:`crdt_tpu.ops.packed.dfs_suffix_boundaries` cut the staging
   split uses) and its pieces spread ACROSS chips — one hot list no
   longer bounds one shard. Inside each shard, anything still over
   the staging split width is re-cut by
   :func:`crdt_tpu.ops.packed._subtree_split`, so per-shard doubling
   runs ceil(log2(split width)) rounds, not
   ceil(log2(deepest structure)).
2. **Stage** — each shard runs the ordinary packed staging
   (layout-only), then every shard's eight sections are padded to
   COMMON bucket sizes and narrow-encoded with ONE shared encoding
   tuple, giving a [K, L] block a single compiled program serves.
3. **Converge** — ONE ``compat.shard_map`` program
   (:func:`crdt_tpu.parallel.gossip.make_packed_shard_step`): each
   device runs the full sortless fused converge on its shard; the
   only inter-chip traffic is the **boundary exchange** — the
   per-shard state vectors, narrow-encoded with the round-9 codec as
   the wire format, all-gathered and max-merged into the swarm SV on
   device (the fetch audits the merge against the host-staged
   vectors and raises on divergence).
4. **Assemble** — the host maps each shard's block-local results
   through its own translation tables and row map; concatenating the
   per-shard streams with disjoint segment ids reproduces the
   single-chip result BIT-identically (tests/test_shard.py pins
   cache + snapshot + SV equality at 2/4/8-way).

Route selection: ``CRDT_TPU_SHARDS`` (unset = all visible devices,
``0``/``1`` disables) and ``CRDT_TPU_SHARD_MIN_ROWS`` (default 2^15 —
below it the extra per-shard fixed costs beat the division). The
one-shot replay, the streaming executor's stream shards, and the
fleet replay all take this route through :func:`active_for`.

Evidence: ``shard.dispatches`` / ``shard.boundary_bytes`` /
``shard.seam_rows`` counters and the ``shard.shards`` gauge (README
"Observability" registry; ``bench.py --multichip`` publishes the
per-device-count scaling table).
"""

from __future__ import annotations

import os
import threading
from typing import NamedTuple, Optional

import numpy as np

from crdt_tpu.compat import enable_x64
from crdt_tpu.obs.profiling import device_annotation
from crdt_tpu.obs.tracer import get_tracer
from crdt_tpu.ops.device import (
    NULLI,
    bucket_grid,
    record_staged_widths,
    wide_staging_forced,
    xfer_fetch,
    xfer_put,
)
from crdt_tpu.ops import packed

SHARD_ENV = "CRDT_TPU_SHARDS"
MIN_ROWS_ENV = "CRDT_TPU_SHARD_MIN_ROWS"
MIN_ROWS_DEFAULT = 1 << 15


def shard_count(n_shards: Optional[int] = None) -> int:
    """Resolved shard count: explicit arg, else ``CRDT_TPU_SHARDS``,
    else every visible device. 0/1 means the sharded route is off."""
    if n_shards is not None:
        return max(0, int(n_shards))
    raw = os.environ.get(SHARD_ENV, "")
    if raw != "":
        try:
            return max(0, int(raw))
        except ValueError:
            pass
    import jax

    return len(jax.devices())


def min_rows() -> int:
    raw = os.environ.get(MIN_ROWS_ENV, "")
    if raw != "":
        try:
            return max(0, int(raw))
        except ValueError:
            pass
    return MIN_ROWS_DEFAULT


def active_for(n_rows: int,
               n_shards: Optional[int] = None) -> bool:
    """Should this union take the sharded route? >1 shard resolved
    AND the union is big enough to amortize the per-shard costs."""
    return shard_count(n_shards) > 1 and n_rows >= min_rows()


class ShardPlan(NamedTuple):
    """Host-side staging result of :func:`stage`: K repadded per-shard
    plans + the common-encoded [K, L] section block + the narrow
    boundary wire. Like a :class:`~crdt_tpu.ops.packed.PackedPlan`,
    a sharded plan is consumed by its one dispatch (the block is
    donated)."""

    plans: tuple            # per-shard PackedPlan (repadded metadata)
    row_maps: tuple         # per-shard caller-row index arrays
    block: np.ndarray       # [K, L] staged sections, shared encoding
    wire: np.ndarray        # [K, W] boundary wire (SV + meta)
    encs: tuple             # shared per-section encodings
    num_segments: int       # common S bucket
    seq_bucket: int         # common B bucket
    map_bucket: int         # common M bucket
    rank_rounds: int        # max over shards
    map_rounds: int         # max over shards
    sv_clients: np.ndarray  # dense rank -> raw client id
    sv_host: np.ndarray     # [K, C] host copy of the per-shard SVs
    sv_mode: str            # wire encoding: 'i16' / 'hilo' / 'wide'
    n_rows: int             # total valid rows staged
    widths: dict = {}       # per-section chosen widths (one record
                            # at upload, like packed._put_mat)
    wide_bytes: int = 0     # pre-diet byte baseline for the record


class ShardResult(NamedTuple):
    """Merged caller-space result — duck-compatible with
    :class:`~crdt_tpu.ops.packed.PackedResult` (the replay gather
    consumes it unchanged), plus the boundary exchange's merged
    swarm state vector."""

    win_rows: np.ndarray
    stream_seg: np.ndarray
    stream_row: np.ndarray
    hard_rows: tuple = ()
    global_sv: Optional[np.ndarray] = None  # [C] dense-rank clocks+1
    sv_clients: Optional[np.ndarray] = None


def _chain_weights(counts: np.ndarray,
                   origin_counts: np.ndarray) -> np.ndarray:
    """Greedy-partition weights honoring CHAIN DEPTH, not just row
    count (ROADMAP item 1 remainder): a shard's converge runs
    ``ceil(log2(longest chain))`` pointer-doubling rounds — the
    Wyllie bound — over ALL its rows, so a deep append chain costs
    ``rows x log2(depth)`` where an equally-sized wide segment costs
    ``rows x 1``. Depth is bounded above by the segment's
    origin-bearing rows + 1 (every chain hop needs a live origin;
    root-attached rows never deepen a chain), which is exact for
    pure chains and errs toward over-weighting branchy segments —
    the safe direction for balance. Returns per-segment integer
    weights ``rows * max(1, ceil(log2(1 + origin_rows)))``."""
    depth = np.maximum(origin_counts, 0) + 1
    rounds = np.maximum(
        1, np.ceil(np.log2(np.maximum(depth, 1))).astype(np.int64)
    )
    return np.asarray(counts, np.int64) * rounds


def _partition(cols, K: int):
    """Whole-segment greedy partition of the union's valid rows into
    K depth-weighted shards (:func:`_chain_weights` — segments weigh
    ``rows x ceil(log2(chain_len))``, the Wyllie rounds bound, so a
    deep chain and a wide segment of equal row count no longer read
    as equal work). Returns ``(shard_rows, pb_tag)``: a list of
    caller-row index arrays (some possibly empty: fewer segments
    than shards) and the cross-shard pre-cut's parent-ref tag column
    (None when nothing was pre-cut — see below), or None for an
    empty union.

    A DOMINATING sequence segment (more rows than ``total // K``) no
    longer bounds one shard: it is pre-cut at DFS-suffix subtree
    granularity (:func:`crdt_tpu.ops.packed.dfs_suffix_boundaries` —
    the exact round-23 staging cut, so concatenating the pieces in
    piece order reproduces the segment's stream bit-for-bit) and the
    pieces assign MONOTONICALLY across shards. ``pb_tag`` carries
    per-piece offsets of ``(piece+1) << 45`` for the ``parent_b``
    column: each piece becomes its own full segment identity inside
    its shard (the offsets cannot collide with real parent refs, all
    < 2^44 by the guard), pieces order by tag within a shard and by
    shard across shards — so the assembler's same-parent run merge
    stitches them back in exact document order with no new seam
    plumbing.

    Duplicate ids are dropped GLOBALLY first (keep the first caller
    row, packed._stage's rule): equal-id rows under different parents
    would land in different shards, where no shard-local dedup could
    see the pair — the single-chip oracle keeps only the leftmost, so
    the sharded route must too.

    Multi-doc unions (round 14: a ``doc`` column is present with >1
    distinct doc) partition by DOC first: a doc's segments — and
    therefore its whole converge — stay co-located on one chip, docs
    greedy-balance across shards by row count, and the duplicate drop
    and segment identity are doc-scoped (two docs legitimately reuse
    the same (client, clock) ids and parent refs). Within a
    single-doc union the whole-segment partition is unchanged."""
    valid = np.asarray(cols["valid"], bool)
    idx = np.flatnonzero(valid)
    if not len(idx):
        return None
    dv = (np.asarray(cols["doc"], np.int64)[idx]
          if "doc" in cols else np.zeros(len(idx), np.int64))
    multi_doc = len(idx) > 0 and int(dv.max()) != int(dv.min())
    cl_v = np.asarray(cols["client"], np.int64)[idx]
    ck_v = np.asarray(cols["clock"], np.int64)[idx]
    so = np.lexsort((np.arange(len(idx)), ck_v, cl_v, dv))
    dup = np.r_[
        False,
        (cl_v[so][1:] == cl_v[so][:-1]) & (ck_v[so][1:] == ck_v[so][:-1])
        & (dv[so][1:] == dv[so][:-1]),
    ]
    if dup.any():
        keep = np.sort(so[~dup])
        idx, dv = idx[keep], dv[keep]
    oc_live = np.asarray(cols["origin_client"], np.int64)[idx] >= 0
    if multi_doc:
        # doc-first: greedy balance whole docs, heaviest first into
        # the lightest bin (fewer docs than shards leaves shards
        # empty — the all-padding shard body handles them). Doc
        # weight honors chain depth like the segment cut: a doc's
        # rounds bound is log2 of its chained rows.
        docs_u, doc_inv, doc_counts = np.unique(
            dv, return_inverse=True, return_counts=True
        )
        doc_oc = np.bincount(
            doc_inv, weights=oc_live, minlength=len(docs_u)
        ).astype(np.int64)
        weights = _chain_weights(doc_counts, doc_oc)
        bins = np.zeros(len(docs_u), np.int64)
        loads = np.zeros(K, np.int64)
        for d in np.argsort(-weights, kind="stable"):
            b = int(np.argmin(loads))
            bins[d] = b
            loads[b] += int(weights[d])
        shard_of_row = bins[doc_inv]
        return [idx[shard_of_row == k] for k in range(K)], None
    pir = np.asarray(cols["parent_is_root"], bool)[idx]
    pa = np.asarray(cols["parent_a"], np.int64)[idx]
    pb = np.asarray(cols["parent_b"], np.int64)[idx]
    kid = np.asarray(cols["key_id"], np.int64)[idx]
    order = np.lexsort((kid, pb, pa, pir))
    same = (
        (pir[order][1:] == pir[order][:-1])
        & (pa[order][1:] == pa[order][:-1])
        & (pb[order][1:] == pb[order][:-1])
        & (kid[order][1:] == kid[order][:-1])
    )
    seg_sorted = np.cumsum(np.r_[True, ~same]) - 1
    seg = np.empty(len(idx), np.int64)
    seg[order] = seg_sorted
    counts = np.bincount(seg)
    seg_oc = np.bincount(
        seg, weights=oc_live, minlength=len(counts)
    ).astype(np.int64)

    # cross-shard subtree pre-cut (round 23): a dominating sequence
    # segment's DFS-suffix pieces spread across chips instead of
    # bounding one shard. The pieces' loads pre-seed the greedy bins;
    # cut segments skip the whole-segment loop below.
    pb_tag = None
    piece_of = {}     # seg id -> (rows_s, piece index per row)
    loads = np.zeros(K, np.int64)
    big = np.flatnonzero(
        counts > max(2048, len(idx) // max(K, 1))
    )
    if len(big):
        cl_i = np.asarray(cols["client"], np.int64)[idx]
        ck_i = np.asarray(cols["clock"], np.int64)[idx]
        rr_i = (np.asarray(cols["right_client"], np.int64)[idx]
                if "right_client" in cols
                else np.full(len(idx), -1, np.int64))
        oc_i = np.asarray(cols["origin_client"], np.int64)[idx]
        ock_i = np.asarray(cols["origin_clock"], np.int64)[idx]
        kid_i = np.asarray(cols["key_id"], np.int64)[idx]
        pb_i = np.asarray(cols["parent_b"], np.int64)[idx]
        uniq_cl = np.unique(cl_i)
        # id-key packing + tag-offset guards, packed._stage's bounds:
        # skip the pre-cut (never the route) when a bound trips
        feasible = (
            len(uniq_cl) < (1 << 22)
            and int(ck_i.max(initial=0)) < (1 << packed._CLOCK_BITS)
            and int(ock_i.max(initial=0)) < (1 << packed._CLOCK_BITS)
            and int(np.abs(pb_i).max(initial=0)) < (1 << 44)
        )
        for s in (big.tolist() if feasible else []):
            rows_s = np.flatnonzero(seg == s)
            if (kid_i[rows_s] >= 0).any() or (rr_i[rows_s] >= 0).any():
                continue  # map segments / right origins stay whole
            # compact-local forest, exactly as packed._stage builds
            # it: rows in id order, origins resolved same-segment
            so_l = np.lexsort((ck_i[rows_s], cl_i[rows_s]))
            rs = rows_s[so_l]
            cd = np.searchsorted(uniq_cl, cl_i[rs])
            ikey_l = (cd << packed._CLOCK_BITS) | ck_i[rs]
            ocd = np.searchsorted(uniq_cl, np.clip(oc_i[rs], 0, None))
            okey_l = np.where(
                oc_i[rs] >= 0,
                (ocd << packed._CLOCK_BITS) | ock_i[rs], np.int64(-1),
            )
            p = np.searchsorted(ikey_l, okey_l)
            pc = np.clip(p, 0, len(rs) - 1)
            par_l = np.where(
                (okey_l >= 0) & (ikey_l[pc] == okey_l), pc, -1
            )
            # hostile cyclic origins: the unsplit path's semantics
            # must stand — leave the segment whole
            m = len(rs)
            f = np.where(par_l >= 0, par_l,
                         np.arange(m, dtype=np.int64))
            for _ in range(max(1, (max(m, 2) - 1).bit_length() + 1)):
                f = f[f]
            if (par_l[f] >= 0).any():
                continue
            width = -(-m // K)
            pos, cuts = packed.dfs_suffix_boundaries(
                par_l, cd, (m - 1) - np.arange(m, dtype=np.int64),
                width, max_pieces=2 * K + 2,
            )
            if len(cuts) < 2:
                continue
            piece = (np.searchsorted(cuts, pos, side="right")
                     - 1).astype(np.int64)
            np_c = len(cuts)
            pshard = (piece * K) // np_c  # monotone piece -> shard
            prows = np.bincount(piece, minlength=np_c)
            in_piece = (par_l >= 0) & (
                piece[np.clip(par_l, 0, m - 1)] == piece
            )
            poc = np.bincount(
                piece, weights=in_piece, minlength=np_c
            ).astype(np.int64)
            pw = _chain_weights(prows, poc)
            for j in range(np_c):
                loads[(j * K) // np_c] += int(pw[j])
            if pb_tag is None:
                pb_tag = np.zeros(
                    len(np.asarray(cols["valid"])), np.int64
                )
            pb_tag[idx[rs]] = (piece + 1) << 45
            piece_of[s] = (rs, pshard)

    # greedy balance by DEPTH-WEIGHTED load, heaviest segments first
    # into the lightest bin (a single huge segment no longer bounds
    # one shard — its pre-cut pieces are already seeded above; only
    # refused shapes keep the honest whole-segment limit)
    weights = _chain_weights(counts, seg_oc)
    bins = np.zeros(len(counts), np.int64)
    for s in np.argsort(-weights, kind="stable"):
        if s in piece_of:
            continue
        b = int(np.argmin(loads))
        bins[s] = b
        loads[b] += int(weights[s])
    shard_of_row = bins[seg]
    for s, (rs, pshard) in piece_of.items():
        shard_of_row[rs] = pshard
    return [idx[shard_of_row == k] for k in range(K)], pb_tag


# per-section pad values for the common-bucket repad (seg_off pads
# with 0: offsets of absent segments are never read through a live
# sseg)
_PAD_VALS = {"seg_off": 0}


def _repad_sections(secs, S: int, B: int, M: int,
                    S2: int, B2: int, M2: int):
    """Pad one shard's eight sections from its natural buckets to the
    common ones. Only ``seq_first`` is position-dependent (its root
    block sits at offset B); every other section pads at the tail —
    values are block-local indices below their own bucket, unchanged
    by a wider block."""
    out = []
    for name, arr in secs:
        pad = _PAD_VALS.get(name, -1)
        if name == "seq_first":
            new = np.full(B2 + S2, -1, arr.dtype)
            new[:B] = arr[:B]
            new[B2:B2 + S] = arr[B:B + S]
        else:
            tgt = {"seq_seg": B2, "seg_off": S2, "seq_parent": B2,
                   "seq_next": B2, "map_key": M2, "map_chain_end": M2,
                   "map_root_end": S2}[name]
            new = np.full(tgt, pad, arr.dtype)
            new[: len(arr)] = arr
        out.append((name, new))
    return out


def _empty_sections(S2: int, B2: int, M2: int):
    """An all-padding shard (fewer segments than shards): the fused
    body on pure padding yields no winners and an all-hole stream."""
    z = np.int64
    return [
        ("seq_seg", np.full(B2, -1, z)),
        ("seg_off", np.zeros(S2, z)),
        ("seq_parent", np.full(B2, -1, z)),
        ("seq_next", np.full(B2, -1, z)),
        ("seq_first", np.full(B2 + S2, -1, z)),
        ("map_key", np.full(M2, -1, z)),
        ("map_chain_end", np.full(M2, -1, z)),
        ("map_root_end", np.full(S2, -1, z)),
    ]


def _empty_plan(S2: int, B2: int, M2: int) -> packed.PackedPlan:
    return packed.PackedPlan(
        mat=None, n=0, num_segments=S2, seq_bucket=B2, map_bucket=M2,
        order=np.empty(0, np.int32), clients=np.empty(0, np.int64),
        rank_rounds=2, map_rounds=2,
        map_back=np.full(M2, NULLI, np.int32),
        seq_back=np.full(B2, NULLI, np.int32),
        seg_counts=np.zeros(S2, np.int64),
    )


def stage(cols, n_shards: Optional[int] = None) -> Optional[ShardPlan]:
    """Partition + per-shard staging + common-bucket encode (the
    tracer's ``pack`` span covers the per-shard layout passes).
    Returns None when the union cannot take the sharded route (a
    shard exceeded the packed bounds, no valid rows, or <2 shards
    resolved) — callers fall back to the single-chip path."""
    K = shard_count(n_shards)
    if K <= 1:
        return None
    part = _partition(cols, K)
    if part is None:
        return None
    shard_rows, pb_tag = part

    col_arrays = {k: np.asarray(v) for k, v in cols.items()}
    if pb_tag is not None:
        # cross-shard pre-cut: the piece tags ride a COPY of the
        # parent_b column (the caller's cols stay untouched — a
        # fallback to the single-chip path must see the original
        # refs). Tags only shape segment identity and pref order;
        # assembly decodes parents from dec, never from this column.
        col_arrays["parent_b"] = (
            col_arrays["parent_b"].astype(np.int64) + pb_tag
        )
    layouts = []  # (plan, secs, rows) per non-empty shard; None empty
    for rows_k in shard_rows:
        if not len(rows_k):
            layouts.append(None)
            continue
        sub = {k: v[rows_k] for k, v in col_arrays.items()}
        secs: list = []
        plan = packed.stage(sub, _sections=secs)
        if plan is None:
            return None
        layouts.append((plan, secs, rows_k))

    live = [lay for lay in layouts if lay is not None]
    if not live:
        return None
    S2 = max(lay[0].num_segments for lay in live)
    B2 = max(lay[0].seq_bucket for lay in live)
    M2 = max(lay[0].map_bucket for lay in live)
    rank2 = max(lay[0].rank_rounds for lay in live)
    map2 = max(lay[0].map_rounds for lay in live)

    wide = wide_staging_forced()
    padded = []
    for lay in layouts:
        if lay is None:
            padded.append(_empty_sections(S2, B2, M2))
        else:
            plan, secs, _ = lay
            padded.append(_repad_sections(
                secs, plan.num_segments, plan.seq_bucket,
                plan.map_bucket, S2, B2, M2,
            ))
    # ONE shared encoding tuple: a section narrows only when it
    # narrows on EVERY shard (forcing hilo elsewhere is exact)
    force = []
    for i, name in enumerate(packed.SECTION_NAMES):
        kind = "i32" if wide else packed._SECTION_NARROW[name]
        if not wide:
            for secs_k in padded:
                arr = secs_k[i][1]
                enc = (packed._narrow_ident(arr) if kind == "i16"
                       else packed._narrow_delta_ref(arr))
                if enc is None:
                    kind = "hilo"
                    break
        force.append(kind)
    flats = []
    encs = widths = None
    for secs_k in padded:
        flat, encs, widths = packed._encode_sections(
            secs_k, wide, force=None if wide else tuple(force)
        )
        flats.append(flat)
    block = np.stack(flats)

    # repadded per-shard plans (assembly metadata at common buckets)
    plans = []
    row_maps = []
    for lay in layouts:
        if lay is None:
            plans.append(_empty_plan(S2, B2, M2))
            row_maps.append(np.empty(0, np.int64))
            continue
        plan, _, rows_k = lay
        mb = np.full(M2, NULLI, np.int32)
        mb[: len(plan.map_back)] = plan.map_back
        sb = np.full(B2, NULLI, np.int32)
        sb[: len(plan.seq_back)] = plan.seq_back
        sc = np.zeros(S2, np.int64)
        sc[: len(plan.seg_counts)] = plan.seg_counts
        ws = plan.win_src
        if ws is not None:
            # identity pad to the common bucket (pad slots read their
            # own — always empty — winner), keeping _assemble_result's
            # index math valid at S2
            ws2 = np.arange(S2, dtype=np.int64)
            ws2[: len(ws)] = ws
            ws = ws2
        plans.append(plan._replace(
            num_segments=S2, seq_bucket=B2, map_bucket=M2,
            map_back=mb, seq_back=sb, seg_counts=sc, win_src=ws,
        ))
        row_maps.append(np.asarray(rows_k, np.int64))

    # the boundary wire: each shard's partial SV over one shared
    # dense client table — the whole inter-chip payload of a sharded
    # round (seam/row evidence rides the tracer counters, never the
    # wire)
    client = col_arrays["client"].astype(np.int64)
    clock = col_arrays["clock"].astype(np.int64)
    valid = col_arrays["valid"].astype(bool)
    uniq = np.unique(client[valid])
    C = max(len(uniq), 1)
    svs = np.zeros((K, C), np.int64)
    n_rows = sum(len(rows_k) for rows_k in shard_rows)
    for k, rows_k in enumerate(shard_rows):
        if len(rows_k):
            r = np.searchsorted(uniq, client[rows_k])
            np.maximum.at(svs[k], r, clock[rows_k] + 1)
    # the wire narrows with the round-9 codec: SV entries are
    # clocks+1, which for real swarms fit ONE identity int16 stretch
    # (the handshake then costs 2 bytes per client per shard, a small
    # fraction of the staged upload); hi/lo below 2^31, int64 past it
    top = int(svs.max(initial=0))
    if wide or top >= (1 << 31):
        sv_mode = "wide"
        wire = svs
    elif top <= (1 << 15) - 1:
        sv_mode = "i16"
        wire = svs.astype(np.int16)
    else:
        sv_mode = "hilo"
        svh, svl = packed._split_hi_lo(svs)
        wire = np.concatenate([svh, svl], axis=1)

    return ShardPlan(
        plans=tuple(plans),
        row_maps=tuple(row_maps),
        block=block,
        wire=wire,
        encs=encs,
        num_segments=S2,
        seq_bucket=B2,
        map_bucket=M2,
        rank_rounds=rank2,
        map_rounds=map2,
        sv_clients=uniq,
        sv_host=svs,
        sv_mode=sv_mode,
        n_rows=n_rows,
        widths=dict(widths or {}),
        wide_bytes=sum(
            5 * bucket_grid(lay[0].n, floor=6) * 4 for lay in live
        ),
    )


# compiled shard_map programs, keyed on every static of the step; the
# stager thread (models/streaming) reaches this module concurrently
_STEP_CACHE: dict = {}
_CACHE_LOCK = threading.Lock()


def _get_step(splan: ShardPlan, mode: str):
    import jax

    from crdt_tpu.parallel.gossip import make_mesh, make_packed_shard_step

    K = splan.block.shape[0]
    key = (
        tuple(id(d) for d in jax.devices()[:K]), K,
        splan.num_segments, splan.seq_bucket, splan.map_bucket,
        splan.rank_rounds, splan.map_rounds, splan.encs, mode,
        splan.sv_mode, splan.wire.shape[1], len(splan.sv_clients),
    )
    with _CACHE_LOCK:
        step = _STEP_CACHE.get(key)
        if step is None:
            mesh = make_mesh(K)
            step = make_packed_shard_step(
                mesh,
                num_segments=splan.num_segments,
                seq_bucket=splan.seq_bucket,
                map_bucket=splan.map_bucket,
                rank_rounds=splan.rank_rounds,
                map_rounds=splan.map_rounds,
                encs=splan.encs,
                mode=mode,
                sv_len=max(len(splan.sv_clients), 1),
                sv_mode=splan.sv_mode,
            )
            _STEP_CACHE[key] = step
    return step


def converge_async(splan: ShardPlan):
    """ENQUEUE the sharded converge: one accounted upload of the
    [K, L] block (donated) + the boundary wire, one shard_map
    dispatch. Returns a handle for :func:`converge_fetch` — the same
    two-step seam the streaming executor drives on the single-chip
    path."""
    K = splan.block.shape[0]
    mode = packed.kernel_mode_for(splan.map_bucket, splan.seq_bucket)
    step = _get_step(splan, mode)
    tracer = get_tracer()
    with tracer.span("converge.dispatch"), \
            device_annotation("crdt.shard.dispatch"), \
            enable_x64(True):
        record_staged_widths(
            splan.widths, splan.block.nbytes, splan.wide_bytes
        )
        blk = xfer_put(splan.block, label="shard.mat")
        wire = xfer_put(splan.wire, label="shard.wire")
        out, gsv = step(blk, wire)
    if tracer.enabled:
        tracer.count("shard.dispatches")
        tracer.gauge("shard.shards", K)
        # the boundary payload crossing the mesh per round (every
        # shard's wire row travels to the other shards once in the
        # gather) — THE number the multichip gate compares against
        # the staged upload
        tracer.count("shard.boundary_bytes", int(splan.wire.nbytes))
        n_seams = sum(len(p.seam_rows) for p in splan.plans)
        if n_seams:
            tracer.count("shard.seam_rows", n_seams)
    return splan, out, gsv


def converge_fetch(handle) -> ShardResult:
    """Block on an in-flight sharded dispatch and assemble the K
    per-shard results into ONE caller-space result (the tracer's
    ``converge.fetch`` span). Fails LOUDLY when the device-side
    boundary exchange disagrees with the host staging — a shard that
    silently dropped rows or mis-decoded the wire must never
    propagate a wrong document."""
    import jax

    splan, out, gsv = handle
    S2, B2 = splan.num_segments, splan.seq_bucket
    with get_tracer().span("converge.fetch"), \
            device_annotation("crdt.shard.fetch"):
        jax.block_until_ready(out)  # execution wait, not transfer
        h = xfer_fetch(out, label="shard.out")
        gs = xfer_fetch(gsv, label="shard.sv")
    want = splan.sv_host.max(axis=0) if len(splan.sv_host) else gs
    if len(splan.sv_clients) and not np.array_equal(
            gs[: len(splan.sv_clients)], want):
        raise RuntimeError(
            "sharded converge boundary exchange diverged from the "
            "host-staged state vectors (wire codec or gather fault)"
        )
    win_parts = []
    seg_parts = []
    row_parts = []
    hard: list = []
    for k, plan in enumerate(splan.plans):
        rm = splan.row_maps[k]
        res = packed._assemble_result(plan, h[k])
        if len(rm):
            win_parts.append(np.where(
                res.win_rows >= 0,
                rm[np.clip(res.win_rows, 0, len(rm) - 1)], NULLI,
            ))
            row_parts.append(np.where(
                res.stream_row >= 0,
                rm[np.clip(res.stream_row, 0, len(rm) - 1)], NULLI,
            ))
            hard.extend(int(rm[r]) for r in res.hard_rows)
        else:
            win_parts.append(np.full(S2, NULLI, np.int64))
            row_parts.append(np.full(B2, NULLI, np.int64))
        # disjoint segment ids across shards: offset by the shard's
        # block position (values only cut runs in the assembler)
        seg_parts.append(np.where(
            res.stream_seg >= 0, res.stream_seg + k * S2, NULLI
        ))
    return ShardResult(
        win_rows=np.concatenate(win_parts),
        stream_seg=np.concatenate(seg_parts).astype(np.int32),
        stream_row=np.concatenate(row_parts),
        hard_rows=tuple(hard),
        global_sv=gs,
        sv_clients=splan.sv_clients,
    )


def converge(splan: ShardPlan) -> ShardResult:
    """Stage -> one sharded dispatch -> one fetch (the production
    two-step seam, synchronously)."""
    return converge_fetch(converge_async(splan))
