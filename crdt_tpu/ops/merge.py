"""Batched fan-in merge — the device-side ``applyUpdate`` for N replicas.

The reference merges one update at a time through the Yjs scalar loop
(crdt.js:294). This kernel takes the *union* of many replicas' op
columns in one shot (duplicates included — full-state gossip relies on
idempotent merge, SURVEY.md Q2) and computes, entirely on device:

  1. dedup by packed (client, clock) id           (sort + adjacent-diff)
  2. origin resolution                            (binary search)
  3. dense (parent, key) map segments             (lexsort + rank scan)
  4. per-segment winner                           (lww.map_winners)
  5. tombstones from delete ranges                (deleteset.apply_mask)
  6. visibility of each winner

Content values never touch the device: the kernel returns winner
*indices* into the caller's record list; materializing the JSON cache
is a host-side gather (crdt.c rebuild, crdt.js:304).

This full-width kernel serves the engine-backed merge modes and the
differential suites; the staged cold replay runs the round-12
sortless dispatch instead (``ops.packed._converge_packed_body`` over
Pallas kernels + staging-precomputed layout — see ops/packed.py).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import jax

from crdt_tpu.compat import enable_x64
import jax.numpy as jnp
import numpy as np

from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.ops import deleteset as ds_ops
from crdt_tpu.ops.device import (
    NULLI,
    lexsort,
    pack_id,
    scatter_perm,
    searchsorted_ids,
)
from crdt_tpu.ops.lww import map_winners


@partial(jax.jit, static_argnames=("num_segments", "ds_mode"))
def converge_maps(
    client,  # [N] int32
    clock,  # [N] int64
    parent_is_root,  # [N] bool
    parent_a,  # [N] int64  root name id | parent item client
    parent_b,  # [N] int64  -1           | parent item clock
    key_id,  # [N] int32  interned map key, -1 for non-map rows
    origin_client,  # [N] int32
    origin_clock,  # [N] int64
    valid,  # [N] bool
    d_client,  # [D] delete-range client
    d_start,  # [D]
    d_end,  # [D]
    num_segments: Optional[int] = None,
    ds_mode: Optional[str] = None,
):
    """Returns (order, seg, winners, winner_visible, del_mask, uniq_valid).

    All outputs except `order` live in id-sorted space; `order[i]` maps
    sorted position i back to the caller's row index.

    ``ds_mode`` (static) is the delete-mask kernel dispatch decision
    (``deleteset.mask_mode()``), computed by the HOST caller — this
    body is traced, so reading CRDT_TPU_PALLAS here would bake the
    flag into the compiled artifact (crdtlint CL702, round 16). None
    degrades to the exact jnp path, never to an ambient read.
    """
    n = client.shape[0]
    if num_segments is None:
        num_segments = n

    # -- 1. sort by packed id, drop duplicates --------------------------
    ikey = jnp.where(valid, pack_id(client, clock), jnp.int64(2**62))
    order = jnp.argsort(ikey, stable=True)
    ikey = ikey[order]
    client = client[order]
    clock = clock[order]
    parent_is_root = parent_is_root[order]
    parent_a = parent_a[order]
    parent_b = parent_b[order]
    key_id = key_id[order]
    origin_client = origin_client[order]
    origin_clock = origin_clock[order]
    valid = valid[order]
    dup = jnp.concatenate([jnp.zeros(1, bool), ikey[1:] == ikey[:-1]])
    uniq_valid = valid & ~dup

    # -- 2. origin indices in sorted space ------------------------------
    okey = pack_id(origin_client, origin_clock)
    origin_idx = searchsorted_ids(ikey, okey)

    # -- 3. dense map segments -----------------------------------------
    is_map = uniq_valid & (key_id >= 0)
    segkey_a = jnp.where(is_map, parent_a, jnp.int64(-2))
    segkey = [
        (~is_map).astype(jnp.int32),  # all non-map rows share one bucket
        parent_is_root.astype(jnp.int32),
        segkey_a,
        jnp.where(is_map, parent_b, jnp.int64(-2)),
        jnp.where(is_map, key_id, -2),
    ]
    sorder = lexsort(segkey)
    # composite change detection in segment-sorted space
    changed = jnp.zeros(n, bool)
    for k in segkey:
        ks = k[sorder]
        changed = changed | jnp.concatenate([jnp.ones(1, bool), ks[1:] != ks[:-1]])
    seg_sorted = jnp.cumsum(changed.astype(jnp.int32)) - 1
    seg = scatter_perm(sorder, seg_sorted)
    seg = jnp.where(is_map, seg, NULLI)

    # -- 4. per-segment winners ----------------------------------------
    # rows are id-sorted here (step 1), so the collapsed sibling key
    # applies. Raw client ids flow through this path, so client_bits
    # must be pack_id's true client width (23); when the collapsed key
    # does not fit an int64 at this width, map_winners falls back to
    # the lexsort internally.
    winners = map_winners(seg, client, clock, origin_idx, is_map, num_segments,
                          rows_id_ranked=True, client_bits=23)

    # -- 5. tombstones --------------------------------------------------
    del_mask = ds_ops.apply_mask_static(
        client, clock, uniq_valid, d_client, d_start, d_end,
        mode=ds_mode or "jnp",
    )

    # -- 6. winner visibility ------------------------------------------
    wc = jnp.clip(winners, 0, n - 1)
    winner_visible = (winners != NULLI) & ~del_mask[wc]

    return order, seg, winners, winner_visible, del_mask, uniq_valid


# ---------------------------------------------------------------------------
# host wrapper: records -> device columns -> materialized maps
# ---------------------------------------------------------------------------


class Interner:
    """Canonical string<->int tables shared across a merge batch."""

    def __init__(self):
        self.roots: Dict[str, int] = {}
        self.keys: Dict[str, int] = {}

    def root(self, name: str) -> int:
        return self.roots.setdefault(name, len(self.roots))

    def key(self, name: str) -> int:
        return self.keys.setdefault(name, len(self.keys))


def _pad_to(arr: np.ndarray, size: int, fill) -> np.ndarray:
    out = np.full(size, fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def records_to_columns(
    records: List[ItemRecord], interner: Interner, pad: Optional[int] = None
) -> Dict[str, np.ndarray]:
    """Symbolic records -> padded integer columns for the kernel.

    Records whose parent/key is implicit (mid-run items) must have been
    resolved first — `Engine.records_since` always emits explicit rows;
    for raw decoded updates run `resolve_parents` below.
    """
    n = len(records)
    size = pad or max(1, n)
    cols = {
        "client": np.full(size, 0, np.int32),
        "clock": np.full(size, 0, np.int64),
        "parent_is_root": np.zeros(size, bool),
        "parent_a": np.full(size, -2, np.int64),
        "parent_b": np.full(size, -2, np.int64),
        "key_id": np.full(size, -1, np.int32),
        "origin_client": np.full(size, -1, np.int32),
        "origin_clock": np.full(size, -1, np.int64),
        "valid": np.zeros(size, bool),
    }
    for i, r in enumerate(records):
        cols["client"][i] = r.client
        cols["clock"][i] = r.clock
        if r.parent_root is not None:
            cols["parent_is_root"][i] = True
            cols["parent_a"][i] = interner.root(r.parent_root)
            cols["parent_b"][i] = -1
        elif r.parent_item is not None:
            cols["parent_a"][i] = r.parent_item[0]
            cols["parent_b"][i] = r.parent_item[1]
        cols["key_id"][i] = interner.key(r.key) if r.key is not None else -1
        if r.origin is not None:
            cols["origin_client"][i] = r.origin[0]
            cols["origin_clock"][i] = r.origin[1]
        cols["valid"][i] = True
    return cols


def resolve_parents(records: List[ItemRecord]) -> List[ItemRecord]:
    """Fill implicit parent/key of mid-run records from their origins.

    Decoded wire runs omit parent info on parts 2..n (derived from the
    origin chain). The kernel needs every row explicit. Unresolvable
    records (origin outside the batch) keep parent unset and simply
    fall out of map segmentation.

    Duplicate ids (a hostile blob forging a client block twice, or
    redelivered runs) resolve against the FIRST occurrence — the
    convention every other consumer applies (``native.dedup_columns``,
    engine admission, Yjs's clock-watermark skip). The differential
    fuzz found the previous last-wins dict here splitting decoders on
    forged duplicates.
    """
    by_id: dict = {}
    for r in records:
        by_id.setdefault((r.client, r.clock), r)
    out = []
    for r in records:
        if r.parent_root is None and r.parent_item is None and r.kind != 0:
            seen = set()
            cur = r
            while (
                cur is not None
                and cur.parent_root is None
                and cur.parent_item is None
            ):
                if cur.id in seen:
                    cur = None
                    break
                seen.add(cur.id)
                nxt = cur.origin if cur.origin is not None else cur.right
                cur = by_id.get(nxt) if nxt is not None else None
            if cur is not None:
                r = ItemRecord(
                    client=r.client,
                    clock=r.clock,
                    parent_root=cur.parent_root,
                    parent_item=cur.parent_item,
                    key=cur.key if r.key is None else r.key,
                    origin=r.origin,
                    right=r.right,
                    kind=r.kind,
                    type_ref=r.type_ref,
                    content=r.content,
                )
        out.append(r)
    return out


def merge_records(
    records: List[ItemRecord],
    delete_set: Optional[DeleteSet] = None,
    interner: Optional[Interner] = None,
) -> Dict[Tuple, Tuple[Optional[ItemRecord], bool]]:
    """Full host->device->host map merge of a record union.

    Returns {(parent, key): (winning record, visible)} where parent is
    ("root", name) or ("item", client, clock).
    """
    records = resolve_parents(records)
    interner = interner or Interner()
    # pad to power-of-two buckets (floor 512) so jit compiles once per
    # bucket, not once per record count
    pad = 1 << max(9, (len(records) - 1).bit_length())
    cols = records_to_columns(records, interner, pad=pad)
    ds = delete_set or DeleteSet()
    d_client, d_start, d_end = ds_ops.ranges_to_device(ds)
    # bucket-pad ranges as well (null client -1 ranges match nothing)
    dpad = 1 << max(6, (len(d_client) - 1).bit_length()) if d_client else 0
    d_client = list(d_client) + [-1] * (dpad - len(d_client))
    d_start = list(d_start) + [-1] * (dpad - len(d_start))
    d_end = list(d_end) + [-1] * (dpad - len(d_end))
    with enable_x64(True):
        order, seg, winners, visible, _, _ = converge_maps(
            jnp.asarray(cols["client"]),
            jnp.asarray(cols["clock"]),
            jnp.asarray(cols["parent_is_root"]),
            jnp.asarray(cols["parent_a"]),
            jnp.asarray(cols["parent_b"]),
            jnp.asarray(cols["key_id"]),
            jnp.asarray(cols["origin_client"]),
            jnp.asarray(cols["origin_clock"]),
            jnp.asarray(cols["valid"]),
            jnp.asarray(np.asarray(d_client, np.int32)),
            jnp.asarray(np.asarray(d_start, np.int64)),
            jnp.asarray(np.asarray(d_end, np.int64)),
            ds_mode=ds_ops.mask_mode(),  # host-computed static (CL702)
        )
    order = np.asarray(order)
    winners = np.asarray(winners)
    visible = np.asarray(visible)
    out: Dict[Tuple, Tuple[Optional[ItemRecord], bool]] = {}
    for w, vis in zip(winners, visible):
        if w == NULLI:
            continue
        rec = records[order[w]]
        parent = (
            ("root", rec.parent_root)
            if rec.parent_root is not None
            else ("item",) + tuple(rec.parent_item)
        )
        out[(parent, rec.key)] = (rec, bool(vis))
    return out
