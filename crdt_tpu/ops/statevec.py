"""State-vector kernels.

The reference calls ``Y.encodeStateVector`` per sync and diffs docs
against peer vectors one at a time (crdt.js:239,258-260,288). Here
state vectors are dense ``[num_clients]`` next-clock arrays and the
whole replica set is processed at once:

- ``build``     items -> state vector (scatter-max of clock+1)
- ``diff_mask`` which items a peer above `sv` still needs
- ``merge``     [R, C] vectors -> componentwise max (anti-entropy join)
- ``missing``   pairwise [R, R] deficit "what does i have that j lacks"
  (pallas-tiled on TPU, exact scan elsewhere)
- ``exact_missing`` the scan path: exact in the input dtype, O(R·C)
  live memory
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from crdt_tpu.ops.device import lexsort, run_edge_lookup


def build(
    client: jnp.ndarray, clock: jnp.ndarray, valid: jnp.ndarray, num_clients: int
) -> jnp.ndarray:
    """Next-clock per client. Assumes per-client clocks are contiguous
    (integration enforces this; see ItemStore.state_vector for the
    host-side gap-honest variant). Scatter-free: sort by (client,
    next-clock) and read each client's run-tail (TPU scatters
    serialize; sorts don't)."""
    nxt = jnp.where(valid, clock + 1, 0)
    cl = jnp.where(valid, client, num_clients).astype(jnp.int32)
    order = lexsort([cl, nxt])
    last_pos, found = run_edge_lookup(cl[order], num_clients, side="right")
    vals = nxt[order][jnp.clip(last_pos, 0, cl.shape[0] - 1)]
    return jnp.where(found, vals, 0).astype(nxt.dtype)


def diff_mask(
    client: jnp.ndarray, clock: jnp.ndarray, valid: jnp.ndarray, sv: jnp.ndarray
) -> jnp.ndarray:
    """True for items NOT covered by `sv` — the delta a peer needs
    (the syncer path, crdt.js:288). A client outside the vector's
    width is one the peer has never seen: watermark 0."""
    known = client < sv.shape[0]
    watermark = jnp.where(known, sv[jnp.clip(client, 0, sv.shape[0] - 1)], 0)
    return valid & (clock >= watermark)


def merge(svs: jnp.ndarray) -> jnp.ndarray:
    """[R, C] -> [C] componentwise max."""
    return jnp.max(svs, axis=0)


def exact_missing_rows(rows: jnp.ndarray, svs: jnp.ndarray) -> jnp.ndarray:
    """[B, C] x [R, C] -> [B, R] deficit rows: what each of ``rows``'s
    replicas holds that every replica in ``svs`` lacks. The block form
    of :func:`exact_missing` — the mesh-sharded handshake computes
    each device's row block with the SAME scan body (the Pallas
    deficit tile is square-only, so sharded blocks take this exact
    path; each block is R/nd rows, so the superlinear term is already
    divided)."""

    def row(_, sv_i):
        return None, jnp.maximum(sv_i[None, :] - svs, 0).sum(axis=-1)

    _, out = jax.lax.scan(row, None, rows)
    return out


def exact_missing(svs: jnp.ndarray) -> jnp.ndarray:
    """Exact [R, R] deficit matrix in the input dtype, O(R·C) live
    memory: a scan over rows keeps one [R, C] broadcast alive per step
    instead of materializing [R, R, C] (4 GB at the north-star
    1k replicas × 1k clients)."""
    return exact_missing_rows(svs, svs)


def deficit_mode() -> str:
    """HOST-side static dispatch for :func:`missing`: ``"jnp"`` |
    ``"pallas"`` | ``"interpret"``. Traced callers (the gossip/delta
    step bodies) must compute this at factory-build time and call
    :func:`missing_static` — an env read inside the traced step bakes
    the flag into the compiled program (crdtlint CL702)."""
    from crdt_tpu.ops import pallas_kernels as _pk

    return _pk.pallas_mode()


def missing(svs: jnp.ndarray, mode: "str | None" = None) -> jnp.ndarray:
    """HOST entry for :func:`missing_static`: resolves the kernel
    mode from the env when ``mode`` is None. Never call from a traced
    body (crdtlint CL702)."""
    return missing_static(
        svs, deficit_mode() if mode is None else mode
    )


def missing_static(svs: jnp.ndarray, mode: str = "jnp") -> jnp.ndarray:
    """[R, C] -> [R, R] total clocks replica i has that j lacks.

    The full-mesh generalization of the per-peer handshake: entry
    (i, j) > 0 means i should send a delta to j.

    With ``mode`` "pallas"/"interpret" this is the tiled Pallas
    kernel (streams C through VMEM, HBM holds only the [R, R]
    result, with a traced-bound fallback to :func:`exact_missing`
    when i32 tiles could wrap); ``"jnp"`` is the exact scan. ``mode``
    is a STATIC computed on the host (:func:`deficit_mode`) — this
    function is traced-safe.
    """
    from crdt_tpu.ops import pallas_kernels as _pk

    if mode != "jnp":
        return _pk.sv_deficit_static(
            svs, interpret=(mode == "interpret")
        )
    return exact_missing(svs)
