"""State-vector kernels.

The reference calls ``Y.encodeStateVector`` per sync and diffs docs
against peer vectors one at a time (crdt.js:239,258-260,288). Here
state vectors are dense ``[num_clients]`` next-clock arrays and the
whole replica set is processed at once:

- ``build``     items -> state vector (scatter-max of clock+1)
- ``diff_mask`` which items a peer above `sv` still needs
- ``merge``     [R, C] vectors -> componentwise max (anti-entropy join)
- ``missing``   pairwise [R, R, C] "what does i have that j lacks"
"""

from __future__ import annotations

import jax.numpy as jnp


def build(
    client: jnp.ndarray, clock: jnp.ndarray, valid: jnp.ndarray, num_clients: int
) -> jnp.ndarray:
    """Next-clock per client. Assumes per-client clocks are contiguous
    (integration enforces this; see ItemStore.state_vector for the
    host-side gap-honest variant)."""
    nxt = jnp.where(valid, clock + 1, 0)
    cl = jnp.where(valid, client, 0)
    return jnp.zeros(num_clients, clock.dtype).at[cl].max(nxt, mode="drop")


def diff_mask(
    client: jnp.ndarray, clock: jnp.ndarray, valid: jnp.ndarray, sv: jnp.ndarray
) -> jnp.ndarray:
    """True for items NOT covered by `sv` — the delta a peer needs
    (the syncer path, crdt.js:288). A client outside the vector's
    width is one the peer has never seen: watermark 0."""
    known = client < sv.shape[0]
    watermark = jnp.where(known, sv[jnp.clip(client, 0, sv.shape[0] - 1)], 0)
    return valid & (clock >= watermark)


def merge(svs: jnp.ndarray) -> jnp.ndarray:
    """[R, C] -> [C] componentwise max."""
    return jnp.max(svs, axis=0)


def missing(svs: jnp.ndarray) -> jnp.ndarray:
    """[R, C] -> [R, R] total clocks replica i has that j lacks.

    The full-mesh generalization of the per-peer handshake: entry
    (i, j) > 0 means i should send a delta to j.

    On TPU this is the tiled Pallas kernel (streams C through VMEM,
    HBM holds only the [R, R] result); the jnp path materializes the
    [R, R, C] deficit tensor — 4 GB at the north-star 1k×1k scale.
    """
    from crdt_tpu.ops import pallas_kernels as _pk

    if _pk.use_pallas():
        return _pk.sv_deficit(svs)
    # deficit[i, j, c] = max(sv[i, c] - sv[j, c], 0)
    deficit = jnp.maximum(svs[:, None, :] - svs[None, :, :], 0)
    return deficit.sum(axis=-1)
