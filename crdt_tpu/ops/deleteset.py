"""Delete-set application kernel.

The reference inherits tombstone handling from Yjs delete sets inside
updates. Here a delete set is three parallel arrays of half-open
ranges; membership for every item is one packed binary search —
O(N log D) fully vectorized, no per-range host loop.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from crdt_tpu.ops.device import _CLOCK_BITS, pack_id


def mask_mode() -> str:
    """HOST-side static dispatch decision for :func:`apply_mask`:
    ``"jnp"`` | ``"pallas"`` | ``"interpret"``. Round 16 (crdtlint
    CL702): traced callers (``merge.converge_maps`` and everything
    above it) must compute this on the host and thread it down as a
    static argument — an env read inside the traced body bakes the
    flag into the compiled artifact, so a later ``CRDT_TPU_PALLAS``
    flip silently reuses the stale branch."""
    from crdt_tpu.ops import pallas_kernels as _pk

    return _pk.pallas_mode()


def ranges_to_device(ds) -> tuple:
    """Host DeleteSet -> (client[D], start[D], end[D]) numpy-ready lists."""
    cs, ss, es = [], [], []
    for client, clock, length in ds.iter_all():
        cs.append(client)
        ss.append(clock)
        es.append(clock + length)
    return cs, ss, es


def apply_mask(
    client: jnp.ndarray,  # [N]
    clock: jnp.ndarray,  # [N]
    valid: jnp.ndarray,  # [N]
    d_client: jnp.ndarray,  # [D] range clients (sorted with starts)
    d_start: jnp.ndarray,  # [D]
    d_end: jnp.ndarray,  # [D]
    mode: Optional[str] = None,
) -> jnp.ndarray:
    """HOST entry for :func:`apply_mask_static`: resolves the kernel
    mode from the env when ``mode`` is None. Never call from a traced
    body (crdtlint CL702) — traced callers use
    :func:`apply_mask_static` with a host-computed :func:`mask_mode`.
    """
    return apply_mask_static(
        client, clock, valid, d_client, d_start, d_end,
        mode=mask_mode() if mode is None else mode,
    )


def apply_mask_static(
    client: jnp.ndarray,  # [N]
    clock: jnp.ndarray,  # [N]
    valid: jnp.ndarray,  # [N]
    d_client: jnp.ndarray,  # [D] range clients (sorted with starts)
    d_start: jnp.ndarray,  # [D]
    d_end: jnp.ndarray,  # [D]
    mode: str = "jnp",
) -> jnp.ndarray:
    """True where item falls inside any delete range.

    With ``mode`` "pallas"/"interpret", small range sets go through
    the fused Pallas kernel — ranges in SMEM, one VMEM pass over the
    item columns; the jnp binary search remains the path for large D,
    non-TPU backends, and ``mode="jnp"``. The dispatch threshold is
    the measured performance crossover
    (pallas_kernels._DS_PALLAS_CROSSOVER), not the kernel's SMEM
    capacity cap. ``mode`` is a STATIC computed on the host
    (:func:`mask_mode`) — this function is traced-safe.
    """
    if d_client.shape[0] == 0:
        return jnp.zeros_like(valid)
    from crdt_tpu.ops import pallas_kernels as _pk

    if mode != "jnp" and d_client.shape[0] <= _pk._DS_PALLAS_CROSSOVER:
        return _pk.ds_mask_static(
            client, clock, valid, d_client, d_start, d_end,
            interpret=(mode == "interpret"),
        )
    # pack range starts and item ids on one axis; ranges never cross a
    # client boundary so a single searchsorted suffices
    rkey = pack_id(d_client, d_start)
    order = jnp.argsort(rkey)
    rkey = rkey[order]
    rend = pack_id(d_client[order], d_end[order])
    ikey = pack_id(client, clock)
    pos = jnp.searchsorted(rkey, ikey, side="right", method="sort") - 1
    pos_c = jnp.clip(pos, 0, rkey.shape[0] - 1)
    inside = (pos >= 0) & (ikey >= rkey[pos_c]) & (ikey < rend[pos_c])
    # same-client guard (packed compare already implies it, but be
    # explicit against clock widths near the packing limit)
    same_client = (ikey >> _CLOCK_BITS) == (rkey[pos_c] >> _CLOCK_BITS)
    return valid & inside & same_client
