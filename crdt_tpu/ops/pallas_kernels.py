"""Pallas TPU kernels for the merge hot path (SURVEY.md §7 stage 3).

Two ops earn hand-written kernels; everything else in :mod:`crdt_tpu.ops`
is already one fused XLA expression (sorts, scans, pointer doubling)
that Mosaic could not schedule better:

- ``ds_mask``   — delete-set membership for every item. The jnp path
  is a packed binary search (O(N log D)); this kernel is the fused
  dense compare (O(N·D)) that wins when D is small (the common case:
  a transaction's delete set holds a handful of ranges) because the
  ranges live in SMEM and the item columns stream through VMEM once —
  no [N, D] broadcast ever hits HBM.
- ``sv_deficit`` — the pairwise anti-entropy plan ``missing`` over
  [R, C] state vectors. The jnp path materializes the full [R, R, C]
  deficit tensor in HBM (4 GB at the north-star 1k replicas × 1k
  clients); this kernel tiles (i, j, c-chunk) over the grid so HBM
  holds only the [R, R] result and VMEM only (tile × chunk) blocks.

Both kernels run in interpret mode off-TPU so the differential tests
(tests/test_pallas.py) exercise the same code path on the CPU mesh.

Dtype strategy: the framework's clocks are int64 with < 2**40 packing
headroom (ops/device.py), but Mosaic wants 32-bit lanes. ``ds_mask``
is EXACT over the full 2**40 range via hi/lo split compares (clock ->
(clock >> 31, clock & 0x7fffffff), lexicographic i32 compares).
``sv_deficit`` subtracts the per-column minimum before narrowing —
deficits are invariant to per-column shifts, so the i32 magnitude
limit applies to the clock SPREAD between replicas (how far apart two
replicas' views are), not to absolute clock values; per-pair deficit
totals likewise accumulate in i32. The envelope is ENFORCED, not
assumed: a traced bound check routes batches whose spread/total could
reach 2**31 to the exact int64 scan fallback (lax.cond, so the check
works under jit/shard_map where gossip calls it).

The reference has no analogue of any of this — its merge is the
scalar Yjs integrate loop (/root/reference/crdt.js:294) and its sync
handshake diffs one peer at a time (crdt.js:286-291).
"""

from __future__ import annotations

import functools
import os

import jax

from crdt_tpu.compat import enable_x64
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# SMEM budget for the delete-range quintuple (5 arrays × _DS_MAX_RANGES
# int32) — a hard capacity limit, NOT the dispatch heuristic.
_DS_MAX_RANGES = 2048

# Dispatch crossover, measured on a real chip (N=131072 items, jitted
# callers like converge_maps): both paths are dispatch-bound ~20-30us
# up to D=64; beyond that the kernel's sequential D-step fori_loop
# only loses ground to the searchsorted path (1.7x slower at D=2048).
# Callers use pallas for D <= this and the jnp binary search above it.
_DS_PALLAS_CROSSOVER = 64

_LANES = 128
_DS_BLOCK_ROWS = 64  # rows of 128 lanes per program: 8192 items

_LO_BITS = 31
_LO_MASK = (1 << _LO_BITS) - 1


def backend() -> str:
    return jax.default_backend()


def use_pallas() -> bool:
    """Trace-time dispatch: pallas on TPU, jnp elsewhere.

    CRDT_TPU_PALLAS=0 forces jnp everywhere; =interpret forces the
    pallas kernels in interpreter mode (how the CPU-mesh tests run);
    =1 forces the pallas kernels — compiled on TPU, interpreter mode
    on any other backend (off-TPU there is nothing for Mosaic to
    compile, so =1 and =interpret coincide there).
    """
    flag = os.environ.get("CRDT_TPU_PALLAS", "auto")
    if flag == "0":
        return False
    if flag in ("1", "interpret"):
        return True
    return backend() == "tpu"


def _interpret() -> bool:
    if os.environ.get("CRDT_TPU_PALLAS") == "interpret":
        return True
    return backend() != "tpu"


def pallas_mode() -> str:
    """HOST-side dispatch decision: ``"jnp"`` | ``"pallas"`` |
    ``"interpret"``. Round 16: the env read must happen on the host,
    once per call, and flow DOWN into traced bodies as a static
    argument — ``use_pallas()``/``_interpret()`` called inside a
    ``jax.jit``/``lax.cond`` body bake the flag into the compiled
    artifact, so a later ``CRDT_TPU_PALLAS`` flip silently reuses the
    stale branch until an unrelated shape change recompiles
    (crdtlint CL702; :func:`converge_kernel_mode` is the same
    discipline with the width guard added)."""
    if not use_pallas():
        return "jnp"
    return "interpret" if _interpret() else "pallas"


def _pad_len(n: int, mult: int) -> int:
    return max(mult, ((n + mult - 1) // mult) * mult)


def _split_hi_lo(x: jnp.ndarray) -> tuple:
    """int64 -> (hi, lo) int32 with lexicographic order preserved for
    non-negative values; negatives (null sentinels) map to (-1, -1)."""
    hi = jnp.where(x < 0, -1, x >> _LO_BITS).astype(jnp.int32)
    lo = jnp.where(x < 0, -1, x & _LO_MASK).astype(jnp.int32)
    return hi, lo


# ---------------------------------------------------------------------------
# delete-set membership
# ---------------------------------------------------------------------------


def _ds_mask_kernel(
    dcl_ref, dsh_ref, dsl_ref, deh_ref, delo_ref, cl_ref, ckh_ref, ckl_ref, out_ref
):
    """One program = one (rows, 128) item block vs ALL ranges.

    Ranges sit in SMEM (scalar memory) and are walked with a
    fori_loop; each step is a full-block VPU compare, so the work is
    D vector ops over an 8192-lane block with zero HBM traffic beyond
    streaming the item columns once. Clocks are (hi, lo) i32 pairs;
    the lexicographic compares are exact over the full int64 range.
    """
    cl = cl_ref[:]
    ckh = ckh_ref[:]
    ckl = ckl_ref[:]
    acc = jnp.zeros(cl.shape, jnp.int32)
    num_ranges = dcl_ref.shape[0]

    def body(d, acc):
        dc = dcl_ref[d]
        sh, sl = dsh_ref[d], dsl_ref[d]
        eh, el = deh_ref[d], delo_ref[d]
        ge_start = (ckh > sh) | ((ckh == sh) & (ckl >= sl))
        lt_end = (ckh < eh) | ((ckh == eh) & (ckl < el))
        hit = (cl == dc) & ge_start & lt_end
        return acc | hit.astype(jnp.int32)

    # int32 bounds: the framework traces under x64, and an i64 loop
    # index fails Mosaic legalization
    out_ref[:] = jax.lax.fori_loop(jnp.int32(0), jnp.int32(num_ranges), body, acc)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _ds_mask_call(cl2, ckh2, ckl2, dcl, dsh, dsl, deh, delo, interpret):
    rows = cl2.shape[0]
    grid = (rows // _DS_BLOCK_ROWS,)
    block = (_DS_BLOCK_ROWS, _LANES)
    # trace with x64 off: the framework traces under x64 and the
    # promoted i64 literals (index maps, reductions) fail Mosaic
    # legalization; every input here is already explicit int32
    with enable_x64(False):
        return pl.pallas_call(
            _ds_mask_kernel,
            out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
            grid=grid,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 5
            + [
                pl.BlockSpec(block, lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec(block, lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec(block, lambda i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec(block, lambda i: (i, 0), memory_space=pltpu.VMEM),
            interpret=interpret,
        )(dcl, dsh, dsl, deh, delo, cl2, ckh2, ckl2)


def ds_mask(
    client: jnp.ndarray,  # [N] int32
    clock: jnp.ndarray,  # [N] int64/int32
    valid: jnp.ndarray,  # [N] bool
    d_client: jnp.ndarray,  # [D] int32
    d_start: jnp.ndarray,  # [D] int64/int32
    d_end: jnp.ndarray,  # [D] int64/int32
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """HOST entry for :func:`ds_mask_static`: resolves the kernel
    mode from the env when ``interpret`` is None. Never call from a
    traced body — the env read would bake into the compiled artifact
    (crdtlint CL702); traced callers use :func:`ds_mask_static` with
    a host-computed static."""
    return ds_mask_static(
        client, clock, valid, d_client, d_start, d_end,
        _interpret() if interpret is None else interpret,
    )


def ds_mask_static(
    client: jnp.ndarray,  # [N] int32
    clock: jnp.ndarray,  # [N] int64/int32
    valid: jnp.ndarray,  # [N] bool
    d_client: jnp.ndarray,  # [D] int32
    d_start: jnp.ndarray,  # [D] int64/int32
    d_end: jnp.ndarray,  # [D] int64/int32
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas counterpart of :func:`crdt_tpu.ops.deleteset.apply_mask`.

    Returns the same [N] bool mask, exact over the framework's full
    clock range. Requires D <= _DS_MAX_RANGES; callers dispatch via
    :func:`use_pallas` and fall back to the jnp path otherwise.
    ``interpret`` is a STATIC, host-computed on the other side of the
    trace boundary — this function is traced-safe (no ambient reads).
    """
    n = client.shape[0]
    d = d_client.shape[0]
    if d == 0:
        return jnp.zeros_like(valid)
    if d > _DS_MAX_RANGES:
        raise ValueError(f"ds_mask: {d} ranges > SMEM budget {_DS_MAX_RANGES}")

    npad = _pad_len(n, _DS_BLOCK_ROWS * _LANES)
    ckh, ckl = _split_hi_lo(clock.astype(jnp.int64))
    # padded item slots get client/clock -1: a real range never has
    # client -1, and a null (-1) range filler's half-open compare
    # rejects even the (-1, -1) padded clock (start == end)
    cl = jnp.full(npad, -1, jnp.int32).at[:n].set(client.astype(jnp.int32))
    ch = jnp.full(npad, -1, jnp.int32).at[:n].set(ckh)
    cg = jnp.full(npad, -1, jnp.int32).at[:n].set(ckl)
    dsh, dsl = _split_hi_lo(d_start.astype(jnp.int64))
    deh, delo = _split_hi_lo(d_end.astype(jnp.int64))

    out2 = _ds_mask_call(
        cl.reshape(-1, _LANES),
        ch.reshape(-1, _LANES),
        cg.reshape(-1, _LANES),
        d_client.astype(jnp.int32),
        dsh,
        dsl,
        deh,
        delo,
        interpret,
    )
    return out2.reshape(-1)[:n].astype(bool) & valid


# ---------------------------------------------------------------------------
# pairwise state-vector deficit (the anti-entropy plan)
# ---------------------------------------------------------------------------

_DEF_TI = 8  # i-tile (sublane batch)
_DEF_TJ = _LANES  # j-tile
_DEF_TC = _LANES  # C chunk per grid step


def _sv_deficit_kernel(svi_ref, svj_ref, out_ref):
    """One program = an (8 × 128) tile of [R, R] for ONE 128-wide C
    chunk; the innermost grid dimension walks C and accumulates into
    the same output tile (index map ignores the chunk index).

    deficit[i, j] = sum_c max(sv[i, c] - sv[j, c], 0): the broadcasts
    ride the two non-lane axes (i over the batch dim, j over the
    second-minor dim) so no relayout is needed.
    """

    @pl.when(pl.program_id(2) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    a = svi_ref[:]  # [TI, TC]
    b = svj_ref[:]  # [TJ, TC]
    diff = a[:, None, :] - b[None, :, :]  # [TI, TJ, TC]
    out_ref[:] += jnp.maximum(diff, 0).sum(axis=2)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _sv_deficit_call(svs, interpret):
    r, c = svs.shape
    grid = (r // _DEF_TI, r // _DEF_TJ, c // _DEF_TC)
    with enable_x64(False):  # see _ds_mask_call
        return pl.pallas_call(
            _sv_deficit_kernel,
            out_shape=jax.ShapeDtypeStruct((r, r), jnp.int32),
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (_DEF_TI, _DEF_TC),
                    lambda i, j, k: (i, k),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (_DEF_TJ, _DEF_TC),
                    lambda i, j, k: (j, k),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (_DEF_TI, _DEF_TJ), lambda i, j, k: (i, j), memory_space=pltpu.VMEM
            ),
            interpret=interpret,
        )(svs, svs)


def sv_deficit(svs: jnp.ndarray,
               interpret: Optional[bool] = None) -> jnp.ndarray:
    """HOST entry for :func:`sv_deficit_static`: resolves the kernel
    mode from the env when ``interpret`` is None. Never call from a
    traced body (crdtlint CL702) — the read would bake into the
    compiled artifact; traced callers use :func:`sv_deficit_static`
    with a host-computed static."""
    return sv_deficit_static(
        svs, _interpret() if interpret is None else interpret
    )


def sv_deficit_static(svs: jnp.ndarray,
                      interpret: bool = False) -> jnp.ndarray:
    """Pallas counterpart of :func:`crdt_tpu.ops.statevec.missing`.

    [R, C] state vectors -> [R, R] total clocks i holds that j lacks,
    without the [R, R, C] HBM intermediate the jnp path builds.

    Exactness: deficits are invariant to subtracting any per-column
    offset, so the per-column minimum is removed before narrowing to
    i32 — absolute clocks may use the full int64 range. The i32 tile
    math is exact while the summed per-column spread stays below
    2**31; that bound is CHECKED on the traced values and batches
    beyond it (a replica lagging another by ~2e9 ops) fall back to
    the exact scan (:func:`crdt_tpu.ops.statevec.exact_missing`), so
    the anti-entropy plan is never silently wrapped.
    """
    from crdt_tpu.ops import statevec

    r, c = svs.shape
    centered = svs.astype(jnp.int64) - jnp.min(svs, axis=0, keepdims=True).astype(
        jnp.int64
    )
    # sum of per-column max spreads bounds every pair's deficit AND
    # (since all terms are >= 0) every single column's spread
    safe = jnp.sum(jnp.max(centered, axis=0)) < jnp.int64(2**31)

    def _pallas(cent):
        rpad = _pad_len(r, _DEF_TJ)
        cpad = _pad_len(c, _DEF_TC)
        # zero-padding is semantically neutral: phantom clients
        # contribute max(0-0, 0)=0, phantom replicas produce rows/cols
        # sliced away
        p = jnp.zeros((rpad, cpad), jnp.int32)
        p = p.at[:r, :c].set(cent.astype(jnp.int32))
        # `interpret` is the host-computed static from the wrapper:
        # an env read HERE would run at trace time inside the
        # lax.cond branch and bake the flag (crdtlint CL702)
        out = _sv_deficit_call(p, interpret)
        return out[:r, :r].astype(svs.dtype)

    def _exact(cent):
        return statevec.exact_missing(cent).astype(svs.dtype)

    return jax.lax.cond(safe, _pallas, _exact, centered)


# ---------------------------------------------------------------------------
# converge hot-path kernels (round 12, the sort diet): segmented
# Lamport argmax + document-order scatter
#
# The fused converge dispatch (ops/packed.py) stages its rows GROUPED
# by dense segment id — staging is a host radix pass that any columnar
# store pays at ingest — so the two primitives that used to burn the
# dispatch budget on global XLA argsorts become one-VMEM-pass kernels:
#
# - ``seg_argmax_scan``: per-run argmax over Lamport (client, position)
#   keys for CONTIGUOUS runs (child groups of the LWW map chain
#   forest, root-children runs per segment). A Hillis–Steele segmented
#   scan over the whole block resident in VMEM: log2(N) rounds of flat
#   rolls + selects, ZERO random gathers and ZERO sorts — the
#   replacement for the collapsed-key argsort + run-edge sort chain in
#   ``lww.map_winners``.
# - ``stream_scatter``: document-order assembly. Per-segment DFS ranks
#   are a permutation within each segment, so with contiguous segments
#   the final stream is out[offset[seg] + rank] = row — a permutation
#   scatter into VMEM, replacing the global ``argsort(skey2)``
#   document-order sort.
#
# Both run in interpret mode off-TPU (the tier-1 differential suite,
# tests/test_sort_diet.py) against the jnp oracles below, which are
# the SAME algorithms expressed as XLA ops (associative_scan /
# .at[].set) — the production fallback for non-TPU backends and for
# blocks past the VMEM width guard. Callers pass the dispatch decision
# as a STATIC mode argument (see :func:`converge_kernel_mode`) so an
# env-var flip between calls recompiles instead of reusing a stale
# cached branch.
# ---------------------------------------------------------------------------

# whole-block-in-VMEM width guard for the scan/scatter kernels: above
# this the jnp oracle path runs (a 1.6M-row scale shard would not fit
# the scan's working set in 16 MB of VMEM). Like _DS_PALLAS_CROSSOVER
# this is a dispatch bound, not a correctness bound.
_SCAN_PALLAS_MAX = 1 << 17

_SUBLANES = 8  # int32 min tile is (8, 128): pad rows to a multiple


def converge_kernel_mode(*widths: int) -> str:
    """STATIC dispatch decision for the fused converge's kernels:
    ``"pallas"`` (compiled), ``"interpret"`` (CPU-mesh tests), or
    ``"jnp"`` (kernels off, or any block past the VMEM width guard).
    Computed by the host wrapper per call and passed down as a static
    argument, so CRDT_TPU_PALLAS flips take effect on the next call
    instead of silently reusing a stale compiled branch."""
    if not use_pallas() or max(widths, default=0) > _SCAN_PALLAS_MAX:
        return "jnp"
    return "interpret" if _interpret() else "pallas"


def _rows2d(x: jnp.ndarray):
    """Flat [N] -> (R, 128) VMEM layout, R a multiple of the int32
    sublane tile."""
    n = x.shape[0]
    npad = _pad_len(n, _SUBLANES * _LANES)
    return jnp.pad(x, (0, npad - n), constant_values=-1).reshape(-1, _LANES)


def _flat_roll(x, s: int):
    """x[i - s] at flat position i of a row-major (R, 128) block
    (positions < s receive wrapped garbage — callers mask)."""
    a, b = s // _LANES, s % _LANES
    if b == 0:
        return pltpu.roll(x, shift=a, axis=0)
    y1 = pltpu.roll(pltpu.roll(x, shift=a, axis=0), shift=b, axis=1)
    y2 = pltpu.roll(pltpu.roll(x, shift=a + 1, axis=0), shift=b, axis=1)
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    return jnp.where(lane >= b, y1, y2)


def _seg_argmax_kernel(cl_ref, fl_ref, arg_ref):
    """Segmented inclusive argmax scan, whole block in VMEM.

    State per position: (best client, best arg, boundary-seen flag).
    Round k combines each position with the one 2^k before it unless a
    run boundary lies between them — the textbook segmented-scan
    operator, with the argmax tie rule "equal client keeps the EARLIER
    position" (clock ascends within a (run, client) group, and the
    sibling rule wants minimum clock at equal client — exactly the
    run-tail the sort-based path selects). log2(N) rounds of flat
    rolls + selects: no sorts, no gathers, no HBM round trips.
    """
    cl = cl_ref[:]
    fl = fl_ref[:]
    shape = cl.shape
    n = shape[0] * shape[1]
    arg = (
        jax.lax.broadcasted_iota(jnp.int32, shape, 0) * jnp.int32(_LANES)
        + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    )
    flat = arg  # flat position index (reused for the wrap mask)
    best_c, best_a, seen = cl, arg, fl
    s = 1
    while s < n:
        ok = flat >= s
        p_c = jnp.where(ok, _flat_roll(best_c, s), jnp.int32(-1))
        p_a = jnp.where(ok, _flat_roll(best_a, s), jnp.int32(0))
        p_f = jnp.where(ok, _flat_roll(seen, s), jnp.int32(1))
        take_prev = (seen == 0) & (
            (p_c > best_c) | ((p_c == best_c) & (p_a < best_a))
        )
        best_c = jnp.where(take_prev, p_c, best_c)
        best_a = jnp.where(take_prev, p_a, best_a)
        seen = seen | p_f
        s <<= 1
    arg_ref[:] = best_a


def seg_argmax_scan_jnp(client: jnp.ndarray,
                        flags: jnp.ndarray) -> jnp.ndarray:
    """jnp oracle of the segmented argmax scan: the identical
    segmented-scan operator via ``lax.associative_scan`` (log-depth
    shifted selects — still sortless; the Pallas kernel wins by
    keeping the whole working set in VMEM)."""
    n = client.shape[0]
    arg0 = jnp.arange(n, dtype=jnp.int32)

    def comb(a, b):
        c1, a1, f1 = a
        c2, a2, f2 = b
        blocked = f2 != 0
        take_prev = (~blocked) & (
            (c1 > c2) | ((c1 == c2) & (a1 < a2))
        )
        return (
            jnp.where(take_prev, c1, c2),
            jnp.where(take_prev, a1, a2),
            f1 | f2,
        )

    _, arg, _ = jax.lax.associative_scan(
        comb, (client.astype(jnp.int32), arg0, flags.astype(jnp.int32))
    )
    return arg


def seg_argmax_scan(client: jnp.ndarray, flags: jnp.ndarray, *,
                    mode: str) -> jnp.ndarray:
    """Per-position inclusive argmax over contiguous runs.

    ``client`` [N] int32 (the Lamport major key; -1 on padding rows),
    ``flags`` [N] int32 (1 = run start; padding rows are their own
    runs). Returns [N] int32: the position holding the run-prefix
    argmax — read at a run's END it is the run's argmax, i.e. the
    sibling-sorted run TAIL of the sort-based path. ``mode`` is the
    static :func:`converge_kernel_mode` decision.
    """
    if mode == "jnp":
        return seg_argmax_scan_jnp(client, flags)
    n = client.shape[0]
    cl2 = _rows2d(client.astype(jnp.int32))
    # _rows2d pads with -1: padded flag slots normalize to 1, so the
    # pad tail forms its own runs and never leaks into a real one
    fl2 = jnp.where(_rows2d(flags.astype(jnp.int32)) != 0, 1, 0).astype(
        jnp.int32
    )
    with enable_x64(False):
        out = pl.pallas_call(
            _seg_argmax_kernel,
            out_shape=jax.ShapeDtypeStruct(cl2.shape, jnp.int32),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=(mode == "interpret"),
        )(cl2, fl2)
    return out.reshape(-1)[:n]


NULL_I32 = -1


def _stream_scatter_kernel(pos_ref, out_ref):
    """Permutation scatter: out[pos[i]] = i for in-range targets.

    One program, whole block in VMEM; the fori_loop walks the input
    once doing dynamic scalar stores — sequential, but each store is a
    VMEM write with zero HBM traffic, and the targets are unique by
    construction (per-segment DFS ranks + exclusive segment offsets),
    so there is no ordering hazard. Rows routed past the output width
    (invalid / padding) fall out via the bounds predicate.
    """
    out_ref[:] = jnp.full(out_ref.shape, NULL_I32, jnp.int32)
    n_in = pos_ref.shape[0] * pos_ref.shape[1]
    limit = jnp.int32(out_ref.shape[0] * out_ref.shape[1])
    # explicit i32 scalars: the kernel body may be traced outside the
    # wrapper's enable_x64(False) window, where a weak python literal
    # promotes to i64 and breaks the i32 index arithmetic
    lanes = jnp.int32(_LANES)

    def body(i, _):
        p = pos_ref[i // lanes, i % lanes]

        @pl.when((p >= 0) & (p < limit))
        def _():
            out_ref[p // lanes, p % lanes] = i

        return jnp.int32(0)  # explicit: a weak `0` promotes to i64
        #                      under an x64-tracing caller and breaks
        #                      the loop carry

    jax.lax.fori_loop(jnp.int32(0), jnp.int32(n_in), body, jnp.int32(0))


def stream_scatter_jnp(pos: jnp.ndarray, n_out: int) -> jnp.ndarray:
    """jnp oracle of the document-order scatter: one XLA scatter with
    out-of-range targets dropped. Targets are unique by construction
    (rank + exclusive offset), so drop-mode scatter is deterministic
    here. Negative targets are redirected PAST the output before the
    scatter: ``.at[-1]`` would wrap to the last slot (jnp negative
    indexing), not drop."""
    idx = jnp.arange(pos.shape[0], dtype=jnp.int32)
    tgt = jnp.where(pos >= 0, pos, jnp.int32(n_out))
    return jnp.full(n_out, NULL_I32, jnp.int32).at[tgt].set(
        idx, mode="drop"
    )


def stream_scatter(pos: jnp.ndarray, n_out: int, *,
                   mode: str) -> jnp.ndarray:
    """Document-order assembly: ``out[pos[i]] = i`` over int32
    positions (targets outside [0, n_out) are dropped — callers route
    invalid rows there). Returns [n_out] int32 with -1 holes. ``mode``
    is the static :func:`converge_kernel_mode` decision."""
    if mode == "jnp":
        return stream_scatter_jnp(pos, n_out)
    pos2 = _rows2d(pos.astype(jnp.int32))
    opad = _pad_len(n_out, _SUBLANES * _LANES)
    with enable_x64(False):
        out = pl.pallas_call(
            _stream_scatter_kernel,
            out_shape=jax.ShapeDtypeStruct(
                (opad // _LANES, _LANES), jnp.int32
            ),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=(mode == "interpret"),
        )(pos2)
    return out.reshape(-1)[:n_out]
