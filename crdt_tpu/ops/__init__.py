"""Device (JAX/XLA/Pallas) kernels for the CRDT merge hot path.

The reference's merge hot loop is ``Y.applyUpdate`` (crdt.js:294) —
a scalar pointer-chasing integrate per item. Here the same semantics
run as vectorized kernels over columnar op tensors:

- ``lww``       map winner selection (segmented scatter-max over the
                origin tree + pointer doubling to the chain tail)
- ``statevec``  state-vector construction / diff masks / merges
- ``deleteset`` tombstone application from delete ranges
- ``merge``     end-to-end batched fan-in merge (dedup -> segment ->
                winner -> visibility) for N-replica convergence
"""

# Packed item IDs ((client, clock) in one sortable int64 word) need
# 64-bit integers on device. The library never flips the global
# jax_enable_x64 flag (that would change dtypes for the whole host
# application); public wrappers scope it with
# jax.experimental.enable_x64, and callers invoking the jitted kernels
# directly must do the same (tests enable it harness-wide).
from crdt_tpu.ops import deleteset, lww, merge, statevec  # noqa: F401
