"""Map last-writer-wins winner kernel.

Semantics being computed (must match ``Engine`` exactly): a map key's
visible entry is the **tail of its YATA key chain** — the chain is a
tree (each item's origin is an earlier item of the same key or null),
and the final order is the depth-first traversal. Sibling order under
one parent follows the Yjs conflict scan: ascending client id, and
within one client id DESCENDING clock — a later same-client sibling
with the same (null) origin and right origin hits the scan's break
rule and is placed BEFORE its predecessor (the reference's engine
inherits this from yjs Item.integrate). The tail is therefore the node
reached from the virtual root by repeatedly stepping to the
**(max client, min clock)** child.

Kernel shape (all vectorized, no data-dependent Python control flow):

1. sort items by (parent slot, packed (client, ~clock)) — each
   parent's run-tail in this order is its last child; one
   searchsorted over the run boundaries builds the dense last-child
   table (scatter-free: XLA TPU scatters serialize, sorts don't).
2. pointer doubling over the last-child function -> rightmost
   descendant (= chain tail) of every node in O(log depth) rounds.
3. gather per-segment winner from each segment's virtual root.

This is the "segmented argmax over Lamport clocks" of the north star
(BASELINE.json), done exactly: a plain per-key argmax over (clock,
client) would disagree with Yjs whenever concurrent branches of
different depths exist; the tree argmax + pointer doubling is both
vectorized and exact.

Round 12 (the sort diet): the staged cold replay no longer routes
here — staging groups each node's children into contiguous runs and
the Pallas segmented argmax scan
(``ops.pallas_kernels.seg_argmax_scan``) reads every run's last child
in one VMEM pass, keeping only the chain doubling (step 2) at
map-bucket width. THIS kernel remains the engine of the general merge
(``ops.merge.converge_maps``) and the incremental splice
(``ops.packed._converge_core``), and the oracle the scan is
differential-tested against.
"""

from __future__ import annotations

import jax.numpy as jnp

from crdt_tpu.ops.device import (
    _CLOCK_BITS,
    NULLI,
    lexsort,
    pointer_double,
    run_edge_lookup,
)


def map_winners(
    seg: jnp.ndarray,  # [N] int32 dense segment id per item (-1 = not a map item)
    client: jnp.ndarray,  # [N] int32
    clock: jnp.ndarray,  # [N] int64 (may be None when rows_id_ranked fits)
    origin_idx: jnp.ndarray,  # [N] int32 index of origin item, NULLI if none
    valid: jnp.ndarray,  # [N] bool
    num_segments: int,  # static
    rows_id_ranked: bool = False,  # static
    chain_rounds: int | None = None,  # static
    client_bits: int = 22,  # static
):
    """Winner item index per segment (NULLI for empty segments).

    ``origin_idx`` must point within the same segment (the engine
    guarantees this for map chains); cross-segment or missing origins
    are treated as segment roots, matching host integration of items
    whose origins were garbage-collected.

    ``rows_id_ranked`` (static): every caller in this package invokes
    the kernel AFTER the shared id sort, where row position is already
    the (client, clock) rank — so within one client, DESCENDING clock
    is exactly DESCENDING row index and the three-part sibling key
    (parent, client, clock desc) collapses into one int64, replacing
    the two-pass lexsort with a single argsort (when the static widths
    fit; the lexsort remains as the wide fallback — callers staging
    dense client ranks can tighten ``client_bits`` so the collapsed
    key still fits at million-row widths, and may then pass
    ``clock=None``). ``chain_rounds`` (static) caps the tail pointer
    doubling when the caller bounded the deepest key chain at staging.
    """
    n = client.shape[0]
    m = n + num_segments  # item nodes + one virtual root per segment
    is_map = valid & (seg >= 0)

    # child -> parent edges; roots hang off their segment's virtual root
    origin_ok = (origin_idx >= 0) & is_map
    origin_seg = jnp.where(origin_ok, seg[jnp.clip(origin_idx, 0, n - 1)], NULLI)
    same_seg = origin_ok & (origin_seg == seg)
    parent = jnp.where(same_seg, origin_idx, n + seg)
    parent = jnp.where(is_map, parent, m)  # overflow slot for non-map rows

    # last child per node = max child by (client, inverted clock) —
    # computed scatter-free: sort children by (parent, key), then each
    # parent's run-tail IS its last child (see run_edge_lookup)
    pbits = int(m).bit_length()
    qbits = int(max(n - 1, 1)).bit_length()
    if rows_id_ranked and pbits + client_bits + qbits <= 63:
        idx_desc = (n - 1) - jnp.arange(n, dtype=jnp.int64)
        key = (
            (parent.astype(jnp.int64) << (client_bits + qbits))
            | (client.astype(jnp.int64) << qbits)
            | idx_desc
        )
        corder = jnp.argsort(key, stable=True)
    else:
        if clock is None:
            raise ValueError(
                "map_winners needs clock when the collapsed id-ranked "
                "key does not fit (stage() must pre-check the widths)"
            )
        inv_clock = ((1 << _CLOCK_BITS) - 1) - clock.astype(jnp.int64)
        pack = (client.astype(jnp.int64) << _CLOCK_BITS) | inv_clock
        corder = lexsort([parent, pack])
    p_sorted = parent[corder]
    last_pos, _ = run_edge_lookup(p_sorted, m, side="right")
    child_idx = jnp.where(
        last_pos >= 0, corder[jnp.clip(last_pos, 0, n - 1)], NULLI
    ).astype(jnp.int32)

    # last-child function with self-loops at leaves
    f = jnp.where(child_idx >= 0, child_idx, jnp.arange(m, dtype=jnp.int32))

    tail = pointer_double(f, max_iters=chain_rounds)

    root_tail = tail[n:]
    winners = jnp.where(
        root_tail == jnp.arange(n, n + num_segments, dtype=jnp.int32),
        NULLI,
        root_tail,
    )
    return winners
