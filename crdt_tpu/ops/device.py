"""Shared device-side helpers: ID packing, lexsort, dense ranks.

Conventions for all kernels in this package:

- Inputs are flat int32/int64/bool arrays of equal length N (static
  shape; callers pad with ``valid=False`` rows).
- Item IDs (client, clock) are packed into one int64 so sorting,
  dedup, and binary search are single-key operations. Limits:
  client < 2**22, clock < 2**40 — far beyond the workloads the
  framework targets (the north-star config is 1k replicas x 100k ops).
- ``NULLI = -1`` marks absent references; packed null IDs sort below
  every real ID.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager

import jax
import jax.numpy as jnp

# persistent XLA compilation cache for the PRODUCT, not just the bench
# (VERDICT r3: first-run ingest of a large doc was dominated by one
# giant-bucket compile that later runs should never pay again). The
# default path is PER-USER: a fixed world-writable /tmp path would let
# another local user pre-seed compiled artifacts this process would
# load (cache poisoning). Set CRDT_TPU_COMPILE_CACHE="" to disable,
# or point it elsewhere.
def _safe_cache_dir(suffix: str = "") -> str:
    """Owner-only cache directory, ownership-verified: a
    pre-created attacker-owned dir in shared /tmp must never be
    adopted (its compiled artifacts would be deserialized and run).
    ``suffix`` separates per-backend caches — XLA:CPU AOT artifacts
    cached under one flag configuration can SIGILL when loaded under
    another, so CPU-pinned consumers (the test suite) must never
    share a directory with TPU processes. Returns "" when no safe
    directory can be established."""
    path = os.environ.get("CRDT_TPU_COMPILE_CACHE")
    if path == "":
        return ""  # explicitly disabled
    explicit = path is not None
    if path is None:
        import tempfile

        path = os.path.join(
            tempfile.gettempdir(), f"crdt_tpu_jax_cache_{os.getuid()}"
        )
    path += suffix
    try:
        if explicit:
            # a user-configured path may deliberately be a symlink
            # (e.g. onto a larger disk); the planting attack needs the
            # PREDICTABLE default name in shared /tmp, so here we
            # follow links but still require the resolved directory
            # be owner-only
            os.makedirs(path, mode=0o700, exist_ok=True)
            st = os.stat(path)
        else:
            # default shared-/tmp path: never create through or adopt
            # a pre-planted symlink. mkdir (unlike makedirs+stat)
            # fails on an existing symlink instead of following it,
            # so a dangling link cannot make us create the attacker's
            # target; lstat then refuses the link itself (advisor
            # finding, round 4: the previous stat-based check was a
            # symlink TOCTOU).
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, mode=0o700, exist_ok=True)
            try:
                os.mkdir(path, 0o700)
            except FileExistsError:
                pass
            st = os.lstat(path)
            import stat as _stat

            if not _stat.S_ISDIR(st.st_mode):
                return ""  # symlink or non-directory: refuse
        if st.st_uid != os.getuid() or (st.st_mode & 0o022):
            return ""  # foreign or group/world-writable: refuse
    except OSError:
        return ""
    return path


_cache_dir = _safe_cache_dir()
# never clobber a host application's own cache configuration: this is
# a library — only fill the knob when it is unset. CPU-pinned
# processes (tests, the multichip dry run) skip the cache entirely:
# XLA:CPU AOT artifacts cached under one flag/feature configuration
# load under another with a SIGILL warning, CPU compiles are cheap,
# and the cache's whole value is the expensive TPU compiles.
def _cpu_pinned() -> bool:
    """Best-effort CPU-backend detection WITHOUT initializing a
    backend (resolving for real could hang on a dead TPU tunnel).
    Machines with no accelerator and no pin keep the cache — their
    artifacts are at least self-consistent per configuration."""
    env = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    cfg = (getattr(jax.config, "jax_platforms", None) or "").strip().lower()
    return env == "cpu" or cfg == "cpu"


if (
    _cache_dir
    and not _cpu_pinned()
    and not getattr(jax.config, "jax_compilation_cache_dir", None)
):
    try:
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.5
        )
    except Exception:  # older jaxlib without the knob: run uncached
        pass

NULLI = -1
_CLOCK_BITS = 40


# ---------------------------------------------------------------------------
# host<->device transfer seam: every staged upload and result fetch in
# the package routes through these two calls, so bytes-on-link is a
# first-class, regression-gated metric (``xfer.h2d_bytes`` /
# ``xfer.d2h_bytes`` counters with matching ``xfer.h2d``/``xfer.d2h``
# latency histograms) instead of a number reconstructed from shapes in
# a session log. The tunnel's fixed per-interaction latency made every
# perf round since r4 argue about exactly these bytes — now they are
# measured where they move.
# ---------------------------------------------------------------------------

_WIDE_ENV = "CRDT_TPU_WIDE_STAGING"

# ---------------------------------------------------------------------------
# device fault hook: the injection seam for the guarded-dispatch
# ladder (crdt_tpu.guard.device). The hook fires before every guarded
# dispatch attempt and may raise RuntimeError to simulate a device
# fault (OOM, preemption, a dropped tunnel) — chaos schedules drive
# the retry → split → host ladder without a real dying accelerator.
# ---------------------------------------------------------------------------

_DEVICE_FAULT_HOOK = None
# guards the fault hook and the one-time reset-hook warning flag:
# this module is reached from the streaming decode pool, and the
# hook's swap-and-return-old contract (DeviceFaultPlan nests restore
# inside install) is only correct if the read-modify-write is atomic
# (crdtlint CL601)
_HOOK_LOCK = threading.Lock()


def set_device_fault_hook(fn):
    """Install ``fn(stage, attempt)`` as the guarded-dispatch fault
    hook (None uninstalls). Returns the previous hook so callers can
    restore it; :class:`crdt_tpu.guard.faults.DeviceFaultPlan` wraps
    this in a context manager."""
    global _DEVICE_FAULT_HOOK
    with _HOOK_LOCK:
        old = _DEVICE_FAULT_HOOK
        _DEVICE_FAULT_HOOK = fn
        return old


def device_fault_hook():
    return _DEVICE_FAULT_HOOK


def wide_staging_forced() -> bool:
    """Debug knob (README "Transfer diet"): CRDT_TPU_WIDE_STAGING=1
    forces every staged upload to the wide int32 layout, bypassing the
    narrow-column encodings — for isolating a suspected narrowing bug
    without touching code."""
    return os.environ.get(_WIDE_ENV, "") not in ("", "0")


def xfer_put(arr, *, label: str = "stage"):
    """The ONE host->device seam: ``jax.device_put`` + byte accounting.

    Records ``xfer.h2d_bytes`` / ``xfer.h2d_puts`` (labelled by call
    site) and observes the put's enqueue latency into the ``xfer.h2d``
    histogram. The put itself stays ASYNCHRONOUS — the span measures
    initiation, exactly what the overlapped paths pipeline behind."""
    from crdt_tpu.obs.tracer import get_tracer

    tracer = get_tracer()
    if not tracer.enabled:
        return jax.device_put(arr)
    import time as _t

    nbytes = int(getattr(arr, "nbytes", 0))
    t0 = _t.perf_counter()
    out = jax.device_put(arr)
    tracer.observe("xfer.h2d", _t.perf_counter() - t0)
    tracer.count("xfer.h2d_bytes", nbytes)
    tracer.count("xfer.h2d_puts")
    tracer.count("xfer.h2d_bytes_by", nbytes, labels={"path": label})
    return out


def xfer_fetch(dev, *, label: str = "result"):
    """The ONE device->host seam: ``np.asarray`` + byte accounting.

    BLOCKS until the array is on host (that is the point of a fetch).
    Execution wait is ALWAYS excluded from the ``xfer.d2h`` histogram
    — the seam blocks on completion itself before timing the
    transfer, so every call site's sample means the same thing (pure
    D2H) and a kernel slowdown can never read as a transfer
    regression in the byte gate."""
    import numpy as np

    from crdt_tpu.obs.tracer import get_tracer

    tracer = get_tracer()
    if not tracer.enabled:
        return np.asarray(dev)
    import time as _t

    jax.block_until_ready(dev)  # execution wait, not transfer
    t0 = _t.perf_counter()
    h = np.asarray(dev)
    tracer.observe("xfer.d2h", _t.perf_counter() - t0)
    tracer.count("xfer.d2h_bytes", int(h.nbytes))
    tracer.count("xfer.d2h_fetches")
    tracer.count("xfer.d2h_bytes_by", int(h.nbytes),
                 labels={"path": label})
    return h


def record_staged_widths(widths: dict, shipped_bytes: int,
                         wide_bytes: int) -> None:
    """Per-upload narrowing record: one ``xfer.col_width`` count per
    column at its chosen width (the per-column width histogram) and
    the ``xfer.narrowed_ratio`` gauge = shipped / wide-equivalent
    bytes (1.0 = no diet, 0.5 = halved)."""
    from crdt_tpu.obs.tracer import get_tracer

    tracer = get_tracer()
    if not tracer.enabled:
        return
    for col, bits in widths.items():
        tracer.count("xfer.col_width", labels={"col": col, "bits": bits})
    if wide_bytes > 0:
        tracer.gauge(
            "xfer.narrowed_ratio", round(shipped_bytes / wide_bytes, 4)
        )
        # staged-upload bytes tracked SEPARATELY from the all-traffic
        # xfer.h2d_bytes: the run-level narrowing ratio is
        # staged / (staged + saved), and mixing in non-staged traffic
        # (fleet columns, resident deltas) would let an unrelated
        # upload-mix change masquerade as a narrowing regression
        tracer.count("xfer.staged_bytes", shipped_bytes)
        tracer.count("xfer.h2d_bytes_saved",
                     max(wide_bytes - shipped_bytes, 0))


# shapes whose local-CPU executable already exists in-process (the
# persistent-cache suppression below is only needed around a fresh
# compile)
_LOCAL_CPU_COMPILED: set = set()

# guards the module-level memo caches (_LOCAL_CPU_COMPILED, _pack_fns):
# this module is reached from the streaming thread pool, and an
# unlocked read-then-write loses one thread's entry (a wasted
# recompile, and CL601 exists to keep the class of bug out)
_CACHE_LOCK = threading.Lock()


_RESET_HOOK_WARNED = False


def _warn_no_reset_hook() -> None:
    """One-time loud signal that persistent-cache suppression is
    DEGRADED: jax's private ``compilation_cache.reset_cache`` hook is
    gone, so XLA:CPU AOT artifacts from an accelerator-backed process
    may persist and feature-mismatch a later loader (the documented
    SIGILL hazard). Silent no-op was the advisor's round-5 finding;
    tests/test_device_merge.py pins the hook so a jax upgrade that
    removes it fails loudly instead of landing here in production."""
    global _RESET_HOOK_WARNED
    with _HOOK_LOCK:
        if _RESET_HOOK_WARNED:
            return
        _RESET_HOOK_WARNED = True
    import warnings

    warnings.warn(
        "jax._src.compilation_cache.reset_cache is unavailable: "
        "persistent-cache suppression around local-CPU compiles "
        "is a no-op (SIGILL hazard for cross-backend cached "
        "artifacts). Pin CRDT_TPU_COMPILE_CACHE=\"\" to disable "
        "the cache, or update crdt_tpu for this jax version.",
        RuntimeWarning,
        stacklevel=3,
    )


def _cache_singleton_reset(cache_dir) -> bool:
    """Point the persistent-cache config at ``cache_dir`` AND drop the
    initialized singleton so the new value actually takes effect
    (flipping the flag alone is a no-op against jax's process-wide
    cache singleton). Returns False — after a one-time warning — when
    the private reset hook is unavailable (callers must then not
    assume suppression worked)."""
    import jax as _jax

    try:
        from jax._src import compilation_cache as _cc

        _reset = _cc.reset_cache
    except Exception:
        _warn_no_reset_hook()
        return False  # no reset hook: leave the config untouched
    _jax.config.update("jax_compilation_cache_dir", cache_dir)
    try:
        _reset()
    except Exception:
        pass  # config did change; restoring it is still required
    return True


@contextmanager
def on_local_cpu(cache_key=None):
    """Execute jitted work on the process's LOCAL CPU backend.

    This is the host path's escape hatch on tunnelled platforms: the
    same XLA program, zero accelerator interactions (a single tunnel
    dispatch costs 25-110 ms fixed — more than many whole host
    rounds). The persistent compile cache is suppressed around fresh
    compiles (``cache_key`` identifies the shape family): XLA:CPU AOT
    artifacts written from a TPU process can feature-mismatch a later
    loader (SIGILL hazard, see the cache setup above)."""
    import jax as _jax

    cpu = _jax.devices("cpu")[0]
    fresh = cache_key is None or cache_key not in _LOCAL_CPU_COMPILED
    old = getattr(_jax.config, "jax_compilation_cache_dir", None)
    # the SIGILL hazard exists only when this process's DEFAULT
    # backend is an accelerator (its cache dir would mix TPU-process
    # CPU artifacts); a CPU-pinned process (tests, the dry run) owns a
    # self-consistent CPU cache that SHOULD persist these compiles
    suppress = (
        fresh and bool(old) and not _cpu_pinned()
        and _cache_singleton_reset(None)
    )
    try:
        with _jax.default_device(cpu):
            yield
        if cache_key is not None:
            with _CACHE_LOCK:
                _LOCAL_CPU_COMPILED.add(cache_key)
    finally:
        if suppress:
            _cache_singleton_reset(old)


def bucket_pow2(n: int, floor: int = 9) -> int:
    """Power-of-two size bucket (host helper): padding to buckets keeps
    jit compiling once per bucket instead of once per exact shape."""
    return 1 << max(floor, (max(n, 1) - 1).bit_length())


def bucket_grid(n: int, floor: int = 9) -> int:
    """Quarter-pow2 size bucket: smallest of {1, 1.25, 1.5, 1.75}*2^k
    >= n. Pow2 padding wastes up to 100% of every downstream sort and
    doubling round; the quarter grid caps waste at 25% for 2x the
    shape-bucket count. Used by the cold packed replay, where the
    doubling loops' width is the dispatch's dominant axis; resident
    buffers keep plain pow2 (their capacity growth amortizes)."""
    n = max(n, 1 << floor)
    k = (n - 1).bit_length() - 1  # candidate exponent: 2^k < n <= 2^(k+1)
    for num in (5, 6, 7, 8):
        cand = num << max(k - 2, 0)
        if cand >= n:
            return cand
    return 1 << (k + 1)


_pack_fns: dict = {}  # arity -> jitted concat (host helper cache)


def fetch_packed_i32(*arrays):
    """Fetch several device index arrays in ONE packed int32 transfer.

    Per-array `np.asarray` fetches pay the transfer stall per call on
    tunnelled platforms; all kernel index/segment outputs fit int32
    (values < the pad bucket, NULLI = -1). Returns host arrays in
    input order."""
    with _CACHE_LOCK:
        fn = _pack_fns.get(len(arrays))
        if fn is None:
            # cheap under the lock: jax.jit only wraps here, the
            # actual compile happens at the (unlocked) call below
            fn = jax.jit(
                lambda *xs: jnp.concatenate(
                    [x.astype(jnp.int32) for x in xs]
                )
            )
            _pack_fns[len(arrays)] = fn
    h = xfer_fetch(fn(*arrays), label="packed_i32")
    out, off = [], 0
    for a in arrays:
        n = a.shape[0]
        out.append(h[off:off + n])
        off += n
    return out


def pack_id(client: jnp.ndarray, clock: jnp.ndarray) -> jnp.ndarray:
    """(client, clock) -> single sortable int64; null (-1,*) -> -1."""
    packed = (client.astype(jnp.int64) << _CLOCK_BITS) | clock.astype(jnp.int64)
    return jnp.where(client < 0, jnp.int64(NULLI), packed)


def unpack_id(packed: jnp.ndarray):
    client = jnp.where(packed < 0, NULLI, packed >> _CLOCK_BITS).astype(jnp.int32)
    clock = jnp.where(packed < 0, NULLI, packed & ((1 << _CLOCK_BITS) - 1)).astype(
        jnp.int64
    )
    return client, clock


def lexsort(keys) -> jnp.ndarray:
    """argsort by multiple keys; keys[0] is most significant.

    Built from iterated stable argsorts (least-significant first), the
    classic radix-style composition XLA handles well.
    """
    order = jnp.argsort(keys[-1], stable=True)
    for k in reversed(keys[:-1]):
        order = order[jnp.argsort(k[order], stable=True)]
    return order


def dense_ranks_sorted(sorted_key: jnp.ndarray) -> jnp.ndarray:
    """Dense 0..S-1 rank per element of an ALREADY SORTED key array."""
    new_seg = jnp.concatenate(
        [
            jnp.zeros(1, jnp.int32),
            (sorted_key[1:] != sorted_key[:-1]).astype(jnp.int32),
        ]
    )
    return jnp.cumsum(new_seg).astype(jnp.int32)


def searchsorted_ids(sorted_ids: jnp.ndarray, query: jnp.ndarray) -> jnp.ndarray:
    """Index of each query id in sorted_ids, or NULLI if absent.

    method='sort' everywhere in this package: the default binary-search
    lowering is a log(N)-step loop of full-width gathers, an order of
    magnitude slower on TPU than one extra radix sort pass (measured
    ~34ms vs ~2.4ms at N=128k on v5e)."""
    pos = jnp.searchsorted(sorted_ids, query, method="sort")
    pos_c = jnp.clip(pos, 0, sorted_ids.shape[0] - 1)
    found = (sorted_ids.shape[0] > 0) & (sorted_ids[pos_c] == query) & (query >= 0)
    return jnp.where(found, pos_c, NULLI).astype(jnp.int32)


def scatter_perm(perm: jnp.ndarray, vals: jnp.ndarray) -> jnp.ndarray:
    """out[perm[i]] = vals[i] for a PERMUTATION perm — as a gather.

    XLA TPU lowers a real scatter to a serialized update loop (~13ms at
    N=128k on v5e); inverting the permutation with one more argsort and
    gathering is ~50x cheaper. Only valid when perm is a permutation of
    0..N-1 (e.g. any argsort output)."""
    return vals[jnp.argsort(perm, stable=True)]


def run_edge_lookup(slots_sorted: jnp.ndarray, size: int, *, side: str):
    """For each dense slot j in [0, size): the index into `slots_sorted`
    of the FIRST (side='left') or LAST (side='right') element equal to
    j, or NULLI when j is absent. `slots_sorted` must be ascending
    (route invalid rows to a value >= size before sorting).

    This is the scatter-free way to build dense per-slot tables (first
    child per parent, last child per node, max per segment): sort rows
    by slot once, then one searchsorted picks each run's edge."""
    iota = jnp.arange(size, dtype=slots_sorted.dtype)
    pos = jnp.searchsorted(slots_sorted, iota, side=side, method="sort")
    if side == "right":
        pos = pos - 1
    pos_c = jnp.clip(pos, 0, slots_sorted.shape[0] - 1)
    found = slots_sorted[pos_c] == iota
    return jnp.where(found, pos_c, NULLI).astype(jnp.int32), found


def dfs_ranks(
    parent: jnp.ndarray,      # [B] int32 tree parent (root children point
                              #     at B+seg; non-items at B+num_roots)
    next_sib: jnp.ndarray,    # [B] int32 next sibling, NULLI at group end
    first_child: jnp.ndarray, # [B+num_roots] int32 first child per node
    is_item: jnp.ndarray,     # [B] bool real tree members
    num_roots: int,
    rank_rounds: int | None = None,
) -> jnp.ndarray:
    """Distance-to-end of the DFS traversal for every node (items and
    the virtual roots appended after them) via successor pointer
    doubling (Wyllie list ranking).

    The DFS successor of a node is its first child if any, else the
    next sibling of the nearest ancestor (itself included) that has
    one — the "climb past last-child chains" step, itself a pointer
    doubling. Shared by :func:`crdt_tpu.ops.yata.tree_order_ranks`
    (full-width) and the packed replay kernel (compact-width; since
    round 12 the staged cold path feeds PRE-BUILT next_sib /
    first_child tables straight from staging, so this ranking is the
    only tree machinery left in that dispatch).

    ``rank_rounds`` (static), when the caller can bound the longest
    per-segment DFS path on the host (e.g. max segment population from
    one ``np.unique`` at staging), fixes both doubling loops to that
    many rounds: the fixpoint reduce per round disappears and the
    whole ranking runs exactly ceil(log2(path)) gathers. ``None``
    keeps the data-driven while-loop with fixpoint early exit (the
    incremental path, where the bound changes every round and a static
    would recompile).
    """
    B = parent.shape[0]
    m = B + num_roots
    idx_m = jnp.arange(m, dtype=jnp.int32)
    pad_next = jnp.pad(next_sib, (0, num_roots), constant_values=NULLI)
    pad_parent = jnp.pad(parent, (0, num_roots), constant_values=0).astype(
        jnp.int32
    )
    pad_item = jnp.pad(is_item, (0, num_roots))

    is_last_child = (idx_m < B) & (pad_next == NULLI) & pad_item
    g = jnp.where(is_last_child, pad_parent, idx_m)
    climb_t = pointer_double(g, max_iters=rank_rounds)

    y_next = pad_next[jnp.clip(climb_t, 0, m - 1)]
    succ = jnp.where((climb_t >= B) | (y_next < 0), idx_m, y_next)
    succ = jnp.where(
        first_child >= 0, jnp.clip(first_child, 0, m - 1), succ
    )
    succ = jnp.where(pad_item | (idx_m >= B), succ, idx_m).astype(jnp.int32)

    return wyllie_dist(succ, rounds=rank_rounds)


# low 32 bits of the packed (pointer, distance) word hold the distance.
# A plain Python int: a module-level jnp scalar would be constructed at
# import time, when jax_enable_x64 may be off, and truncate to int32.
_W_DIST = (1 << 32) - 1


def wyllie_dist(succ: jnp.ndarray, rounds: int | None = None) -> jnp.ndarray:
    """Distance-to-terminal along ``succ`` for every node (terminals
    are self-loops), by pointer doubling with the (pointer, distance)
    pair packed into ONE int64 per node: each round costs a single
    random gather instead of two, and on a gather-latency-bound TPU
    the ranking loop is exactly where the fused replay dispatch spends
    its time (see tools/profile_kernel.py).

    ``rounds`` (static) runs a fixed ``fori_loop`` with no per-round
    fixpoint reduce; callers must guarantee 2**rounds >= the longest
    path. ``None`` falls back to the early-exit while-loop bounded by
    ceil(log2(m)) + 1 (any malformed cycle terminates there and keeps
    an in-cycle value, same convention as :func:`pointer_double`)."""
    m = succ.shape[0]
    idx = jnp.arange(m, dtype=jnp.int32)
    dist0 = (succ != idx).astype(jnp.int64)
    comb = (succ.astype(jnp.int64) << 32) | dist0
    max_iters = max(1, (max(m, 2) - 1).bit_length() + 1)

    def step(c):
        ptr = (c >> 32).astype(jnp.int32)
        c2 = c[ptr]
        newd = (c & _W_DIST) + (c2 & _W_DIST)
        return (c2 & ~_W_DIST) | newd, ptr, (c2 >> 32).astype(jnp.int32)

    if rounds is not None:
        def fbody(_, c):
            return step(c)[0]

        comb = jax.lax.fori_loop(0, min(rounds, max_iters), fbody, comb)
    else:
        def body(state):
            c, it, _ = state
            nc, ptr, nptr = step(c)
            return nc, it + 1, jnp.any(nptr != ptr)

        def cond(state):
            return state[2] & (state[1] < max_iters)

        p0 = (comb >> 32).astype(jnp.int32)
        comb, _, _ = jax.lax.while_loop(
            cond, body, (comb, jnp.int32(0), jnp.any(p0[p0] != p0))
        )
    return (comb & _W_DIST).astype(jnp.int32)


def pointer_double(f: jnp.ndarray, max_iters: int | None = None) -> jnp.ndarray:
    """Iterate f <- f∘f to a fixpoint. `f` maps node->node with
    self-loops at terminals; returns the terminal reached from each
    node in O(log depth) gather rounds.

    The iteration count is hard-bounded at ceil(log2(n))+1: any valid
    forest converges by then, and a malformed input whose pointers form
    a cycle (e.g. a hostile update with cyclic origins) terminates
    instead of spinning the device forever — cycle members simply keep
    an in-cycle value, which downstream visibility checks treat like
    any other non-root result.

    ``max_iters`` (static) tightens the bound when the caller knows the
    chain depth (the early-exit reduce still runs; the cap only clips
    the worst case)."""
    n = f.shape[0]
    cap = max(1, (max(n, 2) - 1).bit_length() + 1)
    max_iters = cap if max_iters is None else max(1, min(max_iters, cap))

    def body(state):
        g, it, _ = state
        g2 = g[g]
        return g2, it + 1, jnp.any(g2 != g)

    def cond(state):
        _, it, changed = state
        return changed & (it < max_iters)

    # initial `changed` is derived from f (not a constant) so the carry
    # carries f's varying-axes type under shard_map, and an input
    # already at fixpoint exits immediately
    g, _, _ = jax.lax.while_loop(
        cond, body, (f, jnp.int32(0), jnp.any(f[f] != f))
    )
    return g
