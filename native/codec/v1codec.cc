// Native v1 update codec — CPython extension.
//
// The end-to-end benchmark showed the pure-Python codec dominating the
// replay pipeline (decode + snapshot encode ≈ 80% of wall-clock while
// the device merge is ~1ms). This module is the native equivalent of
// crdt_tpu/codec/v1.py's hot paths, mirroring the reference stack's
// use of native code for its heavy lifting (SURVEY.md §2.2):
//
//   decode_updates(list[bytes]) -> dict of numpy columns + contents
//     one pass over a batch of v1 blobs: lib0 primitives, struct
//     grammar, run splitting into unit rows, string/key/root
//     interning, implicit-parent resolution via origin chains (the
//     Python path's decode_update + resolve_parents +
//     records_to_columns collapsed into one C pass).
//
//   encode_update(columns..., contents, roots, keys, ds...) -> bytes
//     byte-identical to crdt_tpu.codec.v1.encode_update on the same
//     logical rows: clients descending, maximal runs, Skip structs
//     for clock gaps, the exact lib0 `any` type dispatch.
//
// Semantics are pinned by tests/test_native_codec.py: differential
// round-trips against the Python codec (including the hand-derived
// foreign wire fixtures) must agree byte for byte.

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>

#include <cstdint>
#include <algorithm>
#include <cmath>
#include <cstring>
#include <map>
#include <string>
#include <unordered_map>
#include <vector>

// content kinds (crdt_tpu/core/store.py)
static const int K_GC = 0, K_DELETED = 1, K_JSON = 2, K_BINARY = 3,
                 K_STRING = 4, K_ANY = 5, K_TYPE = 6, K_EMBED = 7,
                 K_FORMAT = 8, K_DOC = 9;
// wire refs (crdt_tpu/codec/v1.py)
// wire sanity bound shared with the Python codec's _MAX_CLOCK (and the
// kernels' 40-bit clock packing): declared clocks/run ends past this
// are hostile, and GC/Deleted expansion is budgeted per blob byte so a
// few declared bytes can never buy unbounded allocation
static const int64_t MAX_CLOCK = (int64_t)1 << 40;
// client-id bound (mirrors v1.py _MAX_ID): [2^63, 2^64) would wrap
// negative through the int64 cast and bypass every downstream check
// (2^64-1 even collides with the -1 "absent" sentinel)
static const uint64_t MAX_ID = (uint64_t)1 << 62;

static const int REF_GC = 0, REF_DELETED = 1, REF_JSON = 2, REF_BINARY = 3,
                 REF_STRING = 4, REF_EMBED = 5, REF_FORMAT = 6, REF_TYPE = 7,
                 REF_ANY = 8, REF_DOC = 9, REF_SKIP = 10;

static int kind_of_ref(int ref) {
  switch (ref) {
    case REF_GC: return K_GC;
    case REF_DELETED: return K_DELETED;
    case REF_JSON: return K_JSON;
    case REF_BINARY: return K_BINARY;
    case REF_STRING: return K_STRING;
    case REF_EMBED: return K_EMBED;
    case REF_FORMAT: return K_FORMAT;
    case REF_TYPE: return K_TYPE;
    case REF_ANY: return K_ANY;
    case REF_DOC: return K_DOC;
  }
  return -1;
}

static int ref_of_kind(int kind) {
  switch (kind) {
    case K_GC: return REF_GC;
    case K_DELETED: return REF_DELETED;
    case K_JSON: return REF_JSON;
    case K_BINARY: return REF_BINARY;
    case K_STRING: return REF_STRING;
    case K_EMBED: return REF_EMBED;
    case K_FORMAT: return REF_FORMAT;
    case K_TYPE: return REF_TYPE;
    case K_ANY: return REF_ANY;
    case K_DOC: return REF_DOC;
  }
  return -1;
}

// module-level cached Python callables / sentinels (set in init)
static PyObject* g_undefined = nullptr;   // crdt_tpu.codec.lib0.UNDEFINED
static PyObject* g_json_dumps = nullptr;  // json.dumps
static PyObject* g_json_loads = nullptr;  // json.loads

// ---------------------------------------------------------------------------
// lib0 reader
// ---------------------------------------------------------------------------

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  bool need(size_t n) {
    if ((size_t)(end - p) < n) { ok = false; return false; }
    return true;
  }
  uint8_t u8() {
    if (!need(1)) return 0;
    return *p++;
  }
  // bounded identity/clock/length field: validated against the cap
  // BEFORE the signed cast (see MAX_ID) — rejection semantics shared
  // with the Python codec's _read_client_id/_read_clock_val
  int64_t field(uint64_t cap) {
    uint64_t v = varuint();
    if (!ok) return 0;
    if (v >= cap) { ok = false; return 0; }
    return (int64_t)v;
  }
  uint64_t varuint() {
    uint64_t n = 0; int shift = 0;
    while (true) {
      if (!need(1)) return 0;
      uint8_t b = *p++;
      uint64_t part = (uint64_t)(b & 0x7F);
      // overflow must REJECT, not wrap: a silently wrapped length
      // would sail under every downstream sanity cap (the Python
      // codec's arbitrary-precision ints reject the same bytes)
      if (shift >= 64 || (shift > 0 && part > (UINT64_MAX >> shift))) {
        ok = false;
        return 0;
      }
      n |= part << shift;
      if (!(b & 0x80)) return n;
      shift += 7;
    }
  }
  int64_t varint() {
    if (!need(1)) return 0;
    uint8_t b = *p++;
    int64_t sign = (b & 0x40) ? -1 : 1;
    uint64_t n = b & 0x3F;
    int shift = 6;
    while (b & 0x80) {
      if (!need(1)) return 0;
      b = *p++;
      uint64_t part = (uint64_t)(b & 0x7F);
      if (shift >= 64 || part > (UINT64_MAX >> shift)) {
        ok = false;  // overflow rejects, never wraps (see varuint)
        return 0;
      }
      n |= part << shift;
      shift += 7;
    }
    // int64-representability (mirrors lib0.py read_var_int):
    // magnitudes in [2^63, 2^64) would wrap negative through the
    // cast below and silently diverge from the Python codec
    if (n >= ((uint64_t)1 << 63)) { ok = false; return 0; }
    return sign * (int64_t)n;
  }
  bool raw(size_t n, const uint8_t** out) {
    if (!need(n)) return false;
    *out = p;
    p += n;
    return true;
  }
  // UTF-8 string -> PyUnicode (new ref), nullptr on error
  PyObject* pystring() {
    uint64_t len = varuint();
    const uint8_t* s;
    if (!ok || !raw(len, &s)) { ok = false; return nullptr; }
    PyObject* u = PyUnicode_DecodeUTF8((const char*)s, len, nullptr);
    if (!u) ok = false;
    return u;
  }
  // UTF-8 string -> std::string (for interning)
  bool cstring(std::string* out) {
    uint64_t len = varuint();
    const uint8_t* s;
    if (!ok || !raw(len, &s)) { ok = false; return false; }
    out->assign((const char*)s, len);
    return true;
  }
  PyObject* pybytes() {
    uint64_t len = varuint();
    const uint8_t* s;
    if (!ok || !raw(len, &s)) { ok = false; return nullptr; }
    return PyBytes_FromStringAndSize((const char*)s, len);
  }
  double f32be() {
    const uint8_t* s;
    if (!raw(4, &s)) return 0;
    uint32_t v = ((uint32_t)s[0] << 24) | ((uint32_t)s[1] << 16) |
                 ((uint32_t)s[2] << 8) | s[3];
    float f;
    memcpy(&f, &v, 4);
    return (double)f;
  }
  double f64be() {
    const uint8_t* s;
    if (!raw(8, &s)) return 0;
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v = (v << 8) | s[i];
    double d;
    memcpy(&d, &v, 8);
    return d;
  }
  int64_t i64be() {
    const uint8_t* s;
    if (!raw(8, &s)) return 0;
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v = (v << 8) | s[i];
    return (int64_t)v;
  }
  PyObject* any();  // defined below
};

PyObject* Reader::any() {
  uint8_t t = u8();
  if (!ok) return nullptr;
  switch (t) {
    case 127: Py_INCREF(g_undefined); return g_undefined;
    case 126: Py_RETURN_NONE;
    case 125: { int64_t v = varint(); if (!ok) return nullptr;
                return PyLong_FromLongLong(v); }
    case 124: { double v = f32be(); if (!ok) return nullptr;
                return PyFloat_FromDouble(v); }
    case 123: { double v = f64be(); if (!ok) return nullptr;
                return PyFloat_FromDouble(v); }
    case 122: { int64_t v = i64be(); if (!ok) return nullptr;
                return PyLong_FromLongLong(v); }
    case 121: Py_RETURN_FALSE;
    case 120: Py_RETURN_TRUE;
    case 119: return pystring();
    case 118: {
      uint64_t n = varuint();
      if (!ok) return nullptr;
      PyObject* d = PyDict_New();
      if (!d) { ok = false; return nullptr; }
      for (uint64_t i = 0; i < n; i++) {
        PyObject* k = pystring();
        if (!k) { Py_DECREF(d); return nullptr; }
        PyObject* v = any();
        if (!v) { Py_DECREF(k); Py_DECREF(d); return nullptr; }
        if (PyDict_SetItem(d, k, v) < 0) {
          Py_DECREF(k); Py_DECREF(v); Py_DECREF(d);
          ok = false; return nullptr;
        }
        Py_DECREF(k); Py_DECREF(v);
      }
      return d;
    }
    case 117: {
      uint64_t n = varuint();
      if (!ok) return nullptr;
      PyObject* l = PyList_New(n);
      if (!l) { ok = false; return nullptr; }
      for (uint64_t i = 0; i < n; i++) {
        PyObject* v = any();
        if (!v) { Py_DECREF(l); return nullptr; }
        PyList_SET_ITEM(l, i, v);
      }
      return l;
    }
    case 116: { PyObject* b = pybytes(); if (!b) ok = false; return b; }
  }
  ok = false;
  return nullptr;
}

// ---------------------------------------------------------------------------
// decode_updates
// ---------------------------------------------------------------------------

struct PairHash {
  size_t operator()(const std::pair<int64_t, int64_t>& p) const {
    return std::hash<int64_t>()(p.first * 1000003 ^ p.second);
  }
};

struct Columns {
  std::vector<int64_t> client, clock;
  std::vector<int32_t> parent_root;   // interned root id, -1
  std::vector<int64_t> pclient, pclock;  // parent item id, -1
  std::vector<int32_t> key_id;        // interned key, -1
  std::vector<int64_t> oclient, oclock;  // left origin, -1
  std::vector<int64_t> rclient, rclock;  // right origin, -1
  std::vector<int32_t> kind, type_ref;
  std::vector<PyObject*> contents;    // owned refs (may be nullptr->None)

  std::unordered_map<std::string, int32_t> root_ids, key_ids;
  std::vector<std::string> roots, keys;

  int32_t intern_root(const std::string& s) {
    auto it = root_ids.find(s);
    if (it != root_ids.end()) return it->second;
    int32_t id = (int32_t)roots.size();
    roots.push_back(s);
    root_ids.emplace(s, id);
    return id;
  }
  int32_t intern_key(const std::string& s) {
    auto it = key_ids.find(s);
    if (it != key_ids.end()) return it->second;
    int32_t id = (int32_t)keys.size();
    keys.push_back(s);
    key_ids.emplace(s, id);
    return id;
  }
  size_t n() const { return client.size(); }
  void push(int64_t cl, int64_t ck, int32_t pr, int64_t pc, int64_t pk,
            int32_t kid, int64_t oc, int64_t ok_, int64_t rc, int64_t rk,
            int32_t kd, int32_t tr, PyObject* content /* stolen */) {
    client.push_back(cl); clock.push_back(ck);
    parent_root.push_back(pr); pclient.push_back(pc); pclock.push_back(pk);
    key_id.push_back(kid); oclient.push_back(oc); oclock.push_back(ok_);
    rclient.push_back(rc); rclock.push_back(rk);
    kind.push_back(kd); type_ref.push_back(tr);
    contents.push_back(content);
  }
  void free_contents() {
    for (PyObject* o : contents) Py_XDECREF(o);
    contents.clear();
  }
};

// split a decoded wire struct covering `len` clocks into unit rows,
// exactly like v1._split_units: part j>0 gets origin (client, clock+j-1)
// and inherits the run's right origin; parent/key only on part 0 (later
// resolved from the origin chain).
static void push_run(Columns& C, int64_t client, int64_t clock, int64_t len,
                     int32_t pr, int64_t pc, int64_t pk, int32_t kid,
                     bool has_origin, int64_t oc, int64_t ok_,
                     bool has_right, int64_t rc, int64_t rk,
                     int32_t kind, int32_t tref,
                     std::vector<PyObject*>* contents /* stolen or null */) {
  for (int64_t j = 0; j < len; j++) {
    PyObject* content = nullptr;
    if (contents) content = (*contents)[j];
    if (j == 0) {
      C.push(client, clock, pr, pc, pk, kid,
             has_origin ? oc : -1, has_origin ? ok_ : -1,
             has_right ? rc : -1, has_right ? rk : -1, kind, tref, content);
    } else {
      C.push(client, clock + j, -1, -1, -1, -1,
             client, clock + j - 1,
             has_right ? rc : -1, has_right ? rk : -1, kind, tref, content);
    }
  }
}

static bool decode_one(Reader& r, Columns& C,
                       std::vector<int64_t>& ds_out /* triples */) {
  // expansion budget (mirrors v1.py): GC/Deleted runs expand to unit
  // rows; bound the total against the blob's byte size
  const int64_t budget =
      std::max((int64_t)1 << 20, 4096 * (int64_t)(r.end - r.p));
  const int64_t n0 = (int64_t)C.n();
  uint64_t num_clients = r.varuint();
  if (!r.ok) return false;
  for (uint64_t ci = 0; ci < num_clients; ci++) {
    uint64_t num_structs = r.varuint();
    int64_t client = r.field(MAX_ID);
    int64_t clock = r.field((uint64_t)MAX_CLOCK);
    if (!r.ok) return false;
    for (uint64_t si = 0; si < num_structs; si++) {
      uint8_t info = r.u8();
      if (!r.ok) return false;
      int ref = info & 0x1F;
      if (ref == REF_SKIP) {
        clock += r.field((uint64_t)MAX_CLOCK);
        if (!r.ok || clock >= MAX_CLOCK) { r.ok = false; return false; }
        continue;
      }
      if (ref == REF_GC) {
        int64_t len = r.field((uint64_t)MAX_CLOCK);
        if (!r.ok) return false;
        if (clock + len >= MAX_CLOCK ||
            (int64_t)C.n() - n0 + len > budget) { r.ok = false; return false; }
        // parts after the first carry chain origins, mirroring the
        // Python _split_units (the engine ignores them for GC)
        for (int64_t j = 0; j < len; j++)
          C.push(client, clock + j, -1, -1, -1, -1,
                 j == 0 ? -1 : client, j == 0 ? -1 : clock + j - 1,
                 -1, -1, K_GC, -1, nullptr);
        clock += len;
        continue;
      }
      int kind = kind_of_ref(ref);
      if (kind < 0) { r.ok = false; return false; }
      bool has_origin = info & 0x80, has_right = info & 0x40;
      int64_t oc = -1, ok_ = -1, rc = -1, rk = -1;
      if (has_origin) {
        oc = r.field(MAX_ID); ok_ = r.field((uint64_t)MAX_CLOCK);
      }
      if (has_right) {
        rc = r.field(MAX_ID); rk = r.field((uint64_t)MAX_CLOCK);
      }
      int32_t pr = -1, kid = -1;
      int64_t pc = -1, pk = -1;
      if (!(info & 0xC0)) {
        if (r.varuint() == 1) {
          std::string name;
          if (!r.cstring(&name)) return false;
          pr = C.intern_root(name);
        } else {
          pc = r.field(MAX_ID);
          pk = r.field((uint64_t)MAX_CLOCK);
        }
        if (info & 0x20) {
          std::string key;
          if (!r.cstring(&key)) return false;
          kid = C.intern_key(key);
        }
      }
      if (!r.ok) return false;

      int64_t len = 1;
      std::vector<PyObject*> contents;  // stolen into C on push_run
      int32_t tref = -1;
      switch (ref) {
        case REF_DELETED:
          len = r.field((uint64_t)MAX_CLOCK);
          if (!r.ok || clock + len >= MAX_CLOCK ||
              (int64_t)C.n() - n0 + len > budget) { r.ok = false; return false; }
          contents.assign(len, nullptr);
          break;
        case REF_JSON: {
          len = r.field((uint64_t)MAX_CLOCK);
          for (int64_t j = 0; r.ok && j < len; j++) {
            PyObject* s = r.pystring();
            if (!s) break;
            PyObject* v;
            if (PyUnicode_CompareWithASCIIString(s, "undefined") == 0) {
              Py_INCREF(g_undefined);
              v = g_undefined;
            } else {
              v = PyObject_CallFunctionObjArgs(g_json_loads, s, nullptr);
            }
            Py_DECREF(s);
            if (!v) { r.ok = false; break; }
            contents.push_back(v);
          }
          break;
        }
        case REF_BINARY: {
          PyObject* b = r.pybytes();
          if (!b) r.ok = false;
          contents.push_back(b);
          break;
        }
        case REF_STRING: {
          // UTF-8 -> UTF-16 code units, one unit row per clock
          std::string raw;
          if (!r.cstring(&raw)) break;
          size_t i = 0;
          while (i < raw.size()) {
            uint32_t cp; int nb;
            uint8_t b0 = raw[i];
            if (b0 < 0x80) { cp = b0; nb = 1; }
            else if ((b0 & 0xE0) == 0xC0) { cp = b0 & 0x1F; nb = 2; }
            else if ((b0 & 0xF0) == 0xE0) { cp = b0 & 0x0F; nb = 3; }
            else if ((b0 & 0xF8) == 0xF0) { cp = b0 & 0x07; nb = 4; }
            else { r.ok = false; break; }
            if (i + nb > raw.size()) { r.ok = false; break; }
            for (int j = 1; j < nb; j++)
              cp = (cp << 6) | (raw[i + j] & 0x3F);
            i += nb;
            if (cp >= 0x10000) {
              uint32_t v = cp - 0x10000;
              uint16_t hi = 0xD800 + (v >> 10), lo = 0xDC00 + (v & 0x3FF);
              Py_UCS2 a = hi, b = lo;
              contents.push_back(
                  PyUnicode_FromKindAndData(PyUnicode_2BYTE_KIND, &a, 1));
              contents.push_back(
                  PyUnicode_FromKindAndData(PyUnicode_2BYTE_KIND, &b, 1));
            } else {
              Py_UCS2 u = (Py_UCS2)cp;
              contents.push_back(
                  PyUnicode_FromKindAndData(PyUnicode_2BYTE_KIND, &u, 1));
            }
          }
          len = (int64_t)contents.size();
          break;
        }
        case REF_EMBED: {
          PyObject* s = r.pystring();
          if (!s) break;
          PyObject* v = PyObject_CallFunctionObjArgs(g_json_loads, s, nullptr);
          Py_DECREF(s);
          if (!v) { r.ok = false; break; }
          contents.push_back(v);
          break;
        }
        case REF_FORMAT: {
          PyObject* k = r.pystring();
          if (!k) break;
          PyObject* s = r.pystring();
          if (!s) { Py_DECREF(k); break; }
          PyObject* v = PyObject_CallFunctionObjArgs(g_json_loads, s, nullptr);
          Py_DECREF(s);
          if (!v) { Py_DECREF(k); r.ok = false; break; }
          contents.push_back(PyTuple_Pack(2, k, v));
          Py_DECREF(k); Py_DECREF(v);
          break;
        }
        case REF_TYPE:
          tref = (int32_t)r.field((uint64_t)1 << 31);
          contents.push_back(nullptr);
          break;
        case REF_ANY: {
          len = r.field((uint64_t)MAX_CLOCK);
          for (int64_t j = 0; r.ok && j < len; j++) {
            PyObject* v = r.any();
            if (!v) break;
            contents.push_back(v);
          }
          break;
        }
        case REF_DOC: {
          PyObject* guid = r.pystring();
          if (!guid) break;
          PyObject* opts = r.any();
          if (!opts) { Py_DECREF(guid); break; }
          contents.push_back(PyTuple_Pack(2, guid, opts));
          Py_DECREF(guid); Py_DECREF(opts);
          break;
        }
      }
      if (!r.ok || (int64_t)contents.size() != len) {
        for (PyObject* o : contents) Py_XDECREF(o);
        r.ok = false;
        return false;
      }
      push_run(C, client, clock, len, pr, pc, pk, kid,
               has_origin, oc, ok_, has_right, rc, rk, kind, tref,
               &contents);
      clock += len;
    }
  }
  // delete set
  uint64_t ds_clients = r.varuint();
  if (!r.ok) return false;
  for (uint64_t i = 0; i < ds_clients; i++) {
    int64_t client = r.field(MAX_ID);
    uint64_t nr = r.varuint();
    if (!r.ok) return false;
    for (uint64_t j = 0; j < nr; j++) {
      int64_t clk = (int64_t)r.varuint();
      int64_t len = (int64_t)r.varuint();
      if (!r.ok) return false;
      if ((uint64_t)clk >= (uint64_t)MAX_CLOCK ||
          (uint64_t)len >= (uint64_t)MAX_CLOCK) {
        r.ok = false; return false;
      }
      if (clk + len >= MAX_CLOCK) { r.ok = false; return false; }
      if (len) {
        ds_out.push_back(client);
        ds_out.push_back(clk);
        ds_out.push_back(len);
      }
    }
  }
  if (r.p != r.end) { r.ok = false; return false; }  // trailing bytes
  return true;
}

// implicit parents: walk the origin (else right) chain until a row with
// explicit parent info; copy its parent columns (and key when absent).
// Port of v1.resolve_parents.
static void resolve_parents(Columns& C) {
  std::unordered_map<std::pair<int64_t, int64_t>, int, PairHash> index;
  size_t n = C.n();
  index.reserve(n * 2);
  for (size_t i = 0; i < n; i++)
    index.emplace(std::make_pair(C.client[i], C.clock[i]), (int)i);
  for (size_t i = 0; i < n; i++) {
    if (C.parent_root[i] != -1 || C.pclient[i] != -1 || C.kind[i] == K_GC)
      continue;
    int cur = (int)i;
    size_t steps = 0;
    while (cur >= 0 && C.parent_root[cur] == -1 && C.pclient[cur] == -1) {
      if (++steps > n) { cur = -1; break; }  // cycle guard
      int64_t nc = C.oclient[cur] != -1 ? C.oclient[cur] : C.rclient[cur];
      int64_t nk = C.oclient[cur] != -1 ? C.oclock[cur] : C.rclock[cur];
      if (nc == -1) { cur = -1; break; }
      auto it = index.find(std::make_pair(nc, nk));
      cur = it == index.end() ? -1 : it->second;
    }
    if (cur >= 0) {
      C.parent_root[i] = C.parent_root[cur];
      C.pclient[i] = C.pclient[cur];
      C.pclock[i] = C.pclock[cur];
      if (C.key_id[i] == -1) C.key_id[i] = C.key_id[cur];
    }
  }
}

template <typename T>
static PyObject* np_from_vec(const std::vector<T>& v, int typenum) {
  npy_intp dims[1] = {(npy_intp)v.size()};
  PyObject* arr = PyArray_SimpleNew(1, dims, typenum);
  if (!arr) return nullptr;
  if (!v.empty())
    memcpy(PyArray_DATA((PyArrayObject*)arr), v.data(), v.size() * sizeof(T));
  return arr;
}

static PyObject* py_string_list(const std::vector<std::string>& v) {
  PyObject* l = PyList_New(v.size());
  if (!l) return nullptr;
  for (size_t i = 0; i < v.size(); i++) {
    PyObject* s = PyUnicode_DecodeUTF8(v[i].data(), v[i].size(), nullptr);
    if (!s) { Py_DECREF(l); return nullptr; }
    PyList_SET_ITEM(l, i, s);
  }
  return l;
}

static PyObject* decode_updates(PyObject*, PyObject* args) {
  PyObject* blobs;
  if (!PyArg_ParseTuple(args, "O", &blobs)) return nullptr;
  PyObject* seq = PySequence_Fast(blobs, "expected a sequence of bytes");
  if (!seq) return nullptr;

  Columns C;
  std::vector<int64_t> ds;
  Py_ssize_t nblobs = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < nblobs; i++) {
    PyObject* b = PySequence_Fast_GET_ITEM(seq, i);
    char* buf;
    Py_ssize_t blen;
    if (PyBytes_AsStringAndSize(b, &buf, &blen) < 0) {
      C.free_contents();
      Py_DECREF(seq);
      return nullptr;
    }
    Reader r{(const uint8_t*)buf, (const uint8_t*)buf + blen};
    if (!decode_one(r, C, ds) || !r.ok) {
      C.free_contents();
      Py_DECREF(seq);
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "malformed v1 update");
      return nullptr;
    }
  }
  Py_DECREF(seq);
  resolve_parents(C);

  size_t n = C.n();
  PyObject* contents = PyList_New(n);
  if (!contents) { C.free_contents(); return nullptr; }
  for (size_t i = 0; i < n; i++) {
    PyObject* o = C.contents[i];
    if (!o) { Py_INCREF(Py_None); o = Py_None; }
    PyList_SET_ITEM(contents, i, o);  // steals our ref
  }
  C.contents.clear();  // ownership moved

  PyObject* out = PyDict_New();
  if (!out) { Py_DECREF(contents); return nullptr; }
  bool fail = false;
  auto set = [&](const char* name, PyObject* v) {
    if (!v || PyDict_SetItemString(out, name, v) < 0) fail = true;
    Py_XDECREF(v);
  };
  set("client", np_from_vec(C.client, NPY_INT64));
  set("clock", np_from_vec(C.clock, NPY_INT64));
  set("parent_root", np_from_vec(C.parent_root, NPY_INT32));
  set("parent_client", np_from_vec(C.pclient, NPY_INT64));
  set("parent_clock", np_from_vec(C.pclock, NPY_INT64));
  set("key_id", np_from_vec(C.key_id, NPY_INT32));
  set("origin_client", np_from_vec(C.oclient, NPY_INT64));
  set("origin_clock", np_from_vec(C.oclock, NPY_INT64));
  set("right_client", np_from_vec(C.rclient, NPY_INT64));
  set("right_clock", np_from_vec(C.rclock, NPY_INT64));
  set("kind", np_from_vec(C.kind, NPY_INT32));
  set("type_ref", np_from_vec(C.type_ref, NPY_INT32));
  set("ds", np_from_vec(ds, NPY_INT64));
  set("roots", py_string_list(C.roots));
  set("keys", py_string_list(C.keys));
  if (PyDict_SetItemString(out, "contents", contents) < 0) fail = true;
  Py_DECREF(contents);
  if (fail) { Py_DECREF(out); return nullptr; }
  return out;
}

// ---------------------------------------------------------------------------
// encode_update (byte-identical to crdt_tpu.codec.v1.encode_update)
// ---------------------------------------------------------------------------

struct Writer {
  std::vector<uint8_t> buf;
  void u8(uint8_t b) { buf.push_back(b); }
  void varuint(uint64_t n) {
    while (true) {
      uint8_t b = n & 0x7F;
      n >>= 7;
      if (n) buf.push_back(0x80 | b);
      else { buf.push_back(b); break; }
    }
  }
  void varint(int64_t v) {
    bool neg = v < 0;
    uint64_t n = neg ? (uint64_t)(-v) : (uint64_t)v;
    uint8_t first = (neg ? 0x40 : 0) | (n & 0x3F);
    n >>= 6;
    if (n) {
      buf.push_back(0x80 | first);
      while (true) {
        uint8_t b = n & 0x7F;
        n >>= 7;
        if (n) buf.push_back(0x80 | b);
        else { buf.push_back(b); break; }
      }
    } else {
      buf.push_back(first);
    }
  }
  void raw(const char* d, size_t n) { buf.insert(buf.end(), d, d + n); }
  bool pystr(PyObject* s) {  // varstring from a PyUnicode
    Py_ssize_t len;
    const char* data = PyUnicode_AsUTF8AndSize(s, &len);
    if (!data) return false;
    varuint(len);
    raw(data, len);
    return true;
  }
  void cstr(const std::string& s) {
    varuint(s.size());
    raw(s.data(), s.size());
  }
  void f32be(double d) {
    float f = (float)d;
    uint32_t v;
    memcpy(&v, &f, 4);
    for (int i = 3; i >= 0; i--) buf.push_back((v >> (8 * i)) & 0xFF);
  }
  void f64be(double d) {
    uint64_t v;
    memcpy(&v, &d, 8);
    for (int i = 7; i >= 0; i--) buf.push_back((v >> (8 * i)) & 0xFF);
  }
  void i64be(int64_t x) {
    uint64_t v = (uint64_t)x;
    for (int i = 7; i >= 0; i--) buf.push_back((v >> (8 * i)) & 0xFF);
  }
  bool any(PyObject* v);  // defined below
};

bool Writer::any(PyObject* v) {
  if (v == g_undefined) { u8(127); return true; }
  if (v == Py_None) { u8(126); return true; }
  if (PyBool_Check(v)) { u8(v == Py_True ? 120 : 121); return true; }
  if (PyLong_Check(v)) {
    int overflow = 0;
    long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (overflow) {
      PyErr_SetString(PyExc_TypeError, "integer out of lib0 int64 range");
      return false;
    }
    const int64_t SAFE = 9007199254740992LL;  // 2**53
    if (x > -SAFE && x < SAFE) { u8(125); varint(x); }
    else { u8(122); i64be(x); }
    return true;
  }
  if (PyFloat_Check(v)) {
    double d = PyFloat_AS_DOUBLE(v);
    if (std::isfinite(d) && (double)(float)d == d) { u8(124); f32be(d); }
    else { u8(123); f64be(d); }
    return true;
  }
  if (PyUnicode_Check(v)) { u8(119); return pystr(v); }
  if (PyDict_Check(v)) {
    u8(118);
    varuint(PyDict_Size(v));
    PyObject *key, *val;
    Py_ssize_t pos = 0;
    while (PyDict_Next(v, &pos, &key, &val)) {
      PyObject* ks = PyObject_Str(key);
      if (!ks) return false;
      bool ok_ = pystr(ks);
      Py_DECREF(ks);
      if (!ok_ || !any(val)) return false;
    }
    return true;
  }
  if (PyList_Check(v) || PyTuple_Check(v)) {
    PyObject* seq = PySequence_Fast(v, "");
    if (!seq) return false;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    u8(117);
    varuint(n);
    for (Py_ssize_t i = 0; i < n; i++) {
      if (!any(PySequence_Fast_GET_ITEM(seq, i))) { Py_DECREF(seq); return false; }
    }
    Py_DECREF(seq);
    return true;
  }
  if (PyBytes_Check(v) || PyByteArray_Check(v)) {
    PyObject* b = PyBytes_FromObject(v);
    if (!b) return false;
    u8(116);
    varuint(PyBytes_GET_SIZE(b));
    raw(PyBytes_AS_STRING(b), PyBytes_GET_SIZE(b));
    Py_DECREF(b);
    return true;
  }
  PyErr_Format(PyExc_TypeError, "cannot encode %R as lib0 any", v);
  return false;
}

// dump JSON via the cached json.dumps (byte-identical to the Python path)
static bool write_json_content(Writer& w, PyObject* content) {
  if (content == g_undefined) {
    w.cstr("undefined");
    return true;
  }
  PyObject* s = PyObject_CallFunctionObjArgs(g_json_dumps, content, nullptr);
  if (!s) return false;
  bool ok_ = w.pystr(s);
  Py_DECREF(s);
  return ok_;
}

// UTF-16 unit contents -> UTF-8, pairing surrogates (v1._join_utf16)
static bool write_string_run(Writer& w, PyObject* contents_list,
                             const int* rows, int count) {
  std::vector<uint16_t> units;
  units.reserve(count);
  for (int i = 0; i < count; i++) {
    PyObject* s = PyList_GET_ITEM(contents_list, rows[i]);
    if (!PyUnicode_Check(s) || PyUnicode_GET_LENGTH(s) != 1) {
      PyErr_SetString(PyExc_TypeError, "string content must be one UTF-16 unit");
      return false;
    }
    Py_UCS4 ch = PyUnicode_READ_CHAR(s, 0);
    if (ch >= 0x10000) {  // tolerate a pre-paired astral char
      Py_UCS4 v = ch - 0x10000;
      units.push_back(0xD800 + (v >> 10));
      units.push_back(0xDC00 + (v & 0x3FF));
    } else {
      units.push_back((uint16_t)ch);
    }
  }
  std::string utf8;
  utf8.reserve(units.size() * 3);
  for (size_t i = 0; i < units.size(); i++) {
    uint32_t cp = units[i];
    if (cp >= 0xD800 && cp < 0xDC00 && i + 1 < units.size() &&
        units[i + 1] >= 0xDC00 && units[i + 1] < 0xE000) {
      cp = 0x10000 + ((cp - 0xD800) << 10) + (units[i + 1] - 0xDC00);
      i++;
    }
    if (cp < 0x80) utf8 += (char)cp;
    else if (cp < 0x800) {
      utf8 += (char)(0xC0 | (cp >> 6));
      utf8 += (char)(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      utf8 += (char)(0xE0 | (cp >> 12));
      utf8 += (char)(0x80 | ((cp >> 6) & 0x3F));
      utf8 += (char)(0x80 | (cp & 0x3F));
    } else {
      utf8 += (char)(0xF0 | (cp >> 18));
      utf8 += (char)(0x80 | ((cp >> 12) & 0x3F));
      utf8 += (char)(0x80 | ((cp >> 6) & 0x3F));
      utf8 += (char)(0x80 | (cp & 0x3F));
    }
  }
  w.cstr(utf8);
  return true;
}

struct EncodeInput {
  const int64_t *client, *clock, *pclient, *pclock;
  const int64_t *oclient, *oclock, *rclient, *rclock;
  const int32_t *parent_root, *key_id, *kind, *type_ref;
  PyObject* contents;  // list
  std::vector<std::string> roots, keys;
  npy_intp n;
};

static bool same_parent(const EncodeInput& E, int a, int prev) {
  bool absent = E.parent_root[a] == -1 && E.pclient[a] == -1 &&
                E.key_id[a] == -1;
  if (absent) return true;
  return E.parent_root[a] == E.parent_root[prev] &&
         E.pclient[a] == E.pclient[prev] && E.pclock[a] == E.pclock[prev] &&
         E.key_id[a] == E.key_id[prev];
}

static bool encode_rows(Writer& w, const EncodeInput& E,
                        const int64_t* ds, npy_intp nds) {
  // group rows by client, clock-ascending; clients descending
  std::map<int64_t, std::vector<int>> by_client;
  for (npy_intp i = 0; i < E.n; i++) by_client[E.client[i]].push_back((int)i);
  for (auto& kv : by_client) {
    auto& rows = kv.second;
    std::stable_sort(rows.begin(), rows.end(), [&](int a, int b) {
      return E.clock[a] < E.clock[b];
    });
  }

  w.varuint(by_client.size());
  for (auto it = by_client.rbegin(); it != by_client.rend(); ++it) {
    const std::vector<int>& rows = it->second;
    // build runs (port of v1._coalesce) + skip markers
    struct Run { int start, count; bool skip; int64_t skip_len; };
    std::vector<Run> runs;
    size_t i = 0;
    int64_t prev_end = -1;
    while (i < rows.size()) {
      int head = rows[i];
      if (prev_end >= 0 && E.clock[head] > prev_end)
        runs.push_back({0, 0, true, E.clock[head] - prev_end});
      size_t j = i + 1;
      int kind = E.kind[head];
      bool mergeable = kind == K_ANY || kind == K_JSON || kind == K_STRING ||
                       kind == K_DELETED;
      while (j < rows.size()) {
        int r = rows[j], p = rows[j - 1];
        bool plain = kind == K_GC && E.kind[r] == K_GC &&
                     E.clock[r] == E.clock[p] + 1;
        bool chained = E.clock[r] == E.clock[p] + 1 &&
                       E.oclient[r] == E.client[p] &&
                       E.oclock[r] == E.clock[p] &&
                       E.rclient[r] == E.rclient[head] &&
                       E.rclock[r] == E.rclock[head];
        if (plain ||
            (mergeable && E.kind[r] == kind && same_parent(E, r, p) && chained))
          j++;
        else
          break;
      }
      runs.push_back({(int)i, (int)(j - i), false, 0});
      prev_end = E.clock[rows[j - 1]] + 1;
      i = j;
    }

    w.varuint(runs.size());
    w.varuint((uint64_t)it->first);
    // start clock of first entry
    const Run& first = runs.front();
    w.varuint(first.skip ? (uint64_t)(E.clock[rows[0]] - first.skip_len)
                         : (uint64_t)E.clock[rows[first.start]]);

    for (const Run& run : runs) {
      if (run.skip) {
        w.u8(REF_SKIP);
        w.varuint((uint64_t)run.skip_len);
        continue;
      }
      int head = rows[run.start];
      if (E.kind[head] == K_GC) {
        w.u8(REF_GC);
        w.varuint(run.count);
        continue;
      }
      int ref = ref_of_kind(E.kind[head]);
      if (ref < 0) {
        PyErr_Format(PyExc_ValueError, "cannot encode kind %d", E.kind[head]);
        return false;
      }
      bool has_origin = E.oclient[head] != -1;
      bool has_right = E.rclient[head] != -1;
      bool write_parent = !has_origin && !has_right;
      bool has_sub = write_parent && E.key_id[head] != -1;
      w.u8(ref | (has_origin ? 0x80 : 0) | (has_right ? 0x40 : 0) |
           (has_sub ? 0x20 : 0));
      if (has_origin) {
        w.varuint((uint64_t)E.oclient[head]);
        w.varuint((uint64_t)E.oclock[head]);
      }
      if (has_right) {
        w.varuint((uint64_t)E.rclient[head]);
        w.varuint((uint64_t)E.rclock[head]);
      }
      if (write_parent) {
        if (E.parent_root[head] != -1) {
          w.varuint(1);
          w.cstr(E.roots[E.parent_root[head]]);
        } else if (E.pclient[head] != -1) {
          w.varuint(0);
          w.varuint((uint64_t)E.pclient[head]);
          w.varuint((uint64_t)E.pclock[head]);
        } else {
          PyErr_SetString(PyExc_ValueError,
                          "row needs parent_root, parent item, or an origin");
          return false;
        }
        if (has_sub) w.cstr(E.keys[E.key_id[head]]);
      }
      // content
      switch (E.kind[head]) {
        case K_DELETED:
          w.varuint(run.count);
          break;
        case K_JSON:
          w.varuint(run.count);
          for (int k = 0; k < run.count; k++)
            if (!write_json_content(
                    w, PyList_GET_ITEM(E.contents, rows[run.start + k])))
              return false;
          break;
        case K_BINARY: {
          PyObject* b = PyList_GET_ITEM(E.contents, head);
          PyObject* bb = PyBytes_FromObject(b);
          if (!bb) return false;
          w.varuint(PyBytes_GET_SIZE(bb));
          w.raw(PyBytes_AS_STRING(bb), PyBytes_GET_SIZE(bb));
          Py_DECREF(bb);
          break;
        }
        case K_STRING: {
          std::vector<int> rws(run.count);
          for (int k = 0; k < run.count; k++) rws[k] = rows[run.start + k];
          if (!write_string_run(w, E.contents, rws.data(), run.count))
            return false;
          break;
        }
        case K_EMBED: {
          PyObject* s = PyObject_CallFunctionObjArgs(
              g_json_dumps, PyList_GET_ITEM(E.contents, head), nullptr);
          if (!s) return false;
          bool ok_ = w.pystr(s);
          Py_DECREF(s);
          if (!ok_) return false;
          break;
        }
        case K_FORMAT: {
          PyObject* t = PyList_GET_ITEM(E.contents, head);
          if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 2) {
            PyErr_SetString(PyExc_TypeError, "format content must be (k, v)");
            return false;
          }
          if (!w.pystr(PyTuple_GET_ITEM(t, 0))) return false;
          PyObject* s = PyObject_CallFunctionObjArgs(
              g_json_dumps, PyTuple_GET_ITEM(t, 1), nullptr);
          if (!s) return false;
          bool ok_ = w.pystr(s);
          Py_DECREF(s);
          if (!ok_) return false;
          break;
        }
        case K_TYPE:
          w.varuint((uint64_t)E.type_ref[head]);
          break;
        case K_ANY:
          w.varuint(run.count);
          for (int k = 0; k < run.count; k++)
            if (!w.any(PyList_GET_ITEM(E.contents, rows[run.start + k])))
              return false;
          break;
        case K_DOC: {
          PyObject* t = PyList_GET_ITEM(E.contents, head);
          if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 2) {
            PyErr_SetString(PyExc_TypeError, "doc content must be (guid, opts)");
            return false;
          }
          if (!w.pystr(PyTuple_GET_ITEM(t, 0))) return false;
          if (!w.any(PyTuple_GET_ITEM(t, 1))) return false;
          break;
        }
      }
    }
  }

  // delete set: triples (client, start, len) pre-ordered by the caller
  // (clients descending, ranges ascending within a client)
  std::vector<std::pair<int64_t, std::pair<npy_intp, npy_intp>>> groups;
  npy_intp i3 = 0;
  while (i3 < nds) {
    int64_t c = ds[i3 * 3];
    npy_intp start = i3;
    while (i3 < nds && ds[i3 * 3] == c) i3++;
    groups.push_back({c, {start, i3}});
  }
  w.varuint(groups.size());
  for (auto& g : groups) {
    w.varuint((uint64_t)g.first);
    w.varuint((uint64_t)(g.second.second - g.second.first));
    for (npy_intp k = g.second.first; k < g.second.second; k++) {
      w.varuint((uint64_t)ds[k * 3 + 1]);
      w.varuint((uint64_t)ds[k * 3 + 2]);
    }
  }
  return true;
}

static const int64_t* i64_data(PyObject* arr, const char* name, npy_intp* n) {
  if (!PyArray_Check(arr)) {
    PyErr_Format(PyExc_TypeError, "%s must be an int64 numpy array", name);
    return nullptr;
  }
  PyArrayObject* a = (PyArrayObject*)arr;
  if (PyArray_TYPE(a) != NPY_INT64 || !PyArray_IS_C_CONTIGUOUS(a)) {
    PyErr_Format(PyExc_TypeError, "%s must be contiguous int64", name);
    return nullptr;
  }
  if (n) *n = PyArray_SIZE(a);
  return (const int64_t*)PyArray_DATA(a);
}

static const int32_t* i32_data(PyObject* arr, const char* name, npy_intp* n) {
  if (!PyArray_Check(arr)) {
    PyErr_Format(PyExc_TypeError, "%s must be an int32 numpy array", name);
    return nullptr;
  }
  PyArrayObject* a = (PyArrayObject*)arr;
  if (PyArray_TYPE(a) != NPY_INT32 || !PyArray_IS_C_CONTIGUOUS(a)) {
    PyErr_Format(PyExc_TypeError, "%s must be contiguous int32", name);
    return nullptr;
  }
  if (n) *n = PyArray_SIZE(a);
  return (const int32_t*)PyArray_DATA(a);
}

static bool fill_strings(PyObject* list, std::vector<std::string>* out) {
  PyObject* seq = PySequence_Fast(list, "expected a list of strings");
  if (!seq) return false;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  out->reserve(n);
  for (Py_ssize_t i = 0; i < n; i++) {
    Py_ssize_t len;
    const char* d =
        PyUnicode_AsUTF8AndSize(PySequence_Fast_GET_ITEM(seq, i), &len);
    if (!d) { Py_DECREF(seq); return false; }
    out->emplace_back(d, len);
  }
  Py_DECREF(seq);
  return true;
}

static PyObject* encode_update(PyObject*, PyObject* args) {
  PyObject *client, *clock, *parent_root, *pclient, *pclock, *key_id;
  PyObject *oclient, *oclock, *rclient, *rclock, *kind, *type_ref;
  PyObject *contents, *roots, *keys, *dsarr;
  if (!PyArg_ParseTuple(args, "OOOOOOOOOOOOOOOO", &client, &clock,
                        &parent_root, &pclient, &pclock, &key_id, &oclient,
                        &oclock, &rclient, &rclock, &kind, &type_ref,
                        &contents, &roots, &keys, &dsarr))
    return nullptr;
  EncodeInput E;
  npy_intp n = 0, nds3 = 0;
  E.client = i64_data(client, "client", &n);
  E.clock = i64_data(clock, "clock", nullptr);
  E.parent_root = i32_data(parent_root, "parent_root", nullptr);
  E.pclient = i64_data(pclient, "parent_client", nullptr);
  E.pclock = i64_data(pclock, "parent_clock", nullptr);
  E.key_id = i32_data(key_id, "key_id", nullptr);
  E.oclient = i64_data(oclient, "origin_client", nullptr);
  E.oclock = i64_data(oclock, "origin_clock", nullptr);
  E.rclient = i64_data(rclient, "right_client", nullptr);
  E.rclock = i64_data(rclock, "right_clock", nullptr);
  E.kind = i32_data(kind, "kind", nullptr);
  E.type_ref = i32_data(type_ref, "type_ref", nullptr);
  const int64_t* ds = i64_data(dsarr, "ds", &nds3);
  if (!E.client || !E.clock || !E.parent_root || !E.pclient || !E.pclock ||
      !E.key_id || !E.oclient || !E.oclock || !E.rclient || !E.rclock ||
      !E.kind || !E.type_ref || !ds)
    return nullptr;
  if (!PyList_Check(contents) || PyList_GET_SIZE(contents) != n) {
    PyErr_SetString(PyExc_TypeError, "contents must be a list of length n");
    return nullptr;
  }
  if (!fill_strings(roots, &E.roots) || !fill_strings(keys, &E.keys))
    return nullptr;
  E.contents = contents;
  E.n = n;

  Writer w;
  if (!encode_rows(w, E, ds, nds3 / 3)) return nullptr;
  return PyBytes_FromStringAndSize((const char*)w.buf.data(), w.buf.size());
}

// ---------------------------------------------------------------------------

static PyMethodDef methods[] = {
    {"decode_updates", decode_updates, METH_VARARGS,
     "Decode a sequence of v1 update blobs into columnar arrays."},
    {"encode_update", encode_update, METH_VARARGS,
     "Encode columnar rows + delete set into one v1 update blob."},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_v1codec",
    "Native v1 update codec (see crdt_tpu/codec/native.py).", -1, methods,
};

PyMODINIT_FUNC PyInit__v1codec(void) {
  import_array();
  PyObject* json = PyImport_ImportModule("json");
  if (!json) return nullptr;
  g_json_dumps = PyObject_GetAttrString(json, "dumps");
  g_json_loads = PyObject_GetAttrString(json, "loads");
  Py_DECREF(json);
  if (!g_json_dumps || !g_json_loads) return nullptr;
  PyObject* lib0 = PyImport_ImportModule("crdt_tpu.codec.lib0");
  if (!lib0) return nullptr;
  g_undefined = PyObject_GetAttrString(lib0, "UNDEFINED");
  Py_DECREF(lib0);
  if (!g_undefined) return nullptr;
  return PyModule_Create(&moduledef);
}
