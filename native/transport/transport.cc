// TPU-framework native transport: reliable-datagram UDP + crypto.
//
// The reference's swarm stack bottoms out in two native libraries
// (SURVEY.md §2.2 native-code census): udx-native (C, reliable streams
// over UDP) and sodium-native (C, libsodium crypto for the encrypted
// peer links). This file is their equivalent seam for the rebuild:
//
//  - crypto: X25519 (RFC 7748) key agreement, HChaCha20 subkey
//    derivation, ChaCha20-Poly1305 AEAD (RFC 8439) and its
//    XChaCha20-Poly1305 extended-nonce form — the same primitive
//    family libsodium uses for crypto_box/secretstream. Implemented
//    from the RFCs; test vectors in tests/test_transport.py.
//  - transport: a poll-driven (event-loop, like udx) UDP endpoint
//    carrying arbitrary-size messages: fragmentation to sub-MTU
//    datagrams, per-fragment acks, timed retransmit with exponential
//    backoff, reassembly, duplicate suppression. No threads: the
//    caller pumps udp_poll(), exactly how udx rides libuv.
//
// Flat C ABI (ctypes on the Python side; the image has no pybind11).
// Single file, no dependencies beyond POSIX sockets.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <deque>
#include <fcntl.h>
#include <map>
#include <netinet/in.h>
#include <set>
#include <string>
#include <sys/random.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>

extern "C" {

// ===========================================================================
// crypto: ChaCha20 (RFC 8439 §2.3)
// ===========================================================================

static inline uint32_t rotl32(uint32_t x, int n) {
  return (x << n) | (x >> (32 - n));
}

static inline uint32_t load32le(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

static inline void store32le(uint8_t* p, uint32_t v) {
  p[0] = v & 0xff; p[1] = (v >> 8) & 0xff;
  p[2] = (v >> 16) & 0xff; p[3] = (v >> 24) & 0xff;
}

#define QR(a, b, c, d)                                               \
  a += b; d ^= a; d = rotl32(d, 16);                                 \
  c += d; b ^= c; b = rotl32(b, 12);                                 \
  a += b; d ^= a; d = rotl32(d, 8);                                  \
  c += d; b ^= c; b = rotl32(b, 7);

static void chacha20_rounds(uint32_t x[16]) {
  for (int i = 0; i < 10; i++) {
    QR(x[0], x[4], x[8], x[12]);
    QR(x[1], x[5], x[9], x[13]);
    QR(x[2], x[6], x[10], x[14]);
    QR(x[3], x[7], x[11], x[15]);
    QR(x[0], x[5], x[10], x[15]);
    QR(x[1], x[6], x[11], x[12]);
    QR(x[2], x[7], x[8], x[13]);
    QR(x[3], x[4], x[9], x[14]);
  }
}

static void chacha20_init_state(uint32_t s[16], const uint8_t key[32],
                                uint32_t counter, const uint8_t nonce[12]) {
  s[0] = 0x61707865; s[1] = 0x3320646e; s[2] = 0x79622d32; s[3] = 0x6b206574;
  for (int i = 0; i < 8; i++) s[4 + i] = load32le(key + 4 * i);
  s[12] = counter;
  for (int i = 0; i < 3; i++) s[13 + i] = load32le(nonce + 4 * i);
}

static void chacha20_block(const uint8_t key[32], uint32_t counter,
                           const uint8_t nonce[12], uint8_t out[64]) {
  uint32_t s[16], x[16];
  chacha20_init_state(s, key, counter, nonce);
  memcpy(x, s, sizeof(s));
  chacha20_rounds(x);
  for (int i = 0; i < 16; i++) store32le(out + 4 * i, x[i] + s[i]);
}

static void chacha20_xor(const uint8_t key[32], uint32_t counter,
                         const uint8_t nonce[12], const uint8_t* in,
                         uint8_t* out, size_t len) {
  uint8_t block[64];
  for (size_t off = 0; off < len; off += 64, counter++) {
    chacha20_block(key, counter, nonce, block);
    size_t n = len - off < 64 ? len - off : 64;
    for (size_t i = 0; i < n; i++) out[off + i] = in[off + i] ^ block[i];
  }
}

// HChaCha20 (draft-irtf-cfrg-xchacha §2.2): the rounds WITHOUT the
// final state addition; output = words 0-3 and 12-15.
void ct_hchacha20(uint8_t out[32], const uint8_t key[32],
                  const uint8_t nonce[16]) {
  uint32_t x[16];
  x[0] = 0x61707865; x[1] = 0x3320646e; x[2] = 0x79622d32; x[3] = 0x6b206574;
  for (int i = 0; i < 8; i++) x[4 + i] = load32le(key + 4 * i);
  for (int i = 0; i < 4; i++) x[12 + i] = load32le(nonce + 4 * i);
  chacha20_rounds(x);
  for (int i = 0; i < 4; i++) store32le(out + 4 * i, x[i]);
  for (int i = 0; i < 4; i++) store32le(out + 16 + 4 * i, x[12 + i]);
}

// ===========================================================================
// crypto: Poly1305 (RFC 8439 §2.5)
// ===========================================================================

typedef struct {
  uint32_t r[5];
  uint32_t h[5];
  uint32_t pad[4];
} poly1305_state;

static void poly1305_init(poly1305_state* st, const uint8_t key[32]) {
  // r with the required clamping, split into 26-bit limbs
  st->r[0] = load32le(key + 0) & 0x3ffffff;
  st->r[1] = (load32le(key + 3) >> 2) & 0x3ffff03;
  st->r[2] = (load32le(key + 6) >> 4) & 0x3ffc0ff;
  st->r[3] = (load32le(key + 9) >> 6) & 0x3f03fff;
  st->r[4] = (load32le(key + 12) >> 8) & 0x00fffff;
  memset(st->h, 0, sizeof(st->h));
  for (int i = 0; i < 4; i++) st->pad[i] = load32le(key + 16 + 4 * i);
}

static void poly1305_blocks(poly1305_state* st, const uint8_t* m, size_t len,
                            int final_partial) {
  const uint32_t hibit = final_partial ? 0 : (1 << 24);
  uint32_t r0 = st->r[0], r1 = st->r[1], r2 = st->r[2], r3 = st->r[3],
           r4 = st->r[4];
  uint32_t s1 = r1 * 5, s2 = r2 * 5, s3 = r3 * 5, s4 = r4 * 5;
  uint32_t h0 = st->h[0], h1 = st->h[1], h2 = st->h[2], h3 = st->h[3],
           h4 = st->h[4];

  while (len > 0) {
    uint8_t block[16];
    size_t n = len < 16 ? len : 16;
    uint32_t hb = hibit;
    if (n < 16) {
      memset(block, 0, 16);
      memcpy(block, m, n);
      block[n] = 1;
      hb = 0;
      m = block;
    }
    h0 += load32le(m + 0) & 0x3ffffff;
    h1 += (load32le(m + 3) >> 2) & 0x3ffffff;
    h2 += (load32le(m + 6) >> 4) & 0x3ffffff;
    h3 += (load32le(m + 9) >> 6) & 0x3ffffff;
    h4 += (load32le(m + 12) >> 8) | hb;

    uint64_t d0 = (uint64_t)h0 * r0 + (uint64_t)h1 * s4 + (uint64_t)h2 * s3 +
                  (uint64_t)h3 * s2 + (uint64_t)h4 * s1;
    uint64_t d1 = (uint64_t)h0 * r1 + (uint64_t)h1 * r0 + (uint64_t)h2 * s4 +
                  (uint64_t)h3 * s3 + (uint64_t)h4 * s2;
    uint64_t d2 = (uint64_t)h0 * r2 + (uint64_t)h1 * r1 + (uint64_t)h2 * r0 +
                  (uint64_t)h3 * s4 + (uint64_t)h4 * s3;
    uint64_t d3 = (uint64_t)h0 * r3 + (uint64_t)h1 * r2 + (uint64_t)h2 * r1 +
                  (uint64_t)h3 * r0 + (uint64_t)h4 * s4;
    uint64_t d4 = (uint64_t)h0 * r4 + (uint64_t)h1 * r3 + (uint64_t)h2 * r2 +
                  (uint64_t)h3 * r1 + (uint64_t)h4 * r0;

    uint64_t c;
    c = d0 >> 26; h0 = d0 & 0x3ffffff; d1 += c;
    c = d1 >> 26; h1 = d1 & 0x3ffffff; d2 += c;
    c = d2 >> 26; h2 = d2 & 0x3ffffff; d3 += c;
    c = d3 >> 26; h3 = d3 & 0x3ffffff; d4 += c;
    c = d4 >> 26; h4 = d4 & 0x3ffffff;
    h0 += (uint32_t)(c * 5);
    c = h0 >> 26; h0 &= 0x3ffffff;
    h1 += (uint32_t)c;

    if (n == 16) m += 16;
    len -= n;
  }
  st->h[0] = h0; st->h[1] = h1; st->h[2] = h2; st->h[3] = h3; st->h[4] = h4;
}

static void poly1305_finish(poly1305_state* st, uint8_t tag[16]) {
  uint32_t h0 = st->h[0], h1 = st->h[1], h2 = st->h[2], h3 = st->h[3],
           h4 = st->h[4];
  uint32_t c;
  c = h1 >> 26; h1 &= 0x3ffffff; h2 += c;
  c = h2 >> 26; h2 &= 0x3ffffff; h3 += c;
  c = h3 >> 26; h3 &= 0x3ffffff; h4 += c;
  c = h4 >> 26; h4 &= 0x3ffffff; h0 += c * 5;
  c = h0 >> 26; h0 &= 0x3ffffff; h1 += c;

  // compute h + -p
  uint32_t g0 = h0 + 5; c = g0 >> 26; g0 &= 0x3ffffff;
  uint32_t g1 = h1 + c; c = g1 >> 26; g1 &= 0x3ffffff;
  uint32_t g2 = h2 + c; c = g2 >> 26; g2 &= 0x3ffffff;
  uint32_t g3 = h3 + c; c = g3 >> 26; g3 &= 0x3ffffff;
  uint32_t g4 = h4 + c - (1 << 26);

  // select h if h < p, else h - p
  uint32_t mask = (g4 >> 31) - 1;
  g0 &= mask; g1 &= mask; g2 &= mask; g3 &= mask; g4 &= mask;
  mask = ~mask;
  h0 = (h0 & mask) | g0; h1 = (h1 & mask) | g1; h2 = (h2 & mask) | g2;
  h3 = (h3 & mask) | g3; h4 = (h4 & mask) | g4;

  // h = h % 2^128, then h += pad
  h0 = (h0 | (h1 << 26)) & 0xffffffff;
  h1 = ((h1 >> 6) | (h2 << 20)) & 0xffffffff;
  h2 = ((h2 >> 12) | (h3 << 14)) & 0xffffffff;
  h3 = ((h3 >> 18) | (h4 << 8)) & 0xffffffff;

  uint64_t f;
  f = (uint64_t)h0 + st->pad[0]; h0 = (uint32_t)f;
  f = (uint64_t)h1 + st->pad[1] + (f >> 32); h1 = (uint32_t)f;
  f = (uint64_t)h2 + st->pad[2] + (f >> 32); h2 = (uint32_t)f;
  f = (uint64_t)h3 + st->pad[3] + (f >> 32); h3 = (uint32_t)f;

  store32le(tag + 0, h0); store32le(tag + 4, h1);
  store32le(tag + 8, h2); store32le(tag + 12, h3);
}

// ===========================================================================
// crypto: ChaCha20-Poly1305 AEAD (RFC 8439 §2.8)
// ===========================================================================

static void aead_mac(const uint8_t otk[32], const uint8_t* aad, size_t aad_len,
                     const uint8_t* ct, size_t ct_len, uint8_t tag[16]) {
  // mac_data = aad | pad16 | ct | pad16 | len(aad) LE64 | len(ct) LE64
  poly1305_state st;
  poly1305_init(&st, otk);
  uint8_t lens[16];
  if (aad_len) {
    size_t full = aad_len - (aad_len % 16);
    if (full) poly1305_blocks(&st, aad, full, 0);
    if (aad_len % 16) {
      uint8_t block[16] = {0};
      memcpy(block, aad + full, aad_len % 16);
      poly1305_blocks(&st, block, 16, 0);
    }
  }
  if (ct_len) {
    size_t full = ct_len - (ct_len % 16);
    if (full) poly1305_blocks(&st, ct, full, 0);
    if (ct_len % 16) {
      uint8_t block[16] = {0};
      memcpy(block, ct + full, ct_len % 16);
      poly1305_blocks(&st, block, 16, 0);
    }
  }
  for (int i = 0; i < 8; i++) {
    lens[i] = (uint8_t)((uint64_t)aad_len >> (8 * i));
    lens[8 + i] = (uint8_t)((uint64_t)ct_len >> (8 * i));
  }
  poly1305_blocks(&st, lens, 16, 0);
  poly1305_finish(&st, tag);
}

int ct_aead_encrypt(const uint8_t key[32], const uint8_t nonce[12],
                    const uint8_t* aad, uint32_t aad_len, const uint8_t* pt,
                    uint32_t pt_len, uint8_t* out /* pt_len + 16 */) {
  uint8_t otk[64];
  chacha20_block(key, 0, nonce, otk);  // poly key = first 32 bytes of block 0
  chacha20_xor(key, 1, nonce, pt, out, pt_len);
  aead_mac(otk, aad, aad_len, out, pt_len, out + pt_len);
  return 0;
}

int ct_aead_decrypt(const uint8_t key[32], const uint8_t nonce[12],
                    const uint8_t* aad, uint32_t aad_len, const uint8_t* ct,
                    uint32_t ct_len, uint8_t* out /* ct_len - 16 */) {
  if (ct_len < 16) return -1;
  uint32_t body = ct_len - 16;
  uint8_t otk[64], tag[16];
  chacha20_block(key, 0, nonce, otk);
  aead_mac(otk, aad, aad_len, ct, body, tag);
  uint8_t diff = 0;
  for (int i = 0; i < 16; i++) diff |= tag[i] ^ ct[body + i];
  if (diff) return -1;
  chacha20_xor(key, 1, nonce, ct, out, body);
  return 0;
}

// XChaCha20-Poly1305 (draft-irtf-cfrg-xchacha §2): HChaCha20 subkey
// from the first 16 nonce bytes, then RFC 8439 with nonce
// 0x00000000 | last 8 nonce bytes.
int ct_xaead_encrypt(const uint8_t key[32], const uint8_t nonce[24],
                     const uint8_t* aad, uint32_t aad_len, const uint8_t* pt,
                     uint32_t pt_len, uint8_t* out) {
  uint8_t subkey[32], n12[12] = {0};
  ct_hchacha20(subkey, key, nonce);
  memcpy(n12 + 4, nonce + 16, 8);
  return ct_aead_encrypt(subkey, n12, aad, aad_len, pt, pt_len, out);
}

int ct_xaead_decrypt(const uint8_t key[32], const uint8_t nonce[24],
                     const uint8_t* aad, uint32_t aad_len, const uint8_t* ct,
                     uint32_t ct_len, uint8_t* out) {
  uint8_t subkey[32], n12[12] = {0};
  ct_hchacha20(subkey, key, nonce);
  memcpy(n12 + 4, nonce + 16, 8);
  return ct_aead_decrypt(subkey, n12, aad, aad_len, ct, ct_len, out);
}

// ===========================================================================
// crypto: X25519 (RFC 7748) — field arithmetic mod 2^255-19, 5x51-bit
// limbs with unsigned __int128 products
// ===========================================================================

typedef uint64_t fe[5];
static const uint64_t MASK51 = 0x7ffffffffffffULL;

static void fe_copy(fe h, const fe f) { memcpy(h, f, sizeof(fe)); }
static void fe_0(fe h) { memset(h, 0, sizeof(fe)); }
static void fe_1(fe h) { fe_0(h); h[0] = 1; }

static void fe_add(fe h, const fe f, const fe g) {
  for (int i = 0; i < 5; i++) h[i] = f[i] + g[i];
}

static void fe_sub(fe h, const fe f, const fe g) {
  // add 2p first so limbs stay non-negative
  h[0] = f[0] + 0xfffffffffffdaULL - g[0];
  h[1] = f[1] + 0xffffffffffffeULL - g[1];
  h[2] = f[2] + 0xffffffffffffeULL - g[2];
  h[3] = f[3] + 0xffffffffffffeULL - g[3];
  h[4] = f[4] + 0xffffffffffffeULL - g[4];
}

static void fe_carry(fe h) {
  uint64_t c;
  c = h[0] >> 51; h[0] &= MASK51; h[1] += c;
  c = h[1] >> 51; h[1] &= MASK51; h[2] += c;
  c = h[2] >> 51; h[2] &= MASK51; h[3] += c;
  c = h[3] >> 51; h[3] &= MASK51; h[4] += c;
  c = h[4] >> 51; h[4] &= MASK51; h[0] += c * 19;
  c = h[0] >> 51; h[0] &= MASK51; h[1] += c;
}

static void fe_mul(fe h, const fe f, const fe g) {
  unsigned __int128 r0, r1, r2, r3, r4;
  uint64_t f0 = f[0], f1 = f[1], f2 = f[2], f3 = f[3], f4 = f[4];
  uint64_t g0 = g[0], g1 = g[1], g2 = g[2], g3 = g[3], g4 = g[4];
  uint64_t g1_19 = g1 * 19, g2_19 = g2 * 19, g3_19 = g3 * 19, g4_19 = g4 * 19;

  r0 = (unsigned __int128)f0 * g0 + (unsigned __int128)f1 * g4_19 +
       (unsigned __int128)f2 * g3_19 + (unsigned __int128)f3 * g2_19 +
       (unsigned __int128)f4 * g1_19;
  r1 = (unsigned __int128)f0 * g1 + (unsigned __int128)f1 * g0 +
       (unsigned __int128)f2 * g4_19 + (unsigned __int128)f3 * g3_19 +
       (unsigned __int128)f4 * g2_19;
  r2 = (unsigned __int128)f0 * g2 + (unsigned __int128)f1 * g1 +
       (unsigned __int128)f2 * g0 + (unsigned __int128)f3 * g4_19 +
       (unsigned __int128)f4 * g3_19;
  r3 = (unsigned __int128)f0 * g3 + (unsigned __int128)f1 * g2 +
       (unsigned __int128)f2 * g1 + (unsigned __int128)f3 * g0 +
       (unsigned __int128)f4 * g4_19;
  r4 = (unsigned __int128)f0 * g4 + (unsigned __int128)f1 * g3 +
       (unsigned __int128)f2 * g2 + (unsigned __int128)f3 * g1 +
       (unsigned __int128)f4 * g0;

  uint64_t c;
  uint64_t h0 = (uint64_t)r0 & MASK51; c = (uint64_t)(r0 >> 51);
  r1 += c;
  uint64_t h1 = (uint64_t)r1 & MASK51; c = (uint64_t)(r1 >> 51);
  r2 += c;
  uint64_t h2 = (uint64_t)r2 & MASK51; c = (uint64_t)(r2 >> 51);
  r3 += c;
  uint64_t h3 = (uint64_t)r3 & MASK51; c = (uint64_t)(r3 >> 51);
  r4 += c;
  uint64_t h4 = (uint64_t)r4 & MASK51; c = (uint64_t)(r4 >> 51);
  h0 += c * 19;
  c = h0 >> 51; h0 &= MASK51; h1 += c;
  h[0] = h0; h[1] = h1; h[2] = h2; h[3] = h3; h[4] = h4;
}

static void fe_sq(fe h, const fe f) { fe_mul(h, f, f); }

static void fe_mul121665(fe h, const fe f) {
  unsigned __int128 r;
  uint64_t c = 0;
  for (int i = 0; i < 5; i++) {
    r = (unsigned __int128)f[i] * 121665 + c;
    h[i] = (uint64_t)r & MASK51;
    c = (uint64_t)(r >> 51);
  }
  h[0] += c * 19;
  c = h[0] >> 51; h[0] &= MASK51; h[1] += c;
}

static void fe_cswap(fe f, fe g, uint64_t b) {
  uint64_t mask = (uint64_t)0 - b;
  for (int i = 0; i < 5; i++) {
    uint64_t x = mask & (f[i] ^ g[i]);
    f[i] ^= x;
    g[i] ^= x;
  }
}

static void fe_frombytes(fe h, const uint8_t s[32]) {
  uint64_t w[4];
  for (int i = 0; i < 4; i++) {
    w[i] = 0;
    for (int j = 0; j < 8; j++) w[i] |= (uint64_t)s[8 * i + j] << (8 * j);
  }
  h[0] = w[0] & MASK51;
  h[1] = ((w[0] >> 51) | (w[1] << 13)) & MASK51;
  h[2] = ((w[1] >> 38) | (w[2] << 26)) & MASK51;
  h[3] = ((w[2] >> 25) | (w[3] << 39)) & MASK51;
  h[4] = (w[3] >> 12) & MASK51;  // top bit of the point masked per RFC
}

static void fe_tobytes(uint8_t s[32], const fe f) {
  fe h;
  fe_copy(h, f);
  fe_carry(h);
  fe_carry(h);
  // canonical reduction: q = 1 iff h >= p
  uint64_t q = (h[0] + 19) >> 51;
  q = (h[1] + q) >> 51;
  q = (h[2] + q) >> 51;
  q = (h[3] + q) >> 51;
  q = (h[4] + q) >> 51;
  h[0] += 19 * q;
  uint64_t c;
  c = h[0] >> 51; h[0] &= MASK51; h[1] += c;
  c = h[1] >> 51; h[1] &= MASK51; h[2] += c;
  c = h[2] >> 51; h[2] &= MASK51; h[3] += c;
  c = h[3] >> 51; h[3] &= MASK51; h[4] += c;
  h[4] &= MASK51;

  uint64_t w0 = h[0] | (h[1] << 51);
  uint64_t w1 = (h[1] >> 13) | (h[2] << 38);
  uint64_t w2 = (h[2] >> 26) | (h[3] << 25);
  uint64_t w3 = (h[3] >> 39) | (h[4] << 12);
  uint64_t w[4] = {w0, w1, w2, w3};
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 8; j++) s[8 * i + j] = (uint8_t)(w[i] >> (8 * j));
}

static void fe_invert(fe out, const fe z) {
  // z^(p-2), p-2 = 2^255 - 21; square-and-multiply over the fixed
  // exponent (handshake-only path, simplicity over speed)
  static const uint8_t exp_bytes[32] = {
      // little-endian p-2
      0xeb, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x7f};
  fe result, base;
  fe_1(result);
  fe_copy(base, z);
  for (int i = 254; i >= 0; i--) {
    fe_sq(result, result);
    if ((exp_bytes[i / 8] >> (i % 8)) & 1) fe_mul(result, result, base);
  }
  fe_copy(out, result);
}

void ct_x25519_scalarmult(uint8_t out[32], const uint8_t scalar[32],
                          const uint8_t point[32]) {
  uint8_t e[32];
  memcpy(e, scalar, 32);
  e[0] &= 248;
  e[31] &= 127;
  e[31] |= 64;

  fe x1, x2, z2, x3, z3;
  fe_frombytes(x1, point);
  fe_1(x2);
  fe_0(z2);
  fe_copy(x3, x1);
  fe_1(z3);

  uint64_t swap = 0;
  for (int t = 254; t >= 0; t--) {
    uint64_t k_t = (e[t / 8] >> (t % 8)) & 1;
    swap ^= k_t;
    fe_cswap(x2, x3, swap);
    fe_cswap(z2, z3, swap);
    swap = k_t;

    fe a, aa, b, bb, eph, cc, d, da, cb, tmp;
    fe_add(a, x2, z2);
    fe_carry(a);
    fe_sq(aa, a);
    fe_sub(b, x2, z2);
    fe_carry(b);
    fe_sq(bb, b);
    fe_sub(eph, aa, bb);
    fe_carry(eph);
    fe_add(cc, x3, z3);
    fe_carry(cc);
    fe_sub(d, x3, z3);
    fe_carry(d);
    fe_mul(da, d, a);
    fe_mul(cb, cc, b);

    fe_add(tmp, da, cb);
    fe_carry(tmp);
    fe_sq(x3, tmp);
    fe_sub(tmp, da, cb);
    fe_carry(tmp);
    fe_sq(tmp, tmp);
    fe_mul(z3, x1, tmp);

    fe_mul(x2, aa, bb);
    fe_mul121665(tmp, eph);
    fe_add(tmp, aa, tmp);
    fe_carry(tmp);
    fe_mul(z2, eph, tmp);
  }
  fe_cswap(x2, x3, swap);
  fe_cswap(z2, z3, swap);

  fe zinv, res;
  fe_invert(zinv, z2);
  fe_mul(res, x2, zinv);
  fe_tobytes(out, res);
}

int ct_x25519(uint8_t out[32], const uint8_t scalar[32],
              const uint8_t point[32]) {
  ct_x25519_scalarmult(out, scalar, point);
  uint8_t acc = 0;
  for (int i = 0; i < 32; i++) acc |= out[i];
  return acc ? 0 : -1;  // all-zero = low-order input point
}

void ct_x25519_base(uint8_t out[32], const uint8_t scalar[32]) {
  uint8_t base[32] = {9};
  ct_x25519_scalarmult(out, scalar, base);
}

void ct_randombytes(uint8_t* out, uint32_t n) {
  // getrandom(2) first (no fd churn on the per-envelope nonce path);
  // fall back to a /dev/urandom fd opened once, like libsodium
  uint32_t off = 0;
  while (off < n) {
    ssize_t got = getrandom(out + off, n - off, 0);
    if (got < 0) {
      if (errno == EINTR) continue;
      break;  // ENOSYS etc. -> urandom fallback
    }
    off += (uint32_t)got;
  }
  if (off == n) return;
  static int urandom_fd = -1;
  if (urandom_fd < 0) urandom_fd = open("/dev/urandom", O_RDONLY);
  while (urandom_fd >= 0 && off < n) {
    ssize_t got = read(urandom_fd, out + off, n - off);
    if (got < 0) {
      if (errno == EINTR) continue;
      break;
    }
    off += (uint32_t)got;
  }
  if (off == n) return;
  // system randomness is part of the platform contract; fail loudly
  // rather than emit weak keys
  fprintf(stderr, "ct_randombytes: no system randomness available\n");
  abort();
}

void ct_free(uint8_t* p) { free(p); }

// ===========================================================================
// transport: poll-driven reliable-datagram UDP endpoint
// ===========================================================================

// 0xC8: header gained the 4-byte ack token (old builds must drop new
// frames immediately rather than misparse payload offsets)
static const uint8_t WIRE_MAGIC = 0xC8;
static const uint8_t T_DATA = 0;
static const uint8_t T_ACK = 1;
static const size_t FRAG_PAYLOAD = 1200;  // conservative sub-MTU
// magic type msg_id idx cnt token — the token is a per-message random
// value echoed in every ack: an ack is honored only when it carries
// the message's token, which only the destination (or an on-path
// observer, who can spoof source addresses anyway) has seen. A source
// == destination address check would add nothing on top and breaks
// multihomed / INADDR_ANY receivers, whose kernel may stamp ack
// replies with a different source IP than the one the sender dialed.
static const size_t HDR = 1 + 1 + 4 + 2 + 2 + 4;
static const int MAX_RETRIES = 30;
static const uint64_t RTO_MS = 40;       // initial retransmit timeout
static const uint64_t RTO_MAX_MS = 1000;
static const uint64_t DONE_TTL_MS = 30000;  // re-ack window for dups

static uint64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

struct Addr {
  uint32_t ip;    // network byte order
  uint16_t port;  // host byte order
  bool operator<(const Addr& o) const {
    return ip != o.ip ? ip < o.ip : port < o.port;
  }
};

struct OutMsg {
  Addr dest;
  uint32_t token = 0;  // random per message; acks must echo it
  std::vector<std::string> frags;  // full datagrams (header included)
  std::vector<bool> acked;
  size_t n_acked = 0;
  uint64_t last_send = 0;
  uint64_t rto = RTO_MS;
  int retries = 0;
};

struct InKey {
  Addr src;
  uint32_t msg_id;
  bool operator<(const InKey& o) const {
    if (src < o.src) return true;
    if (o.src < src) return false;
    return msg_id < o.msg_id;
  }
};

struct InMsg {
  std::vector<std::string> frags;
  std::vector<bool> have;
  size_t n_have = 0;
  uint64_t first_ms = 0;  // for expiring abandoned reassemblies
  uint32_t token = 0;     // first-seen token; mismatching frames dropped
};

struct Done {
  Addr src;
  uint32_t ip;
  uint16_t port;
  std::string payload;
};

struct Endpoint {
  int fd = -1;
  uint16_t port = 0;
  uint32_t next_msg_id = 1;
  std::map<uint32_t, OutMsg> outgoing;
  std::map<InKey, InMsg> incoming;
  std::map<InKey, uint64_t> completed;  // re-ack window
  std::deque<Done> done;
  uint64_t failed = 0;
  // loss injection (tests): permille of outbound datagrams dropped
  int loss_permille = 0;
  uint64_t loss_state = 0x9e3779b97f4a7c15ULL;
};

static bool lose(Endpoint* ep) {
  if (!ep->loss_permille) return false;
  // xorshift64* — deterministic per seed
  uint64_t x = ep->loss_state;
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  ep->loss_state = x;
  return (x * 0x2545F4914F6CDD1DULL >> 32) % 1000 < (uint64_t)ep->loss_permille;
}

static void raw_send(Endpoint* ep, const Addr& to, const std::string& dgram) {
  if (lose(ep)) return;
  struct sockaddr_in sa;
  memset(&sa, 0, sizeof(sa));
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = to.ip;
  sa.sin_port = htons(to.port);
  sendto(ep->fd, dgram.data(), dgram.size(), 0, (struct sockaddr*)&sa,
         sizeof(sa));
}

void* udp_create(const char* bind_ip, int port, char* err, int errlen) {
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) {
    snprintf(err, errlen, "socket: %s", strerror(errno));
    return nullptr;
  }
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
  struct sockaddr_in sa;
  memset(&sa, 0, sizeof(sa));
  sa.sin_family = AF_INET;
  sa.sin_port = htons((uint16_t)port);
  if (!bind_ip || !*bind_ip) {
    sa.sin_addr.s_addr = htonl(INADDR_ANY);
  } else if (inet_pton(AF_INET, bind_ip, &sa.sin_addr) != 1) {
    snprintf(err, errlen, "bad bind ip %s", bind_ip);
    close(fd);
    return nullptr;
  }
  if (bind(fd, (struct sockaddr*)&sa, sizeof(sa)) != 0) {
    snprintf(err, errlen, "bind: %s", strerror(errno));
    close(fd);
    return nullptr;
  }
  socklen_t slen = sizeof(sa);
  getsockname(fd, (struct sockaddr*)&sa, &slen);
  Endpoint* ep = new Endpoint();
  ep->fd = fd;
  ep->port = ntohs(sa.sin_port);
  // random initial msg id: a process restarting on the same port
  // within the peer's re-ack window must not collide with its former
  // self's ids, or its first messages are acked-but-dropped as dups
  uint32_t r = 0;
  ct_randombytes((uint8_t*)&r, sizeof(r));
  ep->next_msg_id = (r & 0x7fffffffu) | 1u;
  return ep;
}

int udp_port(void* h) { return ((Endpoint*)h)->port; }

void udp_close(void* h) {
  Endpoint* ep = (Endpoint*)h;
  if (ep->fd >= 0) close(ep->fd);
  delete ep;
}

void udp_set_loss(void* h, int permille, uint64_t seed) {
  Endpoint* ep = (Endpoint*)h;
  ep->loss_permille = permille;
  ep->loss_state = seed ? seed : 0x9e3779b97f4a7c15ULL;
}

long udp_send(void* h, const char* ip, int port, const uint8_t* buf,
              uint32_t len) {
  Endpoint* ep = (Endpoint*)h;
  Addr to;
  struct in_addr ia;
  if (inet_pton(AF_INET, ip, &ia) != 1) return -1;
  to.ip = ia.s_addr;
  to.port = (uint16_t)port;

  uint32_t id = ep->next_msg_id++;
  size_t n_frags = len == 0 ? 1 : (len + FRAG_PAYLOAD - 1) / FRAG_PAYLOAD;
  if (n_frags > 0xffff) return -1;  // > ~78 MB message

  OutMsg om;
  om.dest = to;
  ct_randombytes((uint8_t*)&om.token, sizeof(om.token));
  om.frags.reserve(n_frags);
  for (size_t i = 0; i < n_frags; i++) {
    size_t off = i * FRAG_PAYLOAD;
    size_t n = len - off < FRAG_PAYLOAD ? len - off : FRAG_PAYLOAD;
    std::string d;
    d.reserve(HDR + n);
    d.push_back((char)WIRE_MAGIC);
    d.push_back((char)T_DATA);
    uint8_t hdr[12];
    store32le(hdr, id);
    hdr[4] = i & 0xff; hdr[5] = (i >> 8) & 0xff;
    hdr[6] = n_frags & 0xff; hdr[7] = (n_frags >> 8) & 0xff;
    store32le(hdr + 8, om.token);
    d.append((const char*)hdr, 12);
    d.append((const char*)buf + off, n);
    om.frags.push_back(std::move(d));
  }
  om.acked.assign(n_frags, false);
  om.last_send = now_ms();
  for (auto& f : om.frags) raw_send(ep, to, f);
  ep->outgoing.emplace(id, std::move(om));
  return (long)id;
}

// Fire-and-forget variant: the message is framed exactly like
// udp_send (receivers reassemble / ack / dedup identically) but no
// retransmit state is kept — no pending entry, no retries, and a lost
// datagram never counts toward `failed`. This is the dial-probe path:
// NAT hole punching sprays hellos at addresses that are EXPECTED to
// blackhole (wrong ports, unopened mappings), and a reliable send to
// each would burn MAX_RETRIES of traffic and report hard failures for
// behavior that is routine. Callers retry at their own layer.
long udp_send_unreliable(void* h, const char* ip, int port,
                         const uint8_t* buf, uint32_t len) {
  Endpoint* ep = (Endpoint*)h;
  Addr to;
  struct in_addr ia;
  if (inet_pton(AF_INET, ip, &ia) != 1) return -1;
  to.ip = ia.s_addr;
  to.port = (uint16_t)port;

  uint32_t id = ep->next_msg_id++;
  size_t n_frags = len == 0 ? 1 : (len + FRAG_PAYLOAD - 1) / FRAG_PAYLOAD;
  if (n_frags > 0xffff) return -1;
  uint32_t token = 0;
  ct_randombytes((uint8_t*)&token, sizeof(token));
  for (size_t i = 0; i < n_frags; i++) {
    size_t off = i * FRAG_PAYLOAD;
    size_t n = len - off < FRAG_PAYLOAD ? len - off : FRAG_PAYLOAD;
    std::string d;
    d.reserve(HDR + n);
    d.push_back((char)WIRE_MAGIC);
    d.push_back((char)T_DATA);
    uint8_t hdr[12];
    store32le(hdr, id);
    hdr[4] = i & 0xff; hdr[5] = (i >> 8) & 0xff;
    hdr[6] = n_frags & 0xff; hdr[7] = (n_frags >> 8) & 0xff;
    store32le(hdr + 8, token);
    d.append((const char*)hdr, 12);
    d.append((const char*)buf + off, n);
    raw_send(ep, to, d);
  }
  return (long)id;
}

static void send_ack(Endpoint* ep, const Addr& to, uint32_t msg_id,
                     uint16_t idx, uint32_t token) {
  std::string d;
  d.push_back((char)WIRE_MAGIC);
  d.push_back((char)T_ACK);
  uint8_t hdr[12];
  store32le(hdr, msg_id);
  hdr[4] = idx & 0xff; hdr[5] = (idx >> 8) & 0xff;
  hdr[6] = 0; hdr[7] = 0;
  store32le(hdr + 8, token);
  d.append((const char*)hdr, 12);
  raw_send(ep, to, d);
}

int udp_poll(void* h) {
  Endpoint* ep = (Endpoint*)h;
  uint64_t now = now_ms();
  int processed = 0;
  uint8_t buf[2048];

  for (;;) {
    struct sockaddr_in sa;
    socklen_t slen = sizeof(sa);
    ssize_t n =
        recvfrom(ep->fd, buf, sizeof(buf), 0, (struct sockaddr*)&sa, &slen);
    if (n < 0) break;  // EAGAIN — drained
    if (n < (ssize_t)HDR || buf[0] != WIRE_MAGIC) continue;
    processed++;
    Addr src{sa.sin_addr.s_addr, ntohs(sa.sin_port)};
    uint8_t type = buf[1];
    uint32_t msg_id = load32le(buf + 2);
    uint16_t idx = (uint16_t)(buf[6] | (buf[7] << 8));
    uint16_t cnt = (uint16_t)(buf[8] | (buf[9] << 8));
    uint32_t token = load32le(buf + 10);

    if (type == T_ACK) {
      auto it = ep->outgoing.find(msg_id);
      // an ack counts only with the message's token echoed — forged
      // acks (guessed msg_id, spoofed source) cannot suppress
      // retransmission (see HDR comment for why token-only)
      if (it != ep->outgoing.end() && token == it->second.token &&
          idx < it->second.acked.size() && !it->second.acked[idx]) {
        it->second.acked[idx] = true;
        if (++it->second.n_acked == it->second.frags.size())
          ep->outgoing.erase(it);
      }
      continue;
    }
    if (type != T_DATA || cnt == 0 || idx >= cnt) continue;

    InKey key{src, msg_id};
    if (ep->completed.count(key)) {  // dup of a done message
      send_ack(ep, src, msg_id, idx, token);  // re-ack (lost-ack case)
      continue;
    }

    auto& im = ep->incoming[key];
    if (im.frags.empty()) {
      im.frags.resize(cnt);
      im.have.assign(cnt, false);
      im.first_ms = now;
      im.token = token;
    }
    // a reassembly is bound to its first-seen token: a spoofed DATA
    // frame (predictable msg_id, forged source) must neither inject
    // bytes into an in-flight message nor elicit an ack that makes the
    // real sender stop retransmitting that fragment. If a forger wins
    // the first-frame race the real frames are dropped unacked, the
    // sender burns its retries and reports the message failed —
    // a visible failure, never silent corruption.
    if (cnt != im.frags.size() || token != im.token) continue;
    send_ack(ep, src, msg_id, idx, token);  // covers lost acks too
    if (im.have[idx]) continue;
    im.frags[idx].assign((const char*)buf + HDR, n - HDR);
    im.have[idx] = true;
    if (++im.n_have == im.frags.size()) {
      std::string payload;
      for (auto& f : im.frags) payload += f;
      ep->done.push_back(Done{src, src.ip, src.port, std::move(payload)});
      ep->incoming.erase(key);
      ep->completed[key] = now;
    }
  }

  // retransmit
  for (auto it = ep->outgoing.begin(); it != ep->outgoing.end();) {
    OutMsg& om = it->second;
    if (now - om.last_send >= om.rto) {
      if (++om.retries > MAX_RETRIES) {
        ep->failed++;
        it = ep->outgoing.erase(it);
        continue;
      }
      for (size_t i = 0; i < om.frags.size(); i++)
        if (!om.acked[i]) raw_send(ep, om.dest, om.frags[i]);
      om.last_send = now;
      om.rto = om.rto * 2 > RTO_MAX_MS ? RTO_MAX_MS : om.rto * 2;
    }
    ++it;
  }

  // expire the re-ack window
  for (auto it = ep->completed.begin(); it != ep->completed.end();) {
    if (now - it->second > DONE_TTL_MS)
      it = ep->completed.erase(it);
    else
      ++it;
  }
  // expire abandoned partial reassemblies (sender gave up after
  // MAX_RETRIES, or a bogus source claimed a huge frag count) —
  // without this, half-arrived messages leak for the endpoint's life
  for (auto it = ep->incoming.begin(); it != ep->incoming.end();) {
    if (now - it->second.first_ms > DONE_TTL_MS)
      it = ep->incoming.erase(it);
    else
      ++it;
  }
  return processed;
}

int udp_recv(void* h, char* src_ip /* >= 64 bytes */, int* src_port,
             uint8_t** out, uint32_t* out_len) {
  Endpoint* ep = (Endpoint*)h;
  if (ep->done.empty()) return 1;
  Done& d = ep->done.front();
  struct in_addr ia;
  ia.s_addr = d.ip;
  inet_ntop(AF_INET, &ia, src_ip, 64);
  *src_port = d.port;
  *out = (uint8_t*)malloc(d.payload.size() ? d.payload.size() : 1);
  memcpy(*out, d.payload.data(), d.payload.size());
  *out_len = (uint32_t)d.payload.size();
  ep->done.pop_front();
  return 0;
}

int udp_pending(void* h) { return (int)((Endpoint*)h)->outgoing.size(); }

uint64_t udp_failed(void* h) { return ((Endpoint*)h)->failed; }

}  // extern "C"
