// kvlog — log-structured ordered KV store (the rebuild's LevelDB).
//
// The reference persists through `level` -> `leveldown`, a C++ LevelDB
// binding (/root/reference/package.json:13, crdt.js:18-20), used for:
// atomic multi-key batch writes (crdt.js:60-71), point gets
// (crdt.js:47), ordered prefix range scans (crdt.js:111-130), and
// close (crdt.js:134). This store implements exactly that capability
// surface natively:
//
//   - append-only write-ahead log, every record CRC32-guarded; a torn
//     or corrupt tail (crash mid-write) is detected and discarded on
//     open, everything before it replays — LevelDB's WAL recovery
//     contract
//   - atomic batches: one batch = one WAL record; it either fully
//     replays or (torn) fully disappears — the reference relies on
//     this for its update+sv+meta triple (crdt.js:60-71)
//   - in-memory ordered index (std::map) rebuilt on open = the
//     memtable; point get O(log n), ordered range scan via iterator
//   - compaction: rewrite live entries to a fresh log, fsync, atomic
//     rename over the old one — dropping overwritten/deleted history
//     (the snapshot-compaction hook the reference lacks, SURVEY.md Q3)
//
// Exposed as a flat C ABI for ctypes (no pybind11 in the image).
// Thread-safe behind one mutex: the access pattern is single-writer
// (one replica process per store, like the reference's one LevelDB
// dir per doc).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <new>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

// ---------------------------------------------------------------------------
// crc32 (IEEE, table-driven)
// ---------------------------------------------------------------------------

uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32(const uint8_t* buf, size_t len) {
  crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++) c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// little-endian helpers
// ---------------------------------------------------------------------------

void put_u32(std::string& out, uint32_t v) {
  out.push_back(static_cast<char>(v & 0xFF));
  out.push_back(static_cast<char>((v >> 8) & 0xFF));
  out.push_back(static_cast<char>((v >> 16) & 0xFF));
  out.push_back(static_cast<char>((v >> 24) & 0xFF));
}

uint32_t get_u32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

// WAL record: [u32 payload_len][u32 crc32(payload)][payload]
// payload: sequence of ops, op = [u8 kind][u32 klen][u32 vlen][key][val]
// kind: 0 = put, 1 = delete (vlen == 0)
constexpr uint8_t OP_PUT = 0;
constexpr uint8_t OP_DEL = 1;

struct Store {
  std::mutex mu;
  std::string path;
  int fd = -1;
  std::map<std::string, std::string> index;  // the memtable
  uint64_t log_bytes = 0;
  uint64_t live_bytes = 0;

  ~Store() {
    if (fd >= 0) ::close(fd);
  }
};

void set_err(char* errbuf, int errlen, const char* msg) {
  if (errbuf && errlen > 0) {
    std::snprintf(errbuf, static_cast<size_t>(errlen), "%s", msg);
  }
}

// Apply one decoded payload to the index. Returns false on malformed
// payload (only possible via API misuse — CRC already passed).
bool apply_payload(Store* s, const uint8_t* p, size_t len) {
  size_t off = 0;
  while (off < len) {
    if (off + 9 > len) return false;
    uint8_t kind = p[off];
    uint32_t klen = get_u32(p + off + 1);
    uint32_t vlen = get_u32(p + off + 5);
    off += 9;
    if (off + klen + vlen > len) return false;
    std::string key(reinterpret_cast<const char*>(p + off), klen);
    off += klen;
    if (kind == OP_PUT) {
      std::string val(reinterpret_cast<const char*>(p + off), vlen);
      off += vlen;
      auto it = s->index.find(key);
      if (it != s->index.end()) s->live_bytes -= it->first.size() + it->second.size();
      s->live_bytes += key.size() + val.size();
      s->index[key] = std::move(val);
    } else if (kind == OP_DEL) {
      if (vlen != 0) return false;
      auto it = s->index.find(key);
      if (it != s->index.end()) {
        s->live_bytes -= it->first.size() + it->second.size();
        s->index.erase(it);
      }
    } else {
      return false;
    }
  }
  return off == len;
}

// Replay the log at fd into the index. Truncates a torn/corrupt tail.
bool replay_log(Store* s, char* errbuf, int errlen) {
  off_t size = ::lseek(s->fd, 0, SEEK_END);
  if (size < 0) {
    set_err(errbuf, errlen, "lseek failed");
    return false;
  }
  std::vector<uint8_t> buf(static_cast<size_t>(size));
  if (size > 0) {
    ssize_t rd = ::pread(s->fd, buf.data(), buf.size(), 0);
    if (rd != size) {
      set_err(errbuf, errlen, "short read replaying log");
      return false;
    }
  }
  size_t off = 0;
  size_t good = 0;  // bytes of fully-valid records
  while (off + 8 <= buf.size()) {
    uint32_t plen = get_u32(buf.data() + off);
    uint32_t want_crc = get_u32(buf.data() + off + 4);
    if (off + 8 + plen > buf.size()) break;  // torn tail
    const uint8_t* payload = buf.data() + off + 8;
    if (crc32(payload, plen) != want_crc) break;  // corrupt tail
    if (!apply_payload(s, payload, plen)) break;
    off += 8 + plen;
    good = off;
  }
  if (good < static_cast<size_t>(size)) {
    // discard the torn tail so the next append starts at a record
    // boundary (LevelDB logs the same "dropping N bytes" recovery)
    if (::ftruncate(s->fd, static_cast<off_t>(good)) != 0) {
      set_err(errbuf, errlen, "ftruncate of torn tail failed");
      return false;
    }
  }
  s->log_bytes = good;
  return true;
}

// Append one framed record; returns 0 on success.
int append_record(Store* s, const std::string& payload) {
  std::string frame;
  frame.reserve(8 + payload.size());
  put_u32(frame, static_cast<uint32_t>(payload.size()));
  put_u32(frame, crc32(reinterpret_cast<const uint8_t*>(payload.data()),
                       payload.size()));
  frame += payload;
  size_t done = 0;
  while (done < frame.size()) {
    ssize_t wr = ::pwrite(s->fd, frame.data() + done, frame.size() - done,
                          static_cast<off_t>(s->log_bytes + done));
    if (wr < 0) {
      if (errno == EINTR) continue;
      // roll back the partial write so the in-file tail stays at a
      // record boundary for this process; crash recovery would drop
      // it anyway via CRC
      ::ftruncate(s->fd, static_cast<off_t>(s->log_bytes));
      return -1;
    }
    done += static_cast<size_t>(wr);
  }
  s->log_bytes += frame.size();
  return 0;
}

void encode_op(std::string& payload, uint8_t kind, const uint8_t* key,
               uint32_t klen, const uint8_t* val, uint32_t vlen) {
  payload.push_back(static_cast<char>(kind));
  put_u32(payload, klen);
  put_u32(payload, vlen);
  payload.append(reinterpret_cast<const char*>(key), klen);
  if (vlen) payload.append(reinterpret_cast<const char*>(val), vlen);
}

uint8_t* dup_bytes(const std::string& s) {
  uint8_t* p = static_cast<uint8_t*>(std::malloc(s.size() ? s.size() : 1));
  if (p && !s.empty()) std::memcpy(p, s.data(), s.size());
  return p;
}

struct Iter {
  // snapshot of the matching range at creation time: iteration stays
  // valid across concurrent writes (same isolation the reference gets
  // from LevelDB's createReadStream snapshot, crdt.js:111-130)
  std::vector<std::pair<std::string, std::string>> rows;
  size_t pos = 0;
};

}  // namespace

extern "C" {

typedef Store kv_t;
typedef Iter kv_iter_t;

kv_t* kv_open(const char* path, char* errbuf, int errlen) {
  Store* s = new (std::nothrow) Store();
  if (!s) {
    set_err(errbuf, errlen, "out of memory");
    return nullptr;
  }
  s->path = path;
  s->fd = ::open(path, O_RDWR | O_CREAT, 0644);
  if (s->fd < 0) {
    set_err(errbuf, errlen, std::strerror(errno));
    delete s;
    return nullptr;
  }
  if (!replay_log(s, errbuf, errlen)) {
    delete s;
    return nullptr;
  }
  return s;
}

void kv_close(kv_t* s) { delete s; }

int kv_put(kv_t* s, const uint8_t* key, uint32_t klen, const uint8_t* val,
           uint32_t vlen) {
  std::lock_guard<std::mutex> lock(s->mu);
  std::string payload;
  encode_op(payload, OP_PUT, key, klen, val, vlen);
  if (append_record(s, payload) != 0) return -1;
  return apply_payload(s, reinterpret_cast<const uint8_t*>(payload.data()),
                       payload.size())
             ? 0
             : -1;
}

int kv_del(kv_t* s, const uint8_t* key, uint32_t klen) {
  std::lock_guard<std::mutex> lock(s->mu);
  std::string payload;
  encode_op(payload, OP_DEL, key, klen, nullptr, 0);
  if (append_record(s, payload) != 0) return -1;
  return apply_payload(s, reinterpret_cast<const uint8_t*>(payload.data()),
                       payload.size())
             ? 0
             : -1;
}

// buf = concatenated ops in the payload format; applied atomically
// (single WAL record).
int kv_batch(kv_t* s, const uint8_t* buf, uint32_t len) {
  std::lock_guard<std::mutex> lock(s->mu);
  // validate before writing: a malformed batch must not reach the log
  {
    Store probe;  // throwaway index; cheap for validation-sized batches
    if (!apply_payload(&probe, buf, len)) return -2;
  }
  std::string payload(reinterpret_cast<const char*>(buf), len);
  if (append_record(s, payload) != 0) return -1;
  return apply_payload(s, buf, len) ? 0 : -1;
}

int kv_get(kv_t* s, const uint8_t* key, uint32_t klen, uint8_t** val,
           uint32_t* vlen) {
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->index.find(std::string(reinterpret_cast<const char*>(key), klen));
  if (it == s->index.end()) return 1;
  *val = dup_bytes(it->second);
  if (!*val) return -1;
  *vlen = static_cast<uint32_t>(it->second.size());
  return 0;
}

void kv_free(uint8_t* p) { std::free(p); }

// Ordered scan over [start, end); empty end = to the last key.
kv_iter_t* kv_scan(kv_t* s, const uint8_t* start, uint32_t slen,
                   const uint8_t* end, uint32_t elen) {
  std::lock_guard<std::mutex> lock(s->mu);
  Iter* it = new (std::nothrow) Iter();
  if (!it) return nullptr;
  std::string lo(reinterpret_cast<const char*>(start), slen);
  std::string hi(reinterpret_cast<const char*>(end), elen);
  if (elen && hi <= lo) return it;  // inverted/empty range
  auto b = s->index.lower_bound(lo);
  auto e = elen ? s->index.lower_bound(hi) : s->index.end();
  for (; b != e; ++b) it->rows.emplace_back(b->first, b->second);
  return it;
}

int kv_iter_next(kv_iter_t* it, uint8_t** key, uint32_t* klen, uint8_t** val,
                 uint32_t* vlen) {
  if (it->pos >= it->rows.size()) return 1;
  const auto& kv = it->rows[it->pos++];
  *key = dup_bytes(kv.first);
  *val = dup_bytes(kv.second);
  if (!*key || !*val) return -1;
  *klen = static_cast<uint32_t>(kv.first.size());
  *vlen = static_cast<uint32_t>(kv.second.size());
  return 0;
}

void kv_iter_close(kv_iter_t* it) { delete it; }

int kv_sync(kv_t* s) {
  std::lock_guard<std::mutex> lock(s->mu);
  return ::fsync(s->fd) == 0 ? 0 : -1;
}

// Rewrite live entries to <path>.compact, fsync, rename over the log.
int kv_compact(kv_t* s) {
  std::lock_guard<std::mutex> lock(s->mu);
  std::string tmp_path = s->path + ".compact";
  int tfd = ::open(tmp_path.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (tfd < 0) return -1;
  // one record per entry keeps records small and the tail torn-safe
  uint64_t off = 0;
  for (const auto& kv : s->index) {
    std::string payload;
    encode_op(payload, OP_PUT,
              reinterpret_cast<const uint8_t*>(kv.first.data()),
              static_cast<uint32_t>(kv.first.size()),
              reinterpret_cast<const uint8_t*>(kv.second.data()),
              static_cast<uint32_t>(kv.second.size()));
    std::string frame;
    put_u32(frame, static_cast<uint32_t>(payload.size()));
    put_u32(frame, crc32(reinterpret_cast<const uint8_t*>(payload.data()),
                         payload.size()));
    frame += payload;
    size_t done = 0;
    while (done < frame.size()) {
      ssize_t wr = ::pwrite(tfd, frame.data() + done, frame.size() - done,
                            static_cast<off_t>(off + done));
      if (wr < 0) {
        if (errno == EINTR) continue;
        ::close(tfd);
        ::unlink(tmp_path.c_str());
        return -1;
      }
      done += static_cast<size_t>(wr);
    }
    off += frame.size();
  }
  if (::fsync(tfd) != 0 || ::rename(tmp_path.c_str(), s->path.c_str()) != 0) {
    ::close(tfd);
    ::unlink(tmp_path.c_str());
    return -1;
  }
  ::close(s->fd);
  s->fd = tfd;
  s->log_bytes = off;
  return 0;
}

uint64_t kv_count(kv_t* s) {
  std::lock_guard<std::mutex> lock(s->mu);
  return s->index.size();
}

uint64_t kv_log_size(kv_t* s) {
  std::lock_guard<std::mutex> lock(s->mu);
  return s->log_bytes;
}

uint64_t kv_live_size(kv_t* s) {
  std::lock_guard<std::mutex> lock(s->mu);
  return s->live_bytes;
}

}  // extern "C"
