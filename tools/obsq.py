#!/usr/bin/env python
"""obsq — query flight-recorder JSONL dumps offline.

The flight recorder answers "what happened at the sync seams" one
process at a time; the ROADMAP item-2 fleet will dump one ring per
server process, and the questions the divergence sentinel raises are
CROSS-dump questions ("which doc forked, and what did each side see
right before?"). This CLI loads one or more dumps (each line one
event, as ``FlightRecorder.dump_jsonl`` writes them), merges them on
the shared monotonic timebase, and answers the recurring postmortem
queries without a notebook:

    python tools/obsq.py summary  dump_a.jsonl dump_b.jsonl
    python tools/obsq.py filter   dump.jsonl --kind update.recv --doc room
    python tools/obsq.py filter   dump.jsonl --tid 7:3
    python tools/obsq.py latency  dump_a.jsonl dump_b.jsonl
    python tools/obsq.py diverge  dump_a.jsonl dump_b.jsonl

- ``summary``  — event counts per kind and per source file, time range.
- ``filter``   — events matching ``--kind`` (exact), ``--doc``
  (matches an event's ``doc`` or ``topic``), ``--peer`` (``peer`` or
  ``replica``), ``--tid`` (``client:seq`` prefix of the origin trace
  id), printed as JSONL oldest-first with a ``_src`` field naming the
  dump each event came from.
- ``latency``  — pairs ``update.send``/``update.recv`` events by
  trace id ACROSS dumps and prints propagation-latency percentiles
  (p50/p90/p99/max) plus the hop-count distribution (round 18: recv
  events carry ``hop``).
- ``diverge``  — finds ``divergence`` events and correlates the two
  dumps around each: the last ``--context`` events from every source
  before the divergence timestamp, filtered to its topic, digests
  compared side by side — the "what did each side see" question.

Exit code: 0 on success (even when nothing matches), 2 on unreadable
input. Stdlib-only (the analysis lane must not import jax).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional


def load_events(paths: List[str]) -> List[Dict[str, Any]]:
    """All events of all dumps, oldest-first on the shared monotonic
    timebase, each tagged with ``_src`` (basename of its dump)."""
    import os

    events: List[Dict[str, Any]] = []
    for path in paths:
        src = os.path.basename(path)
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, start=1):
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError as exc:
                    # surfaces as exit 2 in main() — same unreadable-
                    # input class as a missing file
                    raise ValueError(
                        f"{path}:{lineno}: not JSONL ({exc})"
                    ) from None
                ev["_src"] = src
                events.append(ev)
    events.sort(key=lambda e: (e.get("ts", 0.0), e["_src"]))
    return events


def match(ev: Dict[str, Any], *, kind: Optional[str] = None,
          doc: Optional[str] = None, peer: Optional[str] = None,
          tid: Optional[str] = None) -> bool:
    if kind is not None and ev.get("kind") != kind:
        return False
    if doc is not None and \
            str(ev.get("doc", ev.get("topic"))) != doc:
        return False
    if peer is not None and \
            str(ev.get("peer", ev.get("replica"))) != peer:
        return False
    if tid is not None:
        t = ev.get("tid")
        if not isinstance(t, (list, tuple)) or len(t) < 2:
            return False
        want = tid.split(":")
        if [str(x) for x in t[:len(want)]] != want:
            return False
    return True


def _percentiles(sorted_vals: List[float]) -> Dict[str, float]:
    def q(p: float) -> float:
        if not sorted_vals:
            return 0.0
        i = min(len(sorted_vals) - 1,
                max(0, int(p * len(sorted_vals) + 0.5) - 1))
        return sorted_vals[i]

    return {
        "count": len(sorted_vals),
        "p50_s": q(0.50),
        "p90_s": q(0.90),
        "p99_s": q(0.99),
        "max_s": sorted_vals[-1] if sorted_vals else 0.0,
    }


def cmd_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    kinds: Dict[str, int] = {}
    srcs: Dict[str, int] = {}
    for e in events:
        kinds[e.get("kind", "?")] = kinds.get(e.get("kind", "?"), 0) + 1
        srcs[e["_src"]] = srcs.get(e["_src"], 0) + 1
    ts = [e["ts"] for e in events if isinstance(
        e.get("ts"), (int, float))]
    return {
        "events": len(events),
        "kinds": dict(sorted(kinds.items())),
        "sources": dict(sorted(srcs.items())),
        "ts_range_s": (
            round(max(ts) - min(ts), 6) if len(ts) > 1 else 0.0
        ),
    }


def cmd_latency(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """send/recv pairing by trace id across every loaded dump: the
    cross-process propagation story. One send may fan out to many
    receivers; every (send, recv) pair contributes one latency."""
    sends: Dict[tuple, float] = {}
    for e in events:
        t = e.get("tid")
        if e.get("kind") == "update.send" and isinstance(
                t, (list, tuple)) and len(t) >= 3:
            sends.setdefault((t[0], t[1]), float(t[2]))
    lats: List[float] = []
    unmatched_recv = 0
    hops: Dict[str, int] = {}
    for e in events:
        if e.get("kind") != "update.recv":
            continue
        t = e.get("tid")
        key = (t[0], t[1]) if isinstance(
            t, (list, tuple)) and len(t) >= 2 else None
        if key is not None and key in sends and isinstance(
                e.get("ts"), (int, float)):
            lats.append(max(0.0, e["ts"] - sends[key]))
        else:
            unmatched_recv += 1
        h = e.get("hop")
        hkey = str(h) if isinstance(h, int) else "unknown"
        hops[hkey] = hops.get(hkey, 0) + 1
    lats.sort()
    return {
        "sends": len(sends),
        "pairs": len(lats),
        "unmatched_recv": unmatched_recv,
        "propagation": _percentiles(lats),
        "hops": dict(sorted(hops.items())),
    }


def cmd_diverge(events: List[Dict[str, Any]],
                context: int = 8) -> Dict[str, Any]:
    """Correlate divergence events across the loaded dumps: for each,
    the trailing ``context`` events per source on the same topic
    before the divergence, with digests surfaced for eyeballing which
    update the two sides last disagreed on."""
    out: List[Dict[str, Any]] = []
    divs = [e for e in events if e.get("kind") == "divergence"]
    for div in divs:
        topic = div.get("topic")
        ts = div.get("ts", float("inf"))
        per_src: Dict[str, List[Dict[str, Any]]] = {}
        for e in events:
            if e is div or e.get("ts", 0.0) > ts:
                continue
            if topic is not None and \
                    e.get("topic") not in (None, topic):
                continue
            per_src.setdefault(e["_src"], []).append(e)
        ctx = {
            src: [
                {k: ev.get(k) for k in
                 ("ts", "kind", "peer", "replica", "digest", "tid",
                  "hop", "size") if k in ev}
                for ev in evs[-context:]
            ]
            for src, evs in sorted(per_src.items())
        }
        digests = {
            src: [e.get("digest") for e in evs if e.get("digest")]
            for src, evs in ctx.items()
        }
        common = set.intersection(
            *(set(d) for d in digests.values())
        ) if len(digests) > 1 else set()
        out.append({
            "divergence": {
                k: div.get(k) for k in
                ("ts", "topic", "peer", "replica", "local_digest",
                 "peer_digest", "doc") if k in div
            },
            "src": div["_src"],
            "context": ctx,
            "last_common_digests": sorted(common),
        })
    return {"divergences": len(divs), "events": out}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="obsq",
        description="query flight-recorder JSONL dumps",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("summary", "filter", "latency", "diverge"):
        p = sub.add_parser(name)
        p.add_argument("dumps", nargs="+",
                       help="flight-recorder JSONL dump file(s)")
        if name == "filter":
            p.add_argument("--kind")
            p.add_argument("--doc")
            p.add_argument("--peer")
            p.add_argument("--tid",
                           help="client:seq prefix of the trace id")
        if name == "diverge":
            p.add_argument("--context", type=int, default=8)
    args = ap.parse_args(argv)
    try:
        events = load_events(args.dumps)
    except (OSError, ValueError) as exc:
        print(f"obsq: {exc}", file=sys.stderr)
        return 2

    if args.cmd == "filter":
        for e in events:
            if match(e, kind=args.kind, doc=args.doc,
                     peer=args.peer, tid=args.tid):
                print(json.dumps(e, sort_keys=True, default=str))
        return 0
    if args.cmd == "summary":
        print(json.dumps(cmd_summary(events), indent=1,
                         sort_keys=True))
        return 0
    if args.cmd == "latency":
        print(json.dumps(cmd_latency(events), indent=1,
                         sort_keys=True))
        return 0
    if args.cmd == "diverge":
        print(json.dumps(cmd_diverge(events, args.context),
                         indent=1, sort_keys=True))
        return 0
    return 2  # unreachable (argparse enforces the subcommand)


if __name__ == "__main__":
    sys.exit(main())
