#!/usr/bin/env python
"""obsq — query flight-recorder event streams, offline or live.

The flight recorder answers "what happened at the sync seams" one
process at a time; the round-19 distributed-tracing plane makes the
CROSS-process questions first-class. This CLI is a THIN shell over
the shared analysis core in :mod:`crdt_tpu.obs.propagation` — the
same tid-pairing, per-route hop-lag decomposition, path
reconstruction, and divergence correlation the live fleet collector
serves at ``/fleet`` — so offline dumps and live scrapes share one
implementation (round-19 satellite; the logic used to live here).

Inputs are flight-recorder JSONL dumps (as ``FlightRecorder.
dump_jsonl`` writes them) — or, live, ``http(s)://`` base URLs of
running ``ObsHTTPServer`` processes (their ``/events`` tail is
fetched; ``obsq diverge http://a:9001 http://b:9002`` promotes the
divergence postmortem from offline to live):

    python tools/obsq.py summary  dump_a.jsonl dump_b.jsonl
    python tools/obsq.py filter   dump.jsonl --kind update.recv --doc room
    python tools/obsq.py filter   dump.jsonl --tid 7:3
    python tools/obsq.py latency  dump_a.jsonl dump_b.jsonl
    python tools/obsq.py paths    dump_a.jsonl http://127.0.0.1:9001
    python tools/obsq.py diverge  dump_a.jsonl dump_b.jsonl

- ``summary``  — event counts per kind and per source, time range.
- ``filter``   — events matching ``--kind`` (exact), ``--doc``
  (matches an event's ``doc`` or ``topic``), ``--peer`` (``peer`` or
  ``replica``), ``--tid`` (``client:seq`` prefix of the origin trace
  id), printed as JSONL oldest-first with a ``_src`` field naming the
  source each event came from.
- ``latency``  — pairs origin events (``update.send``,
  ``sync.answer``, ``ae.delta``) with ``update.recv`` by trace id
  ACROSS sources: propagation percentiles, hop-count distribution,
  and per-ROUTE leg-lag percentiles decomposed from the carried path
  records (``crdt_tpu.obs.propagation.pair_latency``).
- ``paths``    — full path reconstruction: the fraction of traced
  receives whose complete per-hop path (route tags + origin pairing)
  reconstructs across sources, with an incomplete sample for
  debugging (``reconstruct_paths``).
- ``diverge``  — correlates ``divergence`` events with each source's
  trailing context and the last common digests
  (``correlate_divergences``).
- ``control``  — round 22: query CONTROL-LEDGER rows (as
  ``ControlLedger.dump_jsonl`` writes them, or a live ``/control``
  URL), ``--tenant T`` / ``--tick-range A:B`` filtered, optionally
  joined with an SLO snapshot (``--slo report.json``) so "why did
  tenant T's budget drop at tick 412" is answerable from dumps
  alone:

      python tools/obsq.py control ledger.jsonl --tenant flood! \\
          --tick-range 400:420 --slo slo_report.json

Exit code: 0 on success (even when nothing matches), 2 on unreadable
input. Stdlib + ``crdt_tpu.obs.propagation`` only — the analysis
lane must not import jax (the package imports it lazily).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from crdt_tpu.obs.propagation import (
    correlate_divergences,
    pair_latency,
    reconstruct_paths,
)


def _read_source(path: str) -> List[str]:
    """Lines of one source: a JSONL file, or — for http(s) URLs — a
    live ObsHTTPServer's ``/events`` tail."""
    if path.startswith(("http://", "https://")):
        import urllib.request

        url = path.rstrip("/")
        if not url.endswith("/events"):
            url += "/events"
        try:
            with urllib.request.urlopen(url, timeout=5.0) as resp:
                return resp.read().decode(
                    "utf-8", "replace"
                ).splitlines()
        except OSError as exc:
            raise OSError(f"{path}: {exc}") from None
    with open(path, encoding="utf-8") as f:
        return f.read().splitlines()


def _src_name(path: str) -> str:
    import os

    if path.startswith(("http://", "https://")):
        return path.split("//", 1)[1].rstrip("/")
    return os.path.basename(path)


def load_events(paths: List[str]) -> List[Dict[str, Any]]:
    """All events of all sources, oldest-first on the shared
    monotonic timebase, each tagged with ``_src``."""
    events: List[Dict[str, Any]] = []
    for path in paths:
        src = _src_name(path)
        for lineno, line in enumerate(_read_source(path), start=1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError as exc:
                # surfaces as exit 2 in main() — same unreadable-
                # input class as a missing file
                raise ValueError(
                    f"{path}:{lineno}: not JSONL ({exc})"
                ) from None
            ev["_src"] = src
            events.append(ev)
    events.sort(key=lambda e: (e.get("ts", 0.0), e["_src"]))
    return events


def match(ev: Dict[str, Any], *, kind: Optional[str] = None,
          doc: Optional[str] = None, peer: Optional[str] = None,
          tid: Optional[str] = None) -> bool:
    if kind is not None and ev.get("kind") != kind:
        return False
    if doc is not None and \
            str(ev.get("doc", ev.get("topic"))) != doc:
        return False
    if peer is not None and \
            str(ev.get("peer", ev.get("replica"))) != peer:
        return False
    if tid is not None:
        t = ev.get("tid")
        if not isinstance(t, (list, tuple)) or len(t) < 2:
            return False
        want = tid.split(":")
        if [str(x) for x in t[:len(want)]] != want:
            return False
    return True


def cmd_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    kinds: Dict[str, int] = {}
    srcs: Dict[str, int] = {}
    for e in events:
        kinds[e.get("kind", "?")] = kinds.get(e.get("kind", "?"), 0) + 1
        srcs[e["_src"]] = srcs.get(e["_src"], 0) + 1
    ts = [e["ts"] for e in events if isinstance(
        e.get("ts"), (int, float))]
    return {
        "events": len(events),
        "kinds": dict(sorted(kinds.items())),
        "sources": dict(sorted(srcs.items())),
        "ts_range_s": (
            round(max(ts) - min(ts), 6) if len(ts) > 1 else 0.0
        ),
    }


# thin aliases over the shared core — kept as module attributes so
# existing callers (tests, notebooks) keep working
cmd_latency = pair_latency
cmd_paths = reconstruct_paths
cmd_diverge = correlate_divergences


# -- the control-ledger lane (round 22) ------------------------------


def load_control_rows(paths: List[str]) -> List[Dict[str, Any]]:
    """Control-ledger rows from JSONL dumps or live ``/control``
    URLs (the endpoint answers a JSON report whose ``rows`` is the
    ledger tail), each tagged ``_src``, sorted by (tick, source)."""
    rows: List[Dict[str, Any]] = []
    for path in paths:
        src = _src_name(path)
        if path.startswith(("http://", "https://")):
            import urllib.request

            url = path.rstrip("/")
            if not url.endswith("/control"):
                url += "/control"
            try:
                with urllib.request.urlopen(
                    url, timeout=5.0
                ) as resp:
                    body = resp.read().decode("utf-8", "replace")
            except OSError as exc:
                raise OSError(f"{path}: {exc}") from None
            try:
                report = json.loads(body)
            except ValueError as exc:
                raise ValueError(
                    f"{path}: not JSON ({exc})") from None
            found = report.get("rows") or []
        else:
            found = []
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, start=1):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        found.append(json.loads(line))
                    except ValueError as exc:
                        raise ValueError(
                            f"{path}:{lineno}: not JSONL ({exc})"
                        ) from None
        for r in found:
            if isinstance(r, dict):
                rows.append(dict(r, _src=src))
    rows.sort(key=lambda r: (r.get("tick", 0), r["_src"]))
    return rows


def cmd_control(rows: List[Dict[str, Any]], *,
                tenant: Optional[str] = None,
                tick_range: Optional[str] = None,
                slo: Optional[str] = None) -> List[Dict[str, Any]]:
    """Filter ledger rows by tenant and tick range; with ``slo`` (a
    JSON file holding ``SLOLedger.report()``, or the ``slo`` section
    of a ``/snapshot``), each row gains an ``slo`` field with the
    tenant's breach/burn/route summary — the decision and the sensor
    history it acted on, joined offline."""
    lo = hi = None
    if tick_range:
        a, _, b = tick_range.partition(":")
        lo = int(a) if a else None
        hi = int(b) if b else None
    slo_tenants: Dict[str, Any] = {}
    if slo:
        with open(slo, encoding="utf-8") as f:
            snap = json.load(f)
        # accept a bare SLOLedger.report() or a /snapshot with an
        # "slo" section
        slo_tenants = (snap.get("slo", snap) or {}).get(
            "tenants") or {}
    out = []
    for r in rows:
        t = r.get("tick", 0)
        if lo is not None and t < lo:
            continue
        if hi is not None and t > hi:
            continue
        if tenant is not None and r.get("tenant") != tenant:
            continue
        if slo_tenants and r.get("tenant") in slo_tenants:
            s = slo_tenants[r["tenant"]]
            r = dict(r, slo={
                "breaches": s.get("breaches"),
                "burn_rate": s.get("burn_rate"),
                "routes": s.get("routes"),
            })
        out.append(r)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="obsq",
        description="query flight-recorder event streams "
                    "(JSONL dumps or live /events URLs)",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("summary", "filter", "latency", "paths", "diverge"):
        p = sub.add_parser(name)
        p.add_argument("dumps", nargs="+",
                       help="flight-recorder JSONL dump file(s) "
                            "or live ObsHTTPServer URL(s)")
        if name == "filter":
            p.add_argument("--kind")
            p.add_argument("--doc")
            p.add_argument("--peer")
            p.add_argument("--tid",
                           help="client:seq prefix of the trace id")
        if name == "diverge":
            p.add_argument("--context", type=int, default=8)
    pc = sub.add_parser("control")
    pc.add_argument("dumps", nargs="+",
                    help="control-ledger JSONL dump(s) or live "
                         "/control URL(s)")
    pc.add_argument("--tenant")
    pc.add_argument("--tick-range", metavar="A:B",
                    help="inclusive tick window (either side open)")
    pc.add_argument("--slo", metavar="REPORT.json",
                    help="SLO report (or /snapshot) to join per "
                         "tenant")
    args = ap.parse_args(argv)
    if args.cmd == "control":
        try:
            rows = load_control_rows(args.dumps)
            out = cmd_control(rows, tenant=args.tenant,
                              tick_range=args.tick_range,
                              slo=args.slo)
        except (OSError, ValueError) as exc:
            print(f"obsq: {exc}", file=sys.stderr)
            return 2
        for r in out:
            print(json.dumps(r, sort_keys=True, default=str))
        return 0
    try:
        events = load_events(args.dumps)
    except (OSError, ValueError) as exc:
        print(f"obsq: {exc}", file=sys.stderr)
        return 2

    if args.cmd == "filter":
        for e in events:
            if match(e, kind=args.kind, doc=args.doc,
                     peer=args.peer, tid=args.tid):
                print(json.dumps(e, sort_keys=True, default=str))
        return 0
    if args.cmd == "summary":
        print(json.dumps(cmd_summary(events), indent=1,
                         sort_keys=True))
        return 0
    if args.cmd == "latency":
        print(json.dumps(cmd_latency(events), indent=1,
                         sort_keys=True))
        return 0
    if args.cmd == "paths":
        print(json.dumps(cmd_paths(events), indent=1,
                         sort_keys=True))
        return 0
    if args.cmd == "diverge":
        print(json.dumps(cmd_diverge(events, args.context),
                         indent=1, sort_keys=True))
        return 0
    return 2  # unreachable (argparse enforces the subcommand)


if __name__ == "__main__":
    sys.exit(main())
