"""Repo tooling: bench diffing (`metrics_diff`), kernel profiling,
and the static invariant checker (`tools.crdtlint`)."""
