#!/usr/bin/env python
"""Compare two BENCH_OUT.json artifacts: regression table + exit code.

The round-5 verdict's complaint was perf evidence living in session
logs; PR 3's bench embeds the tracer report into the committed
artifact, and THIS tool is the follow-through — a one-command answer
to "did this change regress anything?", usable by hand or as a CI
gate:

    python tools/metrics_diff.py OLD.json NEW.json [--threshold 0.2]

Compared (whatever of these both artifacts carry):

- headline metrics: ``value`` (direction inferred from ``unit``),
  ``vs_baseline``, ``vs_python_oracle``, ``kernel_dispatch_ops_per_s``
  (higher = better), ``dispatch_floor_ms`` (lower = better);
- the sort-diet kernel evidence (round 12): per-size
  ``kernel_sweep_net_ms`` and the per-primitive
  ``kernel_ablation.{sort,map_winners,rank}_ms.{pallas,jnp}`` legs
  (lower = better, seconds noise floor), plus
  ``kernel_ablation.sort_map_speedup`` (higher = better, never
  muted) — so the ROADMAP item-3 >=2x claim is a regression-gated
  artifact, not a doc sentence;
- scale/section digests: ``scale_run.vs_baseline``,
  ``scale_run.stream_vs_oneshot``, ``scale_run.rounds.vs_cold_replay``;
- tracer phase spans: per-span ``p50_s``/``p99_s``/``total_s`` from
  the embedded ``tracer`` report (lower = better);
- the serial contenders' ``phases_device_s`` entries (lower = better);
- bytes-on-link: the ``xfer.*`` counters/gauges from the embedded
  tracer report and the headline/scale ``xfer`` digests
  (``h2d_bytes``/``d2h_bytes``/``narrowed_ratio`` — LOWER is better:
  the transfer diet is regression-gated like every latency);
- static analysis: ``lint.findings`` / ``lint.baselined`` from the
  embedded crdtlint digest (lower = better, no noise floor) — a PR
  that grows the crdtlint baseline or adds inline disables moves the
  count and lands in this table, even though tier-1 still passes;
- multi-tenant packing (round 14, ``bench --multitenant``):
  ``multitenant.docs_converged_per_s`` / ``.speedup`` (higher =
  better) and ``.p99_per_doc_ms`` / ``.dispatches_per_tick`` (lower
  = better), plus the tenant-scoped shed counters from the tracer
  report (lower = better, like every guard ladder);
- delta ticks (round 15, the steady-state ``--multitenant`` leg):
  ``multitenant.steady.docs_per_s`` / ``.speedup`` (higher = better
  — the >=10x-over-full-replay bar is a gated artifact) and the
  eviction flood's ``steady.eviction.peak_bytes`` (lower = better),
  plus ``tenant.resident_evictions`` / ``tenant.delta_fallbacks``
  under the guard prefixes;
- observability v2 (round 18): ``slo.breaches`` (total objective
  misses, shed included — lower = better, counts),
  ``timeline.stall_ms`` (blocked-fetch time per tick, lower) and
  ``timeline.overlap_efficiency`` (HIGHER = better: a drop means the
  double-buffered dispatch pipeline re-serialized), from the embedded
  tracer report; plus the run-stable ``--multitenant`` digests —
  ``multitenant.timeline.mean_overlap_efficiency`` (higher),
  ``multitenant.timeline.stall_ms_total`` (lower, ms noise floor),
  and the chaos flooder's deterministic
  ``multitenant.flood.slo_flooder.breaches`` (lower).

- distributed tracing (round 19): the ``fleet_trace.*`` section keys
  from ``bench.py --fleet-trace`` (``procs`` / ``pair_rate`` higher,
  ``wire_overhead_ratio`` lower), the collector federation gauges
  (``collector.procs`` / ``collector.pair_rate``, higher, counts),
  ``propagation.wire_overhead_ratio`` /
  ``propagation.malformed_contexts`` (lower), and the per-route
  ``replica.hop_lag{route=...}`` latency histograms via the span
  loop (lower, seconds noise floor).

- pooled resident matrix (round 20): the steady dispatch floor —
  ``multitenant.steady.device_dispatches_per_tick`` (lower = better,
  COUNT semantics: never muted by the ms noise floor — the O(1)
  batching claim must not rot behind cheap dispatches) and the
  pool's ``multitenant.steady.pool_peak_bytes`` (lower); the
  already-gated ``timeline.overlap_efficiency`` keys hold the
  double-buffer overlap through the pooled route.

Prints a table (one row per metric: old, new, delta, verdict) and
exits non-zero when any metric regressed past ``--threshold``
(relative; default 0.20 = 20%). Improvements never fail the gate.
Tiny absolute timings (< --min-seconds, default 5ms) are reported but
never fail: at that scale the delta is scheduler noise, not signal.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Iterator, List, Optional, Tuple

# (name, higher_is_better) — None direction means "infer from unit"
HEADLINE_KEYS: Tuple[Tuple[str, Optional[bool]], ...] = (
    ("value", None),
    ("vs_baseline", True),
    ("vs_python_oracle", True),
    ("kernel_dispatch_ops_per_s", True),
    ("dispatch_floor_ms", False),
)
SECTION_KEYS: Tuple[Tuple[Tuple[str, ...], bool], ...] = (
    (("scale_run", "vs_baseline"), True),
    (("scale_run", "stream_vs_oneshot"), True),
    (("scale_run", "rounds", "vs_cold_replay"), True),
    # the overload evidence leg (bench.py overload_leg): bounded peak
    # inbox bytes + shed counts + post-heal convergence — robustness
    # regression-gated like xfer.* (all lower-is-better)
    (("overload", "peak_inbox_bytes"), False),
    (("overload", "shed_count"), False),
    (("overload", "shed_bytes"), False),
    # static analysis (tools/crdtlint): TOTAL findings incl. baselined
    # + suppressed — the committed tree always lints clean (tier-1),
    # so this moves exactly when a PR grows the baseline or sprinkles
    # new inline disables, and that shows up in the diff table like
    # any perf regression. Lower is better; counts, not seconds, so
    # the noise floor never mutes it.
    (("lint", "findings"), False),
    (("lint", "baselined"), False),
    # round 16: per-family OPEN finding counts for the new analysis
    # families (trace purity / lock discipline / async handles). The
    # committed tree gates these at zero via tier-1, so a non-zero
    # new value is a straight regression; counts, not seconds — the
    # noise floor never mutes them.
    (("lint", "open_by_family", "cl7"), False),
    (("lint", "open_by_family", "cl8"), False),
    (("lint", "open_by_family", "cl9"), False),
    # round 17: the wire-taint (cl10) and decode-allocation (cl11)
    # families — same count semantics and zero-default as cl7-cl9
    # (an artifact predating round 17 means "0 open findings")
    (("lint", "open_by_family", "cl10"), False),
    (("lint", "open_by_family", "cl11"), False),
    # the multi-chip sharded converge (round 13, bench --multichip):
    # the boundary exchange must stay a small fraction of the staged
    # upload (bytes/fraction lower-is-better, counts so the noise
    # floor never mutes them)
    (("multichip", "boundary_bytes"), False),
    (("multichip", "boundary_fraction"), False),
    # multi-tenant packing (round 14, bench --multitenant): docs
    # converged per second and the packing speedup over the
    # one-dispatch-per-doc baseline (higher = better), tail latency
    # and dispatch count per tick (lower = better). Ratios/counts —
    # the seconds noise floor never mutes them; p99_per_doc_ms is a
    # SECTION key, so it is gated even below the ms floor.
    (("multitenant", "docs_converged_per_s"), True),
    (("multitenant", "speedup"), True),
    (("multitenant", "p99_per_doc_ms"), False),
    (("multitenant", "dispatches_per_tick"), False),
    # delta ticks (round 15, the steady-state leg): docs served per
    # second across N small-delta ticks on large resident docs, and
    # the speedup over the round-14 full-replay tick (higher =
    # better — the >=10x acceptance bar is a gated artifact, not a
    # doc sentence); the eviction flood's committed resident peak
    # must stay bounded (lower = better, bytes — the seconds noise
    # floor never mutes it)
    (("multitenant", "steady", "docs_per_s"), True),
    (("multitenant", "steady", "speedup"), True),
    (("multitenant", "steady", "eviction", "peak_bytes"), False),
    # pooled resident matrix (round 20): steady device dispatches
    # per tick — the O(1)-dispatch tentpole number. A COUNT (lower =
    # better): the ms noise floor never mutes it, so a pooled route
    # rotting back to one-dispatch-per-doc fails the gate even when
    # each dispatch is cheap. The pool's peak allocation is gated
    # like the eviction flood's resident peak (bytes, lower).
    (("multitenant", "steady", "device_dispatches_per_tick"), False),
    (("multitenant", "steady", "pool_peak_bytes"), False),
    # observability v2 (round 18): the run-stable timeline/SLO
    # digests the --multitenant harness embeds — the mean overlap of
    # the double-buffered ticks (higher = better; the per-tick gauge
    # is also gated below but carries only the LAST tick), and the
    # chaos flooder's breach count, which is DETERMINISTIC (the leg
    # runs at slo_ms=0, so breaches = shed + served counts — not a
    # wall-clock artifact like the default-objective legs' totals,
    # whose baseline of 0 would turn one slow-machine miss into an
    # infinite-delta failure). stall_ms_total rides the seconds
    # loop below, where the ms noise floor applies.
    (("multitenant", "timeline", "mean_overlap_efficiency"), True),
    (("multitenant", "flood", "slo_flooder", "breaches"), False),
    # distributed tracing (round 19, bench --fleet-trace): processes
    # federated and the fraction of traced receives whose full
    # per-hop path reconstructs across them (both higher = better,
    # count semantics — never muted by the seconds floor), and the
    # trace-context wire tax as a fraction of traced update bytes
    # (lower = better: the tracing plane must stay cheap)
    (("fleet_trace", "procs"), True),
    (("fleet_trace", "pair_rate"), True),
    (("fleet_trace", "wire_overhead_ratio"), False),
    # crash-proof recovery (round 21, bench --coldstart): the scale
    # doc's snapshot join time (lower = better; a SECTION key, so the
    # ms noise floor never mutes it even when the join is fast) and
    # its speedup over full WAL replay (higher = better — the >=5x
    # acceptance bar is a gated artifact, not a doc sentence). The
    # server-side checkpoint/restore times ride the same contract.
    (("cold_start", "join_ms"), False),
    (("cold_start", "speedup"), True),
    (("cold_start", "checkpoint_ms"), False),
    (("cold_start", "restore_ms"), False),
    # the recovery ladder's fallback count for the leg (the tracer's
    # snap.fallbacks counters are reason-labeled, so the guard loop
    # skips them — the harness publishes the sum here): a rise means
    # the same run hit more damaged/unusable snapshots (lower =
    # better, a count — never muted by the seconds floor)
    (("cold_start", "snap_fallbacks_counted"), False),
    # the control plane (round 22, bench --autopilot): ticks for the
    # seeded flooder's burn rate to recover once the flood stops
    # (tick counts — deterministic, never muted) and the neighbors'
    # p99 serve latency with the controller ON (wall-clock ms, but a
    # SECTION key so a squeeze rule rotting away fails the gate even
    # on a fast machine). Both lower = better.
    (("autopilot", "recovery_ticks"), False),
    (("autopilot", "neighbor_p99_ms"), False),
    # subtree split (round 23, bench --conflict): the staged
    # doubling-rounds bounds on the branching hot-list +
    # deep-map-chain trace at the gated width (lower = better — the
    # tentpole; counts, never muted by the seconds floor) and the cut
    # counts (HIGHER = better: a drop to 0 means the branching or
    # map-chain shapes regressed to refused and the rounds win is
    # gone even if the gauges happen to match)
    (("conflict", "converge", "wyllie_rounds"), False),
    (("conflict", "converge", "map_rounds"), False),
    (("conflict", "converge", "subtree_cuts"), True),
    (("conflict", "converge", "map_chain_cuts"), True),
    # fleet serving (round 24, bench --rebalance): ticks for the
    # flooded tenant's serving burn to recover once the placement
    # loop migrates it (tick counts — deterministic), and the fork
    # guards — double_serves/forks must stay at their committed 0,
    # recoveries at the seeded chaos's count (a rise means the same
    # chaos leaned harder on the recovery ladder)
    (("rebalance", "recovery_ticks"), False),
    (("rebalance", "double_serves"), False),
    (("rebalance", "forks"), False),
    (("rebalance", "migration_recoveries"), False),
    (("rebalance", "lost_flood_updates"), False),
)
SPAN_FIELDS = ("p50_s", "p99_s", "total_s")

# guard-layer counters/gauges (crdt_tpu/guard): sheds, evictions,
# degraded windows, device fallbacks — every one LOWER-is-better (a
# rise means the same workload leaned harder on a degradation ladder),
# and none is time-denominated, so the seconds noise floor never mutes
# a regression. Exact names and prefixes, unlabeled variants only.
GUARD_PREFIXES: Tuple[str, ...] = (
    "guard.",
    "engine.pending_evictions",
    "persist.degraded",
    "persist.errors",
    "persist.retries",
    "persist.dropped_updates",
    "persist.compact_errors",
    "device.retries",
    "device.fallback",
    "device.dispatch_errors",
    "replica.isolation_splits",
    "replica.malformed_updates",
    # round 14: tenant-scoped shedding — a rise means the same trace
    # leaned harder on the admission ladder (tenant.submitted /
    # docs_converged are workload facts and stay ungated)
    "tenant.shed",
    "tenant.fallback_docs",
    # round 15: delta-tick degradations — more evictions means the
    # same trace thrashed the resident budget harder, more fallbacks
    # means more deltas were refused by the incremental route
    # (tenant.delta_docs / delta_rows / promotions are workload
    # facts and stay ungated)
    "tenant.resident_evictions",
    "tenant.delta_fallbacks",
    # round 21: snapshot-plane degradations — more fallbacks means
    # the same trace hit more damaged/unusable snapshots on the
    # recovery ladder, more write errors means the store refused or
    # failed more writes (snap.writes / loads / bytes are workload
    # facts and stay ungated)
    "snap.fallbacks",
    "snap.write_errors",
    # round 22: a hot control loop churning its own bounded audit
    # ledger is a degradation — more dropped rows on the same
    # workload means decisions became unauditable (count semantics;
    # control.decisions / cooldown_skips are rule-mix facts and stay
    # ungated)
    "control.ledger_dropped",
    # round 24: fleet-serving degradations — more fence rejects or
    # fork refusals on the same chaos means more stale claims
    # reached the serving path, more migration recoveries means the
    # same fault schedule knocked more handoffs off the happy path
    # (fleet.redirects / beacons_sent / migration.started are
    # workload facts and stay ungated)
    "fleet.fence_rejects",
    "fleet.fork_refused",
    "fleet.demotions",
    "fleet.frames_malformed",
    "migration.recovery",
    "migration.tail_restores",
)


def _get_path(d: Dict[str, Any], path: Tuple[str, ...]) -> Any:
    for p in path:
        if not isinstance(d, dict) or p not in d:
            return None
        d = d[p]
    return d


def iter_metrics(old: Dict[str, Any], new: Dict[str, Any]
                 ) -> Iterator[Tuple[str, float, float, bool, bool]]:
    """Yield (name, old_value, new_value, higher_is_better,
    is_seconds) for every comparable numeric metric present in BOTH
    artifacts."""
    for key, direction in HEADLINE_KEYS:
        a, b = old.get(key), new.get(key)
        if not _both_numbers(a, b):
            continue
        if direction is None:
            # headline ``value``: a rate unit means higher is better,
            # a time unit means lower
            unit = str(new.get("unit", old.get("unit", "")))
            direction = "/s" in unit or "ops" in unit
        yield key, float(a), float(b), direction, key.endswith(
            ("_s", "_ms")
        )
    for path, direction in SECTION_KEYS:
        a, b = _get_path(old, path), _get_path(new, path)
        if "open_by_family" in path:
            # count semantics with a zero default: an artifact
            # predating the round-16 digest means "0 open findings"
            # (the committed tree always lints clean), so the gate
            # is live the moment the NEW side carries the key —
            # not only after both artifacts were regenerated
            if a is None and b is not None:
                a = 0
            if b is None and a is not None:
                b = 0
        if _both_numbers(a, b):
            yield ".".join(path), float(a), float(b), direction, False
    # the fused-dispatch net-compute sweep (round 12, the sort diet's
    # headline evidence): per-size ms, lower is better, seconds noise
    # floor applies (the *_ms suffix scales it)
    so = old.get("kernel_sweep_net_ms") or {}
    sn = new.get("kernel_sweep_net_ms") or {}
    for size in sorted(set(so) & set(sn)):
        if _both_numbers(so[size], sn[size]):
            yield f"kernel_sweep_net_ms.{size}_ms", float(so[size]), \
                float(sn[size]), False, True
    # the per-primitive kernel ablation (round 12): each primitive's
    # per-path net ms lower-is-better; the sort+map speedup — the
    # ROADMAP item-3 >=2x acceptance number — higher-is-better and
    # never muted by the noise floor
    ao = old.get("kernel_ablation") or {}
    an = new.get("kernel_ablation") or {}
    for prim in ("sort_ms", "map_winners_ms", "rank_ms"):
        po, pn = ao.get(prim), an.get(prim)
        if not (isinstance(po, dict) and isinstance(pn, dict)):
            continue
        for path_key in sorted(set(po) & set(pn)):
            if _both_numbers(po[path_key], pn[path_key]):
                yield f"kernel_ablation.{prim}.{path_key}_ms", \
                    float(po[path_key]), float(pn[path_key]), False, True
    if _both_numbers(ao.get("sort_map_speedup"),
                     an.get("sort_map_speedup")):
        yield "kernel_ablation.sort_map_speedup", \
            float(ao["sort_map_speedup"]), \
            float(an["sort_map_speedup"]), True, False
    # multi-chip scaling (round 13): per-device-count converge
    # speedup over the single-chip run — higher is better, never
    # muted by the seconds noise floor (it is a ratio)
    mo = (old.get("multichip") or {}).get("scaling_efficiency") or {}
    mn = (new.get("multichip") or {}).get("scaling_efficiency") or {}
    for nd in sorted(set(mo) & set(mn), key=str):
        if _both_numbers(mo[nd], mn[nd]):
            yield f"multichip.scaling_efficiency.{nd}", \
                float(mo[nd]), float(mn[nd]), True, False
    spans_old = (old.get("tracer") or {}).get("spans", {})
    spans_new = (new.get("tracer") or {}).get("spans", {})
    for name in sorted(set(spans_old) & set(spans_new)):
        for field in SPAN_FIELDS:
            a = spans_old[name].get(field)
            b = spans_new[name].get(field)
            if _both_numbers(a, b):
                yield f"tracer.{name}.{field}", float(a), float(b), \
                    False, True
    ph_old = old.get("phases_device_s") or {}
    ph_new = new.get("phases_device_s") or {}
    for name in sorted(set(ph_old) & set(ph_new)):
        a, b = ph_old[name], ph_new[name]
        if _both_numbers(a, b):
            yield f"phases_device_s.{name}", float(a), float(b), \
                False, True
    # bytes-on-link (the transfer diet): xfer.* tracer counters and
    # gauges, plus the per-workload xfer digests — direction-aware,
    # bytes/puts/ratio all lower-is-better. Not time-denominated, so
    # the seconds noise floor never mutes a byte regression.
    for section in ("counters", "gauges"):
        xo = (old.get("tracer") or {}).get(section, {})
        xn = (new.get("tracer") or {}).get(section, {})
        for name in sorted(set(xo) & set(xn)):
            if not name.startswith("xfer.") or "{" in name:
                continue
            if name == "xfer.narrowed_ratio":
                # last-writer-wins PER-UPLOAD gauge: whichever shard
                # staged last sets it, which flaps run to run — the
                # stable run-level ratio is derived from the gated
                # byte counters below instead
                continue
            if _both_numbers(xo[name], xn[name]):
                # bytes saved by the diet is the one xfer metric where
                # MORE is better
                yield f"tracer.{name}", float(xo[name]), \
                    float(xn[name]), name.endswith("_saved"), False
    # the sharded converge's boundary traffic and the staging
    # doubling-rounds bounds (rounds 13/23): all lower-is-better,
    # counts (never muted by the seconds floor). shard.dispatches/
    # shards are deliberately ungated — how often the sharded route
    # ran is a workload-mix fact, not a regression signal.
    for section, name in (("counters", "shard.boundary_bytes"),
                          ("gauges", "converge.wyllie_rounds"),
                          ("gauges", "converge.map_rounds")):
        a = (old.get("tracer") or {}).get(section, {}).get(name)
        b = (new.get("tracer") or {}).get(section, {}).get(name)
        if _both_numbers(a, b):
            yield f"tracer.{name}", float(a), float(b), False, False
    # serving SLO + tick timeline (round 18): breaches are the SLO
    # ledger's total objective misses on the same workload (lower is
    # better, counts — never muted); timeline.stall_ms is the tick
    # loop's blocked-fetch time (lower, ms noise floor applies);
    # timeline.overlap_efficiency is the double-buffer's measured
    # overlap (HIGHER is better — the one gauge whose drop means the
    # pipeline re-serialized; a ratio, never muted)
    # distributed tracing (round 19): the collector's federation
    # gauges (procs scraped, live path-reconstruction rate — both
    # HIGHER is better, count semantics), the wire-overhead ratio
    # and malformed-context count (lower). The per-route
    # `replica.hop_lag{route=...}` histograms ride the span loop
    # above (p50/p99/total lower-is-better like every latency).
    for section, name, hib, is_seconds in (
        ("counters", "slo.breaches", False, False),
        ("gauges", "timeline.stall_ms", False, True),
        ("gauges", "timeline.overlap_efficiency", True, False),
        ("gauges", "collector.procs", True, False),
        ("gauges", "collector.pair_rate", True, False),
        ("gauges", "propagation.wire_overhead_ratio", False, False),
        ("counters", "propagation.malformed_contexts", False, False),
    ):
        a = (old.get("tracer") or {}).get(section, {}).get(name)
        b = (new.get("tracer") or {}).get(section, {}).get(name)
        if _both_numbers(a, b):
            yield f"tracer.{name}", float(a), float(b), hib, \
                is_seconds
    # the multitenant timeline's total blocked-fetch time: wall-clock
    # ms, so the seconds noise floor applies (a 1ms wobble is
    # scheduler noise, a 100ms jump is a re-serialized pipeline)
    a = _get_path(old, ("multitenant", "timeline", "stall_ms_total"))
    b = _get_path(new, ("multitenant", "timeline", "stall_ms_total"))
    if _both_numbers(a, b):
        yield "multitenant.timeline.stall_ms_total_ms", float(a), \
            float(b), False, True
    # guard-layer degradation counters/gauges: all lower-is-better
    # (persist.recovered_updates is deliberately NOT gated — it rises
    # and falls with degraded_writes, which already is), never seconds
    for section in ("counters", "gauges"):
        go = (old.get("tracer") or {}).get(section, {})
        gn = (new.get("tracer") or {}).get(section, {})
        for name in sorted(set(go) & set(gn)):
            if "{" in name or not name.startswith(GUARD_PREFIXES):
                continue
            if _both_numbers(go[name], gn[name]):
                yield f"tracer.{name}", float(go[name]), \
                    float(gn[name]), False, False
    # run-level narrowing ratio: shipped / wide-equivalent over the
    # WHOLE run's STAGED uploads only (stable, unlike the per-upload
    # gauge; xfer.staged_bytes excludes fleet/resident-delta traffic,
    # whose mix shifting must not read as a narrowing change)
    def _agg_ratio(art):
        cnt = (art.get("tracer") or {}).get("counters", {})
        staged, saved = cnt.get("xfer.staged_bytes"), \
            cnt.get("xfer.h2d_bytes_saved")
        if _both_numbers(staged, saved) and staged + saved > 0:
            return staged / (staged + saved)
        return None

    a, b = _agg_ratio(old), _agg_ratio(new)
    if a is not None and b is not None:
        yield "xfer.narrowed_ratio_run", a, b, False, False
    for path in (("xfer",), ("scale_run", "xfer_stream"),
                 ("scale_run", "xfer_oneshot")):
        a, b = _get_path(old, path), _get_path(new, path)
        if isinstance(a, dict) and isinstance(b, dict):
            for name in sorted(set(a) & set(b)):
                if _both_numbers(a[name], b[name]):
                    yield ".".join(path) + f".{name}", float(a[name]), \
                        float(b[name]), name.endswith("_saved"), False


def _both_numbers(a: Any, b: Any) -> bool:
    return (
        isinstance(a, (int, float)) and not isinstance(a, bool)
        and isinstance(b, (int, float)) and not isinstance(b, bool)
    )


def compare(old: Dict[str, Any], new: Dict[str, Any], *,
            threshold: float = 0.20, min_seconds: float = 0.005
            ) -> Tuple[List[Dict[str, Any]], List[str]]:
    """Build the regression table. Returns (rows, regressed_names)."""
    rows: List[Dict[str, Any]] = []
    regressed: List[str] = []
    for name, a, b, hib, is_seconds in iter_metrics(old, new):
        if a == 0:
            delta = 0.0 if b == 0 else float("inf")
        else:
            delta = (b - a) / abs(a)
        bad = (delta < -threshold) if hib else (delta > threshold)
        # the noise floor is denominated in seconds; *_ms metrics
        # scale down before the comparison
        scale = 1e-3 if name.endswith("_ms") else 1.0
        noise = is_seconds and max(abs(a), abs(b)) * scale < min_seconds
        if bad and noise:
            verdict = "noise"
        elif bad:
            verdict = "REGRESSION"
            regressed.append(name)
        elif (delta > threshold) if hib else (delta < -threshold):
            verdict = "improved"
        else:
            verdict = "ok"
        rows.append({
            "metric": name, "old": a, "new": b,
            "delta_pct": round(delta * 100, 1), "verdict": verdict,
        })
    return rows, regressed


def format_table(rows: List[Dict[str, Any]]) -> str:
    if not rows:
        return "(no comparable metrics found)"
    w = max(len(r["metric"]) for r in rows)
    lines = [
        f"{'metric':<{w}}  {'old':>12}  {'new':>12}  {'delta':>8}  verdict"
    ]
    for r in rows:
        lines.append(
            f"{r['metric']:<{w}}  {r['old']:>12.6g}  {r['new']:>12.6g}"
            f"  {r['delta_pct']:>+7.1f}%  {r['verdict']}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Regression-diff two BENCH_OUT.json artifacts"
    )
    ap.add_argument("old", help="baseline BENCH_OUT.json")
    ap.add_argument("new", help="candidate BENCH_OUT.json")
    ap.add_argument("--threshold", type=float, default=0.20,
                    help="relative regression threshold (default 0.20)")
    ap.add_argument("--min-seconds", type=float, default=0.005,
                    help="timings below this never fail (noise floor)")
    args = ap.parse_args(argv)
    with open(args.old) as f:
        old = json.load(f)
    with open(args.new) as f:
        new = json.load(f)
    rows, regressed = compare(
        old, new, threshold=args.threshold, min_seconds=args.min_seconds
    )
    print(format_table(rows))
    if regressed:
        print(
            f"\n{len(regressed)} metric(s) regressed past "
            f"{args.threshold:.0%}: {', '.join(regressed)}",
            file=sys.stderr,
        )
        return 1
    print(f"\nno regressions past {args.threshold:.0%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
