"""CL301/CL302/CL303: exception discipline (rounds 7 and 10).

The codec fuzz suite (540 seeded mutants) and the replica's
malformed-batch isolation both rest on one contract: **decode paths
raise ``ValueError`` and nothing else** — the replica catches exactly
``ValueError`` to isolate a poisoned blob, so a stray ``KeyError`` or
``struct.error`` escaping a decoder kills the apply path instead of
triggering bisection. Symmetrically, the ALICE crash-point matrix
relies on ``SimulatedCrash`` deriving from ``BaseException`` so that
NO handler in the storage/guard ladders can swallow a simulated
kill — catching it (or ``BaseException``) un-tests every crash point.

- **CL301** — bare ``except:`` or ``except BaseException`` in the
  codec/storage/guard scope (swallows ``SimulatedCrash``,
  ``KeyboardInterrupt``, everything).
- **CL302** — a decode-path function (``decode*`` / ``read_*`` /
  ``parse*`` / ``apply_update`` / ``loads`` or a ``*Decoder`` method)
  raising anything but ``ValueError``.
- **CL303** — catching ``SimulatedCrash`` (or ``BaseException``)
  inside ``guard/`` — the crash adversary must always propagate.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from tools.crdtlint.astutil import dotted, in_scope
from tools.crdtlint.core import Checker, Finding, LintContext, Module

SCOPE = ("crdt_tpu/codec/", "crdt_tpu/storage/kv.py", "crdt_tpu/guard/")
GUARD_SCOPE = ("crdt_tpu/guard/",)
DECODE_SCOPE = ("crdt_tpu/codec/", "crdt_tpu/storage/kv.py")

_DECODE_FN = re.compile(
    r"^(_?decode|_?read_|_?parse|apply_update$|loads$|from_bytes)"
)


def _is_decode_path(fn: ast.FunctionDef, class_name: str) -> bool:
    if _DECODE_FN.match(fn.name):
        return True
    return "Decoder" in class_name and not fn.name.startswith("__")


def _handler_names(h: ast.ExceptHandler) -> List[str]:
    if h.type is None:
        return ["<bare>"]
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    return [(dotted(t) or "?").rsplit(".", 1)[-1] for t in types]


class ExceptionDisciplineChecker(Checker):
    name = "exceptions"
    codes = {
        "CL301": "bare `except:` / `except BaseException` in "
                 "codec/storage/guard scope",
        "CL302": "decode path raises something other than ValueError",
        "CL303": "guard ladder catches SimulatedCrash/BaseException "
                 "(defeats the crash-point matrix)",
    }
    explain = {
        "CL301": (
            "A bare `except:` in codec/storage/guard scope swallows "
            "KeyboardInterrupt, SimulatedCrash, and every bug the "
            "fuzzers exist to surface.\n"
            "Fix: catch the narrowest concrete exception the seam "
            "can actually raise (decoders: ValueError)."
        ),
        "CL302": (
            "Decoders raise ValueError and nothing else — that is "
            "the round-10 contract callers (and the fuzz suite) "
            "rely on to distinguish malformed input from bugs.\n"
            "Fix: wrap index/struct errors and re-raise as "
            "ValueError with the offset context."
        ),
        "CL303": (
            "The ALICE crash-point harness injects SimulatedCrash "
            "to prove recovery; a guard ladder that catches it "
            "reports a crash-safe path that was never exercised.\n"
            "Fix: catch the concrete OSError/ValueError family and "
            "let SimulatedCrash (a BaseException) propagate."
        ),
    }

    def check_module(self, mod: Module,
                     ctx: LintContext) -> Iterable[Finding]:
        if not in_scope(mod.path, SCOPE):
            return ()
        findings: List[Finding] = []
        in_guard = in_scope(mod.path, GUARD_SCOPE)
        in_decode_scope = in_scope(mod.path, DECODE_SCOPE)

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler):
                names = _handler_names(node)
                if "<bare>" in names or "BaseException" in names:
                    findings.append(Finding(
                        mod.path, node.lineno, "CL301",
                        "bare `except:`/`except BaseException` "
                        "swallows SimulatedCrash and "
                        "KeyboardInterrupt — catch concrete "
                        "exception types",
                        symbol=",".join(names),
                    ))
                if in_guard and "SimulatedCrash" in names:
                    findings.append(Finding(
                        mod.path, node.lineno, "CL303",
                        "guard code catches SimulatedCrash — the "
                        "ALICE crash-point adversary must always "
                        "propagate (it derives from BaseException "
                        "precisely so ladders can't eat it)",
                        symbol="SimulatedCrash",
                    ))

        if not in_decode_scope:
            return findings
        # decode-path raise discipline, per enclosing function
        for parent, class_name in _defs_with_class(mod.tree):
            if not _is_decode_path(parent, class_name):
                continue
            for node in ast.walk(parent):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                exc = node.exc
                name = (
                    dotted(exc.func) if isinstance(exc, ast.Call)
                    else dotted(exc)
                ) or "?"
                short = name.rsplit(".", 1)[-1]
                if short != "ValueError":
                    findings.append(Finding(
                        mod.path, node.lineno, "CL302",
                        f"decode path `{parent.name}` raises "
                        f"`{short}` — decoders must raise ValueError "
                        f"only (the replica's malformed-blob "
                        f"isolation catches exactly that; round-10 "
                        f"fuzz contract)",
                        symbol=f"{parent.name}:{short}",
                    ))
        return findings


def _defs_with_class(tree: ast.Module):
    """(function def, enclosing class name or "") pairs, top-level
    functions included — without double-visiting methods."""
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            yield node, ""
            yield from _nested(node, "")
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef):
                    yield sub, node.name
                    yield from _nested(sub, node.name)


def _nested(fn: ast.FunctionDef, class_name: str):
    for node in fn.body:
        if isinstance(node, ast.FunctionDef):
            yield node, class_name
            yield from _nested(node, class_name)
