"""CL1101/CL1102: decode-allocation contracts (round 17).

The torn-tail discipline the round-10 ALICE matrix proved dynamically,
stated statically: a decode entry point that allocates from a
DECLARED length must have compared that length against the actual
buffer remaining (or an input-derived budget) first — an absolute
constant bound is not enough, because a 2^30 length under a 2^31 cap
still buys a gigabyte from a five-byte varint. And the round-10
``ValueError``-only contract (CL302 checks it lexically, per
decode-named function) must hold through every helper a decode entry
reaches: the replica's malformed-blob isolation catches exactly
``ValueError``, so a ``KeyError`` escaping a helper two calls down
kills the poll loop just as dead as one raised inline.

- **CL1101** — a decode entry point (``decode*`` / ``read_*`` /
  ``parse*`` / ``loads`` / ``from_bytes`` in codec/kv scope) sizes an
  allocation with a wire-read length whose only sanitization was a
  non-buffer-anchored guard (the wire-taint pass marks those *weak*:
  the comparison mentioned no ``len(...)``/``pos``/``remaining``/
  ``budget``-like term).
- **CL1102** — a non-``ValueError`` raise in a helper reachable from
  a decode entry point over STRONG call-graph edges (the round-16
  resolution rules; a guessed edge must never convict a helper).
  Helpers that are themselves decode-named are CL302's lexical job
  and excluded here, so each raise is reported exactly once.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Set

from tools.crdtlint.astutil import dotted, in_scope
from tools.crdtlint.callgraph import get_callgraph, reach_closure
from tools.crdtlint.checkers.exceptions import _is_decode_path
from tools.crdtlint.checkers.wiretaint import (
    _TaintWalk,
    get_taint_index,
)
from tools.crdtlint.core import Checker, Finding, LintContext, Module

DECODE_SCOPE = ("crdt_tpu/codec/", "crdt_tpu/storage/kv.py",
                # round 19: decode_context is a wire-facing decode
                # entry (trace contexts on update frames) — held to
                # the same buffer-anchored allocation standard
                "crdt_tpu/obs/propagation.py")


def _handler_bound_names(fn_node) -> Dict[str, Set[str]]:
    """``except X as e`` bindings in a function: name -> the set of
    caught type shortnames. A ``raise e`` of such a binding re-raises
    one of THOSE types, not a type literally named ``e``."""
    out: Dict[str, Set[str]] = {}
    for node in ast.walk(fn_node):
        if not (isinstance(node, ast.ExceptHandler) and node.name):
            continue
        types = (
            node.type.elts if isinstance(node.type, ast.Tuple)
            else [node.type] if node.type is not None else []
        )
        shorts = {
            (dotted(t) or "?").rsplit(".", 1)[-1] for t in types
        } or {"<bare>"}
        out.setdefault(node.name, set()).update(shorts)
    return out


def _raise_names(node: ast.Raise, bound: Dict[str, Set[str]]) -> Set[str]:
    """Exception type shortname(s) a raise can produce. Empty set =
    unresolvable or type-preserving (bare re-raise, a variable we
    cannot trace) — the conservative direction is to stay silent, a
    checker must never invent a conviction."""
    if node.exc is None:
        return set()  # bare re-raise: preserves the original type
    exc = node.exc
    if isinstance(exc, ast.Call):
        name = dotted(exc.func)
        return {name.rsplit(".", 1)[-1]} if name else set()
    if isinstance(exc, ast.Name):
        if exc.id in bound:
            # `except ValueError as e: raise e` re-raises ValueError;
            # report the HANDLER's types, not the variable name
            return bound[exc.id]
        return set()  # a constructed variable: cannot resolve
    name = dotted(exc)
    return {name.rsplit(".", 1)[-1]} if name else set()


class DecodeAllocChecker(Checker):
    name = "decode-alloc"
    codes = {
        "CL1101": "decode entry allocates from a declared length "
                  "without a buffer-anchored pre-check",
        "CL1102": "non-ValueError raise reachable from a decode "
                  "entry point (interprocedural CL302)",
    }
    explain = {
        "CL1101": (
            "A length prefix is a claim, not a fact: before "
            "allocating `n` of anything, a decoder must check `n` "
            "against what the buffer can actually back — "
            "`pos + n > len(data)` for raw bytes, or an "
            "input-derived budget (decode_update's "
            "`4096 * len(data)` expansion budget) for run "
            "expansion. An absolute cap (`n < 2**31`) silences the "
            "taint but still lets a 5-byte varint buy a gigabyte — "
            "that is exactly the torn-tail/hostile-length family "
            "the round-10 ALICE matrix and codec fuzz probe "
            "dynamically.\n"
            "Fix: make the guard mention the buffer (`len(data)`, "
            "`self.pos`, a `budget` derived from the input size), "
            "or route the length through a `# crdtlint: sanitizes` "
            "helper that owns the buffer-anchored check."
        ),
        "CL1102": (
            "The replica isolates a malformed blob by catching "
            "exactly ValueError (round-10 contract, enforced "
            "lexically by CL302). A helper that raises KeyError or "
            "struct.error two STRONG calls below decode_update "
            "breaks that contract just as hard as an inline raise — "
            "the poll loop dies instead of bisecting the poisoned "
            "batch.\n"
            "Fix: wrap the helper's failure and re-raise as "
            "ValueError with offset context at the decode seam; for "
            "genuinely environmental errors (a missing native "
            "toolchain), baseline with a justification naming the "
            "gate that keeps wire input from reaching the raise."
        ),
    }

    def prepare(self, ctx: LintContext) -> None:
        ctx.shared.setdefault("cl1102_memo", {})

    def check_module(self, mod: Module,
                     ctx: LintContext) -> Iterable[Finding]:
        if not in_scope(mod.path, DECODE_SCOPE) or mod.tree is None:
            return ()
        return list(self._check_allocs(mod, ctx))

    def finalize(self, ctx: LintContext) -> Iterable[Finding]:
        # CL1102 is a whole-graph question (an entry in one module
        # can reach a helper in another); one pass with one dedupe
        # set, so two entries sharing a helper report it once
        return list(self._check_raises(ctx))

    # -- CL1101 ----------------------------------------------------------

    def _check_allocs(self, mod: Module,
                      ctx: LintContext) -> Iterable[Finding]:
        index = get_taint_index(ctx)
        for qual, fn in index.defs.get(mod.path, {}).items():
            cls = qual.rsplit(".", 1)[0] if "." in qual else ""
            if not _is_decode_path(fn, cls):
                continue
            walker = _TaintWalk(
                mod, fn, qual, index,
                taint_params=f"{mod.path}:{qual}" in index.sanitizing,
            )
            walker.run()
            for lineno, tail, name in walker.weak_allocs:
                yield Finding(
                    mod.path, lineno, "CL1101",
                    f"decode entry `{qual}` allocates via `{tail}` "
                    f"from wire length `{name}` guarded only by an "
                    f"absolute bound — pre-check it against the "
                    f"buffer remaining (or an input-derived budget) "
                    f"before allocating",
                    symbol=f"{qual}:{tail}:{name}",
                )

    # -- CL1102 ----------------------------------------------------------

    def _check_raises(self, ctx: LintContext) -> Iterable[Finding]:
        cg = get_callgraph(ctx)
        memo: Dict[str, Set[str]] = ctx.shared["cl1102_memo"]
        seen: Set[str] = set()
        for fkey in sorted(cg.funcs):
            fi = cg.funcs[fkey]
            if not in_scope(fi.module, DECODE_SCOPE):
                continue
            if not _is_decode_path(fi.node, fi.cls or ""):
                continue
            closure = reach_closure(cg, fi.key, strong_only=True,
                                    memo=memo)
            for key in sorted(closure):
                helper = cg.funcs.get(key)
                if helper is None or not in_scope(
                    helper.module, DECODE_SCOPE
                ):
                    continue
                if _is_decode_path(helper.node, helper.cls or ""):
                    continue  # CL302 covers it lexically
                bound = _handler_bound_names(helper.node)
                for node in ast.walk(helper.node):
                    if not isinstance(node, ast.Raise):
                        continue
                    for short in sorted(_raise_names(node, bound)):
                        if short == "ValueError":
                            continue
                        symbol = f"{helper.qual}:{short}"
                        fp = f"{helper.module}|{symbol}"
                        if fp in seen:
                            continue
                        seen.add(fp)
                        yield Finding(
                            helper.module, node.lineno, "CL1102",
                            f"`{helper.qual}` raises `{short}` and "
                            f"is reachable from decode entry "
                            f"`{fi.qual}` — decode paths raise "
                            f"ValueError only (the malformed-blob "
                            f"isolation catches exactly that); wrap "
                            f"and re-raise at the seam",
                            symbol=symbol,
                        )
