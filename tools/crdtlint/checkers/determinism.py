"""CL501–CL504: determinism in the staged-packing core (round 7).

Byte-identical convergence under seeded fault schedules — the chaos
harness's whole proof — requires the packing/converge core to be a
pure function of its inputs. Wall-clock reads, unseeded RNGs, and
hash-order iteration each smuggle ambient state into staged layouts.

Scope: ``ops/``, ``parallel/``, ``core/`` (the staging + converge
core). Seeding (CL503) is checked package-wide at every call site of
a ``net/faults.py`` schedule constructor.

- **CL501** — ``time.time()`` / ``time.time_ns()`` in core scope
  (``perf_counter`` / ``monotonic`` are fine: they time, they don't
  *decide*).
- **CL502** — unseeded randomness: module-level ``random.*`` calls,
  ``random.Random()`` / ``np.random.default_rng()`` with no seed, or
  legacy ``np.random.<dist>`` globals.
- **CL503** — a fault-schedule constructor (any ``net/faults.py``
  class taking a ``seed`` parameter) called without an explicit
  seed — replay of a chaos run must never depend on the default.
- **CL504** — iteration over a ``set`` expression (set literal /
  ``set()`` / ``frozenset()`` / set comprehension) that isn't wrapped
  in ``sorted()``: set order is hash-salted across processes, so any
  packing fed by it differs run to run.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from tools.crdtlint.astutil import call_name, in_scope
from tools.crdtlint.core import Checker, Finding, LintContext, Module

CORE_SCOPE = ("crdt_tpu/ops/", "crdt_tpu/parallel/", "crdt_tpu/core/")
FAULTS_SUFFIX = "net/faults.py"

# random-module functions that are fine without a seed argument
_RANDOM_OK = {"Random", "SystemRandom", "seed"}
# numpy legacy global-state distributions
_NP_RANDOM_GLOBALS = {
    "random", "rand", "randn", "randint", "choice", "shuffle",
    "permutation", "uniform", "normal", "bytes",
}


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return (call_name(node) or "") in ("set", "frozenset")
    return False


class DeterminismChecker(Checker):
    name = "determinism"
    codes = {
        "CL501": "wall-clock read (time.time) in the deterministic "
                 "packing core",
        "CL502": "unseeded randomness in the deterministic packing "
                 "core",
        "CL503": "fault-schedule constructor called without an "
                 "explicit seed",
        "CL504": "unsorted set iteration feeding the packing core "
                 "(hash-salted order)",
    }
    explain = {
        "CL501": (
            "time.time() in the packing core smuggles wall clock "
            "into staged layouts, breaking byte-identical replay "
            "under seeded fault schedules.\n"
            "Fix: thread timestamps in as inputs; perf_counter/"
            "monotonic are fine for spans (they time, they don't "
            "decide)."
        ),
        "CL502": (
            "Process-global unseeded RNGs make two runs of the same "
            "trace diverge — the chaos harness's whole proof is "
            "byte-identical convergence.\n"
            "Fix: thread a seeded random.Random / "
            "np.random.default_rng(seed) through the call chain."
        ),
        "CL503": (
            "A fault schedule constructed without an explicit seed "
            "cannot be replayed; the one failing chaos run you need "
            "to debug is gone.\n"
            "Fix: pass seed= explicitly at every net/faults.py "
            "constructor call."
        ),
        "CL504": (
            "Python set order is hash-salted per process; packing "
            "fed by bare set iteration differs run to run.\n"
            "Fix: wrap the iteration in sorted(...)."
        ),
    }

    def prepare(self, ctx: LintContext) -> None:
        """Collect ``net/faults.py`` classes whose __init__ takes a
        ``seed`` parameter — the constructors CL503 covers."""
        seeded: Set[str] = set()
        mod = ctx.module_by_path(FAULTS_SUFFIX)
        if mod is not None and mod.tree is not None:
            for node in mod.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                for sub in node.body:
                    if (isinstance(sub, ast.FunctionDef)
                            and sub.name == "__init__"):
                        params = [
                            a.arg for a in (
                                sub.args.posonlyargs + sub.args.args
                                + sub.args.kwonlyargs
                            )
                        ]
                        if "seed" in params:
                            seeded.add(node.name)
        ctx.shared["seeded_ctors"] = seeded

    def check_module(self, mod: Module,
                     ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        seeded_ctors: Set[str] = ctx.shared.get("seeded_ctors", set())
        core = in_scope(mod.path, CORE_SCOPE)

        for node in ast.walk(mod.tree):
            # CL503 — package-wide
            if isinstance(node, ast.Call):
                cname = (call_name(node) or "").rsplit(".", 1)[-1]
                if cname in seeded_ctors and not mod.path.endswith(
                    FAULTS_SUFFIX
                ):
                    has_seed = bool(node.args) or any(
                        k.arg == "seed" for k in node.keywords
                    )
                    if not has_seed:
                        findings.append(Finding(
                            mod.path, node.lineno, "CL503",
                            f"`{cname}(...)` without an explicit "
                            f"seed — fault schedules must be "
                            f"seeded for deterministic replay "
                            f"(round-7 contract)",
                            symbol=cname,
                        ))
            if not core:
                continue
            if isinstance(node, ast.Call):
                cname = call_name(node) or ""
                tail = cname.rsplit(".", 1)[-1]
                # CL501
                if cname in ("time.time", "time.time_ns"):
                    findings.append(Finding(
                        mod.path, node.lineno, "CL501",
                        "wall-clock read in the packing core — "
                        "timestamps must arrive as inputs "
                        "(perf_counter/monotonic are fine for "
                        "spans)",
                        symbol=cname,
                    ))
                # CL502
                parts = cname.split(".")
                if (len(parts) == 2 and parts[0] == "random"
                        and parts[1] not in _RANDOM_OK):
                    findings.append(Finding(
                        mod.path, node.lineno, "CL502",
                        f"`{cname}()` uses the process-global "
                        f"unseeded RNG — thread a seeded "
                        f"Random/default_rng through instead",
                        symbol=cname,
                    ))
                elif (tail in _NP_RANDOM_GLOBALS
                        and ".random." in f".{cname}"
                        and "default_rng" not in cname):
                    findings.append(Finding(
                        mod.path, node.lineno, "CL502",
                        f"`{cname}()` uses numpy's legacy global "
                        f"RNG — use np.random.default_rng(seed)",
                        symbol=cname,
                    ))
                elif tail in ("default_rng", "Random") and not (
                    node.args or node.keywords
                ):
                    findings.append(Finding(
                        mod.path, node.lineno, "CL502",
                        f"`{cname}()` without a seed draws OS "
                        f"entropy — pass an explicit seed in the "
                        f"packing core",
                        symbol=f"{cname}:unseeded",
                    ))
            # CL504
            iters: List[ast.AST] = []
            if isinstance(node, ast.For):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp, ast.DictComp)):
                iters.extend(g.iter for g in node.generators)
            for it in iters:
                if _is_set_expr(it):
                    findings.append(Finding(
                        mod.path, it.lineno, "CL504",
                        "iterating a set in the packing core — "
                        "set order is hash-salted across "
                        "processes; wrap in sorted(...)",
                        symbol="set-iter",
                    ))
        return findings
