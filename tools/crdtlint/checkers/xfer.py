"""CL401: transfer-seam bypass (the round-9 byte-accounting contract).

Every H2D/D2H byte must flow through ``ops/device.py``'s
``xfer_put`` / ``xfer_fetch`` seam — that is what makes
``xfer.h2d_bytes`` / ``xfer.d2h_bytes`` trustworthy enough for
``tools/metrics_diff.py`` to gate on. A raw ``jax.device_put`` (or a
``np.asarray(...)`` host-materialization of a dispatch result)
anywhere else ships bytes the accounting never sees, and the diet
silently rots.

Flagged outside ``ops/device.py``:

- ``jax.device_put(...)`` / ``jax.device_get(...)`` — always;
- ``jax.block_until_ready(...)`` — always (legitimate
  execution-waits are baselined with that justification; a wait that
  *precedes a raw fetch* is the classic bypass shape);
- ``np.asarray(v)`` / ``v.item()`` where ``v`` was bound from a known
  device-dispatch call (a donating jit entry, ``converge_async``, or
  ``xfer_put`` itself) — a D2H fetch dressed as a cast.

Baseline fingerprints anchor on ``<op>:<enclosing function>:<ordinal
within that function>`` so they survive unrelated line churn.
"""

from __future__ import annotations

import ast
from collections import Counter
from typing import Dict, Iterable, List, Set

from tools.crdtlint.astutil import (
    assigned_names,
    call_name,
    dotted,
    enclosing_function_map,
)
from tools.crdtlint.core import Checker, Finding, LintContext, Module

SEAM_SUFFIX = "ops/device.py"
_ALWAYS_FLAGGED = ("device_put", "device_get", "block_until_ready")
# call names whose results live on device (fed by the donate index)
_DEVICE_PRODUCERS = ("converge_async", "xfer_put")
_FETCHY_CASTS = ("asarray",)  # np.asarray / _np.asarray — jnp stays on device


class TransferSeamChecker(Checker):
    name = "xfer-seam"
    codes = {
        "CL401": "H2D/D2H traffic outside the ops/device.py "
                 "xfer_put/xfer_fetch accounting seam",
    }
    explain = {
        "CL401": (
            "xfer.h2d_bytes / xfer.d2h_bytes are regression-gated; "
            "a raw device_put or an np.asarray of a dispatch result "
            "ships bytes the gate never sees, and the transfer diet "
            "silently rots.\n"
            "Fix: route uploads through xfer_put and fetches "
            "through xfer_fetch; a pure execution wait "
            "(block_until_ready with no bytes moving) is baselined "
            "with exactly that justification."
        ),
    }

    def check_module(self, mod: Module,
                     ctx: LintContext) -> Iterable[Finding]:
        if mod.path.endswith(SEAM_SUFFIX):
            return ()
        findings: List[Finding] = []
        donating: Dict[str, object] = ctx.shared.get("donating_defs", {})
        fn_of = enclosing_function_map(mod.tree)
        ordinals: Counter = Counter()

        def sym(op: str, node: ast.AST) -> str:
            fn = fn_of.get(id(node), "<module>")
            key = f"{op}:{fn}"
            ordinals[key] += 1
            return f"{key}:{ordinals[key]}"

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            tail = name.rsplit(".", 1)[-1]
            # `x.block_until_ready()` — the array-method spelling — is
            # the same wait as `jax.block_until_ready(x)`; only JAX
            # arrays grow that method, so any attribute call counts
            # (including on un-dotted receivers like `f(x).block_...`)
            method_wait = (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
            )
            if method_wait and not name:
                name = tail = "block_until_ready"
            if tail in _ALWAYS_FLAGGED and (
                name.startswith("jax.") or name == tail or method_wait
            ):
                findings.append(Finding(
                    mod.path, node.lineno, "CL401",
                    f"`{name}` outside ops/device.py — route "
                    f"transfers through xfer_put/xfer_fetch so the "
                    f"round-9 byte accounting sees them "
                    f"(block_until_ready: baseline with an "
                    f"'execution wait, not transfer' justification "
                    f"if no bytes move)",
                    symbol=sym(tail, node),
                ))

        # device-value taint per function, IN SOURCE ORDER: a name is
        # tainted while bound to a dispatch result and untainted the
        # moment it is rebound from anything else (`x = xfer_fetch(x)`
        # produces a host array — a later `np.asarray(x)` is not a
        # bypass, and neither is one that textually PRECEDES the
        # dispatch that binds x)
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            tainted: Set[str] = set()
            # uses are checked before the same line's rebind takes
            # effect, so `x = np.asarray(x)` on a tainted x still fires
            events = sorted(
                (n for n in ast.walk(fn)
                 if isinstance(n, (ast.Call, ast.Assign))),
                key=lambda n: (
                    n.lineno, isinstance(n, ast.Assign), n.col_offset
                ),
            )
            for node in events:
                if isinstance(node, ast.Assign):
                    is_producer = False
                    if isinstance(node.value, ast.Call):
                        cname = (call_name(node.value) or "").rsplit(
                            ".", 1
                        )[-1]
                        # donating_defs maps name -> list of defs (one
                        # per defining module); any non-factory def
                        # means the call returns a device value
                        cands = donating.get(cname) or ()
                        is_producer = (
                            cname in _DEVICE_PRODUCERS
                            or any(
                                not getattr(d, "is_factory", True)
                                for d in cands
                            )
                        )
                    for t in node.targets:
                        if is_producer:
                            tainted.update(assigned_names(t))
                        else:
                            tainted.difference_update(
                                assigned_names(t)
                            )
                    continue
                cname = call_name(node) or ""
                tail = cname.rsplit(".", 1)[-1]
                if tail in _FETCHY_CASTS and not cname.startswith(
                    "jnp."
                ):
                    for a in node.args[:1]:
                        tgt = dotted(a)
                        if tgt in tainted:
                            findings.append(Finding(
                                mod.path, node.lineno, "CL401",
                                f"`{cname}({tgt})` host-materializes "
                                f"a device dispatch result outside "
                                f"the seam — use xfer_fetch so the "
                                f"D2H bytes are accounted",
                                symbol=f"asarray:{fn.name}:{tgt}",
                            ))
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"
                        and not node.args):
                    base = dotted(node.func.value)
                    if base in tainted:
                        findings.append(Finding(
                            mod.path, node.lineno, "CL401",
                            f"`.item()` on device value `{base}` "
                            f"outside the seam — a hidden D2H "
                            f"transfer; fetch through xfer_fetch",
                            symbol=f"item:{fn.name}:{base}",
                        ))
        return findings
