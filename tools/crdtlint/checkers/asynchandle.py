"""CL901/CL902: async-handle and paired-protocol discipline (r16).

The overlap architecture (rounds 9–15) lives on two-step seams:
``converge_async`` returns a handle whose staged buffers are DONATED
to the in-flight dispatch, and only ``converge_fetch`` releases them.
A handle that never reaches a fetch on some control-flow path pins a
donated device buffer for the life of the process — the slow leak no
test notices because the result was never needed on that path. The
same shape governs paired start/stop protocols: a profiler trace left
running corrupts the next capture, an installed fault hook left in
place fails every later dispatch.

Both checkers walk the round-16 lite CFG
(:mod:`tools.crdtlint.cfg`):

- **CL901** — a ``converge_async`` handle bound to a name must be
  CONSUMED on every normal path before function exit: passed to a
  call (``converge_fetch(h)``, ``q.put((h, ...))``), returned,
  yielded, or stored into an attribute/container. A bare
  ``converge_async(plan)`` expression statement drops the handle on
  the spot; rebinding an unconsumed handle (the classic loop bug)
  is reported at the rebind. Exception paths are exempt — an
  unwinding process releases buffers with it.
- **CL902** — after a SUCCESSFUL opener (``start_trace``,
  an ``old = set_device_fault_hook(...)`` capture,
  ``lock.acquire()``), the matching closer must be hit on every
  path INCLUDING exception edges — i.e. the closer lives in a
  ``finally`` or an except-all handler. Protocol objects whose
  opener and closer live in paired methods (``install``/
  ``uninstall``, ``__enter__``/``__exit__``) are exempt: the
  context-manager seam is the discipline.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.crdtlint.astutil import assigned_names, call_name, dotted
from tools.crdtlint.cfg import CFG, EXIT, RAISE
from tools.crdtlint.core import Checker, Finding, LintContext, Module

_ASYNC_PRODUCERS = ("converge_async",)
_CONSUMERS = ("converge_fetch",)

# opener tail -> closer tail. acquire/release is shape-gated (the
# opener must be a bare-expression or assigned call on a lock-like
# receiver; `with lock:` never reaches here).
_PAIRS = {
    "start_trace": "stop_trace",
    "set_device_fault_hook": "set_device_fault_hook",
    "acquire": "release",
}


def _header_nodes(st) -> list:
    """The AST actually evaluated AT a CFG node. Compound statements
    are headers in the CFG — their bodies are separate nodes — so
    dataflow predicates must scan only the header expressions, or an
    `if` whose BODY consumes a handle would wrongly satisfy the path
    through its else."""
    if isinstance(st, ast.If) or isinstance(st, ast.While):
        return [st.test]
    if isinstance(st, (ast.For, ast.AsyncFor)):
        return [st.iter]
    if isinstance(st, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in st.items]
    if isinstance(st, ast.Try):
        return []
    if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
        return []
    return [st]


def _call_tail(node: ast.Call) -> str:
    name = call_name(node) or ""
    tail = name.rsplit(".", 1)[-1]
    if not tail and isinstance(node.func, ast.Attribute):
        tail = node.func.attr
    return tail


def _aliases(fn) -> Dict[str, str]:
    """Local aliases of protocol callables: ``start =
    profiler.start_trace`` maps ``start -> start_trace``."""
    out: Dict[str, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and not isinstance(
            node.value, ast.Call
        ):
            src = dotted(node.value)
            if not src:
                continue
            tail = src.rsplit(".", 1)[-1]
            if tail in _PAIRS or tail in set(_PAIRS.values()):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = tail
    return out


class AsyncHandleChecker(Checker):
    name = "async-handle"
    codes = {
        "CL901": "converge_async handle dropped on some path "
                 "(never reaches converge_fetch — pins a donated "
                 "device buffer)",
        "CL902": "paired start/stop protocol (profiler trace, fault "
                 "hook, lock.acquire) not closed on exception edges",
    }
    explain = {
        "CL901": (
            "converge_async enqueues the dispatch and DONATES the "
            "staged buffers; only converge_fetch (or handing the "
            "handle to whoever will fetch it) releases them. A path "
            "that returns without consuming the handle pins device "
            "memory for the process lifetime — invisible until the "
            "allocator OOMs a thousand ticks later.\n"
            "Fix: fetch on every path (including early returns), or "
            "push the handle into the in-flight queue/deque the "
            "consumer drains; if a path genuinely abandons the "
            "dispatch, fetch-and-discard so the buffers free."
        ),
        "CL902": (
            "start_trace without stop_trace on the exception path "
            "leaves the profiler running into (and corrupting) the "
            "next capture; an installed device fault hook left "
            "behind fails every later dispatch; a bare acquire() "
            "without release() in a finally deadlocks the next "
            "taker.\n"
            "Fix: close in a `finally:` (or an except-all handler "
            "that closes before re-raising), or wrap the pair in a "
            "context manager — protocol objects with install/"
            "uninstall or __enter__/__exit__ methods already are "
            "the fix and are exempt."
        ),
    }

    def check_module(self, mod: Module,
                     ctx: LintContext) -> Iterable[Finding]:
        if mod.tree is None:
            return ()
        findings: List[Finding] = []
        # cheap text pre-filter FIRST: most modules never mention an
        # async producer or a protocol opener, and everything below
        # (class index, per-function tail scans, CFG builds) is cost
        # paid for nothing on those
        interesting = tuple(_ASYNC_PRODUCERS) + tuple(_PAIRS)
        if not any(t in mod.source for t in interesting):
            return findings
        # class -> method names (for the protocol-object exemption)
        class_methods: Dict[int, Set[str]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                names = {
                    n.name for n in node.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                }
                for n in node.body:
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        class_methods[id(n)] = names
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            tails = {
                _call_tail(n) for n in ast.walk(fn)
                if isinstance(n, ast.Call)
            }
            has_producer = any(t in tails for t in _ASYNC_PRODUCERS)
            has_opener = any(t in tails for t in _PAIRS) or any(
                a in set(_PAIRS) | set(_PAIRS.values())
                for a in _aliases(fn).values()
            )
            if not (has_producer or has_opener):
                continue
            cfg = CFG(fn)
            if has_producer:
                self._check_handles(fn, cfg, mod, findings)
            if has_opener:
                self._check_pairs(fn, cfg, mod, findings,
                                  class_methods.get(id(fn), set()))
        return findings

    # ---- CL901 ---------------------------------------------------------

    def _check_handles(self, fn, cfg: CFG, mod: Module,
                       findings: List[Finding]) -> None:
        for st in cfg.stmts:
            # bare-expression producer: dropped immediately
            if isinstance(st, ast.Expr) and isinstance(
                st.value, ast.Call
            ) and _call_tail(st.value) in _ASYNC_PRODUCERS:
                findings.append(Finding(
                    mod.path, st.lineno, "CL901",
                    f"`{call_name(st.value) or 'converge_async'}"
                    f"(...)` result discarded in `{fn.name}` — the "
                    f"handle (and its donated buffers) is dropped "
                    f"on the spot; fetch it or hand it to the "
                    f"consumer",
                    symbol=f"{fn.name}:drop:{st.lineno}",
                ))
                continue
            if not isinstance(st, ast.Assign):
                continue
            if not (isinstance(st.value, ast.Call)
                    and _call_tail(st.value) in _ASYNC_PRODUCERS):
                continue
            names = [t.id for t in st.targets
                     if isinstance(t, ast.Name)]
            for h in names:
                bad = self._walk_handle(cfg, st, h)
                if bad is not None:
                    kind, line = bad
                    msg = (
                        f"handle `{h}` from `converge_async` is "
                        + ("rebound before being consumed (line "
                           f"{line}) — the in-flight dispatch and "
                           f"its donated buffers leak"
                           if kind == "rebind" else
                           "not consumed on every path to return — "
                           "a path exists where the donated "
                           "buffers never free")
                    )
                    findings.append(Finding(
                        mod.path,
                        line if kind == "rebind" else st.lineno,
                        "CL901", msg + f" (in `{fn.name}`)",
                        symbol=f"{fn.name}:{kind}:{h}",
                    ))

    @staticmethod
    def _walk_handle(cfg: CFG, producer: ast.Assign,
                     h: str) -> Optional[Tuple[str, int]]:
        """DFS normal edges from the producer. Returns ("exit", line)
        when some path reaches EXIT unconsumed, ("rebind", line) when
        the handle is overwritten unconsumed (incl. looping back to
        the producer)."""
        def consumes(st) -> bool:
            for root in _header_nodes(st):
                for node in ast.walk(root):
                    if isinstance(node, ast.Name) and node.id == h \
                            and isinstance(node.ctx, ast.Load):
                        return True
            return False

        def rebinds(st) -> bool:
            if isinstance(st, (ast.Assign, ast.AugAssign,
                               ast.AnnAssign)):
                targets = (st.targets if isinstance(st, ast.Assign)
                           else [st.target])
                for t in targets:
                    if h in assigned_names(t):
                        return True
            if isinstance(st, (ast.For, ast.AsyncFor)):
                return h in assigned_names(st.target)
            return False

        seen: Set[int] = set()
        work = list(cfg.succ_norm.get(id(producer), ()))
        while work:
            node = work.pop()
            if node == EXIT:
                return ("exit", producer.lineno)
            if node == RAISE:
                continue  # unwinding frees with the process
            if node is producer or (
                isinstance(node, ast.stmt) and node is producer
            ):
                return ("rebind", producer.lineno)
            nid = id(node)
            if nid in seen:
                continue
            seen.add(nid)
            if consumes(node):
                continue
            if rebinds(node):
                return ("rebind", node.lineno)
            work.extend(cfg.succ_norm.get(nid, ()))
        return None

    # ---- CL902 ---------------------------------------------------------

    def _check_pairs(self, fn, cfg: CFG, mod: Module,
                     findings: List[Finding],
                     sibling_methods: Set[str]) -> None:
        aliases = _aliases(fn)

        def canon(tail: str) -> str:
            return aliases.get(tail, tail)

        # find opener statements
        for st in cfg.stmts:
            call = None
            captured = False
            if isinstance(st, ast.Expr) and isinstance(
                st.value, ast.Call
            ):
                call = st.value
            elif isinstance(st, ast.Assign) and isinstance(
                st.value, ast.Call
            ):
                call = st.value
                captured = True
            if call is None:
                continue
            tail = canon(_call_tail(call))
            closer = _PAIRS.get(tail)
            if closer is None:
                continue
            if tail == "set_device_fault_hook" and not captured:
                continue  # plain restore/uninstall call, not an open
            if tail == "acquire" and not isinstance(
                call.func, ast.Attribute
            ):
                continue
            if tail == "acquire" and not _lockish_recv(call):
                continue

            def is_closer(st2, closer=closer, call=call):
                return _stmt_closes(st2, closer, call, canon)

            # same-function closer present?
            has_local_closer = any(
                is_closer(s) for s in cfg.stmts
                if s is not st
            )
            if not has_local_closer:
                # protocol-object exemption: closer in a sibling
                # method (install/uninstall, __enter__/__exit__)
                if self._sibling_closes(fn, closer, sibling_methods,
                                        mod):
                    continue
                findings.append(Finding(
                    mod.path, st.lineno, "CL902",
                    f"`{tail}` opened in `{fn.name}` with no "
                    f"matching `{closer}` anywhere in the function "
                    f"or a paired method — the protocol never "
                    f"closes",
                    symbol=f"{fn.name}:{tail}:unclosed",
                ))
                continue
            # closer exists: must be hit on every path incl.
            # exception edges, starting AFTER the opener succeeded
            seen: Set[int] = set()
            work = list(cfg.succ_norm.get(id(st), ()))
            leak = None
            while work:
                node = work.pop()
                if node in (EXIT, RAISE):
                    if node == RAISE:
                        leak = "exception"
                        break
                    leak = "return"
                    break
                nid = id(node)
                if nid in seen:
                    continue
                seen.add(nid)
                if is_closer(node):
                    continue
                work.extend(cfg.succ_norm.get(nid, ()))
                work.extend(cfg.succ_exc.get(nid, ()))
            if leak is not None:
                findings.append(Finding(
                    mod.path, st.lineno, "CL902",
                    f"`{tail}` in `{fn.name}`: a "
                    f"{'raising' if leak == 'exception' else 'returning'} "
                    f"path skips `{closer}` — close in a finally "
                    f"(or an except-all that closes before "
                    f"re-raising)",
                    symbol=f"{fn.name}:{tail}:{leak}",
                ))

    @staticmethod
    def _sibling_closes(fn, closer: str, sibling_methods: Set[str],
                        mod: Module) -> bool:
        if not sibling_methods:
            return False
        # the exemption needs the closer to actually appear in some
        # sibling method body
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and node is not fn \
                    and node.name in sibling_methods:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) \
                            and _call_tail(sub) == closer:
                        return True
        return False


def _stmt_closes(st, closer: str, opener_call: ast.Call,
                 canon) -> bool:
    """Does statement ``st`` (header only — compound bodies are their
    own CFG nodes) call the protocol's closer?"""
    for root in _header_nodes(st):
        for node in ast.walk(root):
            if isinstance(node, ast.Call):
                t2 = canon(_call_tail(node))
                if t2 == closer and node is not opener_call:
                    if closer == "release":
                        return _lockish_recv(node)
                    return True
    return False


def _lockish_recv(call: ast.Call) -> bool:
    recv = dotted(call.func.value) if isinstance(
        call.func, ast.Attribute
    ) else None
    if not recv:
        return False
    low = recv.lower()
    return any(s in low for s in ("lock", "mutex", "sem"))
