"""CL201/CL202/CL203: observability registry conformance (round 8).

The README "Observability" tables and ``tests/test_bench_smoke.py``
pin the span/counter/gauge and flight-recorder event registries as
stable contracts. This checker diffs those registries against what
the package actually emits, **both ways**:

- **CL201 — unregistered name.** A string literal passed to
  ``span()`` / ``count()`` / ``gauge()`` / ``observe()`` / recorder
  ``record()`` that no registry documents: new instrumentation must
  land with its registry row (or be baselined while the docs PR is in
  flight).
- **CL202 — dead registry entry.** A documented name nothing emits:
  the docs promise a metric that rotted out of the code.
- **CL203 — non-literal metric name.** A computed first argument
  outside the allowlist. Computed names silently bypass CL201/CL202
  (and make grep-ability lies), so they are opt-in per seam.

Names dotless at the top level (the hot-path spans ``decode``,
``pack`` …) are matched against the HOT_PATH_SPANS pin. Label suffixes
(``name{k="v"}``) are stripped on both sides. Tracer/recorder
infrastructure modules are excluded from the usage scan — they pass
names through generically.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from tools.crdtlint.astutil import enclosing_function_map, str_const
from tools.crdtlint.core import Checker, Finding, LintContext, Module
from tools.crdtlint.registry import Registry, load_registry

_EMIT_METHODS = ("span", "count", "gauge", "observe")
_INFRA_SUFFIXES = (
    "obs/tracer.py", "obs/recorder.py", "obs/export.py",
    "utils/trace.py", "obs/profiling.py",
)
# (path suffix, enclosing function) pairs allowed to emit COMPUTED
# metric names: seams that take the name as an explicit parameter so
# call sites stay greppable
COMPUTED_ALLOWLIST = (
    ("guard/faults.py", "retry_with_backoff"),
    ("ops/device.py", "xfer_put"),
    ("ops/device.py", "xfer_fetch"),
    # round 22: Controller.observe(sensors) is the control plane's
    # rule-engine consult (one sensor dict per tick), not a metric
    # emission — the controller's own tracer calls stay literal and
    # registry-checked
    ("models/multidoc.py", "_run_control"),
    ("obs/control.py", "replay"),
)


class MetricsRegistryChecker(Checker):
    name = "metrics-registry"
    codes = {
        "CL201": "metric/event name emitted but absent from the "
                 "documented registry (README / test_bench_smoke)",
        "CL202": "registry documents a name nothing emits",
        "CL203": "computed (non-literal) metric name outside the "
                 "allowlist",
    }
    explain = {
        "CL201": (
            "A metric emitted but missing from the README registry "
            "tables is invisible to reviewers and ungated by "
            "metrics_diff — exactly the round-8 drift crdtlint was "
            "built to stop.\n"
            "Fix: add the name to the README Observability/Failure "
            "tables (backticked), or rename to an existing "
            "documented name."
        ),
        "CL202": (
            "A documented name nothing emits is a dead registry "
            "entry: dashboards chart nothing and reviewers trust a "
            "fiction.\n"
            "Fix: delete the registry row, or wire the emission it "
            "promised."
        ),
        "CL203": (
            "A computed metric name defeats both registry "
            "directions — the checker cannot see what will be "
            "emitted.\n"
            "Fix: declare the closed name set at the call site with "
            "`# crdtlint: emits=a.b,a.c` (each declared name stays "
            "registry-checked), or switch to a literal name with a "
            "label dict."
        ),
    }

    def prepare(self, ctx: LintContext) -> None:
        reg = ctx.shared.get("metric_registry")
        if reg is None:
            reg = load_registry(
                ctx.config.readme_path, ctx.config.smoke_test_path
            )
            ctx.shared["metric_registry"] = reg
        # name -> first (path, line) that emits it
        ctx.shared["emitted_metrics"] = {}

    def check_module(self, mod: Module,
                     ctx: LintContext) -> Iterable[Finding]:
        if any(mod.path.endswith(s) for s in _INFRA_SUFFIXES):
            return ()
        reg: Registry = ctx.shared["metric_registry"]
        emitted: Dict[str, Tuple[str, int]] = ctx.shared["emitted_metrics"]
        findings: List[Finding] = []

        # enclosing-function map (innermost) for the computed-name
        # allowlist and CL203 symbols
        func_of = enclosing_function_map(mod.tree)

        def check_registered(name: str, lineno: int, what: str):
            emitted.setdefault(name, (mod.path, lineno))
            if name not in reg.all_names:
                findings.append(Finding(
                    mod.path, lineno, "CL201",
                    f"`{name}` ({what}) is not in the documented "
                    f"registry — add it to the README registry "
                    f"table (round-8 contract)",
                    symbol=name,
                ))

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            # metric-name kwargs on pass-through seams (the
            # `retry_with_backoff(..., counter="persist.retries")`
            # pattern): the literal at the CALL site is the emission
            for k in node.keywords:
                if k.arg in ("counter", "metric"):
                    klit = str_const(k.value)
                    if klit:
                        check_registered(klit, node.lineno, "counter")
            # require a receiver (`tracer.count`, `get_tracer().count`,
            # `rec.record`): bare `count()` calls are unrelated
            if not isinstance(node.func, ast.Attribute):
                continue
            meth = node.func.attr
            is_record = meth == "record"
            if meth not in _EMIT_METHODS and not is_record:
                continue
            if not node.args:
                continue
            lit = str_const(node.args[0])
            if lit is None:
                declared = mod.emits_near(node.lineno)
                fn = func_of.get(id(node), "<module>")
                if declared:
                    # the site declares its closed name set; each
                    # declared name is still registry-checked
                    for name in sorted(declared):
                        check_registered(
                            name, node.lineno,
                            "event" if is_record else meth,
                        )
                    continue
                if any(
                    mod.path.endswith(p) and fn == f
                    for p, f in COMPUTED_ALLOWLIST
                ):
                    continue
                findings.append(Finding(
                    mod.path, node.lineno, "CL203",
                    f"computed metric name passed to `{meth}()` — "
                    f"registry conformance can't see it; use a "
                    f"string literal, or declare the closed name "
                    f"set with `# crdtlint: emits=a.b,c.d`",
                    symbol=f"{fn}:{meth}",
                ))
                continue
            check_registered(
                lit, node.lineno, "event" if is_record else meth
            )
        return findings

    def finalize(self, ctx: LintContext) -> Iterable[Finding]:
        reg: Registry = ctx.shared["metric_registry"]
        emitted: Dict[str, Tuple[str, int]] = ctx.shared["emitted_metrics"]
        if not emitted:
            return ()  # synthetic runs with no instrumented modules
        findings: List[Finding] = []
        emitted_names: Set[str] = set(emitted)
        for name in sorted(reg.all_names - emitted_names):
            src_path, src_line = reg.sources.get(name, ("<registry>", 1))
            findings.append(Finding(
                _relish(src_path, ctx), src_line, "CL202",
                f"registry documents `{name}` but nothing in the "
                f"scanned tree emits it — dead entry or renamed "
                f"metric",
                symbol=name,
            ))
        return findings


def _relish(path: str, ctx: LintContext) -> str:
    """Registry source paths are absolute; findings use repo-relative
    posix paths like every other checker."""
    import os

    root = ctx.config.repo_root
    try:
        return os.path.relpath(path, root).replace(os.sep, "/")
    except ValueError:
        return path.replace(os.sep, "/")
