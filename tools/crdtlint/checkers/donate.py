"""CL101/CL102: donated device buffers (the round-9 contract).

``jax.jit(..., donate_argnums=...)`` hands the argument's device
buffer to the compiled program — the allocator reuses it for outputs,
so the caller's array is *invalidated* by the call. The repo's rules:

- **CL101 — use-after-donate.** At any call site of a donating
  callable, reading a donated argument after the call (before
  rebinding it) is a bug on donation-capable backends; so is donating
  the same un-rebound buffer on every loop iteration (the second
  dispatch consumes a dead buffer).
- **CL102 — donating converge entry without an undonated twin.** A
  donating *converge entry point* must ship an escape hatch for
  consumers that redispatch the same buffer (bench probes, host
  routes): a ``<name>_nodonate`` twin in the same module (the
  ``_converge_packed_nodonate`` / ``make_repeat_dispatch`` pattern).
  In-place update kernels (splice/grow/relabel) whose call sites
  always rebind are baselined, not exempted — the ledger keeps the
  reasoning reviewable.

Donating callables are resolved three ways: decorated module-level
defs (``@partial(jax.jit, donate_argnums=...)``), factory functions
returning ``jax.jit(fn, donate_argnums=...)`` (the gossip/delta
``make_*_step`` pattern, including ``self.attr = factory(...)``
assignments), and imports of either.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.crdtlint.astutil import (
    assigned_names,
    call_name,
    dotted,
    int_tuple,
    kw,
    make_module_resolver,
)
from tools.crdtlint.core import Checker, Finding, LintContext, Module


def _donate_argnums_of_jit_call(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """argnums if ``call`` is a jit/partial(jit) call carrying
    ``donate_argnums``."""
    name = call_name(call) or ""
    is_jit = name.endswith("jit")
    is_partial_jit = name.endswith("partial") and any(
        (dotted(a) or "").endswith("jit") for a in call.args
    )
    if not (is_jit or is_partial_jit):
        return None
    dn = kw(call, "donate_argnums")
    if dn is None:
        return None
    return int_tuple(dn) or ()


def _decorated_donation(fn: ast.FunctionDef) -> Optional[Tuple[int, ...]]:
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            nums = _donate_argnums_of_jit_call(dec)
            if nums is not None:
                return nums
    return None


@dataclass
class _DonatingDef:
    module: str
    name: str
    line: int
    argnums: Tuple[int, ...]
    is_factory: bool  # returns a donating jit rather than being one


class DonateChecker(Checker):
    name = "donate"
    codes = {
        "CL101": "donated argument read (or re-donated in a loop) "
                 "after the donating dispatch",
        "CL102": "donating converge entry lacks an undonated twin "
                 "(`_nodonate` / make_repeat_dispatch pattern)",
    }
    explain = {
        "CL101": (
            "donate_argnums hands the argument's device buffer to "
            "the compiled program; the allocator reuses it for "
            "outputs, so the caller's array is DEAD after the call. "
            "Reading it later (or donating the same un-rebound "
            "buffer on every loop iteration) works on CPU and "
            "corrupts on donation-capable backends.\n"
            "Fix: rebind the name from the dispatch's return value "
            "before any further read, or call the `_nodonate` twin "
            "when you genuinely need the input to survive."
        ),
        "CL102": (
            "Repeat-dispatch consumers (bench probes, host routes) "
            "re-feed the same buffer to a converge entry; if the "
            "only entry donates, they consume a dead buffer on the "
            "second call.\n"
            "Fix: ship a `<name>_nodonate` twin (or a "
            "make_repeat_dispatch factory) next to every donating "
            "converge entry; in-place update kernels whose call "
            "sites always rebind are baselined instead, keeping the "
            "reasoning reviewable."
        ),
    }

    def prepare(self, ctx: LintContext) -> None:
        # name -> ALL donating defs with that name, one per defining
        # module: same-named defs in different modules must not
        # overwrite each other (a collision either hid a real CL101 or
        # invented one on an unrelated local function)
        defs: Dict[str, List[_DonatingDef]] = {}
        module_defs: Dict[str, Set[str]] = {}
        for mod in ctx.modules:
            if mod.tree is None:
                continue
            names: Set[str] = set()
            for node in mod.tree.body:
                if isinstance(node, ast.FunctionDef):
                    names.add(node.name)
                    nums = _decorated_donation(node)
                    if nums:
                        defs.setdefault(node.name, []).append(
                            _DonatingDef(
                                mod.path, node.name, node.lineno, nums,
                                False,
                            )
                        )
                        continue
                    fact = self._factory_argnums(node)
                    if fact:
                        defs.setdefault(node.name, []).append(
                            _DonatingDef(
                                mod.path, node.name, node.lineno, fact,
                                True,
                            )
                        )
            module_defs[mod.path] = names
        ctx.shared["donating_defs"] = defs
        ctx.shared["module_defs"] = module_defs

    @staticmethod
    def _make_resolver(mod: Module, defs: Dict[str, List[_DonatingDef]],
                       module_defs: Dict[str, Set[str]]):
        """Module-aware donating-def lookup, built on the shared
        :func:`tools.crdtlint.astutil.make_module_resolver` machinery
        (round 16 moved it there so the call graph resolves names the
        same way): the calling module's own defs win, a local
        non-donating def SHADOWS another module's same-named donating
        def, and an explicit import picks the defining module when
        several donate under one name."""
        return make_module_resolver(
            mod.path, mod.tree, module_defs.get(mod.path, set()), defs,
        )

    @staticmethod
    def _factory_argnums(fn: ast.FunctionDef) -> Optional[Tuple[int, ...]]:
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and isinstance(
                node.value, ast.Call
            ):
                nums = _donate_argnums_of_jit_call(node.value)
                if nums:
                    return nums
        return None

    # -- per-module use-after-donate ------------------------------------
    def check_module(self, mod: Module,
                     ctx: LintContext) -> Iterable[Finding]:
        defs: Dict[str, List[_DonatingDef]] = ctx.shared["donating_defs"]
        module_defs: Dict[str, Set[str]] = ctx.shared["module_defs"]
        if mod.tree is None:
            return ()
        findings: List[Finding] = []
        resolve = self._make_resolver(mod, defs, module_defs)
        # factory-built donating callables bound to self attributes
        # anywhere in the module: attr name -> argnums
        attr_callables: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                argnums = self._resolve_factory_call(node.value, resolve)
                if argnums is None:
                    continue
                for tgt in node.targets:
                    d = dotted(tgt)
                    if d and d.startswith("self."):
                        attr_callables[d] = argnums

        for fn in ast.walk(mod.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            self._scan_function(
                fn, mod, resolve, attr_callables, findings
            )
        return findings

    @staticmethod
    def _resolve_factory_call(
        call: ast.Call, resolve
    ) -> Optional[Tuple[int, ...]]:
        """argnums when ``call`` invokes a known donating factory —
        directly, through a module alias, or through an IfExp choosing
        between factories (the fleet ``build = (a if ... else b)``
        shape collapses to a Name by then, so also accept calls whose
        func resolves via a local binding; that resolution happens in
        ``_scan_function`` for plain names)."""
        fn = call.func
        if isinstance(fn, ast.IfExp):
            cands = [dotted(fn.body), dotted(fn.orelse)]
        else:
            cands = [dotted(fn)]
        for cand in cands:
            if not cand:
                continue
            d = resolve(cand)
            if d is not None and d.is_factory:
                return d.argnums
        return None

    def _scan_function(
        self,
        fn: ast.FunctionDef,
        mod: Module,
        resolve,
        attr_callables: Dict[str, Tuple[int, ...]],
        findings: List[Finding],
    ) -> None:
        # local names bound to donating callables within this function
        # (``step = make_gossip_step(...)`` / ``build = a if c else b``)
        local_callables: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                val = node.value
                argnums = None
                if isinstance(val, ast.Call):
                    argnums = self._resolve_factory_call(val, resolve)
                elif isinstance(val, (ast.IfExp, ast.Name, ast.Attribute)):
                    # a bare factory reference (not yet called):
                    # ``build = make_a if cond else make_b``
                    cands = (
                        [dotted(val.body), dotted(val.orelse)]
                        if isinstance(val, ast.IfExp) else [dotted(val)]
                    )
                    for c in cands:
                        d = resolve(c) if c else None
                        if d is not None and d.is_factory:
                            # calling this name CREATES a donating fn;
                            # the created value donates d.argnums
                            for t in node.targets:
                                nm = dotted(t)
                                if nm:
                                    local_callables[f"{nm}()"] = d.argnums
                if argnums is not None:
                    for t in node.targets:
                        nm = dotted(t)
                        if nm:
                            local_callables[nm] = argnums

        def donating_call(call: ast.Call) -> Optional[Tuple[int, ...]]:
            name = call_name(call)
            if not name:
                return None
            d = resolve(name)
            if d is not None and not d.is_factory:
                return d.argnums
            if name in local_callables:
                return local_callables[name]
            if name in attr_callables:
                return attr_callables[name]
            # ``build(...)`` where build holds a factory reference:
            # the RESULT donates, the call itself doesn't
            return None

        donated: Dict[str, Tuple[int, str]] = {}  # name -> (line, callee)
        self._walk_block(
            list(fn.body), donated, donating_call, mod, findings
        )

    # -- dataflow --------------------------------------------------------
    def _walk_block(self, stmts, donated, donating_call, mod, findings):
        for st in stmts:
            self._walk_stmt(st, donated, donating_call, mod, findings)

    def _walk_stmt(self, st, donated, donating_call, mod, findings):
        if isinstance(st, ast.If):
            # test first: a donation inside the test expression (e.g.
            # ``if _converge(mat):``) must flow into both branches
            self._eval_expr(st.test, donated, donating_call, mod, findings)
            d1, d2 = dict(donated), dict(donated)
            self._walk_block(st.body, d1, donating_call, mod, findings)
            self._walk_block(st.orelse, d2, donating_call, mod, findings)
            donated.clear()
            donated.update(d1)
            donated.update(d2)
        elif isinstance(st, (ast.For, ast.While)):
            if isinstance(st, ast.For):
                self._eval_expr(
                    st.iter, donated, donating_call, mod, findings
                )
                for nm in assigned_names(st.target):
                    donated.pop(nm, None)
            else:
                self._eval_expr(
                    st.test, donated, donating_call, mod, findings
                )
            body_donated: Dict[str, Tuple[int, str]] = dict(donated)
            self._walk_block(
                st.body, body_donated, donating_call, mod, findings
            )
            # back-edge: a name donated inside the body with NO rebind
            # anywhere in the body is re-donated (dead) next iteration
            kills = set()
            for sub in ast.walk(st):
                for t in self._stmt_targets(sub):
                    kills.add(t)
                if isinstance(sub, ast.For):
                    kills.update(assigned_names(sub.target))
                elif isinstance(sub, ast.withitem) and sub.optional_vars:
                    kills.update(assigned_names(sub.optional_vars))
            for nm, (line, callee) in body_donated.items():
                if nm in donated and donated[nm] == (line, callee):
                    continue  # donated before the loop, not inside it
                if nm not in kills:
                    findings.append(Finding(
                        mod.path, line, "CL101",
                        f"`{nm}` is donated to `{callee}` inside a "
                        f"loop and never rebound in the loop body — "
                        f"the next iteration dispatches a dead buffer",
                        symbol=f"loop:{callee}:{nm}",
                    ))
            donated.update(body_donated)
            self._walk_block(
                st.orelse, donated, donating_call, mod, findings
            )
        elif isinstance(st, ast.Try):
            branches = []
            d0 = dict(donated)
            self._walk_block(st.body, d0, donating_call, mod, findings)
            branches.append(d0)
            for h in st.handlers:
                dh = dict(donated)
                self._walk_block(
                    h.body, dh, donating_call, mod, findings
                )
                branches.append(dh)
            donated.clear()
            for b in branches:
                donated.update(b)
            self._walk_block(st.orelse, donated, donating_call, mod,
                             findings)
            self._walk_block(st.finalbody, donated, donating_call, mod,
                             findings)
        elif isinstance(st, ast.With):
            for item in st.items:
                self._eval_expr(
                    item.context_expr, donated, donating_call, mod,
                    findings,
                )
            self._walk_block(st.body, donated, donating_call, mod,
                             findings)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            pass  # nested scopes are scanned independently
        else:
            # leaf statement: evaluate expressions (uses + donations),
            # then apply assignment kills
            for node in ast.iter_child_nodes(st):
                if isinstance(node, ast.expr):
                    self._eval_expr(
                        node, donated, donating_call, mod, findings
                    )
            for nm in self._stmt_targets(st):
                donated.pop(nm, None)

    @staticmethod
    def _stmt_targets(st) -> List[str]:
        out: List[str] = []
        if isinstance(st, ast.Assign):
            for t in st.targets:
                out.extend(assigned_names(t))
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            out.extend(assigned_names(st.target))
        return out

    def _eval_expr(self, expr, donated, donating_call, mod, findings):
        """Check Loads against the donated set, then apply donations
        from any donating calls in the expression (reads happen before
        the dispatch's donation takes effect)."""
        for nm, line in _loads(expr):
            # `self._mat.shape` is a use of donated `self._mat`:
            # match the donated name or any deeper attribute chain
            hit = nm if nm in donated else next(
                (d for d in donated if nm.startswith(d + ".")), None
            )
            if hit is not None:
                dline, callee = donated[hit]
                findings.append(Finding(
                    mod.path, line, "CL101",
                    f"`{hit}` read after being donated to `{callee}` "
                    f"(line {dline}); donated buffers are dead after "
                    f"dispatch — rebind or use an undonated entry",
                    symbol=f"use:{callee}:{hit}",
                ))
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                argnums = donating_call(node)
                if not argnums:
                    continue
                callee = call_name(node) or "<donating>"
                names = self._donated_arg_names(node, argnums)
                for nm in names:
                    donated[nm] = (node.lineno, callee)

    @staticmethod
    def _donated_arg_names(call: ast.Call, argnums) -> List[str]:
        out = []
        pos = 0
        for a in call.args:
            if isinstance(a, ast.Starred):
                # a starred arg covers every remaining donated index:
                # track the starred base name itself
                if any(n >= pos for n in argnums):
                    d = dotted(a.value)
                    if d:
                        out.append(d)
                break
            if pos in argnums:
                d = dotted(a)
                if d:
                    out.append(d)
            pos += 1
        return out

    # -- missing-twin (finalize: needs the whole-module def sets) -------
    def finalize(self, ctx: LintContext) -> Iterable[Finding]:
        defs: Dict[str, List[_DonatingDef]] = ctx.shared["donating_defs"]
        module_defs: Dict[str, Set[str]] = ctx.shared["module_defs"]
        findings: List[Finding] = []
        for d in (d for lst in defs.values() for d in lst):
            siblings = module_defs.get(d.module, set())
            if d.is_factory:
                # factories: any sibling undonated path (a
                # `*_nodonate` def or a repeat-dispatch maker) counts
                has_twin = any(
                    s.endswith("_nodonate") or "repeat_dispatch" in s
                    for s in siblings
                )
            else:
                if "converge" not in d.name:
                    continue  # in-place update kernels: CL101 covers
                    #           their call sites; no twin required
                has_twin = f"{d.name}_nodonate" in siblings
            if not has_twin:
                findings.append(Finding(
                    d.module, d.line, "CL102",
                    f"donating jit `{d.name}` has no undonated twin "
                    f"(`{d.name}_nodonate`) — repeat-dispatch "
                    f"consumers (bench probes, host routes) cannot "
                    f"use it",
                    symbol=d.name,
                ))
        return findings


def _loads(expr) -> List[Tuple[str, int]]:
    """Outermost dotted Load chains in an expression, with lines."""
    out: List[Tuple[str, int]] = []

    class V(ast.NodeVisitor):
        def visit_Attribute(self, node):
            d = dotted(node)
            if d is not None and isinstance(node.ctx, ast.Load):
                out.append((d, node.lineno))
                return  # don't descend into our own chain
            self.generic_visit(node)

        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Load):
                out.append((node.id, node.lineno))

    V().visit(expr)
    return out
