"""CL801–CL803: lock discipline across the threaded surface (r16).

Rounds 6–15 grew a real threaded runtime — the streaming decode pool,
the background stager, the live-ingest ``serve()`` loop — and the
locks guarding it are scattered across eight modules. Three bug
classes survive any amount of per-function review because they are
*relations between* acquisition sites:

- **CL801 — lock-order cycle (potential deadlock).** Build a lock
  acquisition graph: an edge A→B whenever code acquires B while
  holding A — lexically nested ``with`` blocks, plus calls made under
  A whose STRONG call-graph closure acquires B. A cycle means two
  threads can each hold one lock of the cycle and wait on the next.
- **CL802 — blocking call under a lock.** Device dispatch *fetches*
  (``converge_fetch`` / ``xfer_fetch`` / ``block_until_ready``),
  native KV / socket IO (``kv_*`` / ``udp_*`` ABI calls,
  ``subprocess.run``), ``Future.result()`` / ``Thread.join()`` /
  ``queue.get`` / ``time.sleep`` — each can stall for the tunnel's
  25–110 ms (or forever) while every other thread piles up on the
  lock. Checked through the same STRONG closure, so a with-block that
  calls a helper whose callee blocks is still caught.
- **CL803 — guarded-field inconsistency.** For thread-shared classes
  (any class with a method reachable from a ``Thread``/
  ``ThreadPoolExecutor`` target via the call graph, plus every class
  in the CL601 threaded-module scope) that own a lock: an instance
  attribute written both under ``with self.<lock>`` and outside it
  (``__init__`` exempt — the object is not shared yet) is a torn
  write waiting for a scheduler.

Lock identity: ``self.<attr>`` keys on the enclosing class,
module-level names on the defining module, anything else on the bare
name — and ``self._lock = other._lock`` aliasing UNIONs the two
identities (union-find), so an alias never manufactures a phantom
two-lock cycle.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.crdtlint.astutil import MUTATOR_METHODS as _MUTATORS
from tools.crdtlint.astutil import call_name, dotted, import_map
from tools.crdtlint.callgraph import get_callgraph, reach_closure
from tools.crdtlint.checkers.threadshare import (
    THREADED_SUFFIXES,
    _is_lock_expr,
)
from tools.crdtlint.core import Checker, Finding, LintContext, Module

# blocking primitives by dotted-name tail. `.result()` / `.join()` /
# `.get()` are attribute-call-shape-gated below (str.join and
# dict.get must not fire).
_BLOCKING_TAILS = {
    "converge_fetch", "xfer_fetch", "block_until_ready",
    "device_get", "sleep", "wait",
}
_BLOCKING_DOTTED = {
    "subprocess.run", "subprocess.check_call",
    "subprocess.check_output", "subprocess.call",
}
_BLOCKING_PREFIXES = ("kv_", "udp_", "ct_")  # native ABI calls


class _Union:
    def __init__(self):
        self.p: Dict[object, object] = {}

    def find(self, x):
        self.p.setdefault(x, x)
        while self.p[x] != x:
            self.p[x] = self.p[self.p[x]]
            x = self.p[x]
        return x

    def union(self, a, b):
        self.p[self.find(a)] = self.find(b)


class LockDisciplineChecker(Checker):
    name = "lock-discipline"
    codes = {
        "CL801": "lock-order cycle across acquisition sites "
                 "(potential deadlock)",
        "CL802": "blocking call (device fetch / native IO / "
                 "Future.result / join / sleep) while holding a lock",
        "CL803": "instance attribute of a thread-shared class "
                 "written both under its lock and outside it",
    }
    explain = {
        "CL801": (
            "Two code paths that acquire the same pair of locks in "
            "opposite orders deadlock the moment two threads "
            "interleave between the acquisitions — and the threaded "
            "surface (streaming pool, stager, serve loop) provides "
            "the threads. The checker builds the acquisition graph "
            "(held lock -> lock acquired under it, lexically and "
            "through the strong call-graph closure) and reports "
            "every cycle.\n"
            "Fix: pick one global order (document it at the lock "
            "definitions) and acquire in that order everywhere, or "
            "collapse the pair into one lock."
        ),
        "CL802": (
            "A lock held across a blocking call (a tunnel fetch is "
            "25-110 ms, a native build is seconds, Future.result "
            "can be forever) serializes every other thread behind "
            "IO they don't need. The classic outage shape: one slow "
            "dispatch, and the whole decode pool queues on a memo "
            "lock.\n"
            "Fix: move the blocking call out of the with-block — "
            "compute under the lock, IO outside (the "
            "fetch_packed_i32 pattern: wrap under the lock, compile "
            "at the unlocked call) — or baseline with a "
            "justification naming why the wait is bounded and "
            "intentional (e.g. the one-time native-build locks)."
        ),
        "CL803": (
            "An attribute written under `with self._lock` in one "
            "method and bare in another is only *sometimes* "
            "guarded: the unlocked write can interleave mid-"
            "read-modify-write of the locked one and tear the "
            "state. These surface as once-a-week corruption under "
            "production load and never in tests.\n"
            "Fix: take the lock at every write site (reads too, if "
            "compound), or document single-thread confinement by "
            "baselining with that justification. __init__ is exempt "
            "— the object is not shared yet."
        ),
    }

    # ---- lock node identity -------------------------------------------

    def prepare(self, ctx: LintContext) -> None:
        self._uf = _Union()
        self._module_globals: Dict[str, Set[str]] = {}
        for mod in ctx.modules:
            if mod.tree is None:
                continue
            g: Set[str] = set()
            for node in mod.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            g.add(t.id)
                elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name
                ):
                    g.add(node.target.id)
            self._module_globals[mod.path] = g
        # alias pass: `self.X = <recv>.Y` with both sides lock-like
        # unions ("a", cls, X) with the name-group ("n", Y) — shared
        # locks collapse to one node, so aliasing can only REMOVE
        # phantom cycles, never hide a real two-lock inversion
        cg = get_callgraph(ctx)
        for fi in cg.funcs.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Assign):
                    continue
                tgt = node.targets[0] if node.targets else None
                td = dotted(tgt) if tgt is not None else None
                vd = dotted(node.value)
                if not td or not vd or "." not in vd:
                    continue
                if td.startswith("self.") and fi.cls:
                    tattr, vattr = td[5:], vd.rsplit(".", 1)[-1]
                    if _lockish(tattr) and _lockish(vattr):
                        self._uf.union(
                            ("a", f"{fi.module}:{fi.cls}", tattr),
                            ("n", vattr),
                        )

    def _lock_node(self, expr, mod: Module, cls: Optional[str],
                   imap: Dict[str, str]):
        d = dotted(expr)
        if d is None:
            return None  # `with threading.Lock():` — anonymous
        if d.startswith("self.") and cls:
            return self._uf.find(("a", f"{mod.path}:{cls}", d[5:]))
        if "." not in d:
            if d in self._module_globals.get(mod.path, ()):
                return self._uf.find(("g", mod.path, d))
            return self._uf.find(("n", d))
        head, tail = d.split(".", 1)[0], d.rsplit(".", 1)[-1]
        qual = imap.get(head)
        if qual:
            return self._uf.find(("g", qual, tail))
        return self._uf.find(("n", tail))

    # ---- per-run analysis (finalize: needs every module's sites) ------

    def _scan_function(self, fi, mod: Module,
                       imap: Dict[str, str], acq: Set[object],
                       held_calls: List, pair_edges: Dict) -> None:
        """Pass 1 for one function: record every lock acquisition,
        every lexically nested acquisition as a CL801 edge, and every
        call made while a lock is held."""

        def visit(node, held: Tuple[object, ...]):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new = list(held)
                for item in node.items:
                    if _is_lock_expr(item.context_expr):
                        ln = self._lock_node(
                            item.context_expr, mod, fi.cls, imap
                        )
                        if ln is not None:
                            acq.add(ln)
                            for h in held:
                                pair_edges.setdefault(
                                    (h, ln),
                                    (fi.module, node.lineno, fi.qual),
                                )
                            new.append(ln)
                held = tuple(new)
            elif isinstance(node, ast.Call) and held:
                held_calls.append((held, node, fi.qual))
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                return  # nested defs analyzed as their own nodes
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for child in ast.iter_child_nodes(fi.node):
            visit(child, ())

    def finalize(self, ctx: LintContext) -> Iterable[Finding]:
        cg = get_callgraph(ctx)
        findings: List[Finding] = []

        # pass 1: per function — direct acquisitions, and (held locks,
        # statement) pairs for every call made under a lock
        acquires: Dict[str, Set[object]] = {}
        under: Dict[str, List[Tuple[object, ast.Call, str]]] = {}
        # (held, acquired) -> first site, for CL801 edge provenance
        pair_edges: Dict[Tuple[object, object],
                         Tuple[str, int, str]] = {}
        mod_by_path = {m.path: m for m in ctx.modules}
        # one import map per MODULE (import_map walks the whole tree;
        # per-function recomputation blew the <10s budget)
        imaps: Dict[str, Dict[str, str]] = {
            m.path: import_map(m.tree)
            for m in ctx.modules if m.tree is not None
        }
        for fi in cg.funcs.values():
            mod = mod_by_path.get(fi.module)
            if mod is None or mod.tree is None:
                continue
            acq: Set[object] = set()
            held_calls: List[Tuple[object, ast.Call, str]] = []
            self._scan_function(
                fi, mod, imaps[fi.module], acq, held_calls,
                pair_edges,
            )
            acquires[fi.key] = acq
            under[fi.key] = held_calls

        # pass 2: interprocedural closures over STRONG edges
        memo: Dict[str, Set[str]] = {}
        acq_closure: Dict[str, Set[object]] = {}
        blocking: Dict[str, List[Tuple[str, str]]] = {}
        from tools.crdtlint.callgraph import _own_stmts

        for key in cg.funcs:
            mod = mod_by_path.get(cg.funcs[key].module)
            blk: List[Tuple[str, str]] = []
            if mod is not None and mod.tree is not None:
                # own statements only: nested defs are their own
                # call-graph nodes (walking whole subtrees per
                # ancestor re-scans every nested body)
                for node in _own_stmts(cg.funcs[key].node):
                    if isinstance(node, ast.Call):
                        prim = _blocking_primitive(node)
                        if prim:
                            blk.append((prim, key))
            blocking[key] = blk
        for key in cg.funcs:
            reach = reach_closure(cg, key, strong_only=True,
                                  memo=memo)
            clo = set(acquires.get(key, ()))
            for r in reach:
                clo |= acquires.get(r, set())
            acq_closure[key] = clo

        # CL802 + interprocedural CL801 edges
        edge_site: Dict[Tuple[object, object],
                        Tuple[str, int, str]] = dict(pair_edges)
        ordinals: Dict[str, int] = {}
        for key, calls in under.items():
            fi = cg.funcs[key]
            memo_local: Dict[str, Set[str]] = memo
            for held, call, qual in calls:
                prim = _blocking_primitive(call)
                via = ""
                if prim is None:
                    # does a strong-resolved callee block?
                    for cs in cg.callees(key, strong_only=True):
                        if cs.lineno != call.lineno:
                            continue
                        reach = {cs.callee} | reach_closure(
                            cg, cs.callee, strong_only=True,
                            memo=memo_local,
                        )
                        for r in reach:
                            if blocking.get(r):
                                prim = blocking[r][0][0]
                                via = cs.callee.rsplit(":", 1)[-1]
                                break
                        # CL801: locks acquired by the callee while
                        # we hold `held`
                        for ln2 in acq_closure.get(cs.callee, ()):
                            for h in held:
                                edge_site.setdefault(
                                    (h, ln2),
                                    (fi.module, call.lineno, qual),
                                )
                        if prim:
                            break
                if prim:
                    # ordinal scoped per (module, function, primitive)
                    # so the baseline fingerprint survives unrelated
                    # findings elsewhere in the tree
                    okey = f"{fi.module}|{qual}:{prim}"
                    ordinals[okey] = ordinals.get(okey, 0) + 1
                    msg_via = f" (via `{via}`)" if via else ""
                    findings.append(Finding(
                        fi.module, call.lineno, "CL802",
                        f"blocking call `{prim}`{msg_via} while "
                        f"holding a lock in `{qual}` — every other "
                        f"thread queues on the lock for the full "
                        f"wait; move the IO outside the with-block",
                        symbol=f"{qual}:{prim}:{ordinals[okey]}",
                    ))

        # CL801: cycles among the union-find representatives
        graph: Dict[object, Set[object]] = {}
        for (a, b), site in edge_site.items():
            a, b = self._uf.find(a), self._uf.find(b)
            if a == b:
                continue
            graph.setdefault(a, set()).add(b)
        for cyc in _cycles(graph):
            names = sorted(_lock_label(n) for n in cyc)
            anchor = None
            for (a, b), site in sorted(edge_site.items(),
                                       key=lambda kv: kv[1][:2]):
                if self._uf.find(a) in cyc and self._uf.find(b) in cyc:
                    anchor = site
                    break
            path, line, qual = anchor or ("<unknown>", 1, "<unknown>")
            findings.append(Finding(
                path, line, "CL801",
                f"lock-order cycle {' -> '.join(names)} -> "
                f"{names[0]} (potential deadlock): two threads "
                f"taking the cycle from different entry points "
                f"wedge; pick one global order",
                symbol="cycle:" + "|".join(names),
            ))

        findings.extend(self._guarded_fields(ctx, cg))
        return findings

    # ---- CL803 ---------------------------------------------------------

    def _guarded_fields(self, ctx: LintContext,
                        cg) -> Iterable[Finding]:
        findings: List[Finding] = []
        # thread-shared classes: a method reachable from a thread
        # root (weak edges included — reachability must not miss), or
        # defined in a CL601 threaded module
        shared: Set[Tuple[str, str]] = set()
        for key in cg.thread_reachable:
            fi = cg.funcs.get(key)
            if fi is not None and fi.cls:
                shared.add((fi.module, fi.cls))
        for fi in cg.funcs.values():
            if fi.cls and any(fi.module.endswith(s)
                              for s in THREADED_SUFFIXES):
                shared.add((fi.module, fi.cls))

        for (mod_path, cls) in sorted(shared):
            members = [f for f in cg.funcs.values()
                       if f.module == mod_path and f.cls == cls
                       and "<locals>" not in f.qual]
            lock_attrs = self._class_lock_attrs(members)
            if not lock_attrs:
                continue
            locked_writes: Dict[str, List] = {}
            bare_writes: Dict[str, List] = {}
            for fi in members:
                if fi.name == "__init__":
                    continue
                self._method_writes(fi, lock_attrs, locked_writes,
                                    bare_writes)
            for attr, bare in sorted(bare_writes.items()):
                if attr not in locked_writes or attr in lock_attrs:
                    continue
                for (line, qual) in bare:
                    findings.append(Finding(
                        mod_path, line, "CL803",
                        f"`self.{attr}` written without the lock in "
                        f"`{qual}` but under `with self."
                        f"{sorted(lock_attrs)[0]}` elsewhere in "
                        f"`{cls}` — a torn write on the "
                        f"thread-shared instance",
                        symbol=f"{cls}.{attr}:{qual}",
                    ))
        return findings

    @staticmethod
    def _class_lock_attrs(members) -> Set[str]:
        attrs: Set[str] = set()
        for fi in members:
            for node in ast.walk(fi.node):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        d = dotted(item.context_expr)
                        if (d and d.startswith("self.")
                                and _is_lock_expr(item.context_expr)):
                            attrs.add(d[5:])
                elif (isinstance(node, ast.Assign)
                      and fi.name == "__init__"):
                    for t in node.targets:
                        d = dotted(t)
                        if d and d.startswith("self.") and _lockish(
                            d[5:]
                        ):
                            attrs.add(d[5:])
        return attrs

    @staticmethod
    def _method_writes(fi, lock_attrs, locked_writes, bare_writes):
        # statements lexically inside a `with self.<lock>` block
        locked_ids: Set[int] = set()

        def mark(node, locked):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                if any(
                    (dotted(i.context_expr) or "").startswith("self.")
                    and (dotted(i.context_expr) or "")[5:] in lock_attrs
                    for i in node.items
                ):
                    locked = True
            for child in ast.iter_child_nodes(node):
                if locked:
                    locked_ids.add(id(child))
                mark(child, locked)

        mark(fi.node, False)

        def note(attr, node):
            bucket = (locked_writes if id(node) in locked_ids
                      else bare_writes)
            bucket.setdefault(attr, []).append(
                (node.lineno, fi.qual)
            )

        for node in ast.walk(fi.node):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    base = t
                    if isinstance(t, ast.Subscript):
                        base = t.value
                    d = dotted(base)
                    if d and d.startswith("self.") and d.count(".") == 1:
                        note(d[5:], node)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in _MUTATORS):
                d = dotted(node.func.value)
                if d and d.startswith("self.") and d.count(".") == 1:
                    note(d[5:], node)


def _lockish(name: str) -> bool:
    return any(s in name.lower() for s in
               ("lock", "rlock", "mutex", "semaphore"))


def _blocking_primitive(call: ast.Call) -> Optional[str]:
    name = call_name(call) or ""
    tail = name.rsplit(".", 1)[-1]
    attr = call.func.attr if isinstance(call.func, ast.Attribute) \
        else ""
    if name in _BLOCKING_DOTTED:
        return name
    if tail in _BLOCKING_TAILS:
        return name or tail
    if any(tail.startswith(p) for p in _BLOCKING_PREFIXES) and attr:
        return name or tail
    # shape-gated attribute calls: Future.result() / Thread.join()
    # take no positional args; str.join / dict.get take one
    if attr in ("result", "join") and not call.args:
        return f"{dotted(call.func.value) or '<recv>'}.{attr}"
    if attr == "get" and call.args and (
        dotted(call.func.value) or ""
    ).split(".")[-1] in ("q", "queue", "inbox"):
        return f"{dotted(call.func.value)}.get"
    return None


def _lock_label(node) -> str:
    node = node if isinstance(node, tuple) else (str(node),)
    if node[0] == "a":
        return f"{node[1]}.{node[2]}"
    if node[0] == "g":
        return f"{node[1]}:{node[2]}"
    return str(node[-1])


def _cycles(graph: Dict[object, Set[object]]) -> List[Set[object]]:
    """Strongly connected components with >1 node, via the shared
    iterative Tarjan (:func:`tools.crdtlint.callgraph._tarjan` — one
    SCC implementation in the suite, no recursion-limit exposure)."""
    from tools.crdtlint.callgraph import _tarjan

    adj: Dict[object, Set[object]] = dict(graph)
    for succs in graph.values():
        for v in succs:
            adj.setdefault(v, set())
    _, comps = _tarjan(adj)
    return [set(c) for c in comps if len(c) > 1]
