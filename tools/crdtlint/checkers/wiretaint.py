"""CL1001–CL1004: wire-taint bounds analysis for untrusted inputs
(round 17).

The wire-compatible gateway (ROADMAP item 1) will point the decode
paths — ``codec/lib0.py``, ``codec/v1.py``, ``codec/native.py``, the
kv/WAL readers, the udp frame handlers — at UNMODIFIED clients on the
open internet. A hostile varint length or splice offset is the
classic memory-amplification / crash vector in the Yjs binary update
codec this repo is wire-compatible with. The round-10 fuzz suite
defends those paths *dynamically* (540 seeded mutants); this checker
is the static complement: every integer read off the wire must be
bounds-fenced before it reaches an index, a slice, a ``range()``, or
an allocation size.

**Taint sources** — a call whose result is attacker-controlled:

- the lib0/v1 varint/byte readers (``read_var_uint``, ``read_uint8``,
  ``read_any``, ... — matched by tail, any receiver);
- kv get/scan results (``get``/``scan``/``scan_prefix``/``keys`` on a
  receiver spelling that names the kv store: on-disk bytes may have
  been written by a peer or corrupted);
- native udp receive frames (``recv_all``/``recv``/``udp_recv``);
- any function carrying a ``# crdtlint: taints`` directive on its
  ``def`` line (or the comment line directly above), plus —
  interprocedurally — any scope function whose RETURN value derives
  from a source through STRONG-resolved calls (the round-16
  resolution machinery; a guessed edge must never lend a function
  someone else's taint).

**Propagation** — assignments, tuple unpacking, arithmetic,
``int()``/abs()-style magnitude-preserving conversions, and attribute
stores on decoder objects (``self.pos = tainted``).

**Sanitization** (CFG-aware, on the guarded edges):

- a comparison-guarded branch on the tainted value — ``if n > MAX:
  raise ValueError`` kills the taint on the fall-through edge,
  ``if n < bound: use(n)`` kills it inside the guarded branch;
- an explicit ``min()``/``max()`` clamp;
- a call to a helper declared ``# crdtlint: sanitizes`` (the helper
  owns the admission check — e.g. v1's ``_read_client_id``);
- guards that do NOT reference the input buffer (an absolute
  constant bound) still kill the taint here but are remembered as
  *weak* — the decode-allocation checker (CL1101) holds decode entry
  points to the stricter buffer-anchored standard.

**Sinks:**

- **CL1001** — tainted index or slice bound (``buf[n]``,
  ``data[a:b]`` with a tainted bound, tainted subscript-store key);
- **CL1002** — tainted allocation size: ``range``/``bytearray``/
  ``zeros``/``empty``/``full``/``frombuffer`` argument, or a
  sequence-repetition ``[0] * n`` / ``b"x" * n``;
- **CL1003** — tainted loop bound (``for _ in range(n)``) whose body
  neither consumes wire bytes per iteration (a reader call raises on
  exhaustion, so the trip count is buffer-capped) nor checks a
  cumulative budget (a comparison + raise);
- **CL1004** — a tainted value crossing into the staging layer
  (``ops/packed`` column inputs — ``stage``/``stage_resident_delta``
  or any STRONG-resolved callee under ``crdt_tpu/ops/``) without an
  admission check.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.crdtlint.astutil import (
    assigned_names,
    call_name,
    dotted,
    import_map,
    in_scope,
    make_module_resolver,
)
from tools.crdtlint.core import Checker, Finding, LintContext, Module

SCOPE = ("crdt_tpu/codec/", "crdt_tpu/storage/", "crdt_tpu/net/",
         # round 19: the wire trace-context decode rides update
         # frames off the open network — same hostile-input class as
         # the codec paths, same machine-checked fences
         "crdt_tpu/obs/propagation.py")

# wire-reader call tails: distinctive enough to match on any receiver
READER_TAILS = frozenset({
    "read_uint8", "read_var_uint", "read_var_int", "read_var_string",
    "read_var_uint8_array", "read_bytes", "read_float32",
    "read_float64", "read_int64", "read_any",
})
# kv results are tainted only when the receiver spelling names the
# store (`kv.get`, `self._kv.scan_prefix`, `self._require().keys`) —
# `.get` alone is every dict in the package
KV_TAILS = frozenset({"get", "scan", "scan_prefix", "keys"})
UDP_TAILS = frozenset({"recv_all", "recv", "udp_recv"})
ALLOC_TAILS = frozenset({
    "range", "bytearray", "zeros", "empty", "full", "frombuffer",
})
STAGING_TAILS = frozenset({"stage", "stage_resident_delta"})
# magnitude-preserving conversions: the result is as hostile as the
# argument
_PRESERVING = frozenset({"int", "abs", "float", "round"})
# clean-result builtins: the value is a host fact, not wire content
_CLEAN_CALLS = frozenset({"min", "max", "len", "bool", "isinstance",
                          "sorted", "enumerate", "zip"})

_TAINTS_RE = re.compile(r"#\s*crdtlint:\s*taints\b")
_SANITIZES_RE = re.compile(r"#\s*crdtlint:\s*sanitizes\b")

# names that anchor a guard to the input buffer: a comparison
# mentioning one of these (or `len(...)`) bounds the tainted value
# relative to what was actually received, which is the only bound
# that makes a length-prefixed allocation safe
_BUFFER_ANCHORS = ("len", "pos", "remaining", "budget", "data", "buf")


def directive_funcs(mod: Module, directive_re) -> Set[str]:
    """Qualnames of defs carrying ``directive_re`` on their def line
    or the comment line directly above it."""
    marked_lines = {
        i for i, text in enumerate(mod.lines, start=1)
        if "crdtlint" in text and directive_re.search(text)
    }
    if not marked_lines:
        return set()
    out: Set[str] = set()
    for qual, fn in iter_defs(mod.tree):
        cand = {fn.lineno, fn.lineno - 1}
        # decorators shift lineno; accept the decorator line too
        for dec in fn.decorator_list:
            cand.add(dec.lineno - 1)
        if cand & marked_lines:
            out.add(qual)
    return out


def iter_defs(tree) -> Iterable[Tuple[str, ast.FunctionDef]]:
    """(qualname, def) pairs — methods as ``Class.meth``, nested defs
    as ``outer.<locals>.inner`` (matching the call graph's quals)."""

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield qual, child
                yield from visit(child, f"{qual}.<locals>.")
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, f"{prefix}{child.name}.")
            else:
                yield from visit(child, prefix)

    yield from visit(tree, "")


class _FnRef:
    """Candidate shape for make_module_resolver (needs ``.module``)."""

    __slots__ = ("module", "name", "qual")

    def __init__(self, module: str, name: str, qual: str):
        self.module = module
        self.name = name
        self.qual = qual


class TaintIndex:
    """Cross-module taint facts for the scope modules, built once per
    run and shared through ``ctx.shared`` by both wire-taint checkers.

    ``tainting`` / ``sanitizing`` hold ``module:qual`` keys. The
    tainting set starts from the ``# crdtlint: taints`` directives and
    closes over returns: a function whose return value is tainted
    under the current set joins it, to a fixpoint (STRONG resolution
    only — same-module defs, explicit imports, ``self.`` methods)."""

    def __init__(self, ctx: LintContext):
        self.mods = [
            m for m in ctx.modules
            if m.tree is not None and in_scope(m.path, SCOPE)
        ]
        self.defs: Dict[str, Dict[str, ast.FunctionDef]] = {}
        self.tainting: Set[str] = set()
        self.sanitizing: Set[str] = set()
        cands: Dict[str, List[_FnRef]] = {}
        for m in self.mods:
            self.defs[m.path] = {}
            for qual, fn in iter_defs(m.tree):
                self.defs[m.path][qual] = fn
                cands.setdefault(fn.name, []).append(
                    _FnRef(m.path, fn.name, qual)
                )
            for qual in directive_funcs(m, _TAINTS_RE):
                self.tainting.add(f"{m.path}:{qual}")
            for qual in directive_funcs(m, _SANITIZES_RE):
                self.sanitizing.add(f"{m.path}:{qual}")
        # staging-layer defs join the CANDIDATE index only (never
        # walked, never in the fixpoint): a scope module's strong
        # call into crdt_tpu/ops/ must resolve so CL1004 can see the
        # crossing — without this, only the hard-coded stage tails
        # would ever fire
        for m in ctx.modules:
            if m.tree is None or not in_scope(
                m.path, ("crdt_tpu/ops/",)
            ):
                continue
            for qual, fn in iter_defs(m.tree):
                cands.setdefault(fn.name, []).append(
                    _FnRef(m.path, fn.name, qual)
                )
        self._resolvers = {}
        for m in self.mods:
            top = {q for q in self.defs[m.path] if "." not in q}
            self._resolvers[m.path] = make_module_resolver(
                m.path, m.tree, top, cands, fallback_first=False,
                imap=import_map(m.tree),
            )
        # return-taint fixpoint (bounded: the chain depth through
        # wrapper helpers is tiny in practice)
        for _ in range(5):
            grew = False
            for m in self.mods:
                for qual, fn in self.defs[m.path].items():
                    key = f"{m.path}:{qual}"
                    if key in self.tainting or key in self.sanitizing:
                        continue
                    walker = _TaintWalk(m, fn, qual, self,
                                        collect_findings=False)
                    walker.run()
                    if walker.returns_tainted:
                        self.tainting.add(key)
                        grew = True
            if not grew:
                break

    def classify_call(self, call: ast.Call, mod: Module,
                      self_quals: Dict[str, str]) -> str:
        """-> "source" | "sanitizer" | "staging" | "clean" | "other"
        for a call expression seen from ``mod``."""
        name = call_name(call) or ""
        tail = name.rsplit(".", 1)[-1] if name else (
            call.func.attr if isinstance(call.func, ast.Attribute)
            else ""
        )
        if not tail:
            return "other"
        if name in _CLEAN_CALLS:
            return "clean"
        key = self._resolve_key(name, tail, call, mod, self_quals)
        if key is not None:
            if key in self.sanitizing:
                return "sanitizer"
            if key in self.tainting:
                return "source"
            if key.split(":", 1)[0].find("crdt_tpu/ops/") >= 0:
                return "staging"
        if tail in READER_TAILS:
            return "source"
        if tail in UDP_TAILS:
            return "source"
        if tail in KV_TAILS and _kv_receiver(call):
            return "source"
        if tail in STAGING_TAILS:
            return "staging"
        return "other"

    def _resolve_key(self, name: str, tail: str, call: ast.Call,
                     mod: Module,
                     self_quals: Dict[str, str]) -> Optional[str]:
        # self.meth within the enclosing class
        if name.startswith("self.") and "." not in name[5:]:
            q = self_quals.get(name[5:])
            if q is not None:
                return f"{mod.path}:{q}"
        if not name:
            return None
        # bare same-module def (incl. methods called unqualified is
        # not a thing; top-level only)
        if "." not in name and name in self.defs.get(mod.path, {}):
            return f"{mod.path}:{name}"
        hit = self._resolvers.get(mod.path, lambda n: None)(name)
        if hit is not None:
            return f"{hit.module}:{hit.qual}"
        return None


def _kv_receiver(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    recv = call.func.value
    d = dotted(recv)
    if d is not None:
        return "kv" in d.lower() or "store" in d.lower()
    # `self._require().scan_prefix(...)` — receiver is a call to the
    # handle accessor
    if isinstance(recv, ast.Call):
        n = call_name(recv) or ""
        return n.rsplit(".", 1)[-1] in ("_require", "_make_kv")
    return False


def get_taint_index(ctx: LintContext) -> TaintIndex:
    idx = ctx.shared.get("taint_index")
    if idx is None:
        idx = TaintIndex(ctx)
        ctx.shared["taint_index"] = idx
    return idx


class _TaintWalk:
    """Source-ordered, branch-aware intraprocedural taint pass over
    one function (the round-11 'lite walk' style: approximate where a
    full dataflow would be heavy, conservative in the direction that
    misses findings rather than inventing them).

    Collected outputs:
    - ``findings`` (when ``collect_findings``): CL1001/2/3/4 events as
      (code, lineno, detail, symbol_hint) tuples — the checker wraps
      them in Findings;
    - ``returns_tainted``: any ``return`` whose value is tainted (the
      TaintIndex fixpoint input);
    - ``weak_allocs``: allocation sinks whose length was sanitized
      only by a non-buffer-anchored guard — the CL1101 input.
    """

    def __init__(self, mod: Module, fn, qual: str, index: TaintIndex,
                 *, collect_findings: bool = True,
                 taint_params: bool = False):
        self.mod = mod
        self.fn = fn
        self.qual = qual
        self.index = index
        self.collect = collect_findings
        self.tainted: Set[str] = set()
        self.weak: Set[str] = set()     # cleanly guarded, but not
        #                                 against the buffer
        self.findings: List[tuple] = []
        self.weak_allocs: List[tuple] = []
        self.returns_tainted = False
        self._skip_calls: Set[int] = set()  # range() handled as loop
        # methods of the enclosing class, for self.* resolution
        cls = qual.rsplit(".", 2)[0] if "." in qual else None
        self.self_quals: Dict[str, str] = {}
        if cls and ".<locals>" not in cls:
            for q in index.defs.get(mod.path, ()):
                if q.startswith(f"{cls}.") and "." not in q[len(cls) + 1:]:
                    self.self_quals[q.rsplit(".", 1)[-1]] = q
        if taint_params:
            args = fn.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.arg != "self":
                    self.tainted.add(a.arg)

    # -- driver ----------------------------------------------------------

    def run(self) -> None:
        self._block(self.fn.body)

    def _block(self, stmts) -> None:
        for st in stmts:
            self._stmt(st)

    # -- statements ------------------------------------------------------

    def _stmt(self, st) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # separate scope; nested defs walked on their own
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = st.value
            if value is not None:
                self._expr(value)
                hot = self._taint_of(value)
            else:
                hot = False
            targets = (st.targets if isinstance(st, ast.Assign)
                       else [st.target])
            for t in targets:
                # tainted index on a subscript-store is a sink too
                if isinstance(t, ast.Subscript):
                    self._check_subscript(t)
            if isinstance(st, ast.AugAssign):
                hot = hot or self._taint_of(st.target)
            for t in targets:
                for name in assigned_names(t):
                    if hot:
                        self.tainted.add(name)
                        self.weak.discard(name)
                    else:
                        self.tainted.discard(name)
                        self.weak.discard(name)
            return
        if isinstance(st, (ast.If, ast.While)):
            self._expr(st.test)
            self._apply_guard(st.test)
            self._block(st.body)
            self._block(st.orelse)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._for(st)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._expr(item.context_expr)
            self._block(st.body)
            return
        if isinstance(st, ast.Try):
            self._block(st.body)
            for h in st.handlers:
                self._block(h.body)
            self._block(st.orelse)
            self._block(st.finalbody)
            return
        if isinstance(st, ast.Return):
            if st.value is not None:
                self._expr(st.value)
                if self._taint_of(st.value):
                    self.returns_tainted = True
            return
        if isinstance(st, ast.Raise):
            if st.exc is not None:
                self._expr(st.exc)
            return
        if isinstance(st, ast.Expr):
            self._expr(st.value)
            return
        if isinstance(st, (ast.Assert, ast.Delete)):
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._expr(child)
            return
        # pass/break/continue/import/global/nonlocal: nothing to do

    def _for(self, st) -> None:
        it = st.iter
        rng_arg = self._range_len_arg(it)
        if (self.collect and rng_arg is not None
                and self._taint_of(rng_arg)):
            self._skip_calls.add(id(it))
            if not self._loop_consumes(st.body):
                self._emit("CL1003", st.lineno, ast.unparse(rng_arg)
                           if hasattr(ast, "unparse") else "bound")
        self._expr(it)
        if rng_arg is None and self._taint_of(it):
            for name in assigned_names(st.target):
                self.tainted.add(name)
        self._block(st.body)
        self._block(st.orelse)

    # -- expressions -----------------------------------------------------

    def _expr(self, e) -> None:
        """Walk an expression checking sinks (comprehension-aware).
        Sink checks never change taint state, so the fixpoint's
        fast passes (``collect_findings=False``) skip them — that
        keeps the return-taint closure's cost a fraction of the
        finding pass instead of a multiple of it."""
        if not self.collect:
            return
        for node in ast.walk(e):
            if isinstance(node, ast.Subscript):
                self._check_subscript(node)
            elif isinstance(node, ast.Call):
                self._check_call(node)
            elif isinstance(node, ast.BinOp) and isinstance(
                node.op, ast.Mult
            ):
                self._check_repeat(node)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp)):
                self._check_comp(node)

    def _check_subscript(self, node: ast.Subscript) -> None:
        sl = node.slice
        parts = (
            [p for p in (sl.lower, sl.upper, sl.step) if p is not None]
            if isinstance(sl, ast.Slice) else [sl]
        )
        for p in parts:
            hot = sorted(self._names_in(p) & self.tainted)
            if hot:
                kind = ("slice bound"
                        if isinstance(sl, ast.Slice) else "index")
                self._emit("CL1001", node.lineno,
                           f"{hot[0]} ({kind})", symbol=hot[0])
                return

    def _check_call(self, node: ast.Call) -> None:
        if id(node) in self._skip_calls:
            return
        name = call_name(node) or ""
        tail = name.rsplit(".", 1)[-1] if name else (
            node.func.attr if isinstance(node.func, ast.Attribute)
            else ""
        )
        args = list(node.args) + [k.value for k in node.keywords]
        if tail in ALLOC_TAILS:
            for a in args:
                hot = self._names_in(a) & self.tainted
                if hot or self._taint_of(a):
                    self._emit(
                        "CL1002", node.lineno,
                        f"`{tail}` sized by {sorted(hot)[0] if hot else 'a wire read'}",
                        symbol=tail,
                    )
                    return
                weak_hot = self._names_in(a) & self.weak
                if weak_hot:
                    self.weak_allocs.append(
                        (node.lineno, tail, sorted(weak_hot)[0])
                    )
                    return
            return
        cls = self.index.classify_call(node, self.mod, self.self_quals)
        if cls == "staging":
            for a in args:
                hot = self._names_in(a) & self.tainted
                if hot or self._taint_of(a):
                    self._emit(
                        "CL1004", node.lineno,
                        f"`{tail}` receives "
                        f"{sorted(hot)[0] if hot else 'a wire read'}",
                        symbol=tail,
                    )
                    return

    def _check_repeat(self, node: ast.BinOp) -> None:
        # [0] * n, b"\x00" * n — allocation by repetition
        for side, other in ((node.left, node.right),
                            (node.right, node.left)):
            if not (isinstance(other, ast.List) or (
                isinstance(other, ast.Constant)
                and isinstance(other.value, (str, bytes))
            )):
                continue
            hot = self._names_in(side) & self.tainted
            if hot or (not self._names_in(side)
                       and self._taint_of(side)):
                self._emit(
                    "CL1002", node.lineno,
                    f"sequence repetition sized by "
                    f"{sorted(hot)[0] if hot else 'a wire read'}",
                    symbol="repeat",
                )
                return
            weak_hot = self._names_in(side) & self.weak
            if weak_hot:
                self.weak_allocs.append(
                    (node.lineno, "repeat", sorted(weak_hot)[0])
                )
                return

    def _check_comp(self, node) -> None:
        for gen in node.generators:
            rng_arg = self._range_len_arg(gen.iter)
            if rng_arg is None or not self._taint_of(rng_arg):
                continue
            self._skip_calls.add(id(gen.iter))
            elts = ([node.elt] if hasattr(node, "elt")
                    else [node.key, node.value])
            if not any(self._has_reader(e) for e in elts):
                self._emit("CL1003", node.lineno, "comprehension")

    # -- helpers ---------------------------------------------------------

    def _range_len_arg(self, it) -> Optional[ast.expr]:
        if (isinstance(it, ast.Call)
                and (call_name(it) or "").rsplit(".", 1)[-1] == "range"
                and it.args):
            return it.args[-1] if len(it.args) >= 2 else it.args[0]
        return None

    def _loop_consumes(self, body) -> bool:
        """A loop body that reads wire bytes per iteration (the reader
        raises on exhaustion → the trip count is buffer-capped) or
        checks a cumulative budget (comparison + raise) is bounded."""
        for st in body:
            for node in ast.walk(st):
                if isinstance(node, ast.Call) and self._is_reader(node):
                    return True
                if isinstance(node, ast.If) and any(
                    isinstance(s, ast.Raise)
                    for b in (node.body, node.orelse) for s in b
                ) and self._names_in(node.test):
                    return True
        return False

    def _has_reader(self, e) -> bool:
        return any(
            isinstance(n, ast.Call) and self._is_reader(n)
            for n in ast.walk(e)
        )

    def _is_reader(self, call: ast.Call) -> bool:
        # sanitizer helpers (`_read_client_id`) wrap readers: they
        # consume wire bytes and raise at exhaustion just the same,
        # so a loop whose body calls one is buffer-capped too
        return self.index.classify_call(
            call, self.mod, self.self_quals
        ) in ("source", "sanitizer")

    def _names_in(self, e) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(e):
            if isinstance(node, (ast.Name, ast.Attribute)):
                d = dotted(node)
                if d:
                    out.add(d)
        return out

    def _taint_of(self, e) -> bool:
        """Is this expression's VALUE tainted under the current state?
        Clean-wrapping calls (min/max clamps, declared sanitizers,
        len) launder their result; source calls and calls to tainting
        functions poison theirs; everything else propagates from the
        mentioned names and nested calls."""
        if isinstance(e, ast.Call):
            cls = self.index.classify_call(e, self.mod, self.self_quals)
            if cls == "source":
                return True
            if cls in ("sanitizer", "clean"):
                return False
            name = (call_name(e) or "").rsplit(".", 1)[-1]
            if name in _CLEAN_CALLS:
                return False
            if name in _PRESERVING:
                return any(self._taint_of(a) for a in e.args)
            # generic call: tainted if any argument or the receiver is
            # (str.rsplit / json.loads of tainted bytes stay tainted)
            parts = list(e.args) + [k.value for k in e.keywords]
            if isinstance(e.func, ast.Attribute):
                parts.append(e.func.value)
            return any(self._taint_of(a) for a in parts)
        if isinstance(e, (ast.Name, ast.Attribute)):
            d = dotted(e)
            if d is None:
                return any(
                    self._taint_of(c) for c in ast.iter_child_nodes(e)
                    if isinstance(c, ast.expr)
                )
            return d in self.tainted or d.split(".", 1)[0] in self.tainted
        if isinstance(e, ast.Constant):
            return False
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.DictComp,
                          ast.GeneratorExp, ast.Lambda)):
            return False  # contents checked as sinks, value shape new
        return any(
            self._taint_of(c) for c in ast.iter_child_nodes(e)
            if isinstance(c, ast.expr)
        )

    def _apply_guard(self, test) -> None:
        """A comparison in a branch test is the bounds fence: kill
        the taint on the names it mentions (both branch edges — the
        walk is edge-merged: a linter may miss a wrong-way guard,
        never invent one). Buffer-anchored comparisons clear the
        value entirely; absolute-constant ones leave a *weak* mark
        that CL1101 holds decode entries accountable for."""
        mentioned: Set[str] = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Compare):
                mentioned |= self._names_in(node)
        if not mentioned:
            return
        anchored = self._buffer_anchored(test)
        for name in mentioned & self.tainted:
            self.tainted.discard(name)
            if not anchored:
                self.weak.add(name)
        if anchored:
            self.weak -= mentioned

    def _buffer_anchored(self, test) -> bool:
        for node in ast.walk(test):
            if isinstance(node, ast.Call):
                if (call_name(node) or "") == "len":
                    return True
            elif isinstance(node, (ast.Name, ast.Attribute)):
                d = (dotted(node) or "").lower()
                if any(a in d for a in _BUFFER_ANCHORS):
                    return True
        return False

    def _emit(self, code: str, lineno: int, detail: str,
              symbol: str = "") -> None:
        if self.collect:
            self.findings.append((code, lineno, detail, symbol))


_MESSAGES = {
    "CL1001": "wire-tainted {detail} in `{qual}` — bound it against "
              "the buffer (or clamp/guard it) before indexing",
    "CL1002": "wire-tainted allocation in `{qual}`: {detail} — a "
              "hostile declared length buys unbounded memory; fence "
              "it against the buffer remaining or an input-derived "
              "budget first",
    "CL1003": "wire-tainted loop bound in `{qual}` ({detail}) with no "
              "per-iteration wire read and no cumulative budget "
              "check — a few declared bytes must never buy an "
              "unbounded trip count",
    "CL1004": "wire-tainted value crossing into the staging layer in "
              "`{qual}`: {detail} without an admission check — "
              "kernel column inputs must be bounds-fenced at the "
              "decode seam",
}


class WireTaintChecker(Checker):
    name = "wire-taint"
    codes = {
        "CL1001": "wire-tainted value used as an index or slice bound",
        "CL1002": "wire-tainted value sizes an allocation "
                  "(range/frombuffer/zeros/bytearray/repetition)",
        "CL1003": "wire-tainted loop bound without a cumulative cap",
        "CL1004": "wire-tainted value crosses into the staging layer "
                  "without an admission check",
    }
    explain = {
        "CL1001": (
            "An integer read off the wire (varint, byte, kv value, "
            "udp frame) used directly as an index or slice bound "
            "lets a hostile blob address memory the sender never "
            "shipped — the classic Yjs-codec splice-offset crash "
            "vector the round-10 fuzz corpus probes dynamically.\n"
            "Fix: guard it first (`if n > limit: raise ValueError`), "
            "clamp it (`min(n, limit)`), or route it through a "
            "helper declared with `# crdtlint: sanitizes` that owns "
            "the admission check (see v1._read_client_id)."
        ),
        "CL1002": (
            "A declared length is free for the sender and expensive "
            "for you: `bytearray(n)` / `range(n)` / `np.zeros(n)` "
            "sized by an unchecked wire read is memory amplification "
            "— a 5-byte varint allocates gigabytes.\n"
            "Fix: compare the length against the buffer remaining "
            "(or a budget derived from len(data), like "
            "decode_update's expansion budget) and raise ValueError "
            "before allocating."
        ),
        "CL1003": (
            "A loop bounded by a wire-read count with a body that "
            "neither consumes wire bytes per iteration nor checks a "
            "cumulative budget spins as long as the attacker asks. "
            "Bodies that call a reader every iteration are exempt — "
            "the reader raises at end-of-buffer, so the trip count "
            "is capped by bytes actually received.\n"
            "Fix: add a budget check inside the loop (`if total > "
            "budget: raise ValueError`) or read something from the "
            "wire each iteration."
        ),
        "CL1004": (
            "The staging layer (`ops/packed` column inputs) trusts "
            "its columns: clocks fit the 40-bit packing, ids fit the "
            "int64 composites, lengths fit int32 buckets. A wire "
            "value that reaches `stage()` / `stage_resident_delta()` "
            "without passing an admission check can silently alias "
            "rows on device, which no ValueError will ever surface.\n"
            "Fix: fence the value at the decode seam (the _MAX_CLOCK "
            "/ _MAX_ID bounds) or pass it through a `# crdtlint: "
            "sanitizes` helper before it touches column staging."
        ),
    }

    def check_module(self, mod: Module,
                     ctx: LintContext) -> Iterable[Finding]:
        if not in_scope(mod.path, SCOPE) or mod.tree is None:
            return ()
        index = get_taint_index(ctx)
        findings: List[Finding] = []
        for qual, fn in index.defs.get(mod.path, {}).items():
            key = f"{mod.path}:{qual}"
            walker = _TaintWalk(
                mod, fn, qual, index,
                taint_params=key in index.sanitizing,
            )
            walker.run()
            counts: Dict[str, int] = {}
            for code, lineno, detail, sym in walker.findings:
                base = f"{qual}:{sym or code.lower()}"
                counts[base] = counts.get(base, 0) + 1
                symbol = (base if counts[base] == 1
                          else f"{base}:{counts[base]}")
                findings.append(Finding(
                    mod.path, lineno, code,
                    _MESSAGES[code].format(qual=qual, detail=detail),
                    symbol=symbol,
                ))
        return findings
