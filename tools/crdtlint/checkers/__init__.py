"""Checker plugin registry.

Adding a checker: subclass :class:`tools.crdtlint.core.Checker` in a
new module here, give it a unique ``name`` and ``codes`` (pick an
unused ``CLxxx`` range), and append the class to ``ALL_CHECKERS``.
Add a violating + clean snippet pair to ``tests/test_lint.py``'s
still-fires matrix (the tier-1 gate requires every registered code to
fire on its synthetic violation — a checker that can't fire is dead
weight), a README "Static analysis" table row, and an ``explain``
entry (the ``--explain CODE`` CLI surface: rationale + fix recipe).
"""

from tools.crdtlint.checkers.asynchandle import AsyncHandleChecker
from tools.crdtlint.checkers.decodealloc import DecodeAllocChecker
from tools.crdtlint.checkers.determinism import DeterminismChecker
from tools.crdtlint.checkers.donate import DonateChecker
from tools.crdtlint.checkers.exceptions import ExceptionDisciplineChecker
from tools.crdtlint.checkers.lockdiscipline import LockDisciplineChecker
from tools.crdtlint.checkers.metrics import MetricsRegistryChecker
from tools.crdtlint.checkers.threadshare import ThreadSharedStateChecker
from tools.crdtlint.checkers.tracepurity import TracePurityChecker
from tools.crdtlint.checkers.wiretaint import WireTaintChecker
from tools.crdtlint.checkers.xfer import TransferSeamChecker

ALL_CHECKERS = [
    DonateChecker,
    MetricsRegistryChecker,
    ExceptionDisciplineChecker,
    TransferSeamChecker,
    DeterminismChecker,
    ThreadSharedStateChecker,
    TracePurityChecker,
    LockDisciplineChecker,
    AsyncHandleChecker,
    WireTaintChecker,
    DecodeAllocChecker,
]

ALL_CODES = {
    code: desc
    for cls in ALL_CHECKERS
    for code, desc in cls.codes.items()
}

# --explain surface: every code maps to a rationale + fix recipe.
# Checkers may provide an ``explain`` dict; codes without one fall
# back to their one-line invariant.
ALL_EXPLAIN = {
    code: getattr(cls, "explain", {}).get(code, desc)
    for cls in ALL_CHECKERS
    for code, desc in cls.codes.items()
}
