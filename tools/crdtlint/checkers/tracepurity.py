"""CL701–CL704: Python side effects inside traced bodies (round 16).

``jax.jit`` / ``shard_map`` / ``pallas_call`` / ``lax`` control-flow
trace their Python function ONCE per shape bucket and replay the
compiled program forever after. A Python side effect inside a traced
body therefore fires at trace time only — a tracer count that records
1 instead of N, an ``os.environ`` read frozen into the compiled
artifact until an unrelated recompile (the stale-recompile hazard), a
host sync that silently de-pipelines every dispatch, a captured-dict
mutation that happens once and never again. Each is wrong in a way no
runtime test sees: the first call LOOKS right.

The traced set is computed interprocedurally: roots are jit-decorated
defs, function arguments of ``jit``/``shard_map``/``pallas_call`` and
``lax.{while_loop,fori_loop,scan,cond,switch}`` calls, and the inner
defs of donating factories; the closure over the project call graph
(STRONG edges only — a guessed edge must not drag a host helper into
the traced set) is what gets scanned.

- **CL701** — tracer/recorder/print side effects: ``get_tracer`` /
  ``get_recorder`` calls, ``.count/.gauge/.observe/.span/.record`` on
  tracer/recorder-named receivers, bare ``print`` (use
  ``jax.debug.print`` for traced debugging).
- **CL702** — ``os.environ`` reads (``os.environ.get`` / subscript /
  ``os.getenv``): the value is baked at trace time; flipping the env
  knob later silently does nothing until a shape change recompiles.
- **CL703** — host syncs: ``block_until_ready``, ``.item()``,
  ``np.asarray`` (the CL401 fetch-dressed-as-cast shape),
  ``xfer_put``/``xfer_fetch`` — each forces the async dispatch
  pipeline to drain mid-trace.
- **CL704** — mutation of captured state: stores through ``global``/
  ``nonlocal``, mutator calls / subscript stores on names captured
  from an enclosing scope, ``self.*`` stores in traced methods.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from tools.crdtlint.astutil import MUTATOR_METHODS as _MUTATORS
from tools.crdtlint.astutil import call_name, dotted
from tools.crdtlint.callgraph import FuncInfo, get_callgraph
from tools.crdtlint.core import Checker, Finding, LintContext, Module

# call tails whose function-valued arguments are traced
_TRACING_CALLS: Dict[str, Tuple[int, ...]] = {
    "jit": (0,),
    "shard_map": (0,),
    "pallas_call": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "scan": (0,),
    "cond": (1, 2),
    "switch": (1, 2, 3, 4),
    "vmap": (0,),
    "grad": (0,),
    "checkpoint": (0,),
}

_TRACER_METHODS = {"count", "gauge", "observe", "span", "record"}
_SYNC_TAILS = {"block_until_ready", "xfer_put", "xfer_fetch",
               "device_get"}


def _is_jit_decorated(fn) -> bool:
    for dec in fn.decorator_list:
        d = dec
        if isinstance(d, ast.Call):
            name = call_name(d) or ""
            if name.rsplit(".", 1)[-1] in ("jit", "shard_map"):
                return True
            # @partial(jax.jit, ...) AND @partial(shard_map, ...) —
            # the latter is the repo's dominant traced-step shape
            # (every gossip/delta step factory body)
            if name.rsplit(".", 1)[-1] == "partial" and any(
                (dotted(a) or "").rsplit(".", 1)[-1]
                in ("jit", "shard_map")
                for a in d.args
            ):
                return True
        else:
            nm = dotted(d) or ""
            if nm.rsplit(".", 1)[-1] in ("jit", "shard_map"):
                return True
    return False


class TracePurityChecker(Checker):
    name = "trace-purity"
    codes = {
        "CL701": "Python tracer/recorder/print side effect inside a "
                 "jit/shard_map/Pallas-traced body (fires once at "
                 "trace time, not per dispatch)",
        "CL702": "os.environ read inside a traced body (value baked "
                 "at trace time — stale-recompile hazard)",
        "CL703": "host sync (block_until_ready / .item() / "
                 "np.asarray / xfer seam) inside a traced body",
        "CL704": "mutation of captured state (global/nonlocal/"
                 "closure/self) inside a traced body",
    }
    explain = {
        "CL701": (
            "A traced function's Python body runs ONCE per compile; "
            "a tracer.count/span/print inside it records a single "
            "event no matter how many dispatches follow, so the "
            "metric silently under-reports.\n"
            "Fix: move the emission to the host-side dispatcher "
            "(the converge_async/converge_fetch seam), or use "
            "jax.debug.print/jax.debug.callback for genuinely "
            "traced-side debugging."
        ),
        "CL702": (
            "os.environ read at trace time freezes the value into "
            "the compiled program: flipping the knob later changes "
            "nothing until an unrelated shape change recompiles — "
            "the worst kind of heisen-config.\n"
            "Fix: read the env var at module import or call-site "
            "level and pass it in as a static argument."
        ),
        "CL703": (
            "block_until_ready/.item()/np.asarray inside a traced "
            "body forces a host round-trip mid-trace (or fails "
            "under jit); either way the async dispatch pipeline "
            "drains and the overlap the streaming executor builds "
            "is gone.\n"
            "Fix: keep syncs at the fetch seam (xfer_fetch / "
            "converge_fetch); traced code returns arrays, the host "
            "decides when to wait."
        ),
        "CL704": (
            "Mutating captured state (a global, a closure list, "
            "self.*) inside a traced body happens once at trace "
            "time; every later dispatch replays the compiled "
            "program and the mutation never recurs — state drifts "
            "apart from what the code reads as.\n"
            "Fix: thread state through the function as explicit "
            "inputs/outputs (the functional jax discipline), or "
            "hoist the mutation to the host wrapper."
        ),
    }

    def prepare(self, ctx: LintContext) -> None:
        cg = get_callgraph(ctx)
        roots: Set[str] = set()
        lambdas: List[Tuple[Module, ast.Lambda]] = []
        by_node: Dict[int, FuncInfo] = {
            id(f.node): f for f in cg.funcs.values()
        }
        defs_by_module: Dict[str, Dict[str, FuncInfo]] = {}
        for f in cg.funcs.values():
            defs_by_module.setdefault(f.module, {})[f.name] = f
        for mod in ctx.modules:
            if mod.tree is None:
                continue
            local_defs = defs_by_module.get(mod.path, {})
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    if _is_jit_decorated(node):
                        fi = by_node.get(id(node))
                        if fi is not None:
                            roots.add(fi.key)
                elif isinstance(node, ast.Call):
                    tail = (call_name(node) or "").rsplit(".", 1)[-1]
                    if not tail and isinstance(node.func, ast.Attribute):
                        tail = node.func.attr
                    argnums = _TRACING_CALLS.get(tail)
                    if argnums is None:
                        continue
                    for i in argnums:
                        if i >= len(node.args):
                            continue
                        a = node.args[i]
                        if isinstance(a, ast.Lambda):
                            lambdas.append((mod, a))
                            continue
                        d = dotted(a)
                        if not d:
                            continue
                        fi = local_defs.get(d.rsplit(".", 1)[-1])
                        if fi is not None:
                            roots.add(fi.key)
        traced = set(roots)
        work = list(roots)
        while work:
            k = work.pop()
            for cs in cg.callees(k, strong_only=True):
                if cs.callee not in traced:
                    traced.add(cs.callee)
                    work.append(cs.callee)
        ctx.shared["traced_funcs"] = traced
        ctx.shared["traced_lambdas"] = lambdas

    def check_module(self, mod: Module,
                     ctx: LintContext) -> Iterable[Finding]:
        if mod.tree is None:
            return ()
        cg = get_callgraph(ctx)
        traced: Set[str] = ctx.shared.get("traced_funcs", set())
        findings: List[Finding] = []
        for fi in cg.funcs.values():
            if fi.module != mod.path or fi.key not in traced:
                continue
            self._scan(fi.node, fi.qual, mod, findings,
                       is_method=fi.cls is not None)
        for lmod, lam in ctx.shared.get("traced_lambdas", ()):
            if lmod.path == mod.path:
                self._scan(lam, "<lambda>", mod, findings,
                           is_method=False)
        return findings

    def _scan(self, fn, qual: str, mod: Module,
              findings: List[Finding], *, is_method: bool) -> None:
        local: Set[str] = set()
        args = getattr(fn, "args", None)
        if args is not None:
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                local.add(a.arg)
            if args.vararg:
                local.add(args.vararg.arg)
            if args.kwarg:
                local.add(args.kwarg.arg)
        declared: Set[str] = set()
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for node in _own_walk(body):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                declared.update(node.names)
            elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store
            ):
                local.add(node.id)
            elif isinstance(node, ast.For):
                for t in ast.walk(node.target):
                    if isinstance(t, ast.Name):
                        local.add(t.id)
        for node in _own_walk(body):
            self._scan_node(node, qual, mod, findings, local,
                            declared, is_method)

    def _scan_node(self, node, qual, mod, findings, local, declared,
                   is_method) -> None:
        if isinstance(node, ast.Call):
            name = call_name(node) or ""
            tail = name.rsplit(".", 1)[-1]
            attr = (node.func.attr
                    if isinstance(node.func, ast.Attribute) else "")
            # CL701
            recv = name.rsplit(".", 1)[0].lower() if "." in name else ""
            tracerish = ("tracer" in recv or "recorder" in recv
                         or _is_get_tracer_recv(node.func))
            # bare get_tracer()/get_recorder() is NOT reported on its
            # own: real usage always chains a method call, and that
            # method call is the one finding (double-reporting the
            # receiver too made every site two findings)
            if (name == "print"
                    or ((attr in _TRACER_METHODS) and tracerish)):
                findings.append(Finding(
                    mod.path, node.lineno, "CL701",
                    f"`{name or attr}` inside the traced body of "
                    f"`{qual}` — the side effect fires once at trace "
                    f"time, not per dispatch",
                    symbol=f"{qual}:{tail or attr}",
                ))
            # CL702
            if name in ("os.getenv", "os.environ.get", "environ.get"):
                findings.append(Finding(
                    mod.path, node.lineno, "CL702",
                    f"`{name}` inside the traced body of `{qual}` — "
                    f"the value is baked at trace time "
                    f"(stale-recompile hazard); pass it in as a "
                    f"static argument",
                    symbol=f"{qual}:{name}",
                ))
            # CL703
            sync = None
            if tail in _SYNC_TAILS or attr in _SYNC_TAILS:
                sync = tail or attr
            elif attr == "item" and not node.args:
                sync = "item"
            elif tail == "asarray" and not name.startswith("jnp."):
                sync = name
            if sync:
                findings.append(Finding(
                    mod.path, node.lineno, "CL703",
                    f"host sync `{sync}` inside the traced body of "
                    f"`{qual}` — the dispatch pipeline drains "
                    f"mid-trace; sync at the fetch seam instead",
                    symbol=f"{qual}:{sync}",
                ))
            # CL704: mutator call on captured state
            if attr in _MUTATORS and isinstance(
                node.func, ast.Attribute
            ):
                base = dotted(node.func.value)
                if base and self._captured(base, local):
                    findings.append(Finding(
                        mod.path, node.lineno, "CL704",
                        f"`{base}.{attr}()` mutates captured state "
                        f"inside the traced body of `{qual}` — the "
                        f"mutation happens once at trace time",
                        symbol=f"{qual}:{base}.{attr}",
                    ))
        elif isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Load
        ):
            base = dotted(node.value)
            if base == "os.environ":
                findings.append(Finding(
                    mod.path, node.lineno, "CL702",
                    f"`os.environ[...]` read inside the traced body "
                    f"of `{qual}` — baked at trace time; pass it in "
                    f"as a static argument",
                    symbol=f"{qual}:os.environ",
                ))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                # global/nonlocal rebind
                if isinstance(t, ast.Name) and t.id in declared:
                    findings.append(Finding(
                        mod.path, node.lineno, "CL704",
                        f"`{t.id}` (declared global/nonlocal) "
                        f"assigned inside the traced body of "
                        f"`{qual}` — the store happens once at "
                        f"trace time",
                        symbol=f"{qual}:{t.id}",
                    ))
                    continue
                # subscript store on captured container / self store
                base = None
                if isinstance(t, ast.Subscript):
                    base = dotted(t.value)
                elif isinstance(t, ast.Attribute):
                    base = dotted(t)
                if base and self._captured(base, local):
                    findings.append(Finding(
                        mod.path, node.lineno, "CL704",
                        f"store through `{base}` mutates captured "
                        f"state inside the traced body of `{qual}`",
                        symbol=f"{qual}:{base}",
                    ))

    @staticmethod
    def _captured(base: str, local: Set[str]) -> bool:
        head = base.split(".", 1)[0]
        if head == "self":
            return True  # self.* stores/mutations in traced methods
        return head not in local


def _is_get_tracer_recv(func) -> bool:
    """``get_tracer().count(...)`` — receiver is a get_tracer/
    get_recorder call."""
    if isinstance(func, ast.Attribute) and isinstance(
        func.value, ast.Call
    ):
        n = call_name(func.value) or ""
        return n.rsplit(".", 1)[-1] in ("get_tracer", "get_recorder")
    return False


def _own_walk(stmts) -> Iterable[ast.AST]:
    """Walk statements without descending into nested def/class
    bodies (nested defs are separate call-graph nodes; if traced,
    they are scanned as their own roots)."""
    work = list(stmts)
    while work:
        node = work.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        work.extend(ast.iter_child_nodes(node))
