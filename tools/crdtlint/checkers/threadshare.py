"""CL601: unlocked module-level mutable state in threaded modules.

``models/streaming.py`` decodes on a thread pool, and that pool calls
into the process-global tracer, the transfer seam, and the device
fault hook. The round-8 tracer rewrite exists because a module-level
dict was mutated bare from those threads; this checker keeps the
class of bug from coming back.

Scope: the modules the streaming thread pool touches
(``models/streaming.py``, ``obs/tracer.py``, ``obs/recorder.py``,
``ops/device.py``). Flagged:

- assignment to a module-level name through ``global NAME`` inside a
  function, outside any ``with <…lock…>:`` block;
- in-place mutation (``.append``/``.update``/``.pop``/``.add``/
  ``[...] =`` / ``+=``) of a module-level name bound to a mutable
  literal (dict/list/set/deque), outside a lock block.

A ``with`` context naming a lock-like identifier counts as holding a
lock: any dotted component whose ``_``/camelCase segments include
``lock``/``rlock``/``mutex``/``semaphore`` (``self._lock``,
``_TRACER_LOCK``, ``threading.Lock()``) — but NOT incidental
substrings like ``self._blocker``, which must not silence the
checker. Atomic
publish-only rebinds (``set_tracer``-style) are *findings* —
intentionally-unlocked ones belong in the baseline with that
justification, where a reviewer can see the reasoning.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set

from tools.crdtlint.astutil import MUTATOR_METHODS as _MUTATORS
from tools.crdtlint.astutil import dotted
from tools.crdtlint.core import Checker, Finding, LintContext, Module

THREADED_SUFFIXES = (
    "models/streaming.py", "obs/tracer.py", "obs/recorder.py",
    "ops/device.py",
)
_MUTABLE_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                  "OrderedDict", "Counter"}


def _module_mutables(tree: ast.Module) -> Set[str]:
    """Module-level names bound to mutable containers."""
    out: Set[str] = set()
    for node in tree.body:
        # `X = set()` and the annotated `X: set = set()` bind the same
        # shared state — a type annotation must not silence CL601
        if isinstance(node, ast.Assign):
            targets, val = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, val = [node.target], node.value
        else:
            continue
        mutable = isinstance(
            val, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                  ast.DictComp, ast.SetComp)
        ) or (
            isinstance(val, ast.Call)
            and (dotted(val.func) or "").rsplit(".", 1)[-1]
            in _MUTABLE_CTORS
        )
        if mutable:
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


_LOCK_SEGMENTS = {"lock", "rlock", "mutex", "semaphore"}


def _is_lock_expr(expr: ast.AST) -> bool:
    """Does a with-item's context expression name a lock? Matched on
    whole ``_``/camelCase segments of every identifier in the
    expression — ``self._lock`` / ``_CACHE_LOCK`` / ``threading.Lock()``
    hold, ``self._blocker`` / ``_unblocked_region()`` do NOT (the raw
    substring test let ``b·lock`` silence the checker)."""
    idents: List[str] = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            idents.append(node.id)
        elif isinstance(node, ast.Attribute):
            idents.append(node.attr)
    for ident in idents:
        camel_split = re.sub(r"(?<=[a-z0-9])(?=[A-Z])", "_", ident)
        segs = [s for s in re.split(r"[^A-Za-z0-9]+|_", camel_split) if s]
        if any(s.lower() in _LOCK_SEGMENTS for s in segs):
            return True
    return False


def _lock_depth_map(fn: ast.FunctionDef) -> Set[int]:
    """ids of statements lexically inside a ``with <lock>:`` block."""
    inside: Set[int] = set()

    def visit(node, locked):
        if isinstance(node, ast.With):
            holds = any(
                _is_lock_expr(item.context_expr) for item in node.items
            )
            locked = locked or holds
        for child in ast.iter_child_nodes(node):
            if locked:
                inside.add(id(child))
            visit(child, locked)

    visit(fn, False)
    return inside


class ThreadSharedStateChecker(Checker):
    name = "thread-shared"
    codes = {
        "CL601": "module-level mutable state mutated without a lock "
                 "in a thread-pool-reachable module",
    }
    explain = {
        "CL601": (
            "The streaming decode pool reaches this module; a bare "
            "mutation of module-level state from those threads is "
            "the round-8 tracer race class — lost updates that "
            "surface as missing metrics or a wedged memo cache.\n"
            "Fix: take the module's lock around the read-modify-"
            "write (the _CACHE_LOCK pattern in ops/device.py); "
            "publish-only atomic rebinds are baselined with that "
            "justification so the reasoning stays reviewable."
        ),
    }

    def check_module(self, mod: Module,
                     ctx: LintContext) -> Iterable[Finding]:
        if not any(mod.path.endswith(s) for s in THREADED_SUFFIXES):
            return ()
        findings: List[Finding] = []
        mutables = _module_mutables(mod.tree)

        for fn in ast.walk(mod.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            globals_declared: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    globals_declared.update(node.names)
            if not globals_declared and not mutables:
                continue
            locked_ids = _lock_depth_map(fn)

            for node in ast.walk(fn):
                if id(node) in locked_ids:
                    continue
                # global rebind
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        nm = None
                        if isinstance(t, ast.Name):
                            nm = t.id
                        elif isinstance(t, ast.Subscript) and isinstance(
                            t.value, ast.Name
                        ):
                            nm = t.value.id
                            if nm not in mutables:
                                nm = None
                        if nm is None:
                            continue
                        if (nm in globals_declared
                                or (isinstance(t, ast.Subscript)
                                    and nm in mutables)):
                            findings.append(Finding(
                                mod.path, node.lineno, "CL601",
                                f"module global `{nm}` mutated in "
                                f"`{fn.name}` without holding a lock "
                                f"— this module is reached from the "
                                f"streaming thread pool (round-8 "
                                f"tracer race class)",
                                symbol=f"{fn.name}:{nm}",
                            ))
                # in-place mutator call on a module-level container
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ):
                    if node.func.attr not in _MUTATORS:
                        continue
                    base = node.func.value
                    if isinstance(base, ast.Name) and base.id in mutables:
                        findings.append(Finding(
                            mod.path, node.lineno, "CL601",
                            f"module-level container `{base.id}` "
                            f"mutated via `.{node.func.attr}()` in "
                            f"`{fn.name}` without a lock",
                            symbol=f"{fn.name}:{base.id}.{node.func.attr}",
                        ))
        return findings
