"""Parse the observability registries the repo pins in prose + tests.

Two sources of documented truth:

- ``README.md`` — the "Observability" / "Failure semantics" /
  "Overload & failure policy" registry tables name every public
  metric (span/counter/gauge) and flight-recorder event kind in
  backticks. We extract every backticked token that *looks like* a
  metric (lowercase dotted name whose first segment is a known
  namespace), expanding the ``fault.drop/dup/delay`` slash shorthand
  and stripping ``{label=...}`` suffixes.
- ``tests/test_bench_smoke.py`` — ``HOT_PATH_SPANS`` plus the literal
  counter names its asserts pin.

The registry conformance checker diffs these against the names the
package actually emits, both ways.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Optional, Set

# first dotted segment of every registered metric/event namespace;
# extraction is restricted to these so file paths (`tools/...`),
# module paths (`crdt_tpu.obs`) and API references in the same prose
# never read as registry entries
NAMESPACES = frozenset({
    "xfer", "guard", "persist", "engine", "device", "replica",
    "router", "sentinel", "fleet", "gossip", "update", "sync",
    "probe", "ae", "beacon", "dial", "relay", "envelope", "fault",
    "overload", "lint", "converge", "shard", "tenant",
    # round 18 (observability v2): the SLO ledger and the
    # tick-timeline profiler
    "slo", "timeline",
    # round 19 (distributed tracing): the wire trace-context /
    # per-hop lag plane and the live fleet collector
    "propagation", "collector",
    # round 21 (crash-proof recovery): the snapshot store's
    # write/load/fallback plane
    "snap",
    # round 22 (control plane): the SLO-driven controller's
    # decision/cooldown/ledger/setpoint registry
    "control",
    # round 24 (fleet serving): the live-migration recovery ladder
    # (the `fleet.*` ownership counters were already listed above)
    "migration",
})

# backticked dotted names that share a namespace but are NOT metrics
# or event kinds (attribute paths, artifact keys, config knobs)
NON_METRICS = frozenset({
    "replica.sentinel.events",   # Replica attribute, not a counter
    "router.stats",              # router's tracer-free stats dict
    "overload.peak_inbox_bytes",  # BENCH_OUT section keys, gated by
    "overload.shed_count",        # metrics_diff directly
    "fleet.leases",               # snapshot-store blob key (round 24)
    "overload.shed_bytes",
    "lint.findings",              # bench artifact keys (this tool's
    "lint.open_by_family",        # own gated metrics and the round-16
    "lint.callgraph",             # call-graph stats), not tracer names
    "lint.callgraph.collisions",
    "shard.mat",                  # xfer_put call-site labels, not
    "shard.wire",                 # tracer names (they surface only as
    "shard.out",                  # {path=...} label values on the
    "shard.sv",                   # xfer byte counters)
    "timeline.to_perfetto",       # API reference in the round-19
    #                               tracing section, not a metric
})

# span names without a dot, pinned only by HOT_PATH_SPANS
_TOKEN_RE = re.compile(
    r"^[a-z][a-z0-9_]*(?:\.[a-z0-9_/]+)+(?:\{[^}]*\})?$"
)
_BACKTICK_RE = re.compile(r"`([^`\n]+)`")


@dataclass
class Registry:
    """Documented names. ``sources`` maps name -> (path, line) of its
    registry mention, so dead-entry findings point at the prose."""

    metrics: Set[str] = field(default_factory=set)
    events: Set[str] = field(default_factory=set)
    sources: Dict[str, tuple] = field(default_factory=dict)

    @property
    def all_names(self) -> Set[str]:
        return self.metrics | self.events

    def add(self, name: str, kind: str, path: str, line: int) -> None:
        (self.metrics if kind == "metric" else self.events).add(name)
        self.sources.setdefault(name, (path, line))


# event-kind namespaces: first segments that name flight-recorder
# event kinds rather than tracer metrics (``fault.drop`` vs the
# ``fault.disk`` recorder kind share one; the conformance diff treats
# metrics+events as one documented pool, so the split is cosmetic)
_EVENT_FIRST = frozenset({
    "update", "sync", "probe", "ae", "beacon", "dial", "relay",
    "envelope",
})


def _norm(token: str) -> str:
    return re.sub(r"\{[^}]*\}$", "", token.strip())


# dotless flight-recorder event kinds: backticked single words are
# far too common in prose to extract generically, so the known ones
# are named here explicitly
DOTLESS_EVENTS = frozenset({"divergence"})


def parse_readme(path: str, reg: Registry) -> None:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    for lineno, line in enumerate(text.splitlines(), start=1):
        for raw in _BACKTICK_RE.findall(line):
            tok = _norm(raw)
            if tok in DOTLESS_EVENTS:
                reg.add(tok, "event", path, lineno)
                continue
            if not _TOKEN_RE.match(tok):
                continue
            first = tok.split(".", 1)[0]
            if first not in NAMESPACES:
                continue
            # expand  fault.drop/dup/delay/corrupt/partition/fork
            head, _, tail = tok.rpartition(".")
            names = (
                [f"{head}.{p}" for p in tail.split("/")]
                if "/" in tail else [tok]
            )
            for name in names:
                if name in NON_METRICS or "/" in name:
                    continue
                kind = (
                    "event" if first in _EVENT_FIRST else "metric"
                )
                reg.add(name, kind, path, lineno)


def parse_smoke_test(path: str, reg: Registry) -> None:
    """Every string literal in the smoke test that names a registered
    span/counter (HOT_PATH_SPANS entries, counter asserts)."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            tok = _norm(node.value)
            if ("." in tok
                    and _TOKEN_RE.match(tok)
                    and tok.split(".", 1)[0] in NAMESPACES
                    and tok not in NON_METRICS):
                reg.add(tok, "metric", path, node.lineno)
    # dotless hot-path span names (decode, pack, gather…) come only
    # from the HOT_PATH_SPANS tuple assignment, taken verbatim
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name)
                        and t.id == "HOT_PATH_SPANS"
                        for t in node.targets)
                and isinstance(node.value, ast.Tuple)):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    reg.add(elt.value, "metric", path, elt.lineno)


def load_registry(readme_path: Optional[str],
                  smoke_test_path: Optional[str]) -> Registry:
    reg = Registry()
    if readme_path:
        parse_readme(readme_path, reg)
    if smoke_test_path:
        parse_smoke_test(smoke_test_path, reg)
    return reg
