"""CLI: ``python -m tools.crdtlint [paths...]``.

Exit codes: 0 clean (baselined/suppressed findings allowed), 1
unsuppressed findings, 2 usage/baseline errors.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="crdtlint",
        description="AST-based invariant checker for crdt_tpu "
                    "(donation safety, registry conformance, codec "
                    "exception discipline, transfer-seam accounting, "
                    "determinism, thread-shared state, trace purity, "
                    "lock discipline, async-handle discipline)",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: crdt_tpu/)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: "
                         "tools/crdtlint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show everything)")
    ap.add_argument("--write-baseline", metavar="PATH",
                    help="write current unsuppressed findings as a "
                         "baseline skeleton (justifications TODO) "
                         "and exit")
    ap.add_argument("--list-checkers", action="store_true")
    ap.add_argument("--explain", metavar="CODE",
                    help="print a code's rationale and fix recipe "
                         "(e.g. --explain CL803) and exit")
    ap.add_argument("--statistics", action="store_true",
                    help="per-code counts incl. suppressed/baselined, "
                         "plus per-checker wall time")
    ap.add_argument("--sarif", metavar="PATH",
                    help="also write findings as SARIF 2.1.0 (one "
                         "rule per code, --explain text as help; "
                         "baselined/suppressed carried as SARIF "
                         "suppressions). Exit-code semantics are "
                         "unchanged")
    ap.add_argument("--prune-stale", action="store_true",
                    help="rewrite the baseline in place dropping "
                         "entries whose fingerprint matches no live "
                         "finding (surviving justifications kept "
                         "verbatim), then report as usual")
    args = ap.parse_args(argv)

    # repo root = parent of tools/ — resolves default paths whether
    # invoked from the root or elsewhere
    from tools.crdtlint.core import (
        BaselineError, LintConfig, load_baseline, load_modules,
        run_lint, write_baseline,
    )
    from tools.crdtlint.checkers import (
        ALL_CHECKERS, ALL_CODES, ALL_EXPLAIN,
    )

    if args.explain:
        code = args.explain.upper()
        if code not in ALL_CODES:
            print(f"crdtlint: unknown code {code!r} (known: "
                  f"{', '.join(sorted(ALL_CODES))})", file=sys.stderr)
            return 2
        print(f"{code}  {ALL_CODES[code]}")
        print()
        print(ALL_EXPLAIN[code])
        return 0

    if args.list_checkers:
        for cls in ALL_CHECKERS:
            print(f"{cls.name}:")
            for code, desc in cls.codes.items():
                print(f"  {code}  {desc}")
        return 0

    config = LintConfig(baseline_path=args.baseline)
    paths = args.paths or [os.path.join(config.repo_root, "crdt_tpu")]
    t0 = time.perf_counter()
    modules = load_modules(paths, config.repo_root)
    if not modules:
        print("crdtlint: no python files found", file=sys.stderr)
        return 2
    try:
        result = run_lint(
            modules, config=config,
            use_baseline=not args.no_baseline,
        )
    except BaselineError as e:
        print(f"crdtlint: {e}", file=sys.stderr)
        return 2
    dt = time.perf_counter() - t0

    if args.write_baseline:
        # merge, never clobber: existing justified entries that still
        # match a live finding are carried over verbatim; only OPEN
        # findings get TODO skeletons, and stale entries are pruned.
        # --no-baseline only changes reporting (every live finding
        # shows as open) — the committed ledger stays the merge
        # source, so regenerating with it can't wipe justifications.
        live = {f.fingerprint for f in result.findings}
        live.update(f.fingerprint for f in result.baselined)
        try:
            existing = load_baseline(config.baseline_path)
        except BaselineError as e:
            print(f"crdtlint: {e}", file=sys.stderr)
            return 2
        preserved = [e for fp, e in existing.items() if fp in live]
        kept = {e["fingerprint"] for e in preserved}
        fresh = [f for f in result.findings if f.fingerprint not in kept]
        write_baseline(args.write_baseline, fresh, preserved)
        print(
            f"wrote {len(fresh) + len(preserved)} entr(ies) "
            f"to {args.write_baseline} — {len(fresh)} new "
            f"skeleton(s) need justifications, {len(preserved)} "
            f"preserved"
        )
        return 0

    if args.sarif:
        from tools.crdtlint.sarif import write_sarif

        try:
            ledger = load_baseline(config.baseline_path)
        except BaselineError:
            ledger = {}
        write_sarif(args.sarif, result, ALL_CODES, ALL_EXPLAIN,
                    ledger)
        print(f"crdtlint: wrote SARIF to {args.sarif}",
              file=sys.stderr)

    if args.prune_stale and result.stale_baseline:
        # mechanical ledger hygiene: drop entries no live finding
        # matches, keep every surviving justification verbatim
        try:
            existing = load_baseline(config.baseline_path)
        except BaselineError as e:
            print(f"crdtlint: {e}", file=sys.stderr)
            return 2
        stale = set(result.stale_baseline)
        kept = [e for fp, e in sorted(existing.items())
                if fp not in stale]
        write_baseline(config.baseline_path, [], kept)
        print(
            f"crdtlint: pruned {len(stale)} stale baseline "
            f"entr(ies), {len(kept)} kept",
            file=sys.stderr,
        )
        result.stale_baseline = []

    for f in result.findings:
        print(f.format())
    for fp in result.stale_baseline:
        print(f"crdtlint: stale baseline entry (fixed?): {fp}",
              file=sys.stderr)
    if args.statistics:
        from collections import Counter

        by_code = Counter(f.code for f in result.findings)
        base_code = Counter(f.code for f in result.baselined)
        supp_code = Counter(f.code for f in result.suppressed)
        for code in sorted(ALL_CODES):
            n, b, s = by_code[code], base_code[code], supp_code[code]
            if n or b or s:
                print(f"{code}: {n} open, {b} baselined, "
                      f"{s} suppressed")
        # per-checker wall time: the <10 s tier-1 budget, itemized
        timings = result.stats.get("checker_seconds", {})
        for name in sorted(timings, key=timings.get, reverse=True):
            print(f"time {name}: {timings[name]:.3f}s")
    summary = (
        f"crdtlint: {len(modules)} files, "
        f"{len(result.findings)} finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{len(result.suppressed)} suppressed "
        f"({dt:.2f}s)"
    )
    print(summary, file=sys.stderr)
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
