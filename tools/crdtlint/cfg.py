"""Lite per-function control-flow graph for path-sensitive checkers.

Round 16: the CL9xx async-handle and CL902 paired-protocol checkers
need "does every path from HERE reach a consuming statement" — a
question the structured walks the donate checker grew (branch merge,
loop back-edge) cannot answer once exception edges matter. This CFG
is deliberately small:

- Nodes are **statements** (``ast.stmt``). Compound statements
  contribute their header as a node (``if``/``while``/``for``/
  ``with``) or no node at all (``try``). Nested function/class
  definitions are leaf statements — their bodies are separate CFGs.
- ``succ_norm[id(stmt)]`` lists normal-flow successors; ``succ_exc``
  lists where control lands if the statement RAISES (the innermost
  enclosing handler entries, the finally block, or the virtual
  :data:`RAISE` exit). Every statement is conservatively assumed able
  to raise.
- Two virtual exits: :data:`EXIT` (normal return / fall-off) and
  :data:`RAISE` (uncaught exception leaves the function).
- ``finally`` is built ONCE with the union of its normal and
  exceptional continuations as follow targets — a small
  over-approximation of paths (standard for lite CFGs) that never
  *loses* an edge, so "all paths hit X" verdicts stay sound for the
  checkers (they may miss a violation, never invent one... the
  conservative direction for a linter).

The walk helpers (:func:`every_path_hits`) treat cycles as
non-terminating paths: a loop that never exits cannot leak past the
function, so it neither satisfies nor violates an "all paths" query.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, Iterable, List, Optional, Sequence

EXIT = "<exit>"    # normal function exit
RAISE = "<raise>"  # uncaught-exception exit


class CFG:
    """succ_norm / succ_exc map ``id(stmt)`` to successor statements
    (or the EXIT/RAISE sentinels); ``entry`` lists the function's
    first statements."""

    def __init__(self, fn: ast.FunctionDef):
        self.fn = fn
        self.succ_norm: Dict[int, List[object]] = {}
        self.succ_exc: Dict[int, List[object]] = {}
        self.stmts: List[ast.stmt] = []
        self.entry: List[object] = self._block(
            fn.body, [EXIT], [RAISE], None, None, [EXIT]
        )

    # -- construction ----------------------------------------------------

    def _block(self, stmts: Sequence[ast.stmt], follow: List[object],
               exc: List[object], brk: Optional[List[object]],
               cont: Optional[List[object]],
               ret: List[object]) -> List[object]:
        """Wire a statement list; returns the block's entry targets.
        ``follow`` is where control goes after the last statement,
        ``exc`` where an exception lands, ``brk``/``cont`` the
        targets of break/continue (None outside loops), ``ret`` the
        target of a return (EXIT, or the enclosing finally)."""
        entry = follow
        # wire back-to-front so each statement knows its successor
        for st in reversed(stmts):
            entry = self._stmt(st, entry, exc, brk, cont, ret)
        return entry

    def _stmt(self, st: ast.stmt, follow: List[object],
              exc: List[object], brk, cont,
              ret: List[object]) -> List[object]:
        if isinstance(st, ast.If):
            body = self._block(st.body, follow, exc, brk, cont, ret)
            orelse = (self._block(st.orelse, follow, exc, brk, cont,
                                  ret)
                      if st.orelse else follow)
            self._add(st, list(body) + list(orelse), exc)
            return [st]
        if isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
            orelse = (self._block(st.orelse, follow, exc, brk, cont,
                                  ret)
                      if st.orelse else follow)
            # loop header: enter the body or skip past (test false /
            # iterator exhausted); body's last statement loops back
            body = self._block(st.body, [st], exc, follow, [st], ret)
            self._add(st, list(body) + list(orelse), exc)
            return [st]
        if isinstance(st, (ast.With, ast.AsyncWith)):
            body = self._block(st.body, follow, exc, brk, cont, ret)
            self._add(st, list(body), exc)
            return [st]
        if isinstance(st, ast.Try):
            final_entry: Optional[List[object]] = None
            inner_brk, inner_cont, inner_ret = brk, cont, ret
            if st.finalbody:
                # one finally block; its continuations are the union
                # of every way control can LEAVE the protected region
                # (see module doc) — but only the continuation kinds
                # the region actually uses, so a try with no return
                # inside never grows a phantom finally->EXIT edge
                used = _continuations_used(
                    st.body
                    + [s for h in st.handlers for s in h.body]
                    + st.orelse
                )
                final_follow = list(follow) + list(exc)
                if "return" in used:
                    final_follow += list(ret)
                if "break" in used and brk is not None:
                    final_follow += list(brk)
                if "continue" in used and cont is not None:
                    final_follow += list(cont)
                final_entry = self._block(
                    st.finalbody, final_follow, exc, brk, cont, ret
                )
                # return/break/continue inside the protected region
                # must RUN the finally before leaving it — wiring
                # them straight out is how a close-in-finally gets
                # falsely flagged as skipped (CL902)
                inner_ret = final_entry
                if brk is not None:
                    inner_brk = final_entry
                if cont is not None:
                    inner_cont = final_entry
            after = final_entry if final_entry is not None else follow
            # a raise INSIDE a handler (or orelse) propagates outward
            # but must run the finally first — routing it straight to
            # the outer exc would let CL902 claim a close-in-finally
            # was skipped on the handler's exception edge
            inner_exc = (final_entry if final_entry is not None
                         else exc)
            handler_entries: List[object] = []
            for h in st.handlers:
                handler_entries.extend(self._block(
                    h.body, after, inner_exc, inner_brk, inner_cont,
                    inner_ret,
                ))
            body_exc = handler_entries if st.handlers else inner_exc
            orelse = (self._block(st.orelse, after, inner_exc,
                                  inner_brk, inner_cont, inner_ret)
                      if st.orelse else after)
            return self._block(st.body, orelse, body_exc, inner_brk,
                               inner_cont, inner_ret)
        if isinstance(st, (ast.Return,)):
            self._add(st, list(ret), exc)
            return [st]
        if isinstance(st, ast.Raise):
            # deliberate raise: successors ARE the exception targets
            self._add(st, [], exc)
            return [st]
        if isinstance(st, ast.Break) and brk is not None:
            self._add(st, list(brk), exc)
            return [st]
        if isinstance(st, ast.Continue) and cont is not None:
            self._add(st, list(cont), exc)
            return [st]
        # leaf statements (expressions, assignments, nested defs, ...)
        self._add(st, list(follow), exc)
        return [st]

    def _add(self, st: ast.stmt, norm: List[object],
             exc: List[object]) -> None:
        self.stmts.append(st)
        self.succ_norm[id(st)] = norm
        self.succ_exc[id(st)] = list(exc)

    # -- queries ---------------------------------------------------------

    def successors(self, st: ast.stmt,
                   *, with_exc: bool) -> Iterable[object]:
        out = list(self.succ_norm.get(id(st), ()))
        if with_exc:
            out.extend(self.succ_exc.get(id(st), ()))
        return out


def _continuations_used(stmts: Sequence[ast.stmt]) -> set:
    """Which of return/break/continue appear in a protected region
    (nested function/class bodies excluded — their control flow
    never reaches the enclosing finally)."""
    kinds: set = set()
    work = list(stmts)
    while work:
        n = work.pop()
        if isinstance(n, ast.Return):
            kinds.add("return")
        elif isinstance(n, ast.Break):
            kinds.add("break")
        elif isinstance(n, ast.Continue):
            kinds.add("continue")
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        work.extend(ast.iter_child_nodes(n))
    return kinds


def every_path_hits(
    cfg: CFG,
    start: Sequence[object],
    hits: Callable[[ast.stmt], bool],
    *,
    with_exc: bool = False,
    stop: Optional[Callable[[ast.stmt], bool]] = None,
) -> Optional[object]:
    """Walk every path from ``start``; return None when each one
    passes a ``hits`` statement before reaching EXIT (RAISE too when
    ``with_exc``), else the first offending exit sentinel. ``stop``
    prunes a path as *failed immediately* (e.g. a rebind that drops a
    handle) — the caller reports it at the stop site instead."""
    seen = set()
    work = list(start)
    while work:
        node = work.pop()
        if node == EXIT:
            return EXIT
        if node == RAISE:
            if with_exc:
                return RAISE
            continue
        nid = id(node)
        if nid in seen:
            continue
        seen.add(nid)
        if hits(node):
            continue  # this path is satisfied
        if stop is not None and stop(node):
            continue  # caller reports at the stop site
        work.extend(cfg.successors(node, with_exc=with_exc))
    return None
