"""Project-wide, import-aware call graph (round 16 tentpole core).

One graph is built per lint run and memoized in ``ctx.shared`` (every
CL7xx/CL8xx/CL9xx checker walks it; building it once keeps the
whole-tree pass inside the round-11 <10 s budget). Nodes are function
definitions — module-level defs, methods (``Class.meth``), and nested
defs (``outer.<locals>.inner``). Edges carry a **confidence**:

- ``strong`` — the callee was resolved the way the donate checker
  resolves donating defs (:func:`tools.crdtlint.astutil.
  make_module_resolver`): same-module def, explicit import, or
  module-attribute spelling matched on the receiver module; plus
  ``self.meth(...)`` within the enclosing class and calls to nested
  defs. Strong edges are what the lock-discipline checker propagates
  lock/blocking closures through — a guessed edge must never lend a
  function someone else's blocking call.
- ``weak`` — attribute calls on unresolvable receivers
  (``ph.timed(...)``, ``get_tracer().span(...)``) matched by METHOD
  NAME across every class in the project, linking to ALL candidates.
  Weak edges over-approximate, which is exactly right for
  thread-REACHABILITY (CL803's thread-shared-class discovery must not
  miss a class because a receiver was a local variable) and exactly
  wrong for closures. Name collisions (several classes defining the
  method) are counted in ``stats()`` so the bench digest shows how
  much of the graph is guessed.

Thread roots: ``threading.Thread(target=f)`` keywords,
``executor.submit(f, ...)`` / ``executor.map(f, ...)`` first
arguments. ``thread_reachable`` is the closure over strong+weak edges
from those roots — the set CL803 calls "reachable from a Thread /
ThreadPoolExecutor target".
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.crdtlint.astutil import dotted, make_module_resolver

STRONG = "strong"
WEAK = "weak"


@dataclass
class FuncInfo:
    module: str                  # repo-relative defining module path
    qual: str                    # "f", "Class.meth", "f.<locals>.g"
    name: str                    # bare name
    cls: Optional[str]           # enclosing class name (methods only)
    node: object                 # ast.FunctionDef / AsyncFunctionDef
    lineno: int = 0

    @property
    def key(self) -> str:
        return f"{self.module}:{self.qual}"


@dataclass
class CallSite:
    caller: str                  # FuncInfo.key
    callee: str                  # FuncInfo.key
    lineno: int
    confidence: str              # STRONG | WEAK


@dataclass
class CallGraph:
    funcs: Dict[str, FuncInfo] = field(default_factory=dict)
    edges: Dict[str, List[CallSite]] = field(default_factory=dict)
    thread_roots: Set[str] = field(default_factory=set)
    thread_reachable: Set[str] = field(default_factory=set)
    collisions: int = 0          # weak edges fanned over >1 candidate

    def callees(self, key: str, *,
                strong_only: bool = False) -> Iterable[CallSite]:
        for cs in self.edges.get(key, ()):
            if strong_only and cs.confidence != STRONG:
                continue
            yield cs

    def stats(self) -> Dict[str, int]:
        n_edges = sum(len(v) for v in self.edges.values())
        n_weak = sum(
            1 for v in self.edges.values()
            for cs in v if cs.confidence == WEAK
        )
        return {
            "functions": len(self.funcs),
            "edges": n_edges,
            "weak_edges": n_weak,
            "collisions": self.collisions,
            "thread_roots": len(self.thread_roots),
            "thread_reachable": len(self.thread_reachable),
        }


def get_callgraph(ctx) -> CallGraph:
    """The per-run memoized graph: first checker to ask builds it,
    the rest share it (ctx.shared rides one LintContext per run)."""
    cg = ctx.shared.get("callgraph")
    if cg is None:
        cg = build_callgraph(ctx.modules)
        ctx.shared["callgraph"] = cg
        ctx.shared["callgraph_stats"] = cg.stats()
    return cg


# ---------------------------------------------------------------------------
# construction
# ---------------------------------------------------------------------------


def _collect_funcs(modules) -> Tuple[
    Dict[str, FuncInfo],            # key -> info
    Dict[str, Dict[str, FuncInfo]],  # module -> {bare name: top-level def}
    Dict[str, List[FuncInfo]],      # method name -> defs across classes
]:
    funcs: Dict[str, FuncInfo] = {}
    module_defs: Dict[str, Dict[str, FuncInfo]] = {}
    methods: Dict[str, List[FuncInfo]] = {}

    def visit(node, mod, cls, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                fi = FuncInfo(mod.path, qual, child.name, cls, child,
                              child.lineno)
                funcs[fi.key] = fi
                if not prefix:
                    module_defs[mod.path][child.name] = fi
                # direct methods of `cls` only (qual ends with
                # Class.name — covers nested classes too, whose qual
                # keeps the enclosing prefix so a nested `class A`
                # can never overwrite a top-level one in `funcs`)
                if cls is not None and qual.endswith(
                    f"{cls}.{child.name}"
                ):
                    methods.setdefault(child.name, []).append(fi)
                visit(child, mod, cls, f"{qual}.<locals>.")
            elif isinstance(child, ast.ClassDef):
                visit(child, mod, child.name,
                      f"{prefix}{child.name}.")
            else:
                visit(child, mod, cls, prefix)

    for mod in modules:
        if mod.tree is None:
            continue
        module_defs[mod.path] = {}
        visit(mod.tree, mod, None, "")
    return funcs, module_defs, methods


class _ByModule:
    """Adapter giving module-level defs the ``.module`` attribute
    shape :func:`make_module_resolver` candidates need — it already
    have it, so this is just the candidate index."""

    def __init__(self, module_defs: Dict[str, Dict[str, FuncInfo]]):
        self.by_name: Dict[str, List[FuncInfo]] = {}
        for defs in module_defs.values():
            for fi in defs.values():
                self.by_name.setdefault(fi.name, []).append(fi)


def _own_stmts(fn_node) -> Iterable[ast.AST]:
    """Walk a function's body WITHOUT descending into nested function
    or class definitions (those are their own call-graph nodes)."""
    work = list(ast.iter_child_nodes(fn_node))
    while work:
        node = work.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        work.extend(ast.iter_child_nodes(node))


def build_callgraph(modules) -> CallGraph:
    from tools.crdtlint.astutil import import_map

    cg = CallGraph()
    funcs, module_defs, methods = _collect_funcs(modules)
    cg.funcs = funcs
    cands = _ByModule(module_defs).by_name
    # one import map per module, shared by both resolver passes
    # (import_map walks the whole tree — recomputing it per resolver
    # pass was a measurable slice of the <10s budget)
    imaps = {
        m.path: import_map(m.tree)
        for m in modules if m.tree is not None
    }

    # per-module indexes, built once (per-function recomputation over
    # the whole func table is quadratic and blew the <10s budget)
    funcs_by_module: Dict[str, List[FuncInfo]] = {}
    for f in funcs.values():
        funcs_by_module.setdefault(f.module, []).append(f)

    for mod in modules:
        if mod.tree is None:
            continue
        local = set(module_defs.get(mod.path, ()))
        resolve_strong = make_module_resolver(
            mod.path, mod.tree, local, cands, fallback_first=False,
            imap=imaps[mod.path],
        )
        mod_funcs = funcs_by_module.get(mod.path, [])
        by_cls: Dict[Optional[str], Dict[str, FuncInfo]] = {}
        by_parent: Dict[str, Dict[str, FuncInfo]] = {}
        for f in mod_funcs:
            by_cls.setdefault(f.cls, {})[f.name] = f
            if ".<locals>." in f.qual:
                parent = f.qual.rsplit(".<locals>.", 1)[0]
                by_parent.setdefault(parent, {})[f.name] = f
        for fi in mod_funcs:
            self_methods = (by_cls.get(fi.cls, {})
                            if fi.cls is not None else {})
            nested = by_parent.get(fi.qual, {})
            out: List[CallSite] = []
            for node in _own_stmts(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                targets, conf = _resolve_call(
                    node, name, fi, nested, self_methods,
                    resolve_strong, methods,
                )
                if len(targets) > 1:
                    cg.collisions += 1
                for t in targets:
                    out.append(CallSite(
                        fi.key, t.key, node.lineno, conf
                    ))
            if out:
                cg.edges[fi.key] = out

    _find_thread_roots(cg, modules, module_defs, funcs, methods,
                       imaps)
    cg.thread_reachable = _closure(cg, cg.thread_roots)
    return cg


def _resolve_call(call, name, fi, nested, self_methods,
                  resolve_strong, methods):
    """-> (targets, confidence). Resolution ladder mirrors the donate
    checker's (see module doc)."""
    if name:
        tail = name.rsplit(".", 1)[-1]
        if name == tail and tail in nested:
            return [nested[tail]], STRONG
        if name.startswith("self.") and "." not in name[5:]:
            m = self_methods.get(name[5:])
            if m is not None:
                return [m], STRONG
        hit = resolve_strong(name)
        if hit is not None:
            return [hit], STRONG
    # attribute call on an unresolvable receiver (or a call on a call
    # result): fan out by method name — weak
    if isinstance(call.func, ast.Attribute):
        cands = methods.get(call.func.attr, ())
        if cands:
            return list(cands), WEAK
    return [], WEAK


def _find_thread_roots(cg, modules, module_defs, funcs, methods,
                       imaps):
    """``Thread(target=f)`` / ``pool.submit(f, ...)`` /
    ``pool.map(f, it)`` — resolve ``f`` to its def and mark a root."""
    cands = _ByModule(module_defs).by_name
    for mod in modules:
        if mod.tree is None:
            continue
        local = module_defs.get(mod.path, {})
        resolve_strong = make_module_resolver(
            mod.path, mod.tree, set(local), cands,
            fallback_first=False, imap=imaps[mod.path],
        )
        # nested defs visible from each enclosing function
        nested_all = {
            f.name: f for f in funcs.values() if f.module == mod.path
        }

        def as_func(expr, resolve_strong=resolve_strong,
                    nested_all=nested_all,
                    mod_path=mod.path) -> Optional[FuncInfo]:
            d = dotted(expr)
            if not d:
                return None
            tail = d.rsplit(".", 1)[-1]
            hit = resolve_strong(d)
            if hit is not None:
                return hit
            if d == tail and tail in nested_all:
                return nested_all[tail]
            if d.startswith("self."):
                for m in methods.get(tail, ()):
                    if m.module == mod_path:
                        return m
            cands = methods.get(tail, ())
            return cands[0] if len(cands) == 1 else None

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = dotted(node.func) or ""
            tail = cname.rsplit(".", 1)[-1]
            fn = None
            if tail == "Thread":
                for k in node.keywords:
                    if k.arg == "target":
                        fn = as_func(k.value)
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("submit", "map")
                    and node.args):
                fn = as_func(node.args[0])
            if fn is not None:
                cg.thread_roots.add(fn.key)


def _closure(cg: CallGraph, roots: Set[str]) -> Set[str]:
    seen = set(roots)
    work = list(roots)
    while work:
        k = work.pop()
        for cs in cg.edges.get(k, ()):
            if cs.callee not in seen:
                seen.add(cs.callee)
                work.append(cs.callee)
    return seen


def reach_closure(cg: CallGraph, key: str, *, strong_only: bool,
                  memo: Dict[str, Set[str]]) -> Set[str]:
    """Transitive callee set of ``key`` (key excluded unless cyclic).
    The first query computes EVERY node's closure via SCC
    condensation and fills ``memo`` — a naive recursive memo poisons
    cycle members with the in-progress guard's incomplete set (A<->B
    with B->D memoized closure(A) without D), which would silently
    drop CL801/CL802 findings behind mutually recursive helpers."""
    if not memo:
        _fill_closures(cg, strong_only, memo)
    return memo.get(key, set())


def _fill_closures(cg: CallGraph, strong_only: bool,
                   memo: Dict[str, Set[str]]) -> None:
    adj: Dict[str, Set[str]] = {}
    for key in cg.funcs:
        adj[key] = {
            cs.callee for cs in cg.callees(key, strong_only=strong_only)
            if cs.callee in cg.funcs
        }
    comp_of, comps = _tarjan(adj)  # comps emitted callees-first
    comp_reach: List[Set[str]] = []
    for ci, members in enumerate(comps):
        cyclic = len(members) > 1 or any(
            m in adj.get(m, ()) for m in members
        )
        out: Set[str] = set(members) if cyclic else set()
        for m in members:
            for v in adj.get(m, ()):
                cj = comp_of[v]
                if cj != ci:
                    out.add(v)
                    out |= comp_reach[cj]
        comp_reach.append(out)
        for m in members:
            memo[m] = out
    if not memo:
        memo["<empty>"] = set()  # mark computed even for bare graphs


def _tarjan(adj: Dict[str, Set[str]]):
    """Iterative Tarjan SCC; components are emitted in reverse
    topological order of the condensation (every edge out of a
    component lands in an earlier-emitted one)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    comp_of: Dict[str, int] = {}
    comps: List[List[str]] = []
    counter = [0]

    for root in adj:
        if root in index:
            continue
        work = [(root, iter(adj.get(root, ())))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                members = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp_of[w] = len(comps)
                    members.append(w)
                    if w == v:
                        break
                comps.append(members)
    return comp_of, comps
