"""crdtlint core: findings, suppressions, baseline, checker runner.

The analyzer is deliberately **stdlib-only** (``ast`` + ``tokenize``-free
line scanning): it must run in any environment that can run the tests,
import nothing from ``crdt_tpu`` (so a broken package still lints), and
finish in well under ten seconds on the whole tree.

Vocabulary:

- A **Finding** is one violation: ``path:line CODE message``. Its
  *fingerprint* — ``path|code|symbol`` — is stable across line moves,
  so baseline entries survive unrelated edits to the same file.
- A **suppression** is an inline ``# crdtlint: disable=CL101`` (or
  ``disable=CL101,CL402`` / ``disable=all``) on the finding's line or
  the line directly above it; ``# crdtlint: disable-file=CODE`` in the
  first ten lines silences a code for the whole file.
- The **baseline** (``tools/crdtlint/baseline.json``) lists known,
  *justified* findings by fingerprint. Baselined findings don't fail
  the run but are counted (``lint.findings`` rides the bench diff
  table, lower-is-better — growing the baseline is visible). Every
  entry must carry a non-empty ``justification``.

Checkers subclass :class:`Checker` and register in
``tools.crdtlint.checkers.ALL_CHECKERS``; each gets three hooks —
``prepare`` (build cross-module indexes), ``check_module`` (per-file
findings), ``finalize`` (cross-module findings such as dead registry
entries).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_DISABLE_RE = re.compile(
    r"#\s*crdtlint:\s*disable=([A-Za-z0-9_,\s]+|all)"
)
_DISABLE_FILE_RE = re.compile(
    r"#\s*crdtlint:\s*disable-file=([A-Za-z0-9_,\s]+|all)"
)
# a computed-metric-name call site DECLARES the closed set of names
# it can emit: `# crdtlint: emits=fault.drop,fault.dup`. The declared
# names count as emitted (no false dead-entry) and the declaration
# suppresses the computed-name finding — while still registry-checking
# every declared name.
_EMITS_RE = re.compile(
    r"#\s*crdtlint:\s*emits=([A-Za-z0-9_.,\s]+)"
)


@dataclass(frozen=True)
class Finding:
    path: str       # repo-relative, posix separators
    line: int       # 1-based
    code: str       # e.g. "CL101"
    message: str
    symbol: str = ""  # stable context (function / metric name) for
    #                   the baseline fingerprint

    @property
    def fingerprint(self) -> str:
        return f"{self.path}|{self.code}|{self.symbol}"

    def format(self) -> str:
        return f"{self.path}:{self.line} {self.code} {self.message}"


class Module:
    """One parsed source file: tree + per-line suppression map."""

    def __init__(self, path: str, source: str):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:  # surfaced as a finding, not a crash
            self.parse_error = f"syntax error: {e.msg} (line {e.lineno})"
        # line -> set of disabled codes ("all" disables everything)
        self._disabled: Dict[int, set] = {}
        self._file_disabled: set = set()
        self.emits: Dict[int, set] = {}  # line -> declared metric names
        for i, text in enumerate(self.lines, start=1):
            if "crdtlint" not in text:
                continue
            m = _DISABLE_FILE_RE.search(text)
            if m and i <= 10:
                self._file_disabled |= _parse_codes(m.group(1))
            m = _DISABLE_RE.search(text)
            if m:
                self._disabled.setdefault(i, set()).update(
                    _parse_codes(m.group(1))
                )
            m = _EMITS_RE.search(text)
            if m:
                self.emits.setdefault(i, set()).update(
                    _parse_codes(m.group(1))
                )

    def emits_near(self, lineno: int) -> set:
        """Names declared by an `emits=` directive on ``lineno`` or
        the comment line directly above it."""
        out = set(self.emits.get(lineno, ()))
        if _comment_only(self.lines, lineno - 1):
            out |= self.emits.get(lineno - 1, set())
        return out

    def suppressed(self, finding: Finding) -> bool:
        if _hits(self._file_disabled, finding.code):
            return True
        for line in (finding.line, finding.line - 1):
            if _hits(self._disabled.get(line, ()), finding.code):
                # a bare-comment line above applies to the statement
                # below it; a trailing comment applies to its own line
                if line == finding.line or _comment_only(
                    self.lines, line
                ):
                    return True
        return False


def _parse_codes(raw: str) -> set:
    return {c.strip() for c in raw.split(",") if c.strip()}


def _hits(codes, code: str) -> bool:
    return "all" in codes or code in codes


def _comment_only(lines: Sequence[str], lineno: int) -> bool:
    if not (1 <= lineno <= len(lines)):
        return False
    return lines[lineno - 1].lstrip().startswith("#")


class Checker:
    """Base checker. ``codes`` maps code -> one-line invariant."""

    name: str = ""
    codes: Dict[str, str] = {}

    def prepare(self, ctx: "LintContext") -> None:
        pass

    def check_module(self, mod: Module,
                     ctx: "LintContext") -> Iterable[Finding]:
        return ()

    def finalize(self, ctx: "LintContext") -> Iterable[Finding]:
        return ()


@dataclass
class LintConfig:
    """Paths the checkers read. Defaults resolve against the repo
    root (the directory holding ``tools/``); tests override to point
    at synthetic fixtures."""

    repo_root: str = ""
    readme_path: Optional[str] = None     # metric/event registry prose
    smoke_test_path: Optional[str] = None  # HOT_PATH_SPANS pin
    baseline_path: Optional[str] = None

    def __post_init__(self):
        if not self.repo_root:
            self.repo_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ))
        if self.readme_path is None:
            p = os.path.join(self.repo_root, "README.md")
            self.readme_path = p if os.path.exists(p) else None
        if self.smoke_test_path is None:
            p = os.path.join(
                self.repo_root, "tests", "test_bench_smoke.py"
            )
            self.smoke_test_path = p if os.path.exists(p) else None
        if self.baseline_path is None:
            self.baseline_path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "baseline.json",
            )


@dataclass
class LintContext:
    config: LintConfig
    modules: List[Module] = field(default_factory=list)
    # shared cross-checker indexes, keyed by checker-chosen names
    shared: Dict[str, object] = field(default_factory=dict)

    def module_by_path(self, suffix: str) -> Optional[Module]:
        for m in self.modules:
            if m.path.endswith(suffix):
                return m
        return None


# ---------------------------------------------------------------------------
# baseline


class BaselineError(ValueError):
    pass


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> entry. Every entry must carry a non-empty
    ``justification`` — the baseline is a ledger of *intentional*
    exceptions, not a mute button."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    out: Dict[str, dict] = {}
    for entry in data.get("entries", ()):
        fp = entry.get("fingerprint")
        if not fp:
            raise BaselineError(f"baseline entry missing fingerprint: {entry}")
        if not str(entry.get("justification", "")).strip():
            raise BaselineError(
                f"baseline entry for {fp!r} has no justification"
            )
        out[fp] = entry
    return out


def write_baseline(path: str, findings: Sequence[Finding],
                   preserved: Iterable[dict] = ()) -> None:
    """Write a baseline: skeleton entries (justification TODO) for
    ``findings`` merged with ``preserved`` existing entries, whose
    hand-written justifications survive verbatim. A preserved entry
    wins over a skeleton with the same fingerprint — regenerating the
    baseline must never wipe the ledger's reasoning."""
    by_fp: Dict[str, dict] = {}
    for f in findings:
        by_fp[f.fingerprint] = {
            "fingerprint": f.fingerprint,
            "code": f.code,
            "path": f.path,
            "message": f.message,
            "justification": "TODO: justify or fix",
        }
    for entry in preserved:
        fp = entry.get("fingerprint")
        if fp:
            by_fp[fp] = entry
    entries = [by_fp[fp] for fp in sorted(by_fp)]
    with open(path, "w") as fh:
        json.dump({"entries": entries}, fh, indent=2, sort_keys=True)
        fh.write("\n")


# ---------------------------------------------------------------------------
# runner


@dataclass
class LintResult:
    findings: List[Finding]            # unsuppressed: these fail the run
    suppressed: List[Finding]          # inline-disabled
    baselined: List[Finding]
    stale_baseline: List[str]          # fingerprints with no live finding
    # analysis-layer evidence (round 16): the memoized call graph's
    # size stats, embedded in the bench digest so graph growth/decay
    # is visible next to the finding counts
    stats: Dict[str, object] = field(default_factory=dict)

    @property
    def total_raw(self) -> int:
        """Every violation the checkers saw, suppressed or not — the
        ``lint.findings`` bench metric (growing the baseline moves it)."""
        return len(self.findings) + len(self.suppressed) + len(self.baselined)

    def open_by_family(self, families=("CL1", "CL2", "CL3", "CL4",
                                       "CL5", "CL6", "CL7", "CL8",
                                       "CL9", "CL10",
                                       "CL11")) -> Dict[str, int]:
        """OPEN finding count per code family (``cl7`` counts every
        CL7xx). The committed tree gates these at zero (tier-1), so
        ``tools/metrics_diff.py`` sees any new open finding as a
        regression with count semantics — no noise floor. Codes are
        ``CL`` + 3 digits (families cl1–cl9) or ``CL`` + 4 digits
        (the round-17 cl10/cl11 wire-taint families) — a CL1001 must
        count under ``cl10``, never under the donate family ``cl1``."""
        out = {f.lower(): 0 for f in families}
        for f in self.findings:
            fam = (f.code[:4] if len(f.code) == 6
                   else f.code[:3]).lower()
            if fam in out:
                out[fam] += 1
        return out


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)


def load_modules(paths: Sequence[str],
                 repo_root: str) -> List[Module]:
    mods = []
    for fp in iter_py_files(paths):
        with open(fp, encoding="utf-8") as f:
            source = f.read()
        rel = os.path.relpath(os.path.abspath(fp), repo_root)
        mods.append(Module(rel, source))
    return mods


def run_lint(
    modules: Sequence[Tuple[str, str]] | Sequence[Module],
    *,
    config: Optional[LintConfig] = None,
    checkers: Optional[Sequence[Checker]] = None,
    baseline: Optional[Dict[str, dict]] = None,
    use_baseline: bool = True,
    shared: Optional[Dict[str, object]] = None,
) -> LintResult:
    """Lint in-memory or pre-loaded modules. ``modules`` accepts
    ``(relpath, source)`` pairs (the unit-test surface) or
    :class:`Module` objects (the CLI surface). ``shared`` pre-seeds
    cross-checker state — tests inject a synthetic metric registry as
    ``{"metric_registry": Registry(...)}``."""
    config = config or LintConfig()
    if checkers is None:
        from tools.crdtlint.checkers import ALL_CHECKERS

        checkers = [cls() for cls in ALL_CHECKERS]
    mods = [
        m if isinstance(m, Module) else Module(m[0], m[1])
        for m in modules
    ]
    ctx = LintContext(config=config, modules=mods)
    if shared:
        ctx.shared.update(shared)

    raw: List[Finding] = []
    for m in mods:
        if m.parse_error:
            raw.append(Finding(m.path, 1, "CL000", m.parse_error))
    # per-checker wall time (prepare + check_module + finalize),
    # surfaced by --statistics and asserted against the tier-1 <10 s
    # whole-tree budget: a checker that quietly turns quadratic shows
    # up as a named number, not as a mystery slowdown
    import time as _time

    checker_seconds: Dict[str, float] = {}

    def _timed(ch, fn):
        t0 = _time.perf_counter()
        out = fn()
        checker_seconds[ch.name] = (
            checker_seconds.get(ch.name, 0.0)
            + _time.perf_counter() - t0
        )
        return out

    for ch in checkers:
        _timed(ch, lambda ch=ch: ch.prepare(ctx))
    for ch in checkers:
        for m in mods:
            if m.tree is None:
                continue
            raw.extend(_timed(ch, lambda ch=ch, m=m: list(
                ch.check_module(m, ctx)
            )))
    for ch in checkers:
        raw.extend(_timed(ch, lambda ch=ch: list(ch.finalize(ctx))))

    by_path = {m.path: m for m in mods}
    if baseline is None and use_baseline:
        baseline = load_baseline(config.baseline_path)
    baseline = baseline or {}

    open_f: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    seen_fps = set()
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.code)):
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressed(f):
            suppressed.append(f)
        elif f.fingerprint in baseline:
            baselined.append(f)
            seen_fps.add(f.fingerprint)
        else:
            open_f.append(f)
    stale = sorted(set(baseline) - seen_fps)
    stats: Dict[str, object] = {
        "checker_seconds": {
            k: round(v, 4) for k, v in checker_seconds.items()
        },
    }
    if "callgraph_stats" in ctx.shared:
        stats["callgraph"] = ctx.shared["callgraph_stats"]
    return LintResult(open_f, suppressed, baselined, stale, stats)
