"""crdtlint: AST-based invariant checker for the crdt_tpu package.

Five rounds of PRs accumulated contracts that lived only in prose and
runtime tests — donated buffers are dead after dispatch (round 9),
every metric matches the documented registry (round 8), decoders
raise ValueError and nothing else (round 10), all H2D/D2H bytes flow
through the ``xfer_put``/``xfer_fetch`` seam (round 9), fault
schedules are seeded (round 7). crdtlint makes them machine-enforced:

    python -m tools.crdtlint crdt_tpu/

Findings print as ``file:line CODE message`` and fail the run (exit
1) unless suppressed inline (``# crdtlint: disable=CODE``) or listed
with a justification in ``tools/crdtlint/baseline.json``. Tier-1
(``tests/test_lint.py``) runs the suite over the package, so every
future PR inherits the contracts. Stdlib-only by design — no jax, no
crdt_tpu import, runs in well under ten seconds.

See README "Static analysis" for the checker table and the
suppression/baseline workflow.
"""

from tools.crdtlint.core import (  # noqa: F401
    BaselineError,
    Checker,
    Finding,
    LintConfig,
    LintContext,
    LintResult,
    Module,
    load_baseline,
    load_modules,
    run_lint,
    write_baseline,
)

__version__ = "1.0"
