"""SARIF 2.1.0 export (round-17 satellite).

One rule per registered code (the ``--explain`` text rides as the
rule's full description / help), one result per finding. Open
findings are ``error``-level results; baselined and inline-suppressed
findings are included with SARIF ``suppressions`` entries (kind
``external`` for the justified baseline ledger, ``inSource`` for
``# crdtlint: disable``) so a PR-annotation consumer renders exactly
the set that fails the build while the suppressed history stays
inspectable. The export NEVER changes exit-code semantics — it is a
serialization of the same LintResult the CLI prints.
"""

from __future__ import annotations

import json
from typing import Dict, List

SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _result(finding, *, suppression: Dict = None) -> Dict:
    out = {
        "ruleId": finding.code,
        "level": "note" if suppression else "error",
        "message": {"text": finding.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {
                    "uri": finding.path,
                    "uriBaseId": "SRCROOT",
                },
                "region": {"startLine": max(1, finding.line)},
            },
        }],
        # the baseline ledger's stable identity, so annotations
        # survive line moves the same way the ledger does
        "partialFingerprints": {
            "crdtlint/v1": finding.fingerprint,
        },
    }
    if suppression:
        out["suppressions"] = [suppression]
    return out


def to_sarif(result, codes: Dict[str, str],
             explain: Dict[str, str],
             baseline: Dict[str, dict]) -> Dict:
    """Build the SARIF log dict from a
    :class:`tools.crdtlint.core.LintResult`."""
    rules: List[Dict] = [
        {
            "id": code,
            "shortDescription": {"text": codes[code]},
            "fullDescription": {"text": explain.get(code, codes[code])},
            "help": {"text": explain.get(code, codes[code])},
            "defaultConfiguration": {"level": "error"},
        }
        for code in sorted(codes)
    ]
    results: List[Dict] = [_result(f) for f in result.findings]
    for f in result.baselined:
        entry = baseline.get(f.fingerprint, {})
        results.append(_result(f, suppression={
            "kind": "external",
            "justification": str(
                entry.get("justification", "")
            )[:1000],
        }))
    for f in result.suppressed:
        results.append(_result(f, suppression={
            "kind": "inSource",
            "justification": "inline `# crdtlint: disable=` comment",
        }))
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "crdtlint",
                    # informationUri is OMITTED on purpose: the spec
                    # requires a valid absolute URI and ingesters
                    # (github upload-sarif) reject nonconforming
                    # logs — a repo-relative hint here would silently
                    # kill the whole annotation lane. README's
                    # "Static analysis" section is the reference.
                    "rules": rules,
                },
            },
            "results": results,
        }],
    }


def write_sarif(path: str, result, codes, explain, baseline) -> None:
    with open(path, "w") as fh:
        json.dump(to_sarif(result, codes, explain, baseline), fh,
                  indent=1, sort_keys=True)
        fh.write("\n")
