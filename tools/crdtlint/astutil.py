"""Small shared AST helpers for crdtlint checkers (stdlib-only)."""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple


# in-place mutator method names on the stdlib containers — shared by
# the thread-shared (CL601), lock-discipline (CL803), and
# trace-purity (CL704) checkers, which all ask "does this call mutate
# its receiver"
MUTATOR_METHODS = frozenset({
    "append", "update", "pop", "add", "extend", "remove", "clear",
    "setdefault", "appendleft", "popleft", "discard", "insert",
})


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def import_map(tree: ast.Module) -> Dict[str, str]:
    """local name -> fully qualified imported name.

    ``from crdt_tpu.parallel.gossip import make_gossip_step as g`` maps
    ``g -> crdt_tpu.parallel.gossip.make_gossip_step``; ``import jax``
    maps ``jax -> jax``. Relative imports keep their dots stripped —
    checker indexes match on trailing components anyway.
    """
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for alias in node.names:
                out[alias.asname or alias.name] = (
                    f"{mod}.{alias.name}" if mod else alias.name
                )
    return out


def kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """Literal int tuple/list value, or ``tuple(range(n))``."""
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if (isinstance(node, ast.Call) and call_name(node) == "tuple"
            and len(node.args) == 1):
        inner = node.args[0]
        if (isinstance(inner, ast.Call) and call_name(inner) == "range"
                and len(inner.args) == 1):
            n = inner.args[0]
            if isinstance(n, ast.Constant) and isinstance(n.value, int):
                return tuple(range(n.value))
    return None


def assigned_names(target: ast.AST) -> Iterable[str]:
    """Dotted names bound by an assignment target (tuple targets
    flattened; subscripts/stars report their base name)."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            yield from assigned_names(e)
    elif isinstance(target, ast.Starred):
        yield from assigned_names(target.value)
    elif isinstance(target, ast.Subscript):
        base = dotted(target.value)
        if base:
            yield base
    else:
        d = dotted(target)
        if d:
            yield d


def in_scope(path: str, prefixes: Iterable[str]) -> bool:
    """Does a repo-relative path fall under any scope prefix? Matched
    on the path's ``crdt_tpu/``-rooted tail so synthetic test paths
    (``pkg/crdt_tpu/ops/x.py``) scope the same way."""
    idx = path.find("crdt_tpu/")
    tail = path[idx:] if idx >= 0 else path
    return any(tail.startswith(p) for p in prefixes)


def make_module_resolver(
    mod_path: str,
    tree: Optional[ast.Module],
    local_names: Iterable[str],
    cands_by_name: Dict[str, List],
    *,
    fallback_first: bool = True,
    imap: Optional[Dict[str, str]] = None,
):
    """Module-aware def lookup — the resolution machinery the donate
    checker grew over rounds 9–11, generalized so the call graph (and
    any other cross-module index) resolves names the same way.

    ``cands_by_name`` maps a bare def name to ALL candidate objects
    carrying a ``.module`` attribute (repo-relative path of the
    defining module). The returned ``resolve(name)`` applies, in
    order: the calling module's own defs win; a local non-candidate
    def SHADOWS another module's same-named candidate; an explicit
    ``from x import name`` picks the defining module; a
    module-attribute spelling (``pk._step``) matches on the RECEIVER
    module and refuses to guess when that module has no such def.
    ``fallback_first`` keeps the historical first-def guess for
    receivers that aren't imported modules (``self.x._step``); pass
    False to get None instead — the call graph treats that case as a
    low-confidence edge rather than a guess."""
    if imap is None:
        imap = import_map(tree) if tree is not None else {}
    local_names = set(local_names)

    def resolve(name: str):
        tail = name.rsplit(".", 1)[-1]
        cands = cands_by_name.get(tail)
        if not cands:
            return None
        for d in cands:
            if d.module == mod_path:
                return d
        if name == tail:
            if tail in local_names:
                return None  # local non-candidate def shadows it
            qual = imap.get(tail)
            if qual and "." in qual:
                src = (qual.rsplit(".", 1)[0].replace(".", "/")
                       + ".py")
                for d in cands:
                    if d.module.endswith(src):
                        return d
        else:
            chain = name.split(".")[:-1]
            qual = imap.get(chain[0])
            if qual:
                full = (
                    ".".join(chain)
                    if chain[0] == qual.split(".", 1)[0]
                    else ".".join([qual] + chain[1:])
                )
                src = full.replace(".", "/") + ".py"
                for d in cands:
                    if d.module.endswith(src):
                        return d
                return None
            # receiver isn't an imported module (`self.x._step`):
            # can't localize
        return cands[0] if fallback_first else None

    return resolve


def enclosing_function_map(tree: ast.Module) -> Dict[int, str]:
    """id(node) -> name of the INNERMOST enclosing function
    (``"<module>"`` at top level)."""
    out: Dict[int, str] = {}

    def visit(node, current):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            current = node.name
        for child in ast.iter_child_nodes(node):
            out[id(child)] = current
            visit(child, current)

    visit(tree, "<module>")
    return out
