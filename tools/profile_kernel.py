"""Ablation profiler for the fused packed-replay dispatch.

Times the full `_converge_packed` program against variants with pieces
stubbed out, on the live backend in forced-sync mode, to locate where
the dispatch milliseconds actually go (sorts vs list-ranking loops vs
tunnel fixed cost). Throwaway diagnostics — not part of the product.

Usage: python tools/profile_kernel.py [n_ops]
"""
from __future__ import annotations

import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, ".")

import jax

from crdt_tpu.compat import enable_x64
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/tmp/crdt_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from crdt_tpu.ops import packed as pk
from crdt_tpu.ops.device import (
    NULLI, dense_ranks_sorted, dfs_ranks, lexsort, pack_id,
    run_edge_lookup, scatter_perm, searchsorted_ids,
)
from crdt_tpu.ops.lww import map_winners


def _core_ablated(client, clock, pref, kid, oc, ock, valid, *,
                  num_segments: int, seq_bucket: int,
                  do_idsort=True, do_origin=True, do_segsort=True,
                  do_map=True, do_sib=True, do_rank=True, do_dorder=True):
    n = client.shape[0]
    ikey = jnp.where(valid, pack_id(client, clock), jnp.int64(2**62))
    if do_idsort:
        order = jnp.argsort(ikey, stable=True)
    else:
        order = jnp.arange(n, dtype=jnp.int32)
    ikey = ikey[order]
    client = client[order]
    clock = clock[order]
    pref = pref[order]
    kid = kid[order]
    oc = oc[order]
    ock = ock[order]
    valid = valid[order]
    dup = jnp.concatenate([jnp.zeros(1, bool), ikey[1:] == ikey[:-1]])
    uniq_valid = valid & ~dup
    okey = pack_id(oc, ock)
    if do_origin:
        origin_idx = searchsorted_ids(ikey, okey)
    else:
        origin_idx = jnp.where(okey >= 0, 0, NULLI).astype(jnp.int32)

    is_map = uniq_valid & (kid >= 0)
    is_seq = uniq_valid & (kid < 0)

    segkey = jnp.where(
        uniq_valid,
        pk.segkey_of(pref, kid.astype(jnp.int64)),
        jnp.int64(2**63 - 1),
    )
    if do_segsort:
        sorder = jnp.argsort(segkey, stable=True)
        seg_sorted = dense_ranks_sorted(segkey[sorder])
        seg = scatter_perm(sorder, seg_sorted)
    else:
        sorder = jnp.arange(n, dtype=jnp.int32)
        seg = jnp.where(uniq_valid, 0, NULLI).astype(jnp.int32)
    seg_map = jnp.where(is_map, seg, NULLI)

    if do_map:
        winners = map_winners(
            seg_map, client, clock, origin_idx, is_map, num_segments
        )
    else:
        winners = jnp.zeros(num_segments, jnp.int32) - 1
    win_rows = jnp.where(
        winners >= 0, order[jnp.clip(winners, 0, n - 1)], NULLI
    ).astype(jnp.int32)

    B = seq_bucket
    mB = B + num_segments
    sub = sorder[:B]
    c_ok = is_seq[sub]
    c_seg = jnp.where(c_ok, seg[sub], NULLI)
    inv_sorder = jnp.argsort(sorder, stable=True).astype(jnp.int32)
    o = origin_idx[sub]
    o_ok = c_ok & (o >= 0)
    o_seg = jnp.where(o_ok, seg[jnp.clip(o, 0, n - 1)], NULLI)
    same_seg = o_ok & (o_seg == c_seg)
    c_parent = jnp.where(
        same_seg, inv_sorder[jnp.clip(o, 0, n - 1)], NULLI
    ).astype(jnp.int32)

    parent = jnp.where(
        c_ok & (c_parent >= 0), c_parent, B + jnp.maximum(c_seg, 0)
    )
    parent = jnp.where(c_ok, parent, mB).astype(jnp.int32)

    c_client = client[sub]
    pos_desc = (n - 1) - sub
    pbits = int(mB).bit_length()
    qbits = int(max(n - 1, 1)).bit_length()
    if do_sib:
        if pbits + 22 + qbits <= 63:
            sibkey = (
                (parent.astype(jnp.int64) << (22 + qbits))
                | (c_client.astype(jnp.int64) << qbits)
                | pos_desc.astype(jnp.int64)
            )
            sord2 = jnp.argsort(sibkey, stable=True)
        else:
            sord2 = lexsort([
                parent.astype(jnp.int64),
                (c_client.astype(jnp.int64) << qbits)
                | pos_desc.astype(jnp.int64),
            ])
        p_s = parent[sord2]
        same_group = jnp.concatenate([p_s[1:] == p_s[:-1], jnp.zeros(1, bool)])
        nxt_sorted = jnp.where(
            same_group, jnp.roll(sord2, -1), NULLI
        ).astype(jnp.int32)
        next_sib = scatter_perm(sord2, nxt_sorted)
        first_pos, _ = run_edge_lookup(p_s, mB, side="left")
        first_child = jnp.where(
            first_pos >= 0, sord2[jnp.clip(first_pos, 0, B - 1)], NULLI
        ).astype(jnp.int32)
    else:
        next_sib = jnp.zeros(B, jnp.int32) - 1
        first_child = jnp.zeros(mB, jnp.int32) - 1

    if do_rank:
        dist_to_end = dfs_ranks(parent, next_sib, first_child, c_ok,
                                num_segments)
        root_dist = dist_to_end[B + jnp.maximum(c_seg, 0)]
        c_rank = jnp.where(c_ok, root_dist - dist_to_end[:B] - 1, NULLI)
    else:
        c_rank = jnp.where(c_ok, 0, NULLI)

    qb2 = qbits
    skey2 = jnp.where(
        c_ok & (c_rank >= 0),
        (c_seg.astype(jnp.int64) << qb2) | c_rank.astype(jnp.int64),
        jnp.int64(2**62),
    )
    if do_dorder:
        dorder = jnp.argsort(skey2, stable=True)
    else:
        dorder = jnp.arange(B, dtype=jnp.int32)
    d_ok = (c_ok & (c_rank >= 0))[dorder]
    stream_seg = jnp.where(d_ok, c_seg[dorder], NULLI).astype(jnp.int32)
    stream_row = jnp.where(
        d_ok, order[sub[dorder]], NULLI
    ).astype(jnp.int32)

    return jnp.concatenate([win_rows, stream_seg, stream_row])


def make_variant(**flags):
    @partial(jax.jit, static_argnames=("num_segments", "seq_bucket"))
    def fn(mat, num_segments: int, seq_bucket: int):
        client = mat[0].astype(jnp.int32)
        clock = mat[1].astype(jnp.int64)
        pref = mat[2].astype(jnp.int64)
        kid = mat[3].astype(jnp.int32)
        oc = mat[4].astype(jnp.int32)
        ock = mat[5].astype(jnp.int64)
        valid = mat[6] != 0
        return _core_ablated(
            client, clock, pref, kid, oc, ock, valid,
            num_segments=num_segments, seq_bucket=seq_bucket, **flags)
    return fn


def main():
    n_ops = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    import bench

    R = max(1, n_ops // 100)
    t0 = time.perf_counter()
    blobs = bench.build_trace(R, 100)
    dec = bench.decode_stage(blobs)
    cols, _ = bench.column_stage(dec)
    plan = pk.stage(cols, wide=True)  # ablations read raw int32 rows
    print(f"staged {len(cols['client'])} rows in {time.perf_counter()-t0:.1f}s "
          f"(segs={plan.num_segments} seqB={plan.seq_bucket} "
          f"kpad={plan.mat.shape[1]} dtype={plan.mat.dtype})", flush=True)

    # force sync mode (lazy-exec trap)
    np.asarray(jnp.arange(8) + 1)

    with enable_x64(True):
        dev = jnp.asarray(plan.mat)
        jax.block_until_ready(dev)
        kw = dict(num_segments=plan.num_segments, seq_bucket=plan.seq_bucket)

        null = jax.jit(lambda m: m[0, :1] + 1)

        variants = [
            ("null-dispatch", null, {}),
            ("FULL", make_variant(), kw),
            ("no idsort", make_variant(do_idsort=False), kw),
            ("no origin-ss", make_variant(do_origin=False), kw),
            ("no segsort", make_variant(do_segsort=False), kw),
            ("no map_winners", make_variant(do_map=False), kw),
            ("no sib-sort", make_variant(do_sib=False), kw),
            ("no dfs_ranks", make_variant(do_rank=False), kw),
            ("no dorder", make_variant(do_dorder=False), kw),
            ("layout only (no map/rank)",
             make_variant(do_map=False, do_rank=False), kw),
        ]
        for name, fn, kwargs in variants:
            tc = time.perf_counter()
            jax.block_until_ready(fn(dev, **kwargs))
            compile_s = time.perf_counter() - tc
            times = []
            for _ in range(7):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(dev, **kwargs))
                times.append(time.perf_counter() - t0)
            ms = sorted(t * 1e3 for t in times)
            print(f"{name:28s} min={ms[0]:7.1f}ms med={ms[3]:7.1f}ms "
                  f"max={ms[-1]:7.1f}ms (compile {compile_s:.0f}s)",
                  flush=True)


if __name__ == "__main__":
    main()
