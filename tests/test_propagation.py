"""Round 19: the distributed-tracing plane — wire trace context,
per-hop lag attribution, and the shared analysis core.

The codec contract (bounds, fail-closed rejects), the forward-seam
hop incrementer (relayed deliveries record hop=2 with the relay's
identity in the path), the route retag seams, the ledger's
route-tagged decomposition, and the tid-pairing / path-reconstruction
core obsq and the fleet collector share.
"""

import pytest

from crdt_tpu.net.replica import Replica
from crdt_tpu.net.router import LoopbackNetwork, LoopbackRouter
from crdt_tpu.obs import propagation as P
from crdt_tpu.obs.propagation import (
    PropagationLedger,
    TraceContext,
    correlate_divergences,
    decode_context,
    encode_context,
    pair_latency,
    reconstruct_paths,
    set_propagation,
)
from crdt_tpu.obs.recorder import FlightRecorder, set_recorder
from crdt_tpu.obs.tracer import Tracer, set_tracer


@pytest.fixture
def installed():
    tracer = set_tracer(Tracer(enabled=True))
    rec = set_recorder(FlightRecorder(enabled=True))
    ledger = set_propagation(PropagationLedger())
    yield tracer, rec, ledger
    set_tracer(Tracer(enabled=False))
    set_recorder(FlightRecorder(enabled=False))
    set_propagation(PropagationLedger())


# ---------------------------------------------------------------------------
# wire codec: the stable contract
# ---------------------------------------------------------------------------


class TestContextCodec:
    def test_round_trip(self):
        ctx = P.start_context(7, 3, "abcd1234", "direct", ts=100.5)
        P.append_hop(ctx, "relay001", "relayed", 2500)
        out = decode_context(encode_context(ctx))
        assert out.origin_client == 7
        assert out.origin_seq == 3
        assert out.origin_ts == 100.5
        assert out.hops == [("abcd1234", "direct", 0),
                            ("relay001", "relayed", 2500)]
        assert out.tid == [7, 3, 100.5]
        assert out.path_json() == [["abcd1234", "direct", 0],
                                   ["relay001", "relayed", 2500]]

    def test_every_route_tag_round_trips(self):
        for route in P.ROUTES:
            ctx = P.start_context(1, 1, "r", route, ts=0.0)
            assert decode_context(
                encode_context(ctx)
            ).hops[0][1] == route

    def test_compactness(self):
        """The wire tax stays a few dozen bytes even at the hop
        bound — the <5% overhead budget needs this."""
        ctx = P.start_context(2**31 - 1, 10_000, "abcdef12",
                              "direct", ts=12345.678)
        while P.append_hop(ctx, "someproc", "relayed", 10**7):
            pass
        blob = encode_context(ctx)
        assert len(ctx.hops) == P.max_hops()
        assert len(blob) <= 16 + 16 * P.max_hops()

    def test_rejects_non_bytes(self):
        for bad in (None, "text", 7, [1, 2], {"a": 1}, 3.5):
            with pytest.raises(ValueError):
                decode_context(bad)

    def test_rejects_truncations_value_error_only(self):
        ctx = P.start_context(9, 9, "abcd", "anti_entropy", ts=5.0)
        P.append_hop(ctx, "efgh", "relayed", 123)
        blob = encode_context(ctx)
        for cut in range(len(blob)):
            try:
                decode_context(blob[:cut])
            except ValueError:
                pass  # the only legal outcome besides success

    def test_rejects_trailing_garbage(self):
        blob = encode_context(
            P.start_context(1, 1, "a", "direct", ts=0.0)
        )
        with pytest.raises(ValueError):
            decode_context(blob + b"\x00")

    def test_rejects_oversized_hop_list(self):
        from crdt_tpu.codec.lib0 import Encoder

        enc = Encoder()
        enc.write_uint8(1)
        enc.write_var_uint(1)
        enc.write_var_uint(1)
        enc.write_float64(0.0)
        enc.write_var_uint(2**40)  # declared hops: absurd
        with pytest.raises(ValueError):
            decode_context(enc.to_bytes())

    def test_rejects_negative_ts_delta(self):
        from crdt_tpu.codec.lib0 import Encoder

        enc = Encoder()
        enc.write_uint8(1)
        enc.write_var_uint(1)
        enc.write_var_uint(1)
        enc.write_float64(0.0)
        enc.write_var_uint(1)
        enc.write_var_string("ab")
        enc.write_uint8(0)
        enc.write_var_int(-5)
        with pytest.raises(ValueError, match="negative"):
            decode_context(enc.to_bytes())

    def test_rejects_unknown_route_and_version(self):
        from crdt_tpu.codec.lib0 import Encoder

        enc = Encoder()
        enc.write_uint8(1)
        enc.write_var_uint(1)
        enc.write_var_uint(1)
        enc.write_float64(0.0)
        enc.write_var_uint(1)
        enc.write_var_string("ab")
        enc.write_uint8(250)  # no such route
        enc.write_var_int(0)
        with pytest.raises(ValueError, match="route"):
            decode_context(enc.to_bytes())
        blob = bytearray(encode_context(
            P.start_context(1, 1, "a", "direct", ts=0.0)
        ))
        blob[0] = 9  # no such version
        with pytest.raises(ValueError, match="version"):
            decode_context(bytes(blob))

    def test_rejects_oversized_blob_before_parsing(self):
        with pytest.raises(ValueError, match="wire bound"):
            decode_context(b"\x01" + b"x" * P.MAX_CONTEXT_BYTES)

    def test_rejects_non_finite_origin_ts(self):
        for hostile in (float("nan"), float("inf"), float("-inf")):
            blob = encode_context(
                P.start_context(1, 1, "a", "direct", ts=hostile)
            )
            with pytest.raises(ValueError, match="finite"):
                decode_context(blob)

    def test_forward_seam_survives_hostile_stamps(self):
        """A hostile relay attestation (inf/NaN stamp, wrong types)
        must degrade to 'unattributed' — never raise out of the
        router poll loop (OverflowError was the reviewed crash)."""
        from crdt_tpu.net.udp_router import UdpRouter

        blob = encode_context(
            P.start_context(1, 1, "a", "direct", ts=100.0)
        )
        for stamp in (float("inf"), float("-inf"), float("nan")):
            assert P.append_hop_wire(blob, "r", "relayed",
                                     hop_ts=stamp) == blob
        msg = {"update": b"u", "tid": [1, 1, 100.0], "hop": 0,
               "tc": blob}
        for hts in (float("inf"), float("nan"), "soon", None, True):
            out = UdpRouter._merge_relay_hop(msg, ("relay1", hts))
            assert out == msg  # unchanged, no exception
        # a sane far-future stamp clamps into the wire-legal range
        far = P.append_hop_wire(blob, "r", "relayed", hop_ts=1e300)
        assert decode_context(far).hops[-1][2] < 2**53

    def test_decode_or_none_counts_malformed(self, installed):
        tracer, _, _ = installed
        assert P.decode_or_none(b"\xffgarbage") is None
        assert P.decode_or_none("not-bytes") is None
        assert P.decode_or_none(None) is None  # absent != malformed
        assert tracer.report()["counters"][
            "propagation.malformed_contexts"] == 2

    def test_hop_bound_refuses_and_counts(self, installed):
        tracer, _, _ = installed
        ctx = P.start_context(1, 1, "a", "direct", ts=0.0)
        for _ in range(P.max_hops() * 2):
            P.append_hop(ctx, "b", "relayed", 1)
        assert len(ctx.hops) == P.max_hops()
        c = tracer.report()["counters"]
        assert c["propagation.hops_capped"] > 0
        # append_hop_wire honors the same bound (blob unchanged)
        blob = encode_context(ctx)
        assert P.append_hop_wire(blob, "c", "relayed") == blob

    def test_retag_preserves_semantic_routes(self):
        direct = encode_context(
            P.start_context(1, 1, "a", "direct", ts=0.0)
        )
        assert decode_context(
            P.retag_last_hop(direct, "relayed")
        ).hops[0][1] == "relayed"
        assert decode_context(
            P.retag_last_hop(direct, "predicted")
        ).hops[0][1] == "predicted"
        ae = encode_context(
            P.start_context(1, 1, "a", "anti_entropy", ts=0.0)
        )
        assert P.retag_last_hop(ae, "relayed") == ae  # preserved
        assert P.retag_last_hop(b"junk", "relayed") == b"junk"

    def test_sampling_deterministic_and_bounded(self):
        assert P.sampled(1, 1, 1.0)
        assert not P.sampled(1, 1, 0.0)
        picks = [P.sampled(5, s, 0.5) for s in range(400)]
        assert picks == [P.sampled(5, s, 0.5) for s in range(400)]
        assert 0.3 < sum(picks) / len(picks) < 0.7


# ---------------------------------------------------------------------------
# leg attribution math + the ledger
# ---------------------------------------------------------------------------


class TestLegAttribution:
    def test_hop_legs_close_against_next_stamp_then_recv(self):
        path = [("a", "direct", 0), ("r", "relayed", 400_000)]
        legs = P.hop_legs(path, 100.0, 101.0)
        assert legs == [("a", "direct", pytest.approx(0.4)),
                        ("r", "relayed", pytest.approx(0.6))]

    def test_hop_legs_clamp_clock_skew(self):
        # a cross-host offset can put the recv BEFORE a stamp: lags
        # clamp at 0, never negative
        path = [["a", "direct", 900_000]]
        legs = P.hop_legs(path, 100.0, 100.1)
        assert legs == [("a", "direct", 0.0)]

    def test_hop_legs_reject_malformed_offline_paths(self):
        assert P.hop_legs([["a", "bogus_route", 0]], 0.0, 1.0) == []
        assert P.hop_legs([["a", "direct", "NaN"]], 0.0, 1.0) == []

    def test_ledger_routes_and_overhead(self, installed):
        tracer, _, ledger = installed
        ledger.record_send(b"x" * 30, 1000)
        ledger.record_send(b"y" * 20, 1000)
        ctx = TraceContext(1, 1, 0.0, [("a", "direct", 0)])
        assert ledger.record_receipt(ctx, recv_ts=0.25) == 1
        rep = ledger.report()
        assert rep["wire_overhead_ratio"] == pytest.approx(0.025)
        assert rep["contexts_sent"] == 2
        assert rep["contexts_received"] == 1
        assert rep["hop_lag_by_route"]["direct"]["count"] == 1
        assert rep["birth_to_visibility"]["count"] == 1
        g = tracer.report()["gauges"]
        assert g["propagation.wire_overhead_ratio"] == \
            pytest.approx(0.025)
        spans = tracer.report()["spans"]
        assert spans['replica.hop_lag{route="direct"}']["count"] == 1
        assert spans["replica.birth_to_visibility"]["count"] == 1


# ---------------------------------------------------------------------------
# replica integration over the loopback fabric
# ---------------------------------------------------------------------------


class TestReplicaTracing:
    def test_origin_routes_and_paths_recorded(self, installed):
        tracer, rec, _ = installed
        net = LoopbackNetwork()
        ra = Replica(LoopbackRouter(net, "aaaa"), topic="t",
                     client_id=1)
        rb = Replica(LoopbackRouter(net, "bbbb"), topic="t",
                     client_id=2)
        ra.set("m", "k", "v" * 64)
        net.run()
        rc = Replica(LoopbackRouter(net, "cccc"), topic="t",
                     client_id=3)  # late join: sync_answer legs
        net.run()
        events = rec.events()
        sends = [e for e in events if e["kind"] == "update.send"]
        assert sends and all(
            e["path"] == [["aaaa", "direct", 0]] for e in sends
        )
        answers = [e for e in events if e["kind"] == "sync.answer"]
        assert answers
        for e in answers:
            assert e["tid"] is not None
            assert e["path"][0][1] == "sync_answer"
        recvs = [e for e in events if e["kind"] == "update.recv"
                 and e.get("path")]
        assert recvs
        for e in recvs:
            assert e["hop"] == len(e["path"])
        spans = tracer.report()["spans"]
        assert spans['replica.hop_lag{route="direct"}']["count"] > 0
        assert spans[
            'replica.hop_lag{route="sync_answer"}']["count"] > 0
        assert rc.c == ra.c

    def test_anti_entropy_route_tagged(self, installed):
        from crdt_tpu.core.ids import StateVector

        tracer, rec, _ = installed
        net = LoopbackNetwork()
        ra = Replica(LoopbackRouter(net, "aaaa"), topic="t",
                     client_id=1)
        rb = Replica(LoopbackRouter(net, "bbbb"), topic="t",
                     client_id=2)
        ra.set("m", "k", "v")
        net.run()
        ra.peer_state_vectors["bbbb"] = StateVector()  # fake deficit
        ra.anti_entropy()
        net.run()
        deltas = [e for e in rec.events() if e["kind"] == "ae.delta"]
        assert deltas and deltas[0]["path"][0][1] == "anti_entropy"
        assert tracer.report()["spans"][
            'replica.hop_lag{route="anti_entropy"}']["count"] > 0
        assert rb.c == ra.c

    def test_hostile_context_never_blocks_the_update(self, installed):
        tracer, rec, _ = installed
        net = LoopbackNetwork()
        ra = Replica(LoopbackRouter(net, "aaaa"), topic="t",
                     client_id=1)
        rb = Replica(LoopbackRouter(net, "bbbb"), topic="t",
                     client_id=2)
        ra.set("m", "k", "v")
        net.run()
        blob = ra.doc.encode_state_as_update()
        for evil in (b"\xff\x01junk", "not-bytes", 123,
                     b"\x01" + b"z" * 600):
            rb._on_data({"update": blob, "tid": [1, 99, 0.0],
                         "hop": 0, "tc": evil}, "aaaa")
            rb.flush_incoming()
        c = tracer.report()["counters"]
        assert c["propagation.malformed_contexts"] >= 4
        bad = [e for e in rec.events()
               if e["kind"] == "update.bad_context"]
        assert len(bad) >= 4
        assert rb.c == ra.c  # every update applied regardless

    def test_hostile_tid_never_blocks_the_update(self, installed):
        """The tid rides the same untrusted frame as tc: non-numeric
        / non-finite origin stamps and unhashable elements must
        degrade (no lag observed), never raise out of the poll
        loop."""
        tracer, rec, _ = installed
        net = LoopbackNetwork()
        ra = Replica(LoopbackRouter(net, "aaaa"), topic="t",
                     client_id=1)
        rb = Replica(LoopbackRouter(net, "bbbb"), topic="t",
                     client_id=2)
        ra.set("m", "k", "v")
        net.run()
        blob = ra.doc.encode_state_as_update()
        for evil_tid in ([1, 2, "evil"], [1, 2, float("nan")],
                         [1, 2, float("inf")], [[1], 2, 3.0],
                         [1, 2, None], [1, 2, True]):
            rb._on_data({"update": blob, "tid": evil_tid,
                         "hop": 0}, "aaaa")
            rb.flush_incoming()
        assert rb.c == ra.c
        # the analysis core survives the same tids off the ring
        events = [dict(e, _src="x") for e in rec.events()]
        pair_latency(events)
        reconstruct_paths(events)

    def test_obs_off_ships_no_context(self):
        """The free-when-off contract: with tracer AND recorder
        disabled, origin frames carry tid/hop but no wire context —
        the obs-off send path pays no encode, no ledger lock."""
        from crdt_tpu.obs.propagation import (
            PropagationLedger,
            set_propagation,
        )

        ledger = set_propagation(PropagationLedger())
        try:
            net = LoopbackNetwork()
            ra = Replica(LoopbackRouter(net, "aaaa"), topic="t",
                         client_id=1)
            rb = Replica(LoopbackRouter(net, "bbbb"), topic="t",
                         client_id=2)
            seen = []
            orig = ra._propagate
            ra._propagate = lambda m: (seen.append(m),
                                       orig(m))[-1]
            ra.set("m", "k", "v")
            net.run()
            updates = [m for m in seen if "update" in m]
            assert updates and all("tc" not in m for m in updates)
            assert all("tid" in m for m in updates)  # tid stays
            assert ledger.report()["contexts_sent"] == 0
            assert rb.c == ra.c
        finally:
            set_propagation(PropagationLedger())

    def test_sampling_zero_attaches_no_context(self, installed,
                                               monkeypatch):
        monkeypatch.setenv("CRDT_TPU_TRACE_SAMPLE", "0")
        tracer, rec, ledger = installed
        net = LoopbackNetwork()
        ra = Replica(LoopbackRouter(net, "aaaa"), topic="t",
                     client_id=1)
        rb = Replica(LoopbackRouter(net, "bbbb"), topic="t",
                     client_id=2)
        ra.set("m", "k", "v")
        net.run()
        sends = [e for e in rec.events()
                 if e["kind"] == "update.send"]
        assert sends and all(e["path"] is None for e in sends)
        assert ledger.report()["contexts_sent"] == 0
        assert rb.c == ra.c  # tid/hop (and delivery) unaffected


# ---------------------------------------------------------------------------
# the relay forward seam: hop=2 with the relay's identity
# ---------------------------------------------------------------------------


class TestRelayedHopIncrement:
    @pytest.mark.slow
    def test_relayed_delivery_records_two_hops(self, installed):
        from crdt_tpu.net.faults import (
            NatFabric,
            SymmetricNat,
            install_nat,
            pump_until,
        )
        from crdt_tpu.net.udp_router import UdpRouter

        tracer, rec, _ = installed
        fabric = NatFabric()
        boot = UdpRouter(rendezvous=True)
        install_nat(boot, fabric)
        kw = dict(dial_retry_s=0.05, port_prediction=False,
                  relay_after_s=0.3)
        a = UdpRouter(bootstrap=[boot.addr], **kw)
        install_nat(a, fabric, SymmetricNat(21000))
        b = UdpRouter(bootstrap=[boot.addr], **kw)
        install_nat(b, fabric, SymmetricNat(23000))
        routers = [boot, a, b]
        try:
            ra = Replica(a, topic="room", client_id=1,
                         probe_retry_s=0.1, anti_entropy_s=0.2)
            rb = Replica(b, topic="room", client_id=2,
                         probe_retry_s=0.1, anti_entropy_s=0.2)
            ra.set("m", "ka", "x" * 32)
            pump_until(
                routers,
                lambda: rb.c.get("m", {}).get("ka") == "x" * 32,
                timeout_s=30.0,
            )
            assert not a._peers[b.public_key].direct  # really relayed
            relayed = [
                e for e in rec.events()
                if e["kind"] == "update.recv" and e.get("path")
                and len(e["path"]) == 2
            ]
            assert relayed, "no two-hop delivery recorded"
            for e in relayed:
                origin, leg2 = e["path"]
                # the origin leg keeps its SEMANTIC tag when it is a
                # sync answer / AE delta; plain broadcasts retag
                # `relayed` at the send seam
                assert origin[1] in ("relayed", "sync_answer",
                                     "anti_entropy")
                assert leg2[1] == "relayed"
                assert leg2[0] == boot.public_key[:8]  # the relay
                assert e["hop"] == 2
            spans = tracer.report()["spans"]
            assert spans[
                'replica.hop_lag{route="relayed"}']["count"] > 0
        finally:
            for r in routers:
                r.close()


# ---------------------------------------------------------------------------
# the shared analysis core (offline == live; obsq is a thin shell)
# ---------------------------------------------------------------------------


def _mk_events():
    return [
        {"ts": 100.0, "kind": "update.send", "tid": [1, 1, 100.0],
         "hop": 0, "path": [["a", "direct", 0]], "_src": "a"},
        {"ts": 100.2, "kind": "update.recv", "tid": [1, 1, 100.0],
         "hop": 1, "path": [["a", "direct", 0]], "_src": "b"},
        {"ts": 100.3, "kind": "update.recv", "tid": [1, 1, 100.0],
         "hop": 2,
         "path": [["a", "relayed", 0], ["r", "relayed", 100_000]],
         "_src": "c"},
        {"ts": 101.0, "kind": "ae.delta", "tid": [2, 1, 101.0],
         "path": [["b", "anti_entropy", 0]], "_src": "b"},
        {"ts": 101.4, "kind": "update.recv", "tid": [2, 1, 101.0],
         "hop": 1, "path": [["b", "anti_entropy", 0]], "_src": "c"},
    ]


class TestAnalysisCore:
    def test_pair_latency_routes_and_percentiles(self):
        lat = pair_latency(_mk_events())
        assert lat["sends"] == 2
        assert lat["pairs"] == 3
        assert lat["unmatched_recv"] == 0
        assert lat["hops"] == {"1": 2, "2": 1}
        assert set(lat["routes"]) == {"direct", "relayed",
                                      "anti_entropy"}
        assert lat["routes"]["relayed"]["count"] == 2
        assert lat["paths"]["pair_rate"] == 1.0

    def test_reconstruct_flags_incomplete(self):
        evs = _mk_events()
        evs.append({"ts": 102.0, "kind": "update.recv",
                    "tid": [9, 9, 102.0], "hop": 1,
                    "path": [["z", "direct", 0]], "_src": "c"})
        out = reconstruct_paths(evs)
        assert out["traced_recvs"] == 4
        assert out["complete"] == 3
        assert out["pair_rate"] == pytest.approx(3 / 4)
        assert out["incomplete_sample"][0]["tid"] == [9, 9, 102.0]
        # hop-count / path-depth mismatch is incomplete too
        evs2 = _mk_events()
        evs2[1]["hop"] = 5
        assert reconstruct_paths(evs2)["complete"] == 2

    def test_correlate_divergences_matches_obsq_shape(self):
        evs = _mk_events()
        evs.append({"ts": 103.0, "kind": "divergence",
                    "topic": None, "local_digest": "xx",
                    "peer_digest": "yy", "_src": "c"})
        out = correlate_divergences(evs, context=2)
        assert out["divergences"] == 1
        assert set(out["events"][0]["context"]) == {"a", "b", "c"}

    def test_unhashable_tids_never_crash_the_core(self):
        evs = _mk_events()
        evs.append({"ts": 200.0, "kind": "update.send",
                    "tid": [[1], {"a": 2}, 3.0], "hop": 0,
                    "path": [["z", "direct", 0]], "_src": "a"})
        evs.append({"ts": 200.1, "kind": "update.recv",
                    "tid": [[1], 2, 3.0], "hop": 1,
                    "path": [["z", "direct", 0]], "_src": "b"})
        lat = pair_latency(evs)  # no TypeError
        out = reconstruct_paths(evs)
        # the unhashable recv is traced but cannot pair: incomplete
        assert out["complete"] == 3
        assert lat["unmatched_recv"] >= 1

    def test_relayed_hostile_context_counts_once(self, installed):
        tracer, _, _ = installed
        evil = b"\xffnot-a-context"
        # the forward seam declines to count (the receiver is the
        # authoritative counter)
        assert P.append_hop_wire(evil, "r", "relayed") == evil
        assert P.retag_last_hop(evil, "relayed") == evil
        c = tracer.report()["counters"]
        assert c.get("propagation.malformed_contexts", 0) == 0
        assert P.decode_or_none(evil) is None  # receiver seam counts
        assert tracer.report()["counters"][
            "propagation.malformed_contexts"] == 1

    def test_proc_tag_is_src_fallback(self):
        # collector events carry `proc=`, obsq events `_src=` — the
        # core accepts either spelling
        evs = [dict(e) for e in _mk_events()]
        for e in evs:
            e["proc"] = e.pop("_src")
        assert reconstruct_paths(evs)["pair_rate"] == 1.0
        assert sorted(
            reconstruct_paths(evs)["origin_procs"]
        ) == ["a", "b"]


class TestLoopbackEndToEndPairRate:
    def test_full_swarm_reconstructs_completely(self, installed):
        tracer, rec, _ = installed
        net = LoopbackNetwork(reorder=True, duplicate=0.1, seed=3)
        reps = [
            Replica(LoopbackRouter(net, f"r{i}"), topic="t",
                    client_id=10 + i)
            for i in range(3)
        ]
        for i, r in enumerate(reps):
            r.set("m", f"k{i}", "v" * 128)
            net.run()
        events = [dict(e, _src="proc") for e in rec.events()]
        out = reconstruct_paths(events)
        assert out["traced_recvs"] > 0
        assert out["pair_rate"] == 1.0
        assert all(reps[0].c == r.c for r in reps)
        # convergence stamp: the ledger saw every traced delivery
        lat = pair_latency(events)
        assert lat["unmatched_recv"] == 0
