"""Targeted anti-entropy: bytes scale with the deficit, not the doc.

Host path: Replica.anti_entropy unicasts SV-diffed updates to exactly
the peers that lack records. Device path: the delta gossip step gathers
only rows above the swarm floor, and the ring step ppermutes each
successor exactly what it lacks (VERDICT r1 item #5).
"""

import jax
import numpy as np
import pytest

from crdt_tpu.net import LoopbackNetwork, LoopbackRouter, ypear_crdt
from crdt_tpu.parallel.delta import (
    make_delta_gossip_step,
    make_ring_delta_step,
    synth_resident_columns,
)
from crdt_tpu.parallel.gossip import make_mesh


# ---------------------------------------------------------------------------
# host path
# ---------------------------------------------------------------------------


def _partition(net, router):
    """Silently detach a router from its topics (delivery blackhole)."""
    saved = {t: list(subs) for t, subs in net.topics.items()}
    for t in net.topics:
        net.topics[t] = [(r, h) for r, h in net.topics[t] if r is not router]
    return saved


class TestHostAntiEntropy:
    def test_bytes_scale_with_deficit(self):
        """The update sent to a lagging peer grows with the number of
        missed ops, not with document size."""
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t", client_id=1)
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t", client_id=2)
        net.run()
        for i in range(400):
            a.set("m", f"k{i}", i)
        net.run()
        assert dict(b.c) == dict(a.c)

        sizes = {}
        for lag in (2, 20, 200):
            saved = _partition(net, b.router)
            for i in range(lag):
                a.set("m", f"fresh{lag}-{i}", i)
            net.topics.update(saved)  # heal the partition
            # no manual SV refresh: a's record of b advanced with the
            # live broadcasts and handshake diffs, and did NOT advance
            # while b was partitioned — the deficit is exact
            sent = a.anti_entropy()
            net.run()
            assert dict(b.c) == dict(a.c), f"lag={lag} did not converge"
            sizes[lag] = sent["b"]
        full = len(a.doc.encode_state_as_update())
        assert sizes[2] < sizes[20] < sizes[200] < full
        # a 2-op delta must be tiny next to the 600+-op document
        assert sizes[2] * 10 < full

    def test_no_deficit_sends_nothing(self):
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t", client_id=1)
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t", client_id=2)
        net.run()
        a.set("m", "k", 1)
        net.run()
        assert a.anti_entropy() == {}

    def test_targets_only_lagging_peers(self):
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t", client_id=1)
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t", client_id=2)
        c = ypear_crdt(LoopbackRouter(net, "c"), topic="t", client_id=3)
        net.run()
        saved = _partition(net, c.router)
        a.set("m", "k", "v")
        net.run()  # b gets it live; c is dark
        net.topics.update(saved)
        sent = a.anti_entropy()
        net.run()
        assert list(sent) == ["c"]  # only the lagging peer got bytes
        assert dict(c.c) == dict(a.c)


# ---------------------------------------------------------------------------
# device path
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8
    return make_mesh(8)


def _cols_args(cols):
    import jax.numpy as jnp

    from crdt_tpu.parallel.delta import COL_NAMES

    return [jnp.asarray(cols[k]) for k in COL_NAMES]


class TestDeviceDelta:
    def test_delta_gossip_ships_only_fresh_rows(self, mesh):
        R, shared, fresh = 8, 96, 8
        budget = 16  # << N = 104: gathered bytes scale with deficit
        cols = synth_resident_columns(R, shared, fresh, seed=1)
        step = make_delta_gossip_step(mesh, num_clients=R + 2, budget=budget)
        out = step(*_cols_args(cols))
        svs, deficit, n_needed = (np.asarray(x) for x in out[:3])
        u = [np.asarray(x) for x in out[3:]]
        u_client, u_clock, u_valid = u[0], u[1], u[8]

        # every replica needed to ship exactly its fresh rows
        np.testing.assert_array_equal(n_needed, np.full(R, fresh))
        # the gathered union is R*budget wide — NOT R*(shared+fresh)
        assert len(u_client) == R * budget
        got = {
            (int(c), int(k))
            for c, k, v in zip(u_client, u_clock, u_valid)
            if v
        }
        want = {(r + 2, k) for r in range(R) for k in range(fresh)}
        assert got == want, "delta union must be exactly the fresh rows"
        # deficit matrix: replicas owe each other exactly `fresh` clocks
        assert deficit[0, 1] == fresh and deficit[5, 2] == fresh
        assert deficit[3, 3] == 0

    def test_delta_gossip_reports_overflow(self, mesh):
        R, shared, fresh = 8, 32, 12
        budget = 4  # too small: needed_count reveals it
        cols = synth_resident_columns(R, shared, fresh, seed=2)
        step = make_delta_gossip_step(mesh, num_clients=R + 2, budget=budget)
        out = step(*_cols_args(cols))
        n_needed = np.asarray(out[2])
        assert (n_needed > budget).all()  # caller must loop / re-bucket
        # shipped rows are still valid, just capped at budget
        u_valid = np.asarray(out[3 + 8])
        assert u_valid.sum() == R * budget

    def test_ring_delta_reaches_successor(self, mesh):
        R, shared, fresh = 8, 40, 6
        cols = synth_resident_columns(R, shared, fresh, seed=3)
        step = make_ring_delta_step(mesh, num_clients=R + 2, budget=8)
        out = step(*_cols_args(cols))
        sent = np.asarray(out[0])
        recv_client = np.asarray(out[1])
        recv_valid = np.asarray(out[9])
        np.testing.assert_array_equal(sent, np.full(R, fresh))
        for r in range(R):
            pred = (r - 1) % R
            got = {
                int(c) for c, v in zip(recv_client[r], recv_valid[r]) if v
            }
            # predecessor's fresh rows are client pred+2
            assert got == {pred + 2}, f"replica {r} got {got}"
            assert recv_valid[r].sum() == fresh
