"""Differential tests: YATA ordering kernel vs host oracle."""

import random

from crdt_tpu.core.engine import Engine
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.store import TYPE_ARRAY
from crdt_tpu.ops.yata import order_sequences


def union_of(engines):
    recs, ds = [], DeleteSet()
    for e in engines:
        recs.extend(e.records_since(None))
        ds = ds.merge(e.delete_set())
    return recs, ds


def check(engines):
    recs, _ = union_of(engines)
    got = order_sequences(recs)
    oracle = Engine(10**6)
    for e in engines:
        oracle.apply_records(e.records_since(None), e.delete_set())
    want = oracle.seq_order_table()
    # kernel covers sequences only; oracle table may also hold map-less
    # parents — compare on shared parents (sequence parents)
    want = {k: v for k, v in want.items() if v}
    got = {k: v for k, v in got.items() if v}
    assert got == want, (
        f"kernel order diverges\nkernel: {got}\noracle: {want}"
    )
    return oracle


def test_single_author_chain():
    e = Engine(1)
    e.seq_insert("s", 0, list(range(20)))
    check([e])


def test_prepends_and_inserts():
    e = Engine(1)
    e.seq_insert("s", 0, ["a"])
    e.seq_insert("s", 0, ["b"])  # prepend: right origin = a
    e.seq_insert("s", 1, ["c"])  # between b and a
    e.seq_insert("s", 0, ["d"])
    check([e])


def test_concurrent_same_position():
    a, b, c = Engine(1), Engine(2), Engine(3)
    a.seq_insert("s", 0, ["base0", "base1"])
    for e in (b, c):
        e.apply_records(a.records_since(None), a.delete_set())
    a.seq_insert("s", 1, ["A1", "A2"])
    b.seq_insert("s", 1, ["B1"])
    c.seq_insert("s", 1, ["C1", "C2", "C3"])
    check([a, b, c])


def test_concurrent_prepends():
    a, b = Engine(1), Engine(2)
    a.seq_insert("s", 0, ["x"])
    b.apply_records(a.records_since(None), a.delete_set())
    a.seq_insert("s", 0, ["a-pre"])
    b.seq_insert("s", 0, ["b-pre"])
    check([a, b])


def test_insert_into_received_run():
    a, b = Engine(1), Engine(2)
    a.seq_insert("s", 0, ["r0", "r1", "r2", "r3"])
    b.apply_records(a.records_since(None), a.delete_set())
    b.seq_insert("s", 2, ["mid"])  # splits a's run
    a.seq_insert("s", 2, ["also-mid"])  # concurrent split at same spot
    check([a, b])


def test_deletes_do_not_change_chain_order():
    a, b = Engine(1), Engine(2)
    a.seq_insert("s", 0, ["a", "b", "c", "d"])
    b.apply_records(a.records_since(None), a.delete_set())
    a.seq_delete("s", 1, 2)
    b.seq_insert("s", 3, ["after-c"])  # b still sees all four
    check([a, b])


def test_nested_sequences():
    a, b = Engine(1), Engine(2)
    a.map_set_type("m", "lst", TYPE_ARRAY)
    spec = a.map_entry_spec("m", "lst")
    a.seq_insert("", 0, [1, 2], parent=spec)
    b.apply_records(a.records_since(None), a.delete_set())
    bspec = b.map_entry_spec("m", "lst")
    b.seq_insert("", 1, [99], parent=bspec)
    a.seq_insert("", 2, [77], parent=spec)
    check([a, b])


def _seq_fuzz_op(rng, e, peers):
    k = rng.randrange(5)
    if k == 0:
        n = len(e.seq_json("s"))
        e.seq_insert(
            "s", rng.randint(0, n), [rng.randrange(1000) for _ in range(rng.randint(1, 4))]
        )
    elif k == 1:
        n = len(e.seq_json("s"))
        if n:
            e.seq_delete("s", rng.randrange(n), min(n, rng.randint(1, 3)))
    elif k == 2:
        n = len(e.seq_json("t"))
        e.seq_insert("t", rng.randint(0, n), [rng.randrange(1000)])
    elif k == 3:
        src = rng.choice(peers)
        if src is not e:
            e.apply_records(src.records_since(None), src.delete_set())
    else:
        n = len(e.seq_json("s"))
        e.seq_insert("s", 0 if n == 0 else rng.choice([0, n]), ["edge"])


def test_fuzz_sequences_vs_oracle():
    rng = random.Random(4242)
    for trial in range(12):
        engines = [Engine(i + 1) for i in range(rng.choice([2, 3, 5]))]
        for _ in range(120):
            _seq_fuzz_op(rng, rng.choice(engines), engines)
        check(engines)


def test_fuzz_mixed_maps_and_sequences():
    from tests.test_engine import _random_op

    rng = random.Random(31337)
    for trial in range(6):
        engines = [Engine(i + 1) for i in range(3)]
        for _ in range(150):
            _random_op(rng, rng.choice(engines), engines)
        check(engines)


def test_gc_origin_items_are_dropped():
    """An item whose origin is a GC filler never joins the chain.

    The engine splices it after a chain-less row (its head walk omits
    it); the kernel must drop it — and its subtree — rather than rank
    it against the segment root.
    """
    from crdt_tpu.core.records import ItemRecord
    from crdt_tpu.core.store import K_GC

    recs = [
        ItemRecord(client=1, clock=0, kind=K_GC),  # GC'd history
        ItemRecord(client=1, clock=1, parent_root="s", origin=(1, 0)),
        ItemRecord(client=1, clock=2, parent_root="s", origin=(1, 1)),
        ItemRecord(client=2, clock=0, parent_root="s", content="live"),
    ]
    got = order_sequences(recs)
    assert got == {("root", "s"): [(2, 0)]}


def test_same_client_duplicates_stay_on_device(monkeypatch):
    """Same-client same-origin siblings with NO in-group right origins
    (the shape left behind when right origins were GC'd or pruned)
    order clock-DESC via the (client, ~clock) device key — the host
    group scan must NOT run (VERDICT r1 item #7: the fallback is
    attachment groups only)."""
    import crdt_tpu.ops.yata as yata
    from crdt_tpu.core.records import ItemRecord

    def boom(*a, **k):
        raise AssertionError("host scan ran for an attachment-free group")

    monkeypatch.setattr(yata, "_simulate_group", boom)

    recs = [
        ItemRecord(client=1, clock=0, parent_root="s", content="base0"),
        ItemRecord(client=1, clock=1, parent_root="s", origin=(1, 0),
                   content="base1"),
    ]
    # client 2 lands three siblings under base0, rights absent
    for k in range(3):
        recs.append(ItemRecord(client=2, clock=k, parent_root="s",
                               origin=(1, 0), content=f"dup{k}"))
    got = order_sequences(recs)
    oracle = Engine(10**6)
    oracle.apply_records(recs)
    assert got == oracle.seq_order_table()
    # the break rule: later same-client siblings come FIRST
    assert got[("root", "s")] == [(1, 0), (1, 1), (2, 2), (2, 1), (2, 0)]


def test_attachment_groups_still_exact(monkeypatch):
    """Groups with in-group right origins still route through the host
    scan — and produce the oracle order."""
    import crdt_tpu.ops.yata as yata

    calls = []
    real = yata._simulate_group

    def spy(sibs, ids):
        calls.append(len(sibs))
        return real(sibs, ids)

    monkeypatch.setattr(yata, "_simulate_group", spy)

    a, b = Engine(1), Engine(2)
    a.seq_insert("s", 0, ["x"])
    b.apply_records(a.records_since(None))
    # a prepends (right origin = x), b prepends too: b's item's right
    # origin is a member of the same (virtual-root) group as a's
    a.seq_insert("s", 0, ["a0"])
    b.seq_insert("s", 0, ["b0"])
    check([a, b])
    assert calls, "attachment group should have used the host scan"


def test_fuzz_duplicate_heavy_no_host_scan(monkeypatch):
    """Random right-less unions — heavy same-origin duplication across
    and within clients — must order entirely on device and match the
    oracle."""
    import crdt_tpu.ops.yata as yata
    from crdt_tpu.core.records import ItemRecord

    def boom(*a, **k):
        raise AssertionError("host scan ran for an attachment-free group")

    monkeypatch.setattr(yata, "_simulate_group", boom)

    rng = random.Random(13)
    for trial in range(5):
        recs = [ItemRecord(client=1, clock=0, parent_root="s", content=0)]
        for k in range(1, 6):
            recs.append(ItemRecord(client=1, clock=k, parent_root="s",
                                   origin=(1, k - 1), content=k))
        for client in (2, 3, 4):
            for k in range(rng.randint(3, 10)):
                origin = (1, rng.randint(0, 5))  # duplicate-rich
                recs.append(ItemRecord(client=client, clock=k,
                                       parent_root="s", origin=origin,
                                       content=(client, k)))
        got = order_sequences(recs)
        oracle = Engine(10**6)
        oracle.apply_records(recs)
        assert got == oracle.seq_order_table(), f"trial {trial} diverged"


def test_fuzz_mixed_rights_duplicates_and_attachments():
    """Adversarial sibling soup: same-origin duplicates across and
    within clients, right origins pointing OUTSIDE the group (ignored
    by the attachment check), and true in-group anchors — kernel wrapper
    must match the oracle on all of it."""
    from crdt_tpu.core.records import ItemRecord

    rng = random.Random(31)
    for trial in range(8):
        recs = [ItemRecord(client=1, clock=0, parent_root="s", content=0)]
        for k in range(1, 5):
            recs.append(ItemRecord(client=1, clock=k, parent_root="s",
                                   origin=(1, k - 1), content=k))
        ids = [(1, k) for k in range(5)]
        for client in (2, 3, 4):
            for k in range(rng.randint(2, 8)):
                origin = ids[rng.randrange(len(ids))]
                # rights: absent, an existing id (possible in-group
                # anchor), or a dangling id never integrated
                roll = rng.random()
                if roll < 0.4:
                    right = None
                elif roll < 0.8:
                    right = ids[rng.randrange(len(ids))]
                else:
                    right = (99, rng.randrange(50))
                rec = ItemRecord(client=client, clock=k, parent_root="s",
                                 origin=origin, right=right,
                                 content=(client, k))
                recs.append(rec)
                ids.append(rec.id)
        got = order_sequences(recs)
        oracle = Engine(10**6)
        oracle.apply_records(recs)
        want = oracle.seq_order_table()
        assert got == want, f"trial {trial} diverged"
