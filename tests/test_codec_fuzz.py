"""Adversarial decoder fuzz: malformed v1 blobs fail CLOSED.

Random truncations, bit mutations, and splices of valid v1 update
blobs, applied to both the raw decoder and the full document apply
path, must uphold three contracts:

- ``ValueError`` only — never a hang, never any other exception type
  (an AssertionError/KeyError escaping the decode seam means a
  crafted blob can kill a replica's poll loop);
- all-or-nothing per blob — a rejected update leaves the document
  byte-identical (state, pending stash, delete set): partial mutation
  would silently fork replicas;
- bounded cost — the corpus is seeded and fixed-size so this stays
  tier-1 (the decoder's expansion budget, pinned elsewhere, is what
  makes "never hang" hold for the hostile-length family).

A mutant that still decodes cleanly is FINE (bit flips can land in
content bytes); the contracts above are about the rejects.
"""

import random

from crdt_tpu.api.doc import Crdt
from crdt_tpu.codec import v1


def _corpus():
    """Deterministic blobs covering every struct family: map sets,
    nested arrays, sequence runs, deletes, GC-able history, plus a
    full-state snapshot (the densest wire shape)."""
    src = Crdt(7)
    blobs = []
    src.on_update = lambda u, m: blobs.append(u)
    src.set("m", "k1", {"a": [1, 2], "b": None})
    src.set("m", "k2", "v" * 40)
    src.push("l", ["x", "y", "z"])
    src.insert("l", 1, "mid")
    src.cut("l", 0, 2)
    src.delete("m", "k2")
    src.set("nest", "arr", [9, 8], array_method="push")
    src.set("nest", "arr", 7, array_method="insert", index=1)
    blobs.append(src.encode_state_as_update())
    return blobs


def _mutants(blobs, rng, per_blob=60):
    for blob in blobs:
        for _ in range(per_blob):
            b = bytearray(blob)
            op = rng.randrange(3)
            if op == 0 and len(b) > 1:  # truncation
                yield bytes(b[: rng.randrange(1, len(b))])
            elif op == 1:  # bit mutation (1-3 flips)
                for _ in range(rng.randrange(1, 4)):
                    b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
                yield bytes(b)
            else:  # splice two blobs at random offsets
                other = blobs[rng.randrange(len(blobs))]
                cut = rng.randrange(1, len(b) + 1)
                yield bytes(b[:cut]) + other[rng.randrange(len(other)):]


def _doc_fingerprint(doc):
    return (
        doc.encode_state_as_update(),
        doc.encode_state_vector(),
        [r.id for r in doc.engine.pending],
        sorted(doc.engine.pending_deletes.ranges.items()),
    )


def test_fuzzed_blobs_raise_value_error_only_and_never_partially_apply():
    blobs = _corpus()
    rng = random.Random(20260803)
    base = blobs[0]

    checked = rejected = 0
    for m in _mutants(blobs, rng):
        checked += 1
        # raw decoder: ValueError is the whole error contract
        try:
            v1.decode_update(m)
        except ValueError:
            pass

        # full apply path (native codec when available): rejected
        # blobs must leave the doc byte-identical — state, SV,
        # pending stash, pending deletes
        doc = Crdt(9)
        doc.apply_update(base)
        before = _doc_fingerprint(doc)
        try:
            doc.apply_update(m)
        except ValueError:
            rejected += 1
            assert _doc_fingerprint(doc) == before
    assert checked == 540
    # the corpus is adversarial enough that most mutants reject
    assert rejected > checked // 4, (checked, rejected)


def test_fuzzed_single_records_keep_engine_consistent():
    """Mutants that DO decode must still integrate without raising
    anything but ValueError — and an integrated mutant's doc must
    re-encode to a decodable update (no poisoned re-export)."""
    blobs = _corpus()
    rng = random.Random(77)
    for m in _mutants(blobs, rng, per_blob=20):
        try:
            records, ds = v1.decode_update(m)
        except ValueError:
            continue
        doc = Crdt(9)
        try:
            doc.apply_update(m)
        except ValueError:
            continue
        v1.decode_update(doc.encode_state_as_update())
