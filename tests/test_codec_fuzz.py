"""Adversarial decoder fuzz: malformed v1 blobs fail CLOSED.

Random truncations, bit mutations, and splices of valid v1 update
blobs, applied to both the raw decoder and the full document apply
path, must uphold three contracts:

- ``ValueError`` only — never a hang, never any other exception type
  (an AssertionError/KeyError escaping the decode seam means a
  crafted blob can kill a replica's poll loop);
- all-or-nothing per blob — a rejected update leaves the document
  byte-identical (state, pending stash, delete set): partial mutation
  would silently fork replicas;
- bounded cost — the corpus is seeded and fixed-size so this stays
  tier-1 (the decoder's expansion budget, pinned elsewhere, is what
  makes "never hang" hold for the hostile-length family).

A mutant that still decodes cleanly is FINE (bit flips can land in
content bytes); the contracts above are about the rejects.
"""

import random

import pytest

from crdt_tpu.api.doc import Crdt
from crdt_tpu.codec import v1
from crdt_tpu.codec.lib0 import Decoder, Encoder


def _corpus():
    """Deterministic blobs covering every struct family: map sets,
    nested arrays, sequence runs, deletes, GC-able history, plus a
    full-state snapshot (the densest wire shape)."""
    src = Crdt(7)
    blobs = []
    src.on_update = lambda u, m: blobs.append(u)
    src.set("m", "k1", {"a": [1, 2], "b": None})
    src.set("m", "k2", "v" * 40)
    src.push("l", ["x", "y", "z"])
    src.insert("l", 1, "mid")
    src.cut("l", 0, 2)
    src.delete("m", "k2")
    src.set("nest", "arr", [9, 8], array_method="push")
    src.set("nest", "arr", 7, array_method="insert", index=1)
    blobs.append(src.encode_state_as_update())
    return blobs


def _mutants(blobs, rng, per_blob=60):
    for blob in blobs:
        for _ in range(per_blob):
            b = bytearray(blob)
            op = rng.randrange(3)
            if op == 0 and len(b) > 1:  # truncation
                yield bytes(b[: rng.randrange(1, len(b))])
            elif op == 1:  # bit mutation (1-3 flips)
                for _ in range(rng.randrange(1, 4)):
                    b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
                yield bytes(b)
            else:  # splice two blobs at random offsets
                other = blobs[rng.randrange(len(blobs))]
                cut = rng.randrange(1, len(b) + 1)
                yield bytes(b[:cut]) + other[rng.randrange(len(other)):]


def _doc_fingerprint(doc):
    return (
        doc.encode_state_as_update(),
        doc.encode_state_vector(),
        [r.id for r in doc.engine.pending],
        sorted(doc.engine.pending_deletes.ranges.items()),
    )


def test_fuzzed_blobs_raise_value_error_only_and_never_partially_apply():
    blobs = _corpus()
    rng = random.Random(20260803)
    base = blobs[0]

    checked = rejected = 0
    for m in _mutants(blobs, rng):
        checked += 1
        # raw decoder: ValueError is the whole error contract
        try:
            v1.decode_update(m)
        except ValueError:
            pass

        # full apply path (native codec when available): rejected
        # blobs must leave the doc byte-identical — state, SV,
        # pending stash, pending deletes
        doc = Crdt(9)
        doc.apply_update(base)
        before = _doc_fingerprint(doc)
        try:
            doc.apply_update(m)
        except ValueError:
            rejected += 1
            assert _doc_fingerprint(doc) == before
    assert checked == 540
    # the corpus is adversarial enough that most mutants reject
    assert rejected > checked // 4, (checked, rejected)


def test_fuzzed_single_records_keep_engine_consistent():
    """Mutants that DO decode must still integrate without raising
    anything but ValueError — and an integrated mutant's doc must
    re-encode to a decodable update (no poisoned re-export)."""
    blobs = _corpus()
    rng = random.Random(77)
    for m in _mutants(blobs, rng, per_blob=20):
        try:
            records, ds = v1.decode_update(m)
        except ValueError:
            continue
        doc = Crdt(9)
        try:
            doc.apply_update(m)
        except ValueError:
            continue
        v1.decode_update(doc.encode_state_as_update())


# ---------------------------------------------------------------------------
# round-17 targeted mutants: one per CL10xx/CL11xx finding the
# wire-taint pass surfaced and FIXED (crdtlint tentpole). Each pins
# ValueError-only with byte-identical doc/SV/pending on reject.


def _sv_blob(pairs):
    """Hand-rolled state-vector wire blob (numClients, then
    client/clock varuints) — bypasses encode_state_vector so hostile
    values can be written at all."""
    e = Encoder()
    e.write_var_uint(len(pairs))
    for client, clock in pairs:
        e.write_var_uint(client)
        e.write_var_uint(clock)
    return e.to_bytes()


def test_hostile_state_vector_bounds_rejected():
    """CL1001 fix (oversized varint ids): decode_state_vector now
    fences client (< 2^62, the int64-wrap band shared with the native
    codec) and clock (< 2^40, the kernel clock-packing bound). Before
    the round-17 fix these decoded cleanly and the huge ints flowed
    into device staging (statevec deficits, shard boundary exchange),
    where 2^63 overflows int64 — a crash vector no ValueError guard
    ever saw."""
    hostile = (
        [(1 << 62, 5)],            # client at the rejection band
        [(1 << 63, 5)],            # client that wraps int64 negative
        [((1 << 64) - 1, 5)],      # the native codec's -1 sentinel
        [(7, 1 << 40)],            # clock at the kernel packing bound
        [(7, 1 << 62)],            # clock that overflows staging
    )
    for pairs in hostile:
        with pytest.raises(ValueError):
            v1.decode_state_vector(_sv_blob(pairs))
    # honest boundary values stay decodable (off-by-one guard)
    sv = v1.decode_state_vector(
        _sv_blob([((1 << 62) - 1, (1 << 40) - 1)])
    )
    assert sv.clocks == {(1 << 62) - 1: (1 << 40) - 1}


def test_state_vector_trailing_bytes_rejected():
    """The SV decoder now mirrors decode_update's trailing-bytes
    strictness: a valid SV with appended garbage fails closed."""
    good = _sv_blob([(7, 3)])
    assert v1.decode_state_vector(good).clocks == {7: 3}
    with pytest.raises(ValueError):
        v1.decode_state_vector(good + b"\x01")


def test_negative_byte_count_cannot_rewind_decoder():
    """CL1101-family fix (negative-after-sign-decode count): a
    negative n passed the old `pos + n > len` pre-check, returned a
    truncated slice, and REWOUND the cursor (pos += n) — a decoder
    loop could re-read the same bytes forever. The pre-check now
    fences the sign."""
    d = Decoder(b"abcdef")
    d.read_bytes(2)
    with pytest.raises(ValueError):
        d.read_bytes(-1)
    assert d.pos == 2  # cursor did not move, let alone rewind


def test_declared_string_length_past_buffer_rejected_atomically():
    """Splice-offset-past-buffer mutant: a ContentString struct whose
    varUint byte-length prefix declares more bytes than the blob
    carries must raise ValueError and leave an applying doc
    byte-identical (the round-10 all-or-nothing contract, re-pinned
    for the length-prefix family the wire-taint checker fences)."""
    e = Encoder()
    e.write_var_uint(1)        # numClients
    e.write_var_uint(1)        # numStructs
    e.write_var_uint(5)        # client
    e.write_var_uint(0)        # clock
    e.write_uint8(v1.REF_STRING)  # no origin/right -> parent written
    e.write_var_uint(1)        # parent is a root
    e.write_var_string("m")
    e.write_var_uint(1000)     # declared string length...
    e.write_bytes(b"abc")      # ...but only 3 bytes follow
    blob = e.to_bytes()
    with pytest.raises(ValueError):
        v1.decode_update(blob)

    doc = Crdt(9)
    doc.apply_update(_corpus()[0])
    before = _doc_fingerprint(doc)
    with pytest.raises(ValueError):
        doc.apply_update(blob)
    assert _doc_fingerprint(doc) == before


def test_oversized_gc_run_length_bounded_by_budget():
    """Oversized-varint-length mutant: a GC run declaring 2^39 units
    (inside the clock bound, far past any honest compaction) must hit
    the buffer-derived expansion budget — ValueError, no hang, no
    multi-gigabyte record list (the CL1002/CL1101 discipline the
    decode-allocation checker enforces statically)."""
    e = Encoder()
    e.write_var_uint(1)        # numClients
    e.write_var_uint(1)        # numStructs
    e.write_var_uint(5)        # client
    e.write_var_uint(0)        # clock
    e.write_uint8(v1.REF_GC)
    e.write_var_uint(1 << 39)  # hostile run length
    e.write_var_uint(0)        # empty delete set
    blob = e.to_bytes()
    with pytest.raises(ValueError):
        v1.decode_update(blob)

    doc = Crdt(9)
    doc.apply_update(_corpus()[0])
    before = _doc_fingerprint(doc)
    with pytest.raises(ValueError):
        doc.apply_update(blob)
    assert _doc_fingerprint(doc) == before


def test_replica_survives_hostile_peer_state_vector():
    """The net-seam half of the CL1001 fix: a beacon / sync-ready
    message carrying a hostile SV must degrade like a malformed
    update (counted, recorded, dropped) — not raise out of the
    router's poll loop. Pre-round-17 the hostile SV decoded cleanly
    and poisoned peer_state_vectors instead."""
    from crdt_tpu.net.router import LoopbackNetwork, LoopbackRouter
    from crdt_tpu.net.replica import ypear_crdt
    from crdt_tpu.obs.tracer import Tracer, get_tracer, set_tracer

    old_tracer = get_tracer()
    set_tracer(Tracer(enabled=True))
    net = LoopbackNetwork()
    a = ypear_crdt(LoopbackRouter(net, "a"), topic="t", client_id=1)
    b = ypear_crdt(LoopbackRouter(net, "b"), topic="t", client_id=2)
    net.run()
    a.set("m", "k", 1)
    net.run()
    assert dict(b.c)["m"]["k"] == 1

    try:
        hostile = _sv_blob([(1 << 63, 5)])
        # ready probe and beacon, both carrying the hostile SV: the
        # handler must swallow (ValueError isolated), not propagate
        a._on_data(
            {"meta": "ready", "public_key": "b",
             "state_vector": hostile},
            "b",
        )
        a._on_data(
            {"meta": "beacon", "public_key": "b",
             "state_vector": hostile, "digest": "", "ds_digest": ""},
            "b",
        )
        got = get_tracer().counters().get("replica.malformed_updates", 0)
        assert got == 2
        # the hostile SV never landed in the peer ledger
        assert all(
            c < (1 << 62)
            for sv in a.peer_state_vectors.values() for c in sv.clocks
        )
        # the swarm still works
        a.set("m", "k2", 2)
        net.run()
        assert dict(b.c)["m"]["k2"] == 2
    finally:
        set_tracer(old_tracer)


def test_replica_rejects_non_bytes_state_vector_payloads():
    """Review fix: lib0 `any` payloads can put str/int/None where SV
    bytes belong. A non-bytes state_vector must degrade like a
    malformed update — `bytes(2**40)` inside the decoder would BE the
    allocation bomb, and a str raises TypeError, not ValueError."""
    from crdt_tpu.net.router import LoopbackNetwork, LoopbackRouter
    from crdt_tpu.net.replica import ypear_crdt
    from crdt_tpu.obs.tracer import Tracer, get_tracer, set_tracer

    old_tracer = get_tracer()
    set_tracer(Tracer(enabled=True))
    try:
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t", client_id=1)
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t", client_id=2)
        net.run()
        for payload in ("abc", 1 << 40, None, 3.5, [1, 2]):
            a._on_data(
                {"meta": "ready", "public_key": "b",
                 "state_vector": payload},
                "b",
            )
        # the sync-contract hook is held to the same admission check
        a.set_peer_state_vector("b", "not-bytes")
        assert get_tracer().counters()[
            "replica.malformed_updates"
        ] == 6
        a.set("m", "k", 1)
        net.run()
        assert dict(b.c)["m"]["k"] == 1
    finally:
        set_tracer(old_tracer)


def test_replica_survives_keyless_protocol_messages():
    """Review fix round 2: a wire-valid envelope missing the
    state_vector (or public_key) key entirely must reject through the
    same admission check — msg[...] KeyError would kill the poll loop
    before the value fence ever ran."""
    from crdt_tpu.net.router import LoopbackNetwork, LoopbackRouter
    from crdt_tpu.net.replica import ypear_crdt
    from crdt_tpu.obs.tracer import Tracer, get_tracer, set_tracer

    old_tracer = get_tracer()
    set_tracer(Tracer(enabled=True))
    try:
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t", client_id=1)
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t", client_id=2)
        net.run()
        a._on_data({"meta": "beacon"}, "b")
        a._on_data({"meta": "ready"}, "b")
        a._on_data({"meta": "beacon", "public_key": "b",
                    "digest": "", "ds_digest": ""}, "b")
        assert get_tracer().counters()[
            "replica.malformed_updates"
        ] == 3
        a.set("m", "k", 1)
        net.run()
        assert dict(b.c)["m"]["k"] == 1
    finally:
        set_tracer(old_tracer)


# ---------------------------------------------------------------------------
# round 19: trace-context wire fuzz — the `tc` frame field fails CLOSED
# ---------------------------------------------------------------------------


def _context_corpus():
    """Deterministic valid trace-context blobs across the shape
    space: every route tag, empty through max-hops paths, big tids
    and deltas."""
    from crdt_tpu.obs import propagation as P

    blobs = []
    for i, route in enumerate(P.ROUTES):
        ctx = P.start_context(7 + i, 1 + i, f"proc{i:04d}", route,
                              ts=100.0 + i)
        blobs.append(P.encode_context(ctx))
        for h in range(P.max_hops() - 1):
            P.append_hop(ctx, f"fwd{h}", "relayed",
                         10_000 * (h + 1))
        blobs.append(P.encode_context(ctx))
    ctx = P.start_context(2**31 - 1, 2**40, "x" * 16, "direct",
                          ts=1e9)
    P.append_hop(ctx, "y" * 16, "anti_entropy", 2**40)
    blobs.append(P.encode_context(ctx))
    return blobs


def test_fuzzed_trace_contexts_raise_value_error_only():
    """Seeded truncation / bit-flip / splice mutants of valid
    contexts: ValueError is the ONLY legal reject (anything else
    escaping the decode seam would kill a router poll loop), and a
    mutant that still decodes must decode to an in-bounds context."""
    from crdt_tpu.obs import propagation as P

    blobs = _context_corpus()
    rng = random.Random(20260804)
    checked = rejected = 0
    for blob in blobs:
        for _ in range(80):
            b = bytearray(blob)
            op = rng.randrange(3)
            if op == 0 and len(b) > 1:
                m = bytes(b[: rng.randrange(1, len(b))])
            elif op == 1:
                for _ in range(rng.randrange(1, 4)):
                    b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
                m = bytes(b)
            else:
                other = blobs[rng.randrange(len(blobs))]
                cut = rng.randrange(1, len(b) + 1)
                m = bytes(b[:cut]) + other[rng.randrange(len(other)):]
            checked += 1
            try:
                ctx = P.decode_context(m)
            except ValueError:
                rejected += 1
                continue
            # survivors uphold every bound the decoder promises
            assert len(ctx.hops) <= P.max_hops()
            for replica, route, delta in ctx.hops:
                assert route in P.ROUTES
                assert len(replica) <= P.MAX_REPLICA_ID
                assert 0 <= delta < 2**53
    assert checked >= 800
    assert rejected > checked // 4  # the corpus genuinely bites


def test_hostile_context_families_reject():
    """The named hostile families from the round-19 contract:
    oversized hop lists, negative ts-deltas, non-bytes payloads,
    allocation-bomb blobs — each rejects with ValueError."""
    from crdt_tpu.codec.lib0 import Encoder
    from crdt_tpu.obs import propagation as P

    def header(n_hops):
        enc = Encoder()
        enc.write_uint8(1)
        enc.write_var_uint(3)
        enc.write_var_uint(4)
        enc.write_float64(1.0)
        enc.write_var_uint(n_hops)
        return enc

    # oversized hop list (declared count past the protocol bound,
    # with enough real bytes that only the bound can reject it)
    enc = header(P.max_hops() + 1)
    for _ in range(P.max_hops() + 1):
        enc.write_var_string("ab")
        enc.write_uint8(0)
        enc.write_var_int(1)
    with pytest.raises(ValueError):
        P.decode_context(enc.to_bytes())
    # negative ts-delta
    enc = header(1)
    enc.write_var_string("ab")
    enc.write_uint8(0)
    enc.write_var_int(-1)
    with pytest.raises(ValueError, match="negative"):
        P.decode_context(enc.to_bytes())
    # non-bytes payloads
    for bad in (None, "s", 0, 1.5, [b"x"], {}, object()):
        with pytest.raises(ValueError, match="not bytes"):
            P.decode_context(bad)
    # allocation bomb: a huge declared blob rejects on size before
    # any field parses
    with pytest.raises(ValueError, match="wire bound"):
        P.decode_context(b"\x01" * (P.MAX_CONTEXT_BYTES + 1))


def test_hostile_contexts_never_kill_the_poll_loop():
    """Replica-level integration: updates carrying every hostile
    context family apply cleanly (the context drops, counted; the
    payload integrates; later traffic flows) — the same degrade-not-
    die contract as malformed updates."""
    from crdt_tpu.net.replica import ypear_crdt
    from crdt_tpu.net.router import LoopbackNetwork, LoopbackRouter
    from crdt_tpu.obs.tracer import Tracer, get_tracer, set_tracer

    old_tracer = get_tracer()
    set_tracer(Tracer(enabled=True))
    try:
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t",
                       client_id=1)
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t",
                       client_id=2)
        net.run()
        rng = random.Random(42)
        hostiles = [b"", b"\x00", b"\xff" * 40, "str", 99, [1],
                    b"\x01" + bytes(rng.randrange(256)
                                    for _ in range(500))]
        a.set("m", "base", 0)
        net.run()
        blob = a.doc.encode_state_as_update()
        for i, evil in enumerate(hostiles):
            b._on_data({"update": blob, "tid": [1, 100 + i, 0.0],
                        "hop": 0, "tc": evil}, "a")
        b.flush_incoming()
        counters = get_tracer().counters()
        assert counters["propagation.malformed_contexts"] >= \
            len(hostiles) - 1  # b"" et al: every non-decodable shape
        a.set("m", "after", 1)
        net.run()
        assert dict(b.c)["m"]["after"] == 1
    finally:
        set_tracer(old_tracer)
