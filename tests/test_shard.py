"""Differential suite for the round-13 multi-chip sharded converge.

The sharded route (:mod:`crdt_tpu.ops.shard` — whole-segment
partition, ONE shard_map program over the 8-device virtual CPU mesh,
boundary-only exchange) must be BYTE-identical to the single-chip
packed oracle on every leg: caches, snapshots, and the exchanged
state vectors — at 2/4/8-way, across one-shot/stream/fleet routes,
including boundary-straddling segments, empty shards, delete-only
updates, right origins, and the chain-split seam at every width. The
chain-split ROUNDS reduction is pinned via the
``converge.wyllie_rounds`` gauge on a deep-chain trace.
"""

import os

import jax
import numpy as np
import pytest

from crdt_tpu.codec import v1
from crdt_tpu.core.engine import Engine
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.models import replay as rp
from crdt_tpu.obs import Tracer, get_tracer, set_tracer
from crdt_tpu.ops import packed
from crdt_tpu.ops import shard


@pytest.fixture(autouse=True)
def _eight_devices():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"


@pytest.fixture(autouse=True)
def _no_ambient_sharding(monkeypatch):
    # each test opts in explicitly; the ambient env must not flip the
    # oracle legs onto the route under test
    monkeypatch.delenv(shard.SHARD_ENV, raising=False)
    monkeypatch.delenv(shard.MIN_ROWS_ENV, raising=False)
    monkeypatch.delenv(packed._CHAIN_SPLIT_ENV, raising=False)


def chains_trace(n_chains=12, chain_len=120, n_maps=2, deletes=True,
                 rights=False, seed=0):
    """Per-replica blobs: own-chain appends over several lists (the
    chain-split shape), map sets, optional tombstones and right
    origins (mid-inserts)."""
    rng = np.random.default_rng(seed)
    blobs = []
    for c in range(n_chains):
        client = c + 1
        recs = []
        prev = None
        chain = []
        for k in range(chain_len):
            if rights and chain and k % 17 == 5:
                j = int(rng.integers(0, len(chain)))
                recs.append(ItemRecord(
                    client=client, clock=k, parent_root=f"l{c % 3}",
                    origin=chain[j - 1] if j > 0 else None,
                    right=chain[j], content=k,
                ))
                chain.insert(j, (client, k))
            else:
                recs.append(ItemRecord(
                    client=client, clock=k, parent_root=f"l{c % 3}",
                    origin=(client, prev) if prev is not None else None,
                    content=int(c * chain_len + k),
                ))
                chain.append((client, k))
            prev = k
        for k in range(n_maps * 4):
            recs.append(ItemRecord(
                client=client, clock=chain_len + k,
                parent_root=f"m{k % n_maps}", key=f"k{k % 7}",
                content=k,
            ))
        ds = DeleteSet()
        if deletes:
            for k in rng.choice(chain_len, size=chain_len // 15,
                                replace=False):
                ds.add(client, int(k))
        blobs.append(v1.encode_update(recs, ds))
    return blobs


def stage_all(blobs):
    dec = rp.decode(blobs)
    cols, ds = rp.stage(dec)
    return dec, cols, ds


def run_single(dec, cols, ds):
    plan = packed.stage(cols)
    assert plan is not None
    res = packed.converge(plan)
    w, v, o = rp.gather(dec, ds, ("packed", res))
    return rp.materialize(dec, ds, w, v, o)


def run_sharded(dec, cols, ds, K):
    splan = shard.stage(cols, n_shards=K)
    assert splan is not None, f"sharded staging refused at K={K}"
    res = shard.converge(splan)
    w, v, o = rp.gather(dec, ds, ("packed", res))
    return rp.materialize(dec, ds, w, v, o), res


def expected_sv(cols, res):
    """The boundary exchange's merged SV vs the host ground truth."""
    cl = np.asarray(cols["client"])[np.asarray(cols["valid"], bool)]
    ck = np.asarray(cols["clock"])[np.asarray(cols["valid"], bool)]
    for i, c in enumerate(res.sv_clients):
        assert res.global_sv[i] == ck[cl == c].max() + 1, int(c)


class TestShardedDifferential:
    def test_matches_single_chip_2_4_8_way(self):
        blobs = chains_trace(seed=1)
        dec, cols, ds = stage_all(blobs)
        want = run_single(dec, cols, ds)
        for K in (2, 4, 8):
            got, res = run_sharded(dec, cols, ds, K)
            assert got == want, f"K={K} diverges"
            expected_sv(cols, res)

    def test_snapshot_and_replay_route_equality(self, monkeypatch):
        """The product seam: replay_trace with the env knobs flipped
        takes the sharded route and stays byte-identical, snapshot
        included."""
        blobs = chains_trace(n_chains=8, chain_len=80, seed=2)
        base = rp.replay_trace(blobs)
        monkeypatch.setenv(shard.SHARD_ENV, "4")
        monkeypatch.setenv(shard.MIN_ROWS_ENV, "1")
        sharded = rp.replay_trace(blobs)
        assert sharded.cache == base.cache
        assert sharded.snapshot == base.snapshot

    def test_boundary_straddling_segments(self):
        """One giant segment next to many small ones: the greedy
        partition puts the giant alone and packs the rest — every
        segment stays whole and the result is identical."""
        recs = []
        prev = None
        for k in range(900):  # the giant: one list, one chain
            recs.append(ItemRecord(
                client=1, clock=k, parent_root="big",
                origin=(1, prev) if prev is not None else None,
                content=k,
            ))
            prev = k
        for k in range(120):  # 40 tiny segments
            recs.append(ItemRecord(
                client=1, clock=900 + k, parent_root=f"s{k % 40}",
                content=k,
            ))
        blobs = [v1.encode_update(recs, DeleteSet())]
        dec, cols, ds = stage_all(blobs)
        want = run_single(dec, cols, ds)
        for K in (2, 8):
            got, _ = run_sharded(dec, cols, ds, K)
            assert got == want, f"K={K} diverges"

    def test_empty_shards(self):
        """Fewer segments than shards: the empty shards run the fused
        body on pure padding and contribute nothing."""
        recs = [
            ItemRecord(client=1, clock=k, parent_root="only",
                       origin=(1, k - 1) if k else None, content=k)
            for k in range(64)
        ]
        recs += [ItemRecord(client=2, clock=k, parent_root="m",
                            key=f"k{k % 3}", content=k)
                 for k in range(16)]
        blobs = [v1.encode_update(recs, DeleteSet())]
        dec, cols, ds = stage_all(blobs)
        want = run_single(dec, cols, ds)
        got, res = run_sharded(dec, cols, ds, 8)
        assert got == want
        expected_sv(cols, res)

    def test_delete_only_updates(self, monkeypatch):
        """A delete-only tail blob (no item rows of its own) through
        the sharded route; and a FULLY delete-only union falls back
        to the single-chip path without diverging."""
        blobs = chains_trace(n_chains=4, chain_len=40, seed=3)
        ds_only = DeleteSet()
        for k in range(5):
            ds_only.add(1, k)
        blobs.append(v1.encode_update([], ds_only))
        dec, cols, ds = stage_all(blobs)
        want = run_single(dec, cols, ds)
        got, _ = run_sharded(dec, cols, ds, 4)
        assert got == want
        # fully delete-only: no valid rows -> stage refuses, the
        # route falls back (replay path equality)
        only = [v1.encode_update([], ds_only)]
        dec2, cols2, _ = stage_all(only)
        assert shard.stage(cols2, n_shards=4) is None
        base = rp.replay_trace(only)
        monkeypatch.setenv(shard.SHARD_ENV, "4")
        monkeypatch.setenv(shard.MIN_ROWS_ENV, "1")
        assert rp.replay_trace(only).cache == base.cache

    def test_right_origins_exact(self):
        """Mid-inserts with right origins: the sharded route must
        take the identical exact host detours (hard rows are
        shard-local, mapped back to union space)."""
        blobs = chains_trace(n_chains=6, chain_len=60, rights=True,
                             seed=4)
        dec, cols, ds = stage_all(blobs)
        want = run_single(dec, cols, ds)
        for K in (2, 8):
            got, _ = run_sharded(dec, cols, ds, K)
            assert got == want, f"K={K} diverges"

    def test_engine_oracle(self):
        """Ground truth: the sharded converge reproduces the scalar
        engine's document, not merely the packed path's."""
        blobs = chains_trace(n_chains=5, chain_len=30, seed=5)
        eng = Engine(999)
        for b in blobs:
            v1.apply_update(eng, b)
        dec, cols, ds = stage_all(blobs)
        got, _ = run_sharded(dec, cols, ds, 4)
        assert got == eng.to_json()


class TestChainSplit:
    def test_seam_at_every_width(self, monkeypatch):
        """The host-stitched seams are exact at every split width,
        sharded and single-chip alike."""
        blobs = chains_trace(n_chains=3, chain_len=257, seed=6)
        dec, cols, ds = stage_all(blobs)
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "0")
        want = run_single(dec, cols, ds)
        for width in (1, 2, 63, 64, 256, 257):
            monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, str(width))
            got = run_single(dec, cols, ds)
            assert got == want, f"single-chip width={width}"
            got_sh, _ = run_sharded(dec, cols, ds, 4)
            assert got_sh == want, f"sharded width={width}"

    def test_rounds_reduction_pinned(self, monkeypatch):
        """The lever itself: on a deep-chain trace the chain split
        must LOWER the staged doubling-rounds bound (the
        converge.wyllie_rounds gauge) and cut real seams."""
        blobs = chains_trace(n_chains=2, chain_len=600, n_maps=1,
                             deletes=False, seed=7)
        dec, cols, ds = stage_all(blobs)
        prev = get_tracer()
        tracer = set_tracer(Tracer(enabled=True))
        try:
            monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "0")
            assert packed.stage(cols) is not None
            rounds_before = tracer.report()["gauges"][
                "converge.wyllie_rounds"]
            monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "64")
            plan = packed.stage(cols)
            rounds_after = tracer.report()["gauges"][
                "converge.wyllie_rounds"]
            seams = tracer.counters().get("converge.chain_seams", 0)
        finally:
            set_tracer(prev)
        assert rounds_after < rounds_before, (rounds_before,
                                              rounds_after)
        assert seams > 0
        assert len(plan.seam_rows) == seams
        # and the split plan still converges byte-identically
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "0")
        want = run_single(dec, cols, ds)
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "64")
        assert run_single(dec, cols, ds) == want

    def test_branching_trees_now_split(self, monkeypatch):
        """Round 23: branching trees are CUT CANDIDATES — a wide star
        splits at subtree granularity (real seams, byte-identical),
        where round 13 refused it segment-wide."""
        recs = []
        for k in range(200):  # wide star: every op anchors the root op
            recs.append(ItemRecord(
                client=1, clock=k, parent_root="star",
                origin=(1, 0) if k else None, content=k,
            ))
        blobs = [v1.encode_update(recs, DeleteSet())]
        dec, cols, ds = stage_all(blobs)
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "0")
        want = run_single(dec, cols, ds)
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "16")
        plan = packed.stage(cols)
        assert len(plan.seam_rows) > 0  # the star really cut
        assert run_single(dec, cols, ds) == want

    def test_split_skips_cyclic_origin_segments(self, monkeypatch):
        """Hostile cyclic origins: the unsplit path's semantics must
        stand — the cycle's segment stays whole and exact."""
        recs = [
            ItemRecord(client=1, clock=0, parent_root="cyc",
                       origin=(1, 1), content=0),
            ItemRecord(client=1, clock=1, parent_root="cyc",
                       origin=(1, 0), content=1),
        ]
        recs += [ItemRecord(client=1, clock=2 + k, parent_root="cyc",
                            content=k) for k in range(60)]
        blobs = [v1.encode_update(recs, DeleteSet())]
        dec, cols, ds = stage_all(blobs)
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "0")
        want = run_single(dec, cols, ds)
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "16")
        plan = packed.stage(cols)
        assert plan.seam_rows == ()  # refused: origin cycle
        assert run_single(dec, cols, ds) == want


class TestDepthWeightedPartition:
    def _cols_three_segments(self, n=128):
        """Three equal-ROW segments: one deep append chain (client 1,
        root 0 — every row origin-chained to its predecessor) and two
        wide root-attached segments (clients 2, 3 — no origins)."""
        total = 3 * n
        client = np.r_[np.full(n, 1), np.full(n, 2), np.full(n, 3)
                       ].astype(np.int64)
        clock = np.r_[np.arange(n), np.arange(n), np.arange(n)
                      ].astype(np.int64)
        oc = np.full(total, -1, np.int64)
        ock = np.full(total, -1, np.int64)
        oc[1:n] = 1
        ock[1:n] = np.arange(n - 1)
        return {
            "client": client,
            "clock": clock,
            "parent_is_root": np.ones(total, bool),
            "parent_a": np.r_[np.zeros(n, np.int64),
                              np.ones(n, np.int64),
                              np.full(n, 2, np.int64)],
            "parent_b": np.full(total, -1, np.int64),
            "key_id": np.full(total, -1, np.int64),
            "origin_client": oc,
            "origin_clock": ock,
            "valid": np.ones(total, bool),
        }

    def test_deep_chain_vs_wide_balance(self):
        """Chain-depth weighting (the Wyllie rounds bound): a deep
        chain of N rows weighs N*ceil(log2(N)) where a wide segment
        of N root-attached rows weighs N — the greedy cut puts the
        deep chain ALONE on its shard and pairs the two wide
        segments, where row-count-only balance would pair the deep
        chain with a wide one."""
        n = 128
        cols = self._cols_three_segments(n)
        parts, _ = shard._partition(cols, 2)
        assert parts is not None and len(parts) == 2
        by_client = []
        for rows in parts:
            by_client.append(
                set(np.asarray(cols["client"])[rows].tolist())
            )
        deep_shard = [cs for cs in by_client if 1 in cs]
        assert deep_shard and deep_shard[0] == {1}, (
            f"deep chain not isolated: {by_client}"
        )
        assert {2, 3} in by_client, (
            f"wide segments not paired: {by_client}"
        )

    def test_chain_weights_formula(self):
        """The weight helper itself: rows x max(1, ceil(log2(1 +
        origin_rows))) — wide segments weigh their rows, pure chains
        weigh rows x log2(depth)."""
        counts = np.asarray([128, 128, 7, 1])
        origins = np.asarray([127, 0, 6, 0])
        w = shard._chain_weights(counts, origins)
        assert w.tolist() == [128 * 7, 128, 7 * 3, 1]

    def test_depth_weighted_partition_stays_byte_identical(self):
        """Whatever the cut, the sharded converge must stay
        byte-identical to the single-chip oracle on the deep-vs-wide
        shape."""
        blobs = chains_trace(n_chains=3, chain_len=96, seed=21)
        dec, cols, ds = stage_all(blobs)
        want = run_single(dec, cols, ds)
        got, _ = run_sharded(dec, cols, ds, 2)
        assert got == want


class TestRoutes:
    def test_stream_route_sharded(self, monkeypatch):
        """The scale replay's executor: stream shards converge through
        the mesh when >1 device is visible, byte-identical."""
        from crdt_tpu.models.streaming import stream_replay

        blobs = chains_trace(n_chains=10, chain_len=100, seed=8)
        base = stream_replay(blobs, chunk_blobs=3, max_shards=3,
                             min_shard_rows=1)
        monkeypatch.setenv(shard.SHARD_ENV, "4")
        monkeypatch.setenv(shard.MIN_ROWS_ENV, "1")
        got = stream_replay(blobs, chunk_blobs=3, max_shards=3,
                            min_shard_rows=1)
        assert got.cache == base.cache
        assert got.snapshot == base.snapshot
        assert got.path == "stream"

    def test_fleet_route_sharded(self):
        """fleet_replay's sharded mapping vs the replicated mapping
        and the scalar engine (cache + snapshot + SV)."""
        from crdt_tpu.models.fleet import fleet_replay
        from crdt_tpu.parallel.gossip import make_mesh

        blobs = chains_trace(n_chains=8, chain_len=24, seed=9)
        mesh = make_mesh(8)
        sharded = fleet_replay(blobs, mesh=mesh, shard="sharded")
        replicated = fleet_replay(blobs, mesh=mesh, shard="replicas")
        assert sharded.path == "fleet-sharded"
        assert sharded.cache == replicated.cache
        assert sharded.snapshot == replicated.snapshot
        eng = Engine(999)
        for b in blobs:
            v1.apply_update(eng, b)
        assert sharded.cache == eng.to_json()

    def test_shard_counters_live(self):
        """The registry the multichip gate reads: dispatches,
        boundary bytes, shards gauge — live on a sharded converge."""
        blobs = chains_trace(n_chains=4, chain_len=40, seed=10)
        dec, cols, ds = stage_all(blobs)
        prev = get_tracer()
        tracer = set_tracer(Tracer(enabled=True))
        try:
            run_sharded(dec, cols, ds, 2)
            counters = tracer.counters()
            gauges = tracer.report()["gauges"]
        finally:
            set_tracer(prev)
        assert counters.get("shard.dispatches") == 1
        assert counters.get("shard.boundary_bytes", 0) > 0
        assert gauges.get("shard.shards") == 2
        assert "converge.wyllie_rounds" in gauges

    def test_duplicate_ids_across_segments_dedup_globally(self):
        """Equal-id rows under DIFFERENT parents land in different
        shards, where no shard-local dedup can see the pair — the
        partition must drop duplicates globally (first caller row
        wins, packed._stage's rule) or the sharded route diverges
        from the single-chip oracle on crafted input."""
        n = 64
        cols = {
            "client": np.full(n, 7, np.int64),
            "clock": np.arange(n, dtype=np.int64) % (n // 2),
            "parent_is_root": np.ones(n, bool),
            # second half duplicates the first half's ids under a
            # DIFFERENT root -> different segment -> different shard
            "parent_a": np.r_[np.zeros(n // 2, np.int64),
                              np.ones(n // 2, np.int64)],
            "parent_b": np.full(n, -1, np.int64),
            "key_id": np.full(n, -1, np.int64),
            "origin_client": np.full(n, -1, np.int64),
            "origin_clock": np.full(n, -1, np.int64),
            "valid": np.ones(n, bool),
        }
        plan = packed.stage(cols)
        want = packed.converge(plan)
        splan = shard.stage(cols, n_shards=2)
        got = shard.converge(splan)
        keep = np.sort(want.stream_row[want.stream_row >= 0])
        keep_sh = np.sort(got.stream_row[got.stream_row >= 0])
        assert np.array_equal(keep, keep_sh), (
            "duplicate ids survived the shard partition"
        )

    def test_boundary_audit_fails_loudly(self):
        """A corrupted boundary wire must raise, never propagate a
        silently wrong swarm SV."""
        blobs = chains_trace(n_chains=4, chain_len=30, seed=11)
        dec, cols, ds = stage_all(blobs)
        splan = shard.stage(cols, n_shards=2)
        bad_wire = np.array(splan.wire, copy=True)
        # corrupt the DOMINATING clock entry for client 0 (bumping a
        # non-max entry would be masked by the SV max-merge — which
        # shard dominates depends on the partition's weights)
        bad_wire[int(np.argmax(bad_wire[:, 0])), 0] += 1
        bad = splan._replace(wire=bad_wire)
        with pytest.raises(RuntimeError, match="boundary exchange"):
            shard.converge(bad)
