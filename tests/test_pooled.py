"""Differential suite for the round-20 pooled resident matrix.

The tentpole contract: every warm doc's above-crossover delta batches
into ONE pooled scatter-splice + converge dispatch
(:class:`crdt_tpu.ops.resident.ResidentPool`), with per-doc state
BYTE-identical to the unpooled per-doc route — pinned here for mixed
LWW/YATA docs, deletes, duplicate redelivery across ticks,
eviction-then-resubmit reconvergence, a doc alone outgrowing the
pool (private-matrix fallback), and the forced-2-device sharded cold
route. On top: the dispatch-floor pin (>=8 warm docs, <=2 device
dispatches per steady tick vs >=N unpooled) and the round-20
accounting seam (pooled ledger vs ``resident_bytes`` vs the MT
budget estimate; ``tenant.pool_bytes`` peak <= ``max_bytes`` even
mid-compaction).
"""

import numpy as np
import pytest

from crdt_tpu.codec import v1
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.models.incremental import IncrementalReplay
from crdt_tpu.models.multidoc import MultiDocServer
from crdt_tpu.ops import packed, shard
from crdt_tpu.ops.resident import ResidentPool, _EXT_FLOOR, _LANES

from tests.test_multidoc import doc_blobs


@pytest.fixture(autouse=True)
def _no_ambient_sharding(monkeypatch):
    monkeypatch.delenv(shard.SHARD_ENV, raising=False)
    monkeypatch.delenv(shard.MIN_ROWS_ENV, raising=False)


@pytest.fixture
def force_device(monkeypatch):
    """Route every delta above the crossover: engines built during the
    test see threshold 1, so the pooled defer/flush seam is exercised
    by small docs."""
    monkeypatch.setenv("CRDT_TPU_DEVICE_MIN", "1")


def delta_blobs(seed, start, *, n_clients=3, K=6, lists=2, maps=2,
                deletes=False, base=10):
    """Continuation traffic for a doc seeded by :func:`doc_blobs`:
    fresh clocks from ``start`` (contiguous per client, so
    ``delta_admissible`` accepts), list appends chaining onto the
    client's previous tail row — the steady-state delta shape the
    pooled flush batches."""
    rng = np.random.default_rng(seed * 7919 + start)
    blobs = []
    for c in range(n_clients):
        client = base + c
        recs = []
        prev = (client, start - 1)
        for k in range(K):
            clk = start + k
            if k % 3 == 0:
                recs.append(ItemRecord(
                    client=client, clock=clk,
                    parent_root=f"m{k % maps}",
                    key=f"k{int(rng.integers(0, 6))}",
                    content=int(seed * 1000 + c * 100 + clk),
                ))
            else:
                recs.append(ItemRecord(
                    client=client, clock=clk,
                    parent_root=f"l{k % lists}", origin=prev,
                    content=int(seed * 1000 + c * 100 + clk),
                ))
                prev = (client, clk)
        ds = DeleteSet()
        if deletes:
            ds.add(client, start + 1)
        blobs.append(v1.encode_update(recs, ds))
    return blobs


def _pair(**kw):
    """A pooled server and its unpooled oracle, same config."""
    return (MultiDocServer(delta_ticks=True, pool=True, **kw),
            MultiDocServer(delta_ticks=True, pool=False, **kw))


def _warm(srv, doc_sets):
    """Cold-converge then promote every doc (promotion is on the
    second touch: redeliver the history)."""
    for d, blobs in doc_sets.items():
        srv.submit_many(d, blobs)
    srv.tick()
    srv.tick()
    for d, blobs in doc_sets.items():
        srv.submit_many(d, blobs)
    return srv.tick()


def _assert_equal(sp, su, docs):
    for d in docs:
        assert sp.digest(d) == su.digest(d), ("digest", d)
        assert sp.cache(d) == su.cache(d), ("cache", d)
        ep = sp._docs[d].resident
        eu = su._docs[d].resident
        if ep is not None and eu is not None:
            assert ep.state_vector() == eu.state_vector(), ("sv", d)
            assert ep.encode_state_as_update() == \
                eu.encode_state_as_update(), ("snapshot", d)


def test_pooled_matches_unpooled_mixed_docs(force_device):
    """Mixed LWW/YATA docs (varying K, right origins, deletes, shared
    raw client ids): promotion + two delta rounds through the pooled
    route are byte-identical to the per-doc oracle, and each pooled
    tick issues at most ONE flush dispatch."""
    doc_sets = {}
    for i in range(6):
        doc_sets[i] = doc_blobs(
            i, K=18 + 3 * (i % 3), rights=(i % 2 == 1), deletes=True)
    sp, su = _pair()
    rp = _warm(sp, doc_sets)
    ru = _warm(su, doc_sets)
    assert rp.promotions == ru.promotions == 6
    assert rp.pool_dispatches <= 1
    _assert_equal(sp, su, doc_sets)

    for rnd, deletes in ((0, False), (1, True)):
        for i in doc_sets:
            blobs = delta_blobs(i, 18 + 3 * (i % 3) + 6 * rnd,
                                deletes=deletes)
            sp.submit_many(i, blobs)
            su.submit_many(i, blobs)
        rp = sp.tick()
        ru = su.tick()
        assert rp.delta_docs == ru.delta_docs == 6
        assert rp.pool_dispatches <= 1
        assert ru.pool_dispatches == 0
        _assert_equal(sp, su, doc_sets)


def test_duplicate_redelivery_across_ticks(force_device):
    """A delta batch redelivered on a LATER tick (duplicate gossip)
    must dedup identically on both routes — the pooled splice never
    re-admits rows, and the segment state stays byte-stable."""
    doc_sets = {i: doc_blobs(i, K=15) for i in range(4)}
    sp, su = _pair()
    _warm(sp, doc_sets)
    _warm(su, doc_sets)

    deltas = {i: delta_blobs(i, 15) for i in doc_sets}
    for srv in (sp, su):
        for i, blobs in deltas.items():
            srv.submit_many(i, blobs)
        srv.tick()
    _assert_equal(sp, su, doc_sets)

    # redeliver the SAME deltas next tick, plus one fresh doc's worth
    for srv in (sp, su):
        for i, blobs in deltas.items():
            srv.submit_many(i, blobs)
        srv.submit_many(0, delta_blobs(0, 21))
        srv.tick()
    _assert_equal(sp, su, doc_sets)


def test_eviction_then_resubmit_reconverges(force_device):
    """LRU eviction releases the doc's pooled extent; resubmitted
    history re-promotes into a FRESH extent and reconverges exactly.
    The oracle is an UNBUDGETED unpooled server — evictions change
    residency, never state."""
    doc_sets = {i: doc_blobs(i, K=20) for i in range(3)}
    first = {i: doc_sets[i] for i in (0, 1)}
    last = {2: doc_sets[2]}
    # budget fits ~2 POOLED resident docs: doc 2's LATER promotion
    # evicts the LRU and frees its extent (same-tick promotions are
    # protected from the sweep, so the doc arrives on its own tick)
    est = IncrementalReplay.estimate_resident_bytes(60)
    sp = MultiDocServer(delta_ticks=True, pool=True,
                        resident_max_bytes=int(est * 2.5))
    su = MultiDocServer(delta_ticks=True, pool=False)
    for srv in (sp, su):
        _warm(srv, first)
        _warm(srv, last)
    assert sp.eviction_count > 0, "budget should have evicted a doc"
    assert sp.pool.doc_count() == sp.resident_doc_count()
    assert sp.resident_doc_count() < len(doc_sets)
    _assert_equal(sp, su, doc_sets)

    # grow the evicted doc(s): cold re-converge, later re-promote
    for srv in (sp, su):
        for i in doc_sets:
            srv.submit_many(i, delta_blobs(i, 20))
        srv.tick()
        for i in doc_sets:
            srv.submit_many(i, delta_blobs(i, 26))
        srv.tick()
    assert sp.pool.doc_count() == sp.resident_doc_count()
    _assert_equal(sp, su, doc_sets)


def test_doc_alone_outgrows_pool(force_device):
    """A doc whose extent cannot fit ``max_bytes`` even after
    compaction is refused at defer and falls back PERMANENTLY to a
    private resident matrix — with the small docs still pooling and
    every doc byte-identical to the oracle."""
    pool_bytes = _EXT_FLOOR * _LANES * 8  # exactly one minimal extent
    doc_sets = {
        "small": doc_blobs(0, K=12),
        # 3 clients x 400 ops = 1200 rows > the 1024-row extent the
        # budget can hold
        "big": doc_blobs(1, K=400, deletes=False),
    }
    sp, su = _pair(pool_max_bytes=pool_bytes)
    _warm(sp, doc_sets)
    _warm(su, doc_sets)
    _assert_equal(sp, su, doc_sets)

    big_eng = sp._docs["big"].resident
    assert big_eng is not None and big_eng.pool is None, \
        "big doc should have unpooled itself"
    assert sp.pool.doc_count() == 1  # only the small doc pools
    assert sp.pool.device_bytes() <= pool_bytes

    for srv in (sp, su):
        srv.submit_many("small", delta_blobs(0, 12))
        srv.submit_many("big", delta_blobs(1, 400, K=9))
        srv.tick()
    _assert_equal(sp, su, doc_sets)


def test_pooled_matches_on_sharded_route(force_device):
    """Forced-2-device sharded cold route + pooled warm route: the
    cold converge partitions across chips while promoted docs pool —
    both ends byte-identical to the unsharded, unpooled oracle."""
    doc_sets = {i: doc_blobs(i, K=16) for i in range(4)}
    sp = MultiDocServer(delta_ticks=True, pool=True, shards=2)
    su = MultiDocServer(delta_ticks=True, pool=False)
    _warm(sp, doc_sets)
    _warm(su, doc_sets)
    _assert_equal(sp, su, doc_sets)
    for srv in (sp, su):
        for i in doc_sets:
            srv.submit_many(i, delta_blobs(i, 16))
        srv.tick()
    _assert_equal(sp, su, doc_sets)


def test_steady_state_dispatch_floor(force_device):
    """The acceptance pin: >=8 warm above-crossover docs converge
    their steady delta tick in <=2 device-route dispatches pooled
    (was >= N unpooled)."""
    N = 8
    doc_sets = {i: doc_blobs(i, K=18) for i in range(N)}
    sp, su = _pair()
    _warm(sp, doc_sets)
    _warm(su, doc_sets)

    def steady(srv, start):
        for i in doc_sets:
            srv.submit_many(i, delta_blobs(i, start))
        c0 = packed.device_dispatch_count
        rep = srv.tick()
        return rep, packed.device_dispatch_count - c0

    rp, dp = steady(sp, 18)
    ru, du = steady(su, 18)
    assert rp.delta_docs == ru.delta_docs == N
    assert dp <= 2, f"pooled steady tick took {dp} device dispatches"
    assert du >= N, f"unpooled oracle dispatched {du} < {N} times"
    assert rp.pool_dispatches == 1
    _assert_equal(sp, su, doc_sets)


def test_pool_accounting_pins(force_device):
    """Round-20 accounting seam: the pooled ledger, the engine's
    ``resident_bytes``, and the MT budget estimate agree in UNITS —
    a pooled doc's device share is extent_cap x 8 lanes x 8 bytes,
    ``resident_bytes`` folds exactly that share in, and the
    pre-promotion estimate upper-bounds the realized footprint on
    BOTH routes."""
    doc_sets = {i: doc_blobs(i, K=20) for i in range(3)}
    sp, _ = _pair()
    _warm(sp, doc_sets)
    pool = sp.pool
    mat = pool._mat
    # the pool gauge is dtype-derived from the live allocation
    assert pool.device_bytes() == \
        int(mat.shape[0]) * int(mat.shape[1]) * np.dtype(np.int64).itemsize
    for i in doc_sets:
        ep = sp._docs[i].resident
        ext = pool._ext[ep]
        share = ext.cap * _LANES * 8
        assert pool.doc_device_bytes(ep) == share
        # resident_bytes = pooled share + host integer columns, and
        # nothing else (no private matrix on the pooled route)
        from crdt_tpu.models.incremental import _Cols
        assert ep._mat is None
        assert ep.resident_bytes() == \
            share + ep.cols._cap * len(_Cols.INT_COLS) * 8
        est = IncrementalReplay.estimate_resident_bytes(ep.cols.n)
        assert est >= ep.resident_bytes(), "estimate must upper-bound pooled"
    # doc shares partition the allocation (never exceed it)
    assert pool.device_bytes() >= sum(
        pool.doc_device_bytes(sp._docs[i].resident) for i in doc_sets)
    # the fleet accessor speaks the same dtype-derived unit language
    from crdt_tpu.ops.resident import COLUMNS, ResidentColumns
    rc = ResidentColumns(capacity=1024)
    assert rc.device_bytes() == sum(
        rc.capacity * np.dtype(dt).itemsize for _, dt in COLUMNS)
    # the MT ledger never exceeds its budget (commit-time enforcement)
    assert sp.rbudget.total <= (sp.rbudget.max_bytes or float("inf"))


def test_pool_peak_within_budget_mid_compaction(force_device):
    """Eviction holes squeeze without ever bursting ``max_bytes``:
    the compaction target is the covering bucket of the LIVE extents,
    not the default first bucket — peak_bytes stays <= budget."""
    budget = 4 * _EXT_FLOOR * _LANES * 8  # room for 4 minimal extents
    pool = ResidentPool(max_bytes=budget)
    engs = []
    for i in range(3):
        eng = IncrementalReplay(device_min_rows=1, pool=pool)
        eng.apply(doc_blobs(i, K=20))
        engs.append(eng)
    pool.flush()
    assert pool.device_bytes() <= budget
    full = pool.device_bytes()

    # release two docs -> tail (3 extents) > 2x live (1): compaction
    pool.release(engs[0])
    pool.release(engs[1])
    assert pool.compactions >= 1
    assert pool.device_bytes() < full
    assert pool.peak_bytes <= budget, \
        "mid-compaction allocation burst the pool budget"
    # the survivor still converges exactly after the squeeze
    eng = engs[2]
    eng.apply(delta_blobs(2, 20))
    pool.flush()
    oracle = IncrementalReplay(device_min_rows=1)
    oracle.apply(doc_blobs(2, K=20))
    oracle.apply(delta_blobs(2, 20))
    assert eng.cache == oracle.cache
    assert eng.state_vector() == oracle.state_vector()


def test_pool_disabled_by_env(force_device, monkeypatch):
    """``CRDT_TPU_MT_POOL_BYTES=0`` turns pooling off entirely — the
    opt-out knob documented in README."""
    monkeypatch.setenv("CRDT_TPU_MT_POOL_BYTES", "0")
    srv = MultiDocServer(delta_ticks=True)
    assert srv.pool is None
    monkeypatch.setenv("CRDT_TPU_MT_POOL_BYTES", "262144")
    srv = MultiDocServer(delta_ticks=True)
    assert srv.pool is not None
    assert srv.pool.max_bytes == 262144
