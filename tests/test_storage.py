"""Native kvlog store + LogPersistence (SURVEY.md §7 stage 6).

Covers the capability surface the reference gets from LevelDB
(crdt.js:18-20,47,60-71,111-130,134) plus the crash-recovery and
compaction behavior the rebuild adds: torn-tail WAL recovery, atomic
batches, ordered prefix scans, monotonic update keys (D6 fix), stored
accumulated SVs (D5 fix), and log squashing (Q3 fix).
"""

import json
import os

import pytest

from crdt_tpu.net.replica import Replica
from crdt_tpu.net.router import LoopbackNetwork, LoopbackRouter
from crdt_tpu.storage import KvLog, LogPersistence
from crdt_tpu.storage.kv import Batch


@pytest.fixture
def path(tmp_path):
    return str(tmp_path / "store.kvlog")


# ---------------------------------------------------------------------------
# KvLog
# ---------------------------------------------------------------------------


def test_put_get_delete_roundtrip(path):
    with KvLog(path) as kv:
        kv.put(b"a", b"1")
        kv.put(b"b", b"\x00\xff" * 100)
        assert kv.get(b"a") == b"1"
        assert kv.get(b"b") == b"\x00\xff" * 100
        assert kv.get(b"missing") is None
        kv.delete(b"a")
        assert kv.get(b"a") is None
        assert len(kv) == 1


def test_reopen_replays_log(path):
    with KvLog(path) as kv:
        for i in range(100):
            kv.put(f"k{i:03d}".encode(), f"v{i}".encode())
        kv.put(b"k000", b"overwritten")
        kv.delete(b"k001")
    with KvLog(path) as kv:
        assert len(kv) == 99
        assert kv.get(b"k000") == b"overwritten"
        assert kv.get(b"k001") is None
        assert kv.get(b"k099") == b"v99"


def test_ordered_scan_and_prefix(path):
    with KvLog(path) as kv:
        kv.put(b"doc_a_update_002", b"u2")
        kv.put(b"doc_a_update_000", b"u0")
        kv.put(b"doc_b_update_000", b"x")
        kv.put(b"doc_a_update_001", b"u1")
        kv.put(b"doc_a_sv", b"sv")
        rows = list(kv.scan_prefix(b"doc_a_update_"))
        assert [k for k, _ in rows] == [
            b"doc_a_update_000", b"doc_a_update_001", b"doc_a_update_002",
        ]
        assert [v for _, v in rows] == [b"u0", b"u1", b"u2"]
        # half-open range
        rows = list(kv.scan(b"doc_a_update_001", b"doc_b"))
        assert [k for k, _ in rows] == [b"doc_a_update_001", b"doc_a_update_002"]


def test_scan_is_snapshot(path):
    with KvLog(path) as kv:
        kv.put(b"a", b"1")
        kv.put(b"b", b"2")
        it = kv.scan()
        kv.put(b"c", b"3")  # must not appear in the open iterator
        assert [k for k, _ in it] == [b"a", b"b"]


def test_batch_is_atomic_across_crash(path):
    kv = KvLog(path)
    kv.put(b"before", b"x")
    batch = Batch()
    batch.put(b"doc_update_0", b"u" * 50)
    batch.put(b"doc_sv", b"s" * 10)
    batch.put(b"doc_meta", b"m" * 10)
    kv.write(batch)
    kv.close()

    # torn tail: chop bytes off the last (batch) record — recovery must
    # drop the WHOLE batch, never a prefix of it
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 7)
    with KvLog(path) as kv:
        assert kv.get(b"before") == b"x"
        assert kv.get(b"doc_update_0") is None
        assert kv.get(b"doc_sv") is None
        assert kv.get(b"doc_meta") is None
        # the store stays writable after tail truncation
        kv.put(b"after", b"y")
    with KvLog(path) as kv:
        assert kv.get(b"after") == b"y"


def test_corrupt_tail_is_dropped(path):
    with KvLog(path) as kv:
        kv.put(b"good", b"1")
        kv.put(b"bad", b"2")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:  # flip a payload byte of the last record
        f.seek(size - 1)
        f.write(bytes([f.read(0) == b"" and 0x5A]))
    with KvLog(path) as kv:
        assert kv.get(b"good") == b"1"
        assert kv.get(b"bad") is None


def test_compact_drops_history(path):
    with KvLog(path) as kv:
        for i in range(200):
            kv.put(b"hot", f"v{i}".encode())
        kv.put(b"cold", b"keep")
        before = kv.log_size
        kv.compact()
        assert kv.log_size < before
        assert kv.get(b"hot") == b"v199"
        assert kv.get(b"cold") == b"keep"
    with KvLog(path) as kv:  # compacted log replays correctly
        assert kv.get(b"hot") == b"v199"
        assert len(kv) == 2


def test_closed_store_raises_instead_of_segfaulting(path):
    kv = KvLog(path)
    kv.put(b"a", b"1")
    kv.close()
    with pytest.raises(RuntimeError):
        kv.get(b"a")
    with pytest.raises(RuntimeError):
        kv.put(b"b", b"2")
    with pytest.raises(RuntimeError):
        list(kv.scan())
    kv.close()  # double close is a no-op


def test_inverted_scan_range_is_empty(path):
    with KvLog(path) as kv:
        kv.put(b"a", b"1")
        kv.put(b"b", b"2")
        kv.put(b"c", b"3")
        assert list(kv.scan(b"c", b"b")) == []
        assert list(kv.scan(b"b", b"b")) == []


def test_doc_names_with_separator_do_not_collide(path):
    p = LogPersistence(path)
    ua, ub = _mk_update(1), _mk_update(2)
    p.store_update("a", ua)
    p.store_update("a_update_0", ub)  # raw prefix of doc 'a's keyspace
    assert p.get_all_updates("a") == [ua]
    assert p.get_all_updates("a_update_0") == [ub]
    assert p.get_meta("a")["count"] == 1
    p.close()


def test_empty_values_and_binary_keys(path):
    with KvLog(path) as kv:
        kv.put(b"\x00\x01\xfe", b"")
        assert kv.get(b"\x00\x01\xfe") == b""
    with KvLog(path) as kv:
        assert kv.get(b"\x00\x01\xfe") == b""


# ---------------------------------------------------------------------------
# LogPersistence
# ---------------------------------------------------------------------------


def _mk_update(client, n_ops=3):
    """A real v1 update: n_ops map sets from one client."""
    from crdt_tpu.api.doc import Crdt

    doc = Crdt(client)
    for i in range(n_ops):
        doc.map("m", batch=True)
        doc.set("m", f"k{i}", i, batch=True)
    return doc.exec_batch(propagate=False)


def test_store_and_replay_updates(path):
    p = LogPersistence(path)
    u1, u2 = _mk_update(1), _mk_update(2)
    p.store_update("topic", u1, sv=b"\x01")
    p.store_update("topic", u2, sv=b"\x02")
    assert p.get_all_updates("topic") == [u1, u2]
    assert p.get_state_vector("topic") == b"\x02"  # D5: accumulated, not garbage
    meta = p.get_meta("topic")
    assert meta["count"] == 2 and meta["size"] == len(u1) + len(u2)
    p.close()
    # restart: sequence numbers continue after the logged ones (D6)
    p = LogPersistence(path)
    u3 = _mk_update(3)
    p.store_update("topic", u3)
    assert p.get_all_updates("topic") == [u1, u2, u3]
    p.close()


def test_store_updates_batched_window(path):
    """The batched WAL verb: one KV batch per merge window — N log
    keys, one SV, one meta — with persist.appends counting updates
    and persist.batches counting windows."""
    from crdt_tpu.obs import Tracer, get_tracer, set_tracer

    old = get_tracer()
    tr = set_tracer(Tracer(enabled=True))
    try:
        p = LogPersistence(path)
        us = [_mk_update(c) for c in (1, 2, 3)]
        p.store_updates("topic", us, sv=b"\x09")
        assert p.get_all_updates("topic") == us
        assert p.get_state_vector("topic") == b"\x09"
        meta = p.get_meta("topic")
        assert meta["count"] == 3
        assert meta["size"] == sum(map(len, us))
        c = tr.counters("persist.")
        assert c["persist.appends"] == 3
        assert c["persist.batches"] == 1
        # singular store_update rides the same path: +1 append, +1 batch
        u4 = _mk_update(4)
        p.store_update("topic", u4)
        c = tr.counters("persist.")
        assert c["persist.appends"] == 4
        assert c["persist.batches"] == 2
        p.close()
        # restart: batched sequence numbers resume correctly (D6)
        p = LogPersistence(path)
        u5 = _mk_update(5)
        p.store_updates("topic", [u5])
        assert p.get_all_updates("topic") == us + [u4, u5]
        # empty window: no batch written, no counters moved
        before = tr.counters("persist.")
        p.store_updates("topic", [])
        assert tr.counters("persist.") == before
        # one malformed update poisons its whole batch atomically
        with pytest.raises(Exception):
            p.store_updates("topic", [_mk_update(6), b"\xff garbage"])
        assert p.get_all_updates("topic") == us + [u4, u5]
        p.close()
    finally:
        set_tracer(old)


def test_store_updates_accepts_generator(path):
    """A generator argument must survive the validation pass — the
    naive two-pass shape would silently store NOTHING while still
    advancing the state vector (silent data loss on recovery)."""
    p = LogPersistence(path)
    us = [_mk_update(c) for c in (1, 2)]
    p.store_updates("g", (u for u in us), sv=b"\x05")
    assert p.get_all_updates("g") == us
    assert p.get_meta("g")["count"] == 2
    p.close()
    from crdt_tpu.net.replica import MemoryPersistence

    mp = MemoryPersistence()
    mp.store_updates("g", (u for u in us), sv=b"\x05")
    assert mp.get_all_updates("g") == us


def test_persist_many_respects_store_update_overrides(path):
    """A subclass overriding only store_update (the sole verb that
    existed before round 9) must intercept every batched write — the
    inherited batch verb would silently bypass it."""
    from crdt_tpu.net.replica import MemoryPersistence, _prefers_batch_verb

    seen = []

    class Intercepting(MemoryPersistence):
        def store_update(self, doc, update, sv=None):
            seen.append(update)
            super().store_update(doc, update, sv=sv)

    class BatchAware(MemoryPersistence):
        def store_updates(self, doc, updates, sv=None):
            super().store_updates(doc, updates, sv=sv)

    assert not _prefers_batch_verb(Intercepting)
    assert _prefers_batch_verb(BatchAware)
    assert _prefers_batch_verb(MemoryPersistence)
    assert _prefers_batch_verb(LogPersistence)

    class SingleOnly:  # third-party, no batch verb at all
        def store_update(self, doc, update, sv=None):
            pass

    assert not _prefers_batch_verb(SingleOnly)

    from crdt_tpu.net import LoopbackNetwork, LoopbackRouter, ypear_crdt

    net = LoopbackNetwork()
    a = ypear_crdt(LoopbackRouter(net, "pkA"), topic="t", client_id=1)
    b = ypear_crdt(LoopbackRouter(net, "pkB"), topic="t", client_id=2,
                   batch_incoming=True, persistence=Intercepting())
    net.run()
    for i in range(4):
        a.set("m", f"k{i}", i)
    net.run()
    b.flush_incoming()
    assert len(seen) >= 4  # every window update went through the hook
    assert dict(b.c) == dict(a.c)


def test_replica_batched_inbox_persists_one_window(path):
    """flush_incoming applies a whole inbox as one merge transaction;
    the WAL must get ONE batch for it, not one append per update."""
    from crdt_tpu.net import LoopbackNetwork, LoopbackRouter, ypear_crdt
    from crdt_tpu.obs import Tracer, get_tracer, set_tracer

    old = get_tracer()
    tr = set_tracer(Tracer(enabled=True))
    try:
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "pkA"), topic="t",
                       client_id=1)
        b = ypear_crdt(LoopbackRouter(net, "pkB"), topic="t",
                       client_id=2, batch_incoming=True,
                       persistence=LogPersistence(path))
        net.run()
        for i in range(5):
            a.set("m", f"k{i}", i)
        net.run()          # deliver into b's inbox
        b.flush_incoming()  # ONE merge window
        c = tr.counters("persist.")
        assert c["persist.appends"] >= 5
        assert c["persist.batches"] < c["persist.appends"]
        assert dict(b.c) == dict(a.c)
    finally:
        set_tracer(old)


def test_docs_are_isolated(path):
    p = LogPersistence(path)
    ua, ub = _mk_update(1), _mk_update(2)
    p.store_update("a", ua)
    p.store_update("b", ub)
    assert p.get_all_updates("a") == [ua]
    assert p.get_all_updates("b") == [ub]
    assert p.get_meta("a")["count"] == 1
    p.close()


def test_rejects_malformed_updates(path):
    p = LogPersistence(path)
    with pytest.raises(TypeError):
        p.store_update("t", "not bytes")  # crdt.js:29-31
    with pytest.raises(Exception):
        p.store_update("t", b"\xff\xff garbage \x00")
    assert p.get_all_updates("t") == []
    p.close()


def test_compact_replaces_log(path):
    p = LogPersistence(path)
    for c in range(1, 6):
        p.store_update("t", _mk_update(c))
    assert p.get_meta("t")["count"] == 5
    snapshot = _mk_update(9, n_ops=1)
    p.compact("t", snapshot, sv=b"\x07")
    assert p.get_all_updates("t") == [snapshot]
    assert p.get_state_vector("t") == b"\x07"
    assert p.get_meta("t")["count"] == 1
    # post-compaction appends land after the snapshot
    u = _mk_update(10)
    p.store_update("t", u)
    assert p.get_all_updates("t") == [snapshot, u]
    p.close()


# ---------------------------------------------------------------------------
# Replica integration: durable restart (crdt.js:193-217 load path)
# ---------------------------------------------------------------------------


def test_replica_restart_replays_native_log(path):
    net = LoopbackNetwork()
    r1 = Replica(
        LoopbackRouter(net, "pk1"), "room",
        client_id=1, persistence=LogPersistence(path),
    )
    r1.map("users")
    r1.set("users", "alice", {"age": 30})
    r1.push("feed", ["hello", "world"])
    net.run()
    expect = dict(r1.c)
    r1.self_close()
    net.run()

    # cold restart from the same file — state comes back from the log
    r2 = Replica(
        LoopbackRouter(LoopbackNetwork(), "pk1"), "room",
        client_id=1, persistence=LogPersistence(path),
    )
    assert dict(r2.c) == expect
    assert r2.c["users"] == {"alice": {"age": 30}}
    assert r2.c["feed"] == ["hello", "world"]


def test_replica_auto_compaction_threshold(path):
    net = LoopbackNetwork()
    r = Replica(
        LoopbackRouter(net, "pk1"), "room",
        client_id=1, persistence=LogPersistence(path), compact_every=5,
    )
    for i in range(12):
        r.set("m", f"k{i}", i)
    net.run()
    meta = r.persistence.get_meta("room")
    assert meta["count"] < 12  # log was squashed at least once
    r.self_close()
    # the squashed log still restores full state
    r2 = Replica(
        LoopbackRouter(LoopbackNetwork(), "pk2"), "room",
        client_id=2, persistence=LogPersistence(path),
    )
    assert r2.c["m"] == {f"k{i}": i for i in range(12)}


def test_meta_is_json(path):
    p = LogPersistence(path)
    p.store_update("t", _mk_update(1))
    raw = KvLog(path)
    try:
        meta = json.loads(raw.get(b"doc_t_meta"))
        assert set(meta) == {"last_updated", "size", "count"}
    finally:
        raw.close()
        p.close()


def test_crash_consistent_recovery_through_persistence(path):
    """The satellite crash contract, end to end through the replica
    persistence layer: a kvlog whose tail record is torn mid-write
    (and, separately, CRC-corrupted) must reopen replaying every
    intact update, drop ONLY the tail, and accept the next
    store_update as if nothing happened — so a replica restarting
    after a crash resumes from its last durable update and
    anti-entropy refills the lost one."""
    updates = [_mk_update(c) for c in range(1, 6)]
    p = LogPersistence(path)
    for u in updates:
        p.store_update("doc", u, sv=b"sv-%d" % len(u))
    p.close()

    # torn tail: the crash hit mid-append of the LAST batch record
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 11)

    p = LogPersistence(path)
    assert p.get_all_updates("doc") == updates[:-1]  # only the tail gone
    meta = p.get_meta("doc")
    assert meta is not None and meta["count"] == 4
    # the store stays writable: the next update lands on a clean
    # record boundary and persists durably
    recovered = _mk_update(9)
    p.store_update("doc", recovered, sv=b"sv-after")
    p.close()
    p = LogPersistence(path)
    assert p.get_all_updates("doc") == updates[:-1] + [recovered]
    assert p.get_state_vector("doc") == b"sv-after"
    p.close()

    # corrupt tail: a bit flip inside the last record's payload — the
    # CRC guard must drop that whole record, nothing before it
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size - 3)
        byte = f.read(1)
        f.seek(size - 3)
        f.write(bytes([byte[0] ^ 0xFF]))
    p = LogPersistence(path)
    assert p.get_all_updates("doc") == updates[:-1]
    p.store_update("doc", recovered)  # and writes still succeed
    assert p.get_all_updates("doc") == updates[:-1] + [recovered]
    p.close()


def test_replica_restart_after_torn_tail_resyncs(path):
    """A replica whose log lost its torn tail restarts on the intact
    prefix and the sync protocol refills the difference."""
    net = LoopbackNetwork()
    r1 = Replica(
        LoopbackRouter(net, "pk1"), "room",
        client_id=1, persistence=LogPersistence(path),
    )
    # a peer that will survive the crash holding the full document
    survivor = Replica(LoopbackRouter(net, "pk2"), "room", client_id=2)
    for i in range(6):
        r1.set("m", f"k{i}", i)
    net.run()
    assert survivor.c["m"] == {f"k{i}": i for i in range(6)}
    r1.self_close()
    # crash tears the log tail. The file's trailing records include
    # handshake diffs r1 persisted, so walk the truncation point back
    # until exactly one UPDATE record (k5's) is torn away — the CRC
    # guard drops whole records, never prefixes
    size = os.path.getsize(path)
    while True:
        size -= 7
        with open(path, "r+b") as f:
            f.truncate(size)
        probe = LogPersistence(path)
        n = len(probe.get_all_updates("room"))
        probe.close()
        if n <= 5:
            break
    assert n == 5

    restarted = Replica(
        LoopbackRouter(net, "pk3"), "room",
        client_id=3, persistence=LogPersistence(path),
    )
    # the torn update is absent from the replayed log...
    assert len(restarted.c.get("m", {})) < 6
    net.run()  # ...until the ready/sync handshake refills it
    assert restarted.c["m"] == {f"k{i}": i for i in range(6)}
