"""Tracer / metrics subsystem (SURVEY.md §5 rebuild requirement)."""

import json
import time

from crdt_tpu.utils import Tracer, get_tracer, set_tracer


class TestTracer:
    def test_disabled_is_noop(self):
        tr = Tracer(enabled=False)
        with tr.span("merge"):
            pass
        tr.count("ops", 5)
        tr.gauge("pending", 3)
        rep = tr.report()
        assert rep["spans"] == {} and rep["counters"] == {} and rep["gauges"] == {}

    def test_span_aggregates(self):
        tr = Tracer(enabled=True)
        for _ in range(3):
            with tr.span("merge"):
                time.sleep(0.001)
        s = tr.report()["spans"]["merge"]
        assert s["count"] == 3
        assert s["total_s"] >= 0.003
        assert s["max_s"] <= s["total_s"]
        assert abs(s["mean_s"] - s["total_s"] / 3) < 1e-12

    def test_span_records_on_exception(self):
        tr = Tracer(enabled=True)
        try:
            with tr.span("boom"):
                raise RuntimeError("x")
        except RuntimeError:
            pass
        assert tr.report()["spans"]["boom"]["count"] == 1

    def test_counters_and_gauges(self):
        tr = Tracer(enabled=True)
        tr.count("ops")
        tr.count("ops", 9)
        tr.gauge("pending", 4)
        tr.gauge("pending", 2)
        rep = tr.report()
        assert rep["counters"]["ops"] == 10
        assert rep["gauges"]["pending"] == 2

    def test_json_and_reset(self):
        tr = Tracer(enabled=True)
        tr.count("x")
        assert json.loads(tr.to_json())["counters"]["x"] == 1
        tr.reset()
        assert tr.report()["counters"] == {}

    def test_global_install(self):
        old = get_tracer()
        try:
            mine = set_tracer(Tracer(enabled=True))
            assert get_tracer() is mine
        finally:
            set_tracer(old)


class TestReplicaIntegration:
    def test_phases_recorded_across_sync(self):
        from crdt_tpu.net import (
            LoopbackNetwork, LoopbackRouter, MemoryPersistence, Replica,
        )

        old = get_tracer()
        tr = set_tracer(Tracer(enabled=True))
        try:
            net = LoopbackNetwork()
            r1 = Replica(
                LoopbackRouter(net, "a"), topic="t", client_id=1,
                persistence=MemoryPersistence(),
            )
            r2 = Replica(LoopbackRouter(net, "b"), topic="t", client_id=2)
            net.run()
            r1.set("m", "k", 1)
            r2.set("m", "k2", 2)
            net.run()
            assert r1.c == r2.c
            rep = tr.report()
            assert rep["counters"]["replica.updates_applied"] >= 2
            assert rep["counters"]["replica.bytes_received"] > 0
            assert rep["counters"]["replica.bytes_persisted"] > 0
            assert rep["spans"]["replica.apply_update"]["count"] >= 2
            assert rep["spans"]["replica.persist"]["count"] >= 1
        finally:
            set_tracer(old)

    def test_compact_span(self):
        from crdt_tpu.net import (
            LoopbackNetwork, LoopbackRouter, MemoryPersistence, Replica,
        )

        old = get_tracer()
        tr = set_tracer(Tracer(enabled=True))
        try:
            net = LoopbackNetwork()
            r1 = Replica(
                LoopbackRouter(net, "a"), topic="t", client_id=1,
                persistence=MemoryPersistence(), compact_every=2,
            )
            for i in range(5):
                r1.set("m", f"k{i}", i)
            net.run()
            assert tr.report()["spans"]["replica.compact"]["count"] >= 1
        finally:
            set_tracer(old)
