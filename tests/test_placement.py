"""Round-24 placement: the deterministic ring + the fencing gate.

Pins the two properties everything in ``crdt_tpu/fleet`` leans on:

- **Ring determinism + minimal movement** — every process computes
  the SAME doc->owner map with zero communication (sha1-based
  hashing, never ``hash()``), and a member join/leave moves only the
  docs whose arc changed.
- **The fence, both ways** — the ``LeaseTable`` admit ladder (stale
  refused + counted, equal-epoch rival refused as a fork, newer
  adopted), its crash persistence through the snapshot store, and
  the registry pin: ``fleet.fence_rejects`` is DOCUMENTED in the
  README counter tables AND the tracer actually emits it with the
  documented label shape — name drift in either direction fails.
"""

import os

import pytest

from crdt_tpu.fleet.placement import (
    LEASE_BLOB,
    FencingToken,
    HashRing,
    LeaseTable,
    stable_hash,
)
from crdt_tpu.obs import Tracer, set_tracer
from crdt_tpu.storage.snapshot import SnapshotStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _quiet_obs():
    old = set_tracer(Tracer(enabled=False))
    yield
    set_tracer(old)


# ---- the hash ------------------------------------------------------


class TestStableHash:
    def test_pinned_values(self):
        """sha1-prefix hashing: stable across processes and
        interpreter runs (PYTHONHASHSEED randomizes ``hash()``; a
        ring built on it would fork the fleet's ownership map).
        Literal pins so an accidental algorithm change screams."""
        assert stable_hash("doc") == int.from_bytes(
            __import__("hashlib").sha1(b"doc").digest()[:8], "big")
        assert stable_hash("") == 0xDA39A3EE5E6B4B0D
        assert stable_hash("a#0") != stable_hash("a#1")

    def test_independent_of_pythonhashseed(self):
        # same-process proxy: str.__hash__ varies run to run, sha1
        # cannot — equality with a recomputation is the contract
        assert stable_hash("tenant-0") == stable_hash("tenant-0")


# ---- the ring ------------------------------------------------------


class TestHashRing:
    def test_owner_map_is_deterministic_and_pinned(self):
        """Two independently built rings agree doc-by-doc, and the
        concrete assignments are pinned: every fleet test and the
        bench chaos leg rely on these exact owners."""
        r1 = HashRing(["a", "b", "c"], vnodes=64)
        r2 = HashRing(["c", "a", "b"], vnodes=64)  # order-insensitive
        docs = ["doc", "w", "x", "y", "tenant-0", "flood!"]
        assert {d: r1.owner(d) for d in docs} == \
            {d: r2.owner(d) for d in docs}
        assert r1.owner("doc") == "a"
        assert r1.owner("w") == "b"
        assert r1.owner("tenant-0") == "c"
        assert r1.owner("flood!") == "b"

    def test_member_required(self):
        with pytest.raises(ValueError):
            HashRing([], vnodes=8).owner("doc")
        with pytest.raises(ValueError):
            HashRing(["a"], vnodes=0)

    def test_join_moves_only_to_the_joiner(self):
        """Minimal movement: adding ``d`` may claim docs, but every
        doc that CHANGED owner changed to ``d`` — no unrelated
        churn (the property that makes live rebalance affordable)."""
        before = HashRing(["a", "b", "c"], vnodes=64)
        after = HashRing(["a", "b", "c", "d"], vnodes=64)
        docs = ["d%d" % i for i in range(200)]
        moved = [d for d in docs if before.owner(d) != after.owner(d)]
        assert moved, "a joining member should claim some arc"
        assert all(after.owner(d) == "d" for d in moved)

    def test_leave_moves_only_the_leavers_docs(self):
        before = HashRing(["a", "b", "c"], vnodes=64)
        after = HashRing(["a", "b", "c"], vnodes=64)
        after.remove("c")
        docs = ["d%d" % i for i in range(200)]
        for d in docs:
            if before.owner(d) != "c":
                assert after.owner(d) == before.owner(d)
            else:
                assert after.owner(d) in ("a", "b")

    def test_successors_distinct_owner_first(self):
        r = HashRing(["a", "b", "c"], vnodes=64)
        succ = r.successors("doc", 3)
        assert succ[0] == r.owner("doc")
        assert sorted(succ) == ["a", "b", "c"]  # distinct, all
        assert r.successors("doc", 2) == succ[:2]

    def test_least_loaded_successor(self):
        r = HashRing(["a", "b", "c"], vnodes=64)
        # owner excluded; smallest load wins; ties break by name so
        # every process computes the same hint
        dst = r.least_loaded_successor(
            "doc", exclude=["a"], loads={"b": 10.0, "c": 1.0})
        assert dst == "c"
        assert r.least_loaded_successor(
            "doc", exclude=["a"], loads={"b": 5.0, "c": 5.0}) == "b"
        # no loads: ring order decides (deterministic fallback)
        assert r.least_loaded_successor("doc", exclude=["a"]) in \
            ("b", "c")
        assert r.least_loaded_successor(
            "doc", exclude=["a", "b", "c"]) is None


# ---- the fence -----------------------------------------------------


class TestLeaseTable:
    def _table(self, proc="a", store=None):
        return LeaseTable(proc, HashRing(["a", "b", "c"], vnodes=64),
                          store=store)

    def test_ring_seeded_epoch_one(self):
        """Every process derives the same initial (epoch, owner)
        with zero communication: epoch 1, the ring arc owner."""
        t = self._table("a")
        assert t.lease("doc") == (1, "a")
        assert t.holds("doc") and not t.holds("w")
        assert t.token("doc") == FencingToken(1, "a")
        assert t.owned_docs(["doc", "w", "tenant-0"]) == ["doc"]
        assert t.epochs_of(["doc", "w"]) == {"doc": 1, "w": 1}
        assert t.recorded() == {}  # nothing explicitly granted yet

    def test_grant_ladder(self):
        t = self._table("a")
        assert t.grant("doc", 2, "c")           # forward: recorded
        assert t.lease("doc") == (2, "c")
        assert not t.holds("doc")
        assert not t.grant("doc", 1, "a")       # backward: stale
        assert t.fence_rejects == 1
        assert not t.grant("doc", 2, "b")       # equal-epoch rival
        assert t.fork_refused == 1
        assert t.grant("doc", 2, "c")           # idempotent re-grant
        assert t.lease("doc") == (2, "c")

    def test_admit_ladder(self):
        t = self._table("a")
        # stale epoch refused + counted
        t.grant("doc", 3, "a")
        assert not t.admit("doc", FencingToken(2, "b"), op="update")
        assert t.fence_rejects == 1
        # equal epoch, different claimant: fork refused
        assert not t.admit("doc", FencingToken(3, "b"), op="update")
        assert t.fork_refused == 1
        assert t.lease("doc") == (3, "a")
        # equal epoch, the recorded owner: admitted, no change
        assert t.admit("doc", FencingToken(3, "a"), op="update")
        # newer epoch: adopted AND admitted (higher epoch wins —
        # the partition-heal path)
        assert t.admit("doc", FencingToken(5, "b"), op="beacon")
        assert t.lease("doc") == (5, "b")

    def test_fence_reject_tracer_labels(self):
        tracer = set_tracer(Tracer(enabled=True))
        try:
            t = self._table("a")
            t.grant("doc", 3, "a")
            t.admit("doc", FencingToken(1, "b"), op="serve")
            t.admit("doc", FencingToken(1, "b"), op="update")
            t.admit("doc", FencingToken(3, "b"), op="update")
            counters = tracer.counters()
            assert counters['fleet.fence_rejects{op="serve"}'] == 1
            assert counters['fleet.fence_rejects{op="update"}'] == 1
            assert counters["fleet.fork_refused"] == 1
        finally:
            set_tracer(Tracer(enabled=False))

    def test_persistence_round_trip(self, tmp_path):
        """The crash-safety half: grants survive a restart through
        the snapshot store, so a revived process resumes with the
        epochs it held — never the ring defaults."""
        store = SnapshotStore(str(tmp_path))
        t = self._table("a", store=store)
        t.grant("doc", 4, "c")
        t.grant("w", 7, "a")
        raw = store.get_blob(LEASE_BLOB)
        assert raw is not None and b'"doc"' in raw
        t2 = self._table("a", store=store)
        assert t2.lease("doc") == (4, "c")
        assert t2.lease("w") == (7, "a")
        assert t2.holds("w") and not t2.holds("doc")
        # a stale grant is STILL refused after the restart
        assert not t2.grant("doc", 3, "a")
        assert t2.fence_rejects == 1

    def test_corrupt_lease_blob_falls_back_to_ring(self, tmp_path):
        store = SnapshotStore(str(tmp_path))
        store.put_blob(LEASE_BLOB, b"not json {")
        t = self._table("a", store=store)
        assert t.lease("doc") == (1, "a")
        store.put_blob(LEASE_BLOB, b'{"doc": "bogus", "w": [9, "b"]}')
        t2 = self._table("a", store=store)
        assert t2.lease("doc") == (1, "a")  # malformed row skipped
        assert t2.lease("w") == (9, "b")


# ---- the registry pin (both directions) ----------------------------


def test_fence_counters_documented_in_registry():
    """The README counter tables must carry the round-24 fencing
    names — ``tools/crdtlint`` lints emissions against this registry,
    so a name dropping out silently un-checks the namespace."""
    from tools.crdtlint.registry import NAMESPACES, load_registry

    reg = load_registry(
        os.path.join(REPO, "README.md"),
        os.path.join(REPO, "tests", "test_bench_smoke.py"),
    )
    for name in (
        "fleet.fence_rejects", "fleet.fork_refused",
        "fleet.redirects", "fleet.demotions", "fleet.beacons_sent",
        "fleet.frames_malformed", "fleet.advice_dups",
        "fleet.migrations_started",
        "migration.started", "migration.completed",
        "migration.recovery", "migration.tail_blobs",
        "migration.tail_restores",
        "snap.fallbacks",
    ):
        assert name in reg.metrics, (
            f"{name} missing from the README registry tables "
            f"(round-24 fleet contract)"
        )
    assert "migration" in NAMESPACES, (
        "the migration.* namespace must be registry-checked, not "
        "an allowlist hole"
    )
