"""Native transport: crypto test vectors, reliable UDP, router contract.

Crypto primitives are checked against their published vectors
(RFC 8439 for ChaCha20-Poly1305, RFC 7748 for X25519,
draft-irtf-cfrg-xchacha for HChaCha20) — the implementation lives in
native/transport/transport.cc and must match the specs bit-for-bit.
Transport tests run real sockets on 127.0.0.1, including forced
datagram loss (retransmit path) and a genuine second process.
"""

import os
import subprocess
import sys
import time

import pytest

from crdt_tpu.net import transport as t


# ---------------------------------------------------------------------------
# crypto vectors
# ---------------------------------------------------------------------------


class TestAeadRfc8439:
    KEY = bytes(range(0x80, 0xA0))
    NONCE = bytes.fromhex("070000004041424344454647")
    AAD = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
    PT = (
        b"Ladies and Gentlemen of the class of '99: If I could offer you "
        b"only one tip for the future, sunscreen would be it."
    )
    CT = bytes.fromhex(
        "d31a8d34648e60db7b86afbc53ef7ec2"
        "a4aded51296e08fea9e2b5a736ee62d6"
        "3dbea45e8ca9671282fafb69da92728b"
        "1a71de0a9e060b2905d6a5b67ecd3b36"
        "92ddbd7f2d778b8c9803aee328091b58"
        "fab324e4fad675945585808b4831d7bc"
        "3ff4def08e4b7a9de576d26586cec64b"
        "6116"
    )
    TAG = bytes.fromhex("1ae10b594f09e26a7e902ecbd0600691")

    def test_encrypt_matches_rfc(self):
        out = t.aead_encrypt(self.KEY, self.NONCE, self.PT, aad=self.AAD)
        assert out[:-16] == self.CT
        assert out[-16:] == self.TAG

    def test_decrypt_roundtrip_and_vector(self):
        assert (
            t.aead_decrypt(self.KEY, self.NONCE, self.CT + self.TAG, aad=self.AAD)
            == self.PT
        )

    def test_tamper_detected(self):
        sealed = bytearray(self.CT + self.TAG)
        sealed[3] ^= 1
        with pytest.raises(ValueError, match="authentication"):
            t.aead_decrypt(self.KEY, self.NONCE, bytes(sealed), aad=self.AAD)

    def test_wrong_aad_detected(self):
        with pytest.raises(ValueError, match="authentication"):
            t.aead_decrypt(self.KEY, self.NONCE, self.CT + self.TAG, aad=b"x")

    def test_empty_plaintext(self):
        sealed = t.aead_encrypt(self.KEY, self.NONCE, b"", aad=b"meta")
        assert len(sealed) == 16
        assert t.aead_decrypt(self.KEY, self.NONCE, sealed, aad=b"meta") == b""


def _py_hchacha20(key: bytes, nonce: bytes) -> bytes:
    """Independent pure-Python HChaCha20 (draft-irtf-cfrg-xchacha §2.2:
    the ChaCha rounds WITHOUT the final state addition; output words
    0-3 and 12-15) — the differential oracle for the C kernel."""
    import struct

    def rotl(x, n):
        return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF

    def qr(s, a, b, c, d):
        s[a] = (s[a] + s[b]) & 0xFFFFFFFF; s[d] ^= s[a]; s[d] = rotl(s[d], 16)
        s[c] = (s[c] + s[d]) & 0xFFFFFFFF; s[b] ^= s[c]; s[b] = rotl(s[b], 12)
        s[a] = (s[a] + s[b]) & 0xFFFFFFFF; s[d] ^= s[a]; s[d] = rotl(s[d], 8)
        s[c] = (s[c] + s[d]) & 0xFFFFFFFF; s[b] ^= s[c]; s[b] = rotl(s[b], 7)

    x = [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574]
    x += list(struct.unpack("<8I", key))
    x += list(struct.unpack("<4I", nonce))
    for _ in range(10):
        qr(x, 0, 4, 8, 12); qr(x, 1, 5, 9, 13)
        qr(x, 2, 6, 10, 14); qr(x, 3, 7, 11, 15)
        qr(x, 0, 5, 10, 15); qr(x, 1, 6, 11, 12)
        qr(x, 2, 7, 8, 13); qr(x, 3, 4, 9, 14)
    return struct.pack("<4I", *x[0:4]) + struct.pack("<4I", *x[12:16])


class TestHChaCha20:
    def test_draft_input_regression(self):
        # the draft-irtf-cfrg-xchacha §2.2.1 input; expected value
        # pinned from two independent implementations of the spec
        # (this C kernel and _py_hchacha20). The underlying ChaCha
        # rounds are vector-checked by TestAeadRfc8439.
        key = bytes(range(32))
        nonce = bytes.fromhex("000000090000004a0000000031415927")
        out = t.hchacha20(key, nonce)
        assert out == _py_hchacha20(key, nonce)
        assert out == bytes.fromhex(
            "82413b4227b27bfed30e42508a877d73a0f9e4d58a74a853c12ec41326d3ecdc"
        )

    def test_differential_random(self):
        for i in range(16):
            key, nonce = os.urandom(32), os.urandom(16)
            assert t.hchacha20(key, nonce) == _py_hchacha20(key, nonce)


class TestX25519Rfc7748:
    def test_vector_1(self):
        k = bytes.fromhex(
            "a546e36bf0527c9d3b16154b82465edd62144c0ac1fc5a18506a2244ba449ac4"
        )
        u = bytes.fromhex(
            "e6db6867583030db3594c1a424b15f7c726624ec26b3353b10a903a6d0ab1c4c"
        )
        assert t.x25519(k, u) == bytes.fromhex(
            "c3da55379de9c6908e94ea4df28d084f32eccf03491c71f754b4075577a28552"
        )

    def test_vector_2(self):
        k = bytes.fromhex(
            "4b66e9d4d1b4673c5ad22691957d6af5c11b6421e0ea01d42ca4169e7918ba0d"
        )
        u = bytes.fromhex(
            "e5210f12786811d3f4b7959d0538ae2c31dbe7106fc03c3efc4cd549c715a493"
        )
        assert t.x25519(k, u) == bytes.fromhex(
            "95cbde9476e8907d7aade45cb4b873f88b595a68799fa152e6f8f7647aac7957"
        )

    def test_dh_commutes_rfc_keys(self):
        # RFC 7748 §6.1 Diffie-Hellman vector
        a_priv = bytes.fromhex(
            "77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a"
        )
        b_priv = bytes.fromhex(
            "5dab087e624a8a4b79e17f8b83800ee66f3bb1292618b6fd1c2f8b27ff88e0eb"
        )
        a_pub, _ = t.keypair(a_priv)
        b_pub, _ = t.keypair(b_priv)
        assert a_pub == bytes.fromhex(
            "8520f0098930a754748b7ddcb43ef75a0dbf3a0d26381af4eba4a98eaa9b4e6a"
        )
        assert b_pub == bytes.fromhex(
            "de9edb7d7b7dc1b4d35b61c2ece435373f8343c85b78674dadfc7e146f882b4f"
        )
        shared = bytes.fromhex(
            "4a5d9d5ba4ce2de1728e3bf480350f25e07e21c947d19e3376f09b3c1e161742"
        )
        assert t.x25519(a_priv, b_pub) == shared
        assert t.x25519(b_priv, a_pub) == shared

    def test_low_order_point_rejected(self):
        with pytest.raises(ValueError, match="low-order"):
            t.x25519(os.urandom(32), bytes(32))

    def test_differential_vs_openssl(self):
        """Random-key agreement must match the platform's production
        X25519 (cryptography/OpenSSL) in both directions. Skips where
        the optional ``cryptography`` wheel is absent — the RFC 7748
        vectors above still pin the implementation."""
        pytest.importorskip("cryptography")
        from cryptography.hazmat.primitives.asymmetric.x25519 import (
            X25519PrivateKey,
        )
        from cryptography.hazmat.primitives.serialization import (
            Encoding, NoEncryption, PrivateFormat, PublicFormat,
        )

        for _ in range(8):
            ossl_priv = X25519PrivateKey.generate()
            ossl_pub = ossl_priv.public_key().public_bytes(
                Encoding.Raw, PublicFormat.Raw
            )
            ours_pub, ours_sec = t.keypair()
            shared_ours = t.x25519(ours_sec, ossl_pub)
            from cryptography.hazmat.primitives.asymmetric.x25519 import (
                X25519PublicKey,
            )
            shared_ossl = ossl_priv.exchange(X25519PublicKey.from_public_bytes(ours_pub))
            assert shared_ours == shared_ossl
            # and our public key derivation matches theirs
            raw = ossl_priv.private_bytes(
                Encoding.Raw, PrivateFormat.Raw, NoEncryption()
            )
            pub_from_ours, _ = t.keypair(raw)
            assert pub_from_ours == ossl_pub


class TestSecureBox:
    def test_both_directions(self):
        a_pub, a_sec = t.keypair()
        b_pub, b_sec = t.keypair()
        ab = t.SecureBox(a_sec, b_pub)
        ba = t.SecureBox(b_sec, a_pub)
        msg = b"swarm update \x00\x01" * 100
        assert ba.decrypt(ab.encrypt(msg)) == msg
        assert ab.decrypt(ba.encrypt(msg, aad=b"id"), aad=b"id") == msg

    def test_third_party_cannot_decrypt(self):
        a_pub, a_sec = t.keypair()
        b_pub, b_sec = t.keypair()
        _, eve_sec = t.keypair()
        sealed = t.SecureBox(a_sec, b_pub).encrypt(b"secret")
        with pytest.raises(ValueError):
            t.SecureBox(eve_sec, a_pub).decrypt(sealed)

    def test_nonce_randomized(self):
        a_pub, a_sec = t.keypair()
        box = t.SecureBox(a_sec, a_pub)
        assert box.encrypt(b"x") != box.encrypt(b"x")


# ---------------------------------------------------------------------------
# reliable UDP endpoint
# ---------------------------------------------------------------------------


def _pump(endpoints, *, until, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while not until():
        if time.monotonic() > deadline:
            raise TimeoutError("endpoints did not settle")
        for ep in endpoints:
            ep.poll()
        time.sleep(0.002)


class TestUdpEndpoint:
    def test_small_message(self):
        with t.UdpEndpoint() as a, t.UdpEndpoint() as b:
            a.send("127.0.0.1", b.port, b"hello swarm")
            got = []
            _pump([a, b], until=lambda: got or not (got.extend(b.recv_all()) or True) or got)
            ip, port, data = got[0]
            assert data == b"hello swarm"
            assert port == a.port
            _pump([a, b], until=lambda: a.pending == 0)

    def test_large_message_fragments(self):
        payload = os.urandom(300_000)  # 250 fragments
        with t.UdpEndpoint() as a, t.UdpEndpoint() as b:
            a.send("127.0.0.1", b.port, payload)
            got = []
            _pump([a, b], until=lambda: bool(got.extend(b.recv_all()) or got))
            assert got[0][2] == payload
            _pump([a, b], until=lambda: a.pending == 0)

    def test_empty_message(self):
        with t.UdpEndpoint() as a, t.UdpEndpoint() as b:
            a.send("127.0.0.1", b.port, b"")
            got = []
            _pump([a, b], until=lambda: bool(got.extend(b.recv_all()) or got))
            assert got[0][2] == b""

    def test_delivery_under_heavy_loss(self):
        """25% of outbound datagrams dropped on BOTH sides (data and
        acks): retransmit must still deliver everything exactly once."""
        msgs = [os.urandom(5000) for _ in range(10)]
        with t.UdpEndpoint() as a, t.UdpEndpoint() as b:
            a.set_loss(250, seed=1)
            b.set_loss(250, seed=2)
            for m in msgs:
                a.send("127.0.0.1", b.port, m)
            got = []
            _pump(
                [a, b],
                until=lambda: len(got) >= len(msgs)
                or not (got.extend(b.recv_all()) or True)
                or len(got) >= len(msgs),
                timeout_s=30.0,
            )
            assert sorted(d for _, _, d in got) == sorted(msgs)
            assert a.failed == 0

    def test_duplicate_suppression(self):
        """Re-sent datagrams (lost acks) must not duplicate messages."""
        with t.UdpEndpoint() as a, t.UdpEndpoint() as b:
            b.set_loss(400, seed=7)  # b's ACKS get dropped -> a re-sends
            a.send("127.0.0.1", b.port, b"once only")
            got = []
            deadline = time.monotonic() + 20
            while a.pending and time.monotonic() < deadline:
                a.poll(), b.poll()
                got.extend(b.recv_all())
                time.sleep(0.002)
            got.extend(b.recv_all())
            assert [d for _, _, d in got] == [b"once only"]

    def test_forged_ack_cannot_suppress_retransmit(self):
        """An ack without the per-message token must not clear an
        in-flight message (ADVICE r1: msg_id-only ack matching let any
        reachable host forge acks and blackhole traffic, defeating the
        rebind challenge one layer down). The receiver here IS the
        message's destination, so the token check alone is what
        rejects the forgeries."""
        import socket
        import struct

        rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        rx.bind(("127.0.0.1", 0))
        rx.settimeout(5.0)
        try:
            with t.UdpEndpoint() as a:
                mid = a.send("127.0.0.1", rx.getsockname()[1], b"precious")
                frame, _ = rx.recvfrom(2048)
                magic, typ, msg_id, idx, cnt, real_token = struct.unpack_from(
                    "<BBIHHI", frame
                )
                assert typ == 0 and msg_id == mid & 0xFFFFFFFF
                # wrong-token acks (msg_id, idx, source all correct!)
                for bad in (0, 1, 0xFFFFFFFF, real_token ^ 1):
                    rx.sendto(
                        struct.pack("<BBIHHI", magic, 1, msg_id, idx, 0, bad),
                        ("127.0.0.1", a.port),
                    )
                for _ in range(20):
                    a.poll()
                    time.sleep(0.002)
                assert a.pending == 1, "forged ack cleared the message"
                # echoing the token from the DATA header clears it
                rx.sendto(
                    struct.pack("<BBIHHI", magic, 1, msg_id, idx, 0, real_token),
                    ("127.0.0.1", a.port),
                )
                deadline = time.monotonic() + 5
                while a.pending and time.monotonic() < deadline:
                    a.poll()
                    time.sleep(0.002)
                assert a.pending == 0
        finally:
            rx.close()


# ---------------------------------------------------------------------------
# router contract over UDP + replica convergence
# ---------------------------------------------------------------------------


from crdt_tpu.net.replica import Replica  # noqa: E402
from crdt_tpu.net.udp_router import UdpRouter, pump  # noqa: E402


def _mesh(n):
    routers = [UdpRouter() for _ in range(n)]
    for i, r in enumerate(routers):
        for other in routers[:i]:
            r.add_peer(*other.addr)
    pump(routers)
    return routers


class TestUdpRouter:
    def test_handshake_builds_full_mesh(self):
        routers = _mesh(3)
        try:
            for r in routers:
                assert len(r.peers) == 2
        finally:
            for r in routers:
                r.close()

    def test_two_replicas_converge(self):
        routers = _mesh(2)
        try:
            r1 = Replica(routers[0], topic="room", client_id=1)
            r2 = Replica(routers[1], topic="room", client_id=2)
            pump(routers)
            r1.set("users", "alice", {"role": "admin"})
            r2.set("users", "bob", {"role": "guest"})
            pump(routers)
            assert r1.c == r2.c
            assert r1.c["users"]["alice"] == {"role": "admin"}
            assert r1.c["users"]["bob"] == {"role": "guest"}
        finally:
            for r in routers:
                r.close()

    def test_late_joiner_syncs_existing_state(self):
        routers = _mesh(2)
        try:
            r1 = Replica(routers[0], topic="room", client_id=1)
            pump(routers)
            r1.set("cfg", "mode", "dark")
            r1.push("log", ["a", "b"])
            pump(routers)

            late = UdpRouter()
            routers.append(late)
            r3 = Replica(late, topic="room", client_id=3)
            late.add_peer(*routers[0].addr)
            pump(routers)
            assert r3.c == r1.c
            assert r3.c["cfg"]["mode"] == "dark"
        finally:
            for r in routers:
                r.close()

    def test_convergence_under_loss(self):
        routers = _mesh(2)
        try:
            for r in routers:
                r.endpoint.set_loss(150, seed=11)
            r1 = Replica(routers[0], topic="room", client_id=1)
            r2 = Replica(routers[1], topic="room", client_id=2)
            pump(routers, timeout_s=30.0)
            for i in range(10):
                (r1 if i % 2 else r2).set("kv", f"k{i}", i)
            pump(routers, timeout_s=30.0)
            assert r1.c == r2.c
            assert len(r1.c["kv"]) == 10
        finally:
            for r in routers:
                r.close()

    def test_malformed_hello_rejected(self):
        """A hello with a short / non-hex / uppercase pk must not
        poison the peer table (short keys would hand the native x25519
        an undersized buffer)."""
        from crdt_tpu.codec.lib0 import Encoder

        def hello(pk):
            enc = Encoder()
            enc.write_any({"pk": pk, "ack": True})
            return bytes([0]) + enc.to_bytes()

        with t.UdpEndpoint() as attacker:
            target = UdpRouter()
            try:
                for bad in ("aa", "", "zz" * 32, 123):
                    attacker.send("127.0.0.1", target.endpoint.port, hello(bad))
                deadline = time.monotonic() + 5
                while time.monotonic() < deadline and (
                    attacker.pending or target.endpoint.pending
                ):
                    attacker.poll(); target.poll()
                    time.sleep(0.002)
                target.poll()
                assert target.peers == []
                # uppercase hex of a REAL key is accepted, normalized
                pub, _ = t.keypair()
                attacker.send(
                    "127.0.0.1", target.endpoint.port, hello(pub.hex().upper())
                )
                deadline = time.monotonic() + 5
                while time.monotonic() < deadline and not target.peers:
                    attacker.poll(); target.poll()
                    time.sleep(0.002)
                assert target.peers == [pub.hex()]  # lowercase key
            finally:
                target.close()

    def test_envelope_from_unknown_peer_triggers_rehandshake(self):
        routers = _mesh(2)
        try:
            r1 = Replica(routers[0], topic="room", client_id=1)
            r2 = Replica(routers[1], topic="room", client_id=2)
            pump(routers)
            r1.set("m", "k", 1)
            pump(routers)
            # simulate a restart losing peer state on router 0
            routers[0]._peers.clear()
            r2.set("m", "k2", 2)  # arrives as envelope from unknown
            pump(routers)
            assert r1.c == r2.c
        finally:
            for r in routers:
                r.close()


class TestRebindChallenge:
    def test_spoofed_hello_does_not_reroute(self):
        """An attacker replaying a victim's public key from its own
        address must not capture the victim's traffic."""
        routers = _mesh(2)
        a, b = routers
        try:
            r1 = Replica(a, topic="room", client_id=1)
            r2 = Replica(b, topic="room", client_id=2)
            pump(routers)
            victim_addr = a._peers[b.public_key].addr

            from crdt_tpu.codec.lib0 import Encoder

            enc = Encoder()
            enc.write_any({"pk": b.public_key, "ack": True})
            with t.UdpEndpoint() as attacker:
                attacker.send("127.0.0.1", a.endpoint.port, bytes([0]) + enc.to_bytes())
                deadline = time.monotonic() + 3
                while time.monotonic() < deadline and (
                    attacker.pending or a.endpoint.pending
                ):
                    attacker.poll(); a.poll(); b.poll()
                    time.sleep(0.002)
                # the attacker cannot answer the encrypted challenge:
                # the peer's address must be unchanged
                assert a._peers[b.public_key].addr == victim_addr
            r1.set("m", "k", 1)
            pump(routers)
            assert r2.c == r1.c  # traffic still reaches the real peer
        finally:
            for r in routers:
                r.close()

    def test_genuine_restart_reroutes_after_proof(self):
        """The same identity (seeded keypair) rebinding to a new port
        passes the challenge and traffic follows it."""
        seed_b = os.urandom(32)
        a = UdpRouter()
        b1 = UdpRouter(seed=seed_b)
        b1.add_peer(*a.addr)
        pump([a, b1])
        r_a = Replica(a, topic="room", client_id=1)
        r_b1 = Replica(b1, topic="room", client_id=2)
        pump([a, b1])
        r_a.set("m", "k", 1)
        pump([a, b1])
        assert r_b1.c == r_a.c
        old_addr = a._peers[b1.public_key].addr
        b1.close()

        b2 = UdpRouter(seed=seed_b)  # same identity, fresh port
        try:
            assert b2.public_key == b1.public_key
            r_b2 = Replica(b2, topic="room", client_id=3)
            b2.add_peer(*a.addr)
            pump([a, b2], timeout_s=15.0)
            assert a._peers[b2.public_key].addr == b2.addr
            assert a._peers[b2.public_key].addr != old_addr
            r_a.set("m", "k2", 2)
            pump([a, b2], timeout_s=15.0)
            assert r_b2.c["m"] == r_a.c["m"]
        finally:
            a.close()
            b2.close()


    def test_same_port_restart_resets_topic_watermark(self):
        """A restarted process on the SAME address announces from v=1
        again; the old incarnation's higher watermark must not make
        its announcements look like stale retransmits."""
        seed_b = os.urandom(32)
        a = UdpRouter()
        b1 = UdpRouter(seed=seed_b)
        port_b = b1.endpoint.port
        b1.add_peer(*a.addr)
        pump([a, b1])
        # inflate b1's announcement version past the next incarnation's
        for topic in ("t1", "t2", "t3"):
            b1.alow(topic, lambda m, f: None)
        b1.unsubscribe("t1")
        pump([a, b1])
        assert a._peers[b1.public_key].topics_v >= 4
        b1.close()

        b2 = UdpRouter(seed=seed_b, port=port_b)  # same identity+address
        try:
            r_b2 = Replica(b2, topic="room", client_id=5)  # announces v=1
            b2.add_peer(*a.addr)
            pump([a, b2], timeout_s=15.0)
            assert "room" in a._peers[b2.public_key].topics
            r_a = Replica(a, topic="room", client_id=6)
            pump([a, b2], timeout_s=15.0)
            r_a.set("m", "k", 1)
            pump([a, b2], timeout_s=15.0)
            assert r_b2.c == r_a.c
        finally:
            a.close()
            b2.close()


_CHILD = r"""
import sys, time
sys.path.insert(0, "@REPO@")
from crdt_tpu.net.replica import Replica
from crdt_tpu.net.udp_router import UdpRouter, pump

parent_ip, parent_port = sys.argv[1], int(sys.argv[2])
router = UdpRouter()
rep = Replica(router, topic="xproc", client_id=77)
router.add_peer(parent_ip, parent_port)
deadline = time.monotonic() + 15
while time.monotonic() < deadline:
    router.poll()
    if rep.c.get("handshake", {}).get("from_parent") == "hi":
        rep.set("handshake", "from_child", "hello back")
        break
    time.sleep(0.002)
else:
    sys.exit(3)
# keep pumping until the parent has surely received our write
end = time.monotonic() + 5
while time.monotonic() < end and router.endpoint.pending:
    router.poll()
    time.sleep(0.002)
sys.exit(0)
"""


class TestCrossProcess:
    def test_two_os_processes_converge(self, tmp_path):
        """A real second interpreter over real sockets — the closest
        in-tree stand-in for the reference's two-machine swarm."""
        repo = str(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        script = tmp_path / "child.py"
        script.write_text(_CHILD.replace("@REPO@", repo))

        router = UdpRouter()
        rep = Replica(router, topic="xproc", client_id=1)
        rep.set("handshake", "from_parent", "hi")

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        child = subprocess.Popen(
            [sys.executable, str(script), "127.0.0.1", str(router.endpoint.port)],
            env=env,
        )
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                router.poll()
                if rep.c.get("handshake", {}).get("from_child") == "hello back":
                    break
                time.sleep(0.005)
            assert rep.c["handshake"]["from_child"] == "hello back"
            assert child.wait(timeout=15) == 0
        finally:
            if child.poll() is None:
                child.kill()
            router.close()


class TestTopicsReplay:
    def test_replayed_announcement_from_dead_incarnation_ignored(self):
        """The per-pair SecureBox key is static across process lives, so
        a captured high-version 'topics' announcement replays cleanly at
        the crypto layer; the incarnation binding must reject it
        (ADVICE r1: the replayed watermark wedged topic membership)."""
        routers = _mesh(2)
        a, b = routers
        try:
            b.alow("room", lambda m, pk: None)
            pump(routers)
            assert a.peers_on("room") == [b.public_key]

            # attacker replays a capture from b's PREVIOUS incarnation:
            # sealed under the same static pair key, huge version, empty
            # topic set, dead inst token
            from crdt_tpu.net.transport import SecureBox

            old_box = SecureBox(b._secret, bytes.fromhex(a.public_key))
            b_raw = bytes.fromhex(b.public_key)
            from crdt_tpu.net.udp_router import _pack_any

            payload = _pack_any(
                {"t": "topics", "v": 999, "inst": "deadbeefdeadbeef",
                 "topics": []}
            )
            body = b_raw + old_box.encrypt(payload, aad=b_raw)
            assert a._on_envelope(body, b.addr)
            # the replay neither cleared the topic set nor poisoned the
            # version watermark
            assert a.peers_on("room") == [b.public_key]

            # a genuine follow-up announcement (true inst, v below the
            # replayed 999) still applies
            b.alow("room2", lambda m, pk: None)
            pump(routers)
            assert a.peers_on("room2") == [b.public_key]
        finally:
            for r in routers:
                r.close()

    def test_replayed_old_hello_cannot_wedge_membership(self):
        """A replayed plaintext hello carrying a dead incarnation token
        (spoofed source = the peer's real address) must not poison
        peer.inst: inst changes are only adopted from a fresh-nonce
        pong, so the peer's genuine announcements keep applying."""
        routers = _mesh(2)
        a, b = routers
        try:
            b.alow("room", lambda m, pk: None)
            pump(routers)
            assert a.peers_on("room") == [b.public_key]
            true_inst = a._peers[b.public_key].inst

            # attacker replays b's old-incarnation hello; the source
            # address check can be beaten by spoofing, so deliver it
            # as if it came from b's recorded address
            from crdt_tpu.codec.lib0 import Encoder

            enc = Encoder()
            enc.write_any(
                {"pk": b.public_key, "ack": True, "inst": "deadinst"}
            )
            b_addr = a._peers[b.public_key].addr
            a._on_hello(enc.to_bytes(), b_addr)
            # the dead inst was NOT adopted; a challenge went out and
            # b's pong (fresh nonce, live inst) settles the question
            assert a._peers[b.public_key].inst == true_inst
            pump(routers)
            assert a._peers[b.public_key].inst == true_inst

            # membership keeps working end to end
            b.alow("room3", lambda m, pk: None)
            pump(routers)
            assert a.peers_on("room3") == [b.public_key]
            assert a.peers_on("room") == [b.public_key]
        finally:
            for r in routers:
                r.close()



class TestRendezvousDiscovery:
    """Hyperswarm-reduction discovery (crdt.js:315): routers find each
    other through a rendezvous node's topic introductions — no static
    peer lists among the members."""

    def test_three_routers_discover_via_bootstrap_only(self):
        boot = UdpRouter(rendezvous=True)
        members = [UdpRouter(bootstrap=[boot.addr]) for _ in range(3)]
        routers = [boot] + members
        try:
            reps = [
                Replica(r, topic="room", client_id=i + 1)
                for i, r in enumerate(members)
            ]
            # constructing the replica starts the router, which dials
            # ONLY the bootstrap; intros must build the full mesh
            pump(routers, timeout_s=20.0)
            for m in members:
                others = {x.public_key for x in members if x is not m}
                assert others <= set(m.peers), (
                    m.public_key, m.peers
                )
            reps[0].set("m", "k0", 0)
            reps[1].push("l", "v1")
            reps[2].set("m", "k2", 2)
            pump(routers, timeout_s=20.0)
            first = dict(reps[0].c)
            assert first == dict(reps[1].c) == dict(reps[2].c)
            assert first["m"] == {"k0": 0, "k2": 2}
        finally:
            for r in routers:
                r.close()

    def test_late_joiner_discovers_existing_swarm(self):
        boot = UdpRouter(rendezvous=True)
        a = UdpRouter(bootstrap=[boot.addr])
        b = UdpRouter(bootstrap=[boot.addr])
        routers = [boot, a, b]
        try:
            ra = Replica(a, topic="room", client_id=1)
            rb = Replica(b, topic="room", client_id=2)
            pump(routers, timeout_s=20.0)
            ra.set("m", "k", "early")
            pump(routers, timeout_s=20.0)
            late_r = UdpRouter(bootstrap=[boot.addr])
            routers.append(late_r)
            late = Replica(late_r, topic="room", client_id=3)
            pump(routers, timeout_s=20.0)
            assert late.c["m"] == {"k": "early"}
            assert rb.c == ra.c == late.c
        finally:
            for r in routers:
                r.close()

    def test_rendezvous_node_subscribes_nothing(self):
        """The bootstrap node introduces without joining any topic —
        pure rendezvous, like a DHT node storing announcements."""
        boot = UdpRouter(rendezvous=True)
        a = UdpRouter(bootstrap=[boot.addr])
        b = UdpRouter(bootstrap=[boot.addr])
        routers = [boot, a, b]
        try:
            ra = Replica(a, topic="room", client_id=1)
            rb = Replica(b, topic="room", client_id=2)
            pump(routers, timeout_s=20.0)
            assert boot._handlers == {}
            ra.set("m", "k", 1)
            pump(routers, timeout_s=20.0)
            assert rb.c["m"] == {"k": 1}
        finally:
            for r in routers:
                r.close()


_BOOT_CHILD = r"""
import sys, time
sys.path.insert(0, "@REPO@")
from crdt_tpu.net.replica import Replica
from crdt_tpu.net.udp_router import UdpRouter

boot_ip, boot_port, who = sys.argv[1], int(sys.argv[2]), sys.argv[3]
router = UdpRouter(bootstrap=[(boot_ip, boot_port)])
rep = Replica(router, topic="disco", client_id=int(who))
rep.set("m", f"from{who}", who)
# generous: three cold interpreters importing jax may serialize for
# tens of seconds before the fabric even forms
deadline = time.monotonic() + 90
while time.monotonic() < deadline:
    router.poll()
    m = rep.c.get("m", {})
    # wait until we hold ALL THREE writers' keys (discovered through
    # the bootstrap only) and our outbox is drained
    if len(m) >= 3 and not router.endpoint.pending:
        sys.exit(0)
    time.sleep(0.002)
sys.exit(3)
"""


class TestRendezvousCrossProcess:
    def test_three_processes_find_each_other_via_bootstrap(self, tmp_path):
        """VERDICT r2 item #7's acceptance shape: three OS processes,
        each knowing only the bootstrap address, converge."""
        repo = str(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        script = tmp_path / "member.py"
        script.write_text(_BOOT_CHILD.replace("@REPO@", repo))

        boot = UdpRouter(rendezvous=True)
        boot.start(None)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        children = [
            subprocess.Popen(
                [sys.executable, str(script), "127.0.0.1",
                 str(boot.endpoint.port), str(i + 1)],
                env=env,
            )
            for i in range(3)
        ]
        try:
            deadline = time.monotonic() + 120
            done = [None] * 3
            while time.monotonic() < deadline:
                boot.poll()
                for i, ch in enumerate(children):
                    if done[i] is None:
                        done[i] = ch.poll()
                if all(d is not None for d in done):
                    break
                time.sleep(0.005)
            assert done == [0, 0, 0], f"child exit codes: {done}"
        finally:
            for ch in children:
                if ch.poll() is None:
                    ch.kill()
            boot.close()


class TestRendezvousRobustness:
    def test_malformed_intro_entries_do_not_kill_poll(self):
        """Wrong-typed intro fields from an authenticated peer must be
        skipped, not crash the event loop."""
        routers = _mesh(2)
        a, b = routers
        try:
            bad = {"t": "intro", "peers": [
                {"pk": 5, "ip": "1.2.3.4", "port": 1},      # pk not str
                {"pk": "ab", "ip": 7, "port": 1},           # ip not str
                {"pk": "cd", "port": 1},                    # no ip
                "not-a-dict",
                {"pk": "ef" * 32, "ip": "host.invalid", "port": "x"},
            ]}
            # send through b's real box so a decrypts it as genuine
            peer_a = b._peers[a.public_key]
            b._send_envelope(peer_a, bad)
            pump(routers)  # must not raise
            assert a.endpoint.port  # loop alive
        finally:
            for r in routers:
                r.close()

    def test_dead_holder_ages_out_of_introductions(self):
        """A crashed member past the announce TTL is not handed to new
        joiners as a dial target."""
        boot = UdpRouter(rendezvous=True, announce_ttl=0.2)
        # the TTL rides the wire: aging uses the MEMBER's declared ttl
        a = UdpRouter(bootstrap=[boot.addr], announce_ttl=0.2)
        routers = [boot, a]
        try:
            Replica(a, topic="room", client_id=1)
            pump(routers, timeout_s=20.0)
            # a "crashes": stop polling it, let its announcement age out
            a_pk = a.public_key
            time.sleep(0.35)
            late = UdpRouter(bootstrap=[boot.addr])
            routers.append(late)
            Replica(late, topic="room", client_id=2)
            # pump only boot+late: a is dead and must NOT be introduced
            pump([boot, late], timeout_s=20.0)
            assert a_pk not in late.peers
        finally:
            for r in routers:
                r.close()

    @staticmethod
    def _poll_until(routers, cond, timeout_s=20.0):
        """Poll a router set until ``cond()`` — tolerant of exhausted
        retransmits (dials at a dead bootstrap are EXPECTED to burn
        out here, unlike pump(), which treats that as failure)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            for r in routers:
                r.poll()
            if cond():
                return
            time.sleep(0.005)
        raise TimeoutError("condition not reached")

    def test_newcomer_joins_via_second_bootstrap_after_first_dies(self):
        """VERDICT r3 item 6: multi-bootstrap failover. Every
        configured bootstrap is dialed; killing one mid-swarm leaves a
        newcomer joinable through the survivor, the dead dial costing
        only its own burned retransmits."""
        b1 = UdpRouter(rendezvous=True)
        b2 = UdpRouter(rendezvous=True)
        routers = [b1, b2]
        try:
            for b in (b1, b2):
                b.start(None)
                b.alow("room", lambda m, pk: None)
            boots = [b1.addr, b2.addr]
            a = UdpRouter(bootstrap=boots)
            routers.append(a)
            a.start(None)
            a.alow("room", lambda m, pk: None)
            self._poll_until(
                routers,
                lambda: b1.public_key in a._rendezvous_pks
                and b2.public_key in a._rendezvous_pks,
            )
            b1.close()  # kill one rendezvous node mid-swarm
            late = UdpRouter(bootstrap=boots)
            routers.append(late)
            late.start(None)
            late.alow("room", lambda m, pk: None)
            self._poll_until(
                [b2, a, late],
                lambda: a.public_key in late.peers
                and late.public_key in a.peers,
            )
            # introducer trust came from the SURVIVOR, proven not claimed
            assert b2.public_key in late._rendezvous_pks
        finally:
            for r in routers:
                r.close()

    def test_member_reannounces_to_restarted_rendezvous(self):
        """A rendezvous node that restarts (fresh process, same
        address/key) loses its member table; the member's TTL refresh
        plus the incarnation challenge re-register it, so a newcomer
        arriving AFTER the restart still gets introduced."""
        seed = bytes(range(32))
        boot = UdpRouter(rendezvous=True, seed=seed, announce_ttl=0.3)
        boot.start(None)
        port = boot.endpoint.port
        a = UdpRouter(bootstrap=[("127.0.0.1", port)], announce_ttl=0.3)
        routers = [boot, a]
        try:
            a.start(None)
            a.alow("room", lambda m, pk: None)
            boot.alow("room", lambda m, pk: None)
            self._poll_until(
                routers, lambda: boot.public_key in a._rendezvous_pks
            )
            boot.close()
            # restart: same identity and port, empty peer table
            boot2 = UdpRouter(rendezvous=True, seed=seed, port=port)
            routers[0] = boot2
            boot2.start(None)
            boot2.alow("room", lambda m, pk: None)
            # the member's refresh re-registers it at the new process
            self._poll_until(
                [boot2, a],
                lambda: a.public_key in boot2.peers
                and "room" in boot2._peers[a.public_key].topics,
                timeout_s=30.0,
            )
            late = UdpRouter(bootstrap=[("127.0.0.1", port)])
            routers.append(late)
            late.start(None)
            late.alow("room", lambda m, pk: None)
            self._poll_until(
                [boot2, a, late],
                lambda: a.public_key in late.peers
                and late.public_key in a.peers,
                timeout_s=30.0,
            )
        finally:
            for r in routers:
                r.close()

    def test_intro_from_non_bootstrap_peer_ignored(self):
        """Only peers reached at a configured bootstrap address may
        introduce: an ordinary member's intro must not make us dial."""
        boot = UdpRouter(rendezvous=True)
        a = UdpRouter(bootstrap=[boot.addr])
        b = UdpRouter(bootstrap=[boot.addr])
        routers = [boot, a, b]
        try:
            Replica(a, topic="room", client_id=1)
            Replica(b, topic="room", client_id=2)
            pump(routers, timeout_s=20.0)
            assert b.public_key in a.peers  # mesh formed via boot
            # b (an ordinary member) tries to introduce a to a fake peer
            peer_a = b._peers[a.public_key]
            b._send_envelope(peer_a, {"t": "intro", "peers": [
                {"pk": "ab" * 32, "ip": "127.0.0.1", "port": 1}
            ]})
            pump(routers, timeout_s=20.0)
            assert "ab" * 32 not in a.peers
            # while the same intro FROM the bootstrap would be honored
            assert boot.public_key in a._rendezvous_pks
            assert b.public_key not in a._rendezvous_pks
        finally:
            for r in routers:
                r.close()

    def test_spoofed_bootstrap_hello_does_not_mint_trust(self):
        """A plaintext hello claiming a bootstrap source address must
        not grant introducer trust: only a nonce-proven pong FROM the
        bootstrap address does."""
        boot = UdpRouter(rendezvous=True)
        victim = UdpRouter(bootstrap=[boot.addr])
        attacker = UdpRouter()
        routers = [boot, victim, attacker]
        try:
            Replica(victim, topic="room", client_id=1)
            pump(routers, timeout_s=20.0)
            assert boot.public_key in victim._rendezvous_pks
            # attacker completes an ordinary key exchange with victim
            attacker.add_peer(*victim.addr)
            pump(routers, timeout_s=20.0)
            assert attacker.public_key in victim.peers
            # forge a hello whose claimed source is the bootstrap addr
            # (simulate source spoofing by calling the handler with the
            # bootstrap address directly)
            from crdt_tpu.net.udp_router import _pack_any

            body = _pack_any({
                "pk": attacker.public_key, "ack": True,
                "inst": attacker._inst,
            })
            victim._on_hello(body, boot.addr)
            # trust NOT granted from the unauthenticated claim...
            assert attacker.public_key not in victim._rendezvous_pks
            # ...and the attacker's authenticated intro is ignored
            peer_v = attacker._peers[victim.public_key]
            attacker._send_envelope(peer_v, {"t": "intro", "peers": [
                {"pk": "cd" * 32, "ip": "127.0.0.1", "port": 9}
            ]})
            pump(routers, timeout_s=20.0)
            assert "cd" * 32 not in victim.peers
        finally:
            for r in routers:
                r.close()


class TestIntroductionPunch:
    """The cone-NAT traversal mechanics (udp_router module docstring):
    hole punching IS (a) observed-address introductions, (b) BOTH
    sides dialing out on one introduction, (c) hellos that retransmit
    through the window where the other side's mapping does not exist
    yet. A real NAT cannot be interposed on loopback sockets, so each
    property is pinned directly."""

    def test_intro_makes_both_sides_dial_observed_addresses(self):
        boot = UdpRouter(rendezvous=True)
        a = UdpRouter(bootstrap=[boot.addr])
        routers = [boot, a]
        try:
            ra = Replica(a, topic="room", client_id=1)
            pump(routers, timeout_s=20.0)

            dials: dict = {"a": [], "b": []}
            orig_a = a._send_hello
            a._send_hello = lambda ip, port, **kw: (
                dials["a"].append((ip, port)), orig_a(ip, port, **kw)
            )[-1]
            b = UdpRouter(bootstrap=[boot.addr])
            routers.append(b)
            orig_b = b._send_hello
            b._send_hello = lambda ip, port, **kw: (
                dials["b"].append((ip, port)), orig_b(ip, port, **kw)
            )[-1]
            rb = Replica(b, topic="room", client_id=2)
            pump(routers, timeout_s=20.0)

            # (a)+(b): the EXISTING member dialed the newcomer's
            # observed transport address, and the newcomer dialed the
            # existing member's — one introduction, two outbound
            # opens, which is the punch
            assert b.addr in dials["a"], (dials, b.addr)
            assert a.addr in dials["b"], (dials, a.addr)
            assert a.public_key in b.peers and b.public_key in a.peers
            rb.set("m", "k", 1)
            pump(routers, timeout_s=20.0)
            assert dict(ra.c) == dict(rb.c)
        finally:
            for r in routers:
                r.close()

    def test_hello_survives_unopened_window(self):
        """The race half of the punch: A dials an address whose owner
        is not processing packets yet (the NAT-mapping-not-open
        window); once the owner starts polling, the retransmitting
        hello completes the link with no new dial from A."""
        a = UdpRouter()
        b = UdpRouter()
        try:
            a.start()
            b.start()
            a.add_peer(*b.addr)  # ONE dial, before b ever polls
            deadline = time.monotonic() + 1.0
            while time.monotonic() < deadline:
                a.poll()  # only A pumps: hello keeps retransmitting
                time.sleep(0.002)
            assert b.public_key not in a.peers  # window still closed
            pump([a, b], timeout_s=20.0)  # b joins the loop
            assert b.public_key in a.peers
            assert a.public_key in b.peers
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# symmetric-NAT traversal: port prediction + relay fallback
# ---------------------------------------------------------------------------

from crdt_tpu.net.faults import (  # noqa: E402
    ConeNat,
    NatFabric,
    SymmetricNat,
    install_nat,
    pump_until,
)


def _nat_pair(nat_a, nat_b, **router_kw):
    """Rendezvous + two members behind simulated NATs on one virtual
    fabric. Returns (routers, a, b)."""
    fabric = NatFabric()
    boot = UdpRouter(rendezvous=True)
    install_nat(boot, fabric)
    router_kw.setdefault("dial_retry_s", 0.05)
    a = UdpRouter(bootstrap=[boot.addr], **router_kw)
    install_nat(a, fabric, nat_a)
    b = UdpRouter(bootstrap=[boot.addr], **router_kw)
    install_nat(b, fabric, nat_b)
    return [boot, a, b], a, b


class TestSymmetricNatTraversal:
    """A symmetric NAT allocates a NEW external port per destination,
    so the address the rendezvous observed is a dead letter to every
    introduced stranger — the introduction alone (TestIntroductionPunch)
    can no longer traverse. Sequential allocation makes the live
    mapping predictable, and the dial scheduler's probe spray finds
    it."""

    def test_introduction_alone_is_filtered(self):
        """Ground truth for the scenario: with retries/prediction OFF,
        the introduced members stay strangers — their dials at the
        observed addresses die at each other's NAT filters."""
        routers, a, b = _nat_pair(
            SymmetricNat(21000), SymmetricNat(23000),
            port_prediction=False, relay_after_s=3600.0,
            dial_retry_s=3600.0,
        )
        try:
            ra = Replica(a, topic="room", client_id=1)
            rb = Replica(b, topic="room", client_id=2)
            del ra, rb
            deadline = time.monotonic() + 1.5
            while time.monotonic() < deadline:
                for r in routers:
                    r.poll()
                time.sleep(0.002)
            assert b.public_key not in a.peers
            assert a.public_key not in b.peers
            # the dials really happened and really were filtered
            assert a.endpoint.stats["filtered"] > 0
            assert b.endpoint.stats["filtered"] > 0
        finally:
            for r in routers:
                r.close()

    def test_converges_via_port_prediction(self):
        """Symmetric vs (port-restricted) cone: the cone side's probe
        at observed+1 lands in the symmetric side's sequentially
        allocated mapping, each side's spray opens its own filter, and
        the ordinary hello/challenge handshake completes a DIRECT
        path. Replicas then converge over it."""
        from crdt_tpu.utils.trace import Tracer, set_tracer

        tracer = set_tracer(Tracer(enabled=True))
        routers, a, b = _nat_pair(
            SymmetricNat(21000), ConeNat(22000),
            predict_after=1, relay_after_s=3600.0,  # no relay: punch or bust
        )
        try:
            ra = Replica(a, topic="room", client_id=1)
            rb = Replica(b, topic="room", client_id=2)
            pump_until(
                routers,
                lambda: (
                    b.public_key in a._peers and a._peers[b.public_key].direct
                    and a.public_key in b._peers
                    and b._peers[a.public_key].direct
                ),
                timeout_s=30.0,
            )
            assert a.stats["predict_probes"] > 0
            assert tracer.counters("router.")["router.dial_retries"] > 0
            ra.set("m", "ka", 1)
            rb.set("m", "kb", 2)
            pump_until(
                routers,
                lambda: dict(ra.c) == dict(rb.c)
                and ra.c.get("m", {}).get("kb") == 2,
                timeout_s=30.0,
            )
            # the punched mapping, not the advertised one, carries it:
            # b appears to a at its NAT address
            assert a._peers[b.public_key].addr[1] >= 22000
        finally:
            set_tracer(Tracer(enabled=False))
            for r in routers:
                r.close()


class TestRelayFallback:
    """Symmetric vs symmetric with sequentially interleaved probes
    never self-punches; the dial deadline falls back to forwarding
    end-to-end sealed frames through the introducer."""

    def test_converges_via_relay_with_prediction_disabled(self):
        from crdt_tpu.utils.trace import Tracer, set_tracer

        tracer = set_tracer(Tracer(enabled=True))
        routers, a, b = _nat_pair(
            SymmetricNat(21000), SymmetricNat(23000),
            port_prediction=False, relay_after_s=0.3,
        )
        boot = routers[0]
        try:
            ra = Replica(a, topic="room", client_id=1,
                         probe_retry_s=0.1, anti_entropy_s=0.2)
            rb = Replica(b, topic="room", client_id=2,
                         probe_retry_s=0.1, anti_entropy_s=0.2)
            ra.set("m", "ka", 1)
            rb.set("m", "kb", 2)
            pump_until(
                routers,
                lambda: dict(ra.c) == dict(rb.c)
                and ra.c.get("m", {}).get("kb") == 2
                and ra.c.get("m", {}).get("ka") == 1,
                timeout_s=30.0,
            )
            # converged WITHOUT a direct path, through the rendezvous
            pa = a._peers[b.public_key]
            assert not pa.direct and pa.relay == boot.public_key
            assert boot.stats["relay_frames_forwarded"] > 0
            assert boot.stats["relay_bytes_forwarded"] > 0
            assert a.stats["relay_sends"] > 0
            counters = tracer.counters("router.relay")
            assert counters["router.relay_frames_forwarded"] > 0
            assert counters["router.relay_elections"] > 0
        finally:
            set_tracer(Tracer(enabled=False))
            for r in routers:
                r.close()

    def test_later_probe_success_upgrades_relay_to_direct(self):
        """The relay is a bridge, not a destination: once prediction
        is allowed to run and a probe lands, the proven direct path
        replaces the relay leg in place."""
        routers, a, b = _nat_pair(
            SymmetricNat(21000), ConeNat(22000),
            port_prediction=False, relay_after_s=0.2, predict_after=1,
        )
        try:
            ra = Replica(a, topic="room", client_id=1)
            rb = Replica(b, topic="room", client_id=2)
            ra.set("m", "early", 7)
            pump_until(
                routers,
                lambda: rb.c.get("m", {}).get("early") == 7,
                timeout_s=30.0,
            )
            assert not a._peers[b.public_key].direct  # relayed so far
            a._port_prediction = True
            b._port_prediction = True
            pump_until(
                routers,
                lambda: a._peers[b.public_key].direct
                and b._peers[a.public_key].direct,
                timeout_s=30.0,
            )
            assert a._peers[b.public_key].relay is None
            assert a.stats["relay_upgrades"] + b.stats["relay_upgrades"] > 0
            ra.set("m", "late", 8)  # post-upgrade traffic rides direct
            pump_until(
                routers,
                lambda: rb.c.get("m", {}).get("late") == 8,
                timeout_s=15.0,
            )
        finally:
            for r in routers:
                r.close()

    def test_fresh_intro_reopens_expired_dial_for_relayed_peer(self):
        """A relay-routed pair whose dial window expired must not be
        stuck relayed forever: a later introduction carries a fresh
        observed address, re-opens the dial, and the prediction
        escalation upgrades the pair to direct."""
        routers, a, b = _nat_pair(
            SymmetricNat(21000), ConeNat(22000),
            port_prediction=False, relay_after_s=0.2, predict_after=1,
            dial_give_up_s=0.5,
        )
        boot = routers[0]
        try:
            ra = Replica(a, topic="room", client_id=1)
            rb = Replica(b, topic="room", client_id=2)
            ra.set("m", "x", 1)
            pump_until(
                routers,
                lambda: rb.c.get("m", {}).get("x") == 1,
                timeout_s=30.0,
            )
            end = time.monotonic() + 0.8  # let the 0.5s dials expire
            while time.monotonic() < end:
                for r in routers:
                    r.poll()
                time.sleep(0.002)
            assert not a._dials and not b._dials
            assert not a._peers[b.public_key].direct  # still relayed
            a._port_prediction = True
            b._port_prediction = True
            bs = boot._peers
            for src, dst in ((a, b), (b, a)):
                src._apply_intro(
                    {"peers": [{
                        "pk": dst.public_key,
                        "ip": bs[dst.public_key].addr[0],
                        "port": bs[dst.public_key].addr[1],
                    }]},
                    introducer=boot.public_key,
                )
            assert b.public_key in a._dials  # dial re-opened
            pump_until(
                routers,
                lambda: a._peers[b.public_key].direct
                and b._peers[a.public_key].direct,
                timeout_s=30.0,
            )
            assert a._peers[b.public_key].relay is None
        finally:
            for r in routers:
                r.close()

    def test_dead_relay_triggers_reelection_not_a_wedge(self):
        fabric = NatFabric()
        b1 = UdpRouter(rendezvous=True)
        install_nat(b1, fabric)
        b2 = UdpRouter(rendezvous=True)
        install_nat(b2, fabric)
        boots = [b1.addr, b2.addr]
        kw = dict(bootstrap=boots, dial_retry_s=0.05,
                  port_prediction=False, relay_after_s=0.2,
                  relay_stale_s=0.4)
        a = UdpRouter(**kw)
        install_nat(a, fabric, SymmetricNat(31000))
        b = UdpRouter(**kw)
        install_nat(b, fabric, SymmetricNat(33000))
        routers = [b1, b2, a, b]
        try:
            ra = Replica(a, topic="room", client_id=1,
                         probe_retry_s=0.1, anti_entropy_s=0.2)
            rb = Replica(b, topic="room", client_id=2,
                         probe_retry_s=0.1, anti_entropy_s=0.2)
            ra.set("m", "pre", 1)
            pump_until(
                routers,
                lambda: rb.c.get("m", {}).get("pre") == 1,
                timeout_s=30.0,
            )
            relay0 = a._peers[b.public_key].relay
            dead = b1 if relay0 == b1.public_key else b2
            survivor = b2 if dead is b1 else b1
            elections0 = a.stats["relay_elections"]
            dead.close()
            live = [r for r in routers if r is not dead]
            ra.set("m", "after-death", 42)
            pump_until(
                live,
                lambda: rb.c.get("m", {}).get("after-death") == 42,
                timeout_s=40.0,
            )
            assert a.stats["relay_elections"] > elections0
            assert a._peers[b.public_key].relay == survivor.public_key
        finally:
            for r in routers:
                r.close()  # idempotent: the dead relay closed earlier

    def test_saturated_relay_sheds_and_recovers(self):
        """Per-source byte budgets: a relay over budget NAKs, the
        sender pauses its relay leg (sheds to its own retry cadence),
        and the refill lets the pair converge anyway."""
        fabric = NatFabric()
        # budget below ONE side's handshake+sync footprint: the bucket
        # must bind during the initial exchange, whatever the timing
        boot = UdpRouter(rendezvous=True, relay_budget_bytes=400,
                         relay_refill_bps=1500)
        install_nat(boot, fabric)
        kw = dict(bootstrap=[boot.addr], dial_retry_s=0.05,
                  port_prediction=False, relay_after_s=0.2)
        a = UdpRouter(**kw)
        install_nat(a, fabric, SymmetricNat(21000))
        b = UdpRouter(**kw)
        install_nat(b, fabric, SymmetricNat(23000))
        routers = [boot, a, b]
        try:
            ra = Replica(a, topic="room", client_id=1,
                         probe_retry_s=0.1, anti_entropy_s=0.15)
            rb = Replica(b, topic="room", client_id=2,
                         probe_retry_s=0.1, anti_entropy_s=0.15)
            for i in range(8):
                (ra if i % 2 else rb).set("m", f"k{i}", i)
            pump_until(
                routers,
                lambda: dict(ra.c) == dict(rb.c)
                and len(ra.c.get("m", {})) == 8,
                timeout_s=40.0,
            )
            assert boot.stats["relay_sheds"] > 0  # budget really bound
        finally:
            for r in routers:
                r.close()
