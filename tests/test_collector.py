"""Round 19: the live fleet collector + merged Perfetto namespacing.

Push- and scrape-mode federation, proc-labeled registries, the live
analysis surfaces (/fleet pair_rate + divergence correlation), and
the pid-by-process-identity Perfetto merge the round-19 satellite
pins (round 18 exported one flat pid, so merged timelines collided).
"""

import json
import urllib.error
import urllib.request

import pytest

from crdt_tpu.obs.collector import FleetCollector, merge_perfetto
from crdt_tpu.obs.http import ObsHTTPServer
from crdt_tpu.obs.propagation import PropagationLedger, set_propagation
from crdt_tpu.obs.recorder import FlightRecorder, set_recorder
from crdt_tpu.obs.timeline import TickTimeline, set_timeline
from crdt_tpu.obs.tracer import Tracer, set_tracer


@pytest.fixture
def installed():
    tracer = set_tracer(Tracer(enabled=True))
    rec = set_recorder(FlightRecorder(enabled=True))
    tl = set_timeline(TickTimeline(enabled=True))
    set_propagation(PropagationLedger())
    yield tracer, rec, tl
    set_tracer(Tracer(enabled=False))
    set_recorder(FlightRecorder(enabled=False))
    set_timeline(TickTimeline(enabled=False))
    set_propagation(PropagationLedger())


def _events_for(proc, tid, *, recv_only=False):
    out = []
    if not recv_only:
        out.append({"ts": 10.0, "kind": "update.send", "tid": tid,
                    "hop": 0, "path": [[proc, "direct", 0]]})
    else:
        out.append({"ts": 10.5, "kind": "update.recv", "tid": tid,
                    "hop": 1, "path": [["p0", "direct", 0]]})
    return out


class TestPushFederation:
    def test_cross_proc_pairing_and_labels(self, installed):
        tracer, _, _ = installed
        col = FleetCollector()
        col.push("p0", snapshot={"tracer": {
            "counters": {"replica.updates_applied": 3,
                         "tenant.shed{tenant=\"d\"}": 1},
            "gauges": {"timeline.stall_ms": 0.5},
        }}, events=_events_for("p0", [1, 1, 10.0]))
        col.push("p1", snapshot={"tracer": {
            "counters": {"replica.updates_applied": 4},
            "gauges": {},
        }}, events=_events_for("p1", [1, 1, 10.0], recv_only=True))
        rep = col.fleet_report()
        assert rep["procs"] == ["p0", "p1"]
        # the send lives in p0's stream, the recv in p1's — pairing
        # is genuinely cross-process
        assert rep["paths"]["pair_rate"] == 1.0
        assert rep["paths"]["origin_procs"] == ["p0"]
        m = rep["metrics"]
        assert m["counters"][
            'replica.updates_applied{proc="p0"}'] == 3
        assert m["counters"][
            'replica.updates_applied{proc="p1"}'] == 4
        # proc label COMPOSES with existing labels
        assert m["counters"][
            'tenant.shed{proc="p0",tenant="d"}'] == 1
        assert m["sums"]["replica.updates_applied"] == 7
        g = tracer.report()["gauges"]
        assert g["collector.pair_rate"] == 1.0
        assert g["collector.procs"] == 2

    def test_divergence_correlation_is_live(self, installed):
        col = FleetCollector()
        col.push("a", events=[
            {"ts": 1.0, "kind": "update.recv", "topic": "room",
             "digest": "d1"},
            {"ts": 2.0, "kind": "divergence", "topic": "room",
             "local_digest": "xx", "peer_digest": "yy"},
        ])
        col.push("b", events=[
            {"ts": 1.5, "kind": "update.recv", "topic": "room",
             "digest": "d1"},
        ])
        rep = col.fleet_report()
        assert rep["divergence"]["divergences"] == 1
        ev = rep["divergence"]["events"][0]
        assert set(ev["context"]) == {"a", "b"}
        assert ev["last_common_digests"] == ["d1"]

    def test_divergence_counted_once_across_reports(self, installed):
        """The same divergence event sits in the merged stream on
        every scrape; the collector.divergences counter must count
        it ONCE, not once per fleet_report()."""
        tracer, _, _ = installed
        col = FleetCollector()
        col.push("a", events=[
            {"ts": 2.0, "kind": "divergence", "topic": "room",
             "local_digest": "xx", "peer_digest": "yy"},
        ])
        for _ in range(5):
            col.fleet_report()
        assert tracer.report()["counters"][
            "collector.divergences"] == 1
        # a genuinely NEW divergence still counts
        col.push("b", events=[
            {"ts": 3.0, "kind": "divergence", "topic": "room2",
             "local_digest": "aa", "peer_digest": "bb"},
        ])
        col.fleet_report()
        assert tracer.report()["counters"][
            "collector.divergences"] == 2


class TestScrapeFederation:
    def test_scrape_own_endpoint_and_degrade(self, installed):
        tracer, rec, _ = installed
        rec.record("update.send", tid=[1, 1, 1.0], hop=0,
                   path=[["self", "direct", 0]])
        tracer.count("replica.updates_applied", 2)
        obs = ObsHTTPServer(port=0).start()
        try:
            col = FleetCollector()
            col.add_proc("self", obs.url)
            col.add_proc("dead", "http://127.0.0.1:1")  # no listener
            ok = col.scrape()
            assert ok == {"dead": False, "self": True}
            assert col.scrape_errors == 1
            rep = col.fleet_report()
            assert rep["procs"] == ["self"]
            assert rep["stale_procs"] == ["dead"]
            assert any(k.endswith('{proc="self"}')
                       for k in rep["metrics"]["counters"])
            c = tracer.report()["counters"]
            assert c["collector.scrapes"] == 1
            assert c["collector.scrape_errors"] == 1
        finally:
            obs.stop()

    def test_fleet_endpoint_routes(self, installed):
        col = FleetCollector()
        col.push("p0", snapshot={"tracer": {"counters": {},
                                            "gauges": {}}},
                 events=[], timeline={"traceEvents": [
                     {"name": "process_name", "ph": "M", "ts": 0,
                      "pid": 77, "tid": 0, "args": {"name": "x"}},
                 ]})
        obs = ObsHTTPServer(port=0, collector=col).start()
        try:
            body = json.loads(urllib.request.urlopen(
                obs.url + "/fleet?scrape=0").read())
            assert body["procs"] == ["p0"]
            tl = json.loads(urllib.request.urlopen(
                obs.url + "/fleet/timeline").read())
            assert tl["traceEvents"][0]["pid"] == 1  # re-pidded
            # the 404 surface advertises the fleet routes
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(obs.url + "/nope")
            assert "/fleet" in json.loads(exc.value.read())["routes"]
        finally:
            obs.stop()

    def test_no_collector_means_no_fleet_route(self):
        obs = ObsHTTPServer(port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(obs.url + "/fleet")
            body = json.loads(exc.value.read())
            assert body["error"] == "unknown path"
            assert "/fleet" not in body["routes"]
        finally:
            obs.stop()


class TestPerfettoNamespacing:
    def test_to_perfetto_keys_pid_by_process_identity(self,
                                                      installed):
        import os

        _, _, tl = installed
        tl.tick_begin(0)
        with tl.phase("prepare"):
            pass
        tl.tick_end()
        pf = tl.to_perfetto()
        pids = {e["pid"] for e in pf["traceEvents"]}
        assert pids == {os.getpid()}
        meta = [e for e in pf["traceEvents"]
                if e["name"] == "process_name"]
        assert meta[0]["args"]["name"] == \
            f"crdt_tpu.serve[{os.getpid()}]"
        # explicit override for embedders
        pf2 = tl.to_perfetto(pid=5, process_name="gateway")
        assert {e["pid"] for e in pf2["traceEvents"]} == {5}

    def test_merge_assigns_distinct_deterministic_pids(self):
        def trace(label):
            return {"traceEvents": [
                {"name": "process_name", "ph": "M", "ts": 0,
                 "pid": 4242, "tid": 0, "args": {"name": label}},
                {"name": "tick[0]", "ph": "X", "ts": 0, "dur": 5,
                 "pid": 4242, "tid": 1},
            ]}

        # identical flat pids in, per-proc pids out — the round-18
        # collision this satellite closes
        merged = merge_perfetto({
            "p1": trace("x"), "p0": trace("x"), "p2": trace("x"),
        })
        by_pid = {}
        for e in merged["traceEvents"]:
            if e["name"] == "process_name":
                by_pid[e["pid"]] = e["args"]["name"]
        assert by_pid == {1: "p0", 2: "p1", 3: "p2"}
        ticks = [e for e in merged["traceEvents"]
                 if e["name"] == "tick[0]"]
        assert sorted(e["pid"] for e in ticks) == [1, 2, 3]
        # stable under re-merge (sorted by proc name, not dict order)
        again = merge_perfetto({
            "p2": trace("x"), "p0": trace("x"), "p1": trace("x"),
        })
        assert again == merged

    def test_merged_export_pins_collector_path(self, installed):
        _, _, tl = installed
        tl.tick_begin(0)
        tl.tick_end()
        col = FleetCollector()
        col.push("pa", timeline=tl.to_perfetto())
        col.push("pb", timeline=tl.to_perfetto())
        merged = col.merged_perfetto()
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert pids == {1, 2}
        names = {e["args"]["name"]
                 for e in merged["traceEvents"]
                 if e["name"] == "process_name"}
        assert names == {"pa", "pb"}
