"""Loopback router + replication protocol tests.

Covers the router contract (crdt.js:172-317), the ready/sync
anti-entropy handshake, and the BASELINE.json acceptance configs 1-4
at test scale: N replicas in one process with deterministic,
adversarially reordered delivery (SURVEY.md §4).
"""

import pytest

from crdt_tpu.net import (
    LoopbackNetwork,
    LoopbackRouter,
    MemoryPersistence,
    Replica,
    ypear_crdt,
)


@pytest.fixture(
    params=["scalar", "device", "resident"],
    ids=["scalar", "device", "resident"],
)
def merge_mode(request):
    """Acceptance configs run in ALL THREE merge modes: the scalar
    integrate loop, the TPU kernel path over the engine
    (CRDT_TPU_DEVICE semantics, VERDICT r1 item #1), and the
    HBM-resident document that serves merges, local ops, and the sync
    protocol without an engine (VERDICT r2 item #2). All must converge
    to identical state."""
    return request.param


def make_swarm(n, topic="t", net=None, **options):
    net = net or LoopbackNetwork()
    reps = []
    for i in range(n):
        router = LoopbackRouter(net, f"pk{i}")
        reps.append(ypear_crdt(router, topic=topic, **options))
    net.run()  # drain join/sync handshakes
    assert all(r.synced for r in reps)
    return net, reps


def assert_converged(reps):
    first = dict(reps[0].c)
    for r in reps[1:]:
        assert dict(r.c) == first, f"{r.router.public_key} diverged"
    return first


class TestRouterContract:
    def test_rejects_non_router(self):
        with pytest.raises(TypeError):
            Replica(object(), "t")

    def test_requires_topic(self):
        net = LoopbackNetwork()
        with pytest.raises(ValueError):
            ypear_crdt(LoopbackRouter(net, "pk"))

    def test_verbs_and_peers(self):
        net, (a, b, c) = make_swarm(3)
        assert set(a.router.peers) == {"pk1", "pk2"}
        seen = []
        a.for_peers(seen.append)
        assert set(seen) == {"pk1", "pk2"}

    def test_first_node_starts_synced(self):
        net = LoopbackNetwork()
        r = ypear_crdt(LoopbackRouter(net, "pk0"), topic="t")
        assert r.synced

    def test_message_passthrough(self):
        seen = []
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t")
        b = ypear_crdt(
            LoopbackRouter(net, "b"), topic="t", observer_function=seen.append
        )
        net.run()
        a.send_message({"hello": "world"})
        net.run()
        payloads = [m["message"] for m in seen if "message" in m]
        assert {"hello": "world"} in payloads


class TestSyncHandshake:
    def test_late_joiner_gets_state(self):
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t")
        a.set("users", "u1", {"age": 30})
        a.push("log", ["x", "y"])
        net.run()
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t")
        assert not b.synced
        net.run()  # ready -> sync diff -> applied
        assert b.synced
        assert_converged([a, b])
        assert b.users == {"u1": {"age": 30}}

    def test_syncer_records_peer_sv(self):
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t")
        a.set("m", "k", 1)
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t")
        net.run()
        assert "b" in a.peer_state_vectors

    def test_peer_close_drops_sv(self):
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t")
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t")
        net.run()
        a.set("m", "k", 1)
        net.run()
        b.self_close()
        net.run()
        assert "b" not in a.peer_state_vectors
        # a keeps operating
        a.set("m", "k2", 2)
        assert a.m == {"k": 1, "k2": 2}

    def test_rejoin_after_close(self):
        net = LoopbackNetwork()
        store = MemoryPersistence()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t")
        b = ypear_crdt(
            LoopbackRouter(net, "b"), topic="t", persistence=store
        )
        net.run()
        a.set("m", "k", 1)
        net.run()
        b.self_close()
        a.set("m", "k2", 2)  # happens while b is down
        net.run()
        b2 = ypear_crdt(
            LoopbackRouter(net, "b2"), topic="t", persistence=store
        )
        assert b2.m == {"k": 1}  # restored from its log
        net.run()  # anti-entropy catches it up
        assert_converged([a, b2])


class TestAcceptanceConfigs:
    def test_config1_two_replica_map_set_del(self, merge_mode):
        # config #1: 2-replica Y.Map, set/del, no persistence
        net, (a, b) = make_swarm(2, merge_mode=merge_mode)
        for i in range(100):
            a.set("users", f"a{i}", i)
            b.set("users", f"b{i}", i)
        net.run()  # deletes below target keys written by the other side
        for i in range(0, 100, 2):
            a.delete("users", f"b{i}")
            b.delete("users", f"a{i}")
        net.run()
        state = assert_converged([a, b])
        assert len(state["users"]) == 100
        assert state["users"]["a1"] == 1 and "a0" not in state["users"]

    def test_config2_four_replica_array_ops(self, merge_mode):
        # config #2: concurrent push/insert/cut, 4 replicas
        net, reps = make_swarm(4, merge_mode=merge_mode)
        for i, r in enumerate(reps):
            r.push("log", [f"p{i}-{j}" for j in range(5)])
        net.run()
        for i, r in enumerate(reps):
            r.insert("log", i, f"ins{i}")
        net.run()
        for i, r in enumerate(reps):
            r.cut("log", i, 1)
        net.run()
        state = assert_converged(reps)
        assert len(state["log"]) == 4 * 5 + 4 - 4

    def test_config3_sixteen_replica_batch_with_persistence(self, merge_mode):
        # config #3: execBatch mixed Map+Array, 16 replicas, store on
        net = LoopbackNetwork()
        stores = [MemoryPersistence() for _ in range(16)]
        reps = []
        for i in range(16):
            reps.append(
                ypear_crdt(
                    LoopbackRouter(net, f"pk{i}"),
                    topic="t",
                    persistence=stores[i],
                    merge_mode=merge_mode,
                )
            )
        net.run()
        # one replica creates the shared nested array first; concurrent
        # creation would race 16 sibling arrays to one LWW winner
        # (reference semantics: last Y.Array set wins, losers' content
        # is shadowed)
        reps[0].set("nested", "l", "seed", array_method="push")
        net.run()
        for i, r in enumerate(reps):
            r.set("m", f"k{i}", i, batch=True)
            r.push("log", f"v{i}", batch=True)
            r.set("nested", "l", f"n{i}", array_method="push", batch=True)
            r.exec_batch()
        net.run()
        state = assert_converged(reps)
        assert len(state["m"]) == 16
        assert len(state["log"]) == 16
        assert len(state["nested"]["l"]) == 17  # seed + 16 pushes
        # every replica's log is non-empty and replayable
        fresh = ypear_crdt(
            LoopbackRouter(net, "fresh"), topic="t2", persistence=stores[0]
        )
        # different topic: nothing stored under t2 -> no replay crash
        assert stores[3].get_meta("t")["count"] > 0

    def test_config4_nested_array_in_map_64_replicas(self, merge_mode):
        # config #4: nested Array-in-Map, 64 replicas, interleaved edits
        net, reps = make_swarm(64, merge_mode=merge_mode)
        reps[0].set("doc0", "items", "seed", array_method="push")
        net.run()
        for i, r in enumerate(reps):
            r.set("doc0", "items", f"i{i}", array_method="push")
            if i % 4 == 0:
                r.set("doc0", f"meta{i}", {"by": i})
        net.run()
        state = assert_converged(reps)
        assert len(state["doc0"]["items"]) == 65
        assert len(state["doc0"]) == 1 + 16


class TestAdversarialDelivery:
    def test_reorder_and_duplicate(self, merge_mode):
        net = LoopbackNetwork(seed=7, reorder=True, duplicate=0.5)
        reps = []
        for i in range(6):
            reps.append(
                ypear_crdt(LoopbackRouter(net, f"pk{i}"), topic="t",
                           merge_mode=merge_mode)
            )
        net.run()
        for i, r in enumerate(reps):
            r.push("log", f"v{i}")
            r.set("m", f"k{i % 3}", i)
            if i % 2:
                r.unshift("log", f"u{i}")
        net.run()
        state = assert_converged(reps)
        assert len(state["log"]) == 6 + 3

    def test_reorder_seeds_all_converge(self, merge_mode):
        finals = []
        for seed in range(5):
            net = LoopbackNetwork(seed=seed, reorder=True)
            # pinned client ids: the op set must be identical across
            # seeds for the final states to be comparable
            reps = [
                ypear_crdt(
                    LoopbackRouter(net, f"pk{i}"), topic="t", client_id=i + 1,
                    merge_mode=merge_mode,
                )
                for i in range(4)
            ]
            net.run()
            for i, r in enumerate(reps):
                r.insert("log", 0, f"v{i}")
                r.set("m", "shared", f"w{i}")
            net.run()
            finals.append(assert_converged(reps))
        # convergence is delivery-order independent: same op set, same
        # final state whatever the schedule
        assert all(f == finals[0] for f in finals)


class TestCompaction:
    def test_compaction_squashes_log(self):
        net = LoopbackNetwork()
        store = MemoryPersistence()
        a = ypear_crdt(
            LoopbackRouter(net, "a"),
            topic="t",
            persistence=store,
            compact_every=10,
        )
        for i in range(25):
            a.set("m", f"k{i}", i)
        meta = store.get_meta("t")
        assert meta["count"] < 10  # squashed at least twice
        b = ypear_crdt(
            LoopbackRouter(net, "b2"), topic="t", persistence=store
        )
        assert len(b.m) == 25

    def test_compaction_skipped_while_pending(self):
        """Compacting with stashed (dependency-waiting) updates would
        drop them from the log across a restart."""
        from crdt_tpu.api import Crdt

        src_updates = []
        src = Crdt(1, on_update=lambda u, m: src_updates.append(u))
        src.push("l", "a")
        src.push("l", "b")
        net = LoopbackNetwork()
        store = MemoryPersistence()
        r = ypear_crdt(
            LoopbackRouter(net, "r"), topic="t",
            persistence=store, compact_every=1,
        )
        r.doc.apply_update(src_updates[1])  # u2 first: goes pending
        r._persist(src_updates[1])  # would trigger compaction
        assert r.doc.engine.pending  # still stashed
        r.doc.apply_update(src_updates[0])
        r._persist(src_updates[0])
        # restart from the log: nothing lost
        r2 = ypear_crdt(
            LoopbackRouter(net, "r2"), topic="t", persistence=store
        )
        assert r2.l == ["a", "b"]

    def test_restarted_replica_gets_fresh_client_id(self):
        """A replica restarting without persistence must not reuse its
        old client id (its clock would restart below peers' watermarks
        and its ops would be dropped as stale duplicates)."""
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t")
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t")
        net.run()
        b.push("l", "before-crash")
        net.run()
        b.self_close()
        net.run()
        b2 = ypear_crdt(LoopbackRouter(net, "b"), topic="t")  # same identity
        assert b2.doc.engine.client_id != b.doc.engine.client_id
        net.run()
        b2.push("l", "after-restart")
        net.run()
        assert_converged([a, b2])
        assert set(a.l) == {"before-crash", "after-restart"}


class TestAntiEntropyTwoWay:
    def test_requester_surplus_flows_back_to_syncer(self):
        """Reference handshake is one-way: a restarting replica's
        log-loaded state never reached the solo-synced peer. Ours is
        two-way (the sync reply carries the syncer's SV and the
        requester answers with a back-diff)."""
        net = LoopbackNetwork()
        store = MemoryPersistence()
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t", persistence=store)
        b.set("m", "only-b-knows", 1)
        b.self_close()
        net.run()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t")  # solo-synced
        b2 = ypear_crdt(LoopbackRouter(net, "b"), topic="t", persistence=store)
        net.run()
        assert a.synced and b2.synced
        assert_converged([a, b2])
        assert a.m == {"only-b-knows": 1}

    def test_tombstone_only_surplus_flows_back(self):
        net = LoopbackNetwork()
        store = MemoryPersistence()
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t", persistence=store)
        b.set("m", "k", 1)
        b.delete("m", "k")
        b.self_close()
        net.run()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t")
        b2 = ypear_crdt(LoopbackRouter(net, "b"), topic="t", persistence=store)
        net.run()
        assert_converged([a, b2])
        assert a.m == {}

    def test_orphaned_unsynced_peers_recover(self):
        """Two unsynced replicas (their syncer left before answering)
        must still converge: unsynced peers answer ready probes too."""
        net = LoopbackNetwork()
        x = ypear_crdt(LoopbackRouter(net, "x"), topic="t")
        x.set("m", "from-x", 1)
        # y and z join; x leaves before the queue drains
        y = ypear_crdt(LoopbackRouter(net, "y"), topic="t")
        z = ypear_crdt(LoopbackRouter(net, "z"), topic="t")
        x.self_close()
        net.run()
        assert y.synced and z.synced
        assert_converged([y, z])
        # y and z keep working and replicating
        y.set("m", "from-y", 2)
        net.run()
        assert_converged([y, z])

    def test_last_peer_leaving_unwedges_topic(self):
        net = LoopbackNetwork()
        x = ypear_crdt(LoopbackRouter(net, "x"), topic="t")
        y = ypear_crdt(LoopbackRouter(net, "y"), topic="t")
        x.self_close()
        net.run()
        assert y.synced  # solo fallback inside sync()
        z = ypear_crdt(LoopbackRouter(net, "z"), topic="t")
        net.run()
        assert z.synced


class TestBatchIncoming:
    def test_round_batches_apply_as_one_merge(self):
        """With batch_incoming, a delivery round's worth of updates
        lands as ONE merge transaction (one observer flush) — the
        north-star gate at the sync handler."""
        events = []
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t", client_id=1)
        b = ypear_crdt(
            LoopbackRouter(net, "b"), topic="t", client_id=2,
            batch_incoming=True, observer_function=events.append,
        )
        net.run()
        events.clear()
        for i in range(20):
            a.set("m", f"k{i}", i)  # 20 broadcasts queue up
        net.run()
        assert dict(b.c)["m"] == dict(a.c)["m"]
        remote = [e for e in events if e.get("origin") in ("remote", "sync")]
        assert len(remote) == 1, f"{len(remote)} flushes for one round"

    def test_batching_is_default_in_device_mode(self):
        net = LoopbackNetwork()
        r = ypear_crdt(LoopbackRouter(net, "x"), topic="t",
                       device_merge=True)
        assert r.batch_incoming
        r2 = ypear_crdt(LoopbackRouter(net, "y"), topic="t")
        assert not r2.batch_incoming

    def test_batched_device_swarm_converges(self, merge_mode):
        net = LoopbackNetwork(seed=5, reorder=True, duplicate=0.2)
        reps = [
            ypear_crdt(LoopbackRouter(net, f"pk{i}"), topic="t",
                       client_id=i + 1, merge_mode=merge_mode,
                       batch_incoming=True)
            for i in range(6)
        ]
        net.run()
        for i, r in enumerate(reps):
            r.set("m", f"k{i % 3}", i)
            r.push("l", [i])
        net.run()
        assert_converged(reps)

    def test_ready_probe_sees_buffered_updates(self):
        """A syncer must flush its inbox before answering a probe, or
        the diff omits just-received updates."""
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t", client_id=1,
                       batch_incoming=True)
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t", client_id=2)
        net.run()
        b.set("m", "k", "v")
        net.run()  # a buffered+flushed it via the round hook
        late = ypear_crdt(LoopbackRouter(net, "c"), topic="t", client_id=3)
        net.run()
        assert dict(late.c) == dict(a.c) == dict(b.c)

    def test_malformed_update_does_not_poison_the_round(self):
        """One corrupt blob in a buffered round must not discard the
        other peers' valid updates."""
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t", client_id=1,
                       batch_incoming=True)
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t", client_id=2)
        net.run()
        b.set("m", "good", 1)
        # inject a corrupt update into a's inbox alongside b's real one
        a._inbox.append((b"\xff\xfe\xfd", {"meta": None}, "evil"))
        b.set("m", "good2", 2)
        net.run()
        assert dict(a.c)["m"] == {"good": 1, "good2": 2}
        assert not a._inbox

    def test_mixed_round_preserves_observer_origins(self):
        """A sync reply sharing a flush with plain broadcasts must not
        relabel the broadcasts' observer origin."""
        from crdt_tpu.api.doc import Crdt

        events = []
        net = LoopbackNetwork()
        b = ypear_crdt(
            LoopbackRouter(net, "b"), topic="t", client_id=2,
            batch_incoming=True, observer_function=events.append,
        )
        out1, out2 = [], []
        src1 = Crdt(7, on_update=lambda u, m: out1.append(u))
        src2 = Crdt(8, on_update=lambda u, m: out2.append(u))
        src1.set("r", "x", 1)
        src2.set("s", "y", 2)
        b._inbox.append((out1[0], {"meta": None}, "p1"))
        b._inbox.append((out2[0], {"meta": "sync"}, "p2"))
        b.flush_incoming()
        assert dict(b.c) == {"r": {"x": 1}, "s": {"y": 2}}
        by_origin = {e["origin"]: e for e in events if "origin" in e}
        assert set(by_origin) == {"remote", "sync"}, set(by_origin)
        assert "r" in by_origin["remote"]["touched"]
        assert "s" in by_origin["sync"]["touched"]


class TestResidentMode:
    """merge_mode="resident" specifics: the document lives in the
    HBM-resident replay (no scalar engine); the sync protocol, the
    persistence log, and compaction are all answered from resident
    state (VERDICT r2 items #2/#6)."""

    def test_no_engine_store_materialized(self):
        net, (a, b) = make_swarm(2, merge_mode="resident")
        a.set("m", "k", 1)
        net.run()
        assert_converged([a, b])
        from crdt_tpu.api.resident_doc import _ResidentEngineShim

        assert isinstance(a.doc.engine, _ResidentEngineShim)

    def test_env_var_selects_resident(self, monkeypatch):
        """CRDT_TPU_DEVICE=1 selects resident, the device-resident
        product mode — not the engine-backed device gate, which pays a
        device round-trip per merge (VERDICT r3 item 4). Explicit
        arguments still take precedence."""
        from crdt_tpu.api.resident_doc import ResidentCrdt

        monkeypatch.setenv("CRDT_TPU_DEVICE", "1")
        net = LoopbackNetwork()
        r = ypear_crdt(LoopbackRouter(net, "e1"), topic="t")
        assert r.merge_mode == "resident"
        assert isinstance(r.doc, ResidentCrdt)
        # explicit scalar request wins over the env var
        r2 = ypear_crdt(LoopbackRouter(net, "e2"), topic="t2",
                        merge_mode="scalar")
        assert r2.merge_mode == "scalar"
        r3 = ypear_crdt(LoopbackRouter(net, "e3"), topic="t3",
                        device_merge=False)
        assert r3.merge_mode == "scalar"
        # the engine device gate remains reachable explicitly
        r4 = ypear_crdt(LoopbackRouter(net, "e4"), topic="t4",
                        device_merge=True)
        assert r4.merge_mode == "device"

    def test_persistence_replay_and_rejoin(self):
        net = LoopbackNetwork()
        store = MemoryPersistence()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t",
                       merge_mode="resident")
        b = ypear_crdt(LoopbackRouter(net, "b"), topic="t",
                       merge_mode="resident", persistence=store)
        net.run()
        a.set("m", "k", 1)
        b.push("l", "mine")
        net.run()
        b.self_close()
        a.set("m", "k2", 2)  # while b is down
        net.run()
        b2 = ypear_crdt(LoopbackRouter(net, "b2"), topic="t",
                        merge_mode="resident", persistence=store)
        # restored from its own log (resident replay of the update log)
        assert b2.m == {"k": 1} and b2.l == ["mine"]
        net.run()  # anti-entropy catches it up
        assert_converged([a, b2])
        assert b2.m == {"k": 1, "k2": 2}

    def test_compaction_from_resident_columns(self):
        net = LoopbackNetwork()
        store = MemoryPersistence()
        a = ypear_crdt(LoopbackRouter(net, "a"), topic="t",
                       merge_mode="resident", persistence=store,
                       compact_every=10)
        for i in range(25):
            a.set("m", f"k{i}", i)
        a.push("l", ["x", "y"])
        meta = store.get_meta("t")
        assert meta["count"] < 10  # squashed from resident columns
        # the snapshot replays into a fresh ENGINE-backed replica
        # identically (cross-backend snapshot fidelity)
        fresh = ypear_crdt(LoopbackRouter(net, "f"), topic="t",
                           merge_mode="scalar", persistence=store)
        assert dict(fresh.c) == dict(a.c)

    def test_device_forced_protocol_round(self):
        """device_min_rows=0 pushes every protocol merge through the
        device splice+converge dispatch — the full resident device
        path exercised by the live sync protocol, not just the model
        differentials."""
        net, reps = make_swarm(3, merge_mode="resident",
                               device_min_rows=0)
        for i, r in enumerate(reps):
            r.set("m", f"k{i}", i)
            r.push("l", f"v{i}")
        net.run()
        state = assert_converged(reps)
        assert len(state["m"]) == 3 and len(state["l"]) == 3

    def test_resident_observers_fire(self):
        events = []
        net, (a, b) = make_swarm(2, merge_mode="resident")
        b.doc.observe("m", events.append, key="k1")
        a.set("m", "k1", "v1")
        a.set("m", "other", "x")
        net.run()
        assert any(e.get("key") == "k1" and e.get("value") == "v1"
                   for e in events)
        # the per-key observer did not fire for the unrelated key
        assert all(e.get("key") == "k1" for e in events)

    def test_anti_entropy_deficit_from_resident(self):
        net, (a, b) = make_swarm(2, merge_mode="resident")
        for i in range(5):
            a.set("m", f"k{i}", i)
        net.run()
        # forget b's progress, then anti-entropy re-sends the deficit
        from crdt_tpu.core.ids import StateVector

        a.peer_state_vectors["pk1"] = StateVector({})
        sent = a.anti_entropy()
        assert sent.get("pk1", 0) > 0
        net.run()
        assert_converged([a, b])


class TestCursorLocalEditing:
    """Indexed edits resolve anchors from a per-sequence cursor
    (epoch-validated against the replay's order epoch). A mixed swarm
    — one resident editor hammering index-addressed inserts/cuts, one
    scalar peer doing the same concurrently — must converge exactly:
    any stale-cursor anchor would place an item at the wrong position
    on one side only (VERDICT r4 item 8)."""

    def test_mixed_mode_indexed_edit_storm(self):
        """Index SEMANTICS are oracled by a shadow Python list: every
        edit is mirrored with plain list.insert/del on the acting
        replica's CURRENT view, and deliveries are synchronous — so a
        cursor that resolves index i to the wrong anchor diverges
        from the shadow even though both replicas would converge on
        the (identically wrong) placement."""
        import random

        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "pkA"), topic="t",
                       merge_mode="resident", client_id=1)
        b = ypear_crdt(LoopbackRouter(net, "pkB"), topic="t",
                       merge_mode="scalar", client_id=2)
        net.run()
        a.array("items")
        for i in range(40):
            a.push("items", f"seed{i}")
        net.run()
        shadow = list(a.c["items"])
        rng = random.Random(17)
        for round_no in range(30):
            for r, tag in ((a, "A"), (b, "B")):
                for j in range(4):
                    op = rng.random()
                    n = len(r.c["items"])
                    if op < 0.6 or n < 3:
                        idx = rng.randint(0, n)
                        val = f"{tag}{round_no}-{j}"
                        r.insert("items", idx, val)
                        shadow.insert(idx, val)
                    else:
                        idx = rng.randint(0, n - 2)
                        r.cut("items", idx, 1)
                        del shadow[idx]
                net.run()  # synchronous: both views == shadow
                assert list(r.c["items"]) == shadow
        state = assert_converged([a, b])
        assert list(state["items"]) == shadow
        assert len(state["items"]) > 40

    def test_cursor_survives_append_runs(self):
        """Appends must NOT invalidate the cursor (tail inserts move
        no existing position): a mid-insert after a long append run
        still lands exactly where the engine oracle puts it."""
        net = LoopbackNetwork()
        a = ypear_crdt(LoopbackRouter(net, "pkA"), topic="t",
                       merge_mode="resident", client_id=1)
        b = ypear_crdt(LoopbackRouter(net, "pkB"), topic="t",
                       merge_mode="scalar", client_id=2)
        net.run()
        a.array("items")
        for i in range(20):
            a.push("items", i)
        a.insert("items", 10, "first-mid")   # seeds the cursor
        for i in range(200):
            a.push("items", f"tail{i}")      # cursor must survive these
        a.insert("items", 11, "second-mid")  # resolved from the cursor
        a.insert("items", 12, "third-mid")
        a.cut("items", 13, 2)
        net.run()
        state = assert_converged([a, b])
        assert state["items"][10] == "first-mid"
        assert state["items"][11] == "second-mid"
        assert state["items"][12] == "third-mid"
